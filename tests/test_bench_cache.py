"""Unit tests for bench.py's healthy-rung banking and cache fallback.

The banked-cache mechanism is the round's measurement-survival path (a
wedged tunneled chip at bench time must still report the best healthy-chip
rung — PERF.md operational constraints), so its host-side logic gets real
tests: banking criteria, best-keeps-wins, and the workload fingerprint
gate that stops a cache entry from a different workload being reported as
the headline metric.

bench.py's parent process never imports jax (by design), so importing it
here is cheap and side-effect-free beyond a couple of env defaults.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    """A fresh bench module whose cache/partial paths live in tmp_path."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "TPU_CACHE", str(tmp_path / "cache.json"))
    monkeypatch.setattr(mod, "PARTIAL", str(tmp_path / "partial.json"))
    yield mod
    del sys.modules["bench_under_test"]


def _rung(platform="tpu", cps=50.0, B=256, n_ok=256):
    return {"platform": platform, "cps": cps, "B": B, "n_ok": n_ok,
            "wall_s": B / cps, "tau_min": 1e-5, "tau_max": 5e-4}


def test_bank_and_load_roundtrip(bench):
    bench.bank_tpu_rung(_rung(cps=50.0))
    got = bench.load_tpu_cache()
    assert got is not None and got["cps"] == 50.0
    # the banked record carries the workload fingerprint and a timestamp
    assert got["workload"] == bench._workload_fingerprint()
    assert "banked_at" in got


def test_cpu_rungs_are_never_banked(bench):
    bench.bank_tpu_rung(_rung(platform="cpu"))
    assert bench.load_tpu_cache() is None


def test_partial_rungs_are_never_banked(bench):
    bench.bank_tpu_rung(_rung(n_ok=17))  # 17 of 256 lanes succeeded
    assert bench.load_tpu_cache() is None


def test_best_rung_wins_and_slower_does_not_regress(bench):
    bench.bank_tpu_rung(_rung(cps=50.0))
    bench.bank_tpu_rung(_rung(cps=40.0))  # slower: keep the 50
    assert bench.load_tpu_cache()["cps"] == 50.0
    bench.bank_tpu_rung(_rung(cps=60.0))  # faster: replace
    assert bench.load_tpu_cache()["cps"] == 60.0


def test_workload_fingerprint_gates_the_cache(bench):
    """A cache entry measured under a different workload (other horizon,
    other T window) must never be reported as this invocation's metric."""
    bench.bank_tpu_rung(_rung(cps=50.0))
    with open(bench.TPU_CACHE) as f:
        cached = json.load(f)
    cached["workload"]["t1"] = 1e-9  # someone benched a different horizon
    with open(bench.TPU_CACHE, "w") as f:
        json.dump(cached, f)
    assert bench.load_tpu_cache() is None


def test_corrupt_cache_is_ignored(bench):
    with open(bench.TPU_CACHE, "w") as f:
        f.write("{not json")
    assert bench.load_tpu_cache() is None
