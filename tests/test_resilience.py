"""Fault-tolerance layer (resilience/ — docs/robustness.md).

Every recovery path is exercised in tier-1 on a tiny stiff decay ODE via
the deterministic fault-injection harness (resilience/inject.py): a hung
fetch, a corrupt/truncated chunk file, and a NaN lane here, plus the
killed-process path in tests/test_multihost.py.  The recovery contract
asserted throughout: live (never-faulted) lanes are BIT-EXACT against an
uninjected run — recovery may never perturb healthy results.
"""

import json
import os
import signal
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu.obs.recorder import Recorder
from batchreactor_tpu.resilience import (GuardedResult, QuarantinePolicy,
                                         RetryPolicy, WedgeError,
                                         clear_suspects, inject,
                                         normalize_quarantine,
                                         normalize_retry,
                                         resolve_fetch_deadline, run_guarded,
                                         suspect_devices)
from batchreactor_tpu.solver.sdirk import (DT_UNDERFLOW,
                                           MAX_STEPS_REACHED, SUCCESS)


@pytest.fixture(autouse=True)
def _disarm_injection():
    """No armed plan (or suspect registry entry) may leak across tests."""
    inject.disarm()
    clear_suspects()
    yield
    inject.disarm()
    clear_suspects()


def _decay_rhs(t, y, cfg):
    return -cfg["k"] * y


def _decay_setup(B=8):
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    cfgs = {"k": jnp.logspace(1.0, 2.0, B)}
    return y0s, cfgs


def _ckpt_sweep(ckpt_dir, B=8, **kw):
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _decay_setup(B)
    return checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                              str(ckpt_dir), chunk_size=4, **kw)


def _assert_lanes_bit_exact(a, b, lanes=None):
    """Bit-exact comparison of every value field, optionally lane-subset."""
    for f in ("t", "y", "status", "n_accepted", "n_rejected"):
        va, vb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        if lanes is not None:
            va, vb = va[lanes], vb[lanes]
        np.testing.assert_array_equal(va, vb, err_msg=f"field {f}")


# ------------------------------------------------------------------ policies
def test_retry_policy_normalization_and_validation():
    assert normalize_retry(None) is None
    assert normalize_retry(False) is None
    assert normalize_retry(True) == RetryPolicy()
    assert normalize_retry(3).max_retries == 3
    p = normalize_retry({"max_retries": 1, "backoff_s": 0.0})
    assert (p.max_retries, p.backoff_s) == (1, 0.0)
    assert normalize_retry(p) is p
    assert p.delay(0) == 0.0
    assert RetryPolicy(backoff_s=1.0).delay(2) == 4.0
    with pytest.raises(ValueError, match="max_retries"):
        normalize_retry(-1)
    with pytest.raises(ValueError, match="bad retry policy"):
        normalize_retry({"nope": 1})
    with pytest.raises(ValueError, match="retry must be"):
        normalize_retry("yes")


def test_quarantine_policy_normalization_and_validation():
    assert normalize_quarantine(None) is None
    assert normalize_quarantine(True) == QuarantinePolicy()
    q = normalize_quarantine({"oracle": True, "rtol_factor": 0.5})
    assert q.oracle and q.rtol_factor == 0.5
    assert normalize_quarantine(q) is q
    with pytest.raises(ValueError, match="TIGHTENS"):
        normalize_quarantine({"rtol_factor": 2.0})
    with pytest.raises(ValueError, match="max_steps_factor"):
        normalize_quarantine({"max_steps_factor": 0.5})
    with pytest.raises(ValueError, match="bad quarantine policy"):
        normalize_quarantine({"nope": 1})


def test_resolve_fetch_deadline(monkeypatch):
    assert resolve_fetch_deadline(5.0) == 5.0
    with pytest.raises(ValueError, match="> 0"):
        resolve_fetch_deadline(0)
    monkeypatch.delenv("BR_FETCH_DEADLINE_S", raising=False)
    assert resolve_fetch_deadline(None) is None
    monkeypatch.setenv("BR_FETCH_DEADLINE_S", "7.5")
    assert resolve_fetch_deadline(None) == 7.5
    monkeypatch.setenv("BR_FETCH_DEADLINE_S", "0")
    assert resolve_fetch_deadline(None) is None


# ------------------------------------------------------------------ injection
def test_inject_spec_parsing_and_firing_counts():
    inject.arm("hang_fetch:delay=2,count=2;nan_lane:lane=3")
    assert inject.active()
    assert inject.fetch_hang_delay() == 2.0
    assert inject.fetch_hang_delay() == 2.0
    assert inject.fetch_hang_delay() == 0.0   # count exhausted
    with pytest.raises(ValueError, match="unknown fault kind"):
        inject.arm("melt_chip")
    with pytest.raises(ValueError, match="malformed fault param"):
        inject.arm("kill:chunk")


# ------------------------------------------------------------------ watchdog
def test_watchdog_hung_fetch_raises_and_marks_suspect():
    from batchreactor_tpu.resilience.watchdog import fetch_with_deadline

    x = jnp.arange(4.0)
    # un-delayed wait completes inside the deadline
    np.testing.assert_array_equal(fetch_with_deadline(x, 30.0),
                                  np.arange(4.0))
    inject.arm("hang_fetch:delay=10")
    rec = Recorder()
    t0 = time.perf_counter()
    with pytest.raises(WedgeError) as ei:
        fetch_with_deadline(x, 0.3, rec, label="test-fetch")
    assert time.perf_counter() - t0 < 5.0   # deadline, not the hang
    assert ei.value.deadline_s == 0.3
    assert suspect_devices()                # device registry populated
    _spans, events, counters = rec.snapshot()
    assert counters.get("fetch_timeouts") == 1
    fault = next(e for e in events if e["name"] == "fault")
    assert fault["attrs"]["kind"] == "hung_fetch"


def test_segmented_fetch_deadline_surfaces_wedge():
    from batchreactor_tpu.parallel import ensemble_solve_segmented

    y0s, cfgs = _decay_setup(4)
    inject.arm("hang_fetch:delay=10")
    with pytest.raises(WedgeError):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 segment_steps=64, max_segments=50,
                                 fetch_deadline=0.3)


# ------------------------------------------------------------------ guard
def test_run_guarded_clean_child():
    r = run_guarded([sys.executable, "-c",
                     "import sys; print('out'); "
                     "print('err', file=sys.stderr)"], timeout=60)
    assert isinstance(r, GuardedResult)
    assert (r.rc, r.timed_out) == (0, False)
    assert r.stdout.strip() == "out" and r.stderr.strip() == "err"
    m = run_guarded([sys.executable, "-c",
                     "import sys; print('both', file=sys.stderr)"],
                    timeout=60, merge_stderr=True)
    assert m.stderr is None and "both" in m.stdout


def test_run_guarded_timeout_sigterm_then_grace():
    # the child prints on SIGTERM and exits cleanly inside the grace
    # window — proving the guard sent SIGTERM first, not SIGKILL
    child = ("import signal, sys, time\n"
             "signal.signal(signal.SIGTERM,"
             " lambda *a: (print('terml'), sys.exit(3)))\n"
             "print('up', flush=True)\n"
             "time.sleep(60)\n")
    r = run_guarded([sys.executable, "-c", child], timeout=1.5, grace_s=30)
    assert r.timed_out
    assert r.rc == 3                       # SIGTERM handler ran
    assert "terml" in r.stdout
    assert r.wall_s < 30                   # did not burn the grace window


def test_run_guarded_sigkill_after_grace():
    # child ignores SIGTERM -> the guard escalates to SIGKILL after grace
    child = ("import signal, time\n"
             "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
             "time.sleep(60)\n")
    r = run_guarded([sys.executable, "-c", child], timeout=1.0, grace_s=1.0)
    assert r.timed_out and r.rc == -signal.SIGKILL


# ------------------------------------------------------ crash-atomic chunks
def test_chunk_save_is_atomic_no_tmp_residue(tmp_path):
    res = _ckpt_sweep(tmp_path / "ck")
    assert np.all(np.asarray(res.status) == SUCCESS)
    names = sorted(os.listdir(tmp_path / "ck"))
    assert "chunk_00000.npz" in names and "chunk_00001.npz" in names
    assert not any(n.endswith(".tmp.npz") or n.endswith(".tmp")
                   for n in names)


def test_resume_resolves_truncated_chunk(tmp_path):
    """Satellite regression: truncate one chunk mid-manifest; resume must
    re-solve it (not crash) and reproduce the clean result bit-exactly."""
    ck = tmp_path / "ck"
    clean = _ckpt_sweep(ck)
    victim = ck / "chunk_00001.npz"
    size = victim.stat().st_size
    with open(victim, "r+b") as fh:
        fh.truncate(size // 2)
    rec = Recorder()
    resumed = _ckpt_sweep(ck, recorder=rec)
    _assert_lanes_bit_exact(clean, resumed)
    assert (ck / "chunk_00001.npz.corrupt").exists()   # kept for forensics
    assert (ck / "chunk_00001.npz").exists()           # re-solved + saved
    _spans, events, counters = rec.snapshot()
    assert counters.get("chunks_corrupt") == 1
    kinds = [e["attrs"].get("kind") for e in events if e["name"] == "fault"]
    assert "corrupt_chunk" in kinds


def test_injected_corrupt_chunk_recovers_bit_exact(tmp_path):
    clean = _ckpt_sweep(tmp_path / "clean")
    inject.arm("corrupt_chunk:chunk=0")
    _ckpt_sweep(tmp_path / "faulted")            # tears chunk 0 post-save
    resumed = _ckpt_sweep(tmp_path / "faulted")  # resume re-solves it
    _assert_lanes_bit_exact(clean, resumed)


# ------------------------------------------------------------ chunk retry
def test_hung_chunk_retries_and_recovers_bit_exact(tmp_path):
    clean = _ckpt_sweep(tmp_path / "clean")
    inject.arm("hang_fetch:delay=10")
    rec = Recorder()
    res = _ckpt_sweep(tmp_path / "faulted", chunk_budget_s=0.3,
                      retry={"max_retries": 2, "backoff_s": 0.0},
                      recorder=rec)
    _assert_lanes_bit_exact(clean, res)
    _spans, events, counters = rec.snapshot()
    assert counters.get("fetch_timeouts") == 1
    assert counters.get("chunk_retries") == 1
    # the attempt ledger records the failed attempt AND the recovery
    attempts = json.load(open(tmp_path / "faulted" / "manifest.json"))[
        "attempts"]
    rows = attempts["0"]
    assert [r["outcome"] for r in rows] == ["error", "ok"]
    assert rows[0]["kind"] == "WedgeError"


def test_wedge_without_retry_raises(tmp_path):
    inject.arm("hang_fetch:delay=10")
    with pytest.raises(WedgeError):
        _ckpt_sweep(tmp_path / "ck", chunk_budget_s=0.3)


def test_chunk_budget_resolution(monkeypatch):
    from batchreactor_tpu.parallel.checkpoint import resolve_chunk_budget

    assert resolve_chunk_budget(12.5) == 12.5
    assert resolve_chunk_budget("auto") == "auto"
    monkeypatch.delenv("BR_CHUNK_BUDGET_S", raising=False)
    assert resolve_chunk_budget(None) is None
    monkeypatch.setenv("BR_CHUNK_BUDGET_S", "42")
    assert resolve_chunk_budget(None) == 42.0
    monkeypatch.setenv("BR_CHUNK_BUDGET_S", "auto")
    assert resolve_chunk_budget(None) == "auto"


# --------------------------------------------------------- lane quarantine
def test_nan_lane_quarantine_recovers_bit_exact(tmp_path):
    from batchreactor_tpu.resilience.quarantine import PRIMARY, RETRY

    clean = _ckpt_sweep(tmp_path / "clean")
    inject.arm("nan_lane:lane=3")
    rec = Recorder()
    res = _ckpt_sweep(tmp_path / "faulted", quarantine=True, recorder=rec)
    # the whole sweep — poisoned lane included — matches the clean run
    # bit-exactly: the retry pass re-solves the full chunk with unchanged
    # settings, so transient corruption recovers exactly
    _assert_lanes_bit_exact(clean, res)
    prov = np.asarray(res.provenance)
    assert prov[3] == RETRY
    assert np.all(np.delete(prov, 3) == PRIMARY)
    _spans, events, counters = rec.snapshot()
    assert counters.get("lanes_quarantined") == 1
    assert counters.get("lanes_recovered") == 1
    assert "lanes_unrecovered" not in counters
    fault = next(e for e in events if e["name"] == "fault")
    assert fault["attrs"] == {"kind": "lane_quarantine", "lanes": [3],
                              "statuses": [int(DT_UNDERFLOW)]}


def test_quarantine_provenance_persists_in_checkpoint(tmp_path):
    from batchreactor_tpu.parallel.checkpoint import load_result
    from batchreactor_tpu.resilience.quarantine import RETRY

    inject.arm("nan_lane:lane=1")
    _ckpt_sweep(tmp_path / "ck", quarantine=True)
    chunk0, _cfgs = load_result(str(tmp_path / "ck" / "chunk_00000.npz"))
    assert chunk0.provenance is not None
    assert np.asarray(chunk0.provenance)[1] == RETRY
    # resume serves the persisted provenance through concatenation
    res = _ckpt_sweep(tmp_path / "ck", quarantine=True)
    assert np.asarray(res.provenance)[1] == RETRY


def test_quarantine_fallback_pass_raises_budget(tmp_path):
    """A lane that exhausts max_steps is NOT transient: the same-settings
    retry pass reproduces the failure, and the fallback pass (step budget
    x max_steps_factor) is what recovers it."""
    from batchreactor_tpu.resilience.quarantine import FALLBACK, PRIMARY

    # the stiffest lanes need more than 40 attempts at these tolerances
    clean = _ckpt_sweep(tmp_path / "clean", max_steps=2000)
    failing = _ckpt_sweep(tmp_path / "low", max_steps=40)
    bad = np.asarray(failing.status) != SUCCESS
    assert bad.any(), "expected max_steps=40 to exhaust some lane"
    rec = Recorder()
    res = _ckpt_sweep(tmp_path / "faulted", max_steps=40,
                      quarantine={"max_steps_factor": 50.0}, recorder=rec)
    assert np.all(np.asarray(res.status) == SUCCESS)
    prov = np.asarray(res.provenance)
    assert np.all(prov[bad] == FALLBACK)
    assert np.all(prov[~bad] == PRIMARY)
    # live lanes bit-exact against the SAME-settings clean run
    _assert_lanes_bit_exact(_ckpt_sweep(tmp_path / "low2", max_steps=40),
                            res, lanes=np.nonzero(~bad)[0])
    np.testing.assert_array_equal(np.asarray(failing.status)[bad],
                                  MAX_STEPS_REACHED)
    # the recovered values come from a bigger-budget solve of the same
    # lanes: tolerance-level agreement with the unconstrained clean run
    np.testing.assert_allclose(np.asarray(res.y)[bad],
                               np.asarray(clean.y)[bad],
                               rtol=1e-4, atol=1e-9)


def test_quarantine_residue_marked_failed(tmp_path):
    """A lane nothing recovers keeps its primary fields, provenance
    FAILED — graceful degradation, not an exception."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.resilience.quarantine import FAILED

    y0s, cfgs = _decay_setup(4)
    y0s = y0s.at[2, 0].set(jnp.nan)    # permanently poisoned input
    rec = Recorder()
    res = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "ck"), chunk_size=4,
                             quarantine={"max_steps_factor": 1.0},
                             recorder=rec)
    assert np.asarray(res.status)[2] != SUCCESS
    assert np.asarray(res.provenance)[2] == FAILED
    _spans, events, counters = rec.snapshot()
    assert counters.get("lanes_unrecovered") == 1
    kinds = [e["attrs"].get("kind") for e in events if e["name"] == "fault"]
    assert "lane_unrecovered" in kinds


# ----------------------------------------------------- elastic tier knobs
def test_elastic_sweep_retry_budget_quarantine(tmp_path):
    """The elastic tier supports the checkpointed_sweep fault knobs
    in-process (the dead-process path is tests/test_multihost.py): an
    injected hung wait breaches the chunk budget, retries, and recovers;
    an injected NaN lane quarantines; the knobs stay out of the
    fingerprint so single-process checkpointed_sweep resume serves the
    same directory."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.parallel.multihost import \
        elastic_checkpointed_sweep

    y0s, cfgs = _decay_setup(8)
    clean = _ckpt_sweep(tmp_path / "clean")
    inject.arm("hang_fetch:delay=10;nan_lane:lane=3")
    rec = Recorder()
    res = elastic_checkpointed_sweep(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, str(tmp_path / "el"),
        process_id=0, num_processes=1, chunk_size=4,
        chunk_budget_s=0.3, retry={"max_retries": 2, "backoff_s": 0.0},
        quarantine=True, recorder=rec)
    _assert_lanes_bit_exact(clean, res)
    _spans, _events, counters = rec.snapshot()
    assert counters.get("fetch_timeouts") == 1
    assert counters.get("chunk_retries") == 1
    assert counters.get("lanes_recovered") == 1
    # fingerprint interop: a knob-free single-process resume loads every
    # chunk from the elastic directory instead of re-solving
    rec2 = Recorder()
    resumed = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 str(tmp_path / "el"), chunk_size=4,
                                 recorder=rec2)
    _spans2, events2, _c2 = rec2.snapshot()
    assert sum(e["name"] == "chunk_loaded" for e in events2) == 2
    _assert_lanes_bit_exact(clean, resumed)


def test_elastic_sweep_steals_torn_claim(tmp_path):
    """A claim file torn between its O_EXCL create and the json.dump
    (writer killed mid-claim) must age out like a dead owner's claim and
    be stolen — not stall every survivor until timeout."""
    from batchreactor_tpu.parallel.multihost import \
        elastic_checkpointed_sweep

    y0s, cfgs = _decay_setup(8)
    ck = tmp_path / "el"
    ck.mkdir()
    torn = ck / "chunk_00000.npz.claim"
    torn.write_text("")                      # unparsable: owner unknown
    old = time.time() - 60.0
    os.utime(torn, (old, old))               # already stale
    rec = Recorder()
    res = elastic_checkpointed_sweep(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, str(ck), process_id=0,
        num_processes=1, chunk_size=4, heartbeat_s=0.2, timeout_s=60.0,
        recorder=rec)
    _assert_lanes_bit_exact(_ckpt_sweep(tmp_path / "clean"), res)
    _spans, events, counters = rec.snapshot()
    assert counters.get("chunks_reassigned") == 1
    ev = next(e for e in events
              if e["attrs"].get("kind") == "dead_host_reassign")
    assert ev["attrs"]["dead_process"] == -1     # unknown torn-claim owner


def test_elastic_sweep_resolves_corrupt_chunk(tmp_path):
    """An existing-but-torn chunk file in an elastic checkpoint dir must
    be set aside and re-solved (single-process resume convention) — the
    exists() gate alone would treat it as complete forever."""
    from batchreactor_tpu.parallel.multihost import \
        elastic_checkpointed_sweep

    y0s, cfgs = _decay_setup(8)
    clean = _ckpt_sweep(tmp_path / "clean")
    ck = tmp_path / "el"

    def run(rec=None):
        return elastic_checkpointed_sweep(
            _decay_rhs, y0s, 0.0, 1.0, cfgs, str(ck), process_id=0,
            num_processes=1, chunk_size=4, recorder=rec)

    run()
    victim = ck / "chunk_00001.npz"
    with open(victim, "r+b") as fh:
        fh.truncate(victim.stat().st_size // 2)
    rec = Recorder()
    res = run(rec)
    _assert_lanes_bit_exact(clean, res)
    assert (ck / "chunk_00001.npz.corrupt").exists()
    _spans, _events, counters = rec.snapshot()
    assert counters.get("chunks_corrupt") == 1


def test_elastic_rejects_segmented_knobs_on_monolithic_path(tmp_path):
    from batchreactor_tpu.parallel.multihost import \
        elastic_checkpointed_sweep

    y0s, cfgs = _decay_setup(4)
    with pytest.raises(ValueError, match="segmented-path knobs"):
        elastic_checkpointed_sweep(
            _decay_rhs, y0s, 0.0, 1.0, cfgs, str(tmp_path / "el"),
            process_id=0, num_processes=1, chunk_size=4,
            fetch_deadline=30.0)


# ------------------------------------------------------------- obs plumbing
def test_fault_events_flow_through_exports(tmp_path):
    from batchreactor_tpu.obs import export, report

    inject.arm("nan_lane:lane=3")
    rec = Recorder()
    _ckpt_sweep(tmp_path / "ck", quarantine=True, recorder=rec)
    rep = report.build_report(recorder=rec)
    # JSONL round-trips the fault events exactly
    rt = export.from_jsonl(export.to_jsonl(rep))
    faults = [e for e in rt["events"] if e["name"] == "fault"]
    assert faults and faults[0]["attrs"]["kind"] == "lane_quarantine"
    # Prometheus aggregates them by kind
    prom = export.to_prometheus(rep)
    assert 'br_fault_events_total{kind="lane_quarantine"} 1' in prom
    assert 'br_counter_total{name="lanes_recovered"} 1' in prom


def test_diff_maps_missing_fault_counters_to_zero(tmp_path):
    """Schema convention (the setup_reuses/cache_* rule): a fault-free
    report has NO fault counters; diffing it against a faulted report
    must read 0 -> n, and two fault-free reports must not differ."""
    from batchreactor_tpu.obs import report

    rec_clean = Recorder()
    _ckpt_sweep(tmp_path / "clean", recorder=rec_clean)
    inject.arm("nan_lane:lane=3")
    rec_fault = Recorder()
    _ckpt_sweep(tmp_path / "faulted", quarantine=True, recorder=rec_fault)
    a = report.build_report(recorder=rec_clean)
    b = report.build_report(recorder=rec_fault)
    d = report.diff(a, b)
    assert "lanes_quarantined: 0 -> 1" in d
    assert "lanes_recovered: 0 -> 1" in d
    assert "counter lanes_unrecovered" not in d    # 0 == 0: suppressed


# --------------------------------------------------------------- api knobs
def test_api_validates_resilience_knobs(h2o2_bundle):
    import batchreactor_tpu as br

    gm, thermo = h2o2_bundle
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=thermo, md=gm)
    comp = {"H2": 0.25, "O2": 0.25, "N2": 0.5}
    with pytest.raises(ValueError, match="segmented-path knobs"):
        br.batch_reactor_sweep(comp, [1200.0], 1e5, 1e-5,
                               fetch_deadline=5.0, **kw)
    with pytest.raises(ValueError, match="quarantine must be"):
        br.batch_reactor_sweep(comp, [1200.0], 1e5, 1e-5,
                               quarantine="yes", **kw)
    with pytest.raises(ValueError, match="TIGHTENS"):
        br.batch_reactor_sweep(comp, [1200.0], 1e5, 1e-5,
                               quarantine={"rtol_factor": 3.0}, **kw)


@pytest.fixture(scope="module")
def h2o2_bundle(lib_dir):
    import batchreactor_tpu as br

    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    thermo = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, thermo


@pytest.mark.slow
def test_api_sweep_quarantine_provenance(h2o2_bundle):
    """End-to-end: a healthy sweep under quarantine=True reports all-
    primary provenance and an empty quarantine section — and is bit-exact
    against quarantine=None (the zero-fault no-op contract).

    slow: real-chemistry api drive (CI's unfiltered run executes it);
    the decay-ODE tests above carry the tier-1 recovery contract — the
    870 s tier-1 budget has ~no headroom for h2o2 compiles."""
    import batchreactor_tpu as br

    gm, thermo = h2o2_bundle
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=thermo, md=gm)
    comp = {"H2": 0.25, "O2": 0.25, "N2": 0.5}
    base = br.batch_reactor_sweep(comp, [1150.0, 1250.0], 1e5, 1e-5, **kw)
    out = br.batch_reactor_sweep(comp, [1150.0, 1250.0], 1e5, 1e-5,
                                 quarantine=True, **kw)
    assert np.all(out["provenance"] == 0)
    assert out["report"]["quarantine"] == {}
    for sp in base["x"]:
        np.testing.assert_array_equal(out["x"][sp], base["x"][sp],
                                      err_msg=f"species {sp}")
    np.testing.assert_array_equal(out["status"], base["status"])
    assert "provenance" not in base


@pytest.mark.slow
def test_api_sweep_quarantine_fallback_under_buckets(h2o2_bundle):
    """The quarantine passes must honor the primary's execution config:
    the retry pass re-runs the PRIMARY program (bucket padding included)
    and the fallback pass recovers a budget-exhausted lane; live lanes
    stay bit-exact against a same-settings quarantine-off run.

    slow: real-chemistry api drive, see the provenance test's note."""
    import batchreactor_tpu as br
    from batchreactor_tpu.resilience.quarantine import FALLBACK
    from batchreactor_tpu.solver.sdirk import SUCCESS

    gm, thermo = h2o2_bundle
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=thermo, md=gm,
              buckets=(4,), max_steps=40)   # B=3 pads onto the 4-bucket
    comp = {"H2": 0.25, "O2": 0.25, "N2": 0.5}
    T = [1150.0, 1250.0, 1350.0]
    base = br.batch_reactor_sweep(comp, T, 1e5, 1e-5, **kw)
    bad = np.asarray(base["status"]) != SUCCESS
    assert bad.any(), "expected max_steps=40 to exhaust some lane"
    out = br.batch_reactor_sweep(comp, T, 1e5, 1e-5,
                                 quarantine={"max_steps_factor": 100.0},
                                 **kw)
    assert np.all(np.asarray(out["status"]) == SUCCESS)
    prov = np.asarray(out["provenance"])
    assert np.all(prov[bad] == FALLBACK) and np.all(prov[~bad] == 0)
    for sp in base["x"]:
        np.testing.assert_array_equal(
            np.asarray(out["x"][sp])[~bad], np.asarray(base["x"][sp])[~bad],
            err_msg=f"live lanes, species {sp}")


# ------------------------------------------------------------ bench rotation
def test_bench_partial_rotation(tmp_path, monkeypatch):
    import bench

    partial = tmp_path / "bench_partial.json"
    monkeypatch.setattr(bench, "PARTIAL", str(partial))
    monkeypatch.setattr(bench, "_ROTATED", False)
    partial.write_text('{"round": "previous"}')
    bench.save_partial({"round": "current"})
    prev = tmp_path / "bench_partial.prev.json"
    assert json.load(open(prev)) == {"round": "previous"}
    assert json.load(open(partial)) == {"round": "current"}
    # second write of the SAME run updates in place, no double rotation
    bench.save_partial({"round": "current2"})
    assert json.load(open(prev)) == {"round": "previous"}
    assert json.load(open(partial)) == {"round": "current2"}
