"""Tier D: the static jaxpr cost/memory model (analysis/costmodel.py),
budget contracts (analysis/budgets.py + the contract engine), and the
scripts/brcost.py gate/ladder surfaces.

The golden tables pin the 2026-08 walk of the h2o2-fixture traces in
WIDE bands (2x): the model's job is catching structural regressions (an
accidental O(n^3) op, a dropped Pallas kernel, a residency doubling),
not flop-exact bookkeeping across jax versions — the band rationale
lives in docs/development.md "Known model error".
"""

import importlib.util
import json
import math
import pathlib
import sys

import pytest

from batchreactor_tpu.analysis import (Budget, Cost, CostProbe,
                                       check_budget, contract_cost_table,
                                       cost_jaxpr, estimate_rung, fits_hbm,
                                       lu32p_vmem_bytes, run_contracts)
from batchreactor_tpu.analysis.costmodel import (V5E_HBM_BYTES,
                                                 VMEM_BUDGET_BYTES)

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).parent / "fixtures"


def _load_brcost():
    """Import scripts/brcost.py as a module (it is a script, not a
    package member) for the gate-function unit tests."""
    spec = importlib.util.spec_from_file_location(
        "brcost", str(REPO / "scripts" / "brcost.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def table():
    """ONE full trace of every registered contract on the vendored
    fixtures — shared across the golden/gate/stats tests (the build is
    the expensive part; every assertion after it is arithmetic)."""
    return contract_cost_table(fixtures_dir=str(FIXTURES))


# --- golden cost tables ---------------------------------------------------

# (key, flops_lo, flops_hi, peak_hi_bytes): 2x bands around the 2026-08
# walk values (h2o2 fixture, S=9) for every contracted program family
_GOLDEN = [
    ("bdf-step",                              2.6e4, 1.1e5, 80_000),
    ("bdf-step-economy",                      2.6e4, 1.1e5, 110_000),
    ("bdf-step-lu32p",                        2.7e4, 1.1e5, 81_000),
    ("sdirk-step",                            4.5e4, 1.9e5, 76_000),
    ("rhs-modes/gas-rhs",                     4.9e3, 2.0e4, 33_000),
    ("rhs-modes/gas-jac",                     1.0e4, 4.3e4, 39_000),
    ("rhs-modes/surf-rhs",                    7.0e2, 3.1e3, 9_000),
    ("rhs-modes/coupled-rhs",                 5.7e3, 2.4e4, 39_000),
    ("rhs-modes/udf-rhs",                     8.0e1, 4.0e2, 1_000),
    ("energy-eqns/energy-bdf-step",           3.6e4, 1.5e5, 82_000),
    ("mech-padding/gas-rhs-padded",           7.0e3, 2.9e4, 49_000),
    ("sens-forward-step",                     6.9e4, 2.8e5, 96_000),
    ("sens-adjoint-grad",                     1.0e7, 4.1e7, 250_000),
    ("sweep-segment/segment-pipelined-step",  4.8e4, 2.0e5, 106_000),
    ("sweep-segment-bucket/segment-bucket-padded",
                                              9.2e4, 3.8e5, 155_000),
    ("sweep-compact/sweep-compact-admit",     1.4e2, 6.0e2, 17_000),
]


def test_every_contract_is_costed(table):
    """All 13 registered contracts produce at least one table row —
    the Identical-only sweep contracts via their explicit CostProbe."""
    from batchreactor_tpu.analysis.contracts import _REGISTRY

    covered = {k.split("/")[0] for k in table}
    missing = set(_REGISTRY) - covered
    assert not missing, f"contracts with no cost row: {sorted(missing)}"
    assert len(table) >= 25


def test_golden_cost_bands(table):
    errs = []
    for key, lo, hi, peak_hi in _GOLDEN:
        c = table.get(key)
        if c is None:
            errs.append(f"{key}: missing from table ({sorted(table)})")
            continue
        if not (lo <= c.flops <= hi):
            errs.append(f"{key}: flops {c.flops} outside [{lo}, {hi}]")
        if not (0 < c.peak_bytes <= peak_hi):
            errs.append(f"{key}: peak {c.peak_bytes} outside (0, {peak_hi}]")
    assert not errs, "\n".join(errs)


def test_structural_orderings(table):
    """The orderings the physics dictates, jax-version independent:
    a Jacobian costs more than its RHS, a solver step more than either,
    SDIRK's 5 stages more than BDF's 1, adjoint more than forward."""
    t = {k: v.flops for k, v in table.items()}
    assert t["rhs-modes/gas-jac"] > t["rhs-modes/gas-rhs"]
    assert t["bdf-step"] > t["rhs-modes/gas-jac"]
    assert t["sdirk-step"] > t["bdf-step"]
    assert t["sens-adjoint-grad"] > t["sens-forward-step"] > t["bdf-step"]
    # loop structure: step programs carry while loops, RHS programs none
    assert table["bdf-step"].n_while > 0
    assert table["rhs-modes/gas-rhs"].n_while == 0


def test_stats_identity(table):
    """cost(stats=True) == cost(stats=False) + counter-block delta:
    the stats fork adds a small positive tally cost and nothing else —
    the static twin of the obs zero-overhead-when-off contract."""
    for plain, stats in [("bdf-step", "bdf-step/bdf-step-stats"),
                         ("sdirk-step", "sdirk-step/sdirk-step-stats"),
                         ("sweep-segment/segment-pipelined-step",
                          "sweep-segment/segment-pipelined-step-stats")]:
        delta = table[stats].flops - table[plain].flops
        assert delta >= 0, f"{stats} cheaper than {plain}?"
        assert delta <= 0.02 * table[plain].flops, \
            f"{stats} counter block costs {delta} flops (> 2%)"
        assert table[stats].transcendentals == table[plain].transcendentals


# --- the lu32p VMEM contract ----------------------------------------------

def test_lu32p_vmem_fit_both_ways(table):
    """The traced fixture kernel's VMEM footprint matches the closed
    form and fits; a mechanism too large for VMEM is caught BEFORE a
    chip session (the n=1500 no-fit direction)."""
    c = table["bdf-step-lu32p"]
    assert c.n_pallas >= 1, "lu32p program lost its pallas_call"
    assert c.vmem_bytes == lu32p_vmem_bytes(9)
    assert c.vmem_bytes < VMEM_BUDGET_BYTES
    assert lu32p_vmem_bytes(1500) > VMEM_BUDGET_BYTES
    # non-Pallas programs must not report phantom VMEM
    assert table["bdf-step"].vmem_bytes == 0


def test_lu32p_vmem_budget_contract_evaluates(table):
    """The armed vmem_bytes budget on bdf-step-lu32p passes on the
    fixture, and a seeded too-small ceiling fails loudly."""
    c = table["bdf-step-lu32p"]
    ok = check_budget("x", "m", Budget(vmem_bytes=VMEM_BUDGET_BYTES), c)
    assert ok == []
    bad = check_budget("x", "m", Budget(vmem_bytes=c.vmem_bytes - 1), c)
    assert [f.rule for f in bad] == ["budget-vmem"]


# --- budget contracts through the real engine -----------------------------

def test_budgeted_contracts_pass_on_fixtures():
    findings = run_contracts(fixtures_dir=str(FIXTURES),
                             select={"bdf-step", "rhs-modes"},
                             budgets=True)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_over_budget_contract_fails_loudly():
    """A contract whose program blows its armed budget produces
    budget-flops/budget-peak-bytes findings (never a silent pass), and
    a budget with no jaxpr-bearing obligation is itself a finding."""
    from batchreactor_tpu.analysis.contracts import (_REGISTRY, Pure,
                                                     program_contract)

    @program_contract("tmp-over-budget",
                      budget=Budget(flops_per_step=(1.0, 2.0),
                                    peak_bytes=1))
    def _tmp(h):
        yield Pure("tmp", h.jaxpr(h.rhs, 0.0, h.y0, h.cfg))

    @program_contract("tmp-unbound", budget=Budget(flops_per_step=(1, 2)))
    def _tmp2(h):
        return
        yield

    try:
        findings = run_contracts(
            fixtures_dir=str(FIXTURES),
            select={"tmp-over-budget", "tmp-unbound"}, budgets=True)
        rules = sorted(f.rule for f in findings)
        assert "budget-flops" in rules
        assert "budget-peak-bytes" in rules
        assert "budget-unbound" in rules
        # findings name the program and the measured-vs-budget numbers
        flops_f = [f for f in findings if f.rule == "budget-flops"][0]
        assert "tmp-over-budget" in flops_f.message
    finally:
        _REGISTRY.pop("tmp-over-budget", None)
        _REGISTRY.pop("tmp-unbound", None)


def test_budgets_off_by_default():
    """Without budgets=True the same selection reports nothing — tier C
    consumers see no cost findings."""
    from batchreactor_tpu.analysis.contracts import (_REGISTRY, Pure,
                                                     program_contract)

    @program_contract("tmp-over-budget2",
                      budget=Budget(flops_per_step=(1.0, 2.0)))
    def _tmp(h):
        yield Pure("tmp", h.jaxpr(h.rhs, 0.0, h.y0, h.cfg))

    try:
        findings = run_contracts(fixtures_dir=str(FIXTURES),
                                 select={"tmp-over-budget2"})
        assert [f for f in findings if f.rule.startswith("budget")] == []
    finally:
        _REGISTRY.pop("tmp-over-budget2", None)


# --- the stdlib estimator: calibration, S^3, HBM fit ----------------------

def test_estimator_calibrated_against_walker(table):
    """estimate_rung's closed form lands within the documented ~3x band
    of the real jaxpr walk on the fixture shape (B=1, S=9, R=29,
    jac_window=1) — the number the HBM ladder and warm_cache columns
    are built from."""
    est = estimate_rung(1, 9, 29, method="bdf", itemsize=8)
    measured = table["bdf-step"].flops
    ratio = est["flops_per_lane_step"] / measured
    assert 1 / 3 < ratio < 3, (est["flops_per_lane_step"], measured)
    est5 = estimate_rung(1, 9, 29, method="sdirk")
    ratio5 = est5["flops_per_lane_step"] / table["sdirk-step"].flops
    assert 1 / 3 < ratio5 < 3


def test_s_ladder_shows_cubic_wall():
    """Doubling S multiplies the per-lane step cost by -> 8x once LU
    dominates: the dense-Newton S^3 curve (ROADMAP 4) the brcost
    --s-ladder report renders."""
    f = {S: estimate_rung(256, S)["flops_per_lane_step"]
         for S in (256, 512, 1024)}
    assert 6.0 < f[512] / f[256] < 8.5
    assert 6.5 < f[1024] / f[512] < 8.5
    # log-log slope over the asymptotic leg
    slope = (math.log(f[1024]) - math.log(f[512])) / math.log(2)
    assert 2.7 < slope < 3.1
    # and at small S the jac/rhs terms still matter: the ratio is NOT 8
    small = estimate_rung(256, 8)["flops_per_lane_step"]
    assert estimate_rung(256, 16)["flops_per_lane_step"] / small < 6.0


def test_hbm_ladder_fit_both_ways():
    """B=512 x gri30 fits a v5e; B=2M x a 200-species mechanism does
    not — and the fit flips exactly at the headroom product."""
    small = estimate_rung(512, 53, 325)
    assert fits_hbm(small)
    huge = estimate_rung(2_000_000, 200, 1000)
    assert not fits_hbm(huge)
    assert huge["hbm_bytes"] > 0.8 * V5E_HBM_BYTES
    edge = dict(small, hbm_bytes=int(0.8 * V5E_HBM_BYTES) + 1)
    assert not fits_hbm(edge)
    assert fits_hbm(edge, headroom=1.0)


def test_estimator_shape_flags():
    est = estimate_rung(8, 10)
    assert est["r_assumed"] and est["R"] == 40
    est = estimate_rung(8, 10, 29, energy=True)
    assert not est["r_assumed"] and est["n"] == 11
    assert estimate_rung(8, 10, linsolve="lu32p")["vmem_bytes"] == \
        lu32p_vmem_bytes(10)
    assert estimate_rung(8, 10, linsolve="lu")["vmem_bytes"] == 0
    # jac_window amortizes the jac+lu term and ONLY that term
    jw1 = estimate_rung(8, 40, jac_window=1)["flops_per_lane_step"]
    jw8 = estimate_rung(8, 40, jac_window=8)["flops_per_lane_step"]
    assert jw8 < jw1


# --- the brcost gate ------------------------------------------------------

class TestCostGate:
    def test_banked_baseline_passes(self, table):
        """The committed CI baseline accepts the current table — the
        cost-gate job is green at head."""
        brcost = _load_brcost()
        with open(FIXTURES / "cost_gate_baseline.json") as f:
            baseline = json.load(f)
        assert baseline["schema"] == brcost.GATE_SCHEMA
        failures, lines = brcost.run_gate(baseline, table)
        assert failures == [], "\n".join(failures)
        assert len(lines) >= 50

    def test_regression_and_missing_program_fail(self, table):
        brcost = _load_brcost()
        baseline = brcost.make_baseline(table, "test")
        ok, _ = brcost.run_gate(baseline, table)
        assert ok == []
        # a silent 3x flop regression trips the band
        shrunk = json.loads(json.dumps(baseline))
        shrunk["programs"]["bdf-step"]["flops"]["max"] = 1.0
        failures, _ = brcost.run_gate(shrunk, table)
        assert any("bdf-step flops" in f for f in failures)
        # a banked program vanishing from the registry fails loudly
        t2 = dict(table)
        del t2["bdf-step"]
        failures, _ = brcost.run_gate(baseline, t2)
        assert any("disappeared" in f for f in failures)

    def test_gate_rejects_unknown_schema_and_metric(self, table):
        brcost = _load_brcost()
        with pytest.raises(ValueError, match="schema"):
            brcost.run_gate({"schema": "bogus-v9", "programs": {}}, table)
        with pytest.raises(ValueError, match="unknown cost metric"):
            brcost.run_gate(
                {"schema": brcost.GATE_SCHEMA,
                 "programs": {"bdf-step": {"walls": {"max": 1}}}}, table)

    def test_ladder_modes_need_no_jax(self):
        """--ladder/--s-ladder run as a subprocess with jax imports
        poisoned — the pre-chip go/no-go must work on a host with a
        broken accelerator stack."""
        import subprocess

        r = subprocess.run(
            [sys.executable, "-c",
             "import sys, runpy\n"
             "sys.modules['jax'] = None\n"
             "sys.argv = ['brcost', '--ladder', '--s-ladder', '--json']\n"
             f"runpy.run_path({str(REPO / 'scripts' / 'brcost.py')!r}, "
             f"run_name='__main__')"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        out = json.loads(r.stdout)
        assert out["ladder"] and out["s_ladder"]["rows"]
        assert 2.5 < out["s_ladder"]["loglog_slope"] < 3.1


# --- Cost dataclass arithmetic --------------------------------------------

def test_cost_add_scaled():
    a = Cost(flops=10, transcendentals=1, bytes_moved=100, peak_bytes=50)
    b = Cost(flops=3, transcendentals=2, bytes_moved=30, peak_bytes=80,
             n_while=1)
    a.add_scaled(b, 4)
    assert a.flops == 22 and a.transcendentals == 9
    assert a.bytes_moved == 220
    assert a.peak_bytes == 80            # peaks max, never sum
    assert a.n_while == 1                # structure, not trip-scaled
    d = a.as_dict()
    assert set(d) >= {"flops", "bytes_moved", "peak_bytes", "vmem_bytes"}
