"""Multi-process (multi-host analog) sweep tier: 2 OS processes x 4 virtual
CPU devices each, joined through jax.distributed + Gloo — the DCN story
exercised for real, not just a single-process mesh (SURVEY.md §2c: the
reference has nothing here; our scaling surface must).

The test spawns both processes from a child script (jax.distributed cannot
re-initialize inside a pytest process that already has a backend), waits
for both, and asserts the multihost sweep result matches a single-process
reference solve bit-for-tolerance.

The elastic (wedge-resilient) tier below it has the opposite topology:
NO collectives, coordination through the shared checkpoint dir only
(``multihost.elastic_checkpointed_sweep``), which is exactly what lets
its dead-process test kill one process mid-sweep and still finish."""

import json
import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

CHILD = r"""
import json, os, sys
pid, n, port, lib = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from batchreactor_tpu.parallel import multihost as mh

mh.initialize(coordinator_address=f"localhost:{port}", num_processes=n,
              process_id=pid)
assert len(jax.devices()) == 4 * n, jax.devices()

import jax.numpy as jnp
import numpy as np
import batchreactor_tpu as br
from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
from batchreactor_tpu.parallel.grid import sweep_solution_vectors
from batchreactor_tpu.solver.sdirk import SUCCESS

gm = br.compile_gaschemistry(f"{lib}/h2o2.dat")
th = br.create_thermo(list(gm.species), f"{lib}/therm.dat")
sp = list(gm.species)
B = 16  # 2 lanes per device across the 8 global devices
X = np.zeros((B, len(sp)))
X[:, sp.index("H2")], X[:, sp.index("O2")], X[:, sp.index("N2")] = .25, .25, .5
T = jnp.linspace(1150.0, 1350.0, B)
y0s = np.asarray(sweep_solution_vectors(jnp.asarray(X), th.molwt, T, 1e5))
rhs, jac = make_gas_rhs(gm, th), make_gas_jac(gm, th)

res = mh.ensemble_solve_multihost(rhs, y0s, 0.0, 2e-4, {"T": np.asarray(T)},
                                  jac=jac, rtol=1e-6, atol=1e-10)
assert np.all(np.asarray(res.status) == SUCCESS), res.status
if pid == 0:
    print("RESULT " + json.dumps({"y": np.asarray(res.y).tolist(),
                                  "t": np.asarray(res.t).tolist()}))
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_MP_PROBE = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=n, process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.asarray(jax.devices()), ("b",))
local = jnp.zeros((2,))
g = jax.make_array_from_single_device_arrays(
    (2 * n,), NamedSharding(mesh, P("b")),
    [jax.device_put(local[i:i + 1], d) for i, d in
     enumerate(jax.local_devices())])
out = jax.jit(lambda a: a + 1.0)(g)
np.asarray(multihost_utils.process_allgather(out))
print("MP_OK")
"""

_mp_capability = {}


def _multiprocess_cpu_capable(tmp_path_factory):
    """Capability probe: can THIS jax build actually execute a jitted
    computation on a multi-process CPU mesh?  Some CPU backends reject
    it outright ('Multiprocess computations aren't implemented on the
    CPU backend' — the pre-existing PR-7 failure), which is an
    environment limitation, not a regression: the dependent test skips
    instead of failing.  One probe per session (two bare-jax processes,
    a few seconds); any nonzero exit or missing marker means incapable."""
    if "ok" not in _mp_capability:
        d = tmp_path_factory.mktemp("mp_probe")
        script = d / "probe.py"
        script.write_text(_MP_PROBE)
        port = _free_port()
        env = {**os.environ, "PYTHONPATH": str(REPO)}
        env.pop("XLA_FLAGS", None)
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(d)) for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=120)
                outs.append(out)
            _mp_capability["ok"] = all(
                p.returncode == 0 and "MP_OK" in out
                for p, out in zip(procs, outs))
        except subprocess.TimeoutExpired:
            _mp_capability["ok"] = False
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        _mp_capability["detail"] = "\n".join(o[-500:] for o in outs)
    return _mp_capability["ok"]


@pytest.mark.slow
def test_two_process_global_mesh_matches_single(tmp_path, tmp_path_factory,
                                                lib_dir):
    if not _multiprocess_cpu_capable(tmp_path_factory):
        pytest.skip("CPU backend lacks multi-process collectives "
                    "(probe failed: "
                    f"{_mp_capability['detail'].splitlines()[-1:]})" )
    child = tmp_path / "mh_child.py"
    child.write_text(CHILD)
    port = _free_port()
    env = {**os.environ, "PYTHONPATH": str(REPO)}
    env.pop("XLA_FLAGS", None)  # child pins its own 4-device count
    procs = [subprocess.Popen(
        [sys.executable, str(child), str(i), "2", str(port), lib_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=str(tmp_path)) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        # a Gloo rendezvous hang (port race, dead peer) must not leak two
        # live JAX processes pinning the port across reruns
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    payload = next(line for line in outs[0].splitlines()
                   if line.startswith("RESULT "))
    got = json.loads(payload[len("RESULT "):])

    # single-process reference on the plain 8-virtual-device CPU mesh
    import jax.numpy as jnp

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel import ensemble_solve
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors

    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    sp = list(gm.species)
    B = 16
    X = np.zeros((B, len(sp)))
    X[:, sp.index("H2")], X[:, sp.index("O2")] = 0.25, 0.25
    X[:, sp.index("N2")] = 0.5
    T = jnp.linspace(1150.0, 1350.0, B)
    y0s = sweep_solution_vectors(jnp.asarray(X), th.molwt, T, 1e5)
    ref = ensemble_solve(make_gas_rhs(gm, th), y0s, 0.0, 2e-4, {"T": T},
                         jac=make_gas_jac(gm, th), rtol=1e-6, atol=1e-10)
    np.testing.assert_allclose(np.asarray(got["y"]), np.asarray(ref.y),
                               rtol=1e-9, atol=1e-14)
    np.testing.assert_allclose(np.asarray(got["t"]), np.asarray(ref.t),
                               rtol=1e-12)


# --------------------------------------------------------------------------
# elastic tier: dead-process chunk reassignment (resilience/)
# --------------------------------------------------------------------------
ELASTIC_CHILD = r"""
import json, os, sys
pid, n, ckpt = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from batchreactor_tpu.obs.recorder import Recorder
from batchreactor_tpu.parallel import multihost as mh
from batchreactor_tpu.solver.sdirk import SUCCESS


def rhs(t, y, cfg):
    return -cfg["k"] * y


B = 16
y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
cfgs = {"k": jnp.logspace(1.0, 2.0, B)}
rec = Recorder()
res = mh.elastic_checkpointed_sweep(
    rhs, y0s, 0.0, 1.0, cfgs, ckpt, process_id=pid, num_processes=n,
    chunk_size=4, heartbeat_s=0.2, timeout_s=120.0, recorder=rec,
    chunk_log=lambda m: print(m, file=sys.stderr, flush=True))
assert np.all(np.asarray(res.status) == SUCCESS), res.status
_s, _e, counters = rec.snapshot()
print("RESULT " + json.dumps({"pid": pid,
                              "y": np.asarray(res.y).tolist(),
                              "t": np.asarray(res.t).tolist(),
                              "counters": counters}))
"""


def rhs(t, y, cfg):
    """Module-level so its qualname matches ELASTIC_CHILD's ``rhs`` —
    the sweep fingerprint hashes qualname + bytecode, and the in-test
    resume below must land in the children's checkpoint dir."""
    return -cfg["k"] * y


@pytest.mark.slow
def test_elastic_sweep_survivor_completes_dead_process_chunks(tmp_path):
    """Satellite: one process is killed mid-sweep (injected SIGKILL-class
    exit before its chunk save — file missing, claim stale); the survivor
    detects the dead heartbeat, steals the chunk, and completes the sweep
    with results bit-exact vs a single-process run."""
    child = tmp_path / "elastic_child.py"
    child.write_text(ELASTIC_CHILD)
    ckpt = tmp_path / "ck"
    env = {**os.environ, "PYTHONPATH": str(REPO)}
    # chunks round-robin over 2 processes: p1 owns chunks 1 and 3.  The
    # injected kill fires before chunk 1's save — p1's FIRST chunk, whose
    # claim lands at startup, so the faster p0 cannot legitimately claim
    # it first (its other chunk 3 may be picked up as ordinary idle work
    # stealing before p1 dies; chunk 1 forces the dead-owner path)
    env_victim = {**env, "BR_FAULT_INJECT": "kill:chunk=1"}
    procs = [
        subprocess.Popen([sys.executable, str(child), "0", "2", str(ckpt)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True,
                         cwd=str(tmp_path)),
        subprocess.Popen([sys.executable, str(child), "1", "2", str(ckpt)],
                         env=env_victim, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True,
                         cwd=str(tmp_path)),
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    # the victim died to the injected kill (os._exit(137), the SIGKILL rc)
    assert procs[1].returncode == 137, outs[1][-3000:]
    assert procs[0].returncode == 0, outs[0][-3000:]
    payload = next(line for line in outs[0].splitlines()
                   if line.startswith("RESULT "))
    got = json.loads(payload[len("RESULT "):])
    # the survivor recorded the reassignment (counter + log line)
    assert got["counters"].get("chunks_reassigned") == 1
    assert "reassigned chunk 1 from dead p1" in outs[0]
    # claim file records the theft for forensics
    claim = json.load(open(ckpt / "chunk_00001.npz.claim"))
    assert claim == {"pid": 0, "time": claim["time"], "stolen_from": 1}

    # single-process reference: bit-exact (same CPU program, any host)
    import jax.numpy as jnp

    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    B = 16
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    cfgs = {"k": jnp.logspace(1.0, 2.0, B)}
    ref = checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "ref"), chunk_size=4)
    np.testing.assert_array_equal(np.asarray(got["y"]), np.asarray(ref.y))
    np.testing.assert_array_equal(np.asarray(got["t"]), np.asarray(ref.t))

    # the directory interoperates with single-process resume: every chunk
    # loads, nothing re-solves (honest fingerprint across reassignment)
    from batchreactor_tpu.obs.recorder import Recorder

    rec = Recorder()
    resumed = checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, str(ckpt),
                                 chunk_size=4, recorder=rec)
    _spans, events, _ctrs = rec.snapshot()
    assert sum(e["name"] == "chunk_loaded" for e in events) == 4
    np.testing.assert_array_equal(np.asarray(resumed.y), np.asarray(ref.y))
