"""Mesh-sharded ensemble sweep tests on the 8-virtual-device CPU mesh
(SURVEY.md §4: xla_force_host_platform_device_count trick)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.ops.rhs import make_gas_rhs
from batchreactor_tpu.parallel import (
    ensemble_solve,
    ignition_delay,
    make_mesh,
    pad_batch,
    temperature_sweep,
)
from batchreactor_tpu.solver.sdirk import SUCCESS
from batchreactor_tpu.utils.composition import density, mole_to_mass


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    sp = list(gm.species)
    x = np.zeros(9)
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.25, 0.25, 0.5
    rho = density(jnp.asarray(x), th.molwt, 1173.0, 1e5)
    y0 = mole_to_mass(jnp.asarray(x), th.molwt) * rho
    return gm, th, y0


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    assert pad_batch(9, mesh) == 16
    assert pad_batch(8, mesh) == 8


def test_temperature_sweep_sharded(h2o2):
    """16-lane T sweep sharded over 8 devices: all lanes succeed, hotter
    lanes ignite (H2 consumed) faster."""
    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    mesh = make_mesh()
    T_grid = jnp.linspace(1100.0, 1400.0, 16)
    res = temperature_sweep(rhs, y0, T_grid, 1e-2, mesh=mesh,
                            dt0=1e-12, max_steps=100_000)
    assert res.y.shape == (16, 9)
    assert np.all(np.asarray(res.status) == SUCCESS)
    # output actually carries the batch sharding (one shard per device)
    assert len(res.y.sharding.device_set) == 8

    sp = list(gm.species)
    h2_final = np.asarray(res.y)[:, sp.index("H2")]
    # at 10 ms: the hottest lane has burned more H2 than the coldest
    assert h2_final[-1] < h2_final[0]


def test_per_lane_failure_isolation(h2o2):
    """A poisoned lane (NaN initial state) reports failure without breaking
    its neighbours — the per-lane status surface (SURVEY.md §5)."""
    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    y0s = jnp.stack([y0, y0.at[0].set(jnp.nan), y0, y0])
    cfg = {"T": jnp.full((4,), 1173.0)}
    res = ensemble_solve(rhs, y0s, 0.0, 1e-5, cfg, dt0=1e-12)
    status = np.asarray(res.status)
    assert status[1] != SUCCESS
    assert status[0] == SUCCESS and status[2] == SUCCESS


def test_ignition_delay_extraction(h2o2):
    """OH-peak ignition delay decreases monotonically with temperature
    across an 8-lane sweep (isothermal marker per SURVEY.md §7.8)."""
    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    sp = list(gm.species)
    T_grid = jnp.linspace(1150.0, 1450.0, 8)
    res = temperature_sweep(rhs, y0, T_grid, 5e-3, mesh=make_mesh(),
                            n_save=2048, dt0=1e-12)
    assert np.all(np.asarray(res.status) == SUCCESS)
    # H2 half-consumption marker
    tau = np.asarray(ignition_delay(res.ts, res.ys, sp.index("H2"),
                                    mode="half"))
    assert np.all(np.isfinite(tau)) and np.all(tau > 0)
    assert np.all(np.diff(tau) < 0), f"delays not monotone: {tau}"


def test_sharded_matches_unsharded(h2o2):
    """Mesh sharding must not change the numerics: sharded and single-device
    sweeps agree bitwise-close."""
    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    T_grid = jnp.linspace(1150.0, 1300.0, 8)
    a = temperature_sweep(rhs, y0, T_grid, 1e-4, mesh=make_mesh(), dt0=1e-12)
    b = temperature_sweep(rhs, y0, T_grid, 1e-4, mesh=None, dt0=1e-12)
    np.testing.assert_allclose(np.asarray(a.y), np.asarray(b.y), rtol=1e-12)


def test_condition_grid_and_premixed():
    from batchreactor_tpu.parallel import condition_grid, premixed_mole_fracs

    g = condition_grid(T=jnp.linspace(1000., 1300., 4),
                       phi=jnp.linspace(0.5, 2.0, 3))
    assert g["T"].shape == (12,) and g["phi"].shape == (12,)
    # lane-major ordering: T varies slowest
    assert float(g["T"][0]) == float(g["T"][2]) == 1000.0
    assert float(g["phi"][0]) == 0.5 and float(g["phi"][1]) == 1.25

    species = ("CH4", "O2", "N2", "AR")
    x = premixed_mole_fracs(species, "CH4", jnp.array([1.0]), stoich_o2=2.0,
                            diluent="N2", o2_to_diluent=3.76)
    # phi=1 CH4/air: x_CH4 = 1/(1+2+7.52) = 0.0950
    np.testing.assert_allclose(float(x[0, 0]), 1.0 / 10.52, rtol=1e-12)
    np.testing.assert_allclose(float(np.asarray(x).sum()), 1.0, rtol=1e-12)
    x2 = premixed_mole_fracs(species, "CH4", jnp.array([0.5, 2.0]),
                             stoich_o2=2.0)
    # richer mixture -> more fuel fraction
    assert float(x2[1, 0]) > float(x2[0, 0])


def test_sweep_solution_vectors_matches_api(h2o2):
    from batchreactor_tpu.api import get_solution_vector
    from batchreactor_tpu.parallel import sweep_solution_vectors

    gm, th, y0 = h2o2
    sp = list(gm.species)
    x = np.zeros(9)
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.25, 0.25, 0.5
    xs = jnp.broadcast_to(jnp.asarray(x), (3, 9))
    Ts = jnp.array([1100.0, 1173.0, 1250.0])
    ys = sweep_solution_vectors(xs, th.molwt, Ts, 1e5)
    for i, T in enumerate([1100.0, 1173.0, 1250.0]):
        ref = get_solution_vector(x, th.molwt, T, 1e5)
        np.testing.assert_allclose(np.asarray(ys[i]), np.asarray(ref),
                                   rtol=1e-12)


def test_sweep_report(h2o2):
    from batchreactor_tpu.parallel import sweep_report

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    y0s = jnp.stack([y0, y0.at[0].set(jnp.nan), y0])
    cfg = {"T": jnp.array([1173.0, 1173.0, 1200.0])}
    res = ensemble_solve(rhs, y0s, 0.0, 1e-5, cfg, dt0=1e-12)
    rep = sweep_report(res, cfg)
    assert rep["n_lanes"] == 3
    assert rep["counts"]["success"] == 2
    assert rep["failed_lanes"] == [1]
    assert rep["failed_conditions"]["T"] == [1173.0]


def test_checkpointed_sweep_resume(h2o2, tmp_path):
    """Chunked checkpoint/resume: second invocation loads chunks from disk
    (no device work) and reproduces the full-result concatenation exactly."""
    from batchreactor_tpu.parallel import checkpointed_sweep

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    B = 6
    y0s = jnp.broadcast_to(y0, (B, 9))
    cfgs = {"T": jnp.linspace(1150.0, 1300.0, B)}
    ck = str(tmp_path / "sweep")
    res1 = checkpointed_sweep(rhs, y0s, 0.0, 1e-5, cfgs, ck, chunk_size=4,
                              dt0=1e-12)
    assert res1.y.shape == (B, 9)
    import os
    files = sorted(os.listdir(ck))
    assert files == ["chunk_00000.npz", "chunk_00001.npz", "manifest.json"]
    # tamper-proof resume: drop one chunk, re-run -> only that chunk resolves
    os.remove(os.path.join(ck, "chunk_00001.npz"))
    res2 = checkpointed_sweep(rhs, y0s, 0.0, 1e-5, cfgs, ck, chunk_size=4,
                              dt0=1e-12)
    np.testing.assert_allclose(np.asarray(res2.y), np.asarray(res1.y),
                               rtol=1e-12)
    # manifest mismatch fails loudly
    with pytest.raises(ValueError):
        checkpointed_sweep(rhs, y0s, 0.0, 2e-5, cfgs, ck, chunk_size=4,
                           dt0=1e-12)


def test_checkpointed_sweep_async_save_failure(h2o2, tmp_path, monkeypatch):
    """The npz save runs on a background thread (overlapped with the next
    chunk's solve); a save failure must still fail the sweep call itself —
    a silently lost chunk would surface as a corrupt resume much later."""
    import batchreactor_tpu.parallel.checkpoint as ckm

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    B = 6
    y0s = jnp.broadcast_to(y0, (B, 9))
    cfgs = {"T": jnp.linspace(1150.0, 1300.0, B)}

    def boom(path, res, cfgs=None):
        raise OSError("injected: disk full")

    monkeypatch.setattr(ckm, "save_result", boom)
    with pytest.raises(OSError, match="injected"):
        ckm.checkpointed_sweep(rhs, y0s, 0.0, 1e-5, cfgs,
                               str(tmp_path / "sweep"), chunk_size=4,
                               dt0=1e-12)


def test_phases_timer():
    from batchreactor_tpu.utils.profiling import Phases

    ph = Phases()
    with ph("parse"):
        pass
    with ph("solve", block=jnp.ones(4)):
        pass
    with ph("solve"):
        pass
    s = ph.summary()
    assert set(s) == {"parse", "solve"} and all(v >= 0 for v in s.values())
    assert ph.counts["solve"] == 2
    assert "solve" in ph.pretty()


def test_segmented_matches_unsegmented(h2o2):
    """Segmented execution (bounded device launches + host continuation)
    must reproduce the monolithic solve: same final states at tolerance
    scale, same ignition delays from the carried observer fold."""
    from batchreactor_tpu.parallel import (ensemble_solve_segmented,
                                           ignition_observer)

    gm, th, y0 = h2o2
    sp = list(gm.species)
    rhs = make_gas_rhs(gm, th)
    B = 4
    y0s = jnp.broadcast_to(y0, (B, 9))
    cfgs = {"T": jnp.linspace(1200.0, 1400.0, B)}
    obs, obs0 = ignition_observer(sp.index("H2"), mode="half")
    # no dt0 pin: both paths must start from the same Hairer heuristic h0 —
    # the segmented driver computes its own first-segment h0, and under BDF
    # (the default) identical starts make segmented == monolithic bit-exact
    full = ensemble_solve(rhs, y0s, 0.0, 2e-3, cfgs,
                          observer=obs, observer_init=obs0)
    segs = []
    seg = ensemble_solve_segmented(
        rhs, y0s, 0.0, 2e-3, cfgs, segment_steps=64,
        observer=obs, observer_init=obs0,
        progress=lambda p: segs.append(p))
    assert len(segs) >= 2, "expected multiple segments at segment_steps=64"
    assert np.all(np.asarray(seg.status) == SUCCESS)
    np.testing.assert_allclose(np.asarray(seg.t), np.asarray(full.t),
                               rtol=1e-12)
    np.testing.assert_allclose(np.asarray(seg.y), np.asarray(full.y),
                               rtol=1e-5, atol=1e-14)
    np.testing.assert_allclose(np.asarray(seg.observed["tau"]),
                               np.asarray(full.observed["tau"]), rtol=5e-2)


def test_segmented_parks_failed_lanes(h2o2):
    """A terminally failed lane must not burn segment budget re-failing:
    its DT_UNDERFLOW status survives while healthy lanes complete."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented
    from batchreactor_tpu.solver.sdirk import DT_UNDERFLOW

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    y0s = jnp.stack([y0, y0.at[0].set(jnp.nan), y0])
    cfgs = {"T": jnp.full((3,), 1173.0)}
    res = ensemble_solve_segmented(rhs, y0s, 0.0, 1e-5, cfgs,
                                   segment_steps=64, dt_min_factor=1e-12)
    status = np.asarray(res.status)
    assert status[0] == SUCCESS and status[2] == SUCCESS
    assert status[1] == DT_UNDERFLOW


def test_segmented_trajectory_matches_unsegmented(h2o2):
    """n_save under segmentation: per-segment device buffers drained to the
    host must reproduce the monolithic trajectory row-for-row (same accepted
    steps — segmentation does not alter step-size control)."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    B = 3
    y0s = jnp.broadcast_to(y0, (B, 9))
    cfgs = {"T": jnp.linspace(1200.0, 1300.0, B)}
    # both sides use the first-step heuristic (segmented h<=0 carry-in
    # resolves to the same formula) so accepted steps align exactly
    full = ensemble_solve(rhs, y0s, 0.0, 2e-4, cfgs, n_save=4096)
    seg = ensemble_solve_segmented(rhs, y0s, 0.0, 2e-4, cfgs,
                                   segment_steps=64, n_save=4096)
    assert np.all(np.asarray(seg.status) == SUCCESS)
    n_full = np.asarray(full.n_saved)
    n_seg = np.asarray(seg.n_saved)
    np.testing.assert_array_equal(n_seg, n_full)
    for b in range(B):
        np.testing.assert_allclose(np.asarray(seg.ts)[b, :n_seg[b]],
                                   np.asarray(full.ts)[b, :n_full[b]],
                                   rtol=1e-12)
        np.testing.assert_allclose(np.asarray(seg.ys)[b, :n_seg[b]],
                                   np.asarray(full.ys)[b, :n_full[b]],
                                   rtol=1e-9, atol=1e-16)


def test_segmented_n_save_saturates(h2o2):
    """When total accepted steps exceed n_save, the first n_save rows are
    kept (same semantics as the unsegmented buffer)."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    y0s = jnp.broadcast_to(y0, (2, 9))
    cfgs = {"T": jnp.full((2,), 1250.0)}
    full = ensemble_solve(rhs, y0s, 0.0, 2e-4, cfgs, n_save=40)
    seg = ensemble_solve_segmented(rhs, y0s, 0.0, 2e-4, cfgs,
                                   segment_steps=64, n_save=40)
    assert int(seg.n_accepted[0]) > 40  # actually saturated
    np.testing.assert_array_equal(np.asarray(seg.n_saved), [40, 40])
    np.testing.assert_allclose(np.asarray(seg.ts), np.asarray(full.ts),
                               rtol=1e-12)


def test_sharded_matches_unsharded_bdf(h2o2):
    """BDF over the 8-virtual-device mesh == unsharded (method='bdf')."""
    from batchreactor_tpu.ops.rhs import make_gas_jac

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    jacf = make_gas_jac(gm, th)
    B = 8
    y0s = jnp.broadcast_to(y0, (B,) + y0.shape)
    cfgs = {"T": jnp.linspace(1133.0, 1213.0, B)}
    kw = dict(rtol=1e-6, atol=1e-10, jac=jacf, method="bdf")
    r_u = ensemble_solve(rhs, y0s, 0.0, 2e-4, cfgs, **kw)
    r_s = ensemble_solve(rhs, y0s, 0.0, 2e-4, cfgs, mesh=make_mesh(), **kw)
    assert np.all(np.asarray(r_u.status) == SUCCESS)
    np.testing.assert_array_equal(np.asarray(r_s.status),
                                  np.asarray(r_u.status))
    np.testing.assert_allclose(np.asarray(r_s.y), np.asarray(r_u.y),
                               rtol=1e-9, atol=1e-14)


@pytest.fixture(scope="module")
def h2oni(lib_dir, fixtures_dir):
    """h2o2 gas mechanism + synthetic H2-on-Ni surface mechanism — the
    smallest coupled-capable pair (9 gas species, fixtures/h2oni.xml)."""
    from batchreactor_tpu.models.surface import compile_mech

    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    sm = compile_mech(f"{fixtures_dir}/h2oni.xml", th, list(gm.species))
    return gm, th, sm


def test_surface_sweep_sharded_matches_unsharded(h2oni):
    """Surface-only chemistry under mesh sharding == unsharded — closes the
    VERDICT-r3 gap that only gas-only h2o2 ever ran on the virtual mesh
    (reference surface mode: /root/reference/src/BatchReactor.jl:366-367)."""
    _, th, sm = h2oni
    kw = dict(chem=br.Chemistry(surfchem=True), thermo_obj=th, md=sm,
              Asv=jnp.array([1.0, 5.0, 25.0, 125.0] * 4))
    a = br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               1050.0, 1e5, 1e-4, mesh=make_mesh(), **kw)
    b = br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               1050.0, 1e5, 1e-4, mesh=None, **kw)
    assert a["report"]["counts"]["success"] == 16
    np.testing.assert_allclose(a["covg"], b["covg"], rtol=1e-9, atol=1e-14)
    for s in th.species:
        np.testing.assert_allclose(a["x"][s], b["x"][s],
                                   rtol=1e-9, atol=1e-14)


def test_coupled_sweep_sharded_matches_unsharded(h2oni):
    """Coupled gas+surf chemistry (the reference's richest mode,
    /root/reference/src/BatchReactor.jl:368-370) under mesh sharding ==
    unsharded, including an uneven batch that exercises pad_to_mesh."""
    gm, th, sm = h2oni
    B = 12  # not a multiple of 8: pad_to_mesh must pad to 16 and slice back
    kw = dict(chem=br.Chemistry(surfchem=True, gaschem=True),
              thermo_obj=th, gmd=gm, smd=sm, Asv=10.0)
    T_grid = jnp.linspace(1000.0, 1150.0, B)
    a = br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               T_grid, 1e5, 1e-4, mesh=make_mesh(), **kw)
    b = br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               T_grid, 1e5, 1e-4, mesh=None, **kw)
    assert a["report"]["counts"]["success"] == B
    assert a["covg"].shape == b["covg"].shape == (B, len(sm.species))
    np.testing.assert_allclose(a["covg"], b["covg"], rtol=1e-9, atol=1e-14)
    for s in th.species:
        np.testing.assert_allclose(a["x"][s], b["x"][s],
                                   rtol=1e-9, atol=1e-14)


# --- pipelined segmented driver: equivalence & host-sync gates -------------
#
# The pipelined-vs-blocking contract is solver-driver plumbing, not
# chemistry, so these tests run a cheap stiff decay ODE: every traced
# program compiles in ~1 s where an h2o2 segment program costs tens —
# the h2o2-based segmented tests above already pin chemistry-on-segmented
# behavior, and the drivers are bit-exact regardless of RHS.

def _decay_rhs(t, y, cfg):
    """Per-lane stiff linear decay: lanes with larger k need more steps,
    so they terminate in different segments (mid-sweep termination)."""
    return -cfg["k"] * y


def _decay_setup(B=4, poison_lane=None):
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    if poison_lane is not None:
        y0s = y0s.at[poison_lane, 0].set(jnp.nan)
    cfgs = {"k": jnp.logspace(1.0, 2.5, B)}
    return y0s, cfgs


def _decay_observer():
    """Flat-dict observer fold (running max of y[0] + last accepted t):
    exercises the observer carry through parking and segment resume."""
    init = {"ymax": -jnp.inf, "t_last": jnp.nan}

    def obs(t, y, acc):
        return {"ymax": jnp.maximum(y[0], acc["ymax"]), "t_last": t}

    return obs, init


def _solve_result_fields(res):
    """Every value-carrying field of a SolveResult as np arrays (observed
    and stats flattened in), for bit-exact driver comparisons."""
    out = {f: np.asarray(getattr(res, f))
           for f in ("t", "y", "status", "n_accepted", "n_rejected",
                     "ts", "ys", "n_saved", "h")}
    if res.observed is not None:
        for k, v in res.observed.items():
            out[f"obs_{k}"] = np.asarray(v)
    if res.stats is not None:
        for k, v in res.stats.items():
            out[f"stat_{k}"] = np.asarray(v)
    return out


def _assert_bit_exact(a, b, ctx=""):
    fa, fb = _solve_result_fields(a), _solve_result_fields(b)
    assert fa.keys() == fb.keys(), (ctx, fa.keys(), fb.keys())
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k],
                                      err_msg=f"{ctx} field {k}")


@pytest.mark.parametrize("method", ["bdf", "sdirk"])
@pytest.mark.parametrize("n_save", [0, 256])
def test_pipelined_bit_exact_matrix(method, n_save):
    """The pipelined segmented driver (device-resident park logic, carry
    donation, async drain) must be BIT-EXACT against the blocking driver
    across solvers x trajectory modes x poll strides — including
    poll_every > max_segments (a single poll at the run-ahead cap, every
    trailing segment an all-parked no-op), mid-sweep termination (the k
    spread finishes lanes in different segments), and a DT_UNDERFLOW
    lane exercising the parked-lane splice."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented
    from batchreactor_tpu.solver.sdirk import DT_UNDERFLOW

    obs, obs0 = _decay_observer()
    y0s, cfgs = _decay_setup(B=4, poison_lane=1)
    # max_segments tight (the stiffest lane needs ~11): the
    # stride>max_segments case then caps its run-ahead at ~9 trailing
    # all-parked segments instead of burning the suite budget on no-ops
    # (SDIRK's zero-span re-entries reject segment_steps attempts each)
    kw = dict(segment_steps=16, max_segments=20, observer=obs,
              observer_init=obs0, n_save=n_save, method=method,
              dt_min_factor=1e-12)
    blocking = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                        pipeline=False, **kw)
    status = np.asarray(blocking.status)
    assert status[1] == DT_UNDERFLOW and np.all(np.delete(status, 1)
                                                == SUCCESS)
    assert int(blocking.n_accepted.max()) > 32  # spans >2 segments
    for poll_every in (1, 4, 50):
        piped = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                         pipeline=True,
                                         poll_every=poll_every, **kw)
        _assert_bit_exact(blocking, piped,
                          f"{method}/n_save={n_save}/poll={poll_every}")


def test_pipelined_mesh_sharded_bit_exact():
    """The mesh-sharded pipelined path — which drains per-lane buffers
    instead of the flat on-device gather (global destination indices
    would insert collectives into a collective-free program) — matches
    the blocking driver bit-exactly on the 8-virtual-device mesh,
    including n_save saturation (64 rows < ~108-173 accepted steps)."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented

    y0s, cfgs = _decay_setup(B=8)
    kw = dict(segment_steps=16, max_segments=64, n_save=64,
              mesh=make_mesh())
    blocking = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                        pipeline=False, **kw)
    assert np.all(np.asarray(blocking.n_saved) == 64)  # saturated
    piped = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                     pipeline=True, poll_every=4, **kw)
    _assert_bit_exact(blocking, piped, "mesh")


def test_pipelined_budget_parking_bit_exact():
    """The exact max_attempts budget — now latched on device — parks
    lanes with MAX_STEPS_REACHED at exactly the same segment, t, and
    attempt counts as the blocking driver's host-side ledger, and the
    device-side stats accumulator matches the host masked-add fold
    bit-for-bit."""
    from batchreactor_tpu.parallel import ensemble_solve_segmented
    from batchreactor_tpu.solver.sdirk import MAX_STEPS_REACHED

    y0s, cfgs = _decay_setup(B=4)
    # the cheapest lane needs ~108 attempts, the stiffest ~173: a budget
    # of 120 parks the stiff lanes mid-sweep while the cheap lane finishes
    kw = dict(segment_steps=16, max_segments=64, max_attempts=120,
              stats=True)
    blocking = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                        pipeline=False, **kw)
    # the budget must actually bite for the parking path to be exercised,
    # while cheap lanes finish inside it
    status = np.asarray(blocking.status)
    assert np.any(status == MAX_STEPS_REACHED) and np.any(status == SUCCESS)
    for poll_every in (1, 3):
        piped = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                         pipeline=True,
                                         poll_every=poll_every, **kw)
        _assert_bit_exact(blocking, piped, f"budget/poll={poll_every}")


def test_pipelined_host_sync_gate(monkeypatch):
    """Host-sync regression gate: the pipelined driver performs at most
    ceil(segments / poll_every) + 1 main-thread blocking fetches per
    sweep (polls + the final state fetch), where the blocking driver
    pays >= 2 per segment on this stats+trajectory workload — the
    per-segment halo PERF.md blames for the map-vs-rung gap cannot
    silently creep back."""
    import batchreactor_tpu.parallel.sweep as sweep_mod

    y0s, cfgs = _decay_setup(B=4)
    kw = dict(segment_steps=16, max_segments=64, n_save=256, stats=True)

    calls = []
    orig = sweep_mod._host_fetch

    def counting_fetch(x, recorder=None):
        calls.append(1)
        return orig(x, recorder)

    monkeypatch.setattr(sweep_mod, "_host_fetch", counting_fetch)

    segs = []
    sweep_mod.ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, pipeline=False,
        progress=lambda p: segs.append(p), **kw)
    blocking_calls, n_segments = len(calls), len(segs)
    assert n_segments >= 3, "workload too small to exercise the gate"
    assert blocking_calls >= 2 * n_segments  # >=1 status +1 stats per seg

    calls.clear()
    poll_every = 4
    sweep_mod.ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, pipeline=True,
        poll_every=poll_every, **kw)
    budget = -(-n_segments // poll_every) + 1
    assert len(calls) <= budget, (len(calls), budget, n_segments)


def test_pipelined_checkpoint_resume_bit_exact(tmp_path):
    """Checkpointed chunks running the pipelined gear reproduce the
    blocking gear's chunks bit-exactly, including chunks served from a
    resumed checkpoint directory."""
    import os

    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _decay_setup(B=6)
    kw = dict(segment_steps=16, max_steps=2000, n_save=128)
    blocking = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                  str(tmp_path / "blk"), chunk_size=3,
                                  pipeline=False, **kw)
    piped = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                               str(tmp_path / "pipe"), chunk_size=3,
                               pipeline=True, poll_every=4, **kw)
    _assert_bit_exact(blocking, piped, "checkpointed")
    # resume: drop one chunk, re-solve it through the pipelined gear only
    os.remove(str(tmp_path / "pipe" / "chunk_00001.npz"))
    resumed = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 str(tmp_path / "pipe"), chunk_size=3,
                                 pipeline=True, poll_every=4, **kw)
    _assert_bit_exact(blocking, resumed, "checkpointed-resume")


def test_checkpointed_monolithic_gear_knob_handling(tmp_path):
    """Unsegmented checkpointed chunks tolerate None-valued gear knobs
    (the northstar script passes them unconditionally) and reject
    explicit values loudly — the monolithic path has no segmented driver
    to configure."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _decay_setup(B=4)
    res = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "mono"), chunk_size=2,
                             pipeline=None, poll_every=None)
    assert np.all(np.asarray(res.status) == SUCCESS)
    with pytest.raises(ValueError, match="segmented-path"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                           str(tmp_path / "mono2"), chunk_size=2,
                           pipeline=True)
    # the check is up-front: it fires even when every chunk would resume
    # from disk (no _solve_chunk call to host a per-chunk check)
    with pytest.raises(ValueError, match="segmented-path"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                           str(tmp_path / "mono"), chunk_size=2,
                           pipeline=True)


def test_chunk_log_thread_safe(tmp_path):
    """checkpointed_sweep serializes chunk_log calls in the library (the
    writer thread's save lines interleave with the main thread's solve
    lines): a deliberately slow, concurrency-detecting logger must never
    observe itself entered twice at once."""
    import time

    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _decay_setup(B=8)
    state = {"active": 0, "max_active": 0, "lines": 0}

    def log(msg):
        state["active"] += 1
        state["max_active"] = max(state["max_active"], state["active"])
        time.sleep(0.005)  # widen the race window
        state["active"] -= 1
        state["lines"] += 1

    checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                       str(tmp_path / "ck"), chunk_size=2, chunk_log=log)
    assert state["lines"] >= 8  # 4 solve lines + 4 async save lines
    assert state["max_active"] == 1


def test_checkpointed_sweep_lane_cost_order(tmp_path, h2o2):
    """Cost-sorted chunking (lane_cost=) returns results in CALLER lane
    order, per-lane equal to the unsorted run at far-below-rtol level
    (lanes are independent under vmap; batch position shifts bits by ~1 ulp
    through XLA's batched linear algebra, nothing more)."""
    from batchreactor_tpu.ops.rhs import make_gas_jac
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    gm, th, y0 = h2o2
    rhs = make_gas_rhs(gm, th)
    jacf = make_gas_jac(gm, th)
    B = 8
    y0s = jnp.broadcast_to(y0, (B, 9))
    # deliberately interleaved hot/cold lanes: the cost sort must regroup
    T = jnp.asarray([1150., 1400., 1160., 1390., 1170., 1380., 1180., 1370.])
    cfgs = {"T": T}
    kw = dict(rtol=1e-6, atol=1e-10, jac=jacf, method="bdf",
              segment_steps=64)
    plain = checkpointed_sweep(rhs, y0s, 0.0, 2e-4, cfgs,
                               str(tmp_path / "plain"), chunk_size=4, **kw)
    # hotter lanes ignite -> more steps; use -T as a decreasing-cost proxy
    cost = np.asarray(-T)
    sorted_ = checkpointed_sweep(rhs, y0s, 0.0, 2e-4, cfgs,
                                 str(tmp_path / "sorted"), chunk_size=4,
                                 lane_cost=cost, **kw)
    assert np.all(np.asarray(plain.status) == SUCCESS)
    np.testing.assert_array_equal(np.asarray(sorted_.status),
                                  np.asarray(plain.status))
    np.testing.assert_allclose(np.asarray(sorted_.y),
                               np.asarray(plain.y),
                               rtol=1e-10, atol=1e-18)
    np.testing.assert_allclose(np.asarray(sorted_.t),
                               np.asarray(plain.t), rtol=1e-12)
    # resume with the same lane_cost serves the cache (identical bits)
    again = checkpointed_sweep(rhs, y0s, 0.0, 2e-4, cfgs,
                               str(tmp_path / "sorted"), chunk_size=4,
                               lane_cost=cost, **kw)
    np.testing.assert_array_equal(np.asarray(again.y),
                                  np.asarray(sorted_.y))
    # a different cost vector (different permutation) must refuse the dir
    with pytest.raises(ValueError, match="fresh directory"):
        checkpointed_sweep(rhs, y0s, 0.0, 2e-4, cfgs,
                           str(tmp_path / "sorted"), chunk_size=4,
                           lane_cost=np.asarray(T), **kw)
