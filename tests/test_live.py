"""Live telemetry plane + solver timelines (obs/live.py, obs/timeline.py).

Tier-1 coverage for the in-flight metrics endpoint (a real HTTP scrape
mid-streaming-sweep, with `br_sweep_occupancy` moving between scrapes),
the per-lane timeline ring (monolithic == segmented == admission
un-shuffled, bit-exact), the flight recorder (dump replayed through the
`BR_FAULT_INJECT` hung-fetch), fleet snapshot merging, and the
missing-key→0 diff convention for the new counter keys.  Tiny linear
ODEs throughout — the tier-1 budget discipline."""

import glob
import json
import os
import re
import sys
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from batchreactor_tpu import obs  # noqa: E402
from batchreactor_tpu.obs import counters as C  # noqa: E402
from batchreactor_tpu.obs import live as L  # noqa: E402
from batchreactor_tpu.obs import timeline as TL  # noqa: E402
from batchreactor_tpu.parallel import sweep as S  # noqa: E402
from batchreactor_tpu.solver import bdf, sdirk  # noqa: E402
from batchreactor_tpu.solver.sdirk import SUCCESS  # noqa: E402


def rhs(t, y, cfg):
    return -cfg["k"] * y


def _lanes(B, spread=2.0):
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    cfgs = {"k": jnp.logspace(1.0, 1.0 + spread, B)}
    return y0s, cfgs


# --------------------------------------------------------------------------
# live registry + metrics endpoint
# --------------------------------------------------------------------------
def test_resolve_live_metrics_grammar(monkeypatch):
    monkeypatch.delenv("BR_METRICS_PORT", raising=False)
    assert L.resolve_live_metrics(None) is None
    assert L.resolve_live_metrics(False) is None
    assert L.resolve_live_metrics(True) == 0
    assert L.resolve_live_metrics(9107) == 9107
    monkeypatch.setenv("BR_METRICS_PORT", "9108")
    assert L.resolve_live_metrics(None) == 9108
    with pytest.raises(ValueError):
        L.resolve_live_metrics(-1)
    with pytest.raises(ValueError):
        L.resolve_live_metrics(70000)


def test_registry_overlay_and_healthz():
    rec = obs.Recorder()
    rec.counter("lane_attempts", 10)
    reg = L.LiveRegistry(recorder=rec, meta={"entry": "test"})
    reg.publish("sweep", counters={"lane_attempts": 5,
                                   "lane_capacity": 100},
                gauges={"backlog_depth": 7})
    # overlay counters SUM onto recorder counters
    assert reg.report()["counters"]["lane_attempts"] == 15
    text = reg.prometheus()
    assert "br_sweep_occupancy" in text        # 15/100 derivable
    assert "br_sweep_backlog_depth 7" in text
    hz = reg.healthz()
    assert hz["ok"] and hz["gauges"]["backlog_depth"] == 7
    # clearing the overlay drops the in-flight deltas
    reg.clear("sweep")
    assert reg.report()["counters"]["lane_attempts"] == 10
    assert reg.gauges() == {}


def test_metrics_endpoint_mid_streaming_sweep():
    """The acceptance scrape: /healthz + /metrics polled from a thread
    while a streaming (admission=) sweep runs, with br_sweep_occupancy
    and the backlog depth observably changing between scrapes.  Scrapes
    are driven from the progress callback (poll boundaries), so the
    mid-flight timing is deterministic — the HTTP round-trip itself is
    served by the endpoint's own thread."""
    B = 8
    y0s, cfgs = _lanes(B, spread=1.3)
    rec = obs.Recorder()
    reg = L.LiveRegistry(recorder=rec, meta={"entry": "test"})
    scrapes, healths = [], []
    with L.MetricsServer(reg, port=0) as srv:
        url = srv.url

        def progress(_payload):
            scrapes.append(urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode())
            healths.append(json.loads(urllib.request.urlopen(
                url + "/healthz", timeout=10).read()))

        res = S.ensemble_solve_segmented(
            rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8, max_segments=400,
            poll_every=1, stats=True, recorder=rec, live=reg,
            admission=4, refill=1, progress=progress)
    assert np.all(np.asarray(res.status) == SUCCESS)
    assert len(scrapes) >= 2
    occ = [float(m.group(1)) for s in scrapes
           for m in [re.search(r"^br_sweep_occupancy (\S+)$", s, re.M)]
           if m]
    assert len(set(occ)) >= 2, f"occupancy never moved: {occ}"
    depth = [float(m.group(1)) for s in scrapes
             for m in [re.search(r"^br_sweep_backlog_depth (\S+)$", s,
                                 re.M)] if m]
    assert len(set(depth)) >= 2, f"backlog depth never moved: {depth}"
    assert all(h["ok"] for h in healths)
    # scrapes are counted under the LIVE_KEYS convention
    assert rec.snapshot()[2]["metrics_scrapes"] == len(scrapes)
    # the overlay cleared on return: a post-sweep report carries only
    # the recorder's final totals (no double count)
    assert reg.gauges() == {}
    assert (reg.report()["counters"]["lane_attempts"]
            == rec.snapshot()[2]["lane_attempts"])


def test_metrics_server_404():
    reg = L.LiveRegistry()
    with L.MetricsServer(reg, port=0) as srv:
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url + "/nope", timeout=10)


# --------------------------------------------------------------------------
# solver timelines
# --------------------------------------------------------------------------
def test_timeline_monolithic_decode_bdf():
    y0 = jnp.asarray([1.0, 0.5])
    cfg = {"k": jnp.asarray(30.0)}
    r = bdf.solve(rhs, y0, 0.0, 1.0, cfg, rtol=1e-6, atol=1e-10,
                  stats=True, timeline=16)
    st = {k: np.asarray(v) for k, v in r.stats.items()}
    att = int(r.n_accepted) + int(r.n_rejected)
    recs = TL.decode(st)
    assert len(recs) == min(att, 16)
    # chronological, last attempt is the accepted step landing on t1
    assert recs[-1]["code"] > 0
    assert abs(recs[-1]["t"] - 1.0) < 1e-9
    assert all(recs[i]["attempt"] < recs[i + 1]["attempt"]
               for i in range(len(recs) - 1))
    # accept codes are BDF orders 1..5; reject codes match the cause
    # partition keys
    for rec_ in recs:
        assert rec_["code"] in (-2, -1, 1, 2, 3, 4, 5)


def test_timeline_sdirk_codes():
    y0 = jnp.asarray([1.0, 0.5])
    cfg = {"k": jnp.asarray(30.0)}
    r = sdirk.solve(rhs, y0, 0.0, 1.0, cfg, rtol=1e-6, atol=1e-10,
                    stats=True, timeline=8)
    recs = TL.decode({k: np.asarray(v) for k, v in r.stats.items()})
    assert recs and all(rec_["code"] in (-2, -1, 4) for rec_ in recs)


@pytest.mark.parametrize("method", ["bdf", "sdirk"])
def test_timeline_segmented_bit_exact(method):
    """Segmented pipelined ring == monolithic ring at jac_window=1 (the
    timeline_state global-attempt slot keying)."""
    B = 4
    y0s, cfgs = _lanes(B)
    kw = dict(rtol=1e-6, atol=1e-10, stats=True, timeline=32,
              method=method)
    mono = S.ensemble_solve(rhs, y0s, 0.0, 1.0, cfgs, **kw)
    seg = S.ensemble_solve_segmented(rhs, y0s, 0.0, 1.0, cfgs,
                                     segment_steps=8, max_segments=400,
                                     poll_every=1, **kw)
    for k in TL.TIMELINE_KEYS:
        np.testing.assert_array_equal(np.asarray(mono.stats[k]),
                                      np.asarray(seg.stats[k]),
                                      err_msg=f"{method}:{k}")


def test_timeline_admission_unshuffle_bit_exact():
    """The acceptance matrix: under admission= (slot permutation +
    refill) AND bucket padding, the harvested rings land back in caller
    lane order bit-exactly equal to the monolithic run's.  The
    single-rung ladder pads the resident block with dead copy-lanes but
    never down-shifts, so the bit-exact contract holds (the pow2
    down-shift tail is covered at tolerance level below — the
    documented bucket-shape ulp sensitivity, parallel/sweep.py)."""
    B = 5          # ragged vs the 4-lane rung: exercises bucket padding
    y0s, cfgs = _lanes(B, spread=1.5)
    kw = dict(rtol=1e-6, atol=1e-10, stats=True, timeline=24)
    mono = S.ensemble_solve(rhs, y0s, 0.0, 1.0, cfgs, **kw)
    adm = S.ensemble_solve_segmented(
        rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8, max_segments=600,
        poll_every=1, admission=2, refill=1, buckets=(4,), **kw)
    assert np.all(np.asarray(adm.status) == SUCCESS)
    for k in TL.TIMELINE_KEYS:
        np.testing.assert_array_equal(np.asarray(mono.stats[k]),
                                      np.asarray(adm.stats[k]),
                                      err_msg=k)


def test_timeline_admission_pow2_downshift_tolerance():
    """pow2 ladder: the drain-phase bucket down-shift re-runs the tail
    in a smaller program, which perturbs t/h at the documented ulp
    level — the attempt SEQUENCE (codes, counts) stays identical and
    the values stay within solver tolerance."""
    B = 5
    y0s, cfgs = _lanes(B, spread=1.5)
    kw = dict(rtol=1e-6, atol=1e-10, stats=True, timeline=24)
    mono = S.ensemble_solve(rhs, y0s, 0.0, 1.0, cfgs, **kw)
    adm = S.ensemble_solve_segmented(
        rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8, max_segments=600,
        poll_every=1, admission=2, refill=1, buckets="pow2", **kw)
    np.testing.assert_array_equal(np.asarray(mono.stats["timeline_code"]),
                                  np.asarray(adm.stats["timeline_code"]))
    for k in ("timeline_t", "timeline_h"):
        np.testing.assert_allclose(np.asarray(mono.stats[k]),
                                   np.asarray(adm.stats[k]),
                                   rtol=1e-8, atol=1e-10, err_msg=k)


def test_timeline_validation():
    y0 = jnp.asarray([1.0])
    cfg = {"k": jnp.asarray(1.0)}
    with pytest.raises(ValueError, match="stats"):
        bdf.solve(rhs, y0, 0.0, 1.0, cfg, timeline=8)
    with pytest.raises(ValueError, match="ring length"):
        bdf.solve(rhs, y0, 0.0, 1.0, cfg, stats=True, timeline=1)
    with pytest.raises(ValueError, match="ring length"):
        bdf.solve(rhs, y0, 0.0, 1.0, cfg, stats=True, timeline=True)
    y0s, cfgs = _lanes(2)
    with pytest.raises(ValueError, match="pipelined"):
        S.ensemble_solve_segmented(rhs, y0s, 0.0, 1.0, cfgs,
                                   segment_steps=8, stats=True,
                                   timeline=8, pipeline=False)


def test_timeline_noop_byte_identity():
    """timeline=None traces byte-identically before and after a
    timeline program has been built and run (the brlint
    timeline-noop-fork contract, asserted in-suite too)."""
    y0 = jnp.asarray([1.0, 0.5])
    cfg = {"k": jnp.asarray(20.0)}

    def run(y0_, **kw):
        return bdf.solve(rhs, y0_, 0.0, 1.0, cfg, rtol=1e-6, atol=1e-10,
                         stats=True, **kw).y

    before = str(jax.make_jaxpr(run)(y0))
    bdf.solve(rhs, y0, 0.0, 1e-3, cfg, rtol=1e-6, atol=1e-10,
              stats=True, timeline=8)
    after = str(jax.make_jaxpr(run)(y0))
    assert before == after


def test_timeline_rides_report_and_render():
    """End-to-end through the report/export/CLI surface: per-lane
    timeline arrays land in the report, survive the JSONL round-trip,
    and render as strip charts (obs_report.py --timeline)."""
    B = 3
    y0s, cfgs = _lanes(B)
    rec = obs.Recorder()
    res = S.ensemble_solve_segmented(rhs, y0s, 0.0, 1.0, cfgs,
                                     segment_steps=8, max_segments=400,
                                     stats=True, timeline=16,
                                     recorder=rec)
    report = obs.build_report(recorder=rec, solver_stats=res.stats)
    per_lane = report["solver_stats"]["per_lane"]
    assert TL.has_timeline(per_lane)
    assert len(per_lane["timeline_code"]) == B
    # totals never sum ring slots
    assert "timeline_t" not in report["solver_stats"]["totals"]
    rt = obs.from_jsonl(obs.to_jsonl(report))
    assert rt == report
    text = TL.render(report, lanes=[0, 2])
    assert "lane 0" in text and "lane 2" in text and "acc=" in text
    # explicit out-of-range lane fails loudly
    with pytest.raises(ValueError):
        TL.render(report, lanes=[99])


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------
def test_flight_ring_bounded_and_dump(tmp_path):
    fl = L.FlightRecorder(capacity=4)
    for i in range(10):
        fl.note("event", name=f"e{i}")
    recs = fl.records()
    assert len(recs) == 4 and recs[-1]["name"] == "e9"
    path = fl.dump(dir=str(tmp_path), reason="test")
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["kind"] == "flight" and lines[0]["reason"] == "test"
    assert len(lines) == 5
    # a second dump never overwrites the first
    path2 = fl.dump(dir=str(tmp_path), reason="again")
    assert path2 != path and os.path.exists(path)


def test_flight_recorder_hung_fetch_dump(tmp_path):
    """The acceptance postmortem: a BR_FAULT_INJECT hung-fetch wedge
    dumps a flight_*.jsonl whose tail carries the fault event and the
    last counter snapshot preceding it."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.resilience import inject

    B = 4
    y0s, cfgs = _lanes(B)
    rec = obs.Recorder()
    L.arm_flight(recorder=rec, dir=str(tmp_path), install_signal=False)
    inject.arm("hang_fetch:delay=10")
    try:
        res = checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs,
                                 str(tmp_path / "ck"), chunk_size=2,
                                 chunk_budget_s=0.3,
                                 retry={"max_retries": 2,
                                        "backoff_s": 0.0},
                                 recorder=rec)
    finally:
        inject.disarm()
        L.disarm_flight()
    assert np.all(np.asarray(res.status) == SUCCESS)
    dumps = sorted(glob.glob(str(tmp_path / "flight_*.jsonl")))
    assert dumps, "wedge left no flight dump"
    lines = [json.loads(ln) for ln in open(dumps[-1])]
    assert lines[0]["kind"] == "flight"
    tail = lines[-8:]
    fault_idx = [i for i, r in enumerate(tail)
                 if r.get("kind") == "event" and r.get("name") == "fault"
                 and r["attrs"]["kind"] == "hung_fetch"]
    snap_idx = [i for i, r in enumerate(tail)
                if r.get("kind") == "counter_snapshot"]
    assert fault_idx and snap_idx
    # a counter snapshot PRECEDES the fault event (watchdog ordering)
    assert min(snap_idx) < max(fault_idx)
    # counted under the LIVE_KEYS convention
    assert rec.snapshot()[2]["flight_dumps"] >= 1
    # disarm really detached the tap
    assert rec.tap is None


def test_flight_unarmed_noops():
    assert L.flight_dump("nothing") is None
    assert L.armed_flight() is None
    L.flight_note_counters(obs.Recorder())   # must not raise


# --------------------------------------------------------------------------
# fleet aggregation
# --------------------------------------------------------------------------
def test_fleet_merge_and_prometheus(tmp_path):
    d = str(tmp_path)
    for pid, (att, occ_depth) in enumerate([(10, 3), (32, 7)]):
        rec = obs.Recorder()
        rec.counter("lane_attempts", att)
        rec.counter("lane_capacity", 64)
        reg = L.LiveRegistry(recorder=rec)
        reg.publish("sweep", gauges={"backlog_depth": occ_depth})
        L.write_fleet_snapshot(d, pid, reg)
    snaps = L.read_fleet_snapshots(d)
    assert [s["pid"] for s in snaps] == [0, 1]
    merged = L.merge_fleet(snaps)
    # counters summed, gauges max-reduced (the GAUGE convention)
    assert merged["counters"]["lane_attempts"] == 42
    assert merged["counters"]["lane_capacity"] == 128
    assert merged["gauges"]["backlog_depth"] == 7
    text = L.fleet_prometheus(snaps)
    assert 'host="p0"' in text and 'host="p1"' in text
    assert "br_fleet_hosts 2" in text
    assert "br_fleet_occupancy" in text       # 42/128 derivable
    # a registry with fleet_dir serves the merged view from /metrics
    reg2 = L.LiveRegistry(fleet_dir=d)
    assert "br_fleet_hosts 2" in reg2.prometheus()
    # torn snapshot skipped, not fatal
    with open(os.path.join(d, "hosts", "p9.metrics.json"), "w") as f:
        f.write('{"pid": 9, "cou')
    assert len(L.read_fleet_snapshots(d)) == 2


# --------------------------------------------------------------------------
# diff conventions + CLI
# --------------------------------------------------------------------------
def test_diff_missing_live_and_timeline_keys_map_to_zero():
    """The PR-6/8 convention extended: live-plane counters absent from
    an endpoint-less report diff as 0, not as a difference — and ring
    payloads never enter solver totals, so an archived pre-timeline
    report diffs cleanly against a timeline run."""
    base = {"schema": "br-obs-v1", "meta": {}, "spans": [], "events": [],
            "counters": {}, "solver_stats": None, "compile": None}
    b = dict(base)
    b["counters"] = {k: 0 for k in C.LIVE_KEYS}
    out = obs.diff(base, b)
    assert "no differences" in out
    b2 = dict(base)
    b2["counters"] = {"metrics_scrapes": 3}
    out2 = obs.diff(base, b2)
    assert "metrics_scrapes: 0 -> 3" in out2
    # timeline arrays excluded from totals entirely
    st = {"n_accepted": np.asarray([2, 3]),
          "n_rejected": np.asarray([0, 1]),
          "timeline_t": np.zeros((2, 4)),
          "timeline_h": np.zeros((2, 4)),
          "timeline_code": np.zeros((2, 4), np.int8)}
    tot = C.totals(st)
    assert set(tot) == {"n_accepted", "n_rejected"}


def test_obs_report_cli_timeline(tmp_path, capsys):
    B = 2
    y0s, cfgs = _lanes(B)
    res = S.ensemble_solve(rhs, y0s, 0.0, 1.0, cfgs, stats=True,
                           timeline=8)
    report = obs.build_report(solver_stats=res.stats)
    path = str(tmp_path / "tl.jsonl")
    obs.write_jsonl(path, report)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    assert obs_report.main([path, "--timeline"]) == 0
    out = capsys.readouterr().out
    assert "solver timelines" in out and "lane" in out
    assert obs_report.main([path, "--timeline", "--lanes", "1"]) == 0
    assert "lane 1" in capsys.readouterr().out


def test_timeline_joins_checkpoint_fingerprint(tmp_path):
    """A non-None ring changes the persisted chunk stats schema, so it
    PINS the resume fingerprint: same ring resumes, a different ring
    fails loudly, and explicit timeline=None fingerprints identically
    to the knob absent (the buckets=None convention)."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    B = 4
    y0s, cfgs = _lanes(B)
    d = str(tmp_path / "ck")
    kw = dict(chunk_size=2, segment_steps=16, stats=True)
    r1 = checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d, timeline=8, **kw)
    # same ring: resumes from the chunk artifacts
    r2 = checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d, timeline=8, **kw)
    np.testing.assert_array_equal(np.asarray(r1.stats["timeline_t"]),
                                  np.asarray(r2.stats["timeline_t"]))
    # different ring (or off): loud manifest mismatch, never mixed chunks
    with pytest.raises(ValueError, match="different sweep"):
        checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d, timeline=16, **kw)
    with pytest.raises(ValueError, match="different sweep"):
        checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d, **kw)
    # knob-absent and explicit None fingerprint identically
    d2 = str(tmp_path / "ck2")
    checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d2, **kw)
    checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d2, timeline=None, **kw)


def test_api_timeline_and_live_validation():
    import batchreactor_tpu as br
    from batchreactor_tpu import Chemistry

    gm = br.compile_gaschemistry(
        os.path.join(REPO, "tests", "fixtures", "h2o2.dat"))
    th = br.create_thermo(list(gm.species),
                          os.path.join(REPO, "tests", "fixtures",
                                       "therm.dat"))
    with pytest.raises(ValueError, match="telemetry"):
        br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               1000.0, 1e5, 1e-6,
                               chem=Chemistry(gaschem=True),
                               thermo_obj=th, md=gm, timeline=8)
    with pytest.raises(ValueError):
        br.batch_reactor_sweep({"H2": 0.3, "O2": 0.2, "N2": 0.5},
                               1000.0, 1e5, 1e-6,
                               chem=Chemistry(gaschem=True),
                               thermo_obj=th, md=gm, telemetry=True,
                               timeline=1)


def test_two_concurrent_ephemeral_metrics_servers():
    """port=0 regression (the serving satellite): two servers in one
    process bind DISTINCT ephemeral ports, each serving its own
    registry concurrently, and each exposes its bound port on the
    instance and as a recorder event — so daemons, tests, and CI never
    collide on a fixed port."""
    recs = [obs.Recorder(), obs.Recorder()]
    regs = [L.LiveRegistry(recorder=recs[i], meta={"n": i})
            for i in range(2)]
    regs[0].publish("sweep", gauges={"which": 100.0})
    regs[1].publish("sweep", gauges={"which": 200.0})
    logs = []
    with L.MetricsServer(regs[0], port=0) as a, \
            L.MetricsServer(regs[1], port=0,
                            log=logs.append) as b:
        assert a.port != b.port and a.port > 0 and b.port > 0
        ta = urllib.request.urlopen(a.url + "/metrics",
                                    timeout=10).read().decode()
        tb = urllib.request.urlopen(b.url + "/metrics",
                                    timeout=10).read().decode()
        assert "br_sweep_which 100.0" in ta
        assert "br_sweep_which 200.0" in tb
        hz = json.loads(urllib.request.urlopen(
            b.url + "/healthz", timeout=10).read())
        assert hz["meta"] == {"n": 1}
    # the bound port surfaced in logs and as a recorder event
    assert logs and "/metrics" in logs[0]
    for i, srv in enumerate((a, b)):
        _s, events, _c = recs[i].snapshot()
        bound = [e for e in events if e["name"] == "metrics_server_bound"]
        assert len(bound) == 1 and bound[0]["attrs"]["port"] > 0


def test_retire_folds_and_clears_atomically():
    """The clear-on-return fix (this PR's host-concurrency audit): the
    drivers' final recorder fold and the overlay drop happen under ONE
    registry lock (``LiveRegistry.retire``), so a concurrent scrape can
    never sum the final totals WITH the still-standing overlay (the old
    fold-then-clear double count) or see neither."""
    rec = obs.Recorder()
    reg = L.LiveRegistry(recorder=rec)
    reg.publish("sweep", counters={"lane_attempts": 100,
                                   "lane_capacity": 200})
    assert reg.report()["counters"]["lane_attempts"] == 100
    reg.retire("sweep", {"lane_attempts": 100, "lane_capacity": 200})
    # folded exactly once, overlay gone
    assert reg.report()["counters"]["lane_attempts"] == 100
    assert rec.snapshot()[2]["lane_attempts"] == 100
    # idempotent for an absent source, counters still fold
    reg.retire("nope", {"lane_attempts": 1})
    assert rec.snapshot()[2]["lane_attempts"] == 101


def test_retire_never_double_counts_under_concurrent_scrapes():
    """Stress the race window: scrapes run concurrently with
    publish->retire cycles; with the atomic retire no merged read may
    ever exceed the running final total (the double-count signature)."""
    rec = obs.Recorder()
    reg = L.LiveRegistry(recorder=rec)
    N, VAL = 60, 1000
    overshoot = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            seen = reg._merged()[0].get("lane_attempts", 0)
            folded = rec.snapshot()[2].get("lane_attempts", 0)
            # a scrape may see the in-flight overlay OR the folded
            # total, never both summed: bounded by folded + one sweep
            if seen > folded + VAL:
                overshoot.append((seen, folded))

    threads = [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    for _ in range(N):
        reg.publish("sweep", counters={"lane_attempts": VAL})
        reg.retire("sweep", {"lane_attempts": VAL})
    stop.set()
    for t in threads:
        t.join()
    assert overshoot == []
    assert rec.snapshot()[2]["lane_attempts"] == N * VAL


def test_sweep_driver_retires_overlay_with_final_totals():
    """End-to-end: a live= pipelined sweep folds its final occupancy
    pair through retire — totals land exactly once and the overlay is
    gone at return."""
    rec = obs.Recorder()
    reg = L.LiveRegistry(recorder=rec)
    res = S.ensemble_solve_segmented(
        lambda t, y, cfg: -cfg["k"] * y,
        jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (2, 2)), 0.0, 1.0,
        {"k": jnp.asarray([10.0, 40.0])}, segment_steps=8,
        max_segments=200, pipeline=True, poll_every=1, method="bdf",
        recorder=rec, live=reg)
    assert int(np.asarray(res.status).sum()) == 2
    counters = rec.snapshot()[2]
    assert counters["lane_attempts"] > 0
    # overlay retired: the merged view equals the recorder exactly
    assert reg._merged()[0]["lane_attempts"] == counters["lane_attempts"]
    assert reg.gauges() == {}
