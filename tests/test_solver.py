"""SDIRK4 solver tests: accuracy scaling, stiff oracle (Robertson vs scipy),
per-lane vmap adaptivity, trajectory buffer, and failure detection (the
status-code analog of the reference's retcode semantics,
/root/reference/src/BatchReactor.jl:216)."""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.integrate import solve_ivp

from batchreactor_tpu.solver.sdirk import (
    DT_UNDERFLOW,
    MAX_STEPS_REACHED,
    SUCCESS,
    solve,
)


def test_accuracy_tracks_rtol():
    """y' = -y^2, y(0)=1 -> y(2) = 1/3; error must scale with rtol."""
    rhs = lambda t, y, cfg: -y * y
    errs = []
    for rtol in [1e-4, 1e-6, 1e-8]:
        r = solve(rhs, jnp.array([1.0]), 0.0, 2.0, None, rtol=rtol, atol=1e-12)
        assert int(r.status) == SUCCESS
        errs.append(abs(float(r.y[0]) - 1 / 3))
    assert errs[0] < 1e-4 and errs[1] < 1e-6 and errs[2] < 1e-8
    assert errs[2] < errs[1] < errs[0]


def test_linear_decay_tight_tolerance():
    """Stiff linear decay to a value well above atol: rel accuracy ~ rtol."""
    r = solve(lambda t, y, cfg: -10.0 * y, jnp.array([1.0]), 0.0, 1.0, None,
              rtol=1e-10, atol=1e-16)
    assert int(r.status) == SUCCESS
    assert abs(float(r.y[0]) - np.exp(-10.0)) / np.exp(-10.0) < 1e-8


def _robertson(t, y, cfg):
    d1 = -0.04 * y[0] + 1e4 * y[1] * y[2]
    d3 = 3e7 * y[1] * y[1]
    return jnp.stack([d1, -d1 - d3, d3])


def test_robertson_vs_scipy():
    """Canonical stiff benchmark over 5 decades of time."""
    y0 = jnp.array([1.0, 0.0, 0.0])
    r = jax.jit(
        lambda y: solve(_robertson, y, 0.0, 1e5, None, rtol=1e-8, atol=1e-12)
    )(y0)
    assert int(r.status) == SUCCESS
    ref = solve_ivp(
        lambda t, y: np.asarray(_robertson(t, jnp.asarray(y), None)),
        (0, 1e5), np.asarray(y0), method="BDF", rtol=1e-10, atol=1e-14,
    )
    np.testing.assert_allclose(np.asarray(r.y), ref.y[:, -1], rtol=1e-6)


def test_vmap_per_lane_adaptivity():
    """Lanes with 1e4x different stiffness solve independently under vmap."""
    lam = jnp.array([1.0, 100.0, 10000.0])
    r = jax.vmap(
        lambda l: solve(lambda t, y, cfg: -l * y, jnp.array([1.0]), 0.0, 1.0,
                        None, rtol=1e-6, atol=1e-14)
    )(lam)
    assert np.all(np.asarray(r.status) == SUCCESS)
    # step counts must differ across lanes (independent adaptivity)
    assert len(set(np.asarray(r.n_accepted).tolist())) > 1
    np.testing.assert_allclose(
        np.asarray(r.y[:, 0]), np.exp(-np.asarray(lam)), rtol=1e-5, atol=1e-12
    )


def test_trajectory_buffer():
    rhs = lambda t, y, cfg: -y
    r = solve(rhs, jnp.array([1.0]), 0.0, 1.0, None, rtol=1e-6, atol=1e-12,
              n_save=256)
    n = int(r.n_saved)
    assert n == int(r.n_accepted)
    ts = np.asarray(r.ts)[:n]
    assert np.all(np.diff(ts) > 0) and ts[-1] >= 1.0 - 1e-12
    np.testing.assert_allclose(np.asarray(r.ys)[:n, 0], np.exp(-ts), rtol=1e-5)
    # padding is inf beyond n_saved
    assert np.all(np.isinf(np.asarray(r.ts)[n:]))


def test_buffer_overflow_saturates():
    rhs = lambda t, y, cfg: -y
    r = solve(rhs, jnp.array([1.0]), 0.0, 1.0, None, rtol=1e-10, atol=1e-14,
              n_save=4)
    assert int(r.status) == SUCCESS  # solve completes even when buffer fills
    assert int(r.n_saved) == 4
    assert int(r.n_accepted) > 4


def test_max_steps_status():
    r = solve(lambda t, y, cfg: -y, jnp.array([1.0]), 0.0, 1.0, None,
              rtol=1e-12, atol=1e-16, max_steps=3)
    assert int(r.status) == MAX_STEPS_REACHED


def test_dt_underflow_on_nan_rhs():
    """A lane whose RHS goes non-finite must fail loudly, not hang or poison."""
    def bad(t, y, cfg):
        return jnp.where(t > 0.1, jnp.nan, -1.0) * y
    # dt_min_factor pinned: the production default (1e-22, sized for
    # chemistry's 1e-16 s transients) would hit max_steps first
    r = solve(bad, jnp.array([1.0]), 0.0, 1.0, None, rtol=1e-6, atol=1e-12,
              dt_min_factor=1e-14)
    assert int(r.status) == DT_UNDERFLOW
    assert np.all(np.isfinite(np.asarray(r.y)))  # last good state retained


def test_jit_and_grad_compatible():
    """Solve must trace under jit; forward sensitivities via jacfwd over cfg
    (the reference's sens hook returns the problem unsolved,
    /root/reference/src/BatchReactor.jl:205-207 — we differentiate through)."""
    def decay(t, y, cfg):
        return -cfg["k"] * y

    def final(k):
        return solve(decay, jnp.array([1.0]), 0.0, 1.0, {"k": k},
                     rtol=1e-8, atol=1e-12).y[0]

    k = jnp.array(2.0)
    dfdk = jax.jacfwd(final)(k)
    # d/dk exp(-k) = -exp(-k)
    assert abs(float(dfdk) + np.exp(-2.0)) < 1e-5


def test_linsolve_inv32_matches_lu():
    """The mixed-precision Newton linear solver (f32 inverse + f64 iterative
    refinement, the TPU path) must reproduce the exact-f64 LU path: same
    accepted solution well within tolerance, on the canonical stiff oracle."""
    y0 = jnp.array([1.0, 0.0, 0.0])
    r_lu = solve(_robertson, y0, 0.0, 1e4, None, rtol=1e-8, atol=1e-12,
                 linsolve="lu")
    r_iv = solve(_robertson, y0, 0.0, 1e4, None, rtol=1e-8, atol=1e-12,
                 linsolve="inv32")
    assert int(r_lu.status) == SUCCESS and int(r_iv.status) == SUCCESS
    np.testing.assert_allclose(np.asarray(r_iv.y), np.asarray(r_lu.y),
                               rtol=1e-6)


def test_analytic_jac_hook():
    """A user-supplied jac must be used and give the same answer as jacfwd."""
    calls = []

    def decay(t, y, cfg):
        return -cfg["k"] * y

    def jac(t, y, cfg):
        calls.append(1)
        return -cfg["k"] * jnp.eye(y.shape[0], dtype=y.dtype)

    r = solve(decay, jnp.array([1.0]), 0.0, 1.0, {"k": jnp.array(2.0)},
              rtol=1e-8, atol=1e-12, jac=jac)
    assert calls, "analytic jac was never traced"
    assert int(r.status) == SUCCESS
    assert abs(float(r.y[0]) - np.exp(-2.0)) < 1e-7


def test_observer_fold():
    """Observer folds over accepted steps only and lands in res.observed."""
    rhs = lambda t, y, cfg: -y

    def obs(t, y, acc):
        return {"n": acc["n"] + 1, "y_min": jnp.minimum(acc["y_min"], y[0])}

    r = solve(rhs, jnp.array([1.0]), 0.0, 1.0, None, rtol=1e-6, atol=1e-12,
              observer=obs, observer_init={"n": jnp.array(0),
                                           "y_min": jnp.array(jnp.inf)})
    assert int(r.observed["n"]) == int(r.n_accepted)
    np.testing.assert_allclose(float(r.observed["y_min"]), float(r.y[0]),
                               rtol=1e-12)


def test_observer_requires_init():
    import pytest

    with pytest.raises(ValueError):
        solve(lambda t, y, cfg: -y, jnp.array([1.0]), 0.0, 1.0, None,
              observer=lambda t, y, a: a)


def test_jac_window_matches_every_step():
    """jac_window=K (stale Jacobian, h-correct iteration matrix) integrates
    the stiff Robertson problem to the same answer and step counts stay
    comparable — staleness may cost a few extra Newton rejections at most."""

    def rob(t, y, cfg):
        k1, k2, k3 = 0.04, 3e7, 1e4
        d0 = -k1 * y[0] + k3 * y[1] * y[2]
        d2 = k2 * y[1] * y[1]
        return jnp.stack([d0, -d0 - d2, d2])

    y0 = jnp.asarray([1.0, 0.0, 0.0])
    base = solve(rob, y0, 0.0, 1e4, {}, rtol=1e-8, atol=1e-12)
    assert int(base.status) == SUCCESS
    for K in (2, 4, 8):
        r = solve(rob, y0, 0.0, 1e4, {}, rtol=1e-8, atol=1e-12, jac_window=K)
        assert int(r.status) == SUCCESS, K
        np.testing.assert_allclose(np.asarray(r.y), np.asarray(base.y),
                                   rtol=1e-6, atol=1e-14)
        assert int(r.n_accepted) <= int(base.n_accepted) * 1.5
