"""CHEMKIN gas-mechanism parser tests.

Oracles: mechanism feature counts recovered in SURVEY.md §6 from
/root/reference/test/lib/{h2o2,grimech}.dat, plus hand-checked unit
conversions for specific reaction lines.
"""

import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.gas import compile_gaschemistry
from batchreactor_tpu.utils.constants import CAL_TO_J


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    return compile_gaschemistry(f"{lib_dir}/h2o2.dat")


@pytest.fixture(scope="module")
def gri(gri_lib_dir):
    return compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")


def test_h2o2_counts(h2o2):
    assert h2o2.n_species == 9
    assert h2o2.n_reactions == 18
    assert int(h2o2.has_falloff.sum()) == 0
    assert int(h2o2.rev_mask.sum()) == 18  # all reversible


def test_gri_counts(gri):
    assert gri.n_species == 53
    assert gri.n_reactions == 325
    assert int(gri.has_falloff.sum()) == 29  # LOW blocks (SURVEY.md §6)
    assert int(gri.has_troe.sum()) == 26
    assert gri.int_stoich


def test_gri_species_order(gri):
    assert gri.species[:4] == ("H2", "H", "O", "O2")
    assert gri.species[47] == "N2" and gri.species[48] == "AR"


def test_third_body_efficiencies_h2o2(h2o2):
    # H+O2+M=HO2+M with H2O/21./ H2/3.3/ O2/0.0/  (h2o2.dat:12-13)
    i = list(h2o2.equations).index("H+O2+M=HO2+M")
    sp = list(h2o2.species)
    eff = np.asarray(h2o2.eff[i])
    assert eff[sp.index("H2O")] == 21.0
    assert eff[sp.index("H2")] == 3.3
    assert eff[sp.index("O2")] == 0.0
    assert eff[sp.index("N2")] == 1.0  # default
    assert h2o2.has_tb[i] == 1.0


def test_arrhenius_si_conversion(h2o2):
    """OH+H2=H2O+H  1.17E9 1.3 3626. — bimolecular: A_SI = A_cgs*1e-6."""
    i = list(h2o2.equations).index("OH+H2=H2O+H")
    assert np.isclose(float(np.exp(h2o2.log_A[i])), 1.17e9 * 1e-6)
    assert float(h2o2.beta[i]) == 1.3
    assert np.isclose(float(h2o2.Ea[i]), 3626.0 * CAL_TO_J)


def test_third_body_si_conversion(h2o2):
    """H+O2+M=HO2+M 2.1E18: order 2 + M -> A_SI = A_cgs*(1e-6)^2."""
    i = list(h2o2.equations).index("H+O2+M=HO2+M")
    assert np.isclose(float(np.exp(h2o2.log_A[i])), 2.1e18 * 1e-12)


def test_explicit_collider(h2o2):
    """H+O2+O2=HO2+O2 is a plain trimolecular reaction, not third-body."""
    i = list(h2o2.equations).index("H+O2+O2=HO2+O2")
    assert h2o2.has_tb[i] == 0.0
    sp = list(h2o2.species)
    assert float(h2o2.nu_f[i, sp.index("O2")]) == 2.0
    assert float(h2o2.nu_r[i, sp.index("O2")]) == 1.0
    assert np.isclose(float(np.exp(h2o2.log_A[i])), 6.7e19 * 1e-12)


def test_falloff_lowtroe(gri):
    """H+CH3(+M)<=>CH4(+M) (grimech.dat): LOW + 4-param TROE."""
    sp = list(gri.species)
    idx = [
        i
        for i, eq in enumerate(gri.equations)
        if eq.replace(" ", "") == "H+CH3(+M)<=>CH4(+M)"
    ]
    assert len(idx) == 1
    i = idx[0]
    assert gri.has_falloff[i] == 1.0 and gri.has_troe[i] == 1.0
    # kinf: A=1.390E+16 b=-.534 Ea=536.0 cal; bimolecular
    assert np.isclose(float(np.exp(gri.log_A[i])), 1.39e16 * 1e-6)
    assert np.isclose(float(gri.beta[i]), -0.534)
    # LOW/ 2.620E+33 -4.760 2440.00/ : order+1=3 -> (1e-6)^2
    assert np.isclose(float(np.exp(gri.log_A0[i])), 2.62e33 * 1e-12)
    assert np.isclose(float(gri.Ea0[i]), 2440.0 * CAL_TO_J)
    # TROE/ .7830 74.00 2941.00 6964.00/
    np.testing.assert_allclose(
        np.asarray(gri.troe[i]), [0.783, 74.0, 2941.0, 6964.0]
    )
    # efficiencies parsed from following line
    assert float(gri.eff[i, sp.index("CH4")]) == 3.0


def test_gri_troe_all_4param(gri):
    """Every GRI TROE line carries 4 parameters; T2 must be finite there."""
    troe_rows = np.where(np.asarray(gri.has_troe) > 0)[0]
    assert len(troe_rows) == 26
    assert np.all(np.isfinite(np.asarray(gri.troe[troe_rows, 3])))


def test_troe_3param_synthetic(tmp_path):
    """3-parameter TROE (no T2 term) must parse with T2 = +inf sentinel."""
    mech = tmp_path / "mini.dat"
    mech.write_text(
        "ELEMENTS\nH O\nEND\nSPECIES\nH O2 HO2\nEND\nREACTIONS\n"
        "H+O2(+M)<=>HO2(+M)  4.650E+12  0.44  0.0\n"
        "   LOW/ 6.366E+20 -1.72 524.8/\n"
        "   TROE/ 0.5 1.0E-30 1.0E+30/\n"
        "END\n"
    )
    gm = compile_gaschemistry(str(mech))
    assert gm.n_reactions == 1
    assert float(gm.has_troe[0]) == 1.0
    assert np.isinf(float(gm.troe[0, 3]))


def test_duplicates_kept_as_rows(gri):
    """6 DUPLICATE markers -> pairs stay as independent rows (rates add)."""
    eqs = [eq for eq in gri.equations]
    dup_eqs = {eq for eq in eqs if eqs.count(eq) > 1}
    assert len(dup_eqs) >= 3  # e.g. O+C2H4, O+C2H5, OH+HO2, CH+H2O...


def test_irreversible(gri):
    irrev = 325 - int(gri.rev_mask.sum())
    assert irrev == 16  # GRI-Mech 3.0 has 16 '=>' reactions


# --- REV keyword + negative-A duplicates (CHEMKIN-II breadth) ---

def _mini_mech(tmp_path, body):
    p = tmp_path / "mini.dat"
    p.write_text("ELEMENTS\nH O N\nEND\nSPECIES\nH2 O2 OH H2O N2\nEND\n"
                 "REACTIONS\n" + body + "END\n")
    return str(p)


def test_rev_keyword_hand_computed(tmp_path, fixtures_dir):
    """REV /A b Ea/: reverse rate from explicit Arrhenius, not Kc.
    Hand-computed: kf = A T^b exp(-Ea/RT), kr likewise with REV params;
    q = kf [H2][O2] - kr [OH]^2 (SI after cgs conversion)."""
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import reaction_rates
    from batchreactor_tpu.utils.constants import CAL_TO_J, R

    mech = _mini_mech(tmp_path,
                      "H2+O2=2OH   4.0E13  0.5  1000.\n"
                      "REV /2.0E11  0.3  500./\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    assert int(np.asarray(gm.has_rev).sum()) == 1
    T = 1100.0
    conc = np.array([2.0, 1.5, 0.7, 0.0, 3.0])  # mol/m^3, species order
    q = np.asarray(reaction_rates(T, jnp.asarray(conc), gm, th))
    # hand: cgs A for a bimolecular step -> SI factor 1e-6
    kf = 4.0e13 * 1e-6 * T**0.5 * np.exp(-1000.0 * CAL_TO_J / (R * T))
    kr = 2.0e11 * 1e-6 * T**0.3 * np.exp(-500.0 * CAL_TO_J / (R * T))
    q_hand = kf * conc[0] * conc[1] - kr * conc[2] ** 2
    np.testing.assert_allclose(float(q[0]), q_hand, rtol=1e-12)


def test_negative_A_duplicate_hand_computed(tmp_path, fixtures_dir):
    """Negative-A DUPLICATE pair: rates add with sign; the pair total stays
    positive at this T.  A negative A without DUPLICATE is rejected."""
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import production_rates
    from batchreactor_tpu.utils.constants import CAL_TO_J, R

    mech = _mini_mech(tmp_path,
                      "H2+O2=>2OH   4.0E13  0.0  1000.\n"
                      "DUPLICATE\n"
                      "H2+O2=>2OH  -1.0E13  0.0  2000.\n"
                      "DUPLICATE\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    assert np.asarray(gm.sign_A).tolist() == [1.0, -1.0]
    T = 1000.0
    conc = np.array([2.0, 1.5, 0.0, 0.0, 3.0])
    wdot = np.asarray(production_rates(T, jnp.asarray(conc), gm, th))
    k1 = 4.0e13 * 1e-6 * np.exp(-1000.0 * CAL_TO_J / (R * T))
    k2 = -1.0e13 * 1e-6 * np.exp(-2000.0 * CAL_TO_J / (R * T))
    q_hand = (k1 + k2) * conc[0] * conc[1]
    assert q_hand > 0
    np.testing.assert_allclose(wdot[2], 2 * q_hand, rtol=1e-12)  # OH
    np.testing.assert_allclose(wdot[0], -q_hand, rtol=1e-12)     # H2

    bad = _mini_mech(tmp_path, "H2+O2=>2OH  -1.0E13  0.0  2000.\n")
    with pytest.raises(ValueError, match="DUPLICATE"):
        br.compile_gaschemistry(bad)


def test_rev_and_negA_jacobian_matches_jacfwd(tmp_path, fixtures_dir):
    """The closed-form Jacobian handles REV rows (no Kc-scaling of dkr) and
    signed rows exactly."""
    import jax
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import (production_rates,
                                                   production_rates_and_jac)

    mech = _mini_mech(tmp_path,
                      "H2+O2=2OH   4.0E13  0.5  1000.\n"
                      "REV /2.0E11  0.3  500./\n"
                      "2OH=H2O+O2  1.0E12  0.0  300.\n"
                      "H2+O2=>2OH   3.0E13  0.0  1500.\n"
                      "DUPLICATE\n"
                      "H2+O2=>2OH  -1.0E12  0.0  2500.\n"
                      "DUPLICATE\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1200.0
    conc = jnp.asarray([2.0, 1.5, 0.7, 0.4, 3.0])
    _, J = production_rates_and_jac(T, conc, gm, th)
    J_fd = jax.jacfwd(lambda c: production_rates(T, c, gm, th))(conc)
    np.testing.assert_allclose(np.asarray(J), np.asarray(J_fd), rtol=1e-10,
                               atol=1e-10 * float(jnp.abs(J_fd).max()))


def test_malformed_cheb_loud(tmp_path):
    mech = _mini_mech(tmp_path, "H2+O2=2OH 1.0E13 0. 0.\nCHEB /1. 1./\n")
    with pytest.raises(ValueError, match="coefficients"):
        br.compile_gaschemistry(mech)


def test_plog_hand_computed(tmp_path, fixtures_dir):
    """PLOG: ln k piecewise-linear in ln p between per-pressure Arrhenius
    fits, clamped at table ends; p recovered from conc (p = sum(c) R T)."""
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import reaction_rates
    from batchreactor_tpu.utils.constants import CAL_TO_J, R

    mech = _mini_mech(tmp_path,
                      "H2+O2=>2OH   1.0E13  0.0  1000.\n"
                      "PLOG / 0.1   1.0E12  0.0  1000. /\n"
                      "PLOG / 1.0   1.0E13  0.0  1000. /\n"
                      "PLOG / 10.0  1.0E14  0.0  1000. /\n")
    gm = br.compile_gaschemistry(mech)
    assert gm.any_plog and int(np.asarray(gm.has_plog).sum()) == 1
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1000.0

    def rate_at_pressure(p_atm):
        # uniform mixture with total concentration matching the pressure
        Ctot = p_atm * 101325.0 / (R * T)
        conc = np.zeros(5)
        conc[0], conc[1], conc[4] = 0.3 * Ctot, 0.2 * Ctot, 0.5 * Ctot
        q = np.asarray(reaction_rates(T, jnp.asarray(conc), gm, th))
        return float(q[0]) / (conc[0] * conc[1])  # recover k

    arr = np.exp(-1000.0 * CAL_TO_J / (R * T)) * 1e-6  # shared exp + cgs->SI
    # on-grid points hit the table values exactly
    np.testing.assert_allclose(rate_at_pressure(1.0), 1.0e13 * arr, rtol=1e-10)
    # geometric midpoint p = sqrt(0.1*1.0): ln-linear interp -> sqrt(k1 k2)
    np.testing.assert_allclose(rate_at_pressure(np.sqrt(0.1)),
                               np.sqrt(1.0e12 * 1.0e13) * arr, rtol=1e-10)
    # clamped outside the table
    np.testing.assert_allclose(rate_at_pressure(0.001), 1.0e12 * arr,
                               rtol=1e-10)
    np.testing.assert_allclose(rate_at_pressure(100.0), 1.0e14 * arr,
                               rtol=1e-10)


def test_plog_jacobian_matches_jacfwd(tmp_path, fixtures_dir):
    """The pressure chain (dk/dc_k through Ctot) makes PLOG Jacobians dense
    in the concentration vector; closed form == jacfwd."""
    import jax
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import (production_rates,
                                                   production_rates_and_jac)

    mech = _mini_mech(tmp_path,
                      "H2+O2=2OH   1.0E13  0.0  1000.\n"
                      "PLOG / 0.1   1.0E12  0.5  900. /\n"
                      "PLOG / 1.0   1.0E13  0.2  1100. /\n"
                      "PLOG / 10.0  1.0E14  0.0  1300. /\n"
                      "2OH=H2O+O2  1.0E12  0.0  300.\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1100.0
    for scale in (0.3, 3.0, 30.0):  # below/inside/inside table intervals
        conc = jnp.asarray([2.0, 1.5, 0.7, 0.4, 3.0]) * scale
        _, J = production_rates_and_jac(T, conc, gm, th)
        J_fd = jax.jacfwd(lambda c: production_rates(T, c, gm, th))(conc)
        np.testing.assert_allclose(
            np.asarray(J), np.asarray(J_fd), rtol=1e-10,
            atol=1e-12 * float(jnp.abs(J_fd).max()))


def test_plog_validation(tmp_path):
    with pytest.raises(ValueError, match="PLOG cannot combine"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2+M=>2OH+M 1.0E13 0. 0.\nPLOG /1. 1.E12 0. 0./\n"
                      "PLOG /10. 1.E13 0. 0./\n"))
    with pytest.raises(ValueError, match=">= 2 pressure"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=>2OH 1.0E13 0. 0.\nPLOG /1. 1.E12 0. 0./\n"))
    with pytest.raises(NotImplementedError, match="duplicate PLOG"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=>2OH 1.0E13 0. 0.\nPLOG /1. 1.E12 0. 0./\n"
                      "PLOG /1. 2.E12 0. 0./\n"))


def test_cheb_hand_computed(tmp_path, fixtures_dir):
    """CHEB: log10 k = sum a_ij T_i(Ttil) T_j(Ptil); hand-computed at window
    center (Ttil, Ptil = ...) and clamped outside the pressure window."""
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import reaction_rates
    from batchreactor_tpu.utils.constants import R

    # 2x2 table: log10k = a00 + a01*Ptil + a10*Ttil + a11*Ttil*Ptil
    mech = _mini_mech(tmp_path,
                      "H2+O2=>2OH   1.0 0.0 0.0\n"
                      "TCHEB / 500. 2000. /\n"
                      "PCHEB / 0.1 10. /\n"
                      "CHEB / 2 2 8.0 0.5 -0.3 0.1 /\n")
    gm = br.compile_gaschemistry(mech)
    assert gm.any_cheb
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")

    def k_at(T, p_atm):
        Ctot = p_atm * 101325.0 / (R * T)
        conc = np.zeros(5)
        conc[0], conc[1], conc[4] = 0.3 * Ctot, 0.2 * Ctot, 0.5 * Ctot
        q = np.asarray(reaction_rates(T, jnp.asarray(conc), gm, th))
        return float(q[0]) / (conc[0] * conc[1])

    def hand(T, p_atm, clampP=True):
        Ttil = (2.0 / T - 1 / 500.0 - 1 / 2000.0) / (1 / 2000.0 - 1 / 500.0)
        lo, hi = np.log10(0.1 * 101325.0), np.log10(10.0 * 101325.0)
        Ptil = (2 * np.log10(p_atm * 101325.0) - lo - hi) / (hi - lo)
        if clampP:
            Ptil = np.clip(Ptil, -1, 1)
        log10k = 8.0 + 0.5 * Ptil - 0.3 * Ttil + 0.1 * Ttil * Ptil
        return 10.0 ** log10k * 1e-6  # cgs -> SI (bimolecular)

    for T, p in [(1000.0, 1.0), (700.0, 0.3), (1800.0, 5.0)]:
        np.testing.assert_allclose(k_at(T, p), hand(T, p), rtol=1e-10)
    # below/above the pressure window: clamped to the boundary value
    np.testing.assert_allclose(k_at(1000.0, 0.001), hand(1000.0, 0.1),
                               rtol=1e-10)
    np.testing.assert_allclose(k_at(1000.0, 100.0), hand(1000.0, 10.0),
                               rtol=1e-10)


def test_cheb_jacobian_matches_jacfwd(tmp_path, fixtures_dir):
    """Chebyshev pressure chain in the closed-form Jacobian == jacfwd,
    including a higher-degree table (exercises the T'_j = j U_{j-1}
    recurrence) and the clamped window edge."""
    import jax
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import (production_rates,
                                                   production_rates_and_jac)

    mech = _mini_mech(tmp_path,
                      "H2+O2=2OH   1.0 0.0 0.0\n"
                      "TCHEB / 500. 2000. /\n"
                      "PCHEB / 0.1 10. /\n"
                      "CHEB / 3 4 7.0 0.5 -0.1 0.05 -0.3 0.1 0.02 -0.01 "
                      "0.04 -0.02 0.01 0.005 /\n"
                      "2OH=H2O+O2  1.0E12  0.0  300.\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1100.0
    for scale in (1.0, 0.001):  # inside window; clamped below it
        conc = jnp.asarray([2.0, 1.5, 0.7, 0.4, 3.0]) * scale
        _, J = production_rates_and_jac(T, conc, gm, th)
        J_fd = jax.jacfwd(lambda c: production_rates(T, c, gm, th))(conc)
        np.testing.assert_allclose(
            np.asarray(J), np.asarray(J_fd), rtol=1e-9,
            atol=1e-12 * float(jnp.abs(J_fd).max()))


def test_cheb_validation(tmp_path):
    with pytest.raises(ValueError, match="CHEB cannot combine"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2+M=>2OH+M 1.0 0. 0.\nCHEB / 1 1 8.0 /\n"))
    with pytest.raises(ValueError, match="coefficients"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=>2OH 1.0 0. 0.\nCHEB / 2 2 8.0 0.5 /\n"))


def test_cheb_collider_and_bad_dims_loud(tmp_path):
    with pytest.raises(ValueError, match="total pressure"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2(+H2O)=2OH(+H2O) 1.0 0. 0.\n"
                      "CHEB / 1 1 8.0 /\n"))
    with pytest.raises(ValueError, match="N M dims"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=>2OH 1.0 0. 0.\nCHEB / 2. /\n"))
    with pytest.raises(ValueError, match="1..16"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=>2OH 1.0 0. 0.\nCHEB / 9999999 1 8.0 /\n"))


# --- SRI falloff blending (CHEMKIN-II breadth) ---

def test_sri_hand_computed(tmp_path, fixtures_dir):
    """SRI falloff: kf = k_inf L F with F = d T^e [a e^{-b/T} + e^{-T/c}]^X,
    X = 1/(1 + log10(Pr)^2) — hand-computed against the kernel, 3- and
    5-parameter forms."""
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import reaction_rates
    from batchreactor_tpu.utils.constants import CAL_TO_J, R

    # irreversible => isolates the forward falloff rate (no Kc reverse)
    mech = _mini_mech(tmp_path,
                      "H2+O2(+M)=>2OH(+M)   4.0E13  0.5  1000.\n"
                      "LOW /2.0E16  0.0  800./\n"
                      "SRI /0.45  797.  979./\n"
                      "2OH(+M)=>H2+O2(+M)  3.0E13  0.0  1200.\n"
                      "LOW /1.0E16  0.0  700./\n"
                      "SRI /0.54  201.  1024.  0.7  0.1/\n")
    gm = br.compile_gaschemistry(mech)
    assert np.asarray(gm.has_sri).tolist() == [1.0, 1.0]
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1150.0
    conc = np.array([2.0, 1.5, 0.7, 0.4, 3.0])  # H2 O2 OH H2O N2, mol/m^3
    q = np.asarray(reaction_rates(T, jnp.asarray(conc), gm, th))

    cM = conc.sum()  # default efficiencies 1
    fwd_conc = [conc[0] * conc[1], conc[2] ** 2]
    for i, (A, bexp, Ea, low, sri) in enumerate([
            (4.0e13, 0.5, 1000.0, (2.0e16, 0.0, 800.0),
             (0.45, 797.0, 979.0, 1.0, 0.0)),
            (3.0e13, 0.0, 1200.0, (1.0e16, 0.0, 700.0),
             (0.54, 201.0, 1024.0, 0.7, 0.1))]):
        kinf = A * 1e-6 * T**bexp * np.exp(-Ea * CAL_TO_J / (R * T))
        k0 = low[0] * 1e-12 * T**low[1] * np.exp(-low[2] * CAL_TO_J / (R * T))
        Pr = k0 * cM / kinf
        X = 1.0 / (1.0 + np.log10(Pr) ** 2)
        base = sri[0] * np.exp(-sri[1] / T) + np.exp(-T / sri[2])
        F = sri[3] * T ** sri[4] * base ** X
        k_hand = kinf * (Pr / (1.0 + Pr)) * F
        np.testing.assert_allclose(float(q[i]), k_hand * fwd_conc[i],
                                   rtol=1e-10)


def test_sri_jacobian_matches_jacfwd(tmp_path, fixtures_dir):
    """The closed-form Jacobian carries the SRI dF/dPr chain exactly."""
    import jax
    import jax.numpy as jnp
    from batchreactor_tpu.ops.gas_kinetics import (production_rates,
                                                   production_rates_and_jac)

    mech = _mini_mech(tmp_path,
                      "H2+O2(+M)=2OH(+M)   4.0E13  0.5  1000.\n"
                      "LOW /2.0E16  0.0  800./\n"
                      "SRI /0.45  797.  979./\n"
                      "2OH=H2O+O2  1.0E12  0.0  300.\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1200.0
    conc = jnp.asarray([2.0, 1.5, 0.7, 0.4, 3.0])
    _, J = production_rates_and_jac(T, conc, gm, th)
    J_fd = jax.jacfwd(lambda c: production_rates(T, c, gm, th))(conc)
    np.testing.assert_allclose(np.asarray(J), np.asarray(J_fd), rtol=1e-10,
                               atol=1e-10 * float(jnp.abs(J_fd).max()))


def test_sri_native_parity(tmp_path, fixtures_dir):
    """The native C++ runtime mirrors the SRI blending to roundoff."""
    import jax.numpy as jnp
    from batchreactor_tpu import native
    from batchreactor_tpu.ops.rhs import make_gas_rhs

    mech = _mini_mech(tmp_path,
                      "H2+O2(+M)=2OH(+M)   4.0E13  0.5  1000.\n"
                      "LOW /2.0E16  0.0  800./\n"
                      "SRI /0.54  201.  1024.  0.7  0.1/\n")
    gm = br.compile_gaschemistry(mech)
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    T = 1150.0
    y = np.array([0.1, 0.5, 0.01, 0.02, 0.37])  # rho_k, kg/m^3
    dy_jax = np.asarray(make_gas_rhs(gm, th)(0.0, jnp.asarray(y), {"T": T}))
    dy_nat = native.gas_rhs(gm, th, T, y)
    np.testing.assert_allclose(dy_nat, dy_jax, rtol=1e-10,
                               atol=1e-12 * np.abs(dy_jax).max())


def test_sri_validation(tmp_path):
    with pytest.raises(ValueError, match="non-falloff"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2=2OH  1.0E13 0. 0.\nSRI /0.5 100. 200./\n"))
    with pytest.raises(ValueError, match="mutually exclusive"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2(+M)=2OH(+M)  1.0E13 0. 0.\n"
                      "LOW /1.0E16 0. 0./\n"
                      "TROE /0.6 100. 1000./\n"
                      "SRI /0.5 100. 200./\n"))
    with pytest.raises(ValueError, match="3 or 5"):
        br.compile_gaschemistry(_mini_mech(
            tmp_path, "H2+O2(+M)=2OH(+M)  1.0E13 0. 0.\n"
                      "LOW /1.0E16 0. 0./\nSRI /0.5 100./\n"))
