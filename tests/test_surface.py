"""Surface mechanism + kinetics tests.

The oracle is the committed golden trajectory of the coupled gas+surface run
(/root/reference/test/batch_gas_and_surf/{gas_profile,surface_covg}.csv):
its second row, 4.32e-16 s after t=0, is a finite-difference measurement of
the reference's RHS at the initial state, accurate to ~1e-4.  See PARITY.md
for the full convention-recovery analysis.
"""

import csv
import os

import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.surface import compile_mech
from batchreactor_tpu.ops import surface_kinetics
from batchreactor_tpu.ops.rhs import make_surface_rhs
from batchreactor_tpu.solver.sdirk import SUCCESS, solve
from batchreactor_tpu.utils.composition import density, mole_to_mass

GOLD = os.path.join(os.environ.get("BR_REFERENCE", "/root/reference"),
                    "test", "batch_gas_and_surf")

#: golden-CSV tests are reference-only: on a bare clone they must skip,
#: not fail (conftest convention — mechanism tests run from the vendored
#: fixtures, reference-parity tests need the reference checkout).  The
#: guard sits at collection time so the 10 s coupled golden run never
#: compiles before discovering its CSV is absent.
needs_reference = pytest.mark.skipif(
    not os.path.isdir(GOLD),
    reason=f"reference golden CSVs unavailable at {GOLD} (bare clone)")


@pytest.fixture(scope="module")
def setup(gri_lib_dir):
    gm = br.compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{gri_lib_dir}/therm.dat")
    sm = compile_mech(f"{gri_lib_dir}/ch4ni.xml", th, list(gm.species))
    return gm, th, sm


@pytest.fixture(scope="module")
def surf_only(gri_lib_dir):
    """batch_surf config: 7 gas species listed in the XML, no gas mechanism."""
    gasphase = ["CH4", "H2O", "H2", "CO", "CO2", "O2", "N2"]
    th = br.create_thermo(gasphase, f"{gri_lib_dir}/therm.dat")
    sm = compile_mech(f"{gri_lib_dir}/ch4ni.xml", th, gasphase)
    return th, sm


def test_parse_counts(setup):
    _, _, sm = setup
    assert sm.n_surface_species == 13
    assert sm.n_reactions == 42
    assert int(sm.stick.sum()) == 6
    assert float(sm.site_density) == 2.66e-9  # mol/cm^2, ch4ni.xml:6
    assert sm.species[0] == "(NI)"


def test_site_data(setup):
    _, _, sm = setup
    covg = dict(zip(sm.species, np.asarray(sm.ini_covg)))
    assert covg["(NI)"] == 0.6 and covg["H2O(NI)"] == 0.4
    assert abs(float(sm.ini_covg.sum()) - 1.0) < 1e-12
    sigma = dict(zip(sm.species, np.asarray(sm.site_coordination)))
    assert sigma["CH4(NI)"] == 1.0 and sigma["CO(NI)"] == 1.0


def test_coverage_dependence(setup):
    """<coverage id="12 20 21">co(ni)=-50</coverage> + id=23 +50 (kJ/mol)."""
    _, _, sm = setup
    ico = sm.species.index("CO(NI)")
    eps = np.asarray(sm.cov_eps)[:, ico]
    assert eps[11] == -50e3 and eps[19] == -50e3 and eps[20] == -50e3
    assert eps[22] == +50e3
    assert np.count_nonzero(eps) == 4


def test_site_conservation(setup):
    """Every reaction conserves surface sites (sigma-weighted)."""
    _, _, sm = setup
    bal = (np.asarray(sm.nu_r_surf) - np.asarray(sm.nu_f_surf)) @ np.asarray(
        sm.site_coordination
    )
    np.testing.assert_allclose(bal, 0.0, atol=1e-12)


def _initial_state(gm, th, sm):
    sp = list(gm.species)
    x0 = np.zeros(len(sp))
    x0[sp.index("CH4")], x0[sp.index("O2")], x0[sp.index("N2")] = 0.25, 0.5, 0.25
    rho = float(density(jnp.asarray(x0), th.molwt, 1173.0, 1e5))
    y0 = jnp.concatenate([mole_to_mass(jnp.asarray(x0), th.molwt) * rho, sm.ini_covg])
    return y0


def _golden_fd():
    """Finite-difference d(mole_frac)/dt and d(theta)/dt from golden rows 1-2."""
    rows = list(csv.reader(open(f"{GOLD}/gas_profile.csv")))
    hdr, r0, r1 = rows[0], [float(v) for v in rows[1]], [float(v) for v in rows[2]]
    dt = r1[0] - r0[0]
    dx = {hdr[i]: (r1[i] - r0[i]) / dt for i in range(4, len(hdr))}
    rows = list(csv.reader(open(f"{GOLD}/surface_covg.csv")))
    shdr, s0, s1 = rows[0], [float(v) for v in rows[1]], [float(v) for v in rows[2]]
    dth = {shdr[i]: (s1[i] - s0[i]) / dt for i in range(2, len(shdr))}
    return dx, dth


def _our_dx(gm, th, rhs, y0):
    dy = np.asarray(rhs(0.0, y0, {"T": 1173.0, "Asv": 1.0}))
    W = np.asarray(th.molwt)
    ng = len(th.species)
    n = np.asarray(y0)[:ng] / W
    ntot = n.sum()
    dn = dy[:ng] / W
    dx = dn / ntot - (n / ntot) * (dn.sum() / ntot)
    return dx, dy[ng:]


@needs_reference
def test_golden_initial_rates_surface(setup):
    """Coverage derivatives at t=0 match the reference to <0.1% (stick theta^m
    convention, Gamma*theta Arrhenius convention, Asv default 1)."""
    gm, th, sm = setup
    rhs = make_surface_rhs(sm, th, gm=gm, asv_quirk=True)
    y0 = _initial_state(gm, th, sm)
    _, dtheta = _our_dx(gm, th, rhs, y0)
    _, gold = _golden_fd()
    for i, s in enumerate(sm.species):
        if abs(gold[s]) > 1e-3:  # above golden noise floor
            assert abs(dtheta[i] / gold[s] - 1) < 1e-3, (s, dtheta[i], gold[s])


@needs_reference
def test_golden_initial_rates_gas(setup):
    """Surface-driven and forward gas channels match the reference exactly;
    with kc_compat also the dn!=0 reverse channels (PARITY.md)."""
    gm, th, sm = setup
    y0 = _initial_state(gm, th, sm)
    gold, _ = _golden_fd()

    rhs = make_surface_rhs(sm, th, gm=gm, kc_compat=True)
    dx, _ = _our_dx(gm, th, rhs, y0)
    sp = list(gm.species)
    for s in ["CH4", "O2", "H2O", "N2", "HO2", "O", "NNH", "N2O"]:
        assert abs(dx[sp.index(s)] / gold[s] - 1) < 2e-3, s
    # CH3 = exact HO2-route + falloff-reverse route (reference falloff-reverse
    # convention is unresolved; see PARITY.md) — bounded, not exact:
    assert abs(dx[sp.index("CH3")] / gold["CH3"] - 1) < 0.1


def test_asv_quirk(surf_only):
    """Reference :345 scales the WHOLE surface source (incl. coverages) by Asv;
    textbook coverage equation has no Asv term.  Both behaviours available."""
    th, sm = surf_only
    sp = list(th.species)
    x0 = np.zeros(7)
    x0[sp.index("CH4")], x0[sp.index("H2O")], x0[sp.index("N2")] = 0.25, 0.25, 0.5
    rho = float(density(jnp.asarray(x0), th.molwt, 1073.15, 1e5))
    y0 = jnp.concatenate([mole_to_mass(jnp.asarray(x0), th.molwt) * rho, sm.ini_covg])
    cfg10 = {"T": 1073.15, "Asv": 10.0}
    quirk = make_surface_rhs(sm, th, asv_quirk=True)
    plain = make_surface_rhs(sm, th, asv_quirk=False)
    d_q = np.asarray(quirk(0.0, y0, cfg10))
    d_p = np.asarray(plain(0.0, y0, cfg10))
    # gas part identical; coverage part differs by exactly Asv
    np.testing.assert_allclose(d_q[:7], d_p[:7], rtol=1e-14)
    nz = np.abs(d_p[7:]) > 0
    np.testing.assert_allclose(d_q[7:][nz] / d_p[7:][nz], 10.0, rtol=1e-12)


def test_batch_surf_integration(surf_only):
    """batch_surf config end-to-end: CH4 steam reforming on Ni, 10 s, Asv=10
    (/root/reference/test/batch_surf/batch.xml).  Site fraction conserved."""
    th, sm = surf_only
    sp = list(th.species)
    x0 = np.zeros(7)
    x0[sp.index("CH4")], x0[sp.index("H2O")], x0[sp.index("N2")] = 0.25, 0.25, 0.5
    rho = float(density(jnp.asarray(x0), th.molwt, 1073.15, 1e5))
    y0 = jnp.concatenate([mole_to_mass(jnp.asarray(x0), th.molwt) * rho, sm.ini_covg])
    rhs = make_surface_rhs(sm, th, asv_quirk=True)
    r = solve(rhs, y0, 0.0, 10.0, {"T": 1073.15, "Asv": 10.0}, rtol=1e-6,
              atol=1e-10, dt0=1e-16, dt_min_factor=1e-22, max_steps=200000)
    assert int(r.status) == SUCCESS
    theta = np.asarray(r.y)[7:]
    assert abs(theta.sum() - 1.0) < 1e-6  # site conservation
    assert np.all(theta > -1e-9)
    # steam reforming must produce syngas
    yf = np.asarray(r.y)[:7]
    xf = yf / np.asarray(th.molwt)
    xf /= xf.sum()
    assert xf[sp.index("H2")] > 0.01 and xf[sp.index("CO")] > 0.001
    # gas mass exchange balances surface uptake: total mass conserved to the
    # extent the quirk allows (gas mass alone isn't conserved: adsorption)
    assert np.all(np.isfinite(yf))


@needs_reference
def test_gas_and_surf_final_state(setup):
    """Full 10 s coupled run: bulk final composition vs golden CSV (<0.2%).
    Minor-species tails differ through the reference's falloff-reverse
    convention (PARITY.md); bulk thermochemistry must agree."""
    gm, th, sm = setup
    y0 = _initial_state(gm, th, sm)
    rhs = make_surface_rhs(sm, th, gm=gm, asv_quirk=True, kc_compat=True)
    r = solve(rhs, y0, 0.0, 10.0, {"T": 1173.0, "Asv": 1.0}, rtol=1e-6,
              atol=1e-10, dt0=1e-16, dt_min_factor=1e-22, max_steps=400000)
    assert int(r.status) == SUCCESS
    W = np.asarray(th.molwt)
    xg = np.asarray(r.y)[:53] / W
    xg /= xg.sum()
    rows = list(csv.reader(open(f"{GOLD}/gas_profile.csv")))
    hdr, last = rows[0], [float(v) for v in rows[-1]]
    gold = {hdr[i]: last[i] for i in range(len(hdr))}
    sp = list(gm.species)
    for s in ["H2O", "CO2", "N2"]:
        assert abs(xg[sp.index(s)] - gold[s]) / gold[s] < 2e-3, s
    assert xg[sp.index("CH4")] < 1e-8  # full conversion, like the reference


def _jac_match(rhs, jac, y, cfg):
    import jax

    J_a = np.asarray(jac(0.0, y, cfg))
    J_fd = np.asarray(jax.jacfwd(lambda yy: rhs(0.0, yy, cfg))(y))
    scale = np.abs(J_fd).max()
    np.testing.assert_allclose(J_a, J_fd, rtol=1e-12, atol=1e-12 * scale)


def test_surface_jac_matches_jacfwd(surf_only):
    """Analytic surf-only Jacobian == jax.jacfwd to roundoff, at the initial
    state and at a perturbed state with all coverages populated (exercises
    coverage-Ea, stick and MWC derivative terms)."""
    from batchreactor_tpu.ops.rhs import make_surface_jac

    th, sm = surf_only
    sp = list(th.species)
    x0 = np.zeros(7)
    x0[sp.index("CH4")], x0[sp.index("H2O")], x0[sp.index("N2")] = .25, .25, .5
    rho = float(density(jnp.asarray(x0), th.molwt, 1073.15, 1e5))
    y0 = jnp.concatenate(
        [mole_to_mass(jnp.asarray(x0), th.molwt) * rho, sm.ini_covg])
    cfg = {"T": jnp.asarray(1073.15), "Asv": jnp.asarray(10.0)}
    for quirk in (True, False):
        rhs = make_surface_rhs(sm, th, asv_quirk=quirk)
        jac = make_surface_jac(sm, th, asv_quirk=quirk)
        _jac_match(rhs, jac, y0, cfg)
    # perturbed: uniform coverages, shifted gas state
    rng = np.random.default_rng(0)
    theta = np.full(13, 1.0 / 13)
    ygas = np.asarray(y0)[:7] * (1.0 + 0.3 * rng.random(7))
    y1 = jnp.asarray(np.concatenate([ygas, theta]))
    rhs = make_surface_rhs(sm, th, asv_quirk=True)
    jac = make_surface_jac(sm, th, asv_quirk=True)
    _jac_match(rhs, jac, y1, cfg)


def test_coupled_jac_matches_jacfwd(setup):
    """gas+surf (GRI + CH4/Ni, 66-state) analytic block Jacobian == jacfwd."""
    from batchreactor_tpu.ops.rhs import make_surface_jac

    gm, th, sm = setup
    y0 = _initial_state(gm, th, sm)
    cfg = {"T": jnp.asarray(1173.0), "Asv": jnp.asarray(1.0)}
    rhs = make_surface_rhs(sm, th, gm=gm, asv_quirk=True, kc_compat=True)
    jac = make_surface_jac(sm, th, gm=gm, asv_quirk=True, kc_compat=True)
    _jac_match(rhs, jac, y0, cfg)
    # mid-trajectory-like state: everything populated
    rng = np.random.default_rng(1)
    ng = gm.n_species
    ygas = np.asarray(y0)[:ng] + 1e-4 * rng.random(ng)
    theta = rng.random(13)
    theta /= theta.sum()
    y1 = jnp.asarray(np.concatenate([ygas, theta]))
    _jac_match(rhs, jac, y1, cfg)


def test_malformed_xml_raises_loudly(tmp_path, gri_lib_dir):
    """Malformed surface XML fails with the offending element in the
    message, never an AttributeError from a missing tag (the parsers'
    fail-loud contract)."""
    gasphase = ["H2", "O2", "N2"]
    th = br.create_thermo(gasphase, f"{gri_lib_dir}/therm.dat")
    missing_density = """<?xml version="1.0"?>
<surface_mech unit="kJ/mol">
 <species>x(s)</species>
 <site><coordination>x(s)=1</coordination><initial>x(s)=1.0</initial></site>
</surface_mech>"""
    p = tmp_path / "bad1.xml"
    p.write_text(missing_density)
    with pytest.raises(ValueError, match="density"):
        compile_mech(str(p), th, gasphase)

    bad_rxn = """<?xml version="1.0"?>
<surface_mech unit="kJ/mol">
 <species>x(s)</species>
 <site><coordination>x(s)=1</coordination>
   <density unit="mol/cm2">2.6e-9</density>
   <initial>x(s)=1.0</initial></site>
 <arrhenius><rxn id="7">H2 + x(s) no-rate-separator</rxn></arrhenius>
</surface_mech>"""
    p2 = tmp_path / "bad2.xml"
    p2.write_text(bad_rxn)
    with pytest.raises(ValueError, match="reaction 7"):
        compile_mech(str(p2), th, gasphase)
