"""End-to-end trajectory tests: mechanism -> RHS -> SDIRK solve, validated
against physics (equilibrium, conservation) and a scipy-BDF oracle of the
identical RHS (the CPU stand-in for the reference's CVODE baseline;
SURVEY.md §6 baseline protocol)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.thermo import element_matrix
from batchreactor_tpu.ops.rhs import make_gas_rhs
from batchreactor_tpu.solver.sdirk import SUCCESS, solve
from batchreactor_tpu.utils.composition import density, mole_to_mass


@pytest.fixture(scope="module")
def h2o2_problem(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    sp = list(gm.species)
    x = np.zeros(9)
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.25, 0.25, 0.5
    rho = density(jnp.asarray(x), th.molwt, 1173.0, 1e5)
    y0 = mole_to_mass(jnp.asarray(x), th.molwt) * rho
    return gm, th, y0


def test_h2o2_equilibrium(h2o2_problem):
    """batch_h2o2 config (/root/reference/test/batch_h2o2/batch.xml):
    10 s at 1173 K burns H2 to completion -> known stoichiometric endpoint."""
    gm, th, y0 = h2o2_problem
    rhs = make_gas_rhs(gm, th)
    r = jax.jit(
        lambda y: solve(rhs, y, 0.0, 10.0, {"T": 1173.0}, rtol=1e-6, atol=1e-10)
    )(y0)
    assert int(r.status) == SUCCESS
    sp = list(gm.species)
    xf = np.asarray(r.y) / np.asarray(th.molwt)
    xf /= xf.sum()
    np.testing.assert_allclose(xf[sp.index("H2O")], 2 / 7, rtol=1e-4)
    np.testing.assert_allclose(xf[sp.index("O2")], 1 / 7, rtol=1e-4)
    np.testing.assert_allclose(xf[sp.index("N2")], 4 / 7, rtol=1e-4)
    # mass conservation through ~500 implicit steps
    assert abs(float(jnp.sum(r.y) - jnp.sum(y0))) < 1e-12


def test_h2o2_trajectory_vs_scipy(h2o2_problem):
    """Same RHS through scipy BDF at tighter tolerance: intermediate-time
    composition must agree (trajectory-level, not just equilibrium)."""
    gm, th, y0 = h2o2_problem
    rhs = make_gas_rhs(gm, th)
    t_end = 2e-3  # mid-ignition, the numerically hardest region
    r = jax.jit(
        lambda y: solve(rhs, y, 0.0, t_end, {"T": 1173.0}, rtol=1e-8, atol=1e-14)
    )(y0)
    assert int(r.status) == SUCCESS
    f = jax.jit(rhs)
    jac = jax.jit(jax.jacfwd(lambda y: rhs(0.0, y, {"T": 1173.0})))
    from scipy.integrate import solve_ivp

    ref = solve_ivp(
        lambda t, y: np.asarray(f(t, jnp.asarray(y), {"T": 1173.0})),
        (0, t_end), np.asarray(y0), method="BDF",
        jac=lambda t, y: np.asarray(jac(jnp.asarray(y))),
        rtol=1e-9, atol=1e-14,
    )
    assert ref.status == 0
    major = np.asarray(r.y) > 1e-8  # compare species above noise floor
    np.testing.assert_allclose(
        np.asarray(r.y)[major], ref.y[:, -1][major], rtol=5e-4
    )


def test_element_conservation_along_trajectory(h2o2_problem):
    gm, th, y0 = h2o2_problem
    rhs = make_gas_rhs(gm, th)
    r = solve(rhs, y0, 0.0, 10.0, {"T": 1173.0}, rtol=1e-6, atol=1e-10,
              n_save=1024)
    n = int(r.n_saved)
    _, E = element_matrix(th)
    moles = np.asarray(r.ys)[:n] / np.asarray(th.molwt)  # mol/m^3 per row
    elem = moles @ E.T
    np.testing.assert_allclose(elem, np.broadcast_to(elem[0], elem.shape), rtol=1e-9)
