"""Native (C++) runtime tests: RHS parity vs the JAX kernels, BDF accuracy
vs scipy/SDIRK oracles, trajectory buffers, and the Python-callback path.

The native runtime (batchreactor_tpu/native/br_native.cpp) is the framework's analog of the
reference's wrapped C libraries (SUNDIALS CVODE at
/root/reference/src/BatchReactor.jl:138,210): a CHEMKIN-semantics gas RHS
plus a CVODE-class variable-order BDF, loaded via ctypes."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.integrate import solve_ivp

import batchreactor_tpu as br
from batchreactor_tpu import native
from batchreactor_tpu.ops.rhs import make_gas_rhs
from batchreactor_tpu.solver import sdirk
from batchreactor_tpu.utils.composition import density, mole_to_mass

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable (no g++?)")


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, th


@pytest.fixture(scope="module")
def gri(gri_lib_dir):
    gm = br.compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{gri_lib_dir}/therm.dat")
    return gm, th


def _initial_state(gm, th, comp, T, p=1e5):
    sp = list(gm.species)
    x0 = np.zeros(len(sp))
    for name, frac in comp.items():
        x0[sp.index(name)] = frac
    rho = float(density(jnp.asarray(x0), th.molwt, T, p))
    return np.asarray(mole_to_mass(jnp.asarray(x0), th.molwt)) * rho, rho


@pytest.mark.parametrize("kc_compat", [False, True])
def test_gas_rhs_matches_jax_gri(gri, kc_compat):
    """C++ and JAX implementations of the same kernel must agree to rounding
    (GRI-3.0 exercises falloff/TROE/third-body/duplicate paths)."""
    gm, th = gri
    y0, rho = _initial_state(gm, th, {"CH4": 0.25, "O2": 0.5, "N2": 0.25},
                             1500.0)
    # a dirtied state exercises every reaction channel
    rng = np.random.default_rng(42)
    y = y0 + rho * 1e-6 * rng.random(y0.shape[0])
    rhs = make_gas_rhs(gm, th, kc_compat=kc_compat)
    d_jax = np.asarray(rhs(0.0, jnp.asarray(y), {"T": jnp.asarray(1500.0)}))
    d_nat = native.gas_rhs(gm, th, 1500.0, y, kc_compat=kc_compat)
    rel = np.abs(d_jax - d_nat) / np.maximum(np.abs(d_jax), 1e-30)
    assert rel.max() < 1e-8


def test_gas_rhs_matches_jax_h2o2(h2o2):
    gm, th = h2o2
    y0, _ = _initial_state(gm, th, {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                           1173.0)
    rhs = make_gas_rhs(gm, th)
    d_jax = np.asarray(rhs(0.0, jnp.asarray(y0), {"T": jnp.asarray(1173.0)}))
    d_nat = native.gas_rhs(gm, th, 1173.0, y0)
    rel = np.abs(d_jax - d_nat) / np.maximum(np.abs(d_jax), 1e-30)
    assert rel.max() < 1e-10


def test_bdf_vs_scipy_h2o2(h2o2):
    """Full 10 s burnout: native BDF final state matches scipy BDF on the
    identical RHS (solver-vs-solver, physics held fixed)."""
    gm, th = h2o2
    y0, rho = _initial_state(gm, th, {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                             1173.0)
    res = native.solve_gas_bdf(gm, th, 1173.0, y0, 0.0, 10.0)
    assert res.status == "Success"
    assert res.t == pytest.approx(10.0)
    sol = solve_ivp(lambda t, y: native.gas_rhs(gm, th, 1173.0, y),
                    (0.0, 10.0), y0, method="BDF", rtol=1e-6, atol=1e-10)
    assert sol.success
    rel = np.abs(res.y - sol.y[:, -1]) / np.maximum(
        np.abs(sol.y[:, -1]), rho * 1e-9)
    assert rel.max() < 1e-3
    # mass conservation is exact in the physics; solver must preserve it
    assert abs(res.y.sum() - rho) / rho < 1e-12


def test_bdf_matches_sdirk_gri_ignition(gri):
    """The two framework solvers (native BDF, JAX SDIRK4) agree through a
    GRI ignition transient on the major species."""
    gm, th = gri
    y0, rho = _initial_state(gm, th, {"CH4": 0.25, "O2": 0.5, "N2": 0.25},
                             1500.0)
    res_n = native.solve_gas_bdf(gm, th, 1500.0, y0, 0.0, 8e-4)
    assert res_n.status == "Success"
    rhs = make_gas_rhs(gm, th)
    res_j = sdirk.solve(rhs, jnp.asarray(y0), 0.0, 8e-4,
                        {"T": jnp.asarray(1500.0)}, rtol=1e-6, atol=1e-10)
    assert int(res_j.status) == sdirk.SUCCESS
    yj = np.asarray(res_j.y)
    # compare species that remain above 1e-6 of the mixture mass
    major = yj > rho * 1e-6
    rel = np.abs(res_n.y[major] - yj[major]) / np.abs(yj[major])
    assert rel.max() < 5e-3


def test_trajectory_buffer(h2o2):
    gm, th = h2o2
    y0, _ = _initial_state(gm, th, {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                           1173.0)
    res = native.solve_gas_bdf(gm, th, 1173.0, y0, 0.0, 1e-3, n_save=10_000)
    assert res.status == "Success"
    assert res.ts.shape[0] == res.n_accepted
    assert res.ys.shape == (res.n_accepted, y0.shape[0])
    assert np.all(np.diff(res.ts) > 0)
    assert res.ts[-1] == pytest.approx(1e-3)
    np.testing.assert_allclose(res.ys[-1], res.y, rtol=1e-12)


def test_generic_bdf_python_callback_robertson():
    """Generic BDF with a Python RHS callback on the canonical stiff problem
    (same oracle as tests/test_solver.py::test_robertson_vs_scipy)."""

    def rob(t, y):
        d1 = -0.04 * y[0] + 1e4 * y[1] * y[2]
        d3 = 3e7 * y[1] * y[1]
        return np.array([d1, -d1 - d3, d3])

    y0 = np.array([1.0, 0.0, 0.0])
    res = native.solve_bdf(rob, y0, 0.0, 1e5, rtol=1e-8, atol=1e-12)
    assert res.status == "Success"
    sol = solve_ivp(rob, (0.0, 1e5), y0, method="BDF", rtol=1e-10, atol=1e-14)
    np.testing.assert_allclose(res.y, sol.y[:, -1], rtol=1e-5, atol=1e-14)


def test_generic_bdf_propagates_python_error():
    def bad(t, y):
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        native.solve_bdf(bad, np.array([1.0]), 0.0, 1.0)


def test_first_step_and_max_steps(h2o2):
    gm, th = h2o2
    y0, _ = _initial_state(gm, th, {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                           1173.0)
    res = native.solve_gas_bdf(gm, th, 1173.0, y0, 0.0, 10.0, max_steps=5)
    assert res.status == "MaxIters"
    assert res.t < 10.0


class TestNativeSurface:
    """Native surface kinetics vs the JAX kernels (ops/surface_kinetics.py)
    and the all-native surf/gas+surf solve path (backend="cpu")."""

    @pytest.fixture(scope="class")
    def surf(self, reference_dir, lib_dir):
        from batchreactor_tpu.io.config import input_data
        from batchreactor_tpu.api import Chemistry

        id_ = input_data(str(reference_dir / "test/batch_gas_and_surf/batch.xml"),
                         lib_dir, Chemistry(surfchem=True, gaschem=True))
        return id_

    def test_surface_rates_match_jax(self, surf):
        from batchreactor_tpu.ops import surface_kinetics

        id_ = surf
        sm = id_.smd
        T, p = id_.T, id_.p
        x = jnp.asarray(id_.mole_fracs)
        theta = sm.ini_covg
        sg_j, ss_j = surface_kinetics.production_rates(T, p, x, theta, sm)
        sg_n, ss_n = native.surface_rates(sm, T, p, np.asarray(x),
                                          np.asarray(theta))
        np.testing.assert_allclose(sg_n, np.asarray(sg_j), rtol=1e-12,
                                   atol=1e-300)
        np.testing.assert_allclose(ss_n, np.asarray(ss_j), rtol=1e-12,
                                   atol=1e-300)

    @pytest.mark.parametrize("coupled", [False, True])
    def test_surf_rhs_matches_jax(self, surf, coupled):
        from batchreactor_tpu.api import get_solution_vector
        from batchreactor_tpu.ops.rhs import make_surface_rhs

        id_ = surf
        y0 = get_solution_vector(id_.mole_fracs, id_.thermo.molwt, id_.T,
                                 id_.p, ini_covg=id_.smd.ini_covg)
        gm = id_.gmd if coupled else None
        rhs = make_surface_rhs(id_.smd, id_.thermo, gm=gm)
        cfg = {"T": jnp.asarray(id_.T), "Asv": jnp.asarray(id_.Asv)}
        dy_j = np.asarray(rhs(0.0, y0, cfg))
        dy_n = native.surf_rhs(id_.smd, id_.thermo, id_.T, id_.Asv,
                               np.asarray(y0), gm=gm)
        scale = np.max(np.abs(dy_j))
        np.testing.assert_allclose(dy_n, dy_j, rtol=1e-10, atol=1e-12 * scale)

    def test_native_backend_gas_and_surf_run(self, surf, tmp_path, lib_dir):
        """backend="cpu" end-to-end on the golden gas+surf config (short
        horizon): runs all-native and matches the JAX backend's state."""
        import shutil

        src = "/root/reference/test/batch_gas_and_surf/batch.xml"
        dst = tmp_path / "batch.xml"
        txt = open(src).read().replace("<time>10</time>", "<time>1e-4</time>")
        dst.write_text(txt)
        ret = br.batch_reactor(str(dst), lib_dir, gaschem=True, surfchem=True,
                               backend="cpu")
        assert ret == "Success"
        rows_cpu = open(tmp_path / "gas_profile.csv").readlines()
        ret = br.batch_reactor(str(dst), lib_dir, gaschem=True, surfchem=True,
                               backend="jax")
        assert ret == "Success"
        rows_jax = open(tmp_path / "gas_profile.csv").readlines()
        last_cpu = np.array([float(v) for v in rows_cpu[-1].split(",")])
        last_jax = np.array([float(v) for v in rows_jax[-1].split(",")])
        # same final time, state agreement at solver-tolerance scale
        np.testing.assert_allclose(last_cpu[0], last_jax[0], rtol=1e-12)
        np.testing.assert_allclose(last_cpu[1:], last_jax[1:], rtol=5e-4,
                                   atol=1e-12)


def test_gas_rhs_rev_and_negative_A_matches_jax(tmp_path, fixtures_dir):
    """REV rows and negative-A DUPLICATE rows: C++ RHS == JAX RHS (the two
    independent implementations pin the CHEMKIN-II semantics)."""
    p = tmp_path / "mini.dat"
    p.write_text(
        "ELEMENTS\nH O N\nEND\nSPECIES\nH2 O2 OH H2O N2\nEND\nREACTIONS\n"
        "H2+O2=2OH   4.0E13  0.5  1000.\n"
        "REV /2.0E11  0.3  500./\n"
        "2OH=H2O+O2  1.0E12  0.0  300.\n"
        "H2+O2=>2OH   3.0E13  0.0  1500.\n"
        "DUPLICATE\n"
        "H2+O2=>2OH  -1.0E12  0.0  2500.\n"
        "DUPLICATE\nEND\n")
    gm = br.compile_gaschemistry(str(p))
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    y = np.array([0.05, 0.4, 0.01, 0.02, 0.6])  # rho_k, kg/m^3
    rhs = make_gas_rhs(gm, th)
    d_jax = np.asarray(rhs(0.0, jnp.asarray(y), {"T": jnp.asarray(1200.0)}))
    d_nat = native.gas_rhs(gm, th, 1200.0, y)
    np.testing.assert_allclose(d_nat, d_jax, rtol=1e-10)


def test_gas_rhs_plog_matches_jax(tmp_path, fixtures_dir):
    """PLOG pressure interpolation: C++ RHS == JAX RHS at pressures below,
    inside, and above the table."""
    p = tmp_path / "plog.dat"
    p.write_text(
        "ELEMENTS\nH O N\nEND\nSPECIES\nH2 O2 OH H2O N2\nEND\nREACTIONS\n"
        "H2+O2=2OH   1.0E13  0.0  1000.\n"
        "PLOG / 0.1   1.0E12  0.5  900. /\n"
        "PLOG / 1.0   1.0E13  0.2  1100. /\n"
        "PLOG / 10.0  1.0E14  0.0  1300. /\n"
        "2OH=H2O+O2  1.0E12  0.0  300.\nEND\n")
    gm = br.compile_gaschemistry(str(p))
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    rhs = make_gas_rhs(gm, th)
    for scale in (0.05, 1.0, 40.0):
        y = np.array([0.05, 0.4, 0.01, 0.02, 0.6]) * scale
        d_jax = np.asarray(rhs(0.0, jnp.asarray(y),
                               {"T": jnp.asarray(1100.0)}))
        d_nat = native.gas_rhs(gm, th, 1100.0, y)
        np.testing.assert_allclose(d_nat, d_jax, rtol=1e-10)


def test_gas_rhs_cheb_matches_jax(tmp_path, fixtures_dir):
    """Chebyshev tables: C++ RHS == JAX RHS inside and outside the window."""
    p = tmp_path / "cheb.dat"
    p.write_text(
        "ELEMENTS\nH O N\nEND\nSPECIES\nH2 O2 OH H2O N2\nEND\nREACTIONS\n"
        "H2+O2=2OH   1.0 0.0 0.0\n"
        "TCHEB / 500. 2000. /\n"
        "PCHEB / 0.1 10. /\n"
        "CHEB / 3 4 7.0 0.5 -0.1 0.05 -0.3 0.1 0.02 -0.01 "
        "0.04 -0.02 0.01 0.005 /\n"
        "2OH=H2O+O2  1.0E12  0.0  300.\nEND\n")
    gm = br.compile_gaschemistry(str(p))
    th = br.create_thermo(list(gm.species), f"{fixtures_dir}/therm.dat")
    rhs = make_gas_rhs(gm, th)
    for scale in (0.001, 1.0, 50.0):
        y = np.array([0.05, 0.4, 0.01, 0.02, 0.6]) * scale
        d_jax = np.asarray(rhs(0.0, jnp.asarray(y),
                               {"T": jnp.asarray(1100.0)}))
        d_nat = native.gas_rhs(gm, th, 1100.0, y)
        np.testing.assert_allclose(d_nat, d_jax, rtol=1e-10)
