"""Newton linear algebra (solver/linalg.py + solver/linalg_pallas.py).

Three contracts pinned here:

* the exactly-singular pivot guard — downstream Newton-divergence
  recovery (bdf/sdirk ``bad`` gate -> step rejection -> h shrink) is
  ASSERTED, not assumed: the factor stays finite, the solve goes
  non-finite only through the singular directions, and the displacement
  norm the Newton gate reads is non-finite;
* jnp-LU vs Pallas-LU parity (interpret mode — the CPU tier-1 suite
  runs the kernel path end-to-end without Mosaic) on batched random
  systems including pivoting-required and near-singular matrices;
* the factor-as-data layer (``factor_zeros``/``factor_m``/
  ``apply_factor``) that the BDF setup-economy carry rides: structure
  match leaf-for-leaf and closure/carry-form equivalence.

Everything is tiny (n <= 13, B <= 8): pure-linalg compiles, no
mechanism parses, well inside the tier-1 budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu.solver import linalg
from batchreactor_tpu.solver.linalg import (MODES, apply_factor, factor_m,
                                            factor_zeros, lu_factor,
                                            lu_solve, make_solve_m,
                                            resolve_linsolve)
from batchreactor_tpu.solver.linalg_pallas import (lu32p_factor, lu32p_solve,
                                                   padded_n)


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("n", [1, 3, 5, 8, 13])
def test_lu32p_matches_numpy_batched(n):
    """Blocked Pallas LU (interpret on CPU) solves batched random systems
    to f32 accuracy — the inv32* preconditioner accuracy class."""
    rng = np.random.default_rng(n)
    A = rng.standard_normal((8, n, n))
    b = rng.standard_normal((8, n))
    x_ref = np.linalg.solve(A, b[..., None])[..., 0]
    LU, piv = jax.vmap(lu32p_factor)(jnp.asarray(A))
    x = jax.vmap(lu32p_solve)((LU, piv), jnp.asarray(b, dtype=jnp.float32))
    scale = np.max(np.abs(x_ref), axis=-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(x), x_ref, atol=2e-5 * scale.max(),
                               rtol=2e-5)


def test_lu32p_padding_shape():
    assert padded_n(1) == 8 and padded_n(8) == 8 and padded_n(9) == 16
    LU, piv = lu32p_factor(jnp.eye(5))
    assert LU.shape == (8, 8) and piv.shape == (8,)


def test_lu32p_pivoting_required():
    """Zero diagonal: unpivoted elimination would divide by zero at step
    0 — partial pivoting is load-bearing, not an optimization."""
    A = jnp.asarray([[0.0, 1.0, 0.0],
                     [2.0, 0.0, 1.0],
                     [0.0, 3.0, 1.0]])
    b = jnp.asarray([1.0, 2.0, 3.0], dtype=jnp.float32)
    x_ref = np.linalg.solve(np.asarray(A), np.asarray(b))
    x = lu32p_solve(lu32p_factor(A), b)
    np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-5, atol=1e-5)
    # and the jnp reference path agrees with itself on the same system
    xj = lu_solve(lu_factor(A.astype(jnp.float64)),
                  b.astype(jnp.float64))
    np.testing.assert_allclose(np.asarray(xj), x_ref, rtol=1e-12, atol=1e-14)


def test_lu32p_near_singular_parity_with_jnp_f32():
    """Near-singular (cond ~1e5) systems: the two factorizations must
    agree to the accuracy f32 conditioning permits — the stiff-ignition
    iteration matrices the mode exists for are exactly this class."""
    rng = np.random.default_rng(7)
    U, _ = np.linalg.qr(rng.standard_normal((6, 6)))
    V, _ = np.linalg.qr(rng.standard_normal((6, 6)))
    A = (U * np.logspace(0, -5, 6)) @ V  # singular values 1 .. 1e-5
    b = rng.standard_normal(6)
    x_ref = np.linalg.solve(A, b)
    x_p = lu32p_solve(lu32p_factor(jnp.asarray(A)),
                      jnp.asarray(b, dtype=jnp.float32))
    # f32 forward error bound ~ cond * eps32 ~ 1e5 * 1e-7 = 1e-2 relative
    rel = np.max(np.abs(np.asarray(x_p) - x_ref)) / np.max(np.abs(x_ref))
    assert rel < 5e-2, rel


# ------------------------------------------- exactly-singular pivot guard

def _singular():
    # third column identically zero: structurally singular, pivot 0 at k=2
    return jnp.asarray([[1.0, 2.0, 0.0],
                        [3.0, 4.0, 0.0],
                        [5.0, 6.0, 0.0]])


def test_singular_pivot_guard_factor_finite_solve_detectable():
    """The documented recovery seam (linalg.lu_factor docstring): the
    FACTOR is always finite (no NaN smear into nonsingular columns), the
    solve goes non-finite through the singular directions, and the
    displacement norm Newton's ``bad`` gate reads is non-finite — which
    is what turns a singular iteration matrix into a step rejection
    instead of a silent wrong answer."""
    LU, piv = lu_factor(_singular())
    assert bool(jnp.all(jnp.isfinite(LU))), np.asarray(LU)
    x = lu_solve((LU, piv), jnp.asarray([1.0, 1.0, 1.0]))
    assert not bool(jnp.all(jnp.isfinite(x)))
    # the exact gate expression bdf.newton applies to the displacement
    dw = jnp.sqrt(jnp.mean(jnp.square(x / 1.0)))
    assert not bool(jnp.isfinite(dw))


def test_singular_pivot_guard_pallas_matches_contract():
    """Same containment contract on the kernel path (interpret mode)."""
    LU, piv = lu32p_factor(_singular())
    assert bool(jnp.all(jnp.isfinite(LU))), np.asarray(LU)
    x = lu32p_solve((LU, piv), jnp.asarray([1.0, 1.0, 1.0],
                                           dtype=jnp.float32))
    assert not bool(jnp.all(jnp.isfinite(x)))


def test_singular_system_inside_newton_rejects_not_poisons():
    """End-to-end recovery: a solve whose very first iteration matrix is
    singular (rhs rows linearly dependent at y0) must not return NaN with
    SUCCESS — either it converges after step-size recovery or it reports
    a failure status."""
    from batchreactor_tpu.solver import bdf
    from batchreactor_tpu.solver.sdirk import SUCCESS

    def rhs(t, y, cfg):
        # f(y) has rank-deficient Jacobian at y=0 (rows 0 and 1 equal)
        r = y[0] + y[1]
        return jnp.stack([-r, -r, -y[2]])

    r = bdf.solve(rhs, jnp.asarray([1.0, 1.0, 1.0]), 0.0, 1.0, {},
                  rtol=1e-6, atol=1e-10)
    if int(r.status) == SUCCESS:
        assert bool(jnp.all(jnp.isfinite(r.y)))


# ------------------------------------------------- factor-as-data layer

@pytest.mark.parametrize("mode", MODES)
def test_factor_zeros_matches_factor_m_structure(mode):
    """The economy cold-start carry must mirror factor_m leaf for leaf —
    a shape/dtype mismatch would restructure the while-loop carry at the
    first window open (a trace error at best, a recompile at worst)."""
    n = 5
    M = jnp.eye(n, dtype=jnp.float64) * 2.0
    fz = factor_zeros(mode, n, jnp.float64)
    fm = factor_m(M, mode, jnp.float64)
    assert jax.tree.structure(fz) == jax.tree.structure(fm)
    for a, b in zip(jax.tree.leaves(fz), jax.tree.leaves(fm)):
        assert a.shape == b.shape and a.dtype == b.dtype, (mode, a, b)


@pytest.mark.parametrize("mode", MODES)
def test_apply_factor_is_make_solve_m(mode):
    """Closure and carry forms are one implementation (linalg docstring):
    identical bits out."""
    rng = np.random.default_rng(3)
    M = jnp.asarray(rng.standard_normal((5, 5)) + 5 * np.eye(5))
    b = jnp.asarray(rng.standard_normal(5))
    via_closure = make_solve_m(M, mode, jnp.float64)(b)
    via_carry = apply_factor(factor_m(M, mode, jnp.float64), b, mode,
                             jnp.float64)
    np.testing.assert_array_equal(np.asarray(via_closure),
                                  np.asarray(via_carry))


# ------------------------------------------------------- resolution rule

def test_resolve_linsolve_one_rule():
    assert resolve_linsolve("auto", platform="cpu") == "lu"
    assert resolve_linsolve("auto", method="sdirk", platform="tpu") == "inv32"
    assert resolve_linsolve("auto", method="bdf", platform="tpu") == "inv32f"
    # the lu32p gate: TPU + BDF + known batch at/over the lane-equation
    # floor; small sweeps and batch-blind per-lane entry points keep inv32f
    big_b = linalg.LU32P_MIN_BN // 53 + 1
    assert resolve_linsolve("auto", method="bdf", platform="tpu",
                            batch=big_b, n=53) == "lu32p"
    assert resolve_linsolve("auto", method="bdf", platform="tpu",
                            batch=4, n=53) == "inv32f"
    assert resolve_linsolve("auto", method="bdf", platform="gpu",
                            batch=big_b, n=53) == "inv32f"
    # explicit modes pass through validated; unknown raises in ONE place
    assert resolve_linsolve("lu32p", platform="cpu") == "lu32p"
    with pytest.raises(ValueError, match="unknown linsolve"):
        resolve_linsolve("qr")
