"""Deliberately-racy fixture for the brlint host-concurrency lint
(tests/test_analysis.py): one seeded violation per rule class, plus the
clean twins that must NOT flag.  Never imported by the package — the
lint parses it as source only.
"""

import threading
import time

import jax
import numpy as np

_registry_lock = threading.Lock()
_other_lock = threading.Lock()
_REGISTRY = {}


class RacyWorker:
    """Seeded class: a worker thread mutates shared state unguarded,
    blocks under the lock, and calls a *_locked helper bare."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []
        self.result = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self.count += 1                     # RACE: no lock held
            self.items.append(self.count)       # RACE: no lock held
            with self._lock:
                time.sleep(0.1)                 # BLOCKING under lock
            self._flush_locked()                # _locked helper, bare

    def _flush_locked(self):
        self.result = list(self.items)

    def ok_mutation(self):
        with self._lock:
            self.count = 0                      # guarded: must NOT flag


def inconsistent_order_a():
    with _registry_lock:
        with _other_lock:                       # order: registry -> other
            return dict(_REGISTRY)


def inconsistent_order_b():
    with _other_lock:
        with _registry_lock:                    # ABBA: other -> registry
            _REGISTRY.clear()


def unguarded_global(key, value):
    _REGISTRY[key] = value                      # RACE: module lock exists


def guarded_global(key, value):
    with _registry_lock:
        _REGISTRY[key] = value                  # guarded: must NOT flag


_STEP = jax.jit(lambda c: c, donate_argnums=(0,))


def donate_caller_buffer(y0s):
    # the PR-8 corruption class: the caller's array is donated as-is —
    # on the CPU backend the donated output scribbles over its memory
    return _STEP(np.asarray(y0s))           # RACE: donated alias


def donate_owned_copy(y0s):
    carry = np.array(y0s, copy=True)
    return _STEP(carry)                         # owned: must NOT flag
