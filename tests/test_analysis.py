"""brlint (batchreactor_tpu.analysis) tests: every tier-A rule catches its
seeded-violation fixture, suppressions and the baseline round-trip, the
tier-B jaxpr audit flags seeded hazards, and — the contract that makes the
CI gate meaningful — the package itself scans clean.

Also the regression tests for the three ADVICE.md round-5 findings this PR
fixes (api.py jac_window/backend, ops/rhs.py BR_JAC_BARRIER freeze,
scripts/chip_session.py probe placement).
"""

import importlib.util
import json
import os
import pathlib
import sys
import textwrap

import pytest

from batchreactor_tpu.analysis import Baseline, lint_file, lint_paths
from batchreactor_tpu.analysis.cli import main as brlint_main

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "batchreactor_tpu"
FIXTURES = pathlib.Path(__file__).parent / "fixtures"


def _lint_snippet(tmp_path, code, name="snippet.py", select=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(code))
    findings, n_suppressed, _ = lint_file(str(f), select=select)
    return findings, n_suppressed


# --- tier A: one seeded violation per rule --------------------------------

def test_traced_control_flow_on_closure_param(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def make_bad_rhs(k):
            def rhs(t, y, cfg):
                if y[0] > 0.0:
                    return -k * y
                return y
            return rhs
        """)
    assert [f.rule for f in findings] == ["traced-control-flow"]
    assert findings[0].symbol.endswith("rhs")


def test_traced_control_flow_while_on_jnp_local(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def step(x):
            e = jnp.abs(x)
            while e > 1e-3:
                e = e * 0.5
            return e

        batched = jax.vmap(step)
        """)
    assert any(f.rule == "traced-control-flow" for f in findings)


def test_traced_control_flow_through_method_call(tmp_path):
    # taint must survive array-method idioms: y.sum() is a device value
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def make_norm_rhs(k):
            def rhs(t, y, cfg):
                m = y.sum()
                if m > 0:
                    return y / m
                return y
            return rhs
        """)
    assert any(f.rule == "traced-control-flow" for f in findings)


def test_tier_a_cli_needs_no_jax(tmp_path):
    """The wedged-accelerator contract: a tier-A scan must run on a host
    where importing jax fails outright (scripts/brlint.py loads the
    analysis subpackage through a namespace parent, skipping the heavy
    package __init__)."""
    import subprocess
    import sys as _sys

    (tmp_path / "jax.py").write_text("raise ImportError('jax blocked')\n")
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """))
    env = {**os.environ, "PYTHONPATH": str(tmp_path)}
    res = subprocess.run(
        [_sys.executable, str(REPO / "scripts" / "brlint.py"), str(bad)],
        env=env, capture_output=True, text=True, cwd=str(REPO))
    assert res.returncode == 1, res.stderr  # finding reported, no jax paid
    assert "implicit-dtype" in res.stdout


def test_public_api_registers_rules():
    """Importing only batchreactor_tpu.analysis (not .cli) must register
    the tier-A rules — otherwise lint_paths vacuously scans clean."""
    import subprocess
    import sys as _sys

    code = ("import batchreactor_tpu.analysis as a, sys; "
            "sys.exit(0 if len(a.all_rules()) >= 5 else 1)")
    res = subprocess.run([_sys.executable, "-c", code], cwd=str(REPO),
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0


def test_static_tests_not_flagged(tmp_path):
    # is-None / isinstance / shape math are trace-time static: the exact
    # idioms the real RHS factories use must never fire the rule
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def make_ok_rhs(gm, quirk):
            def rhs(t, y, cfg):
                n = y.shape[0]
                if gm is not None and n > 2:
                    y = y * 2.0
                if quirk:
                    y = y + 1.0
                return y
            return rhs
        """)
    assert findings == []


def test_static_argnums_params_exempt(tmp_path):
    # positionally declared statics are as exempt as static_argnames ones;
    # the traced params still flag
    findings, _ = _lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(0,))
        def run(mode, y):
            if mode == "fast":
                y = y * 2.0
            if y > 0:
                y = -y
            return y
        """)
    assert len(findings) == 1 and findings[0].rule == "traced-control-flow"
    assert findings[0].line == 9  # the `if y > 0`, not the mode test
    findings, _ = _lint_snippet(tmp_path, """
        import numpy as np

        def make_bad_jac(a):
            def jac(t, y, cfg):
                return float(y[0]) * np.asarray(y)
            return jac
        """)
    rules = [f.rule for f in findings]
    assert rules.count("host-sync-call") == 2  # float() and np.asarray()


def test_bucket_shape_branch(tmp_path):
    # the bucket-miss hazard: branching on .shape[0] of a traced value
    # is STATIC under trace (so traced-control-flow stays silent) but
    # forks one executable per batch size behind the aot bucket ladder
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def seg(y0s, cfg):
            if y0s.shape[0] > 256:
                return y0s * cfg
            return y0s + cfg

        sweep = jax.jit(seg)
        """)
    assert [f.rule for f in findings] == ["bucket-shape-branch"]
    assert findings[0].symbol.endswith("seg")


def test_bucket_shape_branch_silent_on_assignment(tmp_path):
    # shape *reads* (B = y.shape[0]) are the idiom the sweep drivers are
    # built from — only branching forks the program set
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def seg(y0s):
            B = y0s.shape[0]
            return y0s.reshape(B, -1)

        sweep = jax.jit(seg)
        """)
    assert not any(f.rule == "bucket-shape-branch" for f in findings)


def test_bucket_shape_branch_flags_aliased_dim(tmp_path):
    # the dominant spelling: read the dim into a local, branch on the
    # local — same fork, must flag the same
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def seg(y0s, cfg):
            B = y0s.shape[0]
            if B > 256:
                return y0s * cfg
            return y0s + cfg

        sweep = jax.jit(seg)
        """)
    assert [f.rule for f in findings] == ["bucket-shape-branch"]
    assert findings[0].symbol.endswith("seg")


def test_host_sync_item_method(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        def body(carry):
            return carry + carry.item()

        out = jax.lax.while_loop(lambda c: c < 3, body, 0)
        """)
    assert any(".item()" in f.message for f in findings)


def test_env_read_in_trace(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import os

        def make_fenced_rhs(sm):
            fence = os.environ.get("MY_TOGGLE") == "1"

            def rhs(t, y, cfg):
                return -y if fence else y
            return rhs
        """)
    assert any(f.rule == "env-read-in-trace" for f in findings)


def test_implicit_dtype(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def make_padded_rhs(n):
            def rhs(t, y, cfg):
                pad = jnp.zeros(3)
                one = jnp.asarray(1.0)
                ok = jnp.zeros(3, dtype=y.dtype)
                ok2 = jnp.asarray(y)
                return y + pad + one + ok + ok2
            return rhs
        """)
    assert [f.rule for f in findings] == ["implicit-dtype", "implicit-dtype"]


def test_recompile_hazard_static_list_and_local_jit(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def run(x, opts):
            return x

        def driver(x):
            f = jax.jit(lambda v: v + 1)
            return f(x) + run(x, opts=["a", "b"]) + run(x, f"mode={x.ndim}")
        """)
    rules = [f.rule for f in findings]
    assert rules.count("recompile-hazard") == 3  # local jit, list, f-string


# --- suppressions & baseline ---------------------------------------------

def test_suppression_silences_named_rule(tmp_path):
    code = """
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                pad = jnp.zeros(3)  # brlint: disable=implicit-dtype
                return y + pad
            return rhs
        """
    findings, n_suppressed = _lint_snippet(tmp_path, code)
    assert findings == [] and n_suppressed == 1


def test_suppression_is_rule_specific(tmp_path):
    code = """
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                pad = jnp.zeros(3)  # brlint: disable=host-sync-call
                return y + pad
            return rhs
        """
    findings, n_suppressed = _lint_snippet(tmp_path, code)
    assert [f.rule for f in findings] == ["implicit-dtype"]
    assert n_suppressed == 0


def test_suppression_in_string_literal_ignored(tmp_path):
    code = '''
        import jax.numpy as jnp

        NOTE = "# brlint: disable=implicit-dtype"

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        '''
    findings, _ = _lint_snippet(tmp_path, code)
    assert [f.rule for f in findings] == ["implicit-dtype"]


def test_baseline_roundtrip(tmp_path):
    f = tmp_path / "debt.py"
    f.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """))
    findings, _, sources = lint_paths([str(f)])
    assert len(findings) == 1
    bl = Baseline.from_findings(findings, sources)
    path = tmp_path / "baseline.json"
    bl.save(str(path))
    reloaded = Baseline.load(str(path))
    new, baselined, stale = reloaded.apply(findings, sources)
    assert new == [] and len(baselined) == 1 and stale == []
    # fix the debt -> the entry goes stale (reported so the file shrinks)
    new, baselined, stale = reloaded.apply([], sources)
    assert new == [] and baselined == [] and len(stale) == 1


def test_baseline_duplicate_lines_not_absorbed(tmp_path):
    """A NEW finding on a line textually identical to baselined debt must
    still fail: fingerprints carry an occurrence counter."""
    f = tmp_path / "debt.py"
    one = textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """)
    f.write_text(one)
    findings, _, sources = lint_paths([str(f)])
    bl = Baseline.from_findings(findings, sources)
    # duplicate the identical offending line
    f.write_text(one.replace("return y + jnp.zeros(3)",
                             "y = y + jnp.zeros(3)\n        "
                             "return y + jnp.zeros(3)"))
    findings2, _, sources2 = lint_paths([str(f)])
    assert len(findings2) == 2
    new, baselined, _ = bl.apply(findings2, sources2)
    assert len(baselined) == 1 and len(new) == 1


def test_cli_write_baseline_rejects_jaxpr(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    assert brlint_main([str(bad), "--jaxpr",
                        "--write-baseline", str(tmp_path / "b.json")]) == 2


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """))
    assert brlint_main([str(bad)]) == 1
    baseline = tmp_path / "bl.json"
    assert brlint_main([str(bad), "--write-baseline", str(baseline)]) == 0
    assert brlint_main([str(bad), "--baseline", str(baseline)]) == 0
    assert brlint_main([]) == 2
    assert brlint_main([str(bad), "--select", "no-such-rule"]) == 2


def test_cli_json_output(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """))
    assert brlint_main([str(bad), "--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert data["findings"][0]["rule"] == "implicit-dtype"


# --- the gate itself: the package scans clean ----------------------------

def test_package_scans_clean():
    findings, _, _ = lint_paths([str(PKG)])
    assert findings == [], "\n".join(f.render() for f in findings)


# --- tier B: jaxpr audit --------------------------------------------------

def test_jaxpr_audit_clean_on_fixtures():
    from batchreactor_tpu.analysis.jaxpr_audit import run_audit

    findings = run_audit(fixtures_dir=str(FIXTURES))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_jaxpr_audit_flags_callback_and_loop_transfer():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from batchreactor_tpu.analysis.jaxpr_audit import _audit_jaxpr

    table = np.arange(4.0)

    def with_callback(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape, x.dtype),
            x)

    jaxpr = jax.make_jaxpr(with_callback)(jnp.ones(3))
    found = _audit_jaxpr("cb", jaxpr, check_dtype=False)
    assert any(f.rule == "jaxpr-host-callback" for f in found)

    def with_loop_transfer(x):
        def body(i, acc):
            return acc + jnp.asarray(table)[i]  # np->device inside the loop

        return jax.lax.fori_loop(0, 4, body, x)

    jaxpr = jax.make_jaxpr(with_loop_transfer)(jnp.zeros(()))
    found = _audit_jaxpr("loop", jaxpr, check_dtype=False)
    assert any(f.rule == "jaxpr-device-transfer" for f in found)


def test_jaxpr_audit_flags_f32_leak():
    import jax
    import jax.numpy as jnp

    from batchreactor_tpu.analysis.jaxpr_audit import _audit_jaxpr

    def leaky(x):
        return (x.astype(jnp.float32) * 2.0).astype(jnp.float64)

    jaxpr = jax.make_jaxpr(leaky)(jnp.zeros((), dtype=jnp.float64))
    found = _audit_jaxpr("leak", jaxpr, check_dtype=True)
    assert any(f.rule == "jaxpr-dtype-leak" for f in found)


# --- ADVICE.md round-5 regression tests ----------------------------------

def test_jac_window_rejected_on_native_backend():
    """api.py:222 (ADVICE r5): an explicit jac_window with backend='cpu'
    must fail loudly, not be silently ignored."""
    from batchreactor_tpu import api

    with pytest.raises(ValueError, match="jac_window"):
        api._run_solve("cpu", "gas", None, None, None, None, None,
                       0.0, 1.0, {}, 1e-6, 1e-10, 0, 10, False, True,
                       jac_window=8)


def test_jac_barrier_frozen_at_import(monkeypatch):
    """ops/rhs.py:139 (ADVICE r5): BR_JAC_BARRIER semantics now match the
    docstring — frozen at module import, so a post-import env toggle does
    NOT change newly built closures; explicit fence_blocks=True does."""
    import jax

    from batchreactor_tpu.models.gas import compile_gaschemistry
    from batchreactor_tpu.models.surface import compile_mech
    from batchreactor_tpu.models.thermo import create_thermo
    from batchreactor_tpu.ops import rhs as rhs_mod

    gm = compile_gaschemistry(str(FIXTURES / "h2o2.dat"))
    th = create_thermo(list(gm.species), str(FIXTURES / "therm.dat"))
    sm = compile_mech(str(FIXTURES / "h2oni.xml"), th, list(gm.species))

    import jax.numpy as jnp
    import numpy as np

    y0 = jnp.concatenate([jnp.ones(len(th.species), dtype=jnp.float64),
                          jnp.asarray(sm.ini_covg, dtype=jnp.float64)])
    cfg = {"T": jnp.asarray(1100.0, dtype=jnp.float64),
           "Asv": jnp.asarray(1.0, dtype=jnp.float64)}

    def has_barrier(jacf):
        jaxpr = jax.make_jaxpr(jacf)(0.0, y0, cfg)
        return "optimization_barrier" in str(jaxpr)

    if rhs_mod._JAC_BARRIER_ENV:
        pytest.skip("BR_JAC_BARRIER was set when the module imported")
    # the env var was unset at import -> default stays off even if the
    # env is poked afterwards (the old per-call read would flip here)
    monkeypatch.setenv("BR_JAC_BARRIER", "1")
    assert rhs_mod._JAC_BARRIER_ENV is False
    assert not has_barrier(rhs_mod.make_surface_jac(sm, th))
    # explicit per-closure control still works
    assert has_barrier(rhs_mod.make_surface_jac(sm, th, fence_blocks=True))


def test_chip_session_probes_before_coupled(monkeypatch):
    """scripts/chip_session.py:139 (ADVICE r5): a wedge during smoke must
    be caught by a probe BEFORE the coupled compile starts, so it cannot
    be misattributed to the coupled step."""
    spec = importlib.util.spec_from_file_location(
        "chip_session", str(REPO / "scripts" / "chip_session.py"))
    cs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cs)

    events = []
    probe_results = iter([True, False])  # start probe ok; wedged after smoke

    def fake_run(cmd, timeout, extra_env=None, label=""):
        events.append(("run", label))
        return {"label": label, "rc": 0, "timed_out": False,
                "wall_s": 0.0, "tail": ""}

    monkeypatch.setattr(cs, "run", fake_run)
    monkeypatch.setattr(cs, "probe",
                        lambda: (events.append(("probe",)) or
                                 next(probe_results, True)))
    monkeypatch.setattr(cs, "OUT", str(
        pathlib.Path(os.environ.get("TMPDIR", "/tmp")) / "_cs_test.json"))
    monkeypatch.setenv("CS_STEPS", "smoke,coupled")

    rc = cs.main()
    assert rc == 1
    labels = [e[1] for e in events if e[0] == "run"]
    # the wedge was detected right after smoke: coupled never launched
    assert labels == ["tpu-smoke-tier"]


# --- tier C (a): the program-contract registry ----------------------------

class TestContracts:
    def test_registry_census(self):
        """Every traced-program family owns a contract registered at its
        definition site (the ISSUE-12 census); importing the owners
        populates the registry."""
        from batchreactor_tpu.analysis.contracts import (_import_owners,
                                                         all_contracts)

        _import_owners()
        names = set(all_contracts())
        expected = {
            "rhs-modes", "bdf-step", "bdf-step-economy", "bdf-step-lu32p",
            "sdirk-step", "sens-forward-step", "sens-adjoint-grad",
            "sweep-segment", "sweep-segment-bucket",
            "sweep-segment-resilience", "sweep-compact",
            "sweep-admission", "sweep-timeline"}
        assert expected <= names, expected - names

    def test_definition_site_registration(self):
        """Contracts live with the programs they pin, not in analysis/."""
        from batchreactor_tpu.analysis.contracts import (_import_owners,
                                                         all_contracts)

        _import_owners()
        contracts = all_contracts()
        assert contracts["bdf-step"].module.endswith("solver.bdf")
        assert contracts["sweep-segment"].module.endswith("parallel.sweep")
        assert contracts["rhs-modes"].module.endswith("ops.rhs")
        assert contracts["bdf-step-lu32p"].module.endswith(
            "solver.linalg_pallas")

    def test_completeness_passes_on_package(self):
        """Every armed single_program CompileWatch label in the source
        has a registered contract (the acceptance gate)."""
        from batchreactor_tpu.analysis.contracts import (
            _import_owners, armed_region_labels, completeness_findings)

        _import_owners()
        labels = armed_region_labels()
        # the two armed traced-program labels of the serving-era tree
        assert {"sweep-segment", "sweep-compact"} <= set(labels)
        assert completeness_findings() == []

    def test_completeness_catches_unregistered_label(self, tmp_path):
        """An armed single_program region whose label has no contract
        must fail the run — a new subsystem cannot land an armed traced
        program silently."""
        from batchreactor_tpu.analysis.contracts import (
            _import_owners, completeness_findings)

        _import_owners()
        mod = tmp_path / "newsub.py"
        mod.write_text(textwrap.dedent("""
            def run(watch, fn, x):
                with watch.region("new-frontier", single_program=True):
                    return fn(x)
            """))
        found = completeness_findings(root=str(tmp_path))
        missing = [f for f in found if f.rule == "contract-missing"]
        assert len(missing) == 1
        assert "new-frontier" in missing[0].message

    def test_identity_and_contains_obligations(self):
        """The engine's obligation checks fire (unit level, no solver
        tracing needed)."""
        import jax
        import jax.numpy as jnp

        from batchreactor_tpu.analysis.contracts import (
            Contains, Identical, _check_obligation)

        bad = _check_obligation(Identical("economy-noop-fork", "t",
                                          "jaxpr-a", "jaxpr-b", "forked"))
        assert [f.rule for f in bad] == ["economy-noop-fork"]
        assert _check_obligation(Identical("x", "t", "same", "same",
                                           "m")) == []
        jaxpr = jax.make_jaxpr(lambda x: x + 1.0)(jnp.zeros(()))
        bad = _check_obligation(Contains("kernel-missing", "t", jaxpr,
                                         "pallas", "no kernel"))
        assert [f.rule for f in bad] == ["kernel-missing"]

    def test_broken_contract_reports_not_crashes(self, monkeypatch):
        """One raising contract becomes a contract-error finding; the
        rest of the registry still runs."""
        from batchreactor_tpu.analysis import contracts as C

        def boom(h):
            raise RuntimeError("fixture exploded")
            yield  # pragma: no cover

        fake = {"boom-prog": C.ProgramContract(
            "boom-prog", boom, (), "", "test")}
        monkeypatch.setattr(C, "_REGISTRY", fake)
        monkeypatch.setattr(C, "_import_owners", lambda: None)
        monkeypatch.setattr(
            C, "Harness", lambda fixtures_dir=None: object())
        found = C.run_contracts(registry_audits=False)
        rules = [f.rule for f in found]
        assert "contract-error" in rules
        assert any("fixture exploded" in f.message for f in found)


# --- tier C (a): the repo-level registry audits ---------------------------

class TestFingerprintAudit:
    def test_clean_on_tree(self):
        from batchreactor_tpu.analysis.contracts import \
            fingerprint_registry_findings

        assert fingerprint_registry_findings() == []

    def test_exempting_timeline_fails(self, monkeypatch):
        """The PR-9 regression fixture: removing timeline's fingerprint
        pin (= adding it to the gear-exemption list) must fail the
        audit."""
        from batchreactor_tpu.analysis.contracts import \
            fingerprint_registry_findings
        from batchreactor_tpu.parallel import checkpoint as ck

        monkeypatch.setattr(ck, "_FP_EXEMPT_KEYS",
                            ck._FP_EXEMPT_KEYS + ("timeline",))
        found = fingerprint_registry_findings()
        assert any(f.rule == "fingerprint-registry"
                   and "timeline" in f.message for f in found)

    def test_schema_knobs_actually_pin(self):
        """Behavioral half: toggling each schema knob moves the hash;
        toggling each gear knob does not."""
        import numpy as np

        from batchreactor_tpu.parallel import checkpoint as ck

        def rhs(t, y, cfg):
            return -y

        y0s, cfgs = np.ones((2, 2)), {"k": np.ones((2,))}
        base = ck._sweep_fingerprint(rhs, y0s, cfgs, {})
        assert ck._sweep_fingerprint(rhs, y0s, cfgs,
                                     {"timeline": 8}) != base
        assert ck._sweep_fingerprint(rhs, y0s, cfgs,
                                     {"stats": True}) != base
        assert ck._sweep_fingerprint(rhs, y0s, cfgs,
                                     {"poll_every": 7}) == base
        assert ck._sweep_fingerprint(rhs, y0s, cfgs,
                                     {"admission": 4}) == base


class TestCounterAudit:
    def test_clean_on_tree(self):
        from batchreactor_tpu.analysis.contracts import \
            counter_registry_findings

        assert counter_registry_findings() == []

    def test_unregistered_family_fails(self, monkeypatch):
        """A future FOO_KEYS family that skips FAMILIES must fail the
        audit (the can't-silently-break-diffs satellite)."""
        from batchreactor_tpu.analysis.contracts import \
            counter_registry_findings
        from batchreactor_tpu.obs import counters as C

        monkeypatch.setattr(C, "FRONTIER_KEYS", ("frontier_events",),
                            raising=False)
        found = counter_registry_findings()
        assert any("FRONTIER_KEYS" in f.message for f in found)

    def test_host_family_must_declare_missing_zero(self, monkeypatch):
        from batchreactor_tpu.analysis.contracts import \
            counter_registry_findings
        from batchreactor_tpu.obs import counters as C

        fams = {k: dict(v) for k, v in C.FAMILIES.items()}
        fams["serve"]["missing_zero"] = False
        monkeypatch.setattr(C, "FAMILIES", fams)
        found = counter_registry_findings()
        assert any("serve" in f.message and "missing_zero" in f.message
                   for f in found)

    def test_diff_consumes_registry(self):
        """obs.diff's missing->0 coverage is derived from FAMILIES, so
        a registered family is enrolled by construction."""
        from batchreactor_tpu.obs import counters as C
        from batchreactor_tpu.obs import report as R

        for key in sorted(C.missing_zero_keys()):
            out = R.diff({"counters": {}}, {"counters": {key: 3}})
            assert f"counter {key}: 0 -> 3" in out


# --- tier C (b): the host-concurrency lint --------------------------------

RACY = FIXTURES / "racy_host.py"


class TestConcurrencyLint:
    def _findings(self):
        from batchreactor_tpu.analysis.concurrency import \
            lint_concurrency_file

        findings, _, _ = lint_concurrency_file(str(RACY))
        return findings

    def test_racy_fixture_catches_all_rule_classes(self):
        rules = {f.rule for f in self._findings()}
        assert rules == {"unguarded-shared-mutation",
                         "blocking-call-under-lock",
                         "locked-helper-outside-lock",
                         "lock-order-inversion",
                         "donation-aliasing"}

    def test_seeded_lines_flag_and_clean_twins_do_not(self):
        src = RACY.read_text().splitlines()
        findings = self._findings()
        flagged = {f.line for f in findings}
        # every seeded line carries a RACE/BLOCKING/ABBA/bare marker
        seeded = {i for i, ln in enumerate(src, 1)
                  if "# RACE" in ln or "# BLOCKING" in ln
                  or "# ABBA" in ln or "helper, bare" in ln}
        assert seeded <= flagged
        # the clean twins never flag
        clean = {i for i, ln in enumerate(src, 1) if "must NOT flag" in ln}
        assert not (clean & flagged)

    def test_donation_rule_blesses_owned_copy(self):
        findings = [f for f in self._findings()
                    if f.rule == "donation-aliasing"]
        assert len(findings) == 1
        assert findings[0].symbol == "donate_caller_buffer"

    def test_suppression_applies(self, tmp_path):
        from batchreactor_tpu.analysis.concurrency import \
            lint_concurrency_file

        code = textwrap.dedent("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self.n += 1  # brlint: disable=unguarded-shared-mutation
            """)
        f = tmp_path / "w.py"
        f.write_text(code)
        findings, n_suppressed, _ = lint_concurrency_file(str(f))
        assert findings == [] and n_suppressed == 1

    def test_threaded_host_modules_scan_clean(self):
        """THE acceptance gate: the serving-era threaded stack runs the
        concurrency lint clean (modulo justified suppressions)."""
        from batchreactor_tpu.analysis.concurrency import \
            lint_concurrency_paths

        findings, _, sources = lint_concurrency_paths()
        assert findings == [], "\n".join(f.render() for f in findings)
        scanned = {os.path.basename(p) for p in sources}
        assert {"scheduler.py", "session.py", "server.py", "live.py",
                "watchdog.py", "sweep.py"} <= scanned

    def test_declared_thread_entries_extend_reachability(self, tmp_path):
        """_BRLINT_THREAD_ENTRIES pulls cross-module entry points into
        the shared-state map (the scheduler.submit convention)."""
        from batchreactor_tpu.analysis.concurrency import \
            lint_concurrency_file

        code = textwrap.dedent("""
            import threading

            _BRLINT_THREAD_ENTRIES = ("Q.push",)

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def push(self, x):
                    self.items.append(x)
            """)
        f = tmp_path / "q.py"
        f.write_text(code)
        findings, _, _ = lint_concurrency_file(str(f))
        assert [f.rule for f in findings] == ["unguarded-shared-mutation"]
        # without the declaration the same class scans clean
        f.write_text(code.replace('_BRLINT_THREAD_ENTRIES = ("Q.push",)',
                                  ""))
        findings, _, _ = lint_concurrency_file(str(f))
        assert findings == []

    def test_cli_concurrency_flag(self, capsys):
        assert brlint_main(["--concurrency"]) == 0
        assert brlint_main([str(RACY.parent / "racy_host.py"),
                            "--concurrency"]) == 1

    def test_cli_list_rules_includes_concurrency(self, capsys):
        assert brlint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "donation-aliasing" in out
        assert "unguarded-shared-mutation" in out

    def test_donation_rule_catches_pr8_bare_param_shape(self, tmp_path):
        """The motivating regression: a bare caller parameter donated
        through a declared donating BUILDER inside a relaunch loop.
        The donating call's own result-rebind must NOT bless its
        operand retroactively (ownership is evaluated from bindings
        BEFORE the call site) — with the owned-copy line present the
        scan is clean, with it deleted the call site flags."""
        from batchreactor_tpu.analysis.concurrency import \
            lint_concurrency_file

        template = textwrap.dedent("""
            import jax
            import jax.numpy as jnp

            _BRLINT_DONATING_BUILDERS = {{"_cached_builder": (1,)}}

            def drive(cfgs, carry):
                jitted = _cached_builder(cfgs)
            {bless}    for _seg in range(4):
                    carry, aux = jitted(cfgs, carry)
                return carry
            """)
        bless = ("    carry = (jnp.array(carry[0], copy=True),)"
                 " + tuple(carry[1:])\n")
        f = tmp_path / "drive.py"
        f.write_text(template.format(bless=bless))
        findings, _, _ = lint_concurrency_file(str(f))
        assert findings == [], "\n".join(x.render() for x in findings)
        f.write_text(template.format(bless=""))
        findings, _, _ = lint_concurrency_file(str(f))
        assert [x.rule for x in findings] == ["donation-aliasing"]
        assert "'carry'" in findings[0].message

    def test_sweep_declares_its_donating_builder(self):
        """parallel/sweep.py must keep the _BRLINT_DONATING_BUILDERS
        declaration for its cached donating segment-program builder —
        without it the drivers' donated-carry call sites are invisible
        to the donation rule."""
        from batchreactor_tpu.parallel import sweep

        assert sweep._BRLINT_DONATING_BUILDERS == {
            "_cached_vsolve_segmented_ctrl": (4,)}


# --- env-var-unregistered: the ENV_KNOBS registry rule --------------------

class TestEnvKnobRule:
    """Every os.environ read must name a registered ENV_KNOBS knob with
    an honest read-time class (docs/development.md tier-A catalogue)."""

    def test_unregistered_literal_name_flags(self, tmp_path):
        findings, _ = _lint_snippet(tmp_path, """
            import os

            def f():
                return os.environ.get("BR_NO_SUCH_KNOB", "0")
            """, select={"env-var-unregistered"})
        assert [f.rule for f in findings] == ["env-var-unregistered"]
        assert "BR_NO_SUCH_KNOB" in findings[0].message

    def test_import_class_knob_read_in_function_flags(self, tmp_path):
        # BR_JAC_BARRIER is registered read="import" (frozen at module
        # import, ops/rhs.py); a per-call read makes the operator docs lie
        findings, _ = _lint_snippet(tmp_path, """
            import os

            def f():
                return os.getenv("BR_JAC_BARRIER")
            """, select={"env-var-unregistered"})
        assert [f.rule for f in findings] == ["env-var-unregistered"]
        assert "import" in findings[0].message

    def test_non_literal_name_flags(self, tmp_path):
        findings, _ = _lint_snippet(tmp_path, """
            import os

            def f(name):
                return os.environ.get(name)
            """, select={"env-var-unregistered"})
        assert [f.rule for f in findings] == ["env-var-unregistered"]

    def test_registered_call_class_read_is_clean(self, tmp_path):
        # BR_EXP32 is registered read="call"; membership tests and
        # env WRITES are out of scope either way
        findings, _ = _lint_snippet(tmp_path, """
            import os

            def f():
                os.environ["ANY_NAME_AT_ALL"] = "1"
                if "BR_EXP32" in os.environ:
                    return os.environ.get("BR_EXP32")
            """, select={"env-var-unregistered"})
        assert findings == []

    def test_registry_is_well_formed(self):
        from batchreactor_tpu.envknobs import ENV_KNOBS

        assert len(ENV_KNOBS) >= 50
        for name, knob in ENV_KNOBS.items():
            assert knob.name == name
            assert knob.read in ("import", "call")
            assert knob.owner
        # the package knobs the rule's import-class check keys off
        assert ENV_KNOBS["BR_JAC_BARRIER"].read == "import"
        assert ENV_KNOBS["BR_EXP32"].read == "call"


# --- brlint CLI: tier D surface and the exit-code contract ----------------

def test_cli_list_rules_includes_budget_rules(capsys):
    assert brlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("budget-flops", "budget-peak-bytes", "budget-vmem",
                 "budget-unbound", "env-var-unregistered"):
        assert rule in out, rule


def test_cli_json_exit_code_contract_subprocess(tmp_path):
    """The documented scripts/brlint.py exit-code contract, end to end
    through the real shim: findings -> 1, clean -> 0, with --json the
    same as without (the CI gates trust ONLY the exit code)."""
    import subprocess

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def make_r(n):
            def rhs(t, y, cfg):
                return y + jnp.zeros(3)
            return rhs
        """))
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    script = str(REPO / "scripts" / "brlint.py")
    r = subprocess.run([sys.executable, script, str(bad), "--json"],
                       capture_output=True, text=True)
    assert r.returncode == 1, r.stderr
    assert json.loads(r.stdout)["findings"], "exit 1 must carry findings"
    r = subprocess.run([sys.executable, script, str(clean), "--json"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["findings"] == []
    r = subprocess.run([sys.executable, script, "--json"],
                       capture_output=True, text=True)
    assert r.returncode == 2, "no work must be a usage error, not clean"
