"""AOT program store: bucket ladder, padded-sweep bit-exactness, the
zero-recompile cache contract, warmup + manifest accounting, and the
checkpoint fingerprint coupling (batchreactor_tpu/aot,
docs/performance.md "Compile economy").

Everything runs tiny 2-species decay ODEs — compile cost, not solve
cost, is what these tests exercise, and the tier-1 budget cannot afford
GRI-scale programs.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu import aot
from batchreactor_tpu.aot.buckets import (bucket_ladder, normalize_buckets,
                                          resolve_bucket)
from batchreactor_tpu.obs import CompileWatch
from batchreactor_tpu.parallel import (ensemble_solve,
                                       ensemble_solve_segmented,
                                       pad_to_bucket)
from batchreactor_tpu.parallel.sweep import unpad_result
from batchreactor_tpu.solver.sdirk import (MAX_STEPS_REACHED, RUNNING,
                                           SUCCESS)


@pytest.fixture
def managed_cache(tmp_path):
    """A per-test managed persistent-cache dir, with the process-global
    jax cache config (and the latched cache handle) restored after."""
    old = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    old_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    cache = str(tmp_path / "cache")
    yield cache
    jax.config.update("jax_compilation_cache_dir", old)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", old_size)
    aot.reset_persistent_cache()


def _decay_rhs(t, y, cfg):
    """Module-level (stable identity: the sweep compile caches key on the
    callable) stiff per-lane decay; k spread finishes lanes in different
    segments."""
    return -cfg["k"] * y


def _setup(B):
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    return y0s, {"k": jnp.logspace(1.0, 2.5, B)}


def _fields(res):
    out = {f: np.asarray(getattr(res, f))
           for f in ("t", "y", "status", "n_accepted", "n_rejected",
                     "ts", "ys", "n_saved", "h")}
    if res.observed is not None:
        for k, v in res.observed.items():
            out[f"obs_{k}"] = np.asarray(v)
    if res.stats is not None:
        for k, v in res.stats.items():
            out[f"stat_{k}"] = np.asarray(v)
    return out


def _assert_bit_exact(a, b, ctx=""):
    fa, fb = _fields(a), _fields(b)
    assert fa.keys() == fb.keys(), (ctx, fa.keys(), fb.keys())
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k],
                                      err_msg=f"{ctx} field {k}")


# --------------------------------------------------------------------------
# ladder arithmetic (no jax work)
# --------------------------------------------------------------------------
def test_normalize_buckets_grammar():
    assert normalize_buckets(None) is None
    assert normalize_buckets(False) is None
    assert normalize_buckets("pow2") == "pow2"
    assert normalize_buckets([64, 256]) == (64, 256)
    for bad in ("pow3", 64, 3.5, True, [], [0], [2.0], [64, 64],
                [256, 64]):
        with pytest.raises(ValueError):
            normalize_buckets(bad)


def test_resolve_bucket():
    assert resolve_bucket(1, "pow2") == 1
    assert resolve_bucket(3, "pow2") == 4
    assert resolve_bucket(4, "pow2") == 4
    assert resolve_bucket(4097, "pow2") == 8192
    assert resolve_bucket(7, (8, 64)) == 8
    assert resolve_bucket(9, (8, 64)) == 64
    assert resolve_bucket(5, None) == 5          # bucketing off
    # explicit ladder is a promise: exceeding it is loud
    with pytest.raises(ValueError, match="exceeds the top bucket"):
        resolve_bucket(65, (8, 64))
    # mesh divisibility
    assert resolve_bucket(3, "pow2", mesh_size=8) == 8
    with pytest.raises(ValueError, match="does not divide evenly"):
        resolve_bucket(5, (6, 12), mesh_size=8)
    # a non-power-of-two mesh can never divide a pow2 bucket: loud error,
    # not an infinite doubling loop (regression)
    with pytest.raises(ValueError, match="cannot cover a 6-device mesh"):
        resolve_bucket(3, "pow2", mesh_size=6)
    assert bucket_ladder([3, 5, 9], "pow2") == (4, 8, 16)


def test_pad_to_bucket_roundtrip():
    y0s, cfgs = _setup(3)
    yp, cp, B = pad_to_bucket(y0s, cfgs, 8)
    assert B == 3 and yp.shape == (8, 2) and cp["k"].shape == (8,)
    np.testing.assert_array_equal(np.asarray(yp[:3]), np.asarray(y0s))
    np.testing.assert_array_equal(np.asarray(yp[3:]),
                                  np.broadcast_to(np.asarray(y0s[-1]),
                                                  (5, 2)))
    with pytest.raises(ValueError, match="bucket 2 < lane count"):
        pad_to_bucket(y0s, cfgs, 2)
    # unpad is the exact inverse on the lane axis
    res = ensemble_solve(_decay_rhs, yp, 0.0, 1.0, cp, max_steps=5000)
    assert unpad_result(res, 3).y.shape == (3, 2)


# --------------------------------------------------------------------------
# masked dead lanes never affect live-lane results (the tentpole
# bit-exactness claim: asserted, not assumed)
# --------------------------------------------------------------------------
@pytest.mark.parametrize("method", ["bdf", "sdirk"])
@pytest.mark.parametrize("n_save", [0, 24])
def test_padded_bit_exact_segmented(method, n_save):
    y0s, cfgs = _setup(3)
    kw = dict(segment_steps=16, max_segments=64, n_save=n_save,
              method=method, stats=True)
    plain = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs, **kw)
    padded = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                      buckets="pow2", **kw)
    assert np.all(np.asarray(plain.status) == SUCCESS)
    _assert_bit_exact(plain, padded, f"{method}/n_save={n_save}")


@pytest.mark.parametrize("pipeline", [False, True])
def test_padded_bit_exact_budget_parking(pipeline):
    """The exact max_attempts budget parks the SAME lanes at the same t
    and counts with dead lanes along for the ride — across both
    execution gears."""
    y0s, cfgs = _setup(3)
    kw = dict(segment_steps=16, max_segments=64, max_attempts=120,
              stats=True, pipeline=pipeline)
    plain = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs, **kw)
    status = np.asarray(plain.status)
    assert np.any(status == MAX_STEPS_REACHED) and np.any(status == SUCCESS)
    padded = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                      buckets="pow2", **kw)
    _assert_bit_exact(plain, padded, f"budget/pipeline={pipeline}")


def test_padded_bit_exact_monolithic():
    y0s, cfgs = _setup(5)
    a = ensemble_solve(_decay_rhs, y0s, 0.0, 1.0, cfgs, max_steps=5000,
                       stats=True)
    b = ensemble_solve(_decay_rhs, y0s, 0.0, 1.0, cfgs, max_steps=5000,
                       stats=True, buckets="pow2")
    _assert_bit_exact(a, b, "monolithic")
    assert b.y.shape == (5, 2)  # dead lanes stripped


# --------------------------------------------------------------------------
# the zero-recompile contract
# --------------------------------------------------------------------------
def test_second_B_in_bucket_compiles_nothing():
    """The cache-hit regression gate: after one sweep at any B in a
    bucket, a sweep at a DIFFERENT B in the same bucket runs zero new
    compiles of the sweep program (the padded shapes are identical, so
    the jit dispatch cache serves the executable outright)."""
    y0s5, cfgs5 = _setup(5)
    ensemble_solve_segmented(_decay_rhs, y0s5, 0.0, 1.0, cfgs5,
                             segment_steps=16, max_segments=64,
                             buckets="pow2")
    y0s7, cfgs7 = _setup(7)
    watch = CompileWatch()
    with watch:
        res = ensemble_solve_segmented(_decay_rhs, y0s7, 0.0, 1.0, cfgs7,
                                       segment_steps=16, max_segments=64,
                                       buckets="pow2", watch=watch)
    assert res.y.shape == (7, 2)
    seg = watch.summary()["by_label"].get("sweep-segment", {})
    assert seg.get("compiles", 0) == 0, seg
    assert watch.retraces == 0


def test_bucket_change_is_expected_compile_not_retrace(cold_compile_cache):
    """A single_program label keyed per bucket treats a bucket change as
    the expected first compile of a new canonical program; a second
    compile INSIDE one bucket still flags."""

    def f(x):
        return (x * 2.0).sum()

    jf = jax.jit(f)
    watch = CompileWatch()
    x4, x8, x16 = (jnp.ones((n,)) for n in (4, 8, 16))
    with watch:
        with watch.region("sweep", single_program=True, program_key="b4"):
            jf(x4)
        with watch.region("sweep", single_program=True, program_key="b8"):
            jf(x8)                      # bucket change: expected
        s1 = watch.summary()
        with watch.region("sweep", single_program=True, program_key="b8"):
            jf(x16)                     # same key, new shape: retrace
        s2 = watch.summary()
    assert s1["by_label"]["sweep"]["compiles"] == 2
    assert s1["retraces"] == 0
    assert s2["by_label"]["sweep"]["programs"] == {"b4": 1, "b8": 2}
    assert s2["retraces"] == 1


def test_persistent_cache_hit_not_counted_as_compile(managed_cache):
    """A persistent-cache-served program counts under cache_hits (with
    its deserialize wall in cache_load_s), NOT compiles — the schema the
    'compiles: N -> 0' evidence format rests on."""
    aot.configure_cache(managed_cache)

    def make_g():
        # a FRESH function object per call (jit caches key on callable
        # identity) whose traced program is nonetheless byte-identical —
        # the in-process model of a new process hitting the persistent
        # cache
        def g(x):
            return jnp.cumsum(x * 3.0)

        return g

    x = jnp.ones((13,))
    w1 = CompileWatch()
    with w1:
        jax.jit(make_g())(x)            # cold: true compile, cache miss
    w2 = CompileWatch()
    with w2:
        jax.jit(make_g())(x)
    s1, s2 = w1.summary(), w2.summary()
    assert s1["compiles"] >= 1 and s1["cache_misses"] >= 1
    assert s2["compiles"] == 0, s2
    assert s2["cache_hits"] >= 1
    lbl = s2["by_label"]["program"]
    assert lbl["cache_load_s"] > 0.0


def test_cache_served_build_still_arms_retrace_detection(managed_cache):
    """A persistent-cache-served build registers under its program key
    like a true compile: a later rebuild of the same armed key flags as
    a retrace even though the first build never counted as a compile
    (else a warmed session — exactly the AOT store's target state —
    would silently disable retrace detection)."""
    aot.configure_cache(managed_cache)

    def make_g():
        def g(x):
            return jnp.sort(x * 5.0)

        return g

    # inputs built OUTSIDE the regions: array creation can itself
    # compile tiny eager-op programs that must not attribute to the key
    x11, x12 = jnp.ones((11,)), jnp.ones((12,))
    jax.block_until_ready((x11, x12))
    jax.jit(make_g())(x11)              # populate the persistent cache
    watch = CompileWatch()
    with watch:
        with watch.region("sweep", single_program=True, program_key="b16"):
            jax.jit(make_g())(x11)      # cache-served first build
        s1 = watch.summary()
        with watch.region("sweep", single_program=True, program_key="b16"):
            jax.jit(make_g())(x12)      # true rebuild, same key
    s2 = watch.summary()
    assert s1["compiles"] == 0 and s1["cache_hits"] >= 1
    assert s1["retraces"] == 0
    assert s2["retraces"] == 1
    assert s2["by_label"]["sweep"]["programs"] == {"b16": 2}


def test_warmup_manifest_and_zero_compile_sweep(managed_cache):
    """warmup() compiles each canonical bucket program once through the
    real drivers, writes the manifest, and a later sweep at any B inside
    a warmed bucket compiles nothing; a second warmup reports warm."""
    spec = dict(rhs=_decay_rhs, y0=jnp.asarray([1.0, 0.5]),
                cfg={"k": 10.0}, lanes=[3, 9], buckets="pow2",
                segment_steps=16)
    results = aot.warmup([spec], cache_dir=managed_cache)
    assert [r.bucket for r in results] == [4, 16]
    man = aot.load_manifest(managed_cache)
    assert set(man["entries"]) == {r.key for r in results}
    assert all(e["warmups"] == 1 for e in man["entries"].values())
    assert os.path.exists(aot.manifest_path(managed_cache))
    json.load(open(aot.manifest_path(managed_cache)))  # valid json on disk

    # any B inside a warmed bucket: zero compiles of the sweep program
    y0s, cfgs = _setup(9)
    watch = CompileWatch()
    with watch:
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 segment_steps=16, max_segments=64,
                                 buckets="pow2", watch=watch)
    seg = watch.summary()["by_label"].get("sweep-segment", {})
    assert seg.get("compiles", 0) == 0, seg

    # re-warm: everything already in the dispatch cache
    again = aot.warmup([spec], cache_dir=managed_cache)
    assert all(r.warm and r.compiles == 0 for r in again), again
    man = aot.load_manifest(managed_cache)
    assert all(e["warmups"] == 2 for e in man["entries"].values())

    # an EXPLICIT buckets=None warms the exact lane-count shape (the
    # bucketing-off session), not a silently-coerced pow2 bucket
    exact = aot.warmup([dict(spec, lanes=[3], buckets=None)],
                       cache_dir=managed_cache)
    assert [r.bucket for r in exact] == [3]


def test_host_sync_gate_holds_on_padded_programs(monkeypatch):
    """The PR-4 pipelining regression gate composes with bucketing: a
    padded sweep performs at most ceil(segments/poll_every) + 1
    main-thread blocking fetches."""
    import batchreactor_tpu.parallel.sweep as sweep_mod

    y0s, cfgs = _setup(5)
    kw = dict(segment_steps=16, max_segments=64, n_save=64, stats=True,
              buckets="pow2")
    segs = []
    ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             pipeline=False,
                             progress=lambda p: segs.append(p), **kw)
    n_segments = len(segs)
    assert n_segments >= 3
    assert all(p["n_lanes"] == 8 for p in segs)  # padded shape reported

    calls = []
    orig = sweep_mod._host_fetch
    monkeypatch.setattr(
        sweep_mod, "_host_fetch",
        lambda x, recorder=None: (calls.append(1), orig(x, recorder))[1])
    sweep_mod.ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, pipeline=True, poll_every=4, **kw)
    assert len(calls) <= -(-n_segments // 4) + 1, (len(calls), n_segments)


# --------------------------------------------------------------------------
# api plumbing + validation
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def h2o2(fixtures_dir):
    import batchreactor_tpu as br

    gm = br.compile_gaschemistry(os.path.join(fixtures_dir, "h2o2.dat"))
    th = br.create_thermo(list(gm.species),
                          os.path.join(fixtures_dir, "therm.dat"))
    return gm, th


def test_api_bucket_validation(h2o2):
    import batchreactor_tpu as br

    gm, th = h2o2
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm)
    comp = {"H2": 0.3, "O2": 0.2, "N2": 0.5}
    with pytest.raises(ValueError, match="buckets must be"):
        br.batch_reactor_sweep(comp, np.linspace(1050, 1150, 4), 1e5,
                               1e-6, buckets="pow3", **kw)
    with pytest.raises(ValueError, match="strictly increasing"):
        br.batch_reactor_sweep(comp, np.linspace(1050, 1150, 4), 1e5,
                               1e-6, buckets=[8, 4], **kw)
    # an explicit ladder that cannot cover B fails BEFORE any compile
    with pytest.raises(ValueError, match="exceeds the top bucket"):
        br.batch_reactor_sweep(comp, np.linspace(1050, 1150, 6), 1e5,
                               1e-6, buckets=(2, 4), **kw)


def test_api_bucketed_sweep_matches_unbucketed(h2o2):
    import batchreactor_tpu as br

    gm, th = h2o2
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
              segment_steps=16, ignition_marker="H2")
    comp = {"H2": 0.3, "O2": 0.2, "N2": 0.5}
    T = np.linspace(1050, 1150, 5)
    # telemetry on BOTH: stats=True threads the counter block through the
    # traced program, so only same-instrumentation runs are comparable
    # bit-for-bit (the padding itself is the variable under test)
    plain = br.batch_reactor_sweep(comp, T, 1e5, 1e-5, telemetry=True,
                                   **kw)
    padded = br.batch_reactor_sweep(comp, T, 1e5, 1e-5, buckets="pow2",
                                    telemetry=True, **kw)
    assert padded["telemetry"]["meta"]["bucket"] == 8
    assert padded["t"].shape == (5,)
    np.testing.assert_array_equal(plain["status"], padded["status"])
    np.testing.assert_array_equal(plain["t"], padded["t"])
    # real-mechanism kernels: XLA's batch-size-dependent vectorization
    # introduces <=2 ulp spread on y (measured 8e-16 relative on this
    # workload) — the same order as the documented lane-position
    # sensitivity (checkpoint.py lane_cost), ~1e10 x below rtol.  The
    # strict bit-exactness contract is asserted on the linear-ODE
    # matrix above, where no such re-tiling occurs.
    np.testing.assert_allclose(plain["tau"], padded["tau"], rtol=1e-12)
    for sp in plain["x"]:
        np.testing.assert_allclose(plain["x"][sp], padded["x"][sp],
                                   rtol=1e-12)
    assert padded["report"]["n_lanes"] == 5  # dead lanes stripped
    # per-lane telemetry arrays are stripped to live lanes too
    per_lane = padded["telemetry"]["solver_stats"]["per_lane"]
    assert len(per_lane["newton_iters"]) == 5


# --------------------------------------------------------------------------
# checkpoint coupling
# --------------------------------------------------------------------------
def test_checkpoint_bucketed_resume_bit_exact(tmp_path):
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _setup(6)
    kw = dict(segment_steps=16, max_steps=2000, n_save=64)
    plain = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                               str(tmp_path / "plain"), chunk_size=3, **kw)
    bucketed = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                  str(tmp_path / "buck"), chunk_size=3,
                                  buckets="pow2", **kw)
    _assert_bit_exact(plain, bucketed, "checkpointed")
    # resume: drop a chunk, re-solve through the padded program only
    os.remove(str(tmp_path / "buck" / "chunk_00001.npz"))
    resumed = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 str(tmp_path / "buck"), chunk_size=3,
                                 buckets="pow2", **kw)
    _assert_bit_exact(plain, resumed, "checkpointed-resume")


def test_checkpoint_fingerprint_includes_bucket(tmp_path):
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s, cfgs = _setup(4)
    kw = dict(segment_steps=16, max_steps=2000)
    d = str(tmp_path / "ck")
    checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs, d, chunk_size=2,
                       buckets="pow2", **kw)
    # same ladder, different spelling of the same canonical form: resumes
    checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs, d, chunk_size=2,
                       buckets="pow2", **kw)
    # a different ladder is a different sweep: loud mismatch
    with pytest.raises(ValueError, match="different sweep"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs, d,
                           chunk_size=2, buckets=(4, 8), **kw)
    # buckets=None fingerprints identically to the knob being absent
    # (pre-bucketing checkpoint dirs stay resumable)
    d2 = str(tmp_path / "legacy")
    checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs, d2, chunk_size=2,
                       **kw)
    checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs, d2, chunk_size=2,
                       buckets=None, **kw)
    man = json.load(open(os.path.join(d2, "manifest.json")))
    assert man["fingerprint"]
