"""Mechanism-shape padding + mechanism-as-operand programs
(models/padding.py, api.py ``species_buckets``/``reaction_buckets``/
``mech_operands`` — docs/performance.md "Mechanism-shape economy").

The inertness contract under test:

* dead species/reactions contribute EXACT zeros to production rates and
  to the Jacobian's dead rows AND columns;
* solver step counts, rejection counts, and order histograms are
  IDENTICAL padded vs unpadded (the ``_nlive`` norm operand restores the
  live-count denominator — padding must not perturb error control);
* live final states match the dedicated-shape run to quasi-Newton
  roundoff (XLA reassociates reductions across tensor shapes, so
  Newton-converged states carry a documented few-ulp caveat — the PR-8
  down-shift precedent; production rates themselves are bit-exact);
* in operand mode, two mechanisms padded onto one (S, R) rung run ONE
  compiled executable: the second mechanism's armed ``sweep-segment``
  label records ZERO compiles (the PERF.md round-11 evidence).
"""

import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.padding import (NLIVE_KEY, mech_shape_class,
                                             nlive_cfg, pad_gas_mechanism,
                                             pad_states, pad_thermo)
from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
from batchreactor_tpu.parallel.grid import sweep_solution_vectors
from batchreactor_tpu.parallel.sweep import (ensemble_solve,
                                             ensemble_solve_segmented)

import jax.numpy as jnp

FIX = __file__.rsplit("/", 1)[0] + "/fixtures"
S_PAD, R_PAD = 16, 32


@pytest.fixture(scope="module")
def mech():
    gm = br.compile_gaschemistry(f"{FIX}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{FIX}/therm.dat")
    return gm, th


@pytest.fixture(scope="module")
def mech_n():
    gm = br.compile_gaschemistry(f"{FIX}/h2o2_n.dat")
    th = br.create_thermo(list(gm.species), f"{FIX}/therm.dat")
    return gm, th


def _lanes(gm, th, B=3):
    S = gm.n_species
    X = np.zeros((B, S))
    idx = {s: k for k, s in enumerate(gm.species)}
    X[:, idx["H2"]], X[:, idx["O2"]], X[:, idx["N2"]] = 0.3, 0.15, 0.55
    T = jnp.asarray(np.linspace(1150.0, 1500.0, B))
    y0 = sweep_solution_vectors(jnp.asarray(X), th.molwt, T, 1e5)
    return y0, {"T": T, "Asv": jnp.ones(B)}


# --------------------------------------------------------------------------
# the padding layer itself
# --------------------------------------------------------------------------
def test_padding_validation(mech):
    gm, th = mech
    with pytest.raises(ValueError, match="cannot shrink"):
        pad_gas_mechanism(gm, gm.n_species - 1, R_PAD)
    with pytest.raises(ValueError, match="cannot shrink"):
        pad_thermo(th, th.n_species - 1)
    with pytest.raises(ValueError, match="cannot shrink"):
        pad_states(jnp.zeros((2, 5)), 3)


def test_rhs_and_jac_inertness(mech):
    """Dead species: zero rates, zero Jacobian rows AND columns; live
    block bit-exact (eager — no reduction-shape reassociation here for
    the rates; the Jacobian contraction carries the documented ulp
    caveat, so the dead-block zeros are the hard assertion)."""
    gm, th = mech
    S = gm.n_species
    gmp = pad_gas_mechanism(gm, S_PAD, R_PAD)
    thp = pad_thermo(th, S_PAD)
    y0, _ = _lanes(gm, th, 1)
    cfg = {"T": 1300.0, "Asv": 1.0}
    dy = make_gas_rhs(gm, th)(0.0, y0[0], cfg)
    dyp = make_gas_rhs(gmp, thp)(0.0, pad_states(y0, S_PAD)[0], cfg)
    assert np.array_equal(np.asarray(dy), np.asarray(dyp)[:S])
    assert np.all(np.asarray(dyp)[S:] == 0.0)
    Jp = make_gas_jac(gmp, thp)(0.0, pad_states(y0, S_PAD)[0], cfg)
    Jp = np.asarray(Jp)
    assert np.all(Jp[S:, :] == 0.0), "dead Jacobian rows must be zero"
    assert np.all(Jp[:, S:] == 0.0), "dead Jacobian columns must be zero"


def test_identity_padding_is_value_transparent(mech):
    gm, th = mech
    gmi = pad_gas_mechanism(gm, gm.n_species, gm.n_reactions)
    thi = pad_thermo(th, th.n_species)
    for name in ("nu_f", "log_A", "eff", "troe", "plog_lnp"):
        assert np.array_equal(np.asarray(getattr(gm, name)),
                              np.asarray(getattr(gmi, name)))
    assert gmi.species == gm.species and gmi.equations == gm.equations
    assert np.array_equal(np.asarray(th.molwt), np.asarray(thi.molwt))


def test_shape_class_and_canonical_meta(mech, mech_n):
    gm, th = mech
    gm2, th2 = mech_n
    a = pad_gas_mechanism(gm, S_PAD, R_PAD, canonical=True)
    b = pad_gas_mechanism(gm2, S_PAD, R_PAD, canonical=True)
    assert mech_shape_class(a) == mech_shape_class(b)
    assert a.species == b.species and a.equations == b.equations
    ta = pad_thermo(th, S_PAD, canonical=True)
    tb = pad_thermo(th2, S_PAD, canonical=True)
    assert ta.species == tb.species and ta.composition == tb.composition
    # non-canonical padding keeps the live names (closure-mode reports)
    nc = pad_gas_mechanism(gm, S_PAD, R_PAD)
    assert nc.species[: gm.n_species] == gm.species


# --------------------------------------------------------------------------
# dead species provably inert: step control blind to the padding
# --------------------------------------------------------------------------
@pytest.mark.parametrize("method", ["bdf", "sdirk"])
def test_step_counts_and_order_hist_identical(mech, method):
    gm, th = mech
    S = gm.n_species
    gmp, thp = pad_gas_mechanism(gm, S_PAD, R_PAD), pad_thermo(th, S_PAD)
    y0, cfg = _lanes(gm, th)
    kw = dict(method=method, stats=True, max_steps=20_000)
    a = ensemble_solve(make_gas_rhs(gm, th), y0, 0.0, 5e-5, cfg,
                       jac=make_gas_jac(gm, th), **kw)
    b = ensemble_solve(make_gas_rhs(gmp, thp), pad_states(y0, S_PAD),
                       0.0, 5e-5, nlive_cfg(cfg, S, y0.shape[0]),
                       jac=make_gas_jac(gmp, thp), **kw)
    assert np.array_equal(np.asarray(a.status), np.asarray(b.status))
    assert np.array_equal(np.asarray(a.n_accepted),
                          np.asarray(b.n_accepted))
    assert np.array_equal(np.asarray(a.n_rejected),
                          np.asarray(b.n_rejected))
    assert np.array_equal(np.asarray(a.t), np.asarray(b.t))
    if method == "bdf":
        assert np.array_equal(np.asarray(a.stats["order_hist"]),
                              np.asarray(b.stats["order_hist"]))
    # dead species hold exactly zero through the whole solve
    assert np.all(np.asarray(b.y)[:, S:] == 0.0)
    # live states: quasi-Newton roundoff caveat (module doc)
    ref = np.asarray(a.y)
    assert np.allclose(ref, np.asarray(b.y)[:, :S], rtol=1e-10,
                       atol=1e-22)


def test_segmented_and_admission_padded(mech):
    """The segmented matrix leg: step counts identical and states at
    roundoff through the pipelined driver and continuous batching."""
    gm, th = mech
    S = gm.n_species
    gmp, thp = pad_gas_mechanism(gm, S_PAD, R_PAD), pad_thermo(th, S_PAD)
    y0, cfg = _lanes(gm, th, 5)
    kw = dict(segment_steps=32, max_segments=10_000, stats=True)
    a = ensemble_solve_segmented(make_gas_rhs(gm, th), y0, 0.0, 5e-5,
                                 cfg, jac=make_gas_jac(gm, th), **kw)
    for extra in ({}, {"admission": 2, "refill": 1}):
        b = ensemble_solve_segmented(
            make_gas_rhs(gmp, thp), pad_states(y0, S_PAD), 0.0, 5e-5,
            nlive_cfg(cfg, S, 5), jac=make_gas_jac(gmp, thp), **kw,
            **extra)
        assert np.array_equal(np.asarray(a.status), np.asarray(b.status))
        assert np.array_equal(np.asarray(a.n_accepted),
                              np.asarray(b.n_accepted)), extra
        assert np.allclose(np.asarray(a.y), np.asarray(b.y)[:, :S],
                           rtol=1e-10, atol=1e-22), extra
        assert np.all(np.asarray(b.y)[:, S:] == 0.0)


# --------------------------------------------------------------------------
# the api entry point
# --------------------------------------------------------------------------
def test_sweep_api_padded_strips_live_species(mech):
    gm, th = mech
    chem = br.Chemistry(gaschem=True)
    comp = {"H2": 0.3, "O2": 0.15, "N2": 0.55}
    T = [1200.0, 1400.0]
    base = br.batch_reactor_sweep(comp, T, 1e5, 5e-5, chem=chem,
                                  thermo_obj=th, md=gm)
    pad = br.batch_reactor_sweep(comp, T, 1e5, 5e-5, chem=chem,
                                 thermo_obj=th, md=gm,
                                 species_buckets=(S_PAD,),
                                 reaction_buckets=(R_PAD,),
                                 telemetry=True)
    assert set(pad["x"]) == set(gm.species)  # no _PAD_* names leak
    for s in gm.species:
        assert np.allclose(base["x"][s], pad["x"][s], rtol=1e-10,
                           atol=1e-18)
    assert tuple(pad["telemetry"]["meta"]["mech_shape"]) == (S_PAD, R_PAD)
    # the failure-triage report never carries the reserved operand
    assert all(not k.startswith("_")
               for k in pad["report"].get("failed_conditions", {}))


def test_sweep_api_padding_validation(mech):
    gm, th = mech
    chem = br.Chemistry(gaschem=True)
    comp = {"H2": 1.0}
    with pytest.raises(ValueError, match="segment_steps"):
        br.batch_reactor_sweep(comp, 1200.0, 1e5, 1e-6, chem=chem,
                               thermo_obj=th, md=gm, mech_operands=True)
    with pytest.raises(ValueError, match="analytic Jacobian"):
        br.batch_reactor_sweep(comp, 1200.0, 1e5, 1e-6, chem=chem,
                               thermo_obj=th, md=gm, mech_operands=True,
                               segment_steps=16, analytic_jac=False)
    with pytest.raises(ValueError, match="gas chemistry only"):
        br.batch_reactor_sweep(comp, 1200.0, 1e5, 1e-6,
                               chem=br.Chemistry(userchem=True,
                                                 udf=lambda t, s: 0.0),
                               thermo_obj=th, species_buckets="pow2")


def test_mech_operands_one_executable_two_mechanisms(mech, mech_n,
                                                     cold_compile_cache):
    """THE tentpole contract: a second mechanism padded into a warmed
    (B, S, R) bucket compiles NOTHING — armed ``sweep-segment`` label
    evidence, compact program included — and its results match its own
    dedicated-shape run."""
    gm, th = mech
    gm2, th2 = mech_n
    chem = br.Chemistry(gaschem=True)
    T = [1200.0, 1350.0, 1500.0]
    kw = dict(chem=chem, segment_steps=64, mech_operands=True,
              species_buckets=(S_PAD,), reaction_buckets=(R_PAD,),
              telemetry=True, admission=2, refill=1)

    def armed(rep):
        lbl = rep["telemetry"]["compile"].get("by_label") or {}
        return {k: v["compiles"] for k, v in lbl.items()
                if v.get("single_program")}

    rA = br.batch_reactor_sweep({"H2": 0.3, "O2": 0.15, "N2": 0.55}, T,
                                1e5, 5e-5, thermo_obj=th, md=gm, **kw)
    first = armed(rA)
    assert first.get("sweep-segment", 0) >= 1  # cold bucket compiled
    rB = br.batch_reactor_sweep(
        {"H2": 0.3, "O2": 0.15, "N2": 0.5, "AR": 0.05}, T, 1e5, 5e-5,
        thermo_obj=th2, md=gm2, **kw)
    second = armed(rB)
    assert sum(second.values()) == 0, (
        f"second mechanism in a warmed bucket must compile nothing; "
        f"got {second} (first run: {first})")
    assert set(rB["x"]) == set(gm2.species)
    base = br.batch_reactor_sweep(
        {"H2": 0.3, "O2": 0.15, "N2": 0.5, "AR": 0.05}, T, 1e5, 5e-5,
        chem=chem, thermo_obj=th2, md=gm2)
    for s in gm2.species:
        assert np.allclose(base["x"][s], rB["x"][s], rtol=1e-10,
                           atol=1e-18), s
    # same program + same operands => bit-exact across re-parsed copies
    gm2b = br.compile_gaschemistry(f"{FIX}/h2o2_n.dat")
    th2b = br.create_thermo(list(gm2b.species), f"{FIX}/therm.dat")
    rB2 = br.batch_reactor_sweep(
        {"H2": 0.3, "O2": 0.15, "N2": 0.5, "AR": 0.05}, T, 1e5, 5e-5,
        thermo_obj=th2b, md=gm2b, **kw)
    for s in gm2.species:
        assert np.array_equal(rB["x"][s], rB2["x"][s]), s


# --------------------------------------------------------------------------
# the (B, S, R) aot registry keys
# --------------------------------------------------------------------------
def test_program_key_mech_shape_and_legacy_format():
    from batchreactor_tpu.aot import program_key

    legacy = program_key("fp", "bdf", 8, {"rtol": "1e-06"})
    assert legacy.startswith("bdf-b8-") and len(legacy.split("-")) == 3
    shaped = program_key("fp", "bdf", 8, {"rtol": "1e-06"},
                         mech_shape=(16, 32))
    assert shaped.startswith("bdf-b8-s16r32-")
    assert shaped.split("-")[-1] != legacy.split("-")[-1]


def test_spec_keys_share_rung_across_mechanisms(mech, mech_n):
    """Two mechanisms on one (S, R) rung resolve to the SAME program
    keys (the warm-cache manifest's sharing evidence) while their
    closure-mode specs resolve to different ones."""
    from batchreactor_tpu.aot import spec_keys
    from batchreactor_tpu.api import _padded_mech, _segmented_builder

    gm, th = mech
    gm2, th2 = mech_n
    builder = _segmented_builder("gas", None, False, True)

    def spec_for(g, t):
        gp, tp = _padded_mech(g, t, S_PAD, R_PAD, True)
        y0 = np.zeros(S_PAD)
        y0[0] = 1.0
        return dict(rhs=builder, y0=y0, cfg={"T": 1300.0, "Asv": 1.0,
                                             NLIVE_KEY: 9.0},
                    lanes=[4], buckets=(4,), segment_steps=16,
                    rhs_bundle=(gp, None, tp))

    ka = spec_keys(spec_for(gm, th))
    kb = spec_keys(spec_for(gm2, th2))
    assert ka == kb
    assert all("-s16r32-" in key for key, _b in ka)


def test_registry_lru_pin_and_stats(tmp_path):
    from batchreactor_tpu import aot
    from batchreactor_tpu.obs import Recorder

    cache = str(tmp_path)
    man = aot.load_manifest(cache)
    for i, key in enumerate(["bdf-b2-aaa", "bdf-b4-bbb", "bdf-b8-ccc"]):
        man["entries"][key] = {
            "bucket": 2 ** (i + 1), "warmups": 1, "compiles": 1,
            "compile_s": 1.0, "cache_hits": i,  # first entry never hit
            "cache_misses": 0, "last_used": f"2026-08-0{i + 1}T00:00:00"}
    from batchreactor_tpu.aot.registry import _save_manifest

    _save_manifest(cache, man)
    stats = aot.cache_stats(cache)
    assert stats["entries"] == 3
    assert stats["never_hit"] == ["bdf-b2-aaa"]
    assert stats["total_cache_bytes"] > 0
    # pin the LRU entry: eviction must skip it and take the next-oldest
    assert aot.pin_keys(cache, ["bdf-b2-aaa"]) == ["bdf-b2-aaa"]
    rec = Recorder()
    evicted = aot.enforce_capacity(cache, 2, recorder=rec)
    assert evicted == ["bdf-b4-bbb"]
    assert rec.counters.get("aot_evictions") == 1
    left = set(aot.load_manifest(cache)["entries"])
    assert left == {"bdf-b2-aaa", "bdf-b8-ccc"}
    # touch moves the clock: the touched entry now survives a cap of 1
    aot.touch_keys(cache, ["bdf-b2-aaa"])
    aot.pin_keys(cache, ["bdf-b2-aaa"], pinned=False)
    assert aot.enforce_capacity(cache, 1) == ["bdf-b8-ccc"]


def test_merge_manifests_crash_atomic_fold(tmp_path):
    from batchreactor_tpu import aot
    from batchreactor_tpu.aot.registry import _save_manifest

    cache = str(tmp_path)
    for tag, hits in (("w0", 2), ("w1", 3)):
        part = aot.load_manifest(cache, tag)
        part["entries"]["bdf-b4-xyz"] = {
            "bucket": 4, "warmups": 1, "compiles": 1, "compile_s": 2.0,
            "cache_hits": hits, "cache_misses": 0}
        part["jax"] = "test-jax"
        _save_manifest(cache, part, tag)
    man = aot.merge_manifests(cache, ["w0", "w1"])
    e = man["entries"]["bdf-b4-xyz"]
    assert e["warmups"] == 2 and e["cache_hits"] == 5
    assert e["compile_s"] == 4.0
    # parts pruned; merged manifest persisted
    import os

    assert not os.path.exists(aot.manifest_path(cache, "w0"))
    assert aot.load_manifest(cache)["entries"]["bdf-b4-xyz"][
        "warmups"] == 2
