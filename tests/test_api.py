"""API-parity tests: the reference's 7 integration testsets
(/root/reference/test/runtests.jl:1-78) re-run through our ``batch_reactor``
entry points, plus output-file format checks against the committed golden
artifacts' layout (/root/reference/test/batch_gas_and_surf/*.csv)."""

import shutil

import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br


def _stage(tmp_path, config_dir):
    """Copy a reference batch.xml into a writable dir (outputs land next to
    the input XML, /root/reference/src/BatchReactor.jl:170-173 — the
    reference tree is read-only here)."""
    dst = tmp_path / "batch.xml"
    shutil.copy(config_dir / "batch.xml", dst)
    return str(dst)


# --- testset "surface chemistry" (runtests.jl:13-17) ---
def test_surface_chemistry_file_driven(tmp_path, reference_dir, lib_dir):
    xml = _stage(tmp_path, reference_dir / "test" / "batch_surf")
    ret = br.batch_reactor(xml, lib_dir, surfchem=True)
    assert ret == "Success"
    # outputs land next to the input xml, with both formats x both families
    for name in ("gas_profile.dat", "gas_profile.csv",
                 "surface_covg.dat", "surface_covg.csv"):
        assert (tmp_path / name).exists(), name

    # csv layout: t,T,p,rho,<7 gas species> (docs/src/index.md:158-170)
    header = (tmp_path / "gas_profile.csv").read_text().splitlines()[0]
    cols = header.split(",")
    assert cols[:4] == ["t", "T", "p", "rho"]
    assert len(cols) == 4 + 7
    rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                      skiprows=1)
    assert rows[0, 0] == 0.0 and rows[-1, 0] == pytest.approx(10.0)
    assert np.allclose(rows[:, 1], 1073.15)          # isothermal
    x = rows[:, 4:]
    assert np.allclose(x.sum(axis=1), 1.0, atol=1e-8)

    # coverage csv: t,T,<13 surface species>, coverages sum to 1
    cov = np.loadtxt(tmp_path / "surface_covg.csv", delimiter=",",
                     skiprows=1)
    assert cov.shape[1] == 2 + 13
    assert np.allclose(cov[:, 2:].sum(axis=1), 1.0, atol=1e-6)

    # .dat format: 10-wide right-aligned header, %.4e rows (golden
    # gas_profile.dat layout)
    dat = (tmp_path / "gas_profile.dat").read_text().splitlines()
    assert dat[0].startswith("         t\t         T\t")
    assert dat[1].startswith("0.0000e+00\t")


# --- testset "gas chemistry h2o2" (runtests.jl:19-23) ---
def test_gas_chemistry_h2o2_file_driven(tmp_path, reference_dir, lib_dir):
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    ret = br.batch_reactor(xml, lib_dir, gaschem=True)
    assert ret == "Success"
    rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                      skiprows=1)
    assert rows.shape[1] == 4 + 9
    assert rows[-1, 0] == pytest.approx(10.0)
    # H2 + 1/2 O2 -> H2O at 1173 K: H2 (col 4 = first species) burns out
    header = (tmp_path / "gas_profile.csv").read_text().splitlines()[0]
    cols = header.split(",")
    x_h2 = rows[-1, cols.index("H2")]
    x_h2o = rows[-1, cols.index("H2O")]
    assert x_h2 < 1e-4 and x_h2o > 0.2


# --- testset "gas chemistry GRI" (runtests.jl:25-29): exercised at a short
# horizon here (full 10 s GRI runs live in the benchmark; the API path is
# identical) ---
def test_gas_chemistry_gri_file_driven(tmp_path, reference_dir, lib_dir):
    src = (reference_dir / "test" / "batch_ch4" / "batch.xml").read_text()
    (tmp_path / "batch.xml").write_text(src.replace(
        "<time>10</time>", "<time>1e-4</time>"))
    ret = br.batch_reactor(str(tmp_path / "batch.xml"), lib_dir, gaschem=True)
    assert ret == "Success"
    rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                      skiprows=1)
    assert rows.shape[1] == 4 + 53


# --- testset "gas + surface" (runtests.jl:31-35), short horizon ---
def test_gas_and_surface_file_driven(tmp_path, reference_dir, lib_dir):
    src = (reference_dir / "test" / "batch_gas_and_surf" /
           "batch.xml").read_text()
    (tmp_path / "batch.xml").write_text(src.replace(
        "<time>10</time>", "<time>1e-4</time>"))
    ret = br.batch_reactor(str(tmp_path / "batch.xml"), lib_dir,
                           gaschem=True, surfchem=True, kc_compat=True)
    assert ret == "Success"
    cov = np.loadtxt(tmp_path / "surface_covg.csv", delimiter=",",
                     skiprows=1)
    assert cov.shape[1] == 2 + 13
    assert np.allclose(cov[:, 2:].sum(axis=1), 1.0, atol=1e-6)


# --- testset "surf chemistry" programmatic (runtests.jl:37-49) ---
def test_programmatic_surface(gri_lib_dir):
    gasphase = ["CH4", "H2O", "H2", "CO", "CO2", "O2", "N2"]
    thermo = br.create_thermo(gasphase, f"{gri_lib_dir}/therm.dat")
    md = br.compile_mech(f"{gri_lib_dir}/ch4ni.xml", thermo, gasphase)
    chem = br.Chemistry(surfchem=True)
    t = 10.0
    ts, xf = br.batch_reactor(
        {"CH4": 0.25, "H2O": 0.25, "N2": 0.5}, 1073.15, 1e5, t,
        Asv=10.0, chem=chem, thermo_obj=thermo, md=md)
    # the reference asserts final time == t (runtests.jl:48)
    assert ts[-1] == pytest.approx(t)
    assert set(xf) == set(gasphase)
    x = np.array([xf[s] for s in gasphase])
    assert np.all(x >= -1e-12) and x.sum() == pytest.approx(1.0)
    # steam reforming produces syngas (thresholds as in
    # tests/test_surface.py::test_batch_surf_integration)
    assert xf["H2"] > 0.01 and xf["CO"] > 0.001


# --- testset "gas chemistry" programmatic (runtests.jl:51-67) ---
def test_programmatic_gas(lib_dir):
    md = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    thermo = br.create_thermo(list(md.species), f"{lib_dir}/therm.dat")
    chem = br.Chemistry(gaschem=True)
    t = 10.0
    ts, xf = br.batch_reactor(
        {"H2": 0.25, "O2": 0.25, "N2": 0.5}, 1173.0, 1e5, t,
        chem=chem, thermo_obj=thermo, md=md)
    assert ts[-1] == pytest.approx(t)
    assert xf["H2O"] > 0.2 and xf["H2"] < 1e-4


# --- testset "user defined chemistry" (runtests.jl:70-77): zero source ---
def test_udf_file_driven(tmp_path, reference_dir, lib_dir):
    xml = _stage(tmp_path, reference_dir / "test" / "batch_udf")
    seen_species = []

    def udf(t, state):
        # state carries the static species tuple (UserDefinedState contract,
        # /root/reference/src/BatchReactor.jl:199) so indices map to names
        seen_species.append(state["species"])
        return jnp.zeros_like(state["mole_frac"])

    ret = br.batch_reactor(xml, lib_dir, udf)
    assert ret == "Success"
    assert seen_species and all(
        isinstance(s, tuple) and len(s) == len(seen_species[0]) and
        all(isinstance(n, str) for n in s) for s in seen_species)
    rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                      skiprows=1)
    # zero source: composition frozen at the inlet for all rows
    assert np.allclose(rows[:, 4:], rows[0, 4:], atol=1e-12)
    assert rows[-1, 0] == pytest.approx(10.0)


# --- sens=True hook (reference :205-207 returns without solving) ---
def test_sensitivity_hook(tmp_path, reference_dir, lib_dir):
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    prob = br.batch_reactor(xml, lib_dir, gaschem=True, sens=True)
    assert isinstance(prob, br.SensitivityProblem)
    assert prob.t_span == (0.0, 10.0)
    assert len(prob.species) == 9
    # no files written, no solve run
    assert not (tmp_path / "gas_profile.csv").exists()
    # the returned rhs is live and evaluates
    dy = prob.rhs(0.0, prob.y0, prob.cfg)
    assert dy.shape == prob.y0.shape
    assert bool(jnp.all(jnp.isfinite(dy)))


# --- config-parsing details ---
def test_massfractions_tag(tmp_path, lib_dir):
    (tmp_path / "batch.xml").write_text(
        """<?xml version="1.0"?>
<batch>
  <gasphase>H2 O2 N2</gasphase>
  <massfractions>H2=0.1,O2=0.3,N2=0.6</massfractions>
  <T>300.</T> <p>1e5</p> <time>1.0</time>
</batch>""")
    chem = br.Chemistry()
    id_ = br.input_data(str(tmp_path / "batch.xml"), lib_dir,
                        br.Chemistry(userchem=True))
    # mass 0.1/0.3/0.6 over molwt 2.016/32/28.014 -> mole fracs
    n = np.array([0.1 / 2.01594e-3, 0.3 / 31.9988e-3, 0.6 / 28.0134e-3])
    assert np.allclose(id_.mole_fracs, n / n.sum(), rtol=1e-4)
    assert id_.Asv == 1.0  # missing <Asv> defaults to 1 (PARITY.md)


def test_unknown_species_rejected(tmp_path, lib_dir):
    (tmp_path / "batch.xml").write_text(
        """<?xml version="1.0"?>
<batch>
  <gasphase>H2 O2 N2</gasphase>
  <molefractions>XE=1.0</molefractions>
  <T>300.</T> <p>1e5</p> <time>1.0</time>
</batch>""")
    with pytest.raises(KeyError):
        br.input_data(str(tmp_path / "batch.xml"), lib_dir,
                      br.Chemistry(userchem=True))


# --- backend="cpu": the native C++ BDF runtime through the same API ---
def test_cpu_backend_file_driven_matches_jax(tmp_path, reference_dir,
                                             lib_dir):
    from batchreactor_tpu import native
    if not native.available():
        pytest.skip("native runtime unavailable")
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    ret = br.batch_reactor(xml, lib_dir, gaschem=True, backend="cpu")
    assert ret == "Success"
    cpu_rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                          skiprows=1)
    assert cpu_rows[-1, 0] == pytest.approx(10.0)
    ret = br.batch_reactor(xml, lib_dir, gaschem=True, backend="jax")
    assert ret == "Success"
    jax_rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                          skiprows=1)
    # same physics, two solvers: final compositions agree at tolerance scale
    np.testing.assert_allclose(cpu_rows[-1, 2:], jax_rows[-1, 2:],
                               rtol=1e-3, atol=1e-9)


def test_cpu_backend_programmatic_and_udf(tmp_path, reference_dir, lib_dir):
    from batchreactor_tpu import native
    if not native.available():
        pytest.skip("native runtime unavailable")
    md = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    thermo = br.create_thermo(list(md.species), f"{lib_dir}/therm.dat")
    ts, xf = br.batch_reactor(
        {"H2": 0.25, "O2": 0.25, "N2": 0.5}, 1173.0, 1e5, 10.0,
        chem=br.Chemistry(gaschem=True), thermo_obj=thermo, md=md,
        backend="cpu")
    assert ts[-1] == pytest.approx(10.0)
    assert xf["H2O"] > 0.2 and xf["H2"] < 1e-4
    # UDF through the generic-callback BDF (zero source, runtests.jl:70-77)
    xml = _stage(tmp_path, reference_dir / "test" / "batch_udf")
    import jax.numpy as jnp

    def udf(t, state):
        return jnp.zeros_like(state["mole_frac"])

    ret = br.batch_reactor(xml, lib_dir, udf, backend="cpu")
    assert ret == "Success"


def test_unknown_backend_raises(tmp_path, reference_dir, lib_dir):
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    with pytest.raises(ValueError, match="backend"):
        br.batch_reactor(xml, lib_dir, gaschem=True, backend="gpu")


def test_jac_window_with_cpu_backend_raises(lib_dir):
    """ADVICE r5 regression: an explicit jac_window used to be silently
    ignored by the native backend — it must fail loudly, mirroring the
    unknown-backend error (the check runs before any solve, so no
    native runtime is needed)."""
    md = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    thermo = br.create_thermo(list(md.species), f"{lib_dir}/therm.dat")
    with pytest.raises(ValueError, match="jac_window"):
        br.batch_reactor(
            {"H2": 0.25, "O2": 0.25, "N2": 0.5}, 1173.0, 1e5, 1e-3,
            chem=br.Chemistry(gaschem=True), thermo_obj=thermo, md=md,
            backend="cpu", jac_window=8)


def test_file_driven_segmented_matches_monolithic(tmp_path, reference_dir,
                                                  lib_dir):
    """The accelerator path (segmented=True) must reproduce the monolithic
    run at solver-tolerance level.  (Not byte-identical: the segmented
    program is a different XLA compilation — vmapped B=1 — whose last-ulp
    rounding shifts individual accepted steps; the physics contract is
    tolerance-scale agreement of the trajectory endpoints and a complete,
    well-formed profile file.)"""
    (tmp_path / "mono").mkdir()
    (tmp_path / "seg").mkdir()
    a = _stage(tmp_path / "mono", reference_dir / "test" / "batch_h2o2")
    b = _stage(tmp_path / "seg", reference_dir / "test" / "batch_h2o2")
    assert br.batch_reactor(a, lib_dir, gaschem=True,
                            segmented=False) == "Success"
    assert br.batch_reactor(b, lib_dir, gaschem=True,
                            segmented=True) == "Success"
    ra = np.loadtxt(tmp_path / "mono" / "gas_profile.csv", delimiter=",",
                    skiprows=1)
    rb = np.loadtxt(tmp_path / "seg" / "gas_profile.csv", delimiter=",",
                    skiprows=1)
    # same horizon, same initial row, similar resolution
    np.testing.assert_allclose(rb[0], ra[0], rtol=1e-12)
    assert ra[-1, 0] == pytest.approx(10.0) == rb[-1, 0]
    assert abs(len(rb) - len(ra)) < 0.2 * len(ra)
    # final compositions agree at tolerance scale
    np.testing.assert_allclose(rb[-1, 1:], ra[-1, 1:], rtol=1e-5, atol=1e-10)


def test_default_per_step_progress(tmp_path, reference_dir, lib_dir, capsys):
    """File-driven runs print every accepted step time by default, like the
    reference's per-step @printf (/root/reference/src/BatchReactor.jl:401);
    verbose=False opts out entirely."""
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    ret = br.batch_reactor(xml, lib_dir, gaschem=True)
    assert ret == "Success"
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    # many per-step lines in %.4e format, then one summary line
    step_lines = [ln for ln in lines if not ln.startswith("t = ")]
    assert len(step_lines) > 50
    ts = [float(ln) for ln in step_lines]
    assert ts == sorted(ts) and ts[-1] <= 10.0 + 1e-9
    assert lines[-1].startswith("t = ")

    ret = br.batch_reactor(xml, lib_dir, gaschem=True, verbose=False)
    assert ret == "Success"
    assert capsys.readouterr().out == ""


def test_segmented_max_steps_budget_exact(tmp_path, reference_dir, lib_dir,
                                          capsys):
    """The segmented path parks lanes at the exact max_steps attempt budget
    (host-side tracking), matching the monolithic backends' semantics."""
    xml = _stage(tmp_path, reference_dir / "test" / "batch_h2o2")
    ret = br.batch_reactor(xml, lib_dir, gaschem=True, max_steps=40,
                           segmented=True, verbose=False)
    assert ret == "MaxIters"
