"""On-chip smoke tier (``-m tpu``): the round-3 verdict's gap that no test
ever ran on the real accelerator — chip-specific regressions (e.g. the
coupled-mode TPU compile wall, PERF.md) were only visible through bench
artifacts, never through the test workflow.

Excluded from the default run (pyproject addopts ``-m 'not tpu'``).  Run
through ``scripts/tpu_smoke.py`` (wedge-safe: subprocess + SIGTERM timeout,
writes a TPU_SMOKE json artifact) or directly:

    BR_TEST_TPU=1 python -m pytest tests -m tpu -q

Workload sizes are deliberately small (h2o2 + B=8) so one full pass stays
inside a single rung-scale compile budget on the tunneled chip.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="on-chip tier needs a real accelerator (BR_TEST_TPU=1 and "
               "an attached TPU); default runs exclude it via -m 'not tpu'"),
]


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, th


def test_file_driven_h2o2_on_chip(tmp_path, reference_dir, lib_dir):
    """The reference's batch_h2o2 testset (runtests.jl:19-23), solved on the
    accelerator end-to-end: parse -> jit -> segmented implicit solve ->
    golden-format output files."""
    import shutil

    xml = tmp_path / "batch.xml"
    shutil.copy(reference_dir / "test" / "batch_h2o2" / "batch.xml", xml)
    ret = br.batch_reactor(str(xml), lib_dir, gaschem=True, verbose=False)
    assert ret == "Success"
    rows = np.loadtxt(tmp_path / "gas_profile.csv", delimiter=",",
                      skiprows=1)
    assert rows[-1, 0] == pytest.approx(10.0)
    x = rows[:, 4:]
    assert np.allclose(x.sum(axis=1), 1.0, atol=1e-8)


def test_gri_sweep_b8_on_chip(gri_lib_dir):
    """B=8 GRI-Mech temperature sweep through the product sweep API on the
    chip: all lanes succeed, ignition delays are finite and decrease with
    temperature (the bench workload's physics, tiny shape)."""
    gm = br.compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{gri_lib_dir}/therm.dat")
    out = br.batch_reactor_sweep(
        {"CH4": 0.25, "O2": 0.5, "N2": 0.25},
        jnp.linspace(1500.0, 2000.0, 8), 1e5, 8e-4,
        chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
        segment_steps=256, ignition_marker="CH4")
    assert out["report"]["counts"]["success"] == 8, out["report"]
    tau = out["tau"]
    assert np.all(np.isfinite(tau)) and np.all(tau > 0)
    assert tau[-1] < tau[0]  # hotter ignites faster


def test_segmented_resume_on_chip(tmp_path, h2o2):
    """Checkpointed sweep on the accelerator: solve all 4 chunks, delete
    2 chunk files, re-invoke — the partial resume must re-solve exactly the
    missing chunks and reproduce the straight-through result bit-for-bit
    (the exact-multistep-resume contract exercised where it ships)."""
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors
    from batchreactor_tpu.solver.sdirk import SUCCESS

    gm, th = h2o2
    sp = list(gm.species)
    B = 8
    X = np.zeros((B, len(sp)))
    X[:, sp.index("H2")], X[:, sp.index("O2")] = 0.25, 0.25
    X[:, sp.index("N2")] = 0.5
    T = jnp.linspace(1150.0, 1350.0, B)
    y0s = sweep_solution_vectors(jnp.asarray(X), th.molwt, T, 1e5)
    rhs, jac = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    kw = dict(rtol=1e-6, atol=1e-10, jac=jac, segment_steps=128,
              jac_window=1)  # jw=1: resume is bit-exact (solver/bdf.py)

    import os

    ckpt = tmp_path / "ckpt"
    full = checkpointed_sweep(rhs, y0s, 0.0, 2e-4, {"T": T},
                              str(ckpt), chunk_size=2, **kw)
    assert np.all(np.asarray(full.status) == SUCCESS)
    # partial resume: drop 2 of the 4 chunk files, re-invoke — the missing
    # chunks re-solve on the accelerator, the survivors load from disk
    os.remove(ckpt / "chunk_00001.npz")
    os.remove(ckpt / "chunk_00003.npz")
    resumed = checkpointed_sweep(rhs, y0s, 0.0, 2e-4, {"T": T},
                                 str(ckpt), chunk_size=2, **kw)
    np.testing.assert_array_equal(np.asarray(full.status),
                                  np.asarray(resumed.status))
    np.testing.assert_array_equal(np.asarray(full.y), np.asarray(resumed.y))
    np.testing.assert_array_equal(np.asarray(full.t), np.asarray(resumed.t))
