"""Gas kinetics kernel tests: conservation laws, reversibility, jit/vmap/jacfwd
safety.  The trajectory-level oracle against scipy BDF lives in
test_integration.py (slow-marked)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu.models.gas import compile_gaschemistry
from batchreactor_tpu.models.thermo import create_thermo, element_matrix
from batchreactor_tpu.ops import gas_kinetics
from batchreactor_tpu.ops.rhs import make_gas_rhs
from batchreactor_tpu.utils.composition import density, mole_to_mass
from batchreactor_tpu.utils.constants import R


@pytest.fixture(scope="module")
def h2o2_setup(lib_dir):
    gm = compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, th


@pytest.fixture(scope="module")
def gri_setup(gri_lib_dir):
    gm = compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")
    th = create_thermo(list(gm.species), f"{gri_lib_dir}/therm.dat")
    return gm, th


def _conc(gm, th, T=1173.0, p=1e5, comp=None):
    sp = list(gm.species)
    x = np.zeros(len(sp))
    for name, v in (comp or {"H2": 0.25, "O2": 0.25, "N2": 0.5}).items():
        x[sp.index(name)] = v
    return jnp.asarray(x) * p / (R * T)


def test_mass_conservation(h2o2_setup):
    gm, th = h2o2_setup
    conc = _conc(gm, th)
    wdot = gas_kinetics.production_rates(1173.0, conc, gm, th)
    assert abs(float(jnp.sum(wdot * th.molwt))) < 1e-12 * float(
        jnp.sum(jnp.abs(wdot * th.molwt))
    )


def test_element_conservation_gri(gri_setup):
    gm, th = gri_setup
    conc = _conc(gm, th, comp={"CH4": 0.25, "O2": 0.5, "N2": 0.25})
    wdot = np.asarray(gas_kinetics.production_rates(1173.0, conc, gm, th))
    _, E = element_matrix(th)
    balance = E @ wdot
    assert np.all(np.abs(balance) < 1e-10 * np.abs(wdot).max())


def test_detailed_balance(h2o2_setup):
    """Construct the equilibrium composition of H2+O2=2OH from ln Kc and
    assert that reaction's net rate vanishes (kr = kf/Kc consistency)."""
    gm, th = h2o2_setup
    T = 1500.0
    i = list(gm.equations).index("H2+O2=2OH")
    sp = list(gm.species)
    log_Kc = float(gas_kinetics.equilibrium_constants(T, gm, th)[i])
    # dn = 0 for this reaction: [OH]^2/([H2][O2]) = Kc at equilibrium
    c = np.zeros(9)
    c[sp.index("H2")] = 2.0
    c[sp.index("O2")] = 3.0
    c[sp.index("OH")] = np.sqrt(6.0 * np.exp(log_Kc))
    q = np.asarray(gas_kinetics.reaction_rates(T, jnp.asarray(c), gm, th))
    kf, _ = gas_kinetics.forward_rate_constants(T, jnp.asarray(c), gm)
    rf = float(kf[i]) * 6.0  # forward rate of progress
    assert abs(q[i]) < 1e-10 * rf  # net rate ~ 0 at equilibrium
    # and a deliberately off-equilibrium composition must NOT balance
    c[sp.index("OH")] *= 2.0
    q2 = np.asarray(gas_kinetics.reaction_rates(T, jnp.asarray(c), gm, th))
    assert abs(q2[i]) > 1e-3 * rf


def test_rhs_jit_vmap_jacfwd(h2o2_setup):
    gm, th = h2o2_setup
    rhs = make_gas_rhs(gm, th)
    sp = list(gm.species)
    x = np.zeros(9)
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.25, 0.25, 0.5
    rho = density(jnp.asarray(x), th.molwt, 1173.0, 1e5)
    y0 = mole_to_mass(jnp.asarray(x), th.molwt) * rho
    cfg = {"T": 1173.0}

    dy = jax.jit(rhs)(0.0, y0, cfg)
    assert np.all(np.isfinite(np.asarray(dy)))

    J = jax.jacfwd(lambda y: rhs(0.0, y, cfg))(y0)
    assert J.shape == (9, 9) and np.all(np.isfinite(np.asarray(J)))

    ys = jnp.stack([y0, y0 * 1.1, y0 * 0.9])
    cfgs = {"T": jnp.asarray([1173.0, 1200.0, 1100.0])}
    dys = jax.vmap(lambda y, T: rhs(0.0, y, {"T": T}))(ys, cfgs["T"])
    assert dys.shape == (3, 9) and np.all(np.isfinite(np.asarray(dys)))


def test_negative_conc_no_nan(gri_setup):
    """Newton iterates can momentarily go negative; RHS and Jacobian must stay
    finite (CVODE-parity behaviour; SURVEY.md §7 hard parts)."""
    gm, th = gri_setup
    rhs = make_gas_rhs(gm, th)
    sp = list(gm.species)
    x = np.zeros(53)
    x[sp.index("CH4")], x[sp.index("O2")], x[sp.index("N2")] = 0.25, 0.5, 0.25
    rho = density(jnp.asarray(x), th.molwt, 1173.0, 1e5)
    y0 = np.array(mole_to_mass(jnp.asarray(x), th.molwt) * rho)
    y0[sp.index("OH")] = -1e-13  # small negative excursion
    cfg = {"T": 1173.0}
    dy = rhs(0.0, jnp.asarray(y0), cfg)
    assert np.all(np.isfinite(np.asarray(dy)))
    J = jax.jacfwd(lambda y: rhs(0.0, y, cfg))(jnp.asarray(y0))
    assert np.all(np.isfinite(np.asarray(J)))


def test_troe_falloff_limits(gri_setup):
    """Falloff k must approach k_inf at high [M] and k0[M] at low [M]."""
    gm, th = gri_setup
    i = [
        j
        for j, eq in enumerate(gm.equations)
        if eq.replace(" ", "") == "H+CH3(+M)<=>CH4(+M)"
    ][0]
    T = 1200.0

    def k_eff(scale):
        conc = jnp.full(53, scale)
        kf, _ = gas_kinetics.forward_rate_constants(T, conc, gm)
        return float(kf[i])

    k_inf = float(gas_kinetics._arrhenius(T, gm.log_A, gm.beta, gm.Ea)[i])
    k0 = float(gas_kinetics._arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0)[i])
    cM_hi = float(gm.eff[i] @ jnp.full(53, 1e6))
    assert k_eff(1e6) / k_inf > 0.95  # high-pressure limit
    lo = k_eff(1e-8)
    cM_lo = float(gm.eff[i] @ jnp.full(53, 1e-8))
    assert abs(lo / (k0 * cM_lo) - 1) < 0.5  # low-pressure limit (F<=1)


class TestAnalyticJacobian:
    """ops/rhs.make_gas_jac must equal jax.jacfwd of the RHS to roundoff —
    it is the matrix every implicit step builds (solver/sdirk.py)."""

    def _check(self, mech, lib_dir, comp, kc_compat=False):
        import batchreactor_tpu as br
        from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
        from batchreactor_tpu.utils.composition import density, mole_to_mass

        gm = br.compile_gaschemistry(f"{lib_dir}/{mech}")
        th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
        sp = list(gm.species)
        x0 = np.zeros(len(sp))
        for name, frac in comp.items():
            x0[sp.index(name)] = frac
        T = 1400.0
        rho = float(density(jnp.asarray(x0), th.molwt, T, 1e5))
        y0 = jnp.asarray(np.asarray(mole_to_mass(jnp.asarray(x0), th.molwt)) * rho)
        rhs = make_gas_rhs(gm, th, kc_compat=kc_compat)
        jac = make_gas_jac(gm, th, kc_compat=kc_compat)
        cfg = {"T": jnp.asarray(T)}
        states = [
            y0,  # zeros present (radicals at 0): exclusive-product edge case
            y0 + 1e-4 * jnp.max(y0) * jnp.abs(jnp.sin(1.7 * jnp.arange(len(sp)))),
            jnp.abs(y0) + 1e-7,  # strictly positive
        ]
        for y in states:
            Jf = jax.jacfwd(lambda q: rhs(0.0, q, cfg))(y)
            Ja = jac(0.0, y, cfg)
            scale = float(jnp.max(jnp.abs(Jf)))
            assert float(jnp.max(jnp.abs(Ja - Jf))) / scale < 1e-12

    def test_h2o2(self, lib_dir):
        self._check("h2o2.dat", lib_dir, {"H2": 0.25, "O2": 0.25, "N2": 0.5})

    def test_grimech_with_falloff_and_troe(self, gri_lib_dir):
        self._check("grimech.dat", gri_lib_dir,
                    {"CH4": 0.25, "O2": 0.5, "N2": 0.25})

    def test_kc_compat_mode(self, gri_lib_dir):
        self._check("grimech.dat", gri_lib_dir,
                    {"CH4": 0.25, "O2": 0.5, "N2": 0.25}, kc_compat=True)


def test_frac_stoich_grad_at_zero_conc():
    """Fractional exponents at clamped (zero) concentration: the derivative
    must match jacfwd through the clamped forward path (= 0 there), not the
    raw nu*f/c quotient (~1e150 for nu=0.5 at c=0), which would poison the
    Newton matrix for mechanisms with fractional <order> overrides."""
    import jax
    from batchreactor_tpu.ops.gas_kinetics import (_stoich_prod,
                                                   _stoich_prod_and_grad)

    nu = jnp.asarray([[0.5, 1.0, 0.0], [1.5, 0.0, 2.0]])
    conc = jnp.asarray([0.0, 2.0, 3.0])
    P, dP = _stoich_prod_and_grad(conc, nu, False)
    assert bool(jnp.all(jnp.isfinite(dP)))
    J = jax.jacfwd(lambda c: _stoich_prod(c, nu, False))(conc)
    np.testing.assert_allclose(np.asarray(dP), np.asarray(J),
                               rtol=1e-12, atol=1e-300)
    # nonzero entries still exact
    conc2 = jnp.asarray([0.7, 2.0, 3.0])
    P2, dP2 = _stoich_prod_and_grad(conc2, nu, False)
    J2 = jax.jacfwd(lambda c: _stoich_prod(c, nu, False))(conc2)
    np.testing.assert_allclose(np.asarray(dP2), np.asarray(J2), rtol=1e-12)


def test_exp32_full_clip_window(monkeypatch):
    """BR_EXP32 path: exp(x) = exp32(x/8)^8 must stay finite and ~1e-6
    accurate over the whole +-690 clip window (a naive f32 cast overflows
    past ~88.7 and flushes below ~-87, yielding 0*inf = NaN in kr)."""
    from batchreactor_tpu.ops import gas_kinetics
    from batchreactor_tpu.ops.gas_kinetics import _exp

    x = jnp.asarray([-690.0, -124.0, -87.0, 0.0, 87.0, 160.0, 690.0])
    # force the f32 formulation through the module global: the env var is
    # read once and FROZEN at first kernel trace (accelerator-default
    # resolution), so on the CPU-pinned suite it has already resolved to
    # False by the time this test runs — setenv would be a no-op and the
    # test would silently validate plain f64 exp
    monkeypatch.setattr(gas_kinetics, "_EXP32", True)
    got = np.asarray(_exp(x))
    ref = np.exp(np.asarray(x))
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, ref, rtol=5e-6)
    # product pattern that NaNs under the naive cast: e^-124 * e^160
    kf = np.asarray(_exp(jnp.asarray(-124.0)))
    fac = np.asarray(_exp(jnp.asarray(160.0)))
    assert np.isfinite(kf * fac)
    np.testing.assert_allclose(kf * fac, np.exp(36.0), rtol=1e-5)
