"""Telemetry subsystem tests (obs/ — docs/observability.md).

Covers the ISSUE's acceptance surface: counter exactness against a
hand-derivable tiny ODE, vmap batching of per-lane stats, retrace
detection semantics, JSONL/Prometheus export round-trips, the
``telemetry=`` API contract (including the telemetry=False
no-structure-change guarantee), the step_audit fold into stats, the
Phases compatibility shim, and the obs_report CLI.
"""

import json
import os
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu import obs
from batchreactor_tpu.obs import counters as obs_counters
from batchreactor_tpu.obs.recorder import Recorder
from batchreactor_tpu.obs.retrace import CompileWatch
from batchreactor_tpu.solver import bdf, sdirk

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lin_rhs(t, y, cfg):
    return -y


@pytest.fixture(scope="module")
def lin_stats(fixtures_dir):
    """ONE bdf stats=True solve of the linear ODE, shared by every test
    that only reads counters (each eager solve pays its own trace —
    tier-1 runs on a tight wall-clock budget)."""
    return bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                     rtol=1e-6, atol=1e-12, stats=True)


# ---------------------------------------------------------------------------
# device-side solver counters
# ---------------------------------------------------------------------------

#: primitive kinds the stats=True counter block is allowed to add to the
#: traced step program: masked adds, the gating boolean logic, dtype casts
#: of the masks, the order-histogram scatter, and jit wrapper nodes.
#: Anything else (a dot_general, an extra while, a callback, a device_put)
#: means the telemetry stopped being free.
_COUNTER_BLOCK_PRIMS = frozenset({
    "add", "and", "or", "not", "eq", "ne", "lt", "le", "gt", "ge",
    "select_n", "broadcast_in_dim", "convert_element_type", "reshape",
    "scatter-add", "pjit", "mul", "sub", "integer_pow", "squeeze",
})


@pytest.mark.parametrize("solver", [bdf.solve, sdirk.solve],
                         ids=["bdf", "sdirk"])
def test_stats_on_jaxpr_adds_only_counter_block(solver):
    """The PERF.md measurement-surface guarantee, asserted on program
    STRUCTURE instead of flaky wall time: the stats=True jaxpr differs
    from stats=False only by counter-block primitives — same loop count,
    no new linear algebra, no host callbacks, no in-loop staging."""
    import collections

    from batchreactor_tpu.analysis.jaxpr_audit import _iter_eqns

    def hist(stats):
        jaxpr = jax.make_jaxpr(
            lambda y: solver(_lin_rhs, y, 0.0, 1.0, None, rtol=1e-6,
                             atol=1e-12, max_steps=4, stats=stats).y)(
            jnp.asarray([1.0, 2.0]))
        c = collections.Counter()
        for eqn, _ in _iter_eqns(jaxpr):
            c[eqn.primitive.name] += 1
        return c

    off, on = hist(False), hist(True)
    added = {k: on[k] - off[k] for k in set(on) | set(off)
             if on[k] != off[k]}
    # nothing removed, and nothing added beyond the counter block
    assert all(v > 0 for v in added.values()), added
    assert set(added) <= _COUNTER_BLOCK_PRIMS, added
    # the loop structure itself is untouched
    assert on["while"] == off["while"]
    assert on.get("dot_general", 0) == off.get("dot_general", 0)


def test_bdf_counter_exactness_linear_ode(lin_stats):
    """On a LINEAR ODE with the (exact) default Jacobian and the exact LU
    solve, the first Newton iteration lands on the corrector solution and
    the second proves convergence — so the iteration count is exactly 2
    per attempt, which pins ``newton_iters`` against the independently
    reported attempt counts.  The other identities hold by construction
    and must be exact, not approximate."""
    r = lin_stats
    st = {k: np.asarray(v) for k, v in r.stats.items()}
    n_att = int(r.n_accepted) + int(r.n_rejected)
    assert st["n_accepted"] == int(r.n_accepted)
    assert st["n_rejected"] == int(r.n_rejected)
    assert st["newton_iters"] == 2 * n_att
    # jac_window=1: one J build + one factorization per attempt
    assert st["jac_builds"] == n_att
    assert st["factorizations"] == n_att
    # rejection causes partition the rejections
    assert st["err_rejects"] + st["conv_rejects"] == int(r.n_rejected)
    # every accepted step lands in exactly one order bucket; slot 0 unused
    assert st["order_hist"].shape == (bdf.MAXORD + 1,)
    assert st["order_hist"][0] == 0
    assert st["order_hist"].sum() == int(r.n_accepted)


def test_bdf_jac_window_amortizes_builds(lin_stats):
    r1 = lin_stats
    r4 = bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                   rtol=1e-6, atol=1e-12, stats=True, jac_window=4)
    att4 = int(r4.n_accepted) + int(r4.n_rejected)
    assert int(np.asarray(r4.stats["jac_builds"])) < int(
        np.asarray(r1.stats["jac_builds"]))
    # one J serves up to 4 attempts; ceil(att/4) windows is the floor
    assert int(np.asarray(r4.stats["jac_builds"])) >= -(-att4 // 4)
    # M is still rebuilt c-correct every attempt without freeze_precond
    assert int(np.asarray(r4.stats["factorizations"])) == att4


def test_bdf_freeze_precond_amortizes_factorizations():
    r = bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                  rtol=1e-6, atol=1e-12, stats=True, jac_window=4,
                  freeze_precond=True)
    st = {k: int(np.asarray(v)) for k, v in r.stats.items()
          if k != "order_hist"}
    # frozen window: exactly one factorization per window open = per J
    assert st["factorizations"] == st["jac_builds"]
    assert st["factorizations"] < st["n_accepted"] + st["n_rejected"]


def test_sdirk_counters():
    r = sdirk.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                    rtol=1e-6, atol=1e-12, stats=True)
    st = {k: int(np.asarray(v)) for k, v in r.stats.items()}
    n_att = st["n_accepted"] + st["n_rejected"]
    assert st["n_accepted"] == int(r.n_accepted) > 0
    assert st["factorizations"] == n_att
    assert st["jac_builds"] == n_att      # jac_window=1
    # 5 implicit stages per attempt, >= 1 Newton iteration each
    assert st["newton_iters"] >= 5 * n_att
    assert st["err_rejects"] + st["conv_rejects"] == st["n_rejected"]


def test_stats_off_is_none_and_structure_unchanged(lin_stats):
    """telemetry=False / stats=False must return a SolveResult whose
    pytree structure carries no stats leaves — the existing pytree-shape
    assumptions (checkpoint save/load fields, tree.map over results)
    survive the subsystem's existence."""
    r = bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                  rtol=1e-6, atol=1e-12)
    assert r.stats is None
    leaves, treedef = jax.tree_util.tree_flatten(r)
    # a result rebuilt from the documented persisted fields (the
    # checkpoint contract) has the same structure
    r2 = sdirk.SolveResult(
        t=r.t, y=r.y, status=r.status, n_accepted=r.n_accepted,
        n_rejected=r.n_rejected, ts=r.ts, ys=r.ys, n_saved=r.n_saved,
        h=r.h, err_prev=r.err_prev, solver_state=r.solver_state)
    assert jax.tree_util.tree_structure(r2) == treedef
    assert jax.tree_util.tree_structure(lin_stats) != treedef


def test_vmap_batches_per_lane_stats(lin_stats):
    # lane 0 repeats the lin_stats fixture's solve, so the batched
    # counters can be pinned against an independent eager solve without
    # paying per-lane re-traces
    y0s = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [0.5, 0.25]])
    vs = jax.vmap(lambda y0: bdf.solve(_lin_rhs, y0, 0.0, 1.0, None,
                                       rtol=1e-6, atol=1e-12, stats=True))
    rb = vs(y0s)
    assert rb.stats["newton_iters"].shape == (3,)
    assert rb.stats["order_hist"].shape == (3, bdf.MAXORD + 1)
    for k in ("newton_iters", "jac_builds", "err_rejects",
              "conv_rejects"):
        assert int(rb.stats[k][0]) == int(np.asarray(lin_stats.stats[k])), k
    assert np.array_equal(np.asarray(rb.stats["order_hist"][0]),
                          np.asarray(lin_stats.stats["order_hist"]))
    # every lane keeps its own exact identities
    for i in range(3):
        assert int(rb.stats["order_hist"][i].sum()) == int(rb.n_accepted[i])
        assert (int(rb.stats["err_rejects"][i])
                + int(rb.stats["conv_rejects"][i])
                == int(rb.n_rejected[i]))
        assert int(rb.stats["newton_iters"][i]) == 2 * (
            int(rb.n_accepted[i]) + int(rb.n_rejected[i]))


def test_segmented_stats_accumulation_matches_monolithic():
    # a non-autonomous rhs keeps several segments' worth of adaptive
    # steps while compiling in seconds (tier-1 runs on a tight budget —
    # the mechanism-RHS telemetry path is covered by the h2o2_report
    # fixture below)
    def rhs(t, y, cfg):
        return -y * (1.0 + 0.5 * jnp.sin(400.0 * t))

    from batchreactor_tpu.parallel import (ensemble_solve,
                                           ensemble_solve_segmented)

    y0s = jnp.asarray([[1.0, 2.0], [3.0, 0.5]])
    cfgs = {"T": jnp.asarray([0.0, 0.0])}
    mono = ensemble_solve(rhs, y0s, 0.0, 1.0, cfgs, stats=True)
    seg = ensemble_solve_segmented(rhs, y0s, 0.0, 1.0, cfgs, stats=True,
                                   segment_steps=16)
    tm = obs_counters.totals(mono.stats)
    ts = obs_counters.totals(seg.stats)
    # jac_window=1 segmented resume is bit-exact, so the accumulated
    # counters must match the monolithic ones exactly
    assert tm == ts
    assert tm["n_accepted"] == int(np.asarray(mono.n_accepted).sum())
    # several segments actually ran (the accumulation path was exercised)
    assert int(np.asarray(mono.n_accepted).max()) > 16


def test_segmented_watch_no_false_retraces(cold_compile_cache):
    """Healthy segment relaunches of one cached program must not flag
    retraces: the armed sweep-segment label sees exactly one compile and
    the host loop's own eager-op compiles attribute elsewhere
    (regression: the first wiring flagged every post-first compile under
    a shared label).  cold_compile_cache: the single compile must be a
    TRUE compile — a warm persistent cache (CI restores one) would serve
    it as a cache load, which deliberately doesn't count."""
    def rhs(t, y, cfg):
        return -y * (1.0 + 0.5 * jnp.cos(300.0 * t))

    from batchreactor_tpu.parallel import ensemble_solve_segmented

    rec = Recorder()
    watch = CompileWatch(recorder=rec, default_label="caller")
    y0s = jnp.asarray([[1.0, 2.0], [3.0, 0.5]])
    with watch:
        res = ensemble_solve_segmented(rhs, y0s, 0.0, 1.0,
                                       {"T": jnp.zeros(2)},
                                       segment_steps=8, recorder=rec,
                                       watch=watch)
    assert int(np.asarray(res.n_accepted).max()) > 8   # several segments
    s = watch.summary()
    if not s["available"]:
        pytest.skip("jax.monitoring unavailable on this build")
    # the armed label landed in the CALLER's watch (the report path)
    assert s["by_label"]["sweep-segment"]["compiles"] == 1
    assert s["by_label"]["sweep-segment"]["single_program"] is True
    assert s["retraces"] == 0
    assert "retrace" not in [e["name"] for e in rec.events]


def test_step_audit_folds_into_stats_with_legacy_aliases():
    """ISSUE satellite: step_audit payloads live under SolveResult.stats;
    the legacy top-level fields still alias the same arrays."""
    r = bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                  rtol=1e-6, atol=1e-12, step_audit=True)
    assert r.stats is not None
    assert r.stats["accept_ring"] is r.accept_ring
    assert r.stats["it_matrix"] is r.it_matrix
    # audit alone does not switch the counters on
    assert "newton_iters" not in r.stats
    # combined: counters + audit payloads in one dict
    rc = bdf.solve(_lin_rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                   rtol=1e-6, atol=1e-12, step_audit=True, stats=True)
    assert rc.stats["accept_ring"] is rc.accept_ring
    assert int(np.asarray(rc.stats["newton_iters"])) > 0
    # totals() treats audit payloads as samples, not counters
    tot = obs_counters.totals(rc.stats)
    assert "accept_ring" not in tot and "newton_iters" in tot


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------
def test_recorder_nested_spans_counters_events():
    rec = Recorder()
    with rec.span("outer", workload="x"):
        with rec.span("inner"):
            pass
        with rec.span("inner"):
            pass
    rec.counter("bytes", 10)
    rec.counter("bytes", 5)
    rec.event("note", detail=1)
    spans, events, ctrs = rec.snapshot()
    assert [s["name"] for s in spans] == ["outer", "inner", "inner"]
    assert spans[1]["path"] == "outer/inner" and spans[1]["depth"] == 1
    assert spans[0]["attrs"] == {"workload": "x"}
    assert all(s["dur"] >= 0 for s in spans)
    assert ctrs == {"bytes": 15}
    assert events[0]["name"] == "note"
    agg = rec.by_name()
    assert agg["inner"]["count"] == 2
    assert "outer" in rec.pretty() and "x2" in rec.pretty()


def test_phases_shim_over_recorder():
    from batchreactor_tpu.utils.profiling import Phases

    ph = Phases()
    with ph("parse"):
        pass
    with ph("solve", block=jnp.ones(2)):
        pass
    with ph("solve"):
        pass
    assert set(ph.summary()) == {"parse", "solve"}
    assert ph.counts["solve"] == 2
    # the per-name call counts now display (ISSUE satellite)
    assert "x2" in ph.pretty()
    # the underlying recorder is reachable for export/migration
    assert isinstance(ph.recorder, Recorder)
    assert len(ph.recorder.spans) == 3


# ---------------------------------------------------------------------------
# retrace detection
# ---------------------------------------------------------------------------
def test_compile_watch_counts_and_retrace_semantics(cold_compile_cache):
    # cold_compile_cache: these compiles must be TRUE compiles — a warm
    # persistent cache (CI restores one) would service them as cache
    # loads, which deliberately don't count (obs/retrace.py)
    rec = Recorder()
    watch = CompileWatch(recorder=rec)

    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    # inputs built OUTSIDE the region: array creation can itself compile
    # tiny helper programs that must not attribute to the watched label
    x3a, x3b, x4 = jnp.ones(3), jnp.ones(3) * 2, jnp.ones(4)
    jax.block_until_ready((x3a, x3b, x4))
    with watch:
        with watch.region("f", single_program=True):
            f(x3a)                      # cold: trace + compile (expected)
            f(x3b)                      # cached re-call: silent
    s1 = watch.summary()
    if not s1["available"]:
        pytest.skip("jax.monitoring unavailable on this build")
    assert s1["by_label"]["f"]["compiles"] == 1
    assert s1["retraces"] == 0
    assert not rec.events
    with watch:
        with watch.region("f", single_program=True):
            f(x4)                       # deliberate shape change: retrace
    s2 = watch.summary()
    assert s2["by_label"]["f"]["compiles"] == 2
    assert s2["by_label"]["f"]["retraces"] == 1
    assert [e["name"] for e in rec.events] == ["retrace"]
    assert rec.events[0]["attrs"]["label"] == "f"


def test_compile_watch_plain_label_never_flags():
    watch = CompileWatch(default_label="misc")

    @jax.jit
    def g(x):
        return x + 1

    @jax.jit
    def h(x):
        return x - 1

    with watch:
        g(jnp.ones(2))
        h(jnp.ones(2))                  # second distinct program, same label
    s = watch.summary()
    if not s["available"]:
        pytest.skip("jax.monitoring unavailable on this build")
    assert s["retraces"] == 0           # plain labels only count


# ---------------------------------------------------------------------------
# report assembly + exports
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_report(lin_stats):
    rec = Recorder()
    with rec.span("solve", lanes=2):
        with rec.span("segment", index=0):
            pass
    rec.counter("segments", 1)
    return obs.build_report(recorder=rec, solver_stats=lin_stats.stats,
                            meta={"workload": "tiny"})


def test_jsonl_round_trip_exact(tiny_report):
    txt = obs.to_jsonl(tiny_report)
    for line in txt.strip().splitlines():
        json.loads(line)                # every line is standalone JSON
    assert obs.from_jsonl(txt) == tiny_report


def test_jsonl_file_round_trip(tiny_report, tmp_path):
    p = str(tmp_path / "r.jsonl")
    obs.write_jsonl(p, tiny_report)
    assert obs.read_jsonl(p) == tiny_report


def test_prometheus_exposition(tiny_report):
    text = obs.to_prometheus(tiny_report)
    assert "# TYPE br_span_seconds_total counter" in text
    assert 'br_span_seconds_total{span="solve"}' in text
    assert 'br_solver_steps_total{outcome="accepted"}' in text
    assert 'br_solver_order_steps_total{order="1"}' in text
    # no order-0 sample (structurally unused slot)
    assert 'order="0"' not in text


def test_render_and_diff(tiny_report):
    text = obs.render(tiny_report)
    assert "solve" in text and "n_accepted" in text and "order_hist" in text
    d = obs.diff(tiny_report, tiny_report)
    assert "span solve" in d            # durations differ run to run
    # counter totals identical -> no solver lines
    assert "solver n_accepted" not in d


def test_diff_pre_aot_compile_schema():
    # archived reports predating the cache accounting lack cache_hits/
    # cache_misses: a missing counter is 0, not a difference
    old = {"compile": {"compiles": 2, "retraces": 0, "compile_s": 1.0}}
    new = {"compile": {"compiles": 2, "retraces": 0, "compile_s": 1.0,
                       "cache_hits": 0, "cache_misses": 0}}
    d = obs.diff(old, new)
    assert "cache_hits" not in d and "cache_misses" not in d
    new2 = dict(new, compile={**new["compile"], "cache_hits": 3})
    assert "compile cache_hits: 0 -> 3" in obs.diff(old, new2)


def test_diff_pre_economy_solver_schema():
    # same convention for the setup-economy counters: archived reports
    # predating setup_reuses/precond_age read as 0, not as a difference
    old = {"solver_stats": {"totals": {"jac_builds": 10,
                                       "factorizations": 10}}}
    new = {"solver_stats": {"totals": {"jac_builds": 10,
                                       "factorizations": 10,
                                       "setup_reuses": 0,
                                       "precond_age": 0}}}
    d = obs.diff(old, new)
    assert "setup_reuses" not in d and "precond_age" not in d
    econ = {"solver_stats": {"totals": {"jac_builds": 10,
                                        "factorizations": 4,
                                        "setup_reuses": 6,
                                        "precond_age": 3}}}
    d2 = obs.diff(old, econ)
    assert "solver setup_reuses: 0 -> 6" in d2
    assert "solver factorizations: 10 -> 4" in d2


# ---------------------------------------------------------------------------
# API integration (the acceptance-criterion path)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def h2o2_report(fixtures_dir, tmp_path_factory):
    """One telemetry=True file-driven run on the vendored h2o2 fixture,
    shared by the API-contract tests below (the solve dominates runtime)."""
    tmp = tmp_path_factory.mktemp("obs_run")
    xml = str(tmp / "batch_h2o2.xml")
    shutil.copy(os.path.join(fixtures_dir, "batch_h2o2.xml"), xml)
    ret, report = br.batch_reactor(xml, fixtures_dir, gaschem=True,
                                   verbose=False, telemetry=True)
    return ret, report


def test_batch_reactor_telemetry_report(h2o2_report):
    ret, report = h2o2_report
    assert ret == "Success"
    assert report["schema"] == "br-obs-v1"
    names = {s["name"] for s in report["spans"]}
    assert {"parse", "solve", "write"} <= names
    totals = report["solver_stats"]["totals"]
    for key in ("n_accepted", "n_rejected", "newton_iters", "jac_builds",
                "factorizations", "order_hist"):
        assert key in totals
    assert totals["n_accepted"] > 0
    comp = report["compile"]
    assert comp is not None
    if comp["available"]:
        # under a warm persistent cache (CI restores one between runs)
        # the programs arrive as cache loads, not true compiles — either
        # way the watch must have seen them
        assert comp["compiles"] + comp["cache_hits"] >= 1
        assert comp["retraces"] == 0
    # the report is export-clean as returned
    assert obs.from_jsonl(obs.to_jsonl(report)) == report


@pytest.mark.slow
def test_batch_reactor_telemetry_off_unchanged(fixtures_dir, tmp_path):
    # slow tier (runs in full CI, not the tight tier-1 budget): compiles
    # the uninstrumented program a second time just to pin the return
    # shape; the structural guarantee itself is covered cheaply by
    # test_stats_off_is_none_and_structure_unchanged
    xml = str(tmp_path / "batch_h2o2.xml")
    shutil.copy(os.path.join(fixtures_dir, "batch_h2o2.xml"), xml)
    ret = br.batch_reactor(xml, fixtures_dir, gaschem=True, verbose=False)
    assert ret == "Success"             # bare status string, no tuple


def test_obs_report_cli(h2o2_report, tmp_path, capsys):
    _, report = h2o2_report
    path = str(tmp_path / "r.jsonl")
    obs.write_jsonl(path, report)
    # drive the CLI in-process (each subprocess would pay the full
    # jax+package import); one subprocess below proves the entry point
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    assert obs_report.main([path]) == 0
    rendered = capsys.readouterr().out
    assert "n_accepted" in rendered and "solve" in rendered
    assert obs_report.main([path, "--json"]) == 0
    for line in capsys.readouterr().out.strip().splitlines():
        json.loads(line)
    assert obs_report.main(["--diff", path, path]) == 0
    assert "obs diff" in capsys.readouterr().out


@pytest.mark.slow
def test_obs_report_cli_subprocess(h2o2_report, tmp_path):
    _, report = h2o2_report
    path = str(tmp_path / "r.jsonl")
    obs.write_jsonl(path, report)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         path], capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "n_accepted" in out.stdout


# ---------------------------------------------------------------------------
# checkpointed sweep spans
# ---------------------------------------------------------------------------
def test_checkpointed_sweep_records_spans(tmp_path):
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    # even chunks: both chunk solves share one compiled (2-lane) program
    B = 4
    y0s = jnp.tile(jnp.asarray([1.0, 2.0]), (B, 1))
    cfgs = {"T": jnp.linspace(1000.0, 1200.0, B)}
    rec = Recorder()
    res = checkpointed_sweep(_lin_rhs, y0s, 0.0, 1e-5, cfgs,
                             str(tmp_path / "ck"), chunk_size=2,
                             dt0=1e-7, recorder=rec)
    assert int(np.asarray(res.n_accepted).sum()) > 0
    agg = rec.by_name()
    assert agg["chunk_solve"]["count"] == 2      # ceil(4/2)
    assert agg["chunk_save"]["count"] == 2       # background writer spans
    solve_spans = [s for s in rec.spans if s["name"] == "chunk_solve"]
    assert solve_spans[0]["attrs"]["lanes"] == 2
    assert "attempts_mean" in solve_spans[0]["attrs"]
    # resume: loaded chunks surface as events, not solve spans
    rec2 = Recorder()
    checkpointed_sweep(_lin_rhs, y0s, 0.0, 1e-5, cfgs,
                       str(tmp_path / "ck"), chunk_size=2,
                       dt0=1e-7, recorder=rec2)
    _, events, _ = rec2.snapshot()
    assert [e["name"] for e in events].count("chunk_loaded") == 2
    assert "chunk_solve" not in rec2.by_name()


def test_checkpointed_sweep_persists_stats(tmp_path):
    """stats=True counters survive the npz chunk round-trip: the
    concatenated result carries them, and a resume (chunks loaded from
    disk, not re-solved) reports identical totals (regression: the
    first wiring computed them on device and dropped them at concat)."""
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    y0s = jnp.tile(jnp.asarray([1.0, 2.0]), (4, 1))
    cfgs = {"T": jnp.linspace(1000.0, 1200.0, 4)}
    res = checkpointed_sweep(_lin_rhs, y0s, 0.0, 1e-5, cfgs,
                             str(tmp_path / "ck"), chunk_size=2,
                             dt0=1e-7, stats=True)
    assert res.stats is not None
    tot = obs_counters.totals(res.stats)
    assert tot["n_accepted"] == int(np.asarray(res.n_accepted).sum()) > 0
    assert res.stats["order_hist"].shape == (4, bdf.MAXORD + 1)
    res2 = checkpointed_sweep(_lin_rhs, y0s, 0.0, 1e-5, cfgs,
                              str(tmp_path / "ck"), chunk_size=2,
                              dt0=1e-7, stats=True)
    assert obs_counters.totals(res2.stats) == tot
