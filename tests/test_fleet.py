"""fleet/ — the replicated serving tier (docs/serving.md "Fleet").

Four tiers, matching the subsystem's layering:

* **ring properties** — restart determinism (same member set => same
  routes, across fresh ring builds), BOUNDED CHURN (removing a member
  moves only its own keys, each to its old failover target; adding one
  moves keys only onto the joiner), pack-key affinity (one routing key
  => one member, distinct keys spread);
* **membership** — register/read round-trip over a shared fleet dir,
  heartbeat age-out, the drain handshake flag, re-registration clearing
  a stale flag;
* **router over fake members** — canned stdlib HTTP daemons (no jax, no
  solver) pin the forwarding semantics: key affinity, transport-failure
  failover with suspect demotion, ``draining`` failover, honest
  pass-through of ``invalid``/``overloaded`` (NOT retried), upload
  replication + journal replay to late joiners, and the 503 when the
  fleet is empty or exhausted;
* **end-to-end over real HTTP** on the vendored h2o2 fixture: two real
  member daemons behind a real router answer BIT-EXACT vs the direct
  ``batch_reactor_sweep`` — including after one member dies mid-fleet
  (HTTP torn down abruptly): the re-routed request carries
  ``router.failover`` provenance, matches the dead member's answer
  bit-for-bit (deterministic solves are what make exactly-once cheap),
  and the survivor serves it at zero armed compiles.
"""

import http.server
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from batchreactor_tpu.fleet import (DEFAULT_VNODES,  # noqa: E402
                                    FleetRouter, HashRing,
                                    MemberRegistration, UploadJournal,
                                    member_paths, read_members,
                                    request_key)
from batchreactor_tpu.serving import schema  # noqa: E402


# --------------------------------------------------------------------------
# ring properties
# --------------------------------------------------------------------------
def _keys(n):
    # realistic routing keys: mechanism id x t1 spread (the serve_bench
    # --t1-choices shape), not opaque strings
    return [(f"mech{i % 3}", 1e-5 * (1 + i), None, None, None)
            for i in range(n)]


class TestHashRing:
    def test_restart_determinism(self):
        """Same member set => identical routes from two independently
        built rings (sha256, not python's per-process-salted hash) —
        the on-disk AOT caches outlive a router, so a restarted router
        must send each key back to the member already holding it warm."""
        members = [f"m{i}" for i in range(5)]
        a = HashRing(members)
        b = HashRing(reversed(members))     # order must not matter
        for key in _keys(300):
            assert a.route(key) == b.route(key)
            assert a.preference(key) == b.preference(key)

    def test_bounded_churn_on_removal(self):
        """Removing one member moves ONLY the keys it owned, and each
        moves to its old failover target (preference[1]) — a death
        re-assigns arcs, it does not reshuffle the fleet."""
        ring = HashRing([f"m{i}" for i in range(5)])
        gone = "m2"
        small = ring.with_members(set(ring.members()) - {gone})
        moved = 0
        for key in _keys(400):
            before = ring.preference(key)
            after = small.route(key)
            if before[0] == gone:
                moved += 1
                assert after == before[1]
            else:
                assert after == before[0]
        assert moved > 0    # the sample actually exercised the arcs

    def test_bounded_churn_on_join(self):
        """Adding a member moves keys only ONTO the joiner — nobody
        else's warm state is disturbed."""
        ring = HashRing(["m0", "m1", "m2"])
        grown = ring.with_members(list(ring.members()) + ["m3"])
        joined = 0
        for key in _keys(400):
            before, after = ring.route(key), grown.route(key)
            if after != before:
                joined += 1
                assert after == "m3"
        assert 0 < joined < 400     # some keys moved, most stayed

    def test_pack_key_affinity_and_spread(self):
        """One routing key always lands on one member; a realistic
        key spread (3 mechanisms x many horizons) reaches EVERY member
        of a small fleet (64 vnodes keep arcs even enough)."""
        ring = HashRing(["m0", "m1", "m2", "m3"])
        hit = set()
        for key in _keys(60):
            owner = ring.route(key)
            assert all(ring.route(key) == owner for _ in range(3))
            hit.add(owner)
        assert hit == set(ring.members())
        shares = ring.arc_share(samples=2048)
        assert all(0.05 < v < 0.60 for v in shares.values()), shares

    def test_preference_is_distinct_and_complete(self):
        ring = HashRing(["a", "b", "c"])
        for key in _keys(50):
            prefs = ring.preference(key)
            assert sorted(prefs) == ["a", "b", "c"]
            assert prefs[0] == ring.route(key)
        assert ring.preference(_keys(1)[0], n=2) == ring.preference(
            _keys(1)[0])[:2]

    def test_empty_and_vnodes(self):
        assert HashRing(()).route(("k",)) is None
        assert HashRing(()).preference(("k",)) == []
        assert HashRing(["m"], vnodes=4).vnodes == 4
        assert HashRing(["m"]).vnodes == DEFAULT_VNODES

    def test_request_key_peek(self):
        assert request_key({"t1": 1e-4, "mech": "gri"}) == (
            "gri", 1e-4, None, None, None)
        assert request_key("not a dict") == ("invalid",)


# --------------------------------------------------------------------------
# membership
# --------------------------------------------------------------------------
class TestMembership:
    def test_register_read_roundtrip(self, tmp_path):
        d = str(tmp_path)
        reg = MemberRegistration(d, "m1", "http://127.0.0.1:1234",
                                 pid=4242, heartbeat_s=0.05)
        with reg:
            members = read_members(d, dead_after_s=5.0)
            assert [m["name"] for m in members] == ["m1"]
            m = members[0]
            assert m["url"] == "http://127.0.0.1:1234"
            assert m["pid"] == 4242
            assert m["alive"] and not m["draining"] and m.routable
        # context exit = drain handshake + deregister
        assert read_members(d, dead_after_s=5.0) == []

    def test_heartbeat_age_out(self, tmp_path):
        d = str(tmp_path)
        reg = MemberRegistration(d, "m1", "u", heartbeat_s=0.02)
        reg.register()
        assert read_members(d, dead_after_s=2.0)[0].routable
        reg._hb.stop()      # the daemon wedged/died: beats stop
        time.sleep(0.25)
        m = read_members(d, dead_after_s=0.1)[0]
        assert not m["alive"] and not m.routable
        assert m["age_s"] >= 0.1
        reg.deregister()

    def test_drain_flag_and_reregistration(self, tmp_path):
        d = str(tmp_path)
        reg = MemberRegistration(d, "m1", "u", heartbeat_s=0.05)
        reg.register()
        reg.mark_draining()
        m = read_members(d, dead_after_s=5.0)[0]
        assert m["draining"] and m["alive"] and not m.routable
        reg.deregister()
        # the drain flag outlives deregistration on purpose (metrics
        # snapshots do too); a RE-registration must clear it
        assert os.path.exists(member_paths(d, "m1")[2])
        reg2 = MemberRegistration(d, "m1", "u2", heartbeat_s=0.05)
        reg2.register()
        assert read_members(d, dead_after_s=5.0)[0].routable
        reg2.deregister()

    def test_torn_registration_skipped(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(os.path.join(d, "members"), exist_ok=True)
        with open(os.path.join(d, "members", "bad.json"), "w") as f:
            f.write("{not json")
        assert read_members(d) == []


class TestUploadJournal:
    def test_latest_per_id_in_first_accepted_order(self):
        j = UploadJournal()
        j.record({"id": "a", "mech": "1", "therm": "t", "warm": True})
        j.record({"id": "b", "mech": "2", "therm": "t", "warm": True})
        j.record({"id": "a", "mech": "3", "therm": "t", "warm": True})
        assert j.ids() == ["a", "b"]
        assert [u["mech"] for u in j.replay()] == ["3", "2"]


# --------------------------------------------------------------------------
# router over fake members (no jax, no solver — semantics only)
# --------------------------------------------------------------------------
class FakeMember:
    """A canned member daemon: real stdlib HTTP + real membership, no
    solver.  ``/solve`` answers ok (recording the request id) unless
    scripted with ``error=(status, code)``; ``/mechanism`` records the
    upload and answers an admission receipt.  ``kill_http()`` tears the
    server down ABRUPTLY while the heartbeat keeps beating — the
    pre-age-out death window the failover path exists for."""

    def __init__(self, fleet_dir, name, error=None, heartbeat_s=0.05):
        self.name = name
        self.error = error
        self.solved = []
        self.requests = []      # full /solve bodies, as received
        self.uploads = []
        outer = self

        class _H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(n).decode())
                if self.path == "/mechanism":
                    outer.uploads.append(obj["id"])
                    status, body = 200, schema.ok_response(
                        obj["id"], {"fingerprint": f"fp-{obj['mech']}"})
                elif outer.error is not None:
                    outer.requests.append(obj)
                    status, code = outer.error
                    body = schema.error_response(obj.get("id"), code,
                                                 "canned")
                else:
                    outer.requests.append(obj)
                    outer.solved.append(obj.get("id"))
                    status, body = 200, schema.ok_response(
                        obj.get("id"), {"served_by": outer.name})
                payload = (json.dumps(body) + "\n").encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *_a):
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), _H)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}"
        self.membership = MemberRegistration(
            fleet_dir, name, self.url, pid=f"fake-{name}",
            heartbeat_s=heartbeat_s).register()

    def kill_http(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread.join()
            self._server = None

    def close(self):
        self.kill_http()
        self.membership.deregister()


@pytest.fixture()
def fleet_dir(tmp_path):
    return str(tmp_path / "fleet")


def _router(fleet_dir, **kw):
    # refresh_s=0: tests mutate membership and expect the next call to
    # see it (the TTL is a production knob, not a semantics one)
    kw.setdefault("refresh_s", 0.0)
    kw.setdefault("dead_after_s", 30.0)
    kw.setdefault("request_timeout", 5.0)
    return FleetRouter(fleet_dir, **kw)


def _solve_req(i=0, t1=1e-4):
    return {"id": f"r{i}", "T": [1200.0], "X": {"H2": 1.0}, "t1": t1}


class TestRouterSemantics:
    def test_key_affinity_across_members(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        b = FakeMember(fleet_dir, "b")
        try:
            router = _router(fleet_dir)
            # one key -> one member, every time
            for i in range(6):
                status, resp = router.solve(_solve_req(i, t1=1e-4))
                assert status == 200 and resp["status"] == "ok"
                assert not resp["router"]["failover"]
            hosts = {resp["router"]["host"]}
            assert len(a.solved or b.solved) == 6
            # a t1 spread reaches both members (the serve_bench
            # --t1-choices rationale)
            for i in range(40):
                _s, r = router.solve(_solve_req(100 + i, t1=1e-6 * (i + 1)))
                hosts.add(r["router"]["host"])
            assert hosts == {"a", "b"}
        finally:
            a.close()
            b.close()

    def test_failover_on_transport_death(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        b = FakeMember(fleet_dir, "b")
        try:
            router = _router(fleet_dir)
            _s, first = router.solve(_solve_req(0))
            primary = first["router"]["host"]
            dead, survivor = ((a, b) if primary == "a" else (b, a))
            # abrupt death: HTTP gone, heartbeat still fresh (the
            # pre-age-out window) — the router must fail over, answer
            # exactly once, and say so in the provenance
            dead.kill_http()
            status, resp = router.solve(_solve_req(1))
            assert status == 200 and resp["status"] == "ok"
            assert resp["served_by"] == survivor.name
            assert resp["router"] == {"host": survivor.name,
                                      "attempts": 2, "failover": True,
                                      "tried": [dead.name]}
            # the dead member is now suspect: the next forward skips it
            status, resp = router.solve(_solve_req(2))
            assert status == 200
            assert resp["router"]["failover"] is False
            assert resp["router"]["host"] == survivor.name
            counters = router.recorder.snapshot()[2]
            assert counters["route_failovers"] == 1
            assert counters["route_requests"] == 3
            assert router.healthz()["router"]["suspects"] == [dead.name]
        finally:
            a.close()
            b.close()

    def test_draining_response_fails_over(self, fleet_dir):
        a = FakeMember(fleet_dir, "a", error=(503, "draining"))
        b = FakeMember(fleet_dir, "b", error=(503, "draining"))
        try:
            router = _router(fleet_dir)
            _s, first = router.solve(_solve_req(0))
            assert first["status"] == "error"    # both draining: honest 503
            primary = ((first.get("error") or {}).get("message"))
            assert "failed" in primary
            # revive one: the drain-window race resolves to the survivor
            b.error = None
            status, resp = router.solve(_solve_req(1))
            assert status == 200 and resp["served_by"] == "b"
            if resp["router"]["host"] != resp.get("served_by"):
                pytest.fail(f"provenance mismatch: {resp['router']}")
        finally:
            a.close()
            b.close()

    def test_honest_errors_pass_through_without_retry(self, fleet_dir):
        a = FakeMember(fleet_dir, "a", error=(503, "overloaded"))
        b = FakeMember(fleet_dir, "b", error=(503, "overloaded"))
        try:
            router = _router(fleet_dir)
            status, resp = router.solve(_solve_req(0))
            # overloaded is the member's honest backpressure — retrying
            # it elsewhere would double-serve a request the client will
            # retry itself; it passes through with attempt count 1
            assert status == 503
            assert resp["error"]["code"] == "overloaded"
            assert resp["router"]["attempts"] == 1
            assert not resp["router"]["failover"]
            assert a.solved == b.solved == []
            counters = router.recorder.snapshot()[2]
            assert counters["route_upstream_errors"] == 1
            assert "route_failovers" not in counters
        finally:
            a.close()
            b.close()

    def test_empty_fleet_503(self, fleet_dir):
        router = _router(fleet_dir)
        status, resp = router.solve(_solve_req(0))
        assert status == 503
        assert resp["error"]["code"] == "internal"
        assert "no routable fleet members" in resp["error"]["message"]
        counters = router.recorder.snapshot()[2]
        assert counters["route_no_members"] == 1

    def test_upload_replicates_to_all_members(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        b = FakeMember(fleet_dir, "b")
        try:
            router = _router(fleet_dir)
            up = {"id": "gri", "mech": "MECHTEXT", "therm": "THERMTEXT"}
            status, resp = router.upload(dict(up))
            assert status == 200 and resp["status"] == "ok"
            assert resp["replicated"] == ["a", "b"]
            assert resp["failed"] == []
            assert resp["fingerprint"] == "fp-MECHTEXT"
            assert a.uploads == b.uploads == ["gri"]
        finally:
            a.close()
            b.close()

    def test_upload_partial_failure_is_loud(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        b = FakeMember(fleet_dir, "b")
        try:
            router = _router(fleet_dir)
            b.kill_http()
            status, resp = router.upload(
                {"id": "gri", "mech": "M", "therm": "T"})
            assert status == 500
            assert resp["error"]["code"] == "internal"
            assert resp["replication"]["replicated"] == ["a"]
            assert resp["replication"]["failed"] == ["b"]
        finally:
            a.close()
            b.close()

    def test_late_joiner_absorbs_journal_before_routing(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            router.upload({"id": "gri", "mech": "M", "therm": "T"})
            router.upload({"id": "gri", "mech": "M2", "therm": "T"})
            router.upload({"id": "ni", "mech": "N", "therm": "T"})
            assert a.uploads == ["gri", "gri", "ni"]
            b = FakeMember(fleet_dir, "b")
            try:
                # the next view must replay the CURRENT set (latest per
                # id) to b before it can own an arc
                assert "b" in router.healthz()["router"]["routable"]
                assert b.uploads == ["gri", "ni"]
                assert router.healthz()["router"]["uploads"] == [
                    "gri", "ni"]
            finally:
                b.close()
        finally:
            a.close()

    def test_invalid_upload_and_empty_fleet_upload(self, fleet_dir):
        router = _router(fleet_dir)
        status, resp = router.upload({"id": "x"})     # no mech/therm
        assert status == 400 and resp["error"]["code"] == "invalid"
        status, resp = router.upload(
            {"id": "x", "mech": "M", "therm": "T"})
        assert status == 503 and resp["error"]["code"] == "internal"

    def test_metrics_and_healthz_surfaces(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            router.solve(_solve_req(0))
            text = router.metrics_text()
            # the obs/counters.py FAMILIES enrollment: router counters
            # and the route_seconds histogram are first-class families
            assert "route_requests" in text
            assert "route_seconds" in text
            h = router.healthz()
            assert h["ok"] is True
            assert h["router"]["routable"] == ["a"]
            assert abs(sum(h["router"]["arc_share"].values()) - 1.0) < 0.01
            # membership gauges published on the view refresh
            assert "fleet_members_routable" in text
        finally:
            a.close()

    def test_member_death_ages_out_of_ring(self, fleet_dir):
        a = FakeMember(fleet_dir, "a", heartbeat_s=0.02)
        b = FakeMember(fleet_dir, "b", heartbeat_s=0.02)
        try:
            router = _router(fleet_dir, dead_after_s=0.15)
            assert sorted(router.healthz()["router"]["routable"]) == [
                "a", "b"]
            a.membership._hb.stop()     # a stops beating (wedged/dead)
            time.sleep(0.4)
            h = router.healthz()
            assert h["router"]["routable"] == ["b"]
            # arcs reassigned: every key now routes to b, no failover
            for i in range(4):
                status, resp = router.solve(_solve_req(i, t1=1e-6 * (i + 1)))
                assert status == 200
                assert resp["router"]["host"] == "b"
                assert not resp["router"]["failover"]
            counters = router.recorder.snapshot()[2]
            assert counters["fleet_members_joined"] == 2
            assert counters["fleet_members_left"] == 1
        finally:
            a.close()
            b.close()


class TestRouterTracing:
    """Distributed tracing through the router (docs/observability.md
    "Fleet tracing"): context minting/forwarding, the hop ledger, the
    terminal events error-rate SLOs count, and the ctx-less
    byte-identity contract the acceptance pins."""

    def _trace_events(self, router):
        _s, events, _c = router.recorder.snapshot()
        return [e["attrs"] for e in events
                if e["name"] == "request_trace"]

    def test_ctxless_request_minted_and_response_byte_identical(
            self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            status, resp = router.solve(_solve_req(0))
            assert status == 200
            # byte-identity: the RESPONSE carries no trace ids and the
            # router section is EXACTLY the pre-tracing dict
            assert resp["router"] == {"host": "a", "attempts": 1,
                                      "failover": False, "tried": []}
            assert set(resp) == {"v", "id", "status", "served_by",
                                 "router"}
            # ...but the member received a minted context, hop 1
            fwd = a.requests[0]["trace_ctx"]
            assert fwd["trace"].startswith("r-")
            assert fwd["span"] == "route:1" and fwd["hop"] == 1
            (ev,) = self._trace_events(router)
            assert ev["minted"] is True
            assert ev["trace"] == fwd["trace"]
            assert ev["host"] == "a" and "code" not in ev
            assert [h["outcome"] for h in ev["hops"]] == ["ok"]
            hop = ev["hops"][0]
            assert hop["member"] == "a" and hop["hop"] == 1
            assert hop["send_wall"] <= hop["recv_wall"]
        finally:
            a.close()

    def test_inherited_ctx_forwarded_with_hop_advance(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            obj = _solve_req(0)
            obj["trace_ctx"] = schema.trace_ctx_payload(
                "t-cli", span="client", hop=3)
            status, _resp = router.solve(obj)
            assert status == 200
            fwd = a.requests[0]["trace_ctx"]
            assert fwd == {"v": schema.TRACE_CTX_VERSION,
                           "trace": "t-cli", "span": "route:4",
                           "hop": 4}
            (ev,) = self._trace_events(router)
            assert ev["minted"] is False
            assert ev["trace"] == "t-cli"
            assert ev["parent_span"] == "client" and ev["hop"] == 3
        finally:
            a.close()

    def test_invalid_ctx_rejected_and_counted(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            obj = _solve_req(0)
            obj["trace_ctx"] = {"trace": "t", "bogus": 1}
            status, resp = router.solve(obj)
            assert status == 400
            assert resp["error"]["code"] == "invalid"
            assert a.requests == []     # rejected before any forward
            (ev,) = self._trace_events(router)
            assert ev["failed"] is True and ev["code"] == "invalid"
            assert ev["hops"] == []
            # the rejection is an SLO sample: error-rate counts it
            res = router.slo.evaluate()
            assert res["error_rate"]["bad"] == 1
        finally:
            a.close()

    def test_error_responses_emit_terminal_trace_events(self,
                                                        fleet_dir):
        """ISSUE-18 satellite: every router error path — upstream
        rejection, empty fleet — lands ONE terminal ``request_trace``
        with its rejection code, so error-rate SLOs see what the
        response alone would hide."""
        a = FakeMember(fleet_dir, "a", error=(503, "overloaded"))
        try:
            router = _router(fleet_dir)
            status, _resp = router.solve(_solve_req(0))
            assert status == 503
            (ev,) = self._trace_events(router)
            assert ev["failed"] is True and ev["code"] == "overloaded"
            assert ev["host"] == "a"
            assert [h["outcome"] for h in ev["hops"]] == ["overloaded"]
            a.close()
            router._view(force=True)
            status, _resp = router.solve(_solve_req(1))
            assert status == 503
            evs = self._trace_events(router)
            assert evs[-1]["code"] == "internal"
            assert evs[-1]["hops"] == []
            res = router.slo.evaluate()
            assert res["error_rate"]["bad"] == 2
        finally:
            a.close()

    def test_failover_hop_ledger_is_one_trace(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        b = FakeMember(fleet_dir, "b")
        try:
            router = _router(fleet_dir)
            _s, first = router.solve(_solve_req(0))
            dead, survivor = ((a, b) if first["router"]["host"] == "a"
                              else (b, a))
            dead.kill_http()
            status, resp = router.solve(_solve_req(1))
            assert status == 200
            ev = self._trace_events(router)[-1]
            assert ev["failover"] is True
            assert ev["tried"] == resp["router"]["tried"] == [dead.name]
            assert [(h["member"], h["hop"], h["outcome"])
                    for h in ev["hops"]] == [
                (dead.name, 1, "transport"), (survivor.name, 2, "ok")]
            # both hops under ONE trace id, which the survivor received
            assert survivor.requests[-1]["trace_ctx"]["trace"] \
                == ev["trace"]
            assert survivor.requests[-1]["trace_ctx"]["span"] \
                == "route:2"
            res = router.slo.evaluate()
            assert res["failover_rate"]["bad"] == 1
        finally:
            a.close()
            b.close()

    def test_metrics_text_carries_slo_gauges(self, fleet_dir):
        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            router.solve(_solve_req(0))
            text = router.metrics_text()
            assert "# TYPE br_slo_burn_rate gauge" in text
            assert 'br_slo_requests{window="slow"} 1' in text
            assert 'br_slo_alert{objective="error_rate"} 0' in text
            # the base exposition is intact alongside
            assert "route_requests" in text
        finally:
            a.close()


class TestFleetSnapshotMergeLateJoiner:
    def test_member_snapshot_without_histograms_merges_as_empty(
            self, fleet_dir):
        """ISSUE-18 satellite: a member snapshot missing the
        ``histograms`` key entirely (a late joiner that has not
        observed yet, or a pre-histogram writer) merges as EMPTY
        through the router's /metrics fleet exposition — never a
        KeyError, never a fabricated series."""
        from batchreactor_tpu.obs.live import (LiveRegistry,
                                               write_fleet_snapshot)
        from batchreactor_tpu.obs.recorder import Recorder

        a = FakeMember(fleet_dir, "a")
        try:
            router = _router(fleet_dir)
            rec = Recorder()
            rec.counter("serve_answered", 2)
            for d in (0.01, 0.04):
                rec.observe("serve_stage_seconds", d, stage="total")
            write_fleet_snapshot(fleet_dir, 1,
                                 LiveRegistry(recorder=rec))
            # the late joiner: counters only, no "histograms" key
            hosts = os.path.join(fleet_dir, "hosts")
            os.makedirs(hosts, exist_ok=True)
            with open(os.path.join(hosts, "p2.metrics.json"),
                      "w") as f:
                json.dump({"pid": 2, "time": time.time(),
                           "counters": {"serve_answered": 1},
                           "gauges": {}}, f)
            text = router.metrics_text()
            # merged family = exactly the ONE host's observations
            assert ('br_fleet_serve_stage_seconds_count'
                    '{stage="total"} 2') in text
            # both hosts' counters still merged
            assert 'host="p1",name="serve_answered"' in text
            assert 'host="p2",name="serve_answered"' in text
        finally:
            a.close()


# --------------------------------------------------------------------------
# end-to-end: two real daemons + router over real HTTP, h2o2 fixture
# --------------------------------------------------------------------------
_COMP = {"H2": 0.3, "O2": 0.15, "N2": 0.55}


def _fleet_spec(lib_dir):
    # the test_serving.py bit-exactness recipe: single-rung ladder [8]
    # + a coalesce window wide enough that every concurrent request
    # joins the seed — both members AND the direct sweep run ONE
    # program shape, so answers are bit-identical across hosts
    return {"mechanism": {"mech": f"{lib_dir}/h2o2.dat",
                          "therm": f"{lib_dir}/therm.dat"},
            "solver": {"segment_steps": 8, "stats": True},
            "serve": {"resident": 8, "refill": 1, "buckets": [8],
                      "poll_every": 1, "max_queue_lanes": 64,
                      "idle_timeout_s": 0.3, "coalesce_s": 2.0}}


@pytest.fixture(scope="module")
def live_fleet(lib_dir, tmp_path_factory):
    from batchreactor_tpu.serving.scheduler import Scheduler
    from batchreactor_tpu.serving.server import ServingServer
    from batchreactor_tpu.serving.session import SolverSession

    fdir = str(tmp_path_factory.mktemp("fleet"))
    hosts = {}
    for name in ("m1", "m2"):
        session = SolverSession.from_spec(_fleet_spec(lib_dir))
        session.warmup()
        session.__enter__()
        srv = ServingServer(session, Scheduler(session)).start()
        srv.membership = MemberRegistration(
            fdir, name, srv.url, pid=f"e2e-{name}",
            registry=session.registry, heartbeat_s=0.1).register()
        hosts[name] = (session, srv)
    # dead_after_s=60: an abruptly killed member STAYS in the ring for
    # the whole test — wave 2 must exercise the failover path, not the
    # age-out path
    router = FleetRouter(fdir, dead_after_s=60.0, refresh_s=0.0,
                         request_timeout=120.0).start()
    yield router, hosts
    router.close()
    for name, (session, srv) in hosts.items():
        try:
            srv.close(drain_timeout=10.0)
        except Exception:       # noqa: BLE001 — the killed member's
            pass                # HTTP is already gone
        try:
            srv.membership.deregister()
        except Exception:       # noqa: BLE001
            pass
        session.__exit__(None, None, None)


class TestFleetEndToEnd:
    def test_bit_exact_through_router_and_after_member_death(
            self, live_fleet):
        """Acceptance: the same 8-lane request through the router is
        bit-exact vs the direct sweep — before AND after its serving
        member dies abruptly (the survivor's deterministic solve IS the
        answer, delivered exactly once with failover provenance)."""
        import batchreactor_tpu as br
        from batchreactor_tpu.serving.client import SolveClient

        router, hosts = live_fleet
        client = SolveClient(router.url, timeout=120.0)
        N, t1 = 8, 5e-5
        Ts = [1150.0 + 37.0 * i for i in range(N)]
        req = {"T": Ts, "X": _COMP, "t1": t1}

        # ---- wave 1: routed direct ----------------------------------
        resp1 = client.solve({"id": "w1", **req})
        assert resp1["status"] == "ok"
        assert resp1["provenance"] == ["success"] * N
        assert resp1["router"]["failover"] is False
        assert resp1["router"]["attempts"] == 1
        served_by = resp1["router"]["host"]
        assert served_by in hosts

        # ---- the reference: one direct sweep, same conditions --------
        session = hosts[served_by][0]
        out = br.batch_reactor_sweep(
            _COMP, np.asarray(Ts), 1e5, t1,
            chem=br.Chemistry(gaschem=True), thermo_obj=session.thermo,
            md=session.gm, segment_steps=8, admission=8, refill=1,
            buckets=(8,), poll_every=1)
        np.testing.assert_array_equal(resp1["t"], np.asarray(out["t"]))
        for sp in session.species:
            np.testing.assert_array_equal(
                resp1["x"][sp], np.asarray(out["x"][sp]), err_msg=sp)

        # ---- kill the serving member ABRUPTLY ------------------------
        # (HTTP torn down, heartbeat still beating: the pre-age-out
        # window; no drain handshake — this is the crash path)
        dead_srv = hosts[served_by][1]
        dead_srv._server.shutdown()
        dead_srv._server.server_close()
        dead_srv._thread.join()
        dead_srv._server = dead_srv._thread = None
        (survivor_name,) = [n for n in hosts if n != served_by]

        # ---- wave 2: same key re-routes, bit-exact, exactly once -----
        resp2 = client.solve({"id": "w2", **req})
        assert resp2["status"] == "ok"
        assert resp2["provenance"] == ["success"] * N
        assert resp2["router"]["failover"] is True
        assert resp2["router"]["attempts"] == 2
        assert resp2["router"]["tried"] == [served_by]
        assert resp2["router"]["host"] == survivor_name
        np.testing.assert_array_equal(resp2["t"], resp1["t"])
        for sp in session.species:
            np.testing.assert_array_equal(
                resp2["x"][sp], resp1["x"][sp], err_msg=sp)

        # ---- the survivor served it WARM -----------------------------
        survivor = hosts[survivor_name][0]
        prog = survivor.program_compiles()
        assert all(v == 0 for v in prog.values()), prog

        # ---- router provenance counters ------------------------------
        counters = router.recorder.snapshot()[2]
        assert counters["route_failovers"] >= 1
        assert counters["route_requests"] >= 2

    def test_failover_chain_stitches_into_one_trace(self, live_fleet):
        """Acceptance: a traced request whose serving member is dead
        (abrupt HTTP teardown, heartbeat still fresh) stitches into ONE
        fleet-wide trace — the router's span, the dead member's
        ledger-only attempt, and the survivor's full stage waterfall —
        with hop provenance matching the response's ``router.tried``."""
        from batchreactor_tpu.obs import build_report
        from batchreactor_tpu.obs.stitch import stitch
        from batchreactor_tpu.serving.client import (SolveClient,
                                                     with_trace_ctx)

        router, hosts = live_fleet
        client = SolveClient(router.url, timeout=120.0)
        req = {"T": [1150.0 + 37.0 * i for i in range(8)],
               "X": _COMP, "t1": 5e-5}

        # the key's owner must be DEAD when the traced request lands;
        # the earlier test already killed it — kill it here if this
        # test runs alone
        probe = client.solve({"id": "wt-probe", **req})
        dead_name = next((n for n, (_s, srv) in hosts.items()
                          if srv._server is None), None)
        if dead_name is None:
            dead_name = probe["router"]["host"]
            srv = hosts[dead_name][1]
            srv._server.shutdown()
            srv._server.server_close()
            srv._thread.join()
            srv._server = srv._thread = None
        (survivor,) = [n for n in hosts if n != dead_name]
        # clear the suspect demotion so the dead owner is tried FIRST
        # again — the failover must happen INSIDE this trace
        with router._lock:
            router._suspects.clear()

        resp = client.solve(with_trace_ctx({"id": "wt", **req}))
        assert resp["status"] == "ok"
        assert resp["router"]["failover"] is True
        assert resp["router"]["tried"] == [dead_name]
        # tracing never leaks into the response
        assert "trace" not in resp and "trace_ctx" not in resp

        reports = [(name, sess.obs_report())
                   for name, (sess, _srv) in hosts.items()]
        reports.append(("router",
                        build_report(recorder=router.recorder)))
        stitched = stitch(reports)
        (t,) = [t for t in stitched if t["request"] == "wt"]
        assert t["trace"] == "t-wt"     # with_trace_ctx derivation
        assert t["minted"] is False
        assert t["failover"] is True
        assert t["tried"] == resp["router"]["tried"]
        assert t["host"] == survivor
        assert [(h["member"], h["outcome"]) for h in t["hops"]] == [
            (dead_name, "transport"), (survivor, "ok")]
        dead_hop, ok_hop = t["hops"]
        assert "member_trace" not in dead_hop   # ledger-only attempt
        mt = ok_hop["member_trace"]
        assert mt["parent_span"] == "route:2"
        assert set(mt["stages"]) >= {"submitted", "admitted",
                                     "resolved"}
        assert "skew_s" in ok_hop and "wall_start_corrected" in ok_hop
        # the member's solve fits inside the router's wall bracket
        assert abs(ok_hop["skew_s"]) < 5.0
        assert mt["total_s"] <= (ok_hop["recv_wall"]
                                 - ok_hop["send_wall"]) + 1e-3

    def test_fleet_metrics_merge_members(self, live_fleet):
        """The router /metrics carries the PR-9 fleet merge: both
        members' heartbeat snapshots appear (per-host + merged), plus
        the router's own route_* families."""
        import urllib.request

        router, _hosts = live_fleet
        time.sleep(0.3)     # >= one heartbeat: snapshots on disk
        with urllib.request.urlopen(router.url + "/metrics",
                                    timeout=10.0) as r:
            text = r.read().decode()
        assert "route_requests" in text
        assert "fleet" in text
        # per-host sections for both registered pids
        assert "e2e-m1" in text or "m1" in text
        h = router.healthz()
        assert h["router"]["fleet_dir"]
