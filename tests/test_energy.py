"""energy/ subsystem tests (ISSUE 14 acceptance gates).

Covers: the adiabatic RHS/analytic-Jacobian exactness (both modes,
vs ``jax.jacfwd`` to roundoff); the ``energy=`` grammar (loud errors
naming the accepted literals, incompatible-knob rejections); adiabatic
constant-volume h2o2 ignition end-to-end through ``batch_reactor_sweep``
(monolithic == segmented bit-exact at jac_window=1; admission parity;
``out["T"]`` / ``out["ignition_delay"]`` semantics); padded-vs-unpadded
step-count identity with the T row live; the energy-off structure guard
(energy=None changes neither the result surface nor the traced solver
program); checkpoint-resume with the energy fingerprint pin
(SCHEMA_KNOBS); FD-golden dtau_ign/d(lnA) for the forward-IFT and
adjoint gradient passes (tol-tiered like tests/test_sensitivity.py);
and the serving-plane grammar (schema literals, pack-key isolation,
request-lane packing parity).

Everything runs on the CPU backend (conftest pins it) against
tests/fixtures — no reference checkout needed.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.api import Chemistry, batch_reactor_sweep
from batchreactor_tpu.energy import (DEFAULT_ATOL_T, ENERGY_MODES, eqns,
                                     ignition)
from batchreactor_tpu.models.gas import compile_gaschemistry
from batchreactor_tpu.models.thermo import create_thermo
from batchreactor_tpu.sensitivity import adjoint, params
from batchreactor_tpu.solver import bdf
from batchreactor_tpu.solver.sdirk import (ATOL_SCALE_KEY, SUCCESS,
                                           _scaled_norm)
from batchreactor_tpu.utils.composition import density, mole_to_mass

X_MIX = {"H2": 0.3, "O2": 0.2, "N2": 0.5}


@pytest.fixture(scope="module")
def h2o2(fixtures_dir):
    gm = compile_gaschemistry(os.path.join(fixtures_dir, "h2o2.dat"))
    th = create_thermo(list(gm.species), os.path.join(fixtures_dir,
                                                      "therm.dat"))
    sp = list(gm.species)
    x = np.zeros(len(sp))
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.3, 0.2, 0.5
    x = jnp.asarray(x, dtype=jnp.float64)
    y_gas = density(x, th.molwt, 1100.0, 1e5) * mole_to_mass(x, th.molwt)
    y0e = jnp.concatenate([y_gas, jnp.asarray([1100.0])])
    return gm, th, sp, y_gas, y0e


@pytest.fixture(scope="module")
def energy_theta(h2o2):
    """3-reaction log_A selection over the ADIABATIC constant-volume
    RHS — the physical-ignition-gradient fixture."""
    gm, th, sp, _, _ = h2o2
    spec = params.select(gm, fields=("log_A",), reactions=(0, 1, 5))
    theta = params.extract(gm, spec)
    rhs_theta = params.make_rhs_theta(
        gm, spec, lambda m: eqns.make_energy_rhs(m, th, "adiabatic_v"))

    def jac_theta(t, y, theta, cfg):
        return eqns.make_energy_jac(params.apply(gm, theta, spec), th,
                                    "adiabatic_v")(t, y, cfg)

    return spec, theta, rhs_theta, jac_theta


# ---------------------------------------------------------------------------
# equations: RHS physics + analytic-Jacobian exactness
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ENERGY_MODES)
def test_energy_jacobian_matches_jacfwd(h2o2, mode):
    gm, th, sp, _, y0e = h2o2
    rhs = eqns.make_energy_rhs(gm, th, mode)
    jac = eqns.make_energy_jac(gm, th, mode)
    cfg = {}
    Ja = np.asarray(jac(0.0, y0e, cfg))
    Jf = np.asarray(jax.jacfwd(lambda y: rhs(0.0, y, cfg))(y0e))
    scale = np.abs(Jf) + 1e-6 * np.max(np.abs(Jf))
    assert np.max(np.abs(Ja - Jf) / scale) < 1e-11


@pytest.mark.parametrize("mode", ENERGY_MODES)
def test_energy_rhs_species_rows(h2o2, mode):
    """The species block closes on the isothermal production rates: at
    constant volume exactly; at constant pressure up to the dilution
    term (which sums to the thermal-expansion closure)."""
    from batchreactor_tpu.ops.rhs import make_gas_rhs

    gm, th, sp, y_gas, y0e = h2o2
    dy = eqns.make_energy_rhs(gm, th, mode)(0.0, y0e, {})
    iso = make_gas_rhs(gm, th)(0.0, y_gas, {"T": jnp.asarray(1100.0)})
    if mode == "adiabatic_v":
        np.testing.assert_array_equal(np.asarray(dy[:-1]), np.asarray(iso))
    else:
        # dilution preserves Ctot = p/(RT): d(sum c)/dt == -Ctot/T dT/dt
        conc_dot = np.asarray(dy[:-1]) / np.asarray(th.molwt)
        Ctot = float(jnp.sum(y0e[:-1] / th.molwt))
        assert np.isclose(conc_dot.sum(),
                          -Ctot / 1100.0 * float(dy[-1]), rtol=1e-10)


def test_resolve_energy_grammar():
    assert eqns.resolve_energy(None) is None
    assert eqns.resolve_energy(False) is None
    assert eqns.resolve_energy("adiabatic_v") == "adiabatic_v"
    with pytest.raises(ValueError, match="adiabatic_v.*adiabatic_p"):
        eqns.resolve_energy("isothermal")
    # the schema's jax-free duplicate must never drift from the one rule
    from batchreactor_tpu.serving import schema

    assert tuple(schema.ENERGY_MODES) == tuple(ENERGY_MODES)


def test_atol_scale_norm_weighting():
    """The T-row weight enters the scaled norm exactly as atol * w."""
    e = jnp.asarray([1e-8, 1e-8, 1.0])
    y = jnp.zeros(3)
    w = jnp.asarray([1.0, 1.0, 1e6])
    plain = _scaled_norm(e, y, 1e-6, 1e-10)
    weighted = _scaled_norm(e, y, 1e-6, 1e-10, None, w)
    # hand-rolled reference: scale = atol*w + rtol*|y|
    expect = float(jnp.sqrt(jnp.mean(
        jnp.square(e / (1e-10 * w + 1e-6 * jnp.abs(y))))))
    assert np.isclose(float(weighted), expect, rtol=1e-12)
    # the big T-row error is forgiven by its big atol (factor ~1e6)
    assert float(weighted) < float(plain) / 1e5
    with pytest.raises(ValueError, match="atol_T"):
        eqns.energy_atol_scale(2, 4, 1e-10, atol_T=-1.0)


def test_padded_thermo_inert_rows(h2o2):
    """Dead species carry cp = R, h = RT (so Cv = u = 0 in the energy
    sums — models/padding.py inertness contract)."""
    from batchreactor_tpu.models.padding import pad_thermo
    from batchreactor_tpu.ops.thermo import cp_h_s_over_R
    from batchreactor_tpu.utils.constants import R

    _, th, sp, _, _ = h2o2
    thp = pad_thermo(th, len(sp) + 3)
    cp_R, h_RT, _ = cp_h_s_over_R(jnp.asarray(1234.5), thp)
    assert np.allclose(np.asarray(cp_R)[-3:], 1.0)   # cp = R
    assert np.allclose(np.asarray(h_RT)[-3:], 1.0)   # h = RT
    # => Cv = cp - R = 0 and u = h - RT = 0 exactly
    assert float(cp_R[-1] * R - R) == 0.0


# ---------------------------------------------------------------------------
# the sweep surface (acceptance: end-to-end adiabatic ignition)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def chem_gas():
    return Chemistry(gaschem=True)


@pytest.fixture(scope="module")
def adiabatic_mono(h2o2, chem_gas):
    gm, th, *_ = h2o2
    T = np.linspace(1050.0, 1250.0, 5)
    out = batch_reactor_sweep(X_MIX, T, 1e5, 2e-4, chem=chem_gas,
                              thermo_obj=th, md=gm, energy="adiabatic_v")
    return T, out


def test_adiabatic_v_ignites(adiabatic_mono):
    T, out = adiabatic_mono
    assert (out["status"] == SUCCESS).all()
    # thermal runaway: every lane ends far above its initial T
    assert (out["T"] > T + 1500.0).all()
    tau = out["ignition_delay"]
    assert np.isfinite(tau).all()
    # hotter lanes ignite earlier (the physical ignition-delay table)
    assert (np.diff(tau) < 0).all()
    # species surface unchanged: mole fractions sum to 1 per lane
    x_sum = sum(out["x"].values())
    np.testing.assert_allclose(x_sum, 1.0, rtol=1e-12)


def test_segmented_matches_monolithic_bit_exact(h2o2, chem_gas,
                                                adiabatic_mono):
    gm, th, *_ = h2o2
    T, out = adiabatic_mono
    seg = batch_reactor_sweep(X_MIX, T, 1e5, 2e-4, chem=chem_gas,
                              thermo_obj=th, md=gm, energy="adiabatic_v",
                              segment_steps=64)
    np.testing.assert_array_equal(seg["T"], out["T"])
    np.testing.assert_array_equal(seg["t"], out["t"])
    np.testing.assert_array_equal(seg["ignition_delay"],
                                  out["ignition_delay"])
    for s in out["x"]:
        np.testing.assert_array_equal(seg["x"][s], out["x"][s])


def test_admission_stream_parity(h2o2, chem_gas, adiabatic_mono):
    """Streaming admission (segmented driver, PR-8 gear) carries the
    extended state: positionally identical delays, T within the
    documented companion-set ulp class."""
    gm, th, *_ = h2o2
    T, out = adiabatic_mono
    adm = batch_reactor_sweep(X_MIX, T, 1e5, 2e-4, chem=chem_gas,
                              thermo_obj=th, md=gm, energy="adiabatic_v",
                              segment_steps=64, admission=3, refill=1)
    assert (adm["status"] == SUCCESS).all()
    np.testing.assert_allclose(adm["T"], out["T"], rtol=1e-9)
    np.testing.assert_allclose(adm["ignition_delay"],
                               out["ignition_delay"], rtol=1e-9)


def test_padded_step_count_identity(h2o2, chem_gas):
    """Mechanism padding with the T row live: step counts and order
    histograms identical padded vs unpadded (the PR-13 contract
    extended to the energy norm)."""
    gm, th, *_ = h2o2
    T = np.linspace(1100.0, 1200.0, 3)
    kw = dict(chem=chem_gas, thermo_obj=th, md=gm, energy="adiabatic_v",
              telemetry=True)
    pad = batch_reactor_sweep(X_MIX, T, 1e5, 1e-4,
                              species_buckets=(16,),
                              reaction_buckets=(32,), **kw)
    raw = batch_reactor_sweep(X_MIX, T, 1e5, 1e-4, **kw)
    pl = pad["telemetry"]["solver_stats"]["per_lane"]
    ul = raw["telemetry"]["solver_stats"]["per_lane"]
    np.testing.assert_array_equal(pl["n_accepted"], ul["n_accepted"])
    np.testing.assert_array_equal(pl["n_rejected"], ul["n_rejected"])
    np.testing.assert_array_equal(pl["order_hist"], ul["order_hist"])
    np.testing.assert_allclose(pad["T"], raw["T"], rtol=1e-12)
    assert pad["telemetry"]["meta"]["energy"] == "adiabatic_v"


def test_energy_off_structure_guard(h2o2, chem_gas):
    """energy=None is a no-op: the result surface carries no energy
    keys, the cfg dict is untouched (same object), and the traced
    solver program is byte-identical with or without the energy cfg
    pass."""
    gm, th, sp, y_gas, _ = h2o2
    out = batch_reactor_sweep(X_MIX, np.asarray([1100.0]), 1e5, 1e-6,
                              chem=chem_gas, thermo_obj=th, md=gm)
    assert "T" not in out and "ignition_delay" not in out
    assert sorted(out) == ["report", "status", "t", "x"]
    cfg = {"T": jnp.asarray(1100.0)}
    assert eqns.energy_cfg(cfg, None, 1, len(sp), 1e-10) is cfg

    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs

    rhs, jac = make_gas_rhs(gm, th), make_gas_jac(gm, th)

    def run(cfg_):
        def f(y):
            return bdf.solve(rhs, y, 0.0, 1e-8, cfg_, rtol=1e-6,
                             atol=1e-10, max_steps=3, jac=jac).y
        return str(jax.make_jaxpr(f)(y_gas))

    assert run(cfg) == run(eqns.energy_cfg(cfg, None, 1, len(sp), 1e-10))
    # and the weighted program IS different (the key is live, not dead)
    cfg_e = dict(cfg)
    cfg_e[ATOL_SCALE_KEY] = jnp.ones_like(y_gas)
    assert run(cfg_e) != run(cfg)


def test_energy_validation_errors(h2o2, chem_gas):
    gm, th, *_ = h2o2
    smd = None
    with pytest.raises(ValueError, match="adiabatic_v"):
        batch_reactor_sweep(X_MIX, 1100.0, 1e5, 1e-5, chem=chem_gas,
                            thermo_obj=th, md=gm, energy="bogus")
    with pytest.raises(ValueError, match="atol_T"):
        batch_reactor_sweep(X_MIX, 1100.0, 1e5, 1e-5, chem=chem_gas,
                            thermo_obj=th, md=gm, atol_T=1e-3)
    with pytest.raises(ValueError, match="isothermal-only"):
        batch_reactor_sweep(X_MIX, 1100.0, 1e5, 1e-5, chem=chem_gas,
                            thermo_obj=th, md=gm, energy="adiabatic_v",
                            quarantine={"oracle": True})
    with pytest.raises(ValueError, match="gas chemistry only"):
        batch_reactor_sweep({"H2": 1.0}, 1100.0, 1e5, 1e-5,
                            chem=Chemistry(userchem=True,
                                           udf=lambda t, s: 0.0),
                            thermo_obj=th, energy="adiabatic_v")


def test_merge_observers_collision():
    obs, init = ignition.energy_ignition_observer(3)
    with pytest.raises(ValueError, match="collide"):
        ignition.merge_observers(obs, init, obs, init)


# ---------------------------------------------------------------------------
# checkpoint resume: the extended state + the SCHEMA_KNOBS pin
# ---------------------------------------------------------------------------
def test_checkpoint_resume_energy(h2o2, tmp_path):
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep

    gm, th, sp, _, _ = h2o2
    B = 4
    T = jnp.linspace(1100.0, 1200.0, B)
    x = np.zeros(len(sp))
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = .3, .2, .5
    rhos = jax.vmap(lambda t: density(jnp.asarray(x), th.molwt, t, 1e5))(T)
    y0s = jnp.concatenate(
        [rhos[:, None] * mole_to_mass(jnp.asarray(x), th.molwt)[None, :],
         T[:, None]], axis=1)
    cfgs = {"T": T, ATOL_SCALE_KEY: eqns.energy_atol_scale(
        B, int(y0s.shape[1]), 1e-10)}
    rhs = eqns.make_energy_rhs(gm, th, "adiabatic_v")
    jac = eqns.make_energy_jac(gm, th, "adiabatic_v")
    obs, obs0 = ignition.energy_ignition_observer(len(sp))
    kw = dict(chunk_size=2, jac=jac, observer=obs, observer_init=obs0,
              energy="adiabatic_v")
    ck = str(tmp_path / "ck")
    r1 = checkpointed_sweep(rhs, y0s, 0.0, 1e-4, cfgs, ck, **kw)
    # resume: chunks load from disk, results identical
    r2 = checkpointed_sweep(rhs, y0s, 0.0, 1e-4, cfgs, ck, **kw)
    np.testing.assert_array_equal(np.asarray(r1.y), np.asarray(r2.y))
    np.testing.assert_array_equal(np.asarray(r1.observed["ign_tau_dT"]),
                                  np.asarray(r2.observed["ign_tau_dT"]))
    # the energy mode PINS the fingerprint: a resume that drops (or
    # changes) the declaration fails loudly instead of serving chunks
    # from a different state schema
    with pytest.raises(ValueError, match="different sweep"):
        checkpointed_sweep(rhs, y0s, 0.0, 1e-4, cfgs, ck,
                           **{**kw, "energy": "adiabatic_p"})
    with pytest.raises(ValueError, match="different sweep"):
        checkpointed_sweep(rhs, y0s, 0.0, 1e-4, cfgs, ck,
                           **{**kw, "energy": None})


def test_fingerprint_energy_knob(h2o2):
    """SCHEMA_KNOBS registry behavior: the energy declaration moves the
    hash; explicit None fingerprints identical to absent."""
    from batchreactor_tpu.parallel import checkpoint as ck

    def rhs(t, y, cfg):
        return -y

    y0s = np.ones((2, 2))
    cfgs = {"k": np.ones((2,))}
    base = ck._sweep_fingerprint(rhs, y0s, cfgs, {})
    assert ck._sweep_fingerprint(rhs, y0s, cfgs,
                                 {"energy": "adiabatic_v"}) != base
    assert "energy" in ck.SCHEMA_KNOBS


# ---------------------------------------------------------------------------
# gradients: FD-golden dtau_ign/d(lnA), forward IFT and adjoint
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tau_gradients(h2o2, energy_theta):
    """One forward-IFT gradient pass shared by the FD and adjoint
    comparisons (rtol 1e-8 — the docs/sensitivity.md tangent tier)."""
    gm, th, sp, _, y0e = h2o2
    spec, theta, rhs_theta, jac_theta = energy_theta
    cfg = {ATOL_SCALE_KEY: jnp.ones_like(y0e).at[-1].set(
        DEFAULT_ATOL_T / 1e-12)}
    tau, grad, aux = ignition.delay_sensitivity_forward(
        rhs_theta, y0e, theta, cfg, len(sp), t_max=2e-4, jac=jac_theta,
        rtol=1e-8, atol=1e-12)
    assert aux["ignited"] and aux["Tdot"] > 0
    return cfg, tau, np.asarray(grad["log_A"]), aux


def test_forward_ift_vs_fd(h2o2, energy_theta, tau_gradients):
    """dtau_ign/d(lnA) via the forward IFT pass vs central finite
    differences of the threshold-crossing detector (tier: 5e-3 relative
    — the FD noise floor of an interpolated crossing at eps=1e-4)."""
    gm, th, sp, _, y0e = h2o2
    spec, theta, rhs_theta, jac_theta = energy_theta
    cfg, tau, gf, _ = tau_gradients
    obs, obs0 = ignition.energy_ignition_observer(len(sp))

    def tau_of(th_):
        def r(t, y, cfg):
            return rhs_theta(t, y, th_, cfg)

        def j(t, y, cfg):
            return jac_theta(t, y, th_, cfg)

        res = bdf.solve(r, y0e, 0.0, 2e-4, cfg, rtol=1e-8, atol=1e-12,
                        jac=j, observer=obs, observer_init=obs0)
        return float(np.asarray(res.observed["ign_tau_thr"]))

    eps = 1e-4
    for i in range(gf.shape[0]):
        tp = {"log_A": theta["log_A"].at[i].add(eps)}
        tm = {"log_A": theta["log_A"].at[i].add(-eps)}
        fd = (tau_of(tp) - tau_of(tm)) / (2 * eps)
        assert abs(gf[i] - fd) < 5e-3 * abs(fd) + 1e-12, (i, gf[i], fd)


def test_adjoint_vs_forward_ift(h2o2, energy_theta, tau_gradients):
    """The adjoint temperature-threshold QoI agrees with the forward
    IFT gradient (tier: 1e-2 relative — two independent
    discretizations of the same crossing)."""
    gm, th, sp, _, y0e = h2o2
    spec, theta, rhs_theta, jac_theta = energy_theta
    cfg, tau, gf, _ = tau_gradients
    qoi_fn = ignition.temperature_ignition_qoi(len(sp))
    qoi, grad, aux = adjoint.solve_adjoint(
        rhs_theta, qoi_fn, y0e, 0.0, 2e-4, theta, cfg,
        jac_theta=jac_theta, rtol=1e-8, atol=1e-12, grid_size=1024,
        segments=8)
    assert not bool(aux["truncated"])
    assert abs(float(qoi) - tau) < 5e-3 * tau
    ga = np.asarray(grad["log_A"])
    np.testing.assert_allclose(ga, gf, rtol=1e-2)


def test_adjoint_species_qoi_delegates(h2o2):
    """The promoted crossing helper serves the legacy species QoI: the
    refactored adjoint detector reproduces the observer's tau."""
    tk = jnp.linspace(0.0, 1.0, 11)
    m = jnp.asarray(1.0 - tk)          # falls through 0.5 at t=0.5
    q = adjoint.ignition_delay_qoi(0, frac=0.5)
    tau = q(tk, m[:, None], m[-1:])
    assert np.isclose(float(tau), 0.5)
    # rising crossing (the temperature form) through the same helper
    assert np.isclose(float(ignition.grid_crossing(tk, 2.0 * tk, 1.0,
                                                   rising=True)), 0.5)
    # never-crossed -> NaN (both directions)
    assert np.isnan(float(ignition.grid_crossing(tk, m, -1.0)))


# ---------------------------------------------------------------------------
# serving plane: grammar + lane packing
# ---------------------------------------------------------------------------
def test_schema_energy_grammar():
    from batchreactor_tpu.serving import schema

    base = {"id": "r", "T": 1100.0, "X": {"H2": 1.0}, "t1": 1e-4}
    req = schema.validate_request({**base, "energy": "adiabatic_v"},
                                  energy_modes=("adiabatic_v",))
    assert req.energy == "adiabatic_v"
    assert req.pack_key() == (1e-4, 1e-6, 1e-10, "adiabatic_v")
    # isothermal pack key carries the None slot (never collides)
    req0 = schema.validate_request(base, energy_modes=("adiabatic_v",))
    assert req0.pack_key() == (1e-4, 1e-6, 1e-10, None)
    # unknown literal: the error NAMES the accepted modes
    with pytest.raises(ValueError,
                       match=r"adiabatic_v.*adiabatic_p"):
        schema.validate_request({**base, "energy": "adiabatic"},
                                energy_modes=("adiabatic_v",))
    # a mode the session never warmed
    with pytest.raises(ValueError, match="not enabled"):
        schema.validate_request({**base, "energy": "adiabatic_p"},
                                energy_modes=("adiabatic_v",))
    with pytest.raises(ValueError, match="not enabled"):
        schema.validate_request({**base, "energy": "adiabatic_v"})
    # incompatible knob: Asv with an energy mode rejects loudly
    with pytest.raises(ValueError, match="Asv"):
        schema.validate_request(
            {**base, "energy": "adiabatic_v", "Asv": 2.0},
            energy_modes=("adiabatic_v",))


def test_session_energy_lanes(h2o2):
    """Session lane packing matches the api's energy state construction
    (trailing T row + T-row atol weight)."""
    from batchreactor_tpu.serving import schema
    from batchreactor_tpu.serving.session import SolverSession, load_spec

    gm, th, *_ = h2o2
    spec = load_spec({"mechanism": {"mech": "x", "therm": "y"},
                      "solver": {"segment_steps": 16,
                                 "energy_modes": ["adiabatic_v"]},
                      "serve": {"resident": 2, "buckets": None}})
    sess = SolverSession(gm, th, spec)
    req = schema.validate_request(
        {"id": "e", "T": [1100.0, 1200.0], "X": X_MIX, "t1": 1e-4,
         "energy": "adiabatic_v"},
        species=sess.species, energy_modes=spec.energy_modes)
    y0, cfg = sess.request_lanes(req)
    assert y0.shape == (2, len(sess.species) + 1)
    np.testing.assert_array_equal(y0[:, -1], [1100.0, 1200.0])
    assert cfg[ATOL_SCALE_KEY].shape == y0.shape
    np.testing.assert_allclose(cfg[ATOL_SCALE_KEY][:, -1],
                               DEFAULT_ATOL_T / spec.atol)
    np.testing.assert_allclose(cfg[ATOL_SCALE_KEY][:, :-1], 1.0)
    # warmup specs cover both families: isothermal + the energy mode
    specs = sess.warmup_specs()
    widths = {np.asarray(s["y0"]).shape[0] for s in specs}
    assert widths == {len(sess.species), len(sess.species) + 1}
    # a mode the session never built is loud
    with pytest.raises(ValueError, match="not enabled"):
        sess._energy_fns("adiabatic_p")
    # spec grammar: unknown mode literals reject at load
    with pytest.raises(ValueError, match="adiabatic_v"):
        load_spec({"mechanism": {"mech": "x", "therm": "y"},
                   "solver": {"energy_modes": ["bogus"]}})


@pytest.mark.slow
def test_served_adiabatic_matches_direct(h2o2, chem_gas):
    """Acceptance e2e (scheduler, HTTP-free): a served adiabatic
    request is bit-exact vs direct batch_reactor_sweep on the same
    conditions at the same bucket."""
    from batchreactor_tpu.serving import schema
    from batchreactor_tpu.serving.scheduler import Scheduler
    from batchreactor_tpu.serving.session import SolverSession, load_spec

    gm, th, *_ = h2o2
    spec = load_spec({"mechanism": {"mech": "x", "therm": "y"},
                      "solver": {"segment_steps": 64, "stats": True,
                                 "energy_modes": ["adiabatic_v"]},
                      "serve": {"resident": 4, "refill": 1,
                                "buckets": [2, 4], "poll_every": 1}})
    T = np.asarray([1100.0, 1200.0])
    with SolverSession(gm, th, spec) as sess:
        sched = Scheduler(sess).start()
        req = schema.validate_request(
            {"id": "e1", "T": list(T), "X": X_MIX, "t1": 2e-4,
             "energy": "adiabatic_v"},
            species=sess.species, energy_modes=spec.energy_modes)
        payload = sess.render_result(
            sched.submit(req).result(timeout=300))
        assert sched.drain(60)
    out = batch_reactor_sweep(X_MIX, T, 1e5, 2e-4, chem=chem_gas,
                              thermo_obj=th, md=gm,
                              energy="adiabatic_v", segment_steps=64,
                              admission=4, refill=1, buckets=(2, 4))
    assert payload["energy"] == "adiabatic_v"
    np.testing.assert_array_equal(payload["T"], out["T"])
    np.testing.assert_array_equal(payload["ignition_delay"],
                                  out["ignition_delay"])
    assert payload["solver_status"] == ["Success", "Success"]
