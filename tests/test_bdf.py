"""JAX variable-order BDF solver (solver/bdf.py) — the CVODE-class path.

Oracles: the SDIRK4 solver (independent method, same tolerances), the
native C++ BDF (same algorithm family, independent implementation), and
step-count expectations (variable-order BDF must take far fewer steps than
a 4th-order one-step method at stiff tolerances).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
from batchreactor_tpu.parallel import ensemble_solve, ignition_observer
from batchreactor_tpu.parallel.sweep import ensemble_solve_segmented
from batchreactor_tpu.solver import bdf, sdirk
from batchreactor_tpu.solver.sdirk import SUCCESS
from batchreactor_tpu.utils.composition import density, mole_to_mass


def _rob(t, y, cfg):
    k1, k2, k3 = 0.04, 3e7, 1e4
    d0 = -k1 * y[0] + k3 * y[1] * y[2]
    d2 = k2 * y[1] * y[1]
    return jnp.stack([d0, -d0 - d2, d2])


def test_robertson_matches_sdirk_with_far_fewer_steps():
    y0 = jnp.asarray([1.0, 0.0, 0.0])
    r_s = sdirk.solve(_rob, y0, 0.0, 1e4, {}, rtol=1e-8, atol=1e-12)
    r_b = bdf.solve(_rob, y0, 0.0, 1e4, {}, rtol=1e-8, atol=1e-12)
    assert int(r_b.status) == SUCCESS
    np.testing.assert_allclose(np.asarray(r_b.y), np.asarray(r_s.y),
                               rtol=1e-5)
    # the step-count economy is the whole point (measured: 453 vs 4762)
    assert int(r_b.n_accepted) < int(r_s.n_accepted) / 4


def test_zero_span_solve_is_identity():
    y0 = jnp.asarray([1.0, 0.0, 0.0])
    r = bdf.solve(_rob, y0, 1.0, 1.0, {}, rtol=1e-6, atol=1e-10)
    assert int(r.status) == SUCCESS
    assert int(r.n_accepted) == 0
    np.testing.assert_array_equal(np.asarray(r.y), np.asarray(y0))


@pytest.fixture(scope="module")
def gri(gri_lib_dir):
    gm = br.compile_gaschemistry(f"{gri_lib_dir}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{gri_lib_dir}/therm.dat")
    return gm, th


def _gri_sweep_inputs(gm, th, B):
    sp = list(gm.species)
    x0 = np.zeros(len(sp))
    x0[sp.index("CH4")], x0[sp.index("O2")], x0[sp.index("N2")] = .25, .5, .25
    T_grid = jnp.linspace(1500.0, 2000.0, B)
    rhos = jax.vmap(lambda T: density(jnp.asarray(x0), th.molwt, T, 1e5))(
        T_grid)
    y0s = rhos[:, None] * mole_to_mass(jnp.asarray(x0), th.molwt)[None, :]
    return sp, T_grid, y0s


def test_gri_segmented_resume_is_exact(gri):
    """The multistep history carried across bounded launches reproduces the
    monolithic step sequence exactly — same taus to the last bit."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 4)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")
    kw = dict(rtol=1e-6, atol=1e-10, jac=jacf, observer=obs,
              observer_init=obs0)
    r_m = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                         **kw)
    r_s = ensemble_solve_segmented(rhs, y0s, 0.0, 8e-4, {"T": T_grid},
                                   segment_steps=64, method="bdf", **kw)
    assert np.all(np.asarray(r_m.status) == SUCCESS)
    assert np.all(np.asarray(r_s.status) == SUCCESS)
    np.testing.assert_array_equal(np.asarray(r_m.observed["tau"]),
                                  np.asarray(r_s.observed["tau"]))
    np.testing.assert_array_equal(np.asarray(r_m.n_accepted),
                                  np.asarray(r_s.n_accepted))
    np.testing.assert_allclose(np.asarray(r_m.y), np.asarray(r_s.y),
                               rtol=1e-12)


def test_gri_tau_matches_native_bdf(gri):
    """Ignition delay vs the independent C++ BDF (<0.5%), and the JAX BDF
    takes comparably few steps (same algorithm family)."""
    from batchreactor_tpu import native

    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 3)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")
    r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                       rtol=1e-6, atol=1e-10, jac=jacf, observer=obs,
                       observer_init=obs0)
    tau = np.asarray(r.observed["tau"])
    ch4 = sp.index("CH4")
    for b in range(3):
        y0b = np.asarray(y0s[b])
        rn = native.solve_gas_bdf(gm, th, float(T_grid[b]), y0b, 0.0, 8e-4,
                                  rtol=1e-6, atol=1e-10, n_save=100_000)
        ts = np.concatenate([[0.0], np.asarray(rn.ts)])
        ys = np.concatenate([y0b[None, :], np.asarray(rn.ys)])
        thr = 0.5 * y0b[ch4]
        i = int(np.argmax(ys[:, ch4] < thr))
        m_a, m_b = ys[i - 1, ch4], ys[i, ch4]
        w = (m_a - thr) / (m_a - m_b)
        tau_n = float(ts[i - 1] + w * (ts[i] - ts[i - 1]))
        assert abs(tau[b] - tau_n) / tau_n < 5e-3, (b, tau[b], tau_n)


def test_trajectory_buffer_and_observer(gri):
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 2)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    r = ensemble_solve(rhs, y0s, 0.0, 1e-5, {"T": T_grid}, method="bdf",
                       rtol=1e-6, atol=1e-10, jac=jacf, n_save=64)
    assert np.all(np.asarray(r.status) == SUCCESS)
    n_saved = np.asarray(r.n_saved)
    ts = np.asarray(r.ts)
    for b in range(2):
        k = int(n_saved[b])
        assert 0 < k <= 64
        assert np.all(np.diff(ts[b, :k]) > 0)
        assert np.isinf(ts[b, k:]).all() or k == 64


def test_terminated_lane_carry_frozen(gri):
    """A lane that fails terminally while siblings keep integrating must
    report its carry (h, y) from the failure point, not garbage decayed by
    idle batched iterations."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 2)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    # lane 0: poisoned initial state (negative mass) -> early failure;
    # lane 1: normal ignition run
    y0s = y0s.at[0, :].set(jnp.nan)
    r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                       rtol=1e-6, atol=1e-10, jac=jacf)
    status = np.asarray(r.status)
    assert status[1] == SUCCESS
    assert status[0] != SUCCESS
    # the failed lane's h must be finite-or-nan exactly as at failure, not
    # a 0.5^N decay toward denormal zero from idle iterations
    h0 = float(np.asarray(r.h)[0])
    assert not (0.0 < h0 < 1e-30), h0


def test_method_validation():
    y0 = jnp.zeros((1, 3)) + jnp.asarray([1.0, 0.0, 0.0])
    with pytest.raises(ValueError, match="unknown method"):
        ensemble_solve(_rob, y0, 0.0, 1.0, {}, method="rk4")
    with pytest.raises(ValueError, match="sdirk-only"):
        ensemble_solve(_rob, y0, 0.0, 1.0, {}, method="bdf", newton_tol=0.1)


def test_file_driven_method_bdf(tmp_path, reference_dir, lib_dir, capsys):
    """batch_reactor(..., method="bdf"): end-to-end file-driven parity with
    the default solver's final composition."""
    import csv
    import shutil

    finals = {}
    for method in ("sdirk", "bdf"):
        d = tmp_path / method
        d.mkdir()
        shutil.copy(reference_dir / "test" / "batch_h2o2" / "batch.xml",
                    d / "batch.xml")
        ret = br.batch_reactor(str(d / "batch.xml"), lib_dir, gaschem=True,
                               method=method, verbose=False)
        assert ret == "Success"
        rows = list(csv.reader(open(d / "gas_profile.csv")))
        finals[method] = [float(v) for v in rows[-1][4:]]
    np.testing.assert_allclose(finals["bdf"], finals["sdirk"],
                               rtol=1e-4, atol=1e-9)


def test_coupled_gas_surf_golden_parity(gri, reference_dir):
    """BDF on the coupled GRI + CH4/Ni flagship (10 s horizon): bulk final
    composition matches the committed golden trajectory like sdirk does —
    at ~5x fewer accepted steps (measured 823 vs 3848)."""
    import csv

    from batchreactor_tpu.models.surface import compile_mech
    from batchreactor_tpu.ops.rhs import make_surface_jac, make_surface_rhs

    gm, th = gri
    sm = compile_mech(str(reference_dir / "test" / "lib" / "ch4ni.xml"), th,
                      list(gm.species))
    sp = list(gm.species)
    x0 = np.zeros(53)
    x0[sp.index("CH4")], x0[sp.index("O2")], x0[sp.index("N2")] = .25, .5, .25
    rho = float(density(jnp.asarray(x0), th.molwt, 1173.0, 1e5))
    y0 = jnp.concatenate(
        [mole_to_mass(jnp.asarray(x0), th.molwt) * rho, sm.ini_covg])
    rhs = make_surface_rhs(sm, th, gm=gm, asv_quirk=True, kc_compat=True)
    jacf = make_surface_jac(sm, th, gm=gm, asv_quirk=True, kc_compat=True)
    r = bdf.solve(rhs, y0, 0.0, 10.0, {"T": jnp.asarray(1173.0),
                                       "Asv": jnp.asarray(1.0)},
                  rtol=1e-6, atol=1e-10, jac=jacf, max_steps=400_000)
    assert int(r.status) == SUCCESS
    assert int(r.n_accepted) < 1500  # sdirk needs ~3850
    W = np.asarray(th.molwt)
    xg = np.asarray(r.y)[:53] / W
    xg /= xg.sum()
    gold_csv = reference_dir / "test" / "batch_gas_and_surf" / \
        "gas_profile.csv"
    rows = list(csv.reader(open(gold_csv)))
    hdr, last = rows[0], [float(v) for v in rows[-1]]
    gold = {hdr[i]: last[i] for i in range(len(hdr))}
    for s in ("H2O", "CO2", "N2"):
        assert abs(xg[sp.index(s)] - gold[s]) / gold[s] < 2e-3, s


def test_gri_inv32_linsolve_matches_lu(gri):
    """The TPU Newton path (f32 batched inverse + f64 refinement) under BDF:
    same taus as the exact-f64 LU path to ~1e-5 — pre-validates the
    accelerator configuration on CPU."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 4)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")
    taus = {}
    for ls in ("lu", "inv32", "inv32nr", "inv32f"):
        r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                           rtol=1e-6, atol=1e-10, jac=jacf, linsolve=ls,
                           observer=obs, observer_init=obs0)
        assert np.all(np.asarray(r.status) == SUCCESS), ls
        taus[ls] = np.asarray(r.observed["tau"])
    np.testing.assert_allclose(taus["inv32"], taus["lu"], rtol=1e-4)
    np.testing.assert_allclose(taus["inv32nr"], taus["lu"], rtol=1e-4)
    np.testing.assert_allclose(taus["inv32f"], taus["lu"], rtol=1e-4)


def test_gri_jac_window_matches_fresh_jacobian(gri):
    """jac_window=K under BDF (CVODE's quasi-constant iteration matrix):
    stale-J quasi-Newton converges to the same corrector solution, so
    ignition delays track the fresh-J run to tolerance scale and no lane
    loses convergence."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 4)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")
    taus = {}
    for jw in (1, 3):
        r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                           rtol=1e-6, atol=1e-10, jac=jacf, jac_window=jw,
                           observer=obs, observer_init=obs0)
        assert np.all(np.asarray(r.status) == SUCCESS), jw
        taus[jw] = np.asarray(r.observed["tau"])
    np.testing.assert_allclose(taus[3], taus[1], rtol=1e-3)


def test_gri_freeze_precond_matches_fresh(gri):
    """freeze_precond (window-frozen M with CVODE's cj-ratio rescale, on
    top of jac_window=8): same ignition delays as the per-attempt-exact
    jw=1 run, statuses clean, and step counts comparable — the frozen
    preconditioner only changes the quasi-Newton convergence RATE, and an
    in-window stall closes the window (fresh J and M at the retry h), so
    drift cannot cascade for the remainder of the window."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 4)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")
    runs = {}
    for label, kw in (("fresh", dict(jac_window=1)),
                      ("frozen", dict(jac_window=8, freeze_precond=True))):
        r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                           rtol=1e-6, atol=1e-10, jac=jacf, observer=obs,
                           observer_init=obs0, **kw)
        assert np.all(np.asarray(r.status) == SUCCESS), label
        runs[label] = r
    np.testing.assert_allclose(np.asarray(runs["frozen"].observed["tau"]),
                               np.asarray(runs["fresh"].observed["tau"]),
                               rtol=1e-3)
    acc_f = np.asarray(runs["fresh"].n_accepted, dtype=float)
    acc_z = np.asarray(runs["frozen"].n_accepted, dtype=float)
    assert np.all(acc_z <= 1.5 * acc_f + 10)
    # the early-close refresh keeps stale-J/M rejection inflation bounded
    # across the ignition front (the stiffness transient of this sweep)
    rej_f = np.asarray(runs["fresh"].n_rejected, dtype=float)
    rej_z = np.asarray(runs["frozen"].n_rejected, dtype=float)
    assert np.all(rej_z <= rej_f + 0.25 * acc_f + 10)


def test_gri_jac_window_reject_parity_at_ignition_front(gri):
    """Newton-failure-triggered early window close: jac_window=8 must not
    inflate rejected attempts across the ignition front relative to the
    fresh-J run (CVODE's convergence-triggered refresh semantics)."""
    gm, th = gri
    sp, T_grid, y0s = _gri_sweep_inputs(gm, th, 6)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    runs = {}
    for jw in (1, 8):
        r = ensemble_solve(rhs, y0s, 0.0, 8e-4, {"T": T_grid}, method="bdf",
                           rtol=1e-6, atol=1e-10, jac=jacf, jac_window=jw)
        assert np.all(np.asarray(r.status) == SUCCESS), jw
        runs[jw] = r
    rej1 = np.asarray(runs[1].n_rejected, dtype=float)
    rej8 = np.asarray(runs[8].n_rejected, dtype=float)
    acc1 = np.asarray(runs[1].n_accepted, dtype=float)
    assert np.all(rej8 <= rej1 + 0.25 * acc1 + 10), (rej1, rej8)


def test_forward_sensitivity_through_bdf():
    """jax.jacfwd through bdf.solve: d(final state)/d(rate param) finite and
    matching a central finite difference — the sens=True capability on the
    fast solver."""

    def rhs(t, y, cfg):
        k = cfg["k"]
        d0 = -k * y[0]
        return jnp.stack([d0, -d0])

    y0 = jnp.asarray([1.0, 0.0])

    def final_state(k):
        r = bdf.solve(rhs, y0, 0.0, 1.0, {"k": k}, rtol=1e-8, atol=1e-12)
        return r.y

    k0 = 1.3
    sens = np.asarray(jax.jacfwd(final_state)(jnp.asarray(k0)))
    eps = 1e-5
    fd = (np.asarray(final_state(jnp.asarray(k0 + eps)))
          - np.asarray(final_state(jnp.asarray(k0 - eps)))) / (2 * eps)
    # analytic: d/dk e^{-k t} at t=1 = -e^{-k}
    np.testing.assert_allclose(sens[0], -np.exp(-k0), rtol=1e-3)
    np.testing.assert_allclose(sens, fd, rtol=1e-3, atol=1e-8)
