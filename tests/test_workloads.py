"""Sweep-shaped tests for the five BASELINE.json workload configs.

The reference runs one condition per call; these tests pin the framework's
net-new ensemble layer to the exact workload shapes the benchmark protocol
names (BASELINE.md): (T0, phi) ignition maps, coverage ODEs batched over T,
catalyst-loading (Asv) sweeps, and jacfwd forward-sensitivity sweeps over a
user-defined rate function.  Sizes are kept small for CPU CI; bench.py runs
the full-scale versions on TPU.
"""

import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.surface import compile_mech
from batchreactor_tpu.ops.rhs import (
    make_gas_jac,
    make_gas_rhs,
    make_surface_rhs,
    make_udf_rhs,
)
from batchreactor_tpu.parallel import (
    condition_grid,
    ensemble_solve,
    ignition_observer,
    make_mesh,
    premixed_mole_fracs,
    sweep_solution_vectors,
)
from batchreactor_tpu.solver import sdirk
from batchreactor_tpu.solver.sdirk import SUCCESS


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, th


@pytest.fixture(scope="module")
def ch4ni(gri_lib_dir):
    gasphase = ["CH4", "H2O", "H2", "CO", "CO2", "O2", "N2"]
    th = br.create_thermo(gasphase, f"{gri_lib_dir}/therm.dat")
    sm = compile_mech(f"{gri_lib_dir}/ch4ni.xml", th, gasphase)
    return th, sm


def test_T_phi_ignition_map(h2o2):
    """batch_ch4-shaped workload: a (T0, phi) condition grid solved as one
    mesh-sharded ensemble with in-loop ignition-delay extraction (H2/O2
    chemistry for CPU speed; bench.py runs GRI-scale on TPU)."""
    gm, th = h2o2
    sp = list(gm.species)
    g = condition_grid(T=jnp.linspace(1200.0, 1400.0, 4),
                       phi=jnp.linspace(0.5, 2.0, 4))
    X = premixed_mole_fracs(gm.species, "H2", g["phi"], stoich_o2=0.5,
                            diluent="N2", o2_to_diluent=3.76)
    y0s = sweep_solution_vectors(X, th.molwt, g["T"], 1e5)
    rhs = make_gas_rhs(gm, th)
    jac = make_gas_jac(gm, th)
    obs, obs0 = ignition_observer(sp.index("H2"), mode="half")
    res = ensemble_solve(rhs, y0s, 0.0, 5e-3, {"T": g["T"]},
                         mesh=make_mesh(), dt0=1e-12, jac=jac,
                         observer=obs, observer_init=obs0)
    assert np.all(np.asarray(res.status) == SUCCESS)
    tau = np.asarray(res.observed["tau"]).reshape(4, 4)
    assert np.all(np.isfinite(tau)) and np.all(tau > 0)
    # hotter ignites faster at fixed phi (every column decreasing in T)
    assert np.all(tau[1:, :] < tau[:-1, :])


def test_coverage_ode_batched_over_T(ch4ni):
    """batch_surf-shaped workload: CH4-on-Ni coverage ODEs, one lane per
    temperature, per-lane adaptive stepping (surf-only chemistry,
    /root/reference/test/batch_surf/batch.xml conditions)."""
    th, sm = ch4ni
    from batchreactor_tpu.api import get_solution_vector

    x0 = np.zeros(7)
    sp = list(th.species)
    x0[sp.index("CH4")], x0[sp.index("N2")] = 0.25, 0.75
    y0 = get_solution_vector(x0, th.molwt, 1073.15, 1e5, ini_covg=sm.ini_covg)
    B = 4
    y0s = jnp.broadcast_to(y0, (B,) + y0.shape)
    cfgs = {"T": jnp.linspace(1023.0, 1223.0, B),
            "Asv": jnp.full((B,), 10.0)}
    rhs = make_surface_rhs(sm, th)
    res = ensemble_solve(rhs, y0s, 0.0, 1e-3, cfgs, dt0=1e-12)
    assert np.all(np.asarray(res.status) == SUCCESS)
    ng = 7
    covg = np.asarray(res.y)[:, ng:]
    # coverages stay a partition of unity per lane (site conservation)
    np.testing.assert_allclose(covg.sum(axis=1), 1.0, rtol=1e-6)
    # different temperatures end in measurably different coverage states
    assert np.std(covg[:, 0]) > 0


def test_catalyst_loading_sweep(ch4ni):
    """batch_gas_and_surf-shaped workload: Asv (catalyst loading) varied per
    lane at fixed T — the per-lane cfg axis the reference has no analog for."""
    th, sm = ch4ni
    from batchreactor_tpu.api import get_solution_vector

    x0 = np.zeros(7)
    sp = list(th.species)
    x0[sp.index("CH4")], x0[sp.index("N2")] = 0.25, 0.75
    y0 = get_solution_vector(x0, th.molwt, 1123.0, 1e5, ini_covg=sm.ini_covg)
    B = 4
    y0s = jnp.broadcast_to(y0, (B,) + y0.shape)
    Asv = jnp.array([1.0, 10.0, 100.0, 1000.0])
    cfgs = {"T": jnp.full((B,), 1123.0), "Asv": Asv}
    rhs = make_surface_rhs(sm, th)
    res = ensemble_solve(rhs, y0s, 0.0, 1e-4, cfgs, dt0=1e-12)
    assert np.all(np.asarray(res.status) == SUCCESS)
    ch4_consumed = float(y0[sp.index("CH4")]) - np.asarray(res.y)[:, sp.index("CH4")]
    # more catalyst area -> more CH4 converted, monotonically
    assert np.all(np.diff(ch4_consumed) > 0), ch4_consumed


def test_udf_forward_sensitivity_sweep(h2o2):
    """batch_udf-shaped workload: jacfwd forward sensitivities of the final
    state w.r.t. a UDF rate parameter, vmapped over lanes (the reference's
    sens hook returns the problem unsolved, /root/reference/src/
    BatchReactor.jl:205-207; here the sensitivity is computed natively)."""
    gm, th = h2o2
    sp = list(gm.species)
    i_h2 = sp.index("H2")

    def udf(t, state, k=None):
        # first-order H2 decay with rate parameter k (mol/m^3/s)
        x = state["mole_frac"]
        c = x * state["p"] / (8.314472 * state["T"])
        src = jnp.zeros_like(x).at[i_h2].set(-k * c[i_h2])
        return src

    from batchreactor_tpu.api import get_solution_vector

    x0 = np.zeros(len(sp))
    x0[i_h2], x0[sp.index("N2")] = 0.3, 0.7
    y0 = get_solution_vector(x0, th.molwt, 1100.0, 1e5)

    def final_h2(k, T):
        rhs = make_udf_rhs(lambda t, s: udf(t, s, k=k), th.molwt)
        res = sdirk.solve(rhs, y0, 0.0, 1e-2, {"T": T}, rtol=1e-8,
                          atol=1e-14)
        return res.y[i_h2]

    ks = jnp.array([5.0, 10.0, 20.0])
    Ts = jnp.full((3,), 1100.0)
    vals = jax.vmap(final_h2)(ks, Ts)
    sens = jax.vmap(jax.jacfwd(final_h2))(ks, Ts)
    # exponential decay: y = y0 exp(-k t) -> dy/dk = -t y, all negative
    assert np.all(np.asarray(sens) < 0)
    np.testing.assert_allclose(np.asarray(sens),
                               -1e-2 * np.asarray(vals), rtol=1e-4)


def test_h2o2_single_condition_matches_reference_config(h2o2, lib_dir,
                                                        tmp_path):
    """batch_h2o2-shaped workload: the reference's own config file run
    through the file-driven API (the single-condition anchor the sweep
    workloads extend).  Reference-only: skips on a bare clone (conftest
    convention) instead of failing on the missing config."""
    import os
    import shutil

    src = os.path.join(os.environ.get("BR_REFERENCE", "/root/reference"),
                       "test", "batch_h2o2", "batch.xml")
    if not os.path.isfile(src):
        pytest.skip(f"reference config unavailable at {src} (bare clone)")
    shutil.copy(src, tmp_path / "batch.xml")
    ret = br.batch_reactor(str(tmp_path / "batch.xml"), lib_dir, gaschem=True)
    assert ret == "Success"
    rows = open(tmp_path / "gas_profile.csv").readlines()
    hdr = rows[0].strip().split(",")
    last = dict(zip(hdr, [float(v) for v in rows[-1].split(",")]))
    # H2/O2 equilibrium at 1173 K: complete burnout of the lean H2
    assert last["H2"] < 1e-6


class TestSweepAPI:
    """batch_reactor_sweep — the ensemble analog of the programmatic entry
    point (the BASELINE.json north-star surface)."""

    def test_gas_temperature_sweep_with_tau(self, h2o2):
        gm, th = h2o2
        out = br.batch_reactor_sweep(
            {"H2": 0.25, "O2": 0.25, "N2": 0.5},
            jnp.linspace(1200.0, 1400.0, 4), 1e5, 2e-3,
            chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
            ignition_marker="H2")
        assert out["report"]["counts"]["success"] == 4
        assert np.all(np.diff(out["tau"]) < 0)  # hotter ignites faster
        x_h2o = out["x"]["H2O"]
        assert x_h2o.shape == (4,) and np.all(x_h2o > 0.2)

    def test_surface_asv_sweep(self, ch4ni):
        th, sm = ch4ni
        out = br.batch_reactor_sweep(
            {"CH4": 0.25, "N2": 0.75}, 1123.0, 1e5, 1e-4,
            chem=br.Chemistry(surfchem=True), thermo_obj=th, md=sm,
            Asv=jnp.array([1.0, 100.0]))
        assert out["report"]["counts"]["success"] == 2
        assert out["covg"].shape == (2, 13)
        # more catalyst area converts more CH4
        assert out["x"]["CH4"][1] < out["x"]["CH4"][0]

    def test_segmented_path(self, h2o2):
        gm, th = h2o2
        out = br.batch_reactor_sweep(
            {"H2": 0.25, "O2": 0.25, "N2": 0.5},
            jnp.array([1173.0, 1300.0]), 1e5, 1e-4,
            chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
            segment_steps=64)
        assert out["report"]["counts"]["success"] == 2

    def test_bdf_jac_window_through_sweep_api(self, h2o2):
        """jac_window reaches the solver through batch_reactor_sweep: the
        windowed run tracks the per-attempt-J run at tolerance scale."""
        gm, th = h2o2
        taus = {}
        for jw in (1, 4):
            out = br.batch_reactor_sweep(
                {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                jnp.linspace(1200.0, 1400.0, 3), 1e5, 2e-3,
                chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
                method="bdf", jac_window=jw, ignition_marker="H2")
            assert out["report"]["counts"]["success"] == 3
            taus[jw] = out["tau"]
        np.testing.assert_allclose(taus[4], taus[1], rtol=1e-3)

    def test_udf_sweep_mode(self, h2o2):
        """User-defined chemistry through the sweep API (the reference's UDF
        seam, /root/reference/src/BatchReactor.jl:358-360, widened to the
        ensemble): a first-order decay source vmaps over lanes; per-lane
        rate constants come from the cfg temperature."""
        _, th = h2o2
        sp = list(th.species)
        i_h2 = sp.index("H2")

        def udf(t, state):
            # decay H2 at k(T) = T/1e5 1/s (toy, JAX-traceable): source
            # in mol/m^3/s, converted by the framework via molwt
            c = state["mole_frac"] * state["p"] / (8.314472 * state["T"])
            k = state["T"] / 1e5
            return jnp.zeros_like(c).at[i_h2].set(-k * c[i_h2])

        T = jnp.asarray([1000.0, 2000.0])
        out = br.batch_reactor_sweep(
            {"H2": 0.25, "O2": 0.25, "N2": 0.5}, T, 1e5, 5.0,
            chem=br.Chemistry(userchem=True, udf=udf), thermo_obj=th)
        assert out["report"]["counts"]["success"] == 2
        assert "covg" not in out
        x_h2 = out["x"]["H2"]
        # hotter lane decays faster; both lanes decayed from 0.25
        assert x_h2[1] < x_h2[0] < 0.25
        # quantitative: H2 moles decay exp(-k t) (k = T/1e5, t = 5 s) and
        # total moles shrink with them, so
        # x = 0.25 e^{-kt} / (0.75 + 0.25 e^{-kt})
        import math
        for lane, Tk in enumerate([1000.0, 2000.0]):
            f = 0.25 * math.exp(-Tk / 1e5 * 5.0)
            np.testing.assert_allclose(x_h2[lane], f / (0.75 + f),
                                       rtol=1e-3)

    def test_remat_jac_mode_matches_analytic(self, h2o2):
        """analytic_jac='remat' (closed form under jax.checkpoint) is the
        same math as analytic_jac=True — results must agree to solver
        tolerance (the knob only changes XLA program structure)."""
        gm, th = h2o2
        outs = {}
        for mode in (True, "remat"):
            outs[mode] = br.batch_reactor_sweep(
                {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                jnp.linspace(1200.0, 1350.0, 3), 1e5, 2e-4,
                chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
                analytic_jac=mode)
            assert outs[mode]["report"]["counts"]["success"] == 3
        for s in th.species:
            np.testing.assert_allclose(outs["remat"]["x"][s],
                                       outs[True]["x"][s],
                                       rtol=1e-9, atol=1e-14)

    def test_per_lane_composition(self, h2o2):
        gm, th = h2o2
        out = br.batch_reactor_sweep(
            {"H2": np.array([0.1, 0.3]), "O2": np.array([0.25, 0.25]),
             "N2": np.array([0.65, 0.45])},
            1250.0, 1e5, 2e-3,
            chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm)
        assert out["report"]["counts"]["success"] == 2
        # richer lane makes more water
        assert out["x"]["H2O"][1] > out["x"]["H2O"][0]


class TestSetupEconomy:
    """CVODE-style Newton setup economy (``setup_economy=True``) on the
    north-star regression workload shape: the ``factorizations <
    jac_builds`` acceptance criterion, trajectory tolerance vs the
    economy-off run, and the structural no-op guarantee at
    ``jac_window=1`` (docs/performance.md "Newton setup economy")."""

    def test_economy_counters_and_tau_parity(self, h2o2):
        """Economy run on the small T-grid ignition sweep: reuse fires
        (``setup_reuses > 0``), ``factorizations`` drops strictly below
        ``jac_builds`` (the window-open count), the exact partition
        ``setup_reuses + factorizations == jac_builds`` holds, and the
        ignition delays stay at tolerance scale of the economy-off run."""
        gm, th = h2o2
        outs = {}
        for econ in (False, True):
            outs[econ] = br.batch_reactor_sweep(
                {"H2": 0.25, "O2": 0.25, "N2": 0.5},
                jnp.linspace(1200.0, 1400.0, 3), 1e5, 2e-3,
                chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
                method="bdf", jac_window=8, setup_economy=econ,
                telemetry=True, ignition_marker="H2")
            assert outs[econ]["report"]["counts"]["success"] == 3
        tot = outs[True]["telemetry"]["solver_stats"]["totals"]
        assert tot["setup_reuses"] > 0, tot
        assert tot["factorizations"] < tot["jac_builds"], tot
        assert (tot["setup_reuses"] + tot["factorizations"]
                == tot["jac_builds"]), tot
        # a factorization that was ever reused served >= 2 windows
        assert tot["precond_age"] >= 2, tot
        # economy-off control: no reuse, and M is rebuilt c-correct every
        # attempt (factorizations >= window opens); economy froze in-window
        # AND across windows, so its factorization count is strictly lower
        base = outs[False]["telemetry"]["solver_stats"]["totals"]
        assert base["setup_reuses"] == 0, base
        assert base["factorizations"] >= base["jac_builds"], base
        assert tot["factorizations"] < base["factorizations"], (tot, base)
        # quasi-Newton preconditioning leaves the corrector fixed point
        # alone: ignition delays agree at tolerance scale
        np.testing.assert_allclose(np.asarray(outs[True]["tau"]),
                                   np.asarray(outs[False]["tau"]),
                                   rtol=1e-3)

    def test_economy_survives_segment_relaunches(self, h2o2):
        """The economy state joins the segment carry (solver_state), so
        reuse streaks cross segment relaunches: the counter partition
        holds on segmented totals and reuse still fires."""
        gm, th = h2o2
        out = br.batch_reactor_sweep(
            {"H2": 0.25, "O2": 0.25, "N2": 0.5},
            jnp.array([1200.0, 1350.0]), 1e5, 2e-3,
            chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm,
            method="bdf", jac_window=8, setup_economy=True,
            segment_steps=64, telemetry=True)
        assert out["report"]["counts"]["success"] == 2
        tot = out["telemetry"]["solver_stats"]["totals"]
        assert tot["setup_reuses"] > 0, tot
        assert (tot["setup_reuses"] + tot["factorizations"]
                == tot["jac_builds"]), tot

    def test_economy_noop_at_jac_window1(self):
        """At ``jac_window=1`` economy is structurally meaningless (every
        attempt refactors anyway): the knob must be a NO-OP — identical
        traced program, bit-identical trajectories."""
        from batchreactor_tpu.solver import bdf

        def rob(t, y, cfg):
            k1, k2, k3 = 0.04, 3e7, 1e4
            d0 = -k1 * y[0] + k3 * y[1] * y[2]
            d2 = k2 * y[1] * y[1]
            return jnp.stack([d0, -d0 - d2, d2])

        y0 = jnp.asarray([1.0, 0.0, 0.0])

        def run(econ, y=y0):
            return bdf.solve(rob, y, 0.0, 1e2, {}, rtol=1e-8, atol=1e-12,
                             n_save=16, jac_window=1, setup_economy=econ)

        jaxprs = {e: str(jax.make_jaxpr(lambda y, e=e: run(e, y).y)(y0))
                  for e in (False, True)}
        assert jaxprs[True] == jaxprs[False]
        r_off, r_on = run(False), run(True)
        assert int(r_on.status) == SUCCESS
        np.testing.assert_array_equal(np.asarray(r_on.ys),
                                      np.asarray(r_off.ys))
        np.testing.assert_array_equal(np.asarray(r_on.y),
                                      np.asarray(r_off.y))


def test_northstar_sweep_small(gri_lib_dir, tmp_path):
    """CPU-sized regression of the north-star workload machinery
    (scripts/northstar_sweep.py): T x phi GRI grid through the checkpointed
    + segmented sweep, observer tau interpolated, native-BDF parity < 0.1%,
    and chunk-level resume serving from disk."""
    import sys
    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "scripts"))
    import northstar_sweep

    rec = northstar_sweep.run_sweep(
        n_T=3, n_phi=2, T_lo=1700.0, T_hi=2000.0, t1=4e-4,
        ckpt_dir=str(tmp_path / "ck"), chunk_size=4, segment_steps=512,
        n_spot=3, log=lambda m: None)
    assert rec["B"] == 6
    assert rec["counts"].get("success", 0) == 6
    assert rec["tau_parity_failed_spots"] == 0
    assert rec["tau_parity_max_rel_err"] < 1e-3
    # resume: all chunks on disk -> no device work, same record
    rec2 = northstar_sweep.run_sweep(
        n_T=3, n_phi=2, T_lo=1700.0, T_hi=2000.0, t1=4e-4,
        ckpt_dir=str(tmp_path / "ck"), chunk_size=4, segment_steps=512,
        n_spot=0, log=lambda m: None)
    assert rec2["tau_range_s"] == rec["tau_range_s"]


def test_coupled_gas_surf_sweep_api(lib_dir, fixtures_dir):
    """batch_gas_and_surf-shaped workload through the high-level sweep API:
    coupled gas+surface chemistry (gmd= + smd=), catalyst loading Asv varied
    per lane — the coupled mode the reference's programmatic form cannot
    express (params collision, SURVEY.md §3.3)."""
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    sm = compile_mech(f"{fixtures_dir}/h2oni.xml", th, list(gm.species))
    out = br.batch_reactor_sweep(
        {"H2": 0.3, "O2": 0.2, "N2": 0.5},
        1050.0, 1e5, 1e-4,
        chem=br.Chemistry(surfchem=True, gaschem=True),
        thermo_obj=th, gmd=gm, smd=sm,
        Asv=jnp.array([1.0, 10.0, 100.0, 1000.0]))
    assert out["report"]["counts"]["success"] == 4
    covg = out["covg"]
    assert np.all(np.isfinite(covg))
    np.testing.assert_allclose(covg.sum(axis=1), 1.0, rtol=1e-6)
    # more catalyst area -> larger surface influence on the gas state,
    # monotone over the Asv decades (direction is mechanism-specific: this
    # synthetic fixture net-adsorbs H2O at these conditions)
    h2o = out["x"]["H2O"]
    depart = np.abs(h2o - h2o[0])
    assert np.all(np.diff(depart) > 0), h2o  # incl. depart[1] > 0 == depart[0]
