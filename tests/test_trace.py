"""Request-lifecycle tracing plane (obs/trace.py + the HISTOGRAM
family + the gate/waterfall CLIs — docs/observability.md "Request
tracing"/"Histograms").

Tiers, device-free by construction (the fake-session scheduler and the
obs layer import no jax):

* **RequestTrace** — vocabulary, idempotent marks, monotone stage
  offsets, payload/attrs exports;
* **histograms** — fixed-bucket observe/merge/quantile math, the
  report JSONL <-> Prometheus round trip, the ``serve_latency_s``
  migration regression, and ``obs.diff``'s missing->empty convention;
* **scheduler capture** against the fake session: all stages marked in
  order (out-of-order harvest included), the trace-off no-op (response
  payloads byte-identical with ``trace`` absent), the stalled stage
  under injection, and the ``slow_request`` threshold event;
* **CLIs** — ``scripts/obs_gate.py`` passing on in-band reports and
  failing loudly on perturbed ones; ``scripts/obs_trace.py``
  waterfalls + ``--slowest``.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from batchreactor_tpu.obs import (RequestTrace, Recorder,  # noqa: E402
                                  build_report, diff, from_jsonl,
                                  to_jsonl, to_prometheus)
from batchreactor_tpu.obs import counters as C  # noqa: E402
from batchreactor_tpu.obs import trace as T  # noqa: E402
from batchreactor_tpu.resilience import inject  # noqa: E402
from batchreactor_tpu.serving import schema  # noqa: E402
from batchreactor_tpu.serving.scheduler import Scheduler  # noqa: E402

from test_serving import FakeSession, _req, _request  # noqa: E402


# --------------------------------------------------------------------------
# RequestTrace
# --------------------------------------------------------------------------
class TestRequestTrace:
    def test_vocabulary_and_monotone_offsets(self):
        tr = RequestTrace("r1", pack_key=(1e-4, 1e-6, 1e-10, None),
                          lanes=3)
        for stage in ("coalesced", "admitted", "first_harvest",
                      "resolved"):
            tr.mark(stage)
        offs = tr.stages()
        assert list(offs) == ["submitted", "coalesced", "admitted",
                              "first_harvest", "resolved"]
        vals = list(offs.values())
        assert vals == sorted(vals) and vals[0] == 0.0
        segs = tr.segments()
        assert all(d >= 0 for d in segs.values())
        assert tr.total_s() == pytest.approx(sum(segs.values()))

    def test_mark_idempotent_first_wins(self):
        tr = RequestTrace("r1")
        assert tr.mark("first_harvest", at=tr.at("submitted") + 1.0)
        assert not tr.mark("first_harvest",
                           at=tr.at("submitted") + 9.0)
        assert tr.stages()["first_harvest"] == pytest.approx(1.0)

    def test_unknown_stage_is_loud(self):
        with pytest.raises(ValueError, match="unknown trace stage"):
            RequestTrace("r1").mark("harvested")

    def test_stalled_rides_between_harvest_and_resolve(self):
        tr = RequestTrace("r1")
        t0 = tr.at("submitted")
        tr.mark("admitted", at=t0 + 0.1)
        tr.mark("first_harvest", at=t0 + 0.2)
        tr.mark("stalled", at=t0 + 0.25)
        tr.mark("resolved", at=t0 + 0.75)
        segs = tr.segments()
        assert segs["stalled"] == pytest.approx(0.05)
        assert segs["resolved"] == pytest.approx(0.5)

    def test_exports_are_versioned_and_jsonable(self):
        tr = RequestTrace("r9", pack_key=(1e-4, 1e-6, 1e-10, None),
                          lanes=2)
        tr.mark("resolved")
        payload = tr.to_payload()
        assert payload["v"] == T.TRACE_VERSION
        attrs = tr.to_attrs()
        assert attrs["request"] == "r9" and attrs["lanes"] == 2
        json.dumps(attrs)   # the recorder-event JSONL contract


# --------------------------------------------------------------------------
# histogram math + exports
# --------------------------------------------------------------------------
class TestHistograms:
    def test_observe_merge_quantile(self):
        h = C.hist_new()
        for v in (0.001, 0.001, 0.004, 0.03, 0.5):
            C.hist_observe(h, v)
        assert h["count"] == 5 and sum(h["counts"]) == 5
        assert h["sum"] == pytest.approx(0.536)
        m = C.hist_merge(h, h)
        assert m["count"] == 10 and m["sum"] == pytest.approx(1.072)
        # the single-slot ladder invariant: quantiles bracket the data
        assert 0.0008 <= C.hist_quantile(h, 0.5) <= 0.0064
        assert C.hist_quantile(C.hist_new(), 0.5) is None
        assert C.hist_mean(h) == pytest.approx(0.536 / 5)

    def test_overflow_quantile_is_top_edge(self):
        h = C.hist_observe(C.hist_new(), 1e6)
        assert C.hist_quantile(h, 0.99) == C.HIST_BUCKET_EDGES[-1]

    def test_merge_rejects_schema_mismatch(self):
        a, b = C.hist_new(), C.hist_new()
        b["counts"] = b["counts"][:-1]
        with pytest.raises(ValueError, match="bucket schemas differ"):
            C.hist_merge(a, b)

    def test_family_registered_with_histogram_semantics(self):
        fams = [meta for meta in C.FAMILIES.values()
                if tuple(meta["keys"]) == C.HIST_KEYS]
        assert len(fams) == 1
        assert fams[0]["semantics"] == "histogram"
        assert fams[0]["missing_zero"]

    def _recorder_with_hist(self):
        r = Recorder()
        r.counter("serve_answered", 3)
        for v in (0.002, 0.02, 0.2):
            r.observe("serve_stage_seconds", v, stage="total")
        r.observe("serve_stage_seconds", 0.01, stage="first_harvest")
        return r

    def test_jsonl_round_trip_exact(self):
        rep = build_report(recorder=self._recorder_with_hist())
        assert from_jsonl(to_jsonl(rep)) == rep
        series = rep["histograms"]["serve_stage_seconds"]
        assert {tuple(s["labels"].items()) for s in series} == {
            (("stage", "first_harvest"),), (("stage", "total"),)}

    def test_prometheus_exposition_bucket_sum_count(self):
        """The serve_latency_s migration regression: the exposition
        carries the full histogram triple (cumulative buckets closing
        at +Inf == _count) and NO summed latency counter."""
        prom = to_prometheus(
            build_report(recorder=self._recorder_with_hist()))
        assert "# TYPE br_serve_stage_seconds histogram" in prom
        assert ('br_serve_stage_seconds_bucket{le="+Inf",'
                'stage="total"} 3') in prom
        assert 'br_serve_stage_seconds_count{stage="total"} 3' in prom
        assert 'br_serve_stage_seconds_sum{stage="total"}' in prom
        # cumulative: each bucket line's value never decreases
        cums = [int(ln.rsplit(" ", 1)[1]) for ln in prom.splitlines()
                if ln.startswith("br_serve_stage_seconds_bucket")
                and 'stage="total"' in ln]
        assert cums == sorted(cums)
        assert "serve_latency_s" not in prom

    def test_diff_missing_is_empty(self):
        """obs.diff on reports with/without the histogram family: the
        missing side reads as empty (n 0), never None."""
        with_h = build_report(recorder=self._recorder_with_hist())
        without = build_report(recorder=Recorder())
        out = diff(without, with_h)
        assert 'hist serve_stage_seconds{stage="total"}: n 0 -> 3' \
            in out
        assert "None" not in out
        assert diff(with_h, with_h).splitlines()[-1].startswith(
            "  (no differences")


# --------------------------------------------------------------------------
# scheduler capture (fake session — no device work)
# --------------------------------------------------------------------------
@pytest.fixture(autouse=True)
def _disarm_inject():
    yield
    inject.disarm()


class TestSchedulerCapture:
    def _serve(self, sess, requests, timeout=10.0):
        sched = Scheduler(sess).start()
        futs = [sched.submit(r) for r in requests]
        results = [f.result(timeout) for f in futs]
        sched.drain(5.0)
        return results

    @pytest.mark.parametrize("order", ["fifo", "reverse", "scramble"])
    def test_stages_marked_monotone_under_any_harvest_order(self,
                                                            order):
        sess = FakeSession(harvest=order)
        results = self._serve(sess, [
            _request("a", [1000.0, 1100.0, 1200.0]),
            _request("b", [1300.0])])
        for res in results:
            tr = res.trace
            offs = list(tr.stages().values())
            assert offs == sorted(offs)
            assert set(tr.stages()) == {"submitted", "coalesced",
                                        "admitted", "first_harvest",
                                        "resolved"}
            assert res.elapsed_s == pytest.approx(tr.total_s())

    def test_histograms_and_trace_events_recorded(self):
        sess = FakeSession()
        self._serve(sess, [_request("a", [1000.0]),
                           _request("b", [1100.0, 1200.0])])
        hists = sess.recorder.hist_snapshot()
        fam = hists["serve_stage_seconds"]
        by_stage = {ser["labels"]["stage"]: ser["count"]
                    for ser in fam}
        assert by_stage["total"] == 2
        assert by_stage["first_harvest"] == 2
        _s, events, counters = sess.recorder.snapshot()
        traces = [e for e in events if e["name"] == "request_trace"]
        assert {e["attrs"]["request"] for e in traces} == {"a", "b"}
        assert all(e["attrs"]["v"] == T.TRACE_VERSION for e in traces)
        # the migrated counter must be gone
        assert "serve_latency_s" not in counters

    def test_trace_off_payload_byte_identical(self):
        """The trace-off no-op: with ``trace`` absent the response
        payload carries exactly the pre-trace keys (and an explicit
        ``trace: false`` is indistinguishable from absent)."""
        reqs = [schema.validate_request(_req(id=i, T=[1000.0], **kw))
                for i, kw in (("plain", {}), ("off", {"trace": False}),
                              ("on", {"trace": True}))]
        sess = FakeSession()
        by_id = {r.request.id: r for r in self._serve(sess, reqs)}

        def payload(res):
            # the render_result trace gate, minus the session's
            # device-side rendering (fake session has none)
            out = {"elapsed_ms": round(1e3 * res.elapsed_s, 3)}
            if getattr(res.request, "trace", False) \
                    and res.trace is not None:
                out["trace"] = res.trace.to_payload()
            return out

        assert set(payload(by_id["plain"])) == {"elapsed_ms"}
        assert set(payload(by_id["off"])) == {"elapsed_ms"}
        assert set(payload(by_id["on"])) == {"elapsed_ms", "trace"}
        tr = payload(by_id["on"])["trace"]
        assert tr["v"] == T.TRACE_VERSION and tr["lanes"] == 1

    def test_stalled_stage_under_injection(self):
        inject.arm("slow_request:delay=0.2,request=slow")
        sess = FakeSession()
        results = self._serve(sess, [_request("slow", [1000.0])])
        segs = results[0].trace.segments()
        assert segs["stalled"] >= 0  # stall opens the stage...
        assert segs["resolved"] >= 0.2  # ...and resolve carries it
        by_stage = {ser["labels"]["stage"]: ser
                    for ser in sess.recorder.hist_snapshot()
                    ["serve_stage_seconds"]}
        assert by_stage["resolved"]["sum"] >= 0.2

    def test_slow_request_threshold_event_arms_flight(self):
        from batchreactor_tpu.obs.live import (arm_flight,
                                               disarm_flight)

        inject.arm("slow_request:delay=0.15,request=slow")
        sess = FakeSession(slow_request_s=0.1)
        flight = arm_flight(recorder=sess.recorder,
                            install_signal=False)
        try:
            self._serve(sess, [_request("slow", [1000.0]),
                               _request("fast", [1100.0])])
        finally:
            disarm_flight()
        _s, events, _c = sess.recorder.snapshot()
        slow = [e for e in events if e["name"] == "slow_request"]
        # BOTH requests breach: the injected stall pauses the driver
        # thread exactly where a slow consumer would, so the
        # co-harvested "fast" request feels it too (the inject.py
        # contract) — and its waterfall shows where the time went
        assert {e["attrs"]["request"] for e in slow} == {"slow",
                                                         "fast"}
        by_id = {e["attrs"]["request"]: e["attrs"] for e in slow}
        assert by_id["slow"]["total_s"] >= 0.1
        assert "stalled" in by_id["slow"]["stages"]
        assert "stalled" not in by_id["fast"]["stages"]
        # the flight ring saw the event AND the armed counter snapshot
        kinds = [r["kind"] for r in flight.records()]
        assert "counter_snapshot" in kinds
        assert any(r.get("name") == "slow_request"
                   for r in flight.records() if r["kind"] == "event")

    def test_failed_requests_skip_histograms(self):
        sess = FakeSession(fail=True)
        sched = Scheduler(sess).start()
        fut = sched.submit(_request("dead", [1000.0]))
        with pytest.raises(RuntimeError):
            fut.result(5.0)
        sched.drain(5.0)
        assert "serve_stage_seconds" not in \
            sess.recorder.hist_snapshot()
        _s, events, _c = sess.recorder.snapshot()
        tr = [e for e in events if e["name"] == "request_trace"]
        assert len(tr) == 1 and tr[0]["attrs"]["failed"] is True


# --------------------------------------------------------------------------
# schema: the trace request key
# --------------------------------------------------------------------------
class TestTraceKey:
    def test_default_false_and_not_in_pack_key(self):
        r = schema.validate_request(_req())
        assert r.trace is False
        r_on = schema.validate_request(_req(trace=True))
        assert r_on.trace is True
        assert r.pack_key() == r_on.pack_key()

    def test_non_boolean_is_loud(self):
        with pytest.raises(ValueError, match="trace must be a JSON "
                                             "boolean"):
            schema.validate_request(_req(trace="yes"))


class TestFleetHistograms:
    def test_snapshot_merge_and_fleet_exposition(self, tmp_path):
        """Per-host snapshots carry the latency histograms, merge_fleet
        sums them slot-wise, and the fleet exposition renders the
        merged family — the cross-host latency view."""
        from batchreactor_tpu.obs.live import (LiveRegistry,
                                               fleet_prometheus,
                                               merge_fleet,
                                               read_fleet_snapshots,
                                               write_fleet_snapshot)

        for pid, durs in ((0, (0.01, 0.02)), (1, (0.04,))):
            rec = Recorder()
            for d in durs:
                rec.observe("serve_stage_seconds", d, stage="total")
            write_fleet_snapshot(str(tmp_path), pid,
                                 LiveRegistry(recorder=rec))
        snaps = read_fleet_snapshots(str(tmp_path))
        merged = merge_fleet(snaps)
        ser = merged["histograms"]["serve_stage_seconds"][0]
        assert ser["labels"] == {"stage": "total"}
        assert ser["count"] == 3
        assert ser["sum"] == pytest.approx(0.07)
        prom = fleet_prometheus(snaps)
        assert ('br_fleet_serve_stage_seconds_count{stage="total"} 3'
                in prom)
        assert 'br_fleet_serve_stage_seconds_bucket{le="+Inf"' in prom

    def test_merge_tolerates_pre_histogram_snapshots(self):
        from batchreactor_tpu.obs.live import merge_fleet

        merged = merge_fleet([{"pid": 0, "counters": {"x": 1},
                               "gauges": {}}])
        assert merged["histograms"] == {}
        assert merged["counters"] == {"x": 1}


class TestClientTraceSummary:
    def _record(self, rid, latency_s, total_s, segments):
        return {"id": rid, "ok": True, "latency_s": latency_s,
                "send_at": 0.0, "code": None,
                "response": {"trace": {"v": 1, "total_s": total_s,
                                       "segments": segments,
                                       "stages": {}, "lanes": 1}}}

    def test_stage_decomposition_and_attribution(self):
        from batchreactor_tpu.serving.client import trace_summary

        recs = [self._record(f"r{i}", 0.05 + 0.01 * i, 0.04 + 0.01 * i,
                             {"coalesced": 0.01,
                              "first_harvest": 0.02 + 0.01 * i,
                              "resolved": 0.01})
                for i in range(4)]
        s = trace_summary(recs, attribution_tol_ms=100.0)
        assert set(s["server_stages"]) == {"coalesced", "first_harvest",
                                           "resolved"}
        assert s["server_stages"]["coalesced"]["p50_ms"] == 10.0
        assert s["attribution"]["ok"]
        assert s["attribution"]["max_gap_ms"] == pytest.approx(10.0)

    def test_attribution_violations(self):
        from batchreactor_tpu.serving.client import trace_summary

        good = self._record("good", 0.05, 0.04, {})
        server_exceeds = self._record("impossible", 0.02, 0.08, {})
        huge_gap = self._record("gap", 3.0, 0.04, {})
        s = trace_summary([good, server_exceeds, huge_gap],
                          attribution_tol_ms=500.0)
        assert not s["attribution"]["ok"]
        assert {v["id"] for v in s["attribution"]["violations"]} == {
            "impossible", "gap"}

    def test_none_without_traces(self):
        from batchreactor_tpu.serving.client import trace_summary

        assert trace_summary([{"id": "x", "ok": True, "latency_s": 0.1,
                               "response": {}}]) is None


# --------------------------------------------------------------------------
# the gate + waterfall CLIs
# --------------------------------------------------------------------------
def _bench_like_report():
    r = Recorder()
    r.counter("serve_requests", 5)
    r.counter("serve_answered", 5)
    for i in range(5):
        tr = RequestTrace(f"req-{i}", pack_key=(1e-4, 1e-6, 1e-10,
                                                None), lanes=1)
        t0 = tr.at("submitted")
        tr.mark("coalesced", at=t0 + 0.001 * (i + 1))
        tr.mark("admitted", at=t0 + 0.002 * (i + 1))
        tr.mark("first_harvest", at=t0 + 0.01 * (i + 1))
        tr.mark("resolved", at=t0 + 0.012 * (i + 1))
        for stage, dur in tr.segments().items():
            r.observe("serve_stage_seconds", dur, stage=stage)
        r.observe("serve_stage_seconds", tr.total_s(), stage="total")
        r.event("request_trace", **tr.to_attrs())
    return build_report(recorder=r, meta={"entry": "serving"})


class TestObsGateCLI:
    BASELINE = {
        "schema": "br-obs-gate-v1",
        "counters": {"serve_answered": {"equals": 5},
                     "serve_failed": {"max": 0}},
        "histograms": {"serve_stage_seconds": {
            "stage=total": {"count": {"equals": 5},
                            "p50_s": {"max": 1.0},
                            "p99_s": {"max": 2.0}}}},
        "compile": {"retraces": {"max": 0}},
    }

    def _run(self, tmp_path, baseline, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_gate
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        base_path = tmp_path / "base.json"
        base_path.write_text(json.dumps(baseline))
        rc = obs_gate.main(["--baseline", str(base_path),
                            "--report", str(rep_path)])
        return rc, capsys.readouterr()

    def test_passes_in_band(self, tmp_path, capsys):
        rc, out = self._run(tmp_path, self.BASELINE, capsys)
        assert rc == 0
        assert "gate passed" in out.out
        assert "[FAIL]" not in out.out

    def test_fails_loudly_on_perturbation(self, tmp_path, capsys):
        bad = json.loads(json.dumps(self.BASELINE))
        bad["histograms"]["serve_stage_seconds"]["stage=total"][
            "p50_s"]["max"] = 1e-6
        bad["counters"]["serve_answered"]["equals"] = 7
        rc, out = self._run(tmp_path, bad, capsys)
        assert rc == 1
        assert "GATE FAILED: 2 band(s)" in out.err
        assert "p50_s" in out.err and "serve_answered" in out.err

    def test_missing_histogram_fails_quantile_band(self, tmp_path,
                                                   capsys):
        """A disappeared metric must fail, not vacuously pass: a
        quantile band against an absent series reads 'no
        observations'."""
        bad = json.loads(json.dumps(self.BASELINE))
        bad["histograms"]["serve_stage_seconds"] = {
            "stage=nonexistent": {"p50_s": {"max": 1.0}}}
        rc, out = self._run(tmp_path, bad, capsys)
        assert rc == 1
        assert "no observations" in out.err

    def test_unknown_sections_and_bands_are_loud(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        from obs_gate import run_gate

        with pytest.raises(ValueError, match="unknown gate section"):
            run_gate({"frontier": {}}, _bench_like_report())
        with pytest.raises(ValueError, match="unknown band key"):
            run_gate({"counters": {"x": {"atmost": 1}}},
                     _bench_like_report())


class TestObsTraceCLI:
    def test_waterfall_render_and_slowest(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        out_path = tmp_path / "wf.txt"
        rc = obs_trace.main([str(rep_path), "--slowest", "2",
                             "--out", str(out_path)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "2 requests, slowest first" in text
        # slowest first: req-4 (60ms total) before req-3
        assert text.index("req-4") < text.index("req-3")
        assert "submitted -> coalesced" in text
        assert "admitted -> first_harvest" in text
        assert out_path.read_text().strip() == text.strip()

    def test_json_and_threshold(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        rc = obs_trace.main([str(rep_path), "--threshold-ms", "40",
                             "--json"])
        assert rc == 0
        recs = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines()]
        # only req-3 (48ms) and req-4 (60ms) pass the 40ms threshold
        assert {r["request"] for r in recs} == {"req-3", "req-4"}


# --------------------------------------------------------------------------
# trace context: the propagated fleet identity (docs/observability.md
# "Fleet tracing")
# --------------------------------------------------------------------------
class TestTraceCtx:
    def test_payload_validate_round_trip(self):
        ctx = schema.trace_ctx_payload("t-run-1", span="route:2", hop=2)
        assert ctx == {"v": schema.TRACE_CTX_VERSION, "trace": "t-run-1",
                       "span": "route:2", "hop": 2}
        assert schema.validate_trace_ctx(ctx) == ("t-run-1", "route:2", 2)
        # root context: span omitted, hop 0 omitted from the wire form
        root = schema.trace_ctx_payload("t-root")
        assert root == {"v": schema.TRACE_CTX_VERSION, "trace": "t-root"}
        assert schema.validate_trace_ctx(root) == ("t-root", None, 0)
        assert schema.validate_trace_ctx(None) is None

    @pytest.mark.parametrize("ctx,match", [
        ("t-1", "must be a JSON object"),
        ({"trace": "t", "spam": 1}, "unknown trace_ctx key"),
        ({"v": 2, "trace": "t"}, "unsupported trace_ctx version"),
        ({"v": 1}, "non-empty"),
        ({"trace": ""}, "non-empty"),
        ({"trace": "t", "span": ""}, "parent-span-id"),
        ({"trace": "t", "span": 7}, "parent-span-id"),
        ({"trace": "t", "hop": -1}, "integer >= 0"),
        ({"trace": "t", "hop": True}, "integer >= 0"),
        ({"trace": "t", "hop": 1.5}, "integer >= 0"),
    ])
    def test_loud_validation(self, ctx, match):
        with pytest.raises(ValueError, match=match):
            schema.validate_trace_ctx(ctx, "r1")

    def test_request_field_and_pack_key_exclusion(self):
        """``trace_ctx`` rides the request as the normalized tuple and
        NEVER enters the pack key — trace identity must not split a
        batch."""
        plain = schema.validate_request(_req())
        assert plain.trace_ctx is None
        traced = schema.validate_request(_req(
            trace_ctx=schema.trace_ctx_payload("t-9", span="client")))
        assert traced.trace_ctx == ("t-9", "client", 0)
        assert plain.pack_key() == traced.pack_key()

    def test_adopt_and_attrs_byte_identity(self):
        """``to_attrs`` adds the fleet identity ONLY after adoption —
        a ctx-less trace exports exactly the pre-fleet attribute set
        (the byte-identity regression the acceptance pins)."""
        bare = RequestTrace("r1").to_attrs()
        assert not {"trace", "parent_span", "hop"} & set(bare)
        tr = RequestTrace("r1")
        assert tr.adopt("t-77", parent_span="route:3", hop=3) is tr
        attrs = tr.to_attrs()
        assert attrs["trace"] == "t-77"
        assert attrs["parent_span"] == "route:3"
        assert attrs["hop"] == 3
        assert set(attrs) - set(bare) == {"trace", "parent_span", "hop"}
        with pytest.raises(ValueError, match="non-empty trace id"):
            RequestTrace("r1").adopt("")

    def test_scheduler_adopts_inherited_ctx(self):
        """A request carrying ``trace_ctx`` resolves with its member
        ``request_trace`` event tagged with the inherited identity; a
        ctx-less sibling's event stays untagged."""
        sess = FakeSession()
        sched = Scheduler(sess).start()
        futs = [
            sched.submit(schema.validate_request(_req(
                id="traced", T=[1000.0],
                trace_ctx=schema.trace_ctx_payload(
                    "t-fleet", span="route:1", hop=1)))),
            sched.submit(schema.validate_request(_req(
                id="plain", T=[1100.0])))]
        for f in futs:
            f.result(10.0)
        sched.drain(5.0)
        _s, events, _c = sess.recorder.snapshot()
        by_id = {e["attrs"]["request"]: e["attrs"] for e in events
                 if e["name"] == "request_trace"}
        assert by_id["traced"]["trace"] == "t-fleet"
        assert by_id["traced"]["parent_span"] == "route:1"
        assert by_id["traced"]["hop"] == 1
        assert not {"trace", "parent_span", "hop"} & set(by_id["plain"])


class TestCoalesceTelemetry:
    def test_window_histogram_and_mode_label(self):
        """ISSUE-18 satellite: a coalescing scheduler records the
        window each epoch closed at as the ``coalesce_window_s``
        histogram, labeled by lever mode."""
        sess = FakeSession(coalesce_s=0.01)
        sched = Scheduler(sess).start()
        sched.submit(_request("a", [1000.0])).result(10.0)
        sched.drain(5.0)
        fam = sess.recorder.hist_snapshot()["coalesce_window_s"]
        assert [ser["labels"] for ser in fam] == [{"mode": "fixed"}]
        assert fam[0]["count"] >= 1
        assert fam[0]["sum"] <= 0.011 * fam[0]["count"]

    def test_adaptive_mode_label_and_family_registered(self):
        sess = FakeSession(coalesce_s=0.01, coalesce_adaptive=True)
        sched = Scheduler(sess).start()
        sched.submit(_request("a", [1000.0])).result(10.0)
        sched.drain(5.0)
        fam = sess.recorder.hist_snapshot()["coalesce_window_s"]
        assert [ser["labels"] for ser in fam] == [{"mode": "adaptive"}]
        # FAMILIES enrollment (the brlint tier-C audit contract)
        fams = [meta for meta in C.FAMILIES.values()
                if tuple(meta["keys"]) == C.COALESCE_HIST_KEYS]
        assert len(fams) == 1
        assert fams[0]["semantics"] == "histogram"
        assert fams[0]["missing_zero"]

    def test_no_window_no_family(self):
        """``coalesce_s=0`` (the default) records nothing — the
        telemetry must not invent a distribution for a disabled
        lever."""
        sess = FakeSession()
        sched = Scheduler(sess).start()
        sched.submit(_request("a", [1000.0])).result(10.0)
        sched.drain(5.0)
        assert "coalesce_window_s" not in sess.recorder.hist_snapshot()


# --------------------------------------------------------------------------
# the SLO monitor (obs/slo.py — docs/observability.md "SLO monitor")
# --------------------------------------------------------------------------
class TestSloObjectives:
    def test_defaults_cover_the_vocabulary(self):
        from batchreactor_tpu.obs import slo

        assert [o.kind for o in slo.DEFAULT_OBJECTIVES] == [
            "latency", "error", "failover"]

    @pytest.mark.parametrize("kw,match", [
        (dict(name="", kind="error", budget=0.1), "non-empty"),
        (dict(name="x", kind="uptime", budget=0.1), "unknown kind"),
        (dict(name="x", kind="error", budget=0.0), "fraction in"),
        (dict(name="x", kind="error", budget=1.0), "fraction in"),
        (dict(name="x", kind="latency", budget=0.1), "threshold_s > 0"),
        (dict(name="x", kind="error", budget=0.1, threshold_s=1.0),
         "only applies to latency"),
    ])
    def test_loud_validation(self, kw, match):
        from batchreactor_tpu.obs.slo import Objective

        with pytest.raises(ValueError, match=match):
            Objective(**kw)

    def test_bad_semantics(self):
        from batchreactor_tpu.obs.slo import Objective

        lat = Objective("l", "latency", 0.05, threshold_s=1.0)
        err = Objective("e", "error", 0.01)
        fo = Objective("f", "failover", 0.05)
        # a failed request is the ERROR objective's problem, not the
        # latency one's (its latency is a rejection's, not a solve's)
        assert lat.bad(2.0, ok=True, failover=False)
        assert not lat.bad(2.0, ok=False, failover=False)
        assert not lat.bad(0.5, ok=True, failover=False)
        assert err.bad(0.1, ok=False, failover=False)
        assert not err.bad(9.9, ok=True, failover=True)
        assert fo.bad(0.1, ok=True, failover=True)
        assert not fo.bad(0.1, ok=True, failover=False)


class TestSloMonitor:
    def _monitor(self, rec=None, **kw):
        from batchreactor_tpu.obs.slo import SloMonitor

        kw.setdefault("window_s", 300.0)
        kw.setdefault("fast_window_s", 30.0)
        return SloMonitor(recorder=rec, **kw)

    def test_multi_window_burn_and_transition_events(self):
        """The SRE-workbook shape: the alert fires only when BOTH
        windows burn past the threshold, and each state TRANSITION is
        one ``slo_alert`` event + one ``slo_alerts`` count."""
        rec = Recorder()
        mon = self._monitor(rec)
        t0 = 1_000_000.0
        # 20 good-but-slow samples: latency_p95 burn = 1.0/0.05 = 20
        for i in range(20):
            mon.record(3.5, ok=True, at=t0 + i)
        res = mon.evaluate(now=t0 + 20)
        lat = res["latency_p95"]
        assert lat["requests"] == 20 and lat["bad"] == 20
        assert lat["burn"] == pytest.approx(20.0)
        assert lat["fast"]["burn"] == pytest.approx(20.0)
        assert lat["alerting"] is True
        assert res["error_rate"]["alerting"] is False
        # the bleeding stops: fast window clears first, alert resolves
        for i in range(40):
            mon.record(0.01, ok=True, at=t0 + 60 + i)
        res2 = mon.evaluate(now=t0 + 60 + 40)
        assert res2["latency_p95"]["fast"]["bad"] == 0
        assert res2["latency_p95"]["alerting"] is False
        _s, events, counters = rec.snapshot()
        alerts = [e["attrs"] for e in events if e["name"] == "slo_alert"]
        assert [(a["objective"], a["state"]) for a in alerts] == [
            ("latency_p95", "firing"), ("latency_p95", "resolved")]
        assert counters["slo_alerts"] == 2
        # FAMILIES enrollment (the brlint tier-C audit contract)
        fams = [meta for meta in C.FAMILIES.values()
                if tuple(meta["keys"]) == C.SLO_KEYS]
        assert len(fams) == 1 and fams[0]["missing_zero"]

    def test_one_spike_does_not_page(self):
        """A burst confined to the fast window must not alert while the
        slow window's burn stays under the threshold."""
        mon = self._monitor()
        t0 = 2_000_000.0
        for i in range(300):
            mon.record(0.01, ok=True, at=t0 + i * 0.9)
        mon.record(0.01, ok=False, at=t0 + 271.0)
        res = mon.evaluate(now=t0 + 272.0)
        err = res["error_rate"]
        assert err["fast"]["burn"] >= 2.0      # the spike, fast window
        assert err["burn"] < 2.0               # diluted, slow window
        assert err["alerting"] is False

    def test_window_trim_and_empty_windows(self):
        mon = self._monitor()
        t0 = 3_000_000.0
        mon.record(0.1, ok=False, at=t0)
        res = mon.evaluate(now=t0 + 301.0)     # aged out of the window
        assert all(r["requests"] == 0 and not r["alerting"]
                   for r in res.values())

    def test_prometheus_gauges(self):
        mon = self._monitor()
        t0 = 4_000_000.0
        for i in range(10):
            mon.record(0.01, ok=(i != 0), failover=(i == 1), at=t0 + i)
        prom = mon.prometheus(now=t0 + 10)
        assert '# TYPE br_slo_requests gauge' in prom
        assert 'br_slo_requests{window="slow"} 10' in prom
        assert ('br_slo_bad_fraction{objective="error_rate",'
                'window="slow"} 0.1') in prom
        assert ('br_slo_burn_rate{objective="failover_rate",'
                'window="slow"} 2' in prom)
        assert 'br_slo_alert{objective="latency_p95"} 0' in prom

    def test_constructor_loudness(self):
        from batchreactor_tpu.obs.slo import Objective, SloMonitor

        with pytest.raises(ValueError, match="at least one"):
            SloMonitor(objectives=())
        with pytest.raises(ValueError, match="duplicate objective"):
            SloMonitor(objectives=(Objective("x", "error", 0.1),
                                   Objective("x", "failover", 0.1)))
        with pytest.raises(ValueError, match="must sit inside"):
            SloMonitor(fast_window_s=400.0)
        with pytest.raises(ValueError, match="burn_alert"):
            SloMonitor(burn_alert=0.0)

    def test_evaluate_traces_offline(self):
        from batchreactor_tpu.obs.slo import Objective, evaluate_traces

        traces = ([{"total_s": 0.1, "failover": False}] * 8
                  + [{"total_s": 9.0, "failover": True}]
                  + [{"total_s": 0.2, "failed": True,
                      "code": "internal"}]
                  + [{"total_s": None}])    # unmeasured: skipped
        res = evaluate_traces(traces, (
            Objective("lat", "latency", 0.5, threshold_s=2.5),
            Objective("err", "error", 0.05),
            Objective("fo", "failover", 0.05)))
        assert res["lat"]["requests"] == 10
        assert res["lat"]["bad"] == 1 and res["lat"]["ok"]
        assert res["err"]["bad"] == 1 and not res["err"]["ok"]
        assert res["fo"]["bad_fraction"] == pytest.approx(0.1)
        assert not res["fo"]["ok"]


# --------------------------------------------------------------------------
# cross-host stitching (obs/stitch.py — docs/observability.md
# "Fleet tracing")
# --------------------------------------------------------------------------
def _fleet_reports(skew_s=0.0):
    """A synthetic two-member fleet run: request ``fo`` fails over from
    m1 (transport death) to m2; request ``ok`` routes direct to m1;
    ``lone`` hit m2 without a router.  ``skew_s`` shifts the members'
    wall clocks to exercise the correction."""
    t0 = 1_700_000_000.0
    router = Recorder()
    router.counter("route_requests", 2)
    router.counter("route_failovers", 1)
    router.observe("route_seconds", 0.3, path="failover")
    router.observe("route_seconds", 0.05, path="direct")
    router.event("request_trace", request="fo", v=1, span="route",
                 trace="t-fo", parent_span="client", minted=False,
                 hop=0, wall_start=t0, total_s=0.3, failover=True,
                 tried=["m1"], host="m2", hops=[
                     {"member": "m1", "hop": 1, "send_wall": t0,
                      "recv_wall": t0 + 0.05, "outcome": "transport"},
                     {"member": "m2", "hop": 2,
                      "send_wall": t0 + 0.06,
                      "recv_wall": t0 + 0.3, "outcome": "ok"}])
    router.event("request_trace", request="ok", v=1, span="route",
                 trace="r-deadbeef", minted=True, hop=0,
                 wall_start=t0 + 1.0, total_s=0.05, failover=False,
                 tried=[], host="m1", hops=[
                     {"member": "m1", "hop": 1, "send_wall": t0 + 1.0,
                      "recv_wall": t0 + 1.05, "outcome": "ok"}])

    def member(name, rid, tid, hop, wall, total, parent):
        rec = Recorder()
        rec.counter("serve_answered", 1)
        tr = RequestTrace(rid, lanes=1)
        tr.adopt(tid, parent_span=parent, hop=hop)
        t_sub = tr.at("submitted")
        tr.mark("coalesced", at=t_sub + 0.01)
        tr.mark("admitted", at=t_sub + 0.02)
        tr.mark("first_harvest", at=t_sub + total - 0.01)
        tr.mark("resolved", at=t_sub + total)
        for stage, dur in tr.segments().items():
            rec.observe("serve_stage_seconds", dur, stage=stage)
        attrs = tr.to_attrs()
        attrs["wall_start"] = round(wall, 6)   # scripted clock
        attrs["total_s"] = round(total, 6)
        rec.event("request_trace", **attrs)
        return rec

    # m2 solved "fo" inside the second bracket: 0.2s of member work in
    # a 0.24s bracket -> 0.02s slack per leg
    m2 = member("m2", "fo", "t-fo", 2, t0 + 0.08 + skew_s, 0.2,
                "route:2")
    # the same m2 stream also carries the router-less "lone" request
    lone = RequestTrace("lone", lanes=1)
    lone.mark("resolved", at=lone.at("submitted") + 0.4)
    lone_attrs = lone.to_attrs()
    lone_attrs["wall_start"] = round(t0 + 2.0, 6)   # scripted clock
    m2.event("request_trace", **lone_attrs)
    m1 = member("m1", "ok", "r-deadbeef", 1, t0 + 1.01 + skew_s, 0.03,
                "route:1")
    return [("m1", build_report(recorder=m1)),
            ("m2", build_report(recorder=m2)),
            ("router", build_report(recorder=router,
                                    meta={"entry": "fleet-router"}))]


class TestStitch:
    def test_failover_is_one_trace_with_dead_hop(self):
        from batchreactor_tpu.obs import stitch

        traces = stitch.stitch(_fleet_reports())
        by_req = {t["request"]: t for t in traces}
        fo = by_req["fo"]
        assert fo["trace"] == "t-fo" and fo["router"] == "router"
        assert fo["failover"] and fo["tried"] == ["m1"]
        assert fo["host"] == "m2" and not fo["minted"]
        assert [h["member"] for h in fo["hops"]] == ["m1", "m2"]
        dead, alive = fo["hops"]
        # the SIGKILLed attempt is PART of the trace: ledger only
        assert dead["outcome"] == "transport"
        assert "member_trace" not in dead
        # the survivor's waterfall joined, child of the router's span
        mt = alive["member_trace"]
        assert mt["parent_span"] == "route:2"
        assert mt["stages"]["resolved"] == pytest.approx(0.2)
        ok = by_req["ok"]
        assert ok["minted"] and ok["trace"] == "r-deadbeef"
        assert ok["hops"][0]["member_trace"]["parent_span"] == "route:1"

    @pytest.mark.parametrize("skew", [0.0, -7.5, 42.0])
    def test_clock_skew_correction(self, skew):
        """The member's wall start re-bases onto the router's send/recv
        bracket (slack split evenly), and ``skew_s`` reports how far
        the member's clock sat from that — invariant to the actual
        skew."""
        from batchreactor_tpu.obs import stitch

        traces = stitch.stitch(_fleet_reports(skew_s=skew))
        alive = next(t for t in traces
                     if t["request"] == "fo")["hops"][1]
        # bracket 0.24s, member total 0.2s -> corrected = send + 0.02
        t0 = 1_700_000_000.0
        assert alive["wall_start_corrected"] == pytest.approx(
            t0 + 0.06 + 0.02, abs=1e-6)
        assert alive["skew_s"] == pytest.approx(skew + 0.0, abs=1e-3)

    def test_routerless_member_trace_is_single_hop(self):
        from batchreactor_tpu.obs import stitch

        traces = stitch.stitch(_fleet_reports())
        lone = next(t for t in traces if t["request"] == "lone")
        assert lone["router"] is None and lone["trace"] is None
        assert [h["member"] for h in lone["hops"]] == ["m2"]
        assert lone["hops"][0]["outcome"] == "ok"
        assert lone["hops"][0]["member_trace"]["stages"]["resolved"] \
            == pytest.approx(0.4)

    def test_load_fleet_round_trip_and_loudness(self, tmp_path):
        from batchreactor_tpu.obs import stitch, write_jsonl

        for host, rep in _fleet_reports():
            write_jsonl(str(tmp_path / f"{host}.jsonl"), rep)
        loaded = stitch.load_fleet(str(tmp_path))
        assert [h for h, _ in loaded] == ["m1", "m2", "router"]
        assert stitch.stitch(loaded) == stitch.stitch(_fleet_reports())
        with pytest.raises(ValueError, match="unreadable"):
            stitch.load_fleet(str(tmp_path / "missing"))
        (tmp_path / "empty").mkdir()
        with pytest.raises(ValueError, match="no .*trace streams"):
            stitch.load_fleet(str(tmp_path / "empty"))

    def test_merge_reports_is_gateable(self):
        """The fleet merge is ONE br-obs-v1 report: counters summed,
        histogram families slot-merged — and obs_gate.py can band it
        like any single-host report."""
        from batchreactor_tpu.obs import stitch

        merged = stitch.merge_reports(_fleet_reports())
        assert merged["meta"]["hosts"] == ["m1", "m2", "router"]
        assert merged["counters"]["serve_answered"] == 2
        assert merged["counters"]["route_failovers"] == 1
        routes = merged["histograms"]["route_seconds"]
        assert {ser["labels"]["path"] for ser in routes} == {
            "direct", "failover"}
        stages = {ser["labels"]["stage"]: ser for ser in
                  merged["histograms"]["serve_stage_seconds"]}
        assert stages["resolved"]["count"] == 2     # m1 + m2 merged
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        from obs_gate import run_gate

        failures, _lines = run_gate({
            "counters": {"route_failovers": {"equals": 1}},
            "histograms": {"route_seconds": {
                "path=failover": {"count": {"equals": 1}}}},
        }, merged)
        assert failures == []

    def test_render_fleet_flags_and_bars(self):
        from batchreactor_tpu.obs import stitch

        text = stitch.render_fleet(stitch.stitch(_fleet_reports()))
        assert "fleet traces: 3 stitched" in text
        assert "FAILOVER tried=['m1']" in text
        assert "[transport]" in text and "[ok]" in text
        assert "skew=" in text and "bracket=" in text
        assert "resolved" in text and "minted" in text


class TestStitchedAttribution:
    """ISSUE-18 satellite: ``serve_bench.py --router`` asserts the
    client-side latency against the stitched end-to-end duration —
    the join is ``t-<request id>``, never a response field."""

    def _records(self):
        return [{"id": f"b{i}", "ok": True, "latency_s": 0.1 + 0.01 * i,
                 "send_at": float(i), "code": None, "response": {}}
                for i in range(3)]

    def _stitched(self, gap_s=0.005):
        return [{"trace": f"t-b{i}", "request": f"b{i}",
                 "total_s": 0.1 + 0.01 * i - gap_s}
                for i in range(3)]

    def test_joins_and_passes_within_tolerance(self):
        from batchreactor_tpu.serving.client import stitched_attribution

        s = stitched_attribution(self._records(), self._stitched(),
                                 attribution_tol_ms=50.0)
        assert s["n"] == 3 and s["ok"] and not s["violations"]
        assert s["max_gap_ms"] == pytest.approx(5.0)

    def test_violation_on_gap_and_impossible_server_time(self):
        from batchreactor_tpu.serving.client import stitched_attribution

        stitched = self._stitched()
        stitched[0]["total_s"] = 5.0       # server > client: impossible
        stitched[1]["total_s"] = 0.001     # huge unattributed gap
        s = stitched_attribution(self._records(), stitched,
                                 attribution_tol_ms=50.0)
        assert not s["ok"]
        assert {v["id"] for v in s["violations"]} == {"b0", "b1"}

    def test_none_when_nothing_joins(self):
        from batchreactor_tpu.serving.client import stitched_attribution

        assert stitched_attribution(self._records(), [],
                                    attribution_tol_ms=50.0) is None


# --------------------------------------------------------------------------
# the SLO gate CLI (scripts/obs_slo.py)
# --------------------------------------------------------------------------
class TestObsSloCLI:
    BASELINE = {
        "schema": "br-slo-gate-v1",
        "objectives": {
            "latency_p95": {"kind": "latency", "budget": 0.05,
                            "threshold_s": 2.5,
                            "bad_fraction": {"max": 0.05}},
            "error_rate": {"kind": "error", "budget": 0.01,
                           "bad": {"max": 0}},
            "failover_rate": {"kind": "failover", "budget": 0.6,
                              "bad_fraction": {"max": 0.6}}},
        "requests": {"min": 2},
    }

    def _fleet_dir(self, tmp_path):
        from batchreactor_tpu.obs import write_jsonl

        d = tmp_path / "obs"
        d.mkdir()
        for host, rep in _fleet_reports():
            write_jsonl(str(d / f"{host}.jsonl"), rep)
        return str(d)

    def _run(self, tmp_path, baseline, argv_extra=()):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_slo

        base_path = tmp_path / "slo_base.json"
        base_path.write_text(json.dumps(baseline))
        return obs_slo.main(["--fleet", self._fleet_dir(tmp_path),
                             "--gate", "--baseline", str(base_path),
                             *argv_extra])

    def test_gate_passes_in_band(self, tmp_path, capsys):
        rc = self._run(tmp_path, self.BASELINE)
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo gate ok" in out
        assert "3 stitched trace(s)" in out

    def test_gate_fails_on_breach(self, tmp_path, capsys):
        bad = json.loads(json.dumps(self.BASELINE))
        # 1/3 failovers breaches a 5% failover budget
        bad["objectives"]["failover_rate"]["budget"] = 0.05
        bad["objectives"]["failover_rate"]["bad_fraction"]["max"] = 0.05
        bad["requests"] = {"min": 50}
        rc = self._run(tmp_path, bad, ["--json"])
        out = capsys.readouterr().out
        assert rc == 1
        payload = json.loads(out.splitlines()[0])
        assert payload["traces"] == 3
        assert payload["objectives"]["failover_rate"]["ok"] is False

    def test_unknown_section_is_loud(self, tmp_path):
        bad = {**self.BASELINE, "frontier": {}}
        with pytest.raises(ValueError, match="unknown SLO gate"):
            self._run(tmp_path, bad)

    def test_checked_fixture_is_the_ci_contract(self, tmp_path,
                                                capsys):
        """The banked fleet baseline (tests/fixtures/
        fleet_slo_baseline.json — the CI fleet-smoke gate) parses,
        declares all three default objectives, and passes over the
        synthetic fleet run."""
        with open(os.path.join(REPO, "tests", "fixtures",
                               "fleet_slo_baseline.json")) as f:
            banked = json.load(f)
        assert banked["schema"] == "br-slo-gate-v1"
        assert set(banked["objectives"]) == {
            "latency_p95", "error_rate", "failover_rate"}
        banked = json.loads(json.dumps(banked))
        # re-scale the CI-sized floors to the 3-trace synthetic run
        # (1 deliberate failover in 3 is over the banked 25%, which is
        # sized for fleet-smoke's ~34 requests with ONE SIGKILL)
        banked["requests"] = {"min": 1}
        banked["objectives"]["failover_rate"]["budget"] = 0.5
        banked["objectives"]["failover_rate"]["bad_fraction"]["max"] \
            = 0.5
        rc = self._run(tmp_path, banked)
        assert rc == 0
        assert "slo gate ok" in capsys.readouterr().out


class TestObsTraceFleetCLI:
    def test_fleet_waterfalls_and_artifact(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        d = tmp_path / "obs"
        d.mkdir()
        for host, rep in _fleet_reports():
            write_jsonl(str(d / f"{host}.jsonl"), rep)
        out_path = tmp_path / "fleet_wf.txt"
        rc = obs_trace.main(["--fleet", str(d), "--slowest", "2",
                             "--out", str(out_path)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "fleet traces: 3 stitched, showing 2 slowest" in text
        assert "FAILOVER" in text
        assert out_path.read_text().strip() == text.strip()

    def test_fleet_json_records(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        d = tmp_path / "obs"
        d.mkdir()
        for host, rep in _fleet_reports():
            write_jsonl(str(d / f"{host}.jsonl"), rep)
        rc = obs_trace.main(["--fleet", str(d), "--json"])
        assert rc == 0
        recs = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines()]
        assert {r["request"] for r in recs} == {"fo", "ok", "lone"}

    def test_exactly_one_input_mode(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace

        with pytest.raises(SystemExit):
            obs_trace.main([])
        with pytest.raises(SystemExit):
            obs_trace.main(["rep.jsonl", "--fleet", str(tmp_path)])
