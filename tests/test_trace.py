"""Request-lifecycle tracing plane (obs/trace.py + the HISTOGRAM
family + the gate/waterfall CLIs — docs/observability.md "Request
tracing"/"Histograms").

Tiers, device-free by construction (the fake-session scheduler and the
obs layer import no jax):

* **RequestTrace** — vocabulary, idempotent marks, monotone stage
  offsets, payload/attrs exports;
* **histograms** — fixed-bucket observe/merge/quantile math, the
  report JSONL <-> Prometheus round trip, the ``serve_latency_s``
  migration regression, and ``obs.diff``'s missing->empty convention;
* **scheduler capture** against the fake session: all stages marked in
  order (out-of-order harvest included), the trace-off no-op (response
  payloads byte-identical with ``trace`` absent), the stalled stage
  under injection, and the ``slow_request`` threshold event;
* **CLIs** — ``scripts/obs_gate.py`` passing on in-band reports and
  failing loudly on perturbed ones; ``scripts/obs_trace.py``
  waterfalls + ``--slowest``.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from batchreactor_tpu.obs import (RequestTrace, Recorder,  # noqa: E402
                                  build_report, diff, from_jsonl,
                                  to_jsonl, to_prometheus)
from batchreactor_tpu.obs import counters as C  # noqa: E402
from batchreactor_tpu.obs import trace as T  # noqa: E402
from batchreactor_tpu.resilience import inject  # noqa: E402
from batchreactor_tpu.serving import schema  # noqa: E402
from batchreactor_tpu.serving.scheduler import Scheduler  # noqa: E402

from test_serving import FakeSession, _req, _request  # noqa: E402


# --------------------------------------------------------------------------
# RequestTrace
# --------------------------------------------------------------------------
class TestRequestTrace:
    def test_vocabulary_and_monotone_offsets(self):
        tr = RequestTrace("r1", pack_key=(1e-4, 1e-6, 1e-10, None),
                          lanes=3)
        for stage in ("coalesced", "admitted", "first_harvest",
                      "resolved"):
            tr.mark(stage)
        offs = tr.stages()
        assert list(offs) == ["submitted", "coalesced", "admitted",
                              "first_harvest", "resolved"]
        vals = list(offs.values())
        assert vals == sorted(vals) and vals[0] == 0.0
        segs = tr.segments()
        assert all(d >= 0 for d in segs.values())
        assert tr.total_s() == pytest.approx(sum(segs.values()))

    def test_mark_idempotent_first_wins(self):
        tr = RequestTrace("r1")
        assert tr.mark("first_harvest", at=tr.at("submitted") + 1.0)
        assert not tr.mark("first_harvest",
                           at=tr.at("submitted") + 9.0)
        assert tr.stages()["first_harvest"] == pytest.approx(1.0)

    def test_unknown_stage_is_loud(self):
        with pytest.raises(ValueError, match="unknown trace stage"):
            RequestTrace("r1").mark("harvested")

    def test_stalled_rides_between_harvest_and_resolve(self):
        tr = RequestTrace("r1")
        t0 = tr.at("submitted")
        tr.mark("admitted", at=t0 + 0.1)
        tr.mark("first_harvest", at=t0 + 0.2)
        tr.mark("stalled", at=t0 + 0.25)
        tr.mark("resolved", at=t0 + 0.75)
        segs = tr.segments()
        assert segs["stalled"] == pytest.approx(0.05)
        assert segs["resolved"] == pytest.approx(0.5)

    def test_exports_are_versioned_and_jsonable(self):
        tr = RequestTrace("r9", pack_key=(1e-4, 1e-6, 1e-10, None),
                          lanes=2)
        tr.mark("resolved")
        payload = tr.to_payload()
        assert payload["v"] == T.TRACE_VERSION
        attrs = tr.to_attrs()
        assert attrs["request"] == "r9" and attrs["lanes"] == 2
        json.dumps(attrs)   # the recorder-event JSONL contract


# --------------------------------------------------------------------------
# histogram math + exports
# --------------------------------------------------------------------------
class TestHistograms:
    def test_observe_merge_quantile(self):
        h = C.hist_new()
        for v in (0.001, 0.001, 0.004, 0.03, 0.5):
            C.hist_observe(h, v)
        assert h["count"] == 5 and sum(h["counts"]) == 5
        assert h["sum"] == pytest.approx(0.536)
        m = C.hist_merge(h, h)
        assert m["count"] == 10 and m["sum"] == pytest.approx(1.072)
        # the single-slot ladder invariant: quantiles bracket the data
        assert 0.0008 <= C.hist_quantile(h, 0.5) <= 0.0064
        assert C.hist_quantile(C.hist_new(), 0.5) is None
        assert C.hist_mean(h) == pytest.approx(0.536 / 5)

    def test_overflow_quantile_is_top_edge(self):
        h = C.hist_observe(C.hist_new(), 1e6)
        assert C.hist_quantile(h, 0.99) == C.HIST_BUCKET_EDGES[-1]

    def test_merge_rejects_schema_mismatch(self):
        a, b = C.hist_new(), C.hist_new()
        b["counts"] = b["counts"][:-1]
        with pytest.raises(ValueError, match="bucket schemas differ"):
            C.hist_merge(a, b)

    def test_family_registered_with_histogram_semantics(self):
        fams = [meta for meta in C.FAMILIES.values()
                if tuple(meta["keys"]) == C.HIST_KEYS]
        assert len(fams) == 1
        assert fams[0]["semantics"] == "histogram"
        assert fams[0]["missing_zero"]

    def _recorder_with_hist(self):
        r = Recorder()
        r.counter("serve_answered", 3)
        for v in (0.002, 0.02, 0.2):
            r.observe("serve_stage_seconds", v, stage="total")
        r.observe("serve_stage_seconds", 0.01, stage="first_harvest")
        return r

    def test_jsonl_round_trip_exact(self):
        rep = build_report(recorder=self._recorder_with_hist())
        assert from_jsonl(to_jsonl(rep)) == rep
        series = rep["histograms"]["serve_stage_seconds"]
        assert {tuple(s["labels"].items()) for s in series} == {
            (("stage", "first_harvest"),), (("stage", "total"),)}

    def test_prometheus_exposition_bucket_sum_count(self):
        """The serve_latency_s migration regression: the exposition
        carries the full histogram triple (cumulative buckets closing
        at +Inf == _count) and NO summed latency counter."""
        prom = to_prometheus(
            build_report(recorder=self._recorder_with_hist()))
        assert "# TYPE br_serve_stage_seconds histogram" in prom
        assert ('br_serve_stage_seconds_bucket{le="+Inf",'
                'stage="total"} 3') in prom
        assert 'br_serve_stage_seconds_count{stage="total"} 3' in prom
        assert 'br_serve_stage_seconds_sum{stage="total"}' in prom
        # cumulative: each bucket line's value never decreases
        cums = [int(ln.rsplit(" ", 1)[1]) for ln in prom.splitlines()
                if ln.startswith("br_serve_stage_seconds_bucket")
                and 'stage="total"' in ln]
        assert cums == sorted(cums)
        assert "serve_latency_s" not in prom

    def test_diff_missing_is_empty(self):
        """obs.diff on reports with/without the histogram family: the
        missing side reads as empty (n 0), never None."""
        with_h = build_report(recorder=self._recorder_with_hist())
        without = build_report(recorder=Recorder())
        out = diff(without, with_h)
        assert 'hist serve_stage_seconds{stage="total"}: n 0 -> 3' \
            in out
        assert "None" not in out
        assert diff(with_h, with_h).splitlines()[-1].startswith(
            "  (no differences")


# --------------------------------------------------------------------------
# scheduler capture (fake session — no device work)
# --------------------------------------------------------------------------
@pytest.fixture(autouse=True)
def _disarm_inject():
    yield
    inject.disarm()


class TestSchedulerCapture:
    def _serve(self, sess, requests, timeout=10.0):
        sched = Scheduler(sess).start()
        futs = [sched.submit(r) for r in requests]
        results = [f.result(timeout) for f in futs]
        sched.drain(5.0)
        return results

    @pytest.mark.parametrize("order", ["fifo", "reverse", "scramble"])
    def test_stages_marked_monotone_under_any_harvest_order(self,
                                                            order):
        sess = FakeSession(harvest=order)
        results = self._serve(sess, [
            _request("a", [1000.0, 1100.0, 1200.0]),
            _request("b", [1300.0])])
        for res in results:
            tr = res.trace
            offs = list(tr.stages().values())
            assert offs == sorted(offs)
            assert set(tr.stages()) == {"submitted", "coalesced",
                                        "admitted", "first_harvest",
                                        "resolved"}
            assert res.elapsed_s == pytest.approx(tr.total_s())

    def test_histograms_and_trace_events_recorded(self):
        sess = FakeSession()
        self._serve(sess, [_request("a", [1000.0]),
                           _request("b", [1100.0, 1200.0])])
        hists = sess.recorder.hist_snapshot()
        fam = hists["serve_stage_seconds"]
        by_stage = {ser["labels"]["stage"]: ser["count"]
                    for ser in fam}
        assert by_stage["total"] == 2
        assert by_stage["first_harvest"] == 2
        _s, events, counters = sess.recorder.snapshot()
        traces = [e for e in events if e["name"] == "request_trace"]
        assert {e["attrs"]["request"] for e in traces} == {"a", "b"}
        assert all(e["attrs"]["v"] == T.TRACE_VERSION for e in traces)
        # the migrated counter must be gone
        assert "serve_latency_s" not in counters

    def test_trace_off_payload_byte_identical(self):
        """The trace-off no-op: with ``trace`` absent the response
        payload carries exactly the pre-trace keys (and an explicit
        ``trace: false`` is indistinguishable from absent)."""
        reqs = [schema.validate_request(_req(id=i, T=[1000.0], **kw))
                for i, kw in (("plain", {}), ("off", {"trace": False}),
                              ("on", {"trace": True}))]
        sess = FakeSession()
        by_id = {r.request.id: r for r in self._serve(sess, reqs)}

        def payload(res):
            # the render_result trace gate, minus the session's
            # device-side rendering (fake session has none)
            out = {"elapsed_ms": round(1e3 * res.elapsed_s, 3)}
            if getattr(res.request, "trace", False) \
                    and res.trace is not None:
                out["trace"] = res.trace.to_payload()
            return out

        assert set(payload(by_id["plain"])) == {"elapsed_ms"}
        assert set(payload(by_id["off"])) == {"elapsed_ms"}
        assert set(payload(by_id["on"])) == {"elapsed_ms", "trace"}
        tr = payload(by_id["on"])["trace"]
        assert tr["v"] == T.TRACE_VERSION and tr["lanes"] == 1

    def test_stalled_stage_under_injection(self):
        inject.arm("slow_request:delay=0.2,request=slow")
        sess = FakeSession()
        results = self._serve(sess, [_request("slow", [1000.0])])
        segs = results[0].trace.segments()
        assert segs["stalled"] >= 0  # stall opens the stage...
        assert segs["resolved"] >= 0.2  # ...and resolve carries it
        by_stage = {ser["labels"]["stage"]: ser
                    for ser in sess.recorder.hist_snapshot()
                    ["serve_stage_seconds"]}
        assert by_stage["resolved"]["sum"] >= 0.2

    def test_slow_request_threshold_event_arms_flight(self):
        from batchreactor_tpu.obs.live import (arm_flight,
                                               disarm_flight)

        inject.arm("slow_request:delay=0.15,request=slow")
        sess = FakeSession(slow_request_s=0.1)
        flight = arm_flight(recorder=sess.recorder,
                            install_signal=False)
        try:
            self._serve(sess, [_request("slow", [1000.0]),
                               _request("fast", [1100.0])])
        finally:
            disarm_flight()
        _s, events, _c = sess.recorder.snapshot()
        slow = [e for e in events if e["name"] == "slow_request"]
        # BOTH requests breach: the injected stall pauses the driver
        # thread exactly where a slow consumer would, so the
        # co-harvested "fast" request feels it too (the inject.py
        # contract) — and its waterfall shows where the time went
        assert {e["attrs"]["request"] for e in slow} == {"slow",
                                                         "fast"}
        by_id = {e["attrs"]["request"]: e["attrs"] for e in slow}
        assert by_id["slow"]["total_s"] >= 0.1
        assert "stalled" in by_id["slow"]["stages"]
        assert "stalled" not in by_id["fast"]["stages"]
        # the flight ring saw the event AND the armed counter snapshot
        kinds = [r["kind"] for r in flight.records()]
        assert "counter_snapshot" in kinds
        assert any(r.get("name") == "slow_request"
                   for r in flight.records() if r["kind"] == "event")

    def test_failed_requests_skip_histograms(self):
        sess = FakeSession(fail=True)
        sched = Scheduler(sess).start()
        fut = sched.submit(_request("dead", [1000.0]))
        with pytest.raises(RuntimeError):
            fut.result(5.0)
        sched.drain(5.0)
        assert "serve_stage_seconds" not in \
            sess.recorder.hist_snapshot()
        _s, events, _c = sess.recorder.snapshot()
        tr = [e for e in events if e["name"] == "request_trace"]
        assert len(tr) == 1 and tr[0]["attrs"]["failed"] is True


# --------------------------------------------------------------------------
# schema: the trace request key
# --------------------------------------------------------------------------
class TestTraceKey:
    def test_default_false_and_not_in_pack_key(self):
        r = schema.validate_request(_req())
        assert r.trace is False
        r_on = schema.validate_request(_req(trace=True))
        assert r_on.trace is True
        assert r.pack_key() == r_on.pack_key()

    def test_non_boolean_is_loud(self):
        with pytest.raises(ValueError, match="trace must be a JSON "
                                             "boolean"):
            schema.validate_request(_req(trace="yes"))


class TestFleetHistograms:
    def test_snapshot_merge_and_fleet_exposition(self, tmp_path):
        """Per-host snapshots carry the latency histograms, merge_fleet
        sums them slot-wise, and the fleet exposition renders the
        merged family — the cross-host latency view."""
        from batchreactor_tpu.obs.live import (LiveRegistry,
                                               fleet_prometheus,
                                               merge_fleet,
                                               read_fleet_snapshots,
                                               write_fleet_snapshot)

        for pid, durs in ((0, (0.01, 0.02)), (1, (0.04,))):
            rec = Recorder()
            for d in durs:
                rec.observe("serve_stage_seconds", d, stage="total")
            write_fleet_snapshot(str(tmp_path), pid,
                                 LiveRegistry(recorder=rec))
        snaps = read_fleet_snapshots(str(tmp_path))
        merged = merge_fleet(snaps)
        ser = merged["histograms"]["serve_stage_seconds"][0]
        assert ser["labels"] == {"stage": "total"}
        assert ser["count"] == 3
        assert ser["sum"] == pytest.approx(0.07)
        prom = fleet_prometheus(snaps)
        assert ('br_fleet_serve_stage_seconds_count{stage="total"} 3'
                in prom)
        assert 'br_fleet_serve_stage_seconds_bucket{le="+Inf"' in prom

    def test_merge_tolerates_pre_histogram_snapshots(self):
        from batchreactor_tpu.obs.live import merge_fleet

        merged = merge_fleet([{"pid": 0, "counters": {"x": 1},
                               "gauges": {}}])
        assert merged["histograms"] == {}
        assert merged["counters"] == {"x": 1}


class TestClientTraceSummary:
    def _record(self, rid, latency_s, total_s, segments):
        return {"id": rid, "ok": True, "latency_s": latency_s,
                "send_at": 0.0, "code": None,
                "response": {"trace": {"v": 1, "total_s": total_s,
                                       "segments": segments,
                                       "stages": {}, "lanes": 1}}}

    def test_stage_decomposition_and_attribution(self):
        from batchreactor_tpu.serving.client import trace_summary

        recs = [self._record(f"r{i}", 0.05 + 0.01 * i, 0.04 + 0.01 * i,
                             {"coalesced": 0.01,
                              "first_harvest": 0.02 + 0.01 * i,
                              "resolved": 0.01})
                for i in range(4)]
        s = trace_summary(recs, attribution_tol_ms=100.0)
        assert set(s["server_stages"]) == {"coalesced", "first_harvest",
                                           "resolved"}
        assert s["server_stages"]["coalesced"]["p50_ms"] == 10.0
        assert s["attribution"]["ok"]
        assert s["attribution"]["max_gap_ms"] == pytest.approx(10.0)

    def test_attribution_violations(self):
        from batchreactor_tpu.serving.client import trace_summary

        good = self._record("good", 0.05, 0.04, {})
        server_exceeds = self._record("impossible", 0.02, 0.08, {})
        huge_gap = self._record("gap", 3.0, 0.04, {})
        s = trace_summary([good, server_exceeds, huge_gap],
                          attribution_tol_ms=500.0)
        assert not s["attribution"]["ok"]
        assert {v["id"] for v in s["attribution"]["violations"]} == {
            "impossible", "gap"}

    def test_none_without_traces(self):
        from batchreactor_tpu.serving.client import trace_summary

        assert trace_summary([{"id": "x", "ok": True, "latency_s": 0.1,
                               "response": {}}]) is None


# --------------------------------------------------------------------------
# the gate + waterfall CLIs
# --------------------------------------------------------------------------
def _bench_like_report():
    r = Recorder()
    r.counter("serve_requests", 5)
    r.counter("serve_answered", 5)
    for i in range(5):
        tr = RequestTrace(f"req-{i}", pack_key=(1e-4, 1e-6, 1e-10,
                                                None), lanes=1)
        t0 = tr.at("submitted")
        tr.mark("coalesced", at=t0 + 0.001 * (i + 1))
        tr.mark("admitted", at=t0 + 0.002 * (i + 1))
        tr.mark("first_harvest", at=t0 + 0.01 * (i + 1))
        tr.mark("resolved", at=t0 + 0.012 * (i + 1))
        for stage, dur in tr.segments().items():
            r.observe("serve_stage_seconds", dur, stage=stage)
        r.observe("serve_stage_seconds", tr.total_s(), stage="total")
        r.event("request_trace", **tr.to_attrs())
    return build_report(recorder=r, meta={"entry": "serving"})


class TestObsGateCLI:
    BASELINE = {
        "schema": "br-obs-gate-v1",
        "counters": {"serve_answered": {"equals": 5},
                     "serve_failed": {"max": 0}},
        "histograms": {"serve_stage_seconds": {
            "stage=total": {"count": {"equals": 5},
                            "p50_s": {"max": 1.0},
                            "p99_s": {"max": 2.0}}}},
        "compile": {"retraces": {"max": 0}},
    }

    def _run(self, tmp_path, baseline, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_gate
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        base_path = tmp_path / "base.json"
        base_path.write_text(json.dumps(baseline))
        rc = obs_gate.main(["--baseline", str(base_path),
                            "--report", str(rep_path)])
        return rc, capsys.readouterr()

    def test_passes_in_band(self, tmp_path, capsys):
        rc, out = self._run(tmp_path, self.BASELINE, capsys)
        assert rc == 0
        assert "gate passed" in out.out
        assert "[FAIL]" not in out.out

    def test_fails_loudly_on_perturbation(self, tmp_path, capsys):
        bad = json.loads(json.dumps(self.BASELINE))
        bad["histograms"]["serve_stage_seconds"]["stage=total"][
            "p50_s"]["max"] = 1e-6
        bad["counters"]["serve_answered"]["equals"] = 7
        rc, out = self._run(tmp_path, bad, capsys)
        assert rc == 1
        assert "GATE FAILED: 2 band(s)" in out.err
        assert "p50_s" in out.err and "serve_answered" in out.err

    def test_missing_histogram_fails_quantile_band(self, tmp_path,
                                                   capsys):
        """A disappeared metric must fail, not vacuously pass: a
        quantile band against an absent series reads 'no
        observations'."""
        bad = json.loads(json.dumps(self.BASELINE))
        bad["histograms"]["serve_stage_seconds"] = {
            "stage=nonexistent": {"p50_s": {"max": 1.0}}}
        rc, out = self._run(tmp_path, bad, capsys)
        assert rc == 1
        assert "no observations" in out.err

    def test_unknown_sections_and_bands_are_loud(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        from obs_gate import run_gate

        with pytest.raises(ValueError, match="unknown gate section"):
            run_gate({"frontier": {}}, _bench_like_report())
        with pytest.raises(ValueError, match="unknown band key"):
            run_gate({"counters": {"x": {"atmost": 1}}},
                     _bench_like_report())


class TestObsTraceCLI:
    def test_waterfall_render_and_slowest(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        out_path = tmp_path / "wf.txt"
        rc = obs_trace.main([str(rep_path), "--slowest", "2",
                             "--out", str(out_path)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "2 requests, slowest first" in text
        # slowest first: req-4 (60ms total) before req-3
        assert text.index("req-4") < text.index("req-3")
        assert "submitted -> coalesced" in text
        assert "admitted -> first_harvest" in text
        assert out_path.read_text().strip() == text.strip()

    def test_json_and_threshold(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import obs_trace
        from batchreactor_tpu.obs import write_jsonl

        rep_path = tmp_path / "rep.jsonl"
        write_jsonl(str(rep_path), _bench_like_report())
        rc = obs_trace.main([str(rep_path), "--threshold-ms", "40",
                             "--json"])
        assert rc == 0
        recs = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines()]
        # only req-3 (48ms) and req-4 (60ms) pass the 40ms threshold
        assert {r["request"] for r in recs} == {"req-3", "req-4"}
