"""Continuous batching (parallel/sweep.py ``admission=``): device-side
lane compaction + streaming admission queue.

The equivalence contract under test: per-lane results, telemetry lane
arrays, provenance codes, and checkpoint artifacts from the streaming
admission driver must be BIT-EXACT against the admission-less pipelined
driver, with the permutation un-shuffled back to caller lane order.
Like the pipelined-vs-blocking tests these run a cheap stiff decay ODE
(tiny traced programs, tier-1 budget) — the drivers are results-neutral
regardless of RHS.

Shape discipline: XLA CPU vectorizes some batch shapes differently
(the documented <=2-ulp bucket-padding caveat, parallel/sweep.py
``_pad_lanes``), so the bit-exact matrix pins resident/chunk shapes to
one equality class; the bucket DOWN-SHIFT test, whose whole point is a
mid-lane program-shape switch, asserts exact step counts/statuses/stats
and tolerance-level state instead.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu.parallel import ensemble_solve_segmented
from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
from batchreactor_tpu.parallel.sweep import (make_mesh, resolve_admission,
                                             _refill_slots)
from batchreactor_tpu.solver.sdirk import (DT_UNDERFLOW,
                                           MAX_STEPS_REACHED, SUCCESS)


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    import batchreactor_tpu as br

    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    return gm, th


def _decay_rhs(t, y, cfg):
    return -cfg["k"] * y


def _decay_setup(B=6, poison_lane=None, k_hi=2.5):
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    if poison_lane is not None:
        y0s = y0s.at[poison_lane, 0].set(jnp.nan)
    cfgs = {"k": jnp.logspace(1.0, k_hi, B)}
    return y0s, cfgs


def _decay_observer():
    init = {"ymax": -jnp.inf, "t_last": jnp.nan}

    def obs(t, y, acc):
        return {"ymax": jnp.maximum(y[0], acc["ymax"]), "t_last": t}

    return obs, init


def _fields(res):
    out = {f: np.asarray(getattr(res, f))
           for f in ("t", "y", "status", "n_accepted", "n_rejected",
                     "ts", "ys", "n_saved", "h")}
    if res.observed is not None:
        for k, v in res.observed.items():
            out[f"obs_{k}"] = np.asarray(v)
    if res.stats is not None:
        for k, v in res.stats.items():
            out[f"stat_{k}"] = np.asarray(v)
    return out


def _assert_bit_exact(a, b, ctx=""):
    fa, fb = _fields(a), _fields(b)
    assert fa.keys() == fb.keys(), (ctx, fa.keys(), fb.keys())
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k],
                                      err_msg=f"{ctx} field {k}")


# --------------------------------------------------------------------------
# knob grammar + loud validation
# --------------------------------------------------------------------------
def test_resolve_admission_grammar():
    assert resolve_admission(None, None) == (None, None)
    assert resolve_admission(False, None) == (None, None)
    assert resolve_admission(True, None, n_lanes=7) == (7, 0.25)
    assert resolve_admission(4, None) == (4, 0.25)
    assert resolve_admission(4, 0.5) == (4, 0.5)
    assert resolve_admission(4, 2) == (4, 2)
    for bad in ("pow2", 0, -1, 1.5):
        with pytest.raises(ValueError, match="admission"):
            resolve_admission(bad)
    with pytest.raises(ValueError, match="refill"):
        resolve_admission(None, 0.5)      # refill without admission
    for bad in (0, -2, 0.0, 1.5, True, "x"):
        with pytest.raises(ValueError, match="refill"):
            resolve_admission(4, bad)
    with pytest.raises(ValueError, match="lane count"):
        resolve_admission(True)
    # fractions convert AFTER bucket padding, rounding up, clamped
    assert _refill_slots(0.25, 8) == 2
    assert _refill_slots(0.25, 3) == 1
    assert _refill_slots(1.0, 4) == 4
    assert _refill_slots(100, 4) == 4


def test_admission_driver_validation():
    y0s, cfgs = _decay_setup(B=4)
    kw = dict(segment_steps=16, max_segments=8)
    with pytest.raises(ValueError, match="pipelined gear"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 pipeline=False, admission=2, **kw)
    with pytest.raises(ValueError, match="mesh"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 mesh=make_mesh(), admission=2, **kw)
    with pytest.raises(ValueError, match="n_save"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 n_save=16, admission=2, **kw)
    with pytest.raises(ValueError, match="refill"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 refill=0.5, **kw)


def test_checkpointed_admission_validation(tmp_path):
    y0s, cfgs = _decay_setup(B=4)
    with pytest.raises(ValueError, match="segment_steps"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                           str(tmp_path / "a"), chunk_size=2, admission=2)
    with pytest.raises(ValueError, match="chunk_budget_s"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                           str(tmp_path / "b"), chunk_size=2, admission=2,
                           segment_steps=16, chunk_budget_s=30.0)
    with pytest.raises(ValueError, match="n_save"):
        checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                           str(tmp_path / "c"), chunk_size=2, admission=2,
                           segment_steps=16, n_save=8)


# --------------------------------------------------------------------------
# streaming driver equivalence
# --------------------------------------------------------------------------
@pytest.mark.parametrize("method", ["bdf", "sdirk"])
def test_streaming_bit_exact(method):
    """Compacted/refilled sweep results — state, final t/h, statuses,
    step counts, per-lane telemetry arrays, observer folds — are
    bit-exact vs the admission-less pipelined driver, un-shuffled to
    caller lane order.  Includes a DT_UNDERFLOW lane (slot freed early,
    refilled from the backlog) and mid-sweep terminations (the k
    spread)."""
    obs, obs0 = _decay_observer()
    y0s, cfgs = _decay_setup(B=6, poison_lane=1)
    k_before = np.asarray(cfgs["k"]).copy()
    kw = dict(segment_steps=16, max_segments=60, observer=obs,
              observer_init=obs0, method=method, dt_min_factor=1e-12,
              stats=True)
    ref = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, **kw)
    status = np.asarray(ref.status)
    assert status[1] == DT_UNDERFLOW and np.all(np.delete(status, 1)
                                                == SUCCESS)
    for refill in (1, 0.5):
        adm = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                       pipeline=True, admission=3,
                                       refill=refill, **kw)
        _assert_bit_exact(ref, adm, f"{method}/refill={refill}")
        # donation-aliasing regression: the compaction/relaunch programs
        # donate the resident blocks, and on the CPU backend a zero-copy
        # view would let them scribble over the CALLER's arrays (the
        # corruption only ever surfaced on the NEXT sweep using them)
        assert np.isnan(np.asarray(y0s)[1, 0])
        np.testing.assert_array_equal(np.asarray(cfgs["k"]), k_before)


def test_streaming_budget_parking_bit_exact():
    """The exact max_attempts budget — reset per admitted lane — parks
    lanes at the same attempt counts and statuses as the admission-less
    driver."""
    y0s, cfgs = _decay_setup(B=6)
    kw = dict(segment_steps=16, max_segments=60, max_attempts=120,
              stats=True)
    ref = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, **kw)
    status = np.asarray(ref.status)
    assert np.any(status == MAX_STEPS_REACHED) and np.any(status == SUCCESS)
    adm = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, admission=3, **kw)
    _assert_bit_exact(ref, adm, "budget")


def test_streaming_counters_and_occupancy():
    """The admission telemetry: compactions fire, every backlog lane is
    admitted exactly once, and the occupancy pair reports useful
    attempts <= capacity (docs/observability.md)."""
    from batchreactor_tpu.obs import Recorder

    y0s, cfgs = _decay_setup(B=6)
    rec = Recorder()
    res = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, admission=3, refill=1,
                                   segment_steps=16, max_segments=60,
                                   recorder=rec)
    assert np.all(np.asarray(res.status) == SUCCESS)
    _, _, ctrs = rec.snapshot()
    assert ctrs["admitted_lanes"] == 3          # backlog beyond resident
    assert ctrs["compactions"] >= 1
    att = int(res.n_accepted.sum() + res.n_rejected.sum())
    assert ctrs["lane_attempts"] == att
    assert 0 < ctrs["lane_attempts"] <= ctrs["lane_capacity"]


@pytest.mark.slow   # tier-1 budget (CI satellite): the heavy end of
#   the matrix runs in CI's default suite; the bit-exact core stays
#   in the timed tier-1 run
def test_streaming_bucketed_bit_exact():
    """admission x buckets (no down-shift: the ladder floor equals the
    resident bucket): the resident program runs a canonical bucket
    shape, refills keep it full, and live-lane results stay bit-exact
    vs the admission-less bucketed driver."""
    y0s, cfgs = _decay_setup(B=6)
    kw = dict(segment_steps=16, max_segments=60, stats=True,
              buckets=(4, 16))
    ref = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, **kw)
    adm = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, admission=3, **kw)
    _assert_bit_exact(ref, adm, "bucketed")


@pytest.mark.slow   # tier-1 budget (CI satellite): the heavy end of
#   the matrix runs in CI's default suite; the bit-exact core stays
#   in the timed tier-1 run
def test_bucket_downshift():
    """Backlog drained + live lanes fitting a smaller pow2 rung: the
    driver down-shifts onto the smaller program.  Step counts, statuses,
    and per-lane counters stay exact; carried state is tolerance-level
    across the program-shape switch (the documented bucket-shape ulp
    caveat); the switch is an EXPECTED compile under its new
    program_key, never a retrace."""
    from batchreactor_tpu.obs import CompileWatch, Recorder

    # 7 cheap lanes + 1 stiff straggler: the cheap lanes park early and
    # the drain tail runs long enough for polls to catch live < bucket
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (8, 2))
    cfgs = {"k": jnp.asarray([10.0] * 7 + [10.0 ** 3.2])}
    kw = dict(segment_steps=16, max_segments=120, stats=True,
              buckets="pow2", poll_every=1)
    ref = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                   pipeline=True, **kw)
    rec = Recorder()
    watch = CompileWatch(recorder=rec, default_label="test")
    with watch:
        adm = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                       pipeline=True, admission=True,
                                       refill=1, recorder=rec,
                                       watch=watch, **kw)
    _, _, ctrs = rec.snapshot()
    assert ctrs["bucket_downshifts"] >= 1
    assert watch.summary()["retraces"] == 0
    for f in ("status", "n_accepted", "n_rejected", "n_saved"):
        np.testing.assert_array_equal(np.asarray(getattr(ref, f)),
                                      np.asarray(getattr(adm, f)),
                                      err_msg=f)
    for k in ref.stats:
        np.testing.assert_array_equal(np.asarray(ref.stats[k]),
                                      np.asarray(adm.stats[k]),
                                      err_msg=k)
    for f in ("t", "y", "h"):
        np.testing.assert_allclose(np.asarray(getattr(ref, f)),
                                   np.asarray(getattr(adm, f)),
                                   rtol=1e-9, atol=1e-30, err_msg=f)


# --------------------------------------------------------------------------
# checkpointed backlog mode
# --------------------------------------------------------------------------
def test_checkpointed_streamed_bit_exact_and_resume(tmp_path):
    """Chunks as completion units: artifacts, concatenated results, and
    resume — including a resume finished by the NON-admission driver
    (the knobs are fingerprint-exempt gear) — are bit-exact vs the
    chunked driver.  B divides chunk_size so both drivers run one
    program-shape class (module docstring)."""
    y0s, cfgs = _decay_setup(B=6)
    kw = dict(segment_steps=16, max_steps=2000, stats=True)
    ref = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "ref"), chunk_size=3, **kw)
    adm = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "adm"), chunk_size=3,
                             admission=True, refill=1, **kw)
    _assert_bit_exact(ref, adm, "checkpointed")
    # the manifest records the admission order (operational, non-pinned)
    import json

    man = json.load(open(tmp_path / "adm" / "manifest.json"))
    assert man["admission"]["resident"] == 3
    # resume: drop one chunk, re-stream only it
    os.remove(str(tmp_path / "adm" / "chunk_00001.npz"))
    resumed = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 str(tmp_path / "adm"), chunk_size=3,
                                 admission=True, refill=1, **kw)
    _assert_bit_exact(ref, resumed, "checkpointed-resume")
    # cross-gear resume: the admission-written dir serves the chunked
    # driver (and vice versa) — the fingerprint never learned the gear
    os.remove(str(tmp_path / "adm" / "chunk_00000.npz"))
    cross = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                               str(tmp_path / "adm"), chunk_size=3, **kw)
    _assert_bit_exact(ref, cross, "checkpointed-cross-gear")


@pytest.mark.slow   # tier-1 budget (CI satellite): the heavy end of
#   the matrix runs in CI's default suite; the bit-exact core stays
#   in the timed tier-1 run
def test_provenance_maps_through_permutation(tmp_path):
    """Quarantine provenance codes land at the caller lane index under
    admission — the permutation un-shuffle covers the resilience layer,
    not just results (a NaN lane admitted mid-stream must quarantine as
    lane 4, not as whatever slot it occupied)."""
    y0s, cfgs = _decay_setup(B=6, poison_lane=4)
    kw = dict(segment_steps=16, max_steps=2000, quarantine=True)
    ref = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "ref"), chunk_size=3, **kw)
    adm = checkpointed_sweep(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                             str(tmp_path / "adm"), chunk_size=3,
                             admission=True, refill=1, **kw)
    assert ref.provenance is not None
    np.testing.assert_array_equal(np.asarray(ref.provenance),
                                  np.asarray(adm.provenance))
    np.testing.assert_array_equal(np.asarray(ref.status),
                                  np.asarray(adm.status))
    # the poisoned lane is the one carrying a non-primary code, at its
    # caller index on both gears
    assert int(np.asarray(adm.provenance)[4]) != 0
    assert np.all(np.asarray(adm.provenance)[[0, 1, 2, 3, 5]] == 0)


@pytest.mark.slow   # tier-1 budget (CI satellite): the heavy end of
#   the matrix runs in CI's default suite; the bit-exact core stays
#   in the timed tier-1 run
def test_api_admission_knobs(h2o2):
    """api.py loudness + end-to-end: admission knobs on the monolithic
    path raise before any parsing; a segmented admission sweep matches
    the admission-less sweep (the real-mechanism <=2-ulp cross-shape
    tolerance, the test_aot convention) and reports the occupancy
    counters in its telemetry.  The plain sweep mirrors
    test_api_bucketed_sweep_matches_unbucketed's configuration, so its
    program is persistent-cache-served on a warm suite."""
    import batchreactor_tpu as br

    gm, th = h2o2
    kw = dict(chem=br.Chemistry(gaschem=True), thermo_obj=th, md=gm)
    comp = {"H2": 0.3, "O2": 0.2, "N2": 0.5}
    T = np.linspace(1050, 1150, 5)
    with pytest.raises(ValueError, match="segmented-path"):
        br.batch_reactor_sweep(comp, T, 1e5, 1e-5, admission=3, **kw)
    with pytest.raises(ValueError, match="segmented-path"):
        br.batch_reactor_sweep(comp, T, 1e5, 1e-5, refill=1, **kw)
    with pytest.raises(ValueError, match="refill"):
        br.batch_reactor_sweep(comp, T, 1e5, 1e-5, segment_steps=16,
                               refill=1, **kw)
    with pytest.raises(ValueError, match="mesh"):
        br.batch_reactor_sweep(comp, T, 1e5, 1e-5, segment_steps=16,
                               admission=3, mesh=make_mesh(), **kw)
    seg = dict(segment_steps=16, ignition_marker="H2", telemetry=True)
    ref = br.batch_reactor_sweep(comp, T, 1e5, 1e-5, **kw, **seg)
    adm = br.batch_reactor_sweep(comp, T, 1e5, 1e-5, admission=3,
                                 refill=1, **kw, **seg)
    np.testing.assert_array_equal(ref["status"], adm["status"])
    np.testing.assert_allclose(ref["tau"], adm["tau"], rtol=1e-12)
    for s in ref["x"]:
        np.testing.assert_allclose(ref["x"][s], adm["x"][s], rtol=1e-12)
    ctrs = adm["telemetry"]["counters"]
    assert ctrs["admitted_lanes"] == 2          # 5 lanes, 3 resident
    assert adm["telemetry"]["meta"]["admission"] is True
    assert 0 < ctrs["lane_attempts"] <= ctrs["lane_capacity"]


# --------------------------------------------------------------------------
# live backlog feed (_feed: the serving scheduler's driver hook)
# --------------------------------------------------------------------------
class TestLiveFeed:
    def test_feed_requires_admission(self):
        y0s, cfgs = _decay_setup(B=4)
        with pytest.raises(ValueError, match="_feed"):
            ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                     segment_steps=16,
                                     _feed=lambda n, idle: None)

    @pytest.mark.parametrize("stats", [False, True])
    def test_fed_backlog_bit_exact_vs_static(self, stats):
        """Lanes appended through the live feed solve BIT-EXACT to the
        same lanes handed over as a static backlog up front (same
        resident bucket), and land at their sequential global indices
        — the serving daemon's correctness contract at the driver
        level."""
        y0s, cfgs = _decay_setup(B=6)
        obs, init = _decay_observer()
        kw = dict(segment_steps=16, max_segments=400, poll_every=1,
                  admission=2, refill=1, stats=stats, observer=obs,
                  observer_init=init)
        ref = ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                       **kw)
        # live variant: 2 lanes up front, the other 4 arrive through
        # the feed in two blocks (out of segment-boundary sync with
        # the static run's admissions — admission timing must not
        # matter)
        blocks = [(np.asarray(y0s)[2:4], {"k": np.asarray(cfgs["k"])[2:4]}),
                  (np.asarray(y0s)[4:6], {"k": np.asarray(cfgs["k"])[4:6]})]
        calls = {"idle_seen": False}

        def feed(n_space, idle):
            assert n_space >= 1
            calls["idle_seen"] |= bool(idle)
            if blocks:
                return blocks.pop(0)
            return None

        live = ensemble_solve_segmented(
            _decay_rhs, jnp.asarray(np.asarray(y0s)[:2]),
            0.0, 1.0, {"k": jnp.asarray(np.asarray(cfgs["k"])[:2])},
            _feed=feed, **kw)
        assert not blocks          # every block was pulled in
        _assert_bit_exact(ref, live, "fed vs static backlog")

    def test_feed_zero_rows_while_idle_closes(self):
        """The block-or-close contract: an idle stream handed 0 rows
        treats the feed as closed instead of spinning on an empty
        program."""
        y0s, cfgs = _decay_setup(B=2)
        idle_flags = []

        def feed(n_space, idle):
            idle_flags.append(bool(idle))
            return (np.zeros((0, 2)), {"k": np.zeros((0,))})

        res = ensemble_solve_segmented(
            _decay_rhs, y0s, 0.0, 1.0, cfgs, segment_steps=16,
            max_segments=400, poll_every=1, admission=2, refill=1,
            _feed=feed)
        assert np.all(np.asarray(res.status) == SUCCESS)
        # free slots poll the feed (idle=False, stream still running);
        # the FIRST idle consultation closes it — exactly one, and last
        assert idle_flags.count(True) == 1 and idle_flags[-1] is True

    def test_fed_lanes_counter(self):
        from batchreactor_tpu.obs.recorder import Recorder

        y0s, cfgs = _decay_setup(B=4)
        blocks = [(np.asarray(y0s)[2:4],
                   {"k": np.asarray(cfgs["k"])[2:4]})]
        rec = Recorder()
        ensemble_solve_segmented(
            _decay_rhs, jnp.asarray(np.asarray(y0s)[:2]), 0.0, 1.0,
            {"k": jnp.asarray(np.asarray(cfgs["k"])[:2])},
            segment_steps=16, max_segments=400, poll_every=1,
            admission=2, refill=1, recorder=rec,
            _feed=lambda n, idle: blocks.pop(0) if blocks else None)
        _s, _e, counters = rec.snapshot()
        assert counters["fed_lanes"] == 2


# --------------------------------------------------------------------------
# capacity levers (ISSUE 20): resident-bucket up-shift autoscaling +
# the mesh-sharded resident program
# --------------------------------------------------------------------------
def test_upshift_bucket_ladder():
    """aot.buckets.upshift_bucket: always the SINGLE next rung up, only
    under real demand, capped at the knob's resolved ceiling — the dual
    of downshift_bucket."""
    from batchreactor_tpu.aot.buckets import upshift_bucket

    assert upshift_bucket(10, "pow2", 4) == 8      # one rung, not 16
    assert upshift_bucket(3, "pow2", 4) is None    # demand fits current
    assert upshift_bucket(100, "pow2", 8, cap=8) is None   # at ceiling
    assert upshift_bucket(100, "pow2", 8, cap=32) == 16
    assert upshift_bucket(5, (4, 16, 64), 4) == 16
    assert upshift_bucket(100, (4, 16, 64), 64) is None    # ladder top
    assert upshift_bucket(100, None, 4) is None    # bucketing off
    assert upshift_bucket(9, "pow2", 4, mesh_size=8) == 8
    assert upshift_bucket(5, (4, 6, 8), 4, mesh_size=4) == 8  # 6 skipped


def test_capacity_knob_validation():
    y0s, cfgs = _decay_setup(B=4)
    kw = dict(segment_steps=16, max_segments=8)
    with pytest.raises(ValueError, match="upshift= climbs the buckets"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 admission=2, upshift=8, **kw)
    with pytest.raises(ValueError, match="upshift must be an int"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 admission=4, buckets="pow2", upshift=2,
                                 **kw)
    with pytest.raises(ValueError, match="upshift_patience"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 admission=2, buckets="pow2", upshift=8,
                                 upshift_patience=0, **kw)
    with pytest.raises(ValueError, match="local device"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 admission=2, mesh_resident=99, **kw)
    # the capacity knobs are streaming-only gear, loud elsewhere
    with pytest.raises(ValueError, match="mesh_resident"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 mesh_resident=1, **kw)
    with pytest.raises(ValueError, match="upshift"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 upshift=8, **kw)
    with pytest.raises(ValueError, match="_live_source"):
        ensemble_solve_segmented(_decay_rhs, y0s, 0.0, 1.0, cfgs,
                                 _live_source="sweep-e1", **kw)


def _upshift_run(recorder=None, watch=None):
    """A backlog that outgrows its seed bucket: 2 resident slots, 6
    backlog lanes, ceiling 8 — the autoscaler must climb 2 -> 4 -> 8
    on the pow2 ladder to absorb it."""
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (8, 2))
    cfgs = {"k": jnp.asarray([10.0, 20.0, 40.0, 80.0] * 2)}
    return ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8,
        max_segments=160, poll_every=1, admission=2, refill=1,
        buckets="pow2", upshift=8, upshift_patience=1, stats=True,
        recorder=recorder, watch=watch)


def test_bucket_upshift_fires_and_warm_ladder_zero_compiles():
    """Acceptance: the up-shift fires under sustained backlog, every
    lane still solves, and on a WARMED ladder (every rung's programs
    already compiled) the whole multi-shift stream records zero
    compiles and zero retraces under CompileWatch — the migration is an
    executable switch, never a compile."""
    from batchreactor_tpu.obs import CompileWatch, Recorder

    warm = _upshift_run()          # bakes every rung's programs
    assert np.all(np.asarray(warm.status) == SUCCESS)
    rec = Recorder()
    watch = CompileWatch(recorder=rec, default_label="test")
    with watch:
        res = _upshift_run(recorder=rec, watch=watch)
    assert np.all(np.asarray(res.status) == SUCCESS)
    _s, events, ctrs = rec.snapshot()
    assert ctrs["bucket_upshifts"] >= 1
    w = watch.summary()
    assert w["compiles"] == 0 and w["retraces"] == 0, w
    # the shift event carries the migration's shape evidence
    ups = [e for e in events if e["name"] == "bucket_upshift"]
    assert ups and all(e["attrs"]["bucket"] > 2 for e in ups)
    # determinism: the warmed re-run reproduces the first run exactly
    _assert_bit_exact(warm, res, "upshift warm re-run")


def test_upshift_hysteresis_no_thrash():
    """An oscillating backlog must not thrash the ladder: a single-lane
    trickle (blips that never exceed the next rung's headroom) climbs
    nothing; one sustained burst climbs monotonically — at most one
    shift per rung — and the stream never re-climbs after its post-burst
    down-shift (the patience + cooldown damping)."""
    from batchreactor_tpu.obs import Recorder

    state = {"calls": 0, "fed": 0, "burst": False}
    one = (np.asarray([[1.0, 0.5]]), {"k": np.asarray([30.0])})

    def feed(n_space, idle):
        state["calls"] += 1
        p = state["calls"]
        if p < 11:
            # trickle: one lane per consultation — the backlog never
            # exceeds the next rung's headroom, so nothing qualifies
            state["fed"] += 1
            return one
        if not state["burst"]:
            # ONE sustained burst, sized to the driver's over-ask
            # (feed contract: k <= n_space)
            state["burst"] = True
            k = min(int(n_space), 8)
            state["fed"] += k
            return (np.broadcast_to(np.asarray([1.0, 0.5]),
                                    (k, 2)).copy(),
                    {"k": np.logspace(1.0, 1.9, k)})
        if p % 5 == 0 and p <= 40:
            state["fed"] += 1          # post-burst blips: must not re-climb
            return one
        if idle:
            return None                # drained: close the feed
        return (np.zeros((0, 2)), {"k": np.zeros((0,))})

    rec = Recorder()
    y0s, cfgs = _decay_setup(B=2)
    res = ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8,
        max_segments=2000, poll_every=1, admission=2, refill=1,
        buckets="pow2", upshift=8, upshift_patience=2, _feed=feed,
        recorder=rec)
    assert np.all(np.asarray(res.status) == SUCCESS)
    assert np.asarray(res.status).shape[0] == 2 + state["fed"]
    _s, events, ctrs = rec.snapshot()
    # the pow2 climb 2 -> 8 is at most two shifts; a thrashing ladder
    # would re-climb after down-shifting and exceed it
    assert 1 <= ctrs["bucket_upshifts"] <= 2, ctrs
    shifts = [e["name"] for e in events
              if e["name"] in ("bucket_upshift", "bucket_downshift")]
    first_down = (shifts.index("bucket_downshift")
                  if "bucket_downshift" in shifts else len(shifts))
    assert "bucket_upshift" not in shifts[first_down:], shifts


def _mesh_resident_pair(mr):
    from batchreactor_tpu.obs import Recorder

    y0s, cfgs = _decay_setup(B=6)
    rec = Recorder()
    res = ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, pipeline=True,
        segment_steps=16, max_segments=60, stats=True, buckets="pow2",
        poll_every=1, admission=3, refill=1, mesh_resident=mr,
        recorder=rec)
    # drop wall-clock counters (poll_wait_s): only the admission
    # bookkeeping is results-equivalence material
    ctrs = {k: v for k, v in rec.snapshot()[2].items()
            if not k.endswith("_s")}
    return res, ctrs


def test_mesh_resident_one_device_bit_exact():
    """``mesh_resident=1`` lays the carry out through the NamedSharding
    path over a single device; that must be bit-exact against the
    unsharded driver across every field and admission counter — the
    no-op fork the brlint contract pins at the jaxpr level, asserted
    here at the results level."""
    base, base_c = _mesh_resident_pair(None)
    one, one_c = _mesh_resident_pair(1)
    _assert_bit_exact(base, one, "mesh_resident=1 vs None")
    assert base_c == one_c


def test_mesh_resident_multi_device_shard():
    """``mesh_resident=True`` shards the resident program over ALL local
    devices (8 virtual CPU devices under conftest's harness).  Cross-
    shard vectorization is the documented ulp-class caveat (module
    docstring), so the sharded run pins statuses, step counts and
    tolerance-level state rather than bits."""
    import jax

    assert len(jax.local_devices()) == 8  # conftest harness contract
    base, base_c = _mesh_resident_pair(None)
    shard, shard_c = _mesh_resident_pair(True)
    assert np.all(np.asarray(shard.status) == SUCCESS)
    assert np.array_equal(np.asarray(base.status),
                          np.asarray(shard.status))
    assert np.array_equal(np.asarray(base.n_accepted),
                          np.asarray(shard.n_accepted))
    np.testing.assert_allclose(np.asarray(shard.y), np.asarray(base.y),
                               rtol=1e-6, atol=1e-12)
    np.testing.assert_allclose(np.asarray(shard.t), np.asarray(base.t),
                               rtol=1e-12)


def test_mesh_resident_upshift_compose():
    """The levers stack: a sharded resident program still climbs the
    (mesh-divisible) ladder under backlog pressure."""
    from batchreactor_tpu.obs import Recorder

    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (8, 2))
    cfgs = {"k": jnp.logspace(1.0, 1.9, 8)}
    rec = Recorder()
    res = ensemble_solve_segmented(
        _decay_rhs, y0s, 0.0, 1.0, cfgs, segment_steps=8,
        max_segments=160, poll_every=1, admission=2, refill=1,
        buckets="pow2", mesh_resident=1, upshift=8, upshift_patience=1,
        recorder=rec)
    assert np.all(np.asarray(res.status) == SUCCESS)
    assert rec.snapshot()[2]["bucket_upshifts"] >= 1
