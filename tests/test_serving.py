"""serving/ — the resident sweep-as-a-service daemon (docs/serving.md).

Three tiers, matching the subsystem's layering:

* **schema** — the versioned request grammar's loud validation;
* **scheduler invariants** against a fake session (no device work):
  request -> lane packing round-trip, OUT-OF-ORDER harvest resolving
  the right futures, backpressure rejection at the queue bound,
  drain-on-shutdown answering every accepted request exactly once,
  pack-key isolation, the live-feed path, and the ``slow_request``
  fault injection;
* **end-to-end over real HTTP** on the vendored h2o2 fixture: N
  concurrent requests against a live daemon return results BIT-EXACT
  vs a direct ``batch_reactor_sweep`` call on the same conditions,
  with ``compiles == 0`` on the armed program labels after warmup
  (CompileWatch-asserted) and the live gauges observably moving
  between mid-flight ``/metrics`` scrapes; plus the SIGTERM graceful
  drain of ``scripts/serve.py`` (subprocess: answers accepted work,
  rejects new work with ``draining``, exits 0).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from batchreactor_tpu.obs.recorder import Recorder  # noqa: E402
from batchreactor_tpu.resilience import inject  # noqa: E402
from batchreactor_tpu.serving import schema  # noqa: E402
from batchreactor_tpu.serving.scheduler import (Draining,  # noqa: E402
                                                Overloaded, Scheduler)


# --------------------------------------------------------------------------
# schema
# --------------------------------------------------------------------------
def _req(**over):
    base = {"id": "r1", "T": [1200.0, 1300.0],
            "X": {"H2": 0.3, "O2": 0.15, "N2": 0.55}, "t1": 1e-4}
    base.update(over)
    return base


class TestSchema:
    def test_roundtrip_broadcast(self):
        r = schema.validate_request(_req(p=2e5, rtol=1e-7))
        assert r.n_lanes == 2 and r.id == "r1"
        np.testing.assert_array_equal(r.T, [1200.0, 1300.0])
        np.testing.assert_array_equal(r.p, [2e5, 2e5])
        np.testing.assert_array_equal(r.X["H2"], [0.3, 0.3])
        # the trailing slot is the energy mode (None = isothermal —
        # docs/energy.md; energy lanes never share a resident program)
        assert r.pack_key() == (1e-4, 1e-7, 1e-10, None)

    def test_default_id_and_defaults(self):
        obj = _req()
        del obj["id"]
        r = schema.validate_request(obj, default_id="auto-7",
                                    rtol_default=2e-6, atol_default=1e-9)
        assert r.id == "auto-7" and r.rtol == 2e-6 and r.atol == 1e-9

    @pytest.mark.parametrize("mutate,match", [
        (dict(T=[]), "must not be empty"),
        (dict(T=-5.0), "positive Kelvin"),
        (dict(T="hot"), "must be a number"),
        (dict(T=[[1200.0]]), "FLAT"),
        (dict(p=0.0), "positive Pa"),
        (dict(X={}), "non-empty"),
        (dict(X={"H2": -0.1}), "non-negative"),
        (dict(X={"H2": 0.0}), "sum"),
        (dict(X={"H2": [0.3, 0.0]}), "lane 1"),
        (dict(t1=0.0), "positive"),
        (dict(n_save=16), "n_save"),
        (dict(v=2), "schema version"),
        (dict(bogus=1), "unknown request key"),
        (dict(T=[1.0, 2.0], p=[1e5, 1e5, 1e5]), "disagree on lane count"),
    ])
    def test_loud_validation(self, mutate, match):
        with pytest.raises(ValueError, match=match):
            schema.validate_request(_req(**mutate))

    def test_species_check(self):
        with pytest.raises(ValueError, match="XE"):
            schema.validate_request(_req(X={"XE": 1.0}),
                                    species=("H2", "O2", "N2"))

    def test_max_lanes_bound(self):
        with pytest.raises(ValueError, match="exceeds the per-request"):
            schema.validate_request(_req(T=[1.0] * 9), max_lanes=8)

    def test_missing_id_without_default(self):
        obj = _req()
        del obj["id"]
        with pytest.raises(ValueError, match="id"):
            schema.validate_request(obj)

    def test_response_builders(self):
        ok = schema.ok_response("a", {"lanes": 1})
        assert ok["status"] == "ok" and ok["v"] == schema.SCHEMA_VERSION
        err = schema.error_response("a", "overloaded", "full")
        assert err["error"]["code"] == "overloaded"
        with pytest.raises(ValueError, match="error code"):
            schema.error_response("a", "nope", "x")


# --------------------------------------------------------------------------
# scheduler invariants (fake session: no device, no HTTP)
# --------------------------------------------------------------------------
from batchreactor_tpu.solver.sdirk import SUCCESS  # noqa: E402

_SPEC = dict(max_queue_lanes=16, idle_timeout_s=0.05, coalesce_s=0.0,
             rtol=1e-6, atol=1e-10, request_timeout_s=10.0,
             max_lanes_per_request=None)


class FakeSession:
    """The scheduler-facing session surface (request_lanes / stream /
    spec / bucket_cap), with a scripted driver: lanes "solve" to
    ``y0 + 1000`` at ``t = t1``, harvested in a configurable order and
    chunking — so the un-shuffle bookkeeping is what's under test, not
    the solver."""

    def __init__(self, harvest="fifo", chunk=3, hold=None, fail=False,
                 **spec_over):
        self.spec = types.SimpleNamespace(**{**_SPEC, **spec_over})
        self.bucket_cap = 4
        self.recorder = Recorder()
        self.registry = None
        self.streams = []          # (t1, rtol, atol) per epoch
        self.sources = []          # live_source kw per epoch (None = unset)
        self.harvest = harvest
        self.chunk = chunk
        self.hold = hold           # threading.Event gating the epoch
        self.fail = fail

    def request_lanes(self, req):
        k = req.n_lanes
        # distinctive per-lane payloads: y0 = (T, Asv)
        y0 = np.stack([np.asarray(req.T), np.asarray(req.Asv)], axis=1)
        return y0, {"T": np.asarray(req.T), "Asv": np.asarray(req.Asv)}

    def stream(self, y0s, cfgs, *, t1, rtol, atol, on_harvest, feed,
               **kw):
        self.streams.append((t1, rtol, atol))
        self.sources.append(kw.get("live_source"))
        if self.hold is not None:
            self.hold.wait(5.0)
        if self.fail:
            raise RuntimeError("injected stream death")
        rows = {g: np.asarray(y0s)[g] for g in range(len(y0s))}
        pending = list(rows)
        while True:
            order = list(pending)
            if self.harvest == "reverse":
                order = order[::-1]
            elif self.harvest == "scramble":
                order = order[1::2] + order[0::2]
            for i in range(0, len(order), self.chunk):
                gids = np.asarray(order[i:i + self.chunk], dtype=np.int64)
                if not gids.size:
                    continue
                k = gids.size
                on_harvest(gids, {
                    "t": np.full((k,), t1),
                    "y": np.stack([rows[g] + 1000.0 for g in gids]),
                    "status": np.full((k,), int(SUCCESS), dtype=np.int32),
                    "h": np.full((k,), 1e-6),
                    "n_accepted": np.full((k,), 7, dtype=np.int64),
                    "n_rejected": np.zeros((k,), dtype=np.int64)})
            pending = []
            if feed is None:
                break
            got = feed(4, True)
            if got is None:
                break
            y_new, _cfg_new = got
            base = len(rows)
            for j in range(np.asarray(y_new).shape[0]):
                rows[base + j] = np.asarray(y_new)[j]
                pending.append(base + j)
            if not pending:
                break


def _request(rid, T, t1=1e-4, **over):
    return schema.validate_request(
        _req(id=rid, T=T, t1=t1, **over))


def _results(futures, timeout=10.0):
    return [f.result(timeout=timeout) for f in futures]


@pytest.fixture(autouse=True)
def _disarm_inject():
    yield
    inject.disarm()


class TestSchedulerInvariants:
    def test_concurrent_start_is_safe(self):
        """Regression for the brlint host-concurrency finding this PR
        fixed: ``start()`` used an unguarded check-then-set, so two
        front-end threads racing it could both see ``_started`` False
        and double-start the worker (``Thread.start`` raises
        RuntimeError on the loser).  Under the lock every racer returns
        the same started scheduler."""
        for _ in range(20):
            sess = FakeSession()
            sched = Scheduler(sess)
            barrier = threading.Barrier(8)
            errors = []

            def go():
                try:
                    barrier.wait(5.0)
                    sched.start()
                except BaseException as e:  # noqa: BLE001 — the assert
                    errors.append(e)
            threads = [threading.Thread(target=go) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(5.0)
            assert errors == []
            assert sched._worker.is_alive()
            sched.drain(5.0)

    def test_packing_round_trip(self):
        """Requests with distinct lane vectors come back in request
        lane order, regardless of how they were packed together."""
        sess = FakeSession()
        sched = Scheduler(sess).start()
        futs = [sched.submit(_request("a", [1000.0, 1100.0, 1200.0])),
                sched.submit(_request("b", [2000.0])),
                sched.submit(_request("c", [3000.0, 3100.0]))]
        ra, rb, rc = _results(futs)
        sched.drain(5.0)
        np.testing.assert_array_equal(ra.y[:, 0],
                                      [2000.0, 2100.0, 2200.0])
        np.testing.assert_array_equal(rb.y[:, 0], [3000.0])
        np.testing.assert_array_equal(rc.y[:, 0], [4000.0, 4100.0])
        assert all(p == "success" for r in (ra, rb, rc)
                   for p in r.provenance)
        np.testing.assert_array_equal(ra.t, [1e-4] * 3)
        assert ra.n_accepted.tolist() == [7, 7, 7]

    @pytest.mark.parametrize("order", ["reverse", "scramble"])
    def test_out_of_order_harvest(self, order):
        """Harvests arriving in arbitrary gid order (and arbitrary
        chunking) still resolve each future with ITS lanes, in ITS
        order."""
        sess = FakeSession(harvest=order, chunk=2)
        sched = Scheduler(sess).start()
        futs = [sched.submit(_request(f"r{i}",
                                      [1000.0 * (i + 1) + j
                                       for j in range(1 + i % 3)]))
                for i in range(5)]
        res = _results(futs)
        sched.drain(5.0)
        for i, r in enumerate(res):
            np.testing.assert_array_equal(
                r.y[:, 0], [1000.0 * (i + 1) + j + 1000.0
                            for j in range(1 + i % 3)])

    def test_backpressure_overloaded(self):
        """The queue bound rejects loudly (never silent queueing), and
        everything ACCEPTED is still answered."""
        hold = threading.Event()
        sess = FakeSession(hold=hold, max_queue_lanes=4)
        sched = Scheduler(sess).start()
        futs = [sched.submit(_request("a", [1000.0, 1100.0]))]
        # worker may seed "a" into the held epoch; fill the queue with
        # whatever fits, then the bound must trip
        accepted = []
        with pytest.raises(Overloaded):
            for i in range(9):
                accepted.append(
                    sched.submit(_request(f"q{i}", [1500.0 + i])))
        _s, _e, counters = sess.recorder.snapshot()
        assert counters["serve_rejects_overload"] >= 1
        hold.set()
        for r in _results(futs + accepted):
            assert all(p == "success" for p in r.provenance)
        sched.drain(5.0)

    def test_drain_answers_exactly_once_then_rejects(self):
        hold = threading.Event()
        sess = FakeSession(hold=hold)
        sched = Scheduler(sess).start()
        futs = [sched.submit(_request(f"d{i}", [1000.0 + i]))
                for i in range(6)]
        t = threading.Thread(target=lambda: (time.sleep(0.05),
                                             hold.set()))
        t.start()
        drained = sched.drain(10.0)
        t.join()
        assert drained
        res = _results(futs, timeout=1.0)   # all already resolved
        assert len(res) == 6
        with pytest.raises(Draining):
            sched.submit(_request("late", [999.0]))
        _s, _e, counters = sess.recorder.snapshot()
        assert counters["serve_answered"] == 6
        assert counters["serve_rejects_draining"] == 1

    def test_pack_key_isolation(self):
        """Distinct (t1, rtol, atol) never share an epoch; both keys
        answer."""
        sess = FakeSession()
        sched = Scheduler(sess).start()
        futs = [sched.submit(_request("a", [1000.0], t1=1e-4)),
                sched.submit(_request("b", [1001.0], t1=2e-4)),
                sched.submit(_request("c", [1002.0], t1=1e-4,
                                      rtol=1e-8))]
        res = _results(futs)
        sched.drain(5.0)
        assert res[0].t[0] == 1e-4 and res[1].t[0] == 2e-4
        keys = {(t1, rtol) for t1, rtol, _ in sess.streams}
        assert keys == {(1e-4, 1e-6), (2e-4, 1e-6), (1e-4, 1e-8)}

    def test_feed_joins_resident_epoch(self):
        """Requests arriving while an epoch is resident ride its live
        feed instead of a fresh dispatch (idle_timeout holds the
        stream open)."""
        sess = FakeSession(idle_timeout_s=1.0)
        sched = Scheduler(sess).start()
        f1 = sched.submit(_request("a", [1000.0]))
        f1.result(5.0)
        # the epoch is now idle-parked inside feed(); this lands there
        f2 = sched.submit(_request("b", [2000.0, 2100.0]))
        r2 = f2.result(5.0)
        sched.drain(5.0)
        np.testing.assert_array_equal(r2.y[:, 0], [3000.0, 3100.0])
        assert len(sess.streams) == 1   # ONE resident epoch served both
        _s, _e, counters = sess.recorder.snapshot()
        assert counters["serve_epochs"] == 1

    def test_stream_death_answers_with_error(self):
        """A dead stream must answer its admitted requests (internal
        error), not strand their futures — and the scheduler survives
        for the next epoch."""
        sess = FakeSession(fail=True)
        sched = Scheduler(sess).start()
        fut = sched.submit(_request("a", [1000.0]))
        with pytest.raises(RuntimeError, match="stream ended"):
            fut.result(5.0)
        sess.fail = False
        ok = sched.submit(_request("b", [1200.0])).result(5.0)
        assert ok.provenance == ["success"]
        sched.drain(5.0)

    def test_slow_request_injection(self):
        """The slow_request fault stalls the matched request between
        admission and harvest-resolution; everything still answers."""
        inject.arm("slow_request:delay=0.3,request=slow")
        sess = FakeSession()
        sched = Scheduler(sess).start()
        t0 = time.perf_counter()
        f_slow = sched.submit(_request("slow", [1000.0]))
        f_fast = sched.submit(_request("fast", [1100.0]))
        r_slow = f_slow.result(5.0)
        f_fast.result(5.0)
        wall = time.perf_counter() - t0
        sched.drain(5.0)
        assert r_slow.provenance == ["success"]
        assert wall >= 0.3 and r_slow.elapsed_s >= 0.3
        _s, events, counters = sess.recorder.snapshot()
        assert counters["serve_stalls"] == 1
        assert any(e["name"] == "fault"
                   and e["attrs"].get("kind") == "slow_request"
                   for e in events)


class TestAdaptiveCoalesce:
    """ROADMAP 2d: ``SessionSpec.coalesce_adaptive`` scales the batch
    window by the queue's fill fraction — an unsaturated stream stops
    paying the full fixed window for a batch that was never coming."""

    def _p50_coalesce_wait(self, adaptive, n=3):
        sess = FakeSession(coalesce_s=0.6, coalesce_adaptive=adaptive)
        sched = Scheduler(sess).start()
        for i in range(n):
            # unsaturated: one 1-lane request at a time against
            # bucket_cap=4, each fully resolved — and its epoch's idle
            # feed window (idle_timeout_s=0.05) fully CLOSED — before
            # the next fires, so every request pays the seed window
            # rather than riding the previous epoch's live feed
            sched.submit(_request(f"u{i}", [1000.0 + i])).result(10.0)
            time.sleep(0.2)
        sched.drain(5.0)
        waits = sorted(
            e["attrs"]["stages"]["coalesced"]
            for e in sess.recorder.snapshot()[1]
            if e["name"] == "request_trace")
        assert len(waits) == n
        return waits[n // 2]

    def test_unsaturated_p50_submitted_to_coalesced_drops(self):
        """The fixed window holds every lone request for ~coalesce_s;
        the adaptive window releases it at ~coalesce_s x 1/cap (fill
        fraction 1/4 here) — p50 submitted->coalesced drops by more
        than half, with CI-loose margins."""
        fixed = self._p50_coalesce_wait(adaptive=False)
        adaptive = self._p50_coalesce_wait(adaptive=True)
        assert fixed >= 0.5, fixed          # ~0.6 windowed
        assert adaptive <= 0.35, adaptive   # ~0.15 earned
        assert adaptive < fixed / 2

    def test_saturated_burst_still_seeds_full(self):
        """A queue that already fills the resident program seeds
        immediately under BOTH policies (the window only ever waits on
        unearned capacity), in one epoch."""
        for adaptive in (False, True):
            sess = FakeSession(coalesce_s=0.6,
                               coalesce_adaptive=adaptive)
            sched = Scheduler(sess).start()
            t0 = time.monotonic()
            sched.submit(_request("burst", [1000.0, 1100.0, 1200.0,
                                            1300.0])).result(10.0)
            assert time.monotonic() - t0 < 0.4
            sched.drain(5.0)
            assert len(sess.streams) == 1

    def test_adaptive_window_collapses_with_free_slots(self):
        """ISSUE 20 satellite: when the resident tier can absorb the
        whole queue RIGHT NOW (free slots >= queued lanes) waiting buys
        no batch density — the adaptive window collapses toward ZERO,
        not just the earned fill fraction, so the unsaturated
        submitted->coalesced stage wait is negligible."""
        p50 = self._p50_coalesce_wait(adaptive=True)
        assert p50 <= 0.1, p50


class TestMultiEpoch:
    """Capacity plane (scheduler module doc "Multi-epoch capacity"):
    ``resident_epochs=N`` runs N resident epochs off ONE shared
    pack-key queue with pull-based spray — pops are disjoint under the
    scheduler lock, so the harvest un-shuffle stays exactly-once per
    request no matter which epoch pulled it."""

    def test_two_epochs_spray_and_unshuffle(self):
        """Both epochs seed disjoint slices of one queued burst (held
        open so the spray is observable), harvests arrive scrambled and
        chunked inside each epoch, and every request still resolves
        with ITS lanes in ITS order."""
        hold = threading.Event()
        sess = FakeSession(harvest="scramble", chunk=2, hold=hold,
                           resident_epochs=2, idle_timeout_s=0.05)
        sched = Scheduler(sess)
        assert sched.epochs == 2 and len(sched._workers) == 2
        # queue BEFORE start so the seed pops race for real: 9 lanes
        # against two bucket_cap=4 epochs — neither epoch can take it all
        futs = [sched.submit(_request(f"m{i}",
                                      [1000.0 * (i + 1) + j
                                       for j in range(1 + i % 2)]))
                for i in range(6)]
        sched.start()
        deadline = time.monotonic() + 5.0
        while len(sess.streams) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(sess.streams) == 2   # both epochs took a seed
        hold.set()
        res = _results(futs)
        sched.drain(5.0)
        for i, r in enumerate(res):
            assert all(p == "success" for p in r.provenance)
            np.testing.assert_array_equal(
                r.y[:, 0], [1000.0 * (i + 1) + j + 1000.0
                            for j in range(1 + i % 2)])
        # each epoch published under its own live source
        assert sorted(sess.sources) == ["sweep-e0", "sweep-e1"]
        _s, _e, counters = sess.recorder.snapshot()
        assert counters["epoch_spray"] >= 1   # the sibling pulled lanes
        assert counters["serve_answered"] == 6

    def test_single_epoch_stream_signature_unchanged(self):
        """``resident_epochs=1`` (the default) is byte-identical to the
        pre-multi-epoch scheduler at the session boundary: a session
        pinned to the OLD ``stream`` signature (no ``**kw``) serves
        unchanged, on the same single worker thread name."""
        class StrictSession(FakeSession):
            def stream(self, y0s, cfgs, *, t1, rtol, atol, on_harvest,
                       feed):
                return FakeSession.stream(
                    self, y0s, cfgs, t1=t1, rtol=rtol, atol=atol,
                    on_harvest=on_harvest, feed=feed)

        sess = StrictSession()
        sched = Scheduler(sess).start()
        r = sched.submit(_request("a", [1000.0])).result(5.0)
        sched.drain(5.0)
        assert r.provenance == ["success"]
        assert sess.sources == [None]   # no live_source kw at N=1
        assert sched.epochs == 1 and len(sched._workers) == 1
        assert sched._worker.name == "br-serve-scheduler"
        _s, _e, counters = sess.recorder.snapshot()
        assert "epoch_spray" not in counters


# --------------------------------------------------------------------------
# end-to-end: real session, real HTTP, vendored h2o2 fixture
# --------------------------------------------------------------------------
_COMP = {"H2": 0.3, "O2": 0.15, "N2": 0.55}


def _session_spec(lib_dir, segment_steps=8, **serve_over):
    # segment_steps=8: every lane spans MANY segments, so the live
    # plane publishes at many poll boundaries — the gauge-motion
    # assertion below is structural, not a wall-clock race
    # coalesce_s=2.0: the e2e fires its whole request set concurrently
    # and compares bit-exact against one direct sweep at the TOP bucket
    # — the window guarantees every request joins the seed (ends early
    # once the queue fills the resident program), so a straggler thread
    # on a loaded runner cannot drop the epoch onto a smaller bucket's
    # ulp class
    # single-rung ladder [8]: the bit-exact comparison needs both the
    # daemon epoch and the direct sweep to run ONE program shape —
    # the daemon holds its resident bucket while the feed is open (no
    # up-shift path), while a feed-less direct sweep down-shifts its
    # drain tail, and down-shifted tails differ at the documented ulp
    serve = {"resident": 8, "refill": 1, "buckets": [8],
             "poll_every": 1, "max_queue_lanes": 64,
             "idle_timeout_s": 0.3, "coalesce_s": 2.0}
    serve.update(serve_over)
    return {"mechanism": {"mech": f"{lib_dir}/h2o2.dat",
                          "therm": f"{lib_dir}/therm.dat"},
            "solver": {"segment_steps": segment_steps, "stats": True},
            "serve": serve}


@pytest.fixture(scope="module")
def h2o2_session(lib_dir):
    from batchreactor_tpu.serving.session import SolverSession

    session = SolverSession.from_spec(_session_spec(lib_dir))
    session.warmup()
    with session:
        yield session


class TestServingEndToEnd:
    def test_http_single_request_bit_exact_and_warm(self, h2o2_session):
        """Acceptance, deterministic half: one 8-lane request over real
        HTTP returns results BIT-EXACT vs the direct
        batch_reactor_sweep on the same conditions (identical packing
        order => identical lane positions => identical programs), with
        zero armed-label compiles after warmup."""
        import batchreactor_tpu as br
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        N, t1 = 8, 5e-5
        Ts = [1150.0 + 37.0 * i for i in range(N)]
        sched = Scheduler(session)
        with ServingServer(session, sched) as srv:
            resp = SolveClient(srv.url).solve(
                {"id": "bitexact", "T": Ts, "X": _COMP, "t1": t1})
        out = br.batch_reactor_sweep(
            _COMP, np.asarray(Ts), 1e5, t1,
            chem=br.Chemistry(gaschem=True), thermo_obj=session.thermo,
            md=session.gm, segment_steps=8, admission=8, refill=1,
            buckets=(8,), poll_every=1)
        assert resp["solver_status"] == ["Success"] * N
        assert resp["provenance"] == ["success"] * N
        np.testing.assert_array_equal(resp["t"], np.asarray(out["t"]))
        for sp in session.species:
            np.testing.assert_array_equal(
                resp["x"][sp], np.asarray(out["x"][sp]), err_msg=sp)
        prog = session.program_compiles()
        assert all(v == 0 for v in prog.values()), prog
        assert session.compile_summary()["retraces"] == 0

    def test_http_concurrent_requests_and_live_scrapes(self,
                                                       h2o2_session):
        """Acceptance, concurrent half: N concurrent single-lane
        requests coalesce onto one resident stream; every answer
        matches the direct sweep to the repo's real-chemistry
        admission-equivalence convention (rtol 1e-12 — arrival order
        varies lane positions, the documented cross-position ulp
        class), and the live gauges observably move between mid-flight
        /metrics scrapes."""
        import batchreactor_tpu as br
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        N, t1 = 8, 5e-5
        Ts = [1150.0 + 37.0 * i for i in range(N)]
        # injected stalls spread across the harvests keep the stream
        # observably in-flight long enough for distinct mid-flight
        # scrapes (the stall sits in the harvest path — lanes park at
        # different segments, so successive stalls expose successive
        # harvested/occupancy states)
        inject.arm("slow_request:delay=0.06,count=6")
        sched = Scheduler(session)
        responses = [None] * N
        scrapes = []
        with ServingServer(session, sched) as srv:
            client = SolveClient(srv.url)
            stop = threading.Event()

            def scraper():
                while not stop.is_set():
                    try:
                        scrapes.append(client.metrics())
                    except OSError:
                        pass
                    stop.wait(0.02)

            scr = threading.Thread(target=scraper, daemon=True)
            scr.start()

            def fire(i):
                responses[i] = client.solve(
                    {"id": f"e{i}", "T": [Ts[i]], "X": _COMP, "t1": t1})

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(N)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            stop.set()
            scr.join()
            health = client.healthz()
        assert health["serving"]["fingerprint"] == session.fingerprint

        # ---- bit-exact vs the direct sweep on the same conditions ----
        out = br.batch_reactor_sweep(
            _COMP, np.asarray(Ts), 1e5, t1,
            chem=br.Chemistry(gaschem=True), thermo_obj=session.thermo,
            md=session.gm, segment_steps=8, admission=8, refill=1,
            buckets=(8,), poll_every=1)
        for i, resp in enumerate(responses):
            assert resp["status"] == "ok" and resp["lanes"] == 1
            assert resp["solver_status"] == ["Success"]
            assert resp["provenance"] == ["success"]
            assert resp["t"][0] == float(out["t"][i])
            for sp in session.species:
                np.testing.assert_allclose(
                    resp["x"][sp][0], float(out["x"][sp][i]),
                    rtol=1e-12, err_msg=f"lane {i} species {sp}")
            assert resp["n_accepted"][0] > 0

        # ---- still zero armed-label compiles ------------------------
        prog = session.program_compiles()
        assert all(v == 0 for v in prog.values()), prog

        # ---- live gauges moved between mid-flight scrapes ------------
        def gauge(text, name):
            for ln in text.splitlines():
                if ln.startswith(f"br_sweep_{name} "):
                    return float(ln.split()[-1])
            return None

        states = {(gauge(s, "harvested_lanes"),
                   gauge(s, "backlog_depth"), gauge(s, "occupancy"))
                  for s in scrapes}
        moving = {st for st in states
                  if any(v is not None for v in st)}
        assert len(moving) >= 2, (len(scrapes), states)

    def test_two_epoch_daemon_bit_exact_zero_compiles(self,
                                                      h2o2_session):
        """ISSUE 20 acceptance (the CI serve-smoke's in-process
        mirror): a 2-epoch daemon answers two pack keys bit-exact vs
        the direct sweep per key, with zero armed compiles, and a
        mid-flight scrape shows ``br_sweep_resident_epochs 2`` plus a
        per-epoch occupancy gauge."""
        import batchreactor_tpu as br
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        N = 8
        Ts = [1150.0 + 37.0 * i for i in range(N)]
        t1s = (5e-5, 1e-4)
        old = session.resident_epochs
        session.resident_epochs = 2
        inject.arm("slow_request:delay=0.1,count=4")
        responses = {}
        scrapes = []
        try:
            sched = Scheduler(session)
            assert sched.epochs == 2
            with ServingServer(session, sched) as srv:
                client = SolveClient(srv.url)
                stop = threading.Event()

                def scraper():
                    while not stop.is_set():
                        try:
                            scrapes.append(client.metrics())
                        except OSError:
                            pass
                        stop.wait(0.02)

                scr = threading.Thread(target=scraper, daemon=True)
                scr.start()

                def fire(t1):
                    responses[t1] = client.solve(
                        {"id": f"k{t1}", "T": Ts, "X": _COMP,
                         "t1": t1})

                threads = [threading.Thread(target=fire, args=(t1,))
                           for t1 in t1s]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                stop.set()
                scr.join()
                health = client.healthz()
        finally:
            session.resident_epochs = old
        assert health["serving"]["resident_epochs"] == 2
        # each key's 8-lane request fills one epoch's bucket-8 program
        # whole, so per-key results stay bit-exact vs the direct sweep
        # regardless of which epoch pulled it
        for t1 in t1s:
            resp = responses[t1]
            assert resp["solver_status"] == ["Success"] * N
            out = br.batch_reactor_sweep(
                _COMP, np.asarray(Ts), 1e5, t1,
                chem=br.Chemistry(gaschem=True),
                thermo_obj=session.thermo, md=session.gm,
                segment_steps=8, admission=8, refill=1, buckets=(8,),
                poll_every=1)
            np.testing.assert_array_equal(resp["t"],
                                          np.asarray(out["t"]))
            for sp in session.species:
                np.testing.assert_array_equal(
                    resp["x"][sp], np.asarray(out["x"][sp]),
                    err_msg=f"t1={t1} species {sp}")
        prog = session.program_compiles()
        assert all(v == 0 for v in prog.values()), prog
        # the capacity plane was visible mid-flight
        assert any("br_sweep_resident_epochs 2" in s
                   for s in scrapes), len(scrapes)
        assert any(ln.startswith("br_sweep_lanes_running_e")
                   for s in scrapes for ln in s.splitlines()), \
            len(scrapes)

    def test_request_level_stats_and_counters(self, h2o2_session):
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        sched = Scheduler(session)
        with ServingServer(session, sched) as srv:
            client = SolveClient(srv.url)
            resp = client.solve({"id": "s1", "T": [1250.0, 1350.0],
                                 "X": _COMP, "t1": 5e-5})
        assert resp["stats"]["newton_iters"][0] > 0
        assert len(resp["stats"]["jac_builds"]) == 2
        _s, _e, counters = session.recorder.snapshot()
        assert counters["serve_answered"] >= 1
        assert counters["serve_lanes"] >= 2

    def test_trace_request_and_histogram_scrape(self, h2o2_session):
        """Acceptance (request tracing): a trace=true request over real
        HTTP returns stage timestamps whose stages sum to the
        client-observed latency within tolerance, the
        br_serve_stage_seconds histogram buckets appear on a /metrics
        scrape of the live daemon and MOVE between scrapes, and a
        trace-less request's response carries no trace section."""
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        sched = Scheduler(session)

        def total_count(prom):
            line = [ln for ln in prom.splitlines()
                    if ln.startswith('br_serve_stage_seconds_count'
                                     '{stage="total"}')]
            return int(line[0].rsplit(" ", 1)[1]) if line else 0

        with ServingServer(session, sched) as srv:
            client = SolveClient(srv.url)
            t0 = time.perf_counter()
            resp = client.solve({"id": "traced", "T": [1200.0, 1300.0],
                                 "X": _COMP, "t1": 5e-5,
                                 "trace": True})
            client_lat = time.perf_counter() - t0
            tr = resp["trace"]
            assert tr["v"] == 1 and tr["lanes"] == 2
            offs = tr["stages"]
            assert list(offs) == ["submitted", "coalesced", "admitted",
                                  "first_harvest", "resolved"]
            assert list(offs.values()) == sorted(offs.values())
            # the stages decompose the total exactly, and the server
            # wall matches the client-observed latency: server never
            # exceeds client, transport/scheduling overhead bounded
            assert sum(tr["segments"].values()) == pytest.approx(
                tr["total_s"], abs=5e-5)
            assert tr["total_s"] <= client_lat + 5e-3
            assert client_lat - tr["total_s"] <= 0.75
            prom1 = client.metrics()
            n1 = total_count(prom1)
            assert n1 >= 1
            assert 'br_serve_stage_seconds_bucket{' in prom1
            assert '# TYPE br_serve_stage_seconds histogram' in prom1
            # the migrated summed counter must be gone for good
            assert "serve_latency_s" not in prom1
            resp2 = client.solve({"id": "plain", "T": [1250.0],
                                  "X": _COMP, "t1": 5e-5})
            assert "trace" not in resp2   # trace-off no-op
            assert total_count(client.metrics()) == n1 + 1   # it moved

    def test_http_invalid_and_overload_codes(self, h2o2_session):
        from batchreactor_tpu.serving.client import (ServeError,
                                                     SolveClient)
        from batchreactor_tpu.serving.server import ServingServer

        session = h2o2_session
        sched = Scheduler(session, max_queue_lanes=1)
        with ServingServer(session, sched) as srv:
            client = SolveClient(srv.url)
            with pytest.raises(ServeError) as ei:
                client.solve({"id": "bad", "T": [1200.0],
                              "X": {"XE": 1.0}, "t1": 1e-5})
            assert ei.value.code == "invalid"
            # hold the worker with a stall so the 1-lane queue bound
            # trips deterministically on the second in-flight request
            inject.arm("slow_request:delay=0.6,count=1")
            codes = []

            def fire(i):
                try:
                    client.solve({"id": f"o{i}", "T": [1200.0 + i],
                                  "X": _COMP, "t1": 5e-5})
                    codes.append("ok")
                except ServeError as e:
                    codes.append(e.code)

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(6)]
            for th in threads:
                th.start()
                time.sleep(0.03)
            for th in threads:
                th.join()
            assert "overloaded" in codes, codes

    def test_jsonl_front_end(self, h2o2_session):
        import io

        from batchreactor_tpu.serving.server import serve_jsonl

        session = h2o2_session
        sched = Scheduler(session).start()
        lines = [json.dumps({"id": "j1", "T": [1200.0], "X": _COMP,
                             "t1": 5e-5}),
                 json.dumps({"id": "j2", "T": "bogus", "X": _COMP,
                             "t1": 5e-5}),
                 json.dumps({"T": [1300.0], "X": _COMP, "t1": 5e-5})]
        out = io.StringIO()
        accepted, rejected = serve_jsonl(session, sched,
                                         io.StringIO("\n".join(lines)),
                                         out)
        assert (accepted, rejected) == (2, 1)
        got = {}
        for ln in out.getvalue().splitlines():
            obj = json.loads(ln)
            got[obj["id"]] = obj
        assert got["j1"]["status"] == "ok"
        assert got["j2"]["status"] == "error"
        assert got["j2"]["error"]["code"] == "invalid"
        auto = [o for rid, o in got.items() if rid not in ("j1", "j2")]
        assert len(auto) == 1 and auto[0]["status"] == "ok"

    def test_warmup_specs_match_served_programs(self, h2o2_session):
        """warm_cache --spec coverage invariant: the keys the spec
        DERIVES (aot.spec_keys, no execution) are exactly the keys the
        warmup pass COMPILED — the warmer and the daemon share one
        fingerprint by construction."""
        from batchreactor_tpu import aot

        expected = {k for spec in h2o2_session.warmup_specs()
                    for k, _b in aot.spec_keys(spec)}
        warmed = {r.key for r in h2o2_session.warmed}
        assert expected == warmed and len(expected) == 1


class TestServeDaemonSubprocess:
    def test_sigterm_graceful_drain(self, lib_dir, tmp_path):
        """Acceptance: SIGTERM during an in-flight trace answers all
        accepted requests, rejects new ones with `draining`, dumps a
        flight recorder postmortem, and exits 0."""
        from batchreactor_tpu.serving.client import (ServeError,
                                                     SolveClient)

        spec = _session_spec(lib_dir, resident=4, buckets=[4],
                             coalesce_s=0.0)
        spec_path = tmp_path / "serve.json"
        spec_path.write_text(json.dumps(spec))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO,
               # two slow requests hold the stream so the drain window
               # is wide and deterministic
               "BR_FAULT_INJECT": "slow_request:delay=1.2,count=2"}
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
             "--spec", str(spec_path), "--no-warmup",
             "--flight-dir", str(tmp_path)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            startup = {}

            def read_startup():
                startup["line"] = proc.stdout.readline()

            t = threading.Thread(target=read_startup, daemon=True)
            t.start()
            t.join(120)
            assert startup.get("line"), "daemon never printed its " \
                                        "startup line"
            info = json.loads(startup["line"])["serving"]
            client = SolveClient(info["url"], timeout=120)
            results = []

            def fire(i):
                try:
                    results.append(
                        ("ok", client.solve(
                            {"id": f"d{i}", "T": [1200.0 + 10 * i],
                             "X": _COMP, "t1": 5e-5})))
                except ServeError as e:
                    results.append((e.code, None))

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(3)]
            for th in threads:
                th.start()
            # let the requests be accepted and the stalls engage, then
            # pull the plug mid-flight
            time.sleep(1.0)
            proc.send_signal(signal.SIGTERM)
            # new work must now reject with `draining` (retry until the
            # flag lands; the stalled stream holds the window open)
            saw_draining = False
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not saw_draining:
                try:
                    client.solve({"id": "late", "T": [1500.0],
                                  "X": _COMP, "t1": 5e-5})
                except ServeError as e:
                    saw_draining = e.code == "draining"
                except OSError:
                    break     # server already down: drain completed
                time.sleep(0.05)
            for th in threads:
                th.join(120)
            rc = proc.wait(timeout=120)
            out, err = proc.communicate(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert rc == 0, f"daemon exited {rc}:\n{err[-2000:]}"
        assert saw_draining, "no `draining` rejection observed"
        oks = [r for code, r in results if code == "ok"]
        assert len(oks) == 3, results     # every accepted answered
        assert all(r["provenance"] == ["success"] for r in oks)
        flights = list(tmp_path.glob("flight_*.jsonl"))
        assert flights, "SIGTERM left no flight recorder dump"

    def test_warm_cache_spec_list_flags_missing(self, lib_dir,
                                                tmp_path):
        """--list --spec against an empty cache flags every expected
        program key as MISSING and exits 1 (the coverage probe)."""
        spec = _session_spec(lib_dir, buckets=[4], resident=4)
        spec_path = tmp_path / "serve.json"
        spec_path.write_text(json.dumps(spec))
        cache = tmp_path / "cache"
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "warm_cache.py"),
             "--spec", str(spec_path), "--list",
             "--cache-dir", str(cache)],
            cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu",
                           "PYTHONPATH": REPO},
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "MISSING" in r.stdout
        assert "fingerprint" in r.stdout


# --------------------------------------------------------------------------
# mixed-mechanism serving: one daemon, many mechanisms, one executable
# (SessionStore — docs/serving.md "Multi-mechanism serving")
# --------------------------------------------------------------------------
_FIXTURES = os.path.join(REPO, "tests", "fixtures")


def _mechshape_spec(**solver_over):
    solver = {"segment_steps": 16, "stats": True, "mech_operands": True}
    solver.update(solver_over)
    return {"mechanism": {"mech": f"{_FIXTURES}/h2o2.dat",
                          "therm": f"{_FIXTURES}/therm.dat"},
            "solver": solver,
            "serve": {"resident": 8, "refill": 1, "buckets": [8],
                      "poll_every": 1, "max_queue_lanes": 64,
                      "idle_timeout_s": 0.3, "coalesce_s": 2.0,
                      "max_mechanisms": 4}}


class TestMixedMechanismServing:
    def test_upload_schema_validation(self):
        from batchreactor_tpu.serving.schema import validate_upload

        ok = validate_upload({"id": "m1", "mech": "SPECIES\nH2\nEND",
                              "therm": "THERMO\nEND"})
        assert ok["warm"] is True
        with pytest.raises(ValueError, match="unknown upload key"):
            validate_upload({"id": "m1", "mech": "x", "therm": "y",
                             "path": "/etc/passwd"})
        with pytest.raises(ValueError, match="non-empty string 'id'"):
            validate_upload({"mech": "x", "therm": "y"})
        with pytest.raises(ValueError, match="inline file text"):
            validate_upload({"id": "m1", "mech": "  ", "therm": "y"})
        with pytest.raises(ValueError, match="warm must be a boolean"):
            validate_upload({"id": "m1", "mech": "x", "therm": "y",
                             "warm": "yes"})

    def test_mixed_mechanisms_one_daemon(self):
        """THE acceptance test: h2o2 + the vendored 12-species variant
        padded into one (S, R) bucket, served concurrently by one
        daemon — per-mechanism results BIT-EXACT vs the same
        mechanism's dedicated (padded-program) direct sweep, the
        scrambled multi-lane harvest un-shuffled exactly per mechanism,
        and ZERO armed-label compiles on the uploaded mechanism after
        warmup (the `sweep-segment compiles: 1 -> 0` evidence)."""
        import batchreactor_tpu as br
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.scheduler import Scheduler
        from batchreactor_tpu.serving.server import ServingServer
        from batchreactor_tpu.serving.session import (SessionStore,
                                                      SolverSession)

        session = SolverSession.from_spec(_mechshape_spec())
        session.warmup()
        comp_b = {"H2": 0.3, "O2": 0.15, "N2": 0.5, "AR": 0.05}
        # scrambled per-lane temperatures: the un-shuffle target
        Ts_a = [1480.0, 1170.0, 1390.0, 1255.0]
        Ts_b = [1420.0, 1205.0, 1333.0]
        with session:
            sched = Scheduler(session)
            store = SessionStore(session, sched)
            with ServingServer(session, sched, store=store) as srv:
                client = SolveClient(srv.url)
                up = client.upload_mechanism(
                    "h2o2n", open(f"{_FIXTURES}/h2o2_n.dat").read(),
                    open(f"{_FIXTURES}/therm.dat").read())
                assert tuple(up["mech_shape"]) == (16, 32)
                # warmed through the SHARED rung: zero armed compiles
                assert sum((up["program_compiles"] or {}).values()) == 0
                # both mechanisms' requests in flight concurrently
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(4) as pool:
                    fa = pool.submit(client.solve, {
                        "id": "mix-a", "T": Ts_a, "X": _COMP,
                        "t1": 5e-5})
                    fb = pool.submit(client.solve, {
                        "id": "mix-b", "T": Ts_b, "X": comp_b,
                        "t1": 5e-5, "mech": "h2o2n"})
                    ra, rb = fa.result(120), fb.result(120)
            census = {m["ids"][0]: m for m in store.mechanisms()}
        assert ra["provenance"] == ["success"] * len(Ts_a)
        assert rb["provenance"] == ["success"] * len(Ts_b)
        assert "NO" in rb["x"] and "NO" not in ra["x"]
        # dedicated direct sweeps under the SAME padded program config:
        # bit-exact, lane order preserved through the scrambled harvest
        kw = dict(chem=br.Chemistry(gaschem=True), segment_steps=16,
                  admission=8, refill=1, buckets=(8,), poll_every=1,
                  mech_operands=True)
        da = br.batch_reactor_sweep(
            _COMP, np.asarray(Ts_a), 1e5, 5e-5,
            thermo_obj=session.thermo, md=session.gm, **kw)
        for sp in session.species:
            np.testing.assert_array_equal(
                ra["x"][sp], np.asarray(da["x"][sp]), err_msg=sp)
        np.testing.assert_array_equal(ra["t"], np.asarray(da["t"]))
        gm2 = br.compile_gaschemistry(f"{_FIXTURES}/h2o2_n.dat")
        th2 = br.create_thermo(list(gm2.species),
                               f"{_FIXTURES}/therm.dat")
        db = br.batch_reactor_sweep(
            comp_b, np.asarray(Ts_b), 1e5, 5e-5, thermo_obj=th2,
            md=gm2, **kw)
        for sp in gm2.species:
            np.testing.assert_array_equal(
                rb["x"][sp], np.asarray(db["x"][sp]), err_msg=sp)
        # per-mechanism armed compiles after serving: all zero
        assert census["default"]["program_compiles"] == 0, census
        assert census["h2o2n"]["program_compiles"] == 0, census

    def test_store_routing_and_lru_eviction(self):
        from batchreactor_tpu.serving.scheduler import Scheduler
        from batchreactor_tpu.serving.session import (SessionStore,
                                                      SolverSession,
                                                      UnknownMechanism)

        spec = _mechshape_spec()
        spec["serve"]["max_mechanisms"] = 2
        session = SolverSession.from_spec(spec)
        session.warmup()
        with session:
            store = SessionStore(session, Scheduler(session))
            fp1 = store.add_mechanism(f"{_FIXTURES}/h2o2_n.dat",
                                      f"{_FIXTURES}/therm.dat",
                                      mech_id="m1")
            # routing: by id, by fingerprint prefix, default, unknown
            assert store.resolve("m1")[0].fingerprint == fp1
            assert store.resolve(fp1[:10])[0].fingerprint == fp1
            assert (store.resolve(None)[0].fingerprint
                    == session.fingerprint)
            with pytest.raises(UnknownMechanism):
                store.resolve("nope")
            # capacity 2: a third mechanism LRU-evicts m1 (default is
            # pinned), and requests for m1 then answer unknown
            store.add_mechanism(f"{_FIXTURES}/grimech.dat",
                                f"{_FIXTURES}/therm.dat", mech_id="m2",
                                warm=False)
            ids = {m["ids"][0] for m in store.mechanisms()}
            assert ids == {"default", "m2"}
            with pytest.raises(UnknownMechanism, match="no longer|unknown"):
                store.resolve("m1")
            assert session.recorder.counters.get("mech_evicted") == 1
            store.drain(5.0)
