"""Property-based tier (hypothesis): invariants that must hold for ANY
physically valid input, not just the fixture points the example-based
tests pin.  Complements the reference-parity tiers — these are the
contracts the kinetics/composition/solver layers promise to every caller.

Deadlines are disabled: jit compilation inside a property makes the first
example slow; hypothesis would misreport it as flaky.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, strategies as st  # noqa: E402

import batchreactor_tpu as br
from batchreactor_tpu.solver.sdirk import SUCCESS
from batchreactor_tpu.utils.composition import (
    average_molwt,
    density,
    mass_to_mole,
    mole_to_mass,
    pressure,
)

# bounded, strictly positive molecular weights [kg/mol] — H2 to heavy HC
MOLWT = st.lists(st.floats(2e-3, 0.3), min_size=2, max_size=20)


def _normalized_fracs(draw, n):
    raw = draw(st.lists(st.floats(1e-6, 1.0), min_size=n, max_size=n))
    x = np.asarray(raw)
    return x / x.sum()


@st.composite
def _mix(draw):
    molwt = np.asarray(draw(MOLWT))
    x = _normalized_fracs(draw, molwt.size)
    return molwt, x


@given(_mix())
def test_mass_mole_round_trip(mix):
    """mole->mass->mole is the identity for any normalized composition."""
    molwt, x = mix
    y = mole_to_mass(jnp.asarray(x), jnp.asarray(molwt))
    x_back = mass_to_mole(y, jnp.asarray(molwt))
    np.testing.assert_allclose(np.asarray(x_back), x, rtol=1e-12)
    # mass fractions normalize too
    np.testing.assert_allclose(float(jnp.sum(y)), 1.0, rtol=1e-12)


@given(_mix(), st.floats(300.0, 3000.0), st.floats(1e3, 1e7))
def test_ideal_gas_state_consistency(mix, T, p):
    """rho = p Wbar / RT and p = rho R T / Wbar are exact inverses, and
    average_molwt is bounded by the min/max species weight."""
    molwt, x = mix
    wbar = float(average_molwt(jnp.asarray(x), jnp.asarray(molwt)))
    assert molwt.min() - 1e-12 <= wbar <= molwt.max() + 1e-12
    rho = float(density(jnp.asarray(x), jnp.asarray(molwt), T, p))
    assert rho > 0
    p_back = float(pressure(rho, jnp.asarray(x), jnp.asarray(molwt), T))
    np.testing.assert_allclose(p_back, p, rtol=1e-12)


@pytest.fixture(scope="module")
def h2o2(lib_dir):
    gm = br.compile_gaschemistry(f"{lib_dir}/h2o2.dat")
    th = br.create_thermo(list(gm.species), f"{lib_dir}/therm.dat")
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs

    return (gm, th, jax.jit(make_gas_rhs(gm, th)),
            jax.jit(make_gas_jac(gm, th)))


@given(st.floats(800.0, 2500.0), st.floats(0.05, 0.45), st.floats(0.05, 0.45))
def test_gas_rhs_conserves_mass_everywhere(h2o2, T, xh2, xo2):
    """Sum of d(rho_k)/dt is exactly zero (mass conservation) for ANY
    temperature/composition in the physical range, and the RHS is finite
    — the invariant every reaction row must satisfy because each row
    conserves atoms (nu_f/nu_r are balanced)."""
    gm, th, rhs, _ = h2o2
    x = np.zeros(len(th.species))
    sp = list(th.species)
    x[sp.index("H2")], x[sp.index("O2")] = xh2, xo2
    x[sp.index("N2")] = 1.0 - xh2 - xo2
    rho = float(density(jnp.asarray(x), th.molwt, T, 1e5))
    y = np.asarray(mole_to_mass(jnp.asarray(x), th.molwt)) * rho
    dy = np.asarray(rhs(0.0, jnp.asarray(y), {"T": T}))
    assert np.all(np.isfinite(dy))
    # scale-relative zero: rates reach ~1e6 kg/m^3/s at hot ignition
    scale = max(np.abs(dy).max(), 1.0)
    assert abs(dy.sum()) < 1e-10 * scale, (dy.sum(), scale)


@given(st.floats(900.0, 2000.0))
def test_analytic_jacobian_matches_jacfwd_everywhere(h2o2, T):
    """The closed-form Jacobian equals jax.jacfwd at machine precision for
    any temperature — not only at the fixture points."""
    gm, th, rhs, jacf = h2o2
    x = np.zeros(len(th.species))
    sp = list(th.species)
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = .3, .2, .5
    rho = float(density(jnp.asarray(x), th.molwt, T, 1e5))
    y = jnp.asarray(np.asarray(mole_to_mass(jnp.asarray(x), th.molwt)) * rho)
    J_ana = np.asarray(jacf(0.0, y, {"T": T}))
    J_fwd = np.asarray(jax.jacfwd(lambda yy: rhs(0.0, yy, {"T": T}))(y))
    scale = np.abs(J_fwd).max() or 1.0
    np.testing.assert_allclose(J_ana, J_fwd, atol=1e-9 * scale)


@given(st.floats(-3.0, 3.0), st.floats(0.05, 4.0))
def test_bdf_linear_decay_exact_family(lam_exp, t1):
    """BDF reproduces exp(-lambda t) within tolerance for any decay rate
    over 6 orders of magnitude and any horizon — the solver contract, not
    a tuned fixture."""
    from batchreactor_tpu.solver import bdf

    lam = 10.0 ** lam_exp

    def rhs(t, y, cfg):
        return -cfg["lam"] * y

    y0 = jnp.asarray([1.0])
    res = bdf.solve(rhs, y0, 0.0, t1, {"lam": jnp.asarray(lam)},
                    rtol=1e-8, atol=1e-12)
    assert int(res.status) == SUCCESS, int(res.status)
    exact = np.exp(-lam * t1)
    np.testing.assert_allclose(float(res.y[0]), exact,
                               rtol=1e-5, atol=1e-11)
