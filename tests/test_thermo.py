"""NASA-7 thermo parsing + evaluation tests.

Oracles: JANAF standard-state values, the golden initial density committed at
/root/reference/test/batch_gas_and_surf/gas_profile.csv (row t=0), and
internal-consistency (range continuity at T_mid).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from batchreactor_tpu.models.thermo import create_thermo, element_matrix, parse_thermo_entries
from batchreactor_tpu.ops.thermo import cp_h_s_over_R, gibbs_over_RT
from batchreactor_tpu.utils.composition import density, mass_to_mole, mole_to_mass
from batchreactor_tpu.utils.constants import R


@pytest.fixture(scope="module")
def therm(lib_dir):
    return f"{lib_dir}/therm.dat"


def test_parse_all_entries(gri_lib_dir):
    entries = parse_thermo_entries(f"{gri_lib_dir}/therm.dat")
    assert len(entries) == 53  # GRI-Mech 3.0 thermo (SURVEY.md §6)
    assert "CH2(S)" in entries and "AR" in entries


def test_parse_vendored_fixture(fixtures_dir):
    # round-3: the vendored therm.dat is the full 53-species GRI set (the
    # round-2 trim only covered h2o2; grimech.dat/ch4ni.xml are vendored now)
    entries = parse_thermo_entries(f"{fixtures_dir}/therm.dat")
    assert len(entries) == 53
    assert "CH2(S)" in entries and "AR" in entries


def test_molecular_weights(therm):
    t = create_thermo(["H2", "O2", "CH4", "AR"], therm)
    np.testing.assert_allclose(
        np.asarray(t.molwt) * 1e3, [2.01594, 31.9988, 16.04303, 39.948], rtol=1e-4
    )


def test_janaf_standard_state(therm):
    t = create_thermo(["H2O", "O2", "CH4", "CO2"], therm)
    cp, h, s = cp_h_s_over_R(298.15, t)
    # heats of formation at 298.15 K [kJ/mol]
    np.testing.assert_allclose(
        np.asarray(h) * R * 298.15 / 1e3,
        [-241.83, 0.0, -74.87, -393.52],
        rtol=2e-3,
        atol=0.3,
    )
    # standard entropies [J/mol/K]
    np.testing.assert_allclose(
        np.asarray(s) * R, [188.8, 205.1, 186.3, 213.8], rtol=2e-3
    )
    # cp [J/mol/K]
    np.testing.assert_allclose(np.asarray(cp) * R, [33.6, 29.4, 35.7, 37.1], rtol=5e-3)


def test_range_continuity(therm):
    """cp/h/s must be continuous at the low/high switch temperature."""
    t = create_thermo(["H2O", "CH4", "OH", "CO"], therm)
    Tmid = float(t.T_mid[0])
    lo = jnp.stack(cp_h_s_over_R(Tmid - 1e-7, t))
    hi = jnp.stack(cp_h_s_over_R(Tmid + 1e-7, t))
    np.testing.assert_allclose(np.asarray(lo), np.asarray(hi), rtol=1e-5)


def test_golden_initial_density(therm):
    """Pin R & atomic masses against the committed golden CSV initial row
    (/root/reference/test/batch_gas_and_surf/gas_profile.csv)."""
    t = create_thermo(["CH4", "O2", "N2"], therm)
    x = jnp.asarray([0.25, 0.5, 0.25])
    rho = float(density(x, t.molwt, 1173.0, 1e5))
    assert abs(rho - 0.27697974868307573) / 0.27697974868307573 < 1e-5


def test_mass_mole_roundtrip(therm):
    t = create_thermo(["H2", "O2", "H2O", "N2"], therm)
    x = jnp.asarray([0.1, 0.2, 0.3, 0.4])
    y = mole_to_mass(x, t.molwt)
    x2 = mass_to_mole(y, t.molwt)
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x), rtol=1e-12)


def test_element_matrix(therm):
    t = create_thermo(["CH4", "O2", "CO2", "H2O"], therm)
    elements, E = element_matrix(t)
    assert set(elements) == {"C", "H", "O"}
    ch4 = E[:, 0]
    assert ch4[elements.index("C")] == 1 and ch4[elements.index("H")] == 4


def test_gibbs_matches_h_minus_s(therm):
    t = create_thermo(["H2", "OH"], therm)
    _, h, s = cp_h_s_over_R(1500.0, t)
    g = gibbs_over_RT(1500.0, t)
    np.testing.assert_allclose(np.asarray(g), np.asarray(h - s), rtol=1e-14)
