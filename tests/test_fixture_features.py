"""Parser features only exercised by the vendored fixtures.

The reference's ch4ni.xml shows <mwc>, <order>, and 3-number <stick> entries
only in comments (/root/reference/test/lib/ch4ni.xml:57-59), and no committed
mechanism uses REACTIONS unit keywords — these paths were parsed-but-untested
in round 1.  tests/fixtures/h2oni.xml exercises all of them; every rate here
is asserted against a hand-computed value, not a stored snapshot.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.surface import compile_mech
from batchreactor_tpu.ops import surface_kinetics
from batchreactor_tpu.ops.gas_kinetics import forward_rate_constants
from batchreactor_tpu.utils.constants import R

GASPHASE = ["H2", "O2", "H2O", "H", "O", "OH", "HO2", "H2O2", "N2"]


@pytest.fixture(scope="module")
def h2oni(fixtures_dir):
    th = br.create_thermo(GASPHASE, f"{fixtures_dir}/therm.dat")
    sm = compile_mech(f"{fixtures_dir}/h2oni.xml", th, GASPHASE)
    return th, sm


def test_parse_features(h2oni):
    th, sm = h2oni
    assert sm.species == ("(NI)", "H(NI)", "O(NI)", "OH(NI)", "H2O(NI)")
    assert sm.n_reactions == 7
    # 3-number stick entry: s0 beta Ea(kJ/mol -> J/mol)
    np.testing.assert_allclose(np.asarray(sm.stick_s0)[:3], [1e-2, 2e-2, 1e-1])
    assert float(sm.beta[1]) == 0.5
    assert float(sm.Ea[1]) == pytest.approx(10.0e3)
    # mwc applies to stick id 3 only
    np.testing.assert_allclose(np.asarray(sm.mwc), [0, 0, 1, 0, 0, 0, 0])
    # <order id="4">h(ni)=1.5</order> overrides the exponent, not stoichiometry
    h_idx = sm.species.index("H(NI)")
    assert float(sm.expo_surf[3, h_idx]) == 1.5
    assert float(sm.nu_f_surf[3, h_idx]) == 2.0
    assert sm.int_expo is False  # fractional exponent forces the log/exp path
    # <coverage id="5 6">o(ni)=-30</coverage> in kJ/mol
    o_idx = sm.species.index("O(NI)")
    np.testing.assert_allclose(
        np.asarray(sm.cov_eps)[:, o_idx], [0, 0, 0, 0, -30e3, -30e3, 0])


def test_hand_computed_rates(h2oni):
    """Every reaction's rate of progress vs closed-form hand arithmetic."""
    th, sm = h2oni
    T, p = 900.0, 1.2e5
    x = np.zeros(len(GASPHASE))
    x[GASPHASE.index("H2")] = 0.3
    x[GASPHASE.index("O2")] = 0.2
    x[GASPHASE.index("H2O")] = 0.1
    x[GASPHASE.index("N2")] = 0.4
    theta = np.array([0.4, 0.2, 0.2, 0.1, 0.1])  # (ni) h o oh h2o

    q = np.asarray(surface_kinetics.reaction_rates(
        T, p, jnp.asarray(x), jnp.asarray(theta), sm))

    c = x * p / (R * T) * 1e-6                  # mol/cm^3
    molwt = np.asarray(th.molwt) * 1e3          # g/mol
    gamma = 2.66e-9                             # mol/cm^2 (fixture site density)
    R_cgs = R * 1e7

    def flux(M):                                # sqrt(RT/2piM), cm/s
        return np.sqrt(R_cgs * T / (2 * np.pi * M))

    # 1: plain stick, h2 + 2(ni): s0 * flux * c_H2 * theta_ni^2
    q1 = 1e-2 * flux(molwt[0]) * c[0] * theta[0] ** 2
    # 2: 3-number stick: s0 T^0.5-style beta and Ea enter the probability
    s2 = 2e-2 * np.exp(0.5 * np.log(T) - 10.0e3 / (R * T))
    q2 = s2 * flux(molwt[1]) * c[1] * theta[0] ** 2
    # 3: Motz-Wise: s0 -> s0/(1 - s0/2)
    s3 = 1e-1 / (1.0 - 1e-1 / 2.0)
    q3 = s3 * flux(molwt[2]) * c[2] * theta[0]
    # 4: Arrhenius with <order> h(ni)=1.5: k * (Gamma theta_h)^1.5
    q4 = 2.545e19 * np.exp(-81.21e3 / (R * T)) * (gamma * theta[1]) ** 1.5
    # 5: coverage-dependent Ea: Ea_eff = 97.9e3 - 30e3 * theta_o
    k5 = 5.0e22 * np.exp(-(97.90e3 - 30e3 * theta[2]) / (R * T))
    q5 = k5 * (gamma * theta[2]) * (gamma * theta[1])
    # 6: same coverage tag on id 6
    k6 = 3.0e20 * np.exp(-(42.70e3 - 30e3 * theta[2]) / (R * T))
    q6 = k6 * (gamma * theta[3]) * (gamma * theta[1])
    # 7: unimolecular desorption
    q7 = 3.732e12 * np.exp(-60.79e3 / (R * T)) * (gamma * theta[4])

    np.testing.assert_allclose(
        q, [q1, q2, q3, q4, q5, q6, q7], rtol=1e-12)


MECH_TEMPLATE = """ELEMENTS
H O
END
SPECIES
H2 O2 OH HO2
END
REACTIONS {units}
H2+O2=2OH   1.7E13  0.0  {ea}
END
"""


@pytest.mark.parametrize("units,ea_text,ea_si", [
    ("", "47780.", 47780.0 * 4.184),            # CHEMKIN default cal/mol
    ("CAL/MOLE", "47780.", 47780.0 * 4.184),
    ("KCAL/MOLE", "47.78", 47.78 * 4184.0),
    ("JOULES/MOLE", "199911.5", 199911.5),
    ("KJOULES/MOLE", "199.9115", 199.9115e3),
    ("KELVINS", "24043.", 24043.0 * R),
])
def test_reactions_unit_keywords(tmp_path, units, ea_text, ea_si):
    """REACTIONS unit keywords rescale Ea (models/gas.py:_energy_factor);
    asserted through the compiled tensor AND the forward rate constant."""
    mech = tmp_path / "m.dat"
    mech.write_text(MECH_TEMPLATE.format(units=units, ea=ea_text))
    gm = br.compile_gaschemistry(str(mech))
    assert float(gm.Ea[0]) == pytest.approx(ea_si, rel=1e-12)
    T = 1100.0
    conc = jnp.asarray([1.0, 2.0, 0.0, 0.0])    # mol/m^3
    kf, _tb = forward_rate_constants(T, conc, gm)
    # bimolecular: A_SI = A_cgs * 1e-6
    k_hand = 1.7e13 * 1e-6 * np.exp(-ea_si / (R * T))
    np.testing.assert_allclose(float(kf[0]), k_hand, rtol=1e-12)
