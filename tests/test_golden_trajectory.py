"""Full-horizon golden-trajectory regression (VERDICT round-1 item #3).

The reference's only committed numerical oracle is the batch_gas_and_surf
run: 1919 CVODE-accepted steps over 10 s at reltol 1e-6 / abstol 1e-10
(/root/reference/test/batch_gas_and_surf/{gas_profile,surface_covg}.csv).
These tests integrate the same config end-to-end in reference-parity mode
(``kc_compat=True`` — quirk Kc + falloff-collider convention, PARITY.md)
and assert quantified bounds against every golden row.

Error structure (measured, scripts/golden_measure.py): the only significant
deviation is a ~0.8% shift of the ignition-front *time*; pointwise errors
outside the front window are <7e-4 mole fraction and <2.6e-3 coverage.
Bounds below carry ~5x margin over the measured values while remaining
orders of magnitude tighter than any wrong falloff convention (the physical
TROE convention misses pre-ignition radical pools by 20x-8e4x).
"""

import shutil

import numpy as np
import pytest

import batchreactor_tpu as br

FRONT_LO, FRONT_HI = 0.8, 1.2   # excluded window around the ignition front


def _load(path):
    hdr = open(path).readline().strip().split(",")
    return hdr, np.loadtxt(path, delimiter=",", skiprows=1)


def _crossing(t, x, level):
    j = int(np.argmax(x < level))
    return t[j]


@pytest.fixture(scope="module")
def golden_run(tmp_path_factory, reference_dir, lib_dir):
    """One native-backend 10 s parity run shared by the assertions below
    (the native BDF is the fast CVODE-role solver; the JAX path is
    cross-checked against it in test_jax_solver_matches_native_mid_ignition)."""
    from batchreactor_tpu import native
    if not native.available():
        pytest.skip("native runtime unavailable")
    out = tmp_path_factory.mktemp("golden")
    shutil.copy(reference_dir / "test/batch_gas_and_surf/batch.xml",
                out / "batch.xml")
    ret = br.batch_reactor(str(out / "batch.xml"), lib_dir,
                           gaschem=True, surfchem=True, kc_compat=True,
                           backend="cpu")
    assert ret == "Success"
    gold_dir = reference_dir / "test" / "batch_gas_and_surf"
    return {
        "gas_gold": _load(str(gold_dir / "gas_profile.csv")),
        "gas_ours": _load(str(out / "gas_profile.csv")),
        "covg_gold": _load(str(gold_dir / "surface_covg.csv")),
        "covg_ours": _load(str(out / "surface_covg.csv")),
    }


def test_ignition_front_time(golden_run):
    """CH4 half-consumption instant within 2% of the golden 3.7583e-3 s
    (measured deviation 0.8%)."""
    gh, gold = golden_run["gas_gold"]
    oh, ours = golden_run["gas_ours"]
    i = gh.index("CH4")
    t_gold = _crossing(gold[:, 0], gold[:, i], 0.125)
    t_ours = _crossing(ours[:, 0], ours[:, i], 0.125)
    assert t_gold == pytest.approx(3.7583e-3, rel=1e-3)  # oracle sanity
    assert abs(t_ours - t_gold) / t_gold < 0.02


def test_gas_profile_all_rows(golden_run):
    """Every species column, all 1919 golden rows, outside the front window:
    max abs mole-fraction error < 5e-3 (measured < 7e-4).  Density and
    pressure tighter still."""
    gh, gold = golden_run["gas_gold"]
    oh, ours = golden_run["gas_ours"]
    assert gh == oh
    assert len(gold) == 1919  # 1920 lines incl. header (SURVEY.md §6)
    tg = gold[:, 0]
    i_ch4 = gh.index("CH4")
    t_front = _crossing(tg, gold[:, i_ch4], 0.125)
    outside = (tg < FRONT_LO * t_front) | (tg > FRONT_HI * t_front)
    # CVODE concentrates ~1/3 of its steps inside the ignition front; the
    # excluded window covers only that sliver of *time* (0.3% of horizon)
    assert outside.sum() > 1300
    for i, name in enumerate(gh):
        oi = np.interp(tg, ours[:, 0], ours[:, i])
        d = np.abs(oi - gold[:, i])[outside]
        if name == "t":
            continue
        if name in ("p", "rho", "T"):
            rel = d / np.abs(gold[outside, i])
            assert rel.max() < 1e-3, f"{name}: max rel {rel.max():.2e}"
        else:
            assert d.max() < 5e-3, f"{name}: max abs {d.max():.2e}"


def test_surface_coverages_all_rows(golden_run):
    """All 13 coverages, all golden rows outside the front window:
    max abs error < 2e-2 (measured < 2.6e-3)."""
    ch, covg = golden_run["covg_gold"]
    co, covo = golden_run["covg_ours"]
    assert ch == co
    tg = covg[:, 0]
    gh, gold = golden_run["gas_gold"]
    t_front = _crossing(gold[:, 0], gold[:, gh.index("CH4")], 0.125)
    outside = (tg < FRONT_LO * t_front) | (tg > FRONT_HI * t_front)
    for i, name in enumerate(ch):
        if name in ("t", "T"):
            continue
        oi = np.interp(tg, covo[:, 0], covo[:, i])
        d = np.abs(oi - covg[:, i])[outside]
        assert d.max() < 2e-2, f"{name}: max abs {d.max():.2e}"


def test_final_state_all_species(golden_run):
    """End-of-horizon state (t=10 s): every golden mole fraction above 1e-6
    matched to 1% relative (trace NOx channels at the 1e-8 level to 10%);
    equilibrium is convention-sensitive, so this pins Kc handling across the
    whole mechanism."""
    gh, gold = golden_run["gas_gold"]
    oh, ours = golden_run["gas_ours"]
    for i, name in enumerate(gh):
        if name == "t":
            continue
        g, o = gold[-1, i], ours[-1, i]
        if abs(g) > 1e-6:
            assert abs(o - g) / abs(g) < 0.01, f"{name}: {o} vs {g}"
        elif abs(g) > 1e-8:
            assert abs(o - g) / abs(g) < 0.10, f"{name}: {o} vs {g}"


def test_jax_solver_matches_native_mid_ignition(reference_dir, lib_dir,
                                                tmp_path):
    """Cross-solver check in parity mode: the JAX SDIRK4 path reproduces the
    native BDF mid-ignition state (t=1e-3, pre-front) to 0.5%."""
    src = (reference_dir / "test/batch_gas_and_surf/batch.xml").read_text()
    for sub in ("jax", "cpu"):
        d = tmp_path / sub
        d.mkdir()
        (d / "batch.xml").write_text(
            src.replace("<time>10</time>", "<time>1e-3</time>"))
    from batchreactor_tpu import native
    backends = ["jax"] + (["cpu"] if native.available() else [])
    if len(backends) < 2:
        pytest.skip("native runtime unavailable")
    rows = {}
    for b in backends:
        ret = br.batch_reactor(str(tmp_path / b / "batch.xml"), lib_dir,
                               gaschem=True, surfchem=True, kc_compat=True,
                               backend=b)
        assert ret == "Success"
        rows[b] = np.loadtxt(tmp_path / b / "gas_profile.csv",
                             delimiter=",", skiprows=1)[-1]
    np.testing.assert_allclose(rows["jax"][1:], rows["cpu"][1:],
                               rtol=5e-3, atol=1e-9)
