"""Sensitivity subsystem tests (ISSUE 2 acceptance gates).

Covers: params select/extract/apply round-trips; staggered forward
tangents vs central finite differences on the vendored h2o2 fixture
(tol-tiered); adjoint-vs-forward gradient consistency on a scalar QoI;
the vmapped 8-lane forward-sensitivity sweep; the ``sens=`` kwarg
surface of ``batch_reactor`` (validation, legacy-hook theta, solved
forward/adjoint returns); the unknown-status-code fallback; and the
``scripts/sens_rank.py`` CLI.

Everything runs on the CPU backend (conftest pins it) against
tests/fixtures — no reference checkout needed.
"""

import functools
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import batchreactor_tpu as br
from batchreactor_tpu.models.gas import compile_gaschemistry
from batchreactor_tpu.models.thermo import create_thermo
from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
from batchreactor_tpu.sensitivity import adjoint, forward, params, rank
from batchreactor_tpu.solver import bdf
from batchreactor_tpu.solver.sdirk import SUCCESS
from batchreactor_tpu.utils.composition import density, mole_to_mass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared fixture mechanism state (module-scoped: parsed once)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def h2o2(fixtures_dir):
    gm = compile_gaschemistry(os.path.join(fixtures_dir, "h2o2.dat"))
    th = create_thermo(list(gm.species), os.path.join(fixtures_dir,
                                                      "therm.dat"))
    sp = list(gm.species)
    x = np.zeros(len(sp))
    x[sp.index("H2")], x[sp.index("O2")], x[sp.index("N2")] = 0.3, 0.2, 0.5
    x = jnp.asarray(x, dtype=jnp.float64)
    y0 = density(x, th.molwt, 1100.0, 1e5) * mole_to_mass(x, th.molwt)
    cfg = {"T": jnp.asarray(1100.0, dtype=jnp.float64),
           "Asv": jnp.asarray(1.0, dtype=jnp.float64)}
    return gm, th, sp, y0, cfg


@pytest.fixture(scope="module")
def h2o2_theta(h2o2):
    """3-reaction log_A selection + theta-parameterized RHS/Jacobian —
    small P keeps the forward tangent block (and FD loop) cheap."""
    gm, th, sp, y0, cfg = h2o2
    spec = params.select(gm, fields=("log_A",), reactions=(1, 2, 4))
    theta = params.extract(gm, spec)
    rhs_theta = params.make_rhs_theta(gm, spec,
                                      lambda m: make_gas_rhs(m, th))

    def jac_theta(t, y, theta, cfg):
        return make_gas_jac(params.apply(gm, theta, spec), th)(t, y, cfg)

    return spec, theta, rhs_theta, jac_theta


# ---------------------------------------------------------------------------
# params: the theta layer
# ---------------------------------------------------------------------------
def test_select_extract_apply_roundtrip(h2o2):
    gm, *_ = h2o2
    spec = params.select(gm, fields=("log_A", "Ea"))
    theta = params.extract(gm, spec)
    assert theta["log_A"].shape == (gm.n_reactions,)
    gm2 = params.apply(gm, theta, spec)
    # unperturbed splice is the identity
    np.testing.assert_array_equal(np.asarray(gm2.log_A),
                                  np.asarray(gm.log_A))
    # perturbation lands on exactly the selected rows
    spec3 = params.select(gm, reactions=(2, 5))
    th3 = params.extract(gm, spec3)
    gm3 = params.apply(gm, {"log_A": th3["log_A"] + 0.1}, spec3)
    delta = np.asarray(gm3.log_A) - np.asarray(gm.log_A)
    expect = np.zeros(gm.n_reactions)
    expect[[2, 5]] = 0.1
    np.testing.assert_allclose(delta, expect, atol=1e-14)
    # names align with flatten order
    flat, unflat = params.flatten(theta)
    assert flat.shape == (2 * gm.n_reactions,)
    assert len(params.names(spec)) == 2 * gm.n_reactions
    np.testing.assert_array_equal(np.asarray(unflat(flat)["Ea"]),
                                  np.asarray(theta["Ea"]))


def test_select_glob_and_errors(h2o2):
    gm, *_ = h2o2
    spec = params.select(gm, reactions="*H2O2*")
    assert spec.n_reactions > 0
    assert all("H2O2" in e for e in spec.equations)
    with pytest.raises(ValueError, match="matches nothing"):
        params.select(gm, reactions="*XENON*")
    with pytest.raises(ValueError, match="unknown gas field"):
        params.select(gm, fields=("nu_f",))
    with pytest.raises(IndexError):
        params.select(gm, reactions=(0, 10_000))


# ---------------------------------------------------------------------------
# forward: analytic oracle + mechanism FD golden (tol-tiered)
# ---------------------------------------------------------------------------
def test_forward_tangents_analytic_decay():
    """dy/dt = -k y: S = dy(t)/dk = -t e^{-kt}, exact oracle."""

    def rhs_theta(t, y, theta, cfg):
        return -theta["k"][0] * y

    theta = {"k": jnp.asarray([1.3])}
    r = forward.solve_forward(rhs_theta, jnp.asarray([1.0]), 0.0, 1.0,
                              theta, None, rtol=1e-10, atol=1e-14)
    assert int(r.status) == SUCCESS
    np.testing.assert_allclose(float(r.tangents[0, 0]), -np.exp(-1.3),
                               rtol=1e-7)
    # jac_window staleness must not move tangents beyond tolerance noise
    r4 = forward.solve_forward(rhs_theta, jnp.asarray([1.0]), 0.0, 1.0,
                               theta, None, rtol=1e-10, atol=1e-14,
                               jac_window=4)
    np.testing.assert_allclose(np.asarray(r4.tangents),
                               np.asarray(r.tangents), rtol=1e-6)


def test_forward_matches_central_fd_h2o2(h2o2, h2o2_theta):
    """Acceptance gate: staggered forward tangents vs central finite
    differences on the fixture mechanism, tol-tiered — the loose tier
    checks the production tolerance tracks, the tight tier checks the
    1e-3 contract."""
    gm, th, sp, y0, cfg = h2o2
    spec, theta, rhs_theta, jac_theta = h2o2_theta
    t1 = 3e-5

    # FD baseline: theta enters traced, so all 6 perturbed solves share
    # ONE compiled executable
    @jax.jit
    def final_at(th_flat):
        th_ = {"log_A": th_flat}
        return bdf.solve(
            lambda t, y, cfg: rhs_theta(t, y, th_, cfg), y0, 0.0, t1, cfg,
            rtol=1e-10, atol=1e-14,
            jac=lambda t, y, cfg: jac_theta(t, y, th_, cfg)).y

    base = theta["log_A"]
    eps = 1e-4
    fd = np.stack([
        (np.asarray(final_at(base.at[i].add(eps)))
         - np.asarray(final_at(base.at[i].add(-eps)))) / (2 * eps)
        for i in range(base.shape[0])])

    def jac_fixed(t, y, cfg):
        return jac_theta(t, y, theta, cfg)

    # tol tiers: the production tolerance documents the (expected,
    # CVODES-like) faster degradation of non-error-controlled tangents;
    # the tight tier pins the 1e-3 acceptance contract
    for rtol, tol in ((1e-6, 0.25), (1e-8, 1.5e-3)):
        r = forward.solve_forward(rhs_theta, y0, 0.0, t1, theta, cfg,
                                  rtol=rtol, atol=rtol * 1e-4,
                                  jac=jac_fixed)
        assert int(r.status) == SUCCESS
        S = np.asarray(r.tangents)
        scale = np.max(np.abs(fd), axis=1, keepdims=True)
        np.testing.assert_allclose(S / scale, fd / scale, atol=tol)


def test_adjoint_analytic_decay_and_nan_when_never_crossed():
    """Adjoint on the decay oracle: final-state gradient matches the
    closed form, and a never-crossing ignition marker yields NaN tau
    with a zero (constant-branch) gradient — never a silently-plausible
    clipped-interpolation value."""

    def rhs_theta(t, y, theta, cfg):
        return -theta["k"][0] * y

    theta = {"k": jnp.asarray([1.3])}
    y0 = jnp.asarray([1.0])
    qoi, grad, aux = adjoint.solve_adjoint(
        rhs_theta, adjoint.final_species_qoi(0), y0, 0.0, 1.0, theta,
        None, rtol=1e-9, atol=1e-13, grid_size=64, segments=4)
    assert int(aux["status"]) == SUCCESS
    np.testing.assert_allclose(float(qoi), np.exp(-1.3), rtol=1e-7)
    np.testing.assert_allclose(float(grad["k"][0]), -np.exp(-1.3),
                               rtol=1e-6)
    # decaying y never drops below half within t=1e-3 -> NaN tau, 0 grad
    qoi2, grad2, _ = adjoint.solve_adjoint(
        rhs_theta, adjoint.ignition_delay_qoi(0), y0, 0.0, 1e-3, theta,
        None, rtol=1e-6, atol=1e-10, grid_size=32, segments=4)
    assert np.isnan(float(qoi2))
    np.testing.assert_array_equal(np.asarray(grad2["k"]), np.zeros(1))


# ---------------------------------------------------------------------------
# sweep: vmapped 8-lane forward-sensitivity smoke (JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------
def test_forward_sensitivity_sweep_8_lanes(h2o2):
    gm, th, sp, y0, cfg = h2o2
    from batchreactor_tpu.parallel import ensemble_solve_forward

    spec = params.select(gm, reactions=(1, 2))
    theta = params.extract(gm, spec)
    rhs_theta = params.make_rhs_theta(gm, spec,
                                      lambda m: make_gas_rhs(m, th))

    def jac_fixed(t, y, cfg):
        return make_gas_jac(params.apply(gm, theta, spec), th)(t, y, cfg)

    B = 8
    T = jnp.linspace(1050.0, 1200.0, B)
    y0s = jnp.broadcast_to(y0, (B,) + y0.shape)
    cfgs = {"T": T, "Asv": jnp.ones((B,))}
    res = ensemble_solve_forward(rhs_theta, y0s, 0.0, 1e-5, theta, cfgs,
                                 rtol=1e-6, atol=1e-10, jac=jac_fixed)
    assert np.all(np.asarray(res.status) == SUCCESS)
    S = np.asarray(res.tangents)
    assert S.shape == (B, 2, len(sp))
    assert np.all(np.isfinite(S))
    # hotter lanes react further: the tangent magnitudes must actually
    # vary across lanes (a broadcast bug would repeat lane 0)
    mags = np.max(np.abs(S), axis=(1, 2))
    assert len(np.unique(mags)) == B


# ---------------------------------------------------------------------------
# api surface: sens= normalization, legacy hook, solved modes, status fix
# ---------------------------------------------------------------------------
@pytest.fixture()
def h2o2_xml(tmp_path):
    (tmp_path / "batch.xml").write_text("""<?xml version="1.0"?>
<batch>
  <gas_mech>h2o2.dat</gas_mech>
  <molefractions>H2=0.3,O2=0.2,N2=0.5</molefractions>
  <T>1100.0</T> <p>1e5</p> <time>3e-5</time>
</batch>""")
    return str(tmp_path / "batch.xml")


def test_sens_kwarg_validation(h2o2_xml, fixtures_dir):
    with pytest.raises(ValueError, match="sens must be"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="bogus")
    with pytest.raises(ValueError, match="sens must be"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True, sens=1)
    # adjoint without a QoI is loud
    with pytest.raises(ValueError, match="scalar QoI"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="adjoint", verbose=False)
    # sensitivity solves are jax-backend / BDF only
    with pytest.raises(ValueError, match="jax backend"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="forward", backend="cpu", verbose=False)
    with pytest.raises(ValueError, match="BDF"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="forward", method="sdirk", verbose=False)
    # forward cannot do trajectory QoIs
    with pytest.raises(ValueError, match="adjoint"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="forward", sens_qoi=("ignition", "H2"),
                         verbose=False)
    # an explicit segmented= would be silently ignored — loud instead
    with pytest.raises(ValueError, match="monolithically"):
        br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                         sens="forward", segmented=True, verbose=False)


def test_sens_rejected_on_programmatic_form(fixtures_dir):
    gm = compile_gaschemistry(os.path.join(fixtures_dir, "h2o2.dat"))
    th = create_thermo(list(gm.species), os.path.join(fixtures_dir,
                                                      "therm.dat"))
    with pytest.raises(ValueError, match="file-driven"):
        br.batch_reactor({"H2": 0.3, "O2": 0.2, "N2": 0.5}, 1100.0, 1e5,
                         1e-5, chem=br.Chemistry(gaschem=True),
                         thermo_obj=th, md=gm, sens=True)


def test_legacy_hook_carries_theta(h2o2_xml, fixtures_dir):
    prob = br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                            sens=True)
    assert isinstance(prob, br.SensitivityProblem)
    assert prob.spec is not None and prob.theta is not None
    assert prob.theta["log_A"].shape == (prob.spec.n_reactions,)
    # the hook composes with sensitivity.params: a perturbed-theta rhs
    # evaluates and differs from the base rhs
    gm = compile_gaschemistry(os.path.join(fixtures_dir, "h2o2.dat"))
    gm2 = params.apply(gm, {"log_A": prob.theta["log_A"] + 0.2},
                       prob.spec)
    assert not np.allclose(np.asarray(gm2.log_A), np.asarray(gm.log_A))


def test_api_forward_and_adjoint(h2o2_xml, fixtures_dir):
    """Acceptance gate: batch_reactor(sens="forward") and
    (sens="adjoint") both solve, and the two differentiation routes —
    staggered tangents through the adaptive BDF loop vs IFT-vjp backward
    pass over the pinned grid — agree on the QoI gradient to the 1e-3
    contract (small 2-parameter selection keeps it fast; the full-theta
    FD gate is test_forward_matches_central_fd_h2o2)."""
    sel = {"reactions": (1, 2)}
    fwd_sol = br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                               sens="forward", sens_params=sel,
                               sens_qoi="H2O", rtol=1e-8, atol=1e-12,
                               verbose=False)
    assert isinstance(fwd_sol, br.SensitivitySolution)
    assert fwd_sol.status == "Success"
    assert fwd_sol.tangents.shape == (2, 9)
    assert fwd_sol.names == ("log_A[OH+H2=H2O+H]", "log_A[H+O2=OH+O]")
    # 125 rounds up to the adjoint's segment multiple internally — any
    # sens_grid value is a valid capacity
    adj_sol = br.batch_reactor(h2o2_xml, fixtures_dir, gaschem=True,
                               sens="adjoint", sens_params=sel,
                               sens_qoi="H2O", rtol=1e-8, atol=1e-12,
                               sens_grid=125, verbose=False)
    assert adj_sol.status == "Success"
    assert adj_sol.truncated is False
    np.testing.assert_allclose(adj_sol.qoi, fwd_sol.qoi, rtol=2e-3)
    gf = np.asarray(fwd_sol.qoi_grad["log_A"])
    ga = np.asarray(adj_sol.qoi_grad["log_A"])
    scale = np.max(np.abs(gf))
    np.testing.assert_allclose(ga / scale, gf / scale, atol=1e-3)
    # normalized ranking runs on the result
    coeffs = rank.normalized_sensitivities(adj_sol.qoi, ga)
    ranking = rank.top_k(coeffs, adj_sol.spec.equations, k=2)
    assert len(ranking) == 2 and ranking[0][0] == 1


def test_status_fallback_unknown_code():
    """Regression (ISSUE 2 satellite): an unknown/future solver code must
    degrade to "Failure(<code>)", never KeyError."""
    from batchreactor_tpu.api import _STATUS, _status_str

    assert _status_str(1) == "Success"
    assert _status_str(2) == "MaxIters"
    assert _status_str(3) == "DtLessThanMin"
    assert _status_str(99) == "Failure(99)"
    assert _status_str(np.int32(-7)) == "Failure(-7)"
    assert 99 not in _STATUS


# ---------------------------------------------------------------------------
# solver hooks: step audit surface
# ---------------------------------------------------------------------------
def test_step_audit_surfaces_ring_and_matrix():
    def rhs(t, y, cfg):
        return -y

    r = bdf.solve(rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                  rtol=1e-6, atol=1e-12, step_audit=True)
    ring = np.asarray(r.accept_ring)
    n_attempts = int(r.n_accepted) + int(r.n_rejected)
    assert ring.shape == (64,)
    # every used slot is 0/1; unused slots keep the -1 sentinel
    used = ring[ring >= 0]
    assert used.size == min(n_attempts, 64)
    assert used.sum() <= int(r.n_accepted)
    M = np.asarray(r.it_matrix)
    assert M.shape == (2, 2) and np.all(np.isfinite(M))
    # M = I - cJ with J = -I here: symmetric with M[0,0] > 1
    assert M[0, 0] > 1.0 and abs(M[0, 1]) < 1e-12
    # default solves pay none of this: fields stay None
    r0 = bdf.solve(rhs, jnp.asarray([1.0, 2.0]), 0.0, 1.0, None,
                   rtol=1e-6, atol=1e-12)
    assert r0.accept_ring is None and r0.it_matrix is None
    assert r0.tangents is None


# ---------------------------------------------------------------------------
# CLI: scripts/sens_rank.py (fast: 3-reaction selection)
# ---------------------------------------------------------------------------
def test_sens_rank_cli(h2o2_xml, fixtures_dir):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "sens_rank.py"),
         h2o2_xml, fixtures_dir, "--qoi", "H2O", "--mode", "forward",
         "--reactions", "*H2O2*", "-k", "3"],
        capture_output=True, text=True, timeout=280,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "QoI =" in out.stdout
    assert "dln(H2O)/dlnA" in out.stdout
    # 3 ranked rows, all naming H2O2 reactions
    rows = [ln for ln in out.stdout.splitlines()
            if ln.strip() and ln.split()[0].isdigit()]
    assert len(rows) == 3
    assert all("H2O2" in r for r in rows)
