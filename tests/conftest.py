"""Test harness config: CPU backend, float64, 8 virtual devices for mesh tests.

Must run before jax initializes a backend (SURVEY.md §4: the standard
fake-multi-device trick, XLA_FLAGS=--xla_force_host_platform_device_count=N).
"""

import os

# BR_TEST_TPU=1 runs the on-chip smoke tier (-m tpu, scripts/tpu_smoke.py):
# the real accelerator backend is left in place and no virtual devices are
# forced.  Default: CPU pinned with 8 virtual devices for the mesh tests.
_TPU_TIER = os.environ.get("BR_TEST_TPU") == "1"

# The axon TPU plugin in this image overrides the JAX_PLATFORMS env var, so the
# cpu pin must go through jax.config (verified: env alone still yields the TPU).
if not _TPU_TIER:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

# The aot/ compile-economy discipline applied to the LOCAL tier-1 run,
# matching what CI has done since PR 5 (ci.yml restores/saves
# /tmp/jax_cache around the suite): warmed executables from a previous
# run are cache-served instead of recompiled — compile cost dominates
# the suite wall.  setdefault so CI's own dir (and any operator
# override) wins; min-compile-time 0 is the established cache
# discipline (bench.py).  Tests that assert TRUE compiles/retraces pin
# the cache off via the cold_compile_cache fixture below — the same
# contract that already holds under CI's warm cache.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import pathlib

import jax
import pytest

try:
    # one place, loaded for the whole session regardless of collection
    # order: jit compilation inside properties breaks per-example deadlines
    from hypothesis import settings as _hyp_settings

    _hyp_settings.register_profile("br", deadline=None, max_examples=25)
    _hyp_settings.load_profile("br")
except ImportError:  # property tier simply absent without hypothesis
    pass

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# BR_REFERENCE= (empty/nonexistent) simulates a bare clone: mechanism tests
# run from the vendored fixtures, reference-only tests skip
REFERENCE = pathlib.Path(os.environ.get("BR_REFERENCE", "/root/reference"))
LIB = REFERENCE / "test" / "lib"
FIXTURES = pathlib.Path(__file__).parent / "fixtures"


@pytest.fixture
def cold_compile_cache():
    """Pin the persistent compilation cache OFF for tests that assert
    TRUE XLA compiles or retraces.  Under a warm ``.jax_cache`` (exactly
    what CI restores between tier-1 runs — ci.yml) those compiles are
    serviced as cache loads, which CompileWatch deliberately does NOT
    count as compiles (obs/retrace.py): right for production, wrong for
    these assertions.  Also detaches jax's latched cache handle so the
    config change takes effect mid-process."""
    from batchreactor_tpu.aot import reset_persistent_cache

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    reset_persistent_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", old)
    reset_persistent_cache()


@pytest.fixture(scope="session")
def lib_dir():
    # prefer the reference mechanism library; a bare clone (CI) falls back to
    # the vendored fixtures (h2o2.dat + trimmed therm.dat + h2oni.xml), so
    # the mechanism-driven core tests run everywhere
    if LIB.is_dir():
        return str(LIB)
    return str(FIXTURES)


@pytest.fixture(scope="session")
def gri_lib_dir(lib_dir):
    # GRI-3.0 / CH4-Ni mechanisms are vendored in tests/fixtures since
    # round 3, so lib_dir (reference checkout or fixtures fallback) always
    # carries them; the skip remains as a guard for partial checkouts
    if not (pathlib.Path(lib_dir) / "grimech.dat").is_file():
        pytest.skip(f"grimech.dat/ch4ni.xml unavailable in {lib_dir}")
    return lib_dir


@pytest.fixture(scope="session")
def fixtures_dir():
    return str(FIXTURES)


@pytest.fixture(scope="session")
def reference_dir():
    if not REFERENCE.is_dir():
        pytest.skip(f"reference checkout unavailable at {REFERENCE}")
    return REFERENCE
