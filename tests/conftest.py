"""Test harness config: CPU backend, float64, 8 virtual devices for mesh tests.

Must run before jax initializes a backend (SURVEY.md §4: the standard
fake-multi-device trick, XLA_FLAGS=--xla_force_host_platform_device_count=N).
"""

import os

# The axon TPU plugin in this image overrides the JAX_PLATFORMS env var, so the
# cpu pin must go through jax.config (verified: env alone still yields the TPU).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import pathlib

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

REFERENCE = pathlib.Path("/root/reference")
LIB = REFERENCE / "test" / "lib"


@pytest.fixture(scope="session")
def lib_dir():
    # CI runners have no reference checkout: mechanism-driven tests skip
    # there and the pure-solver/pure-math tests still give signal
    if not LIB.is_dir():
        pytest.skip(f"reference mechanism library unavailable at {LIB}")
    return str(LIB)


@pytest.fixture(scope="session")
def reference_dir():
    if not REFERENCE.is_dir():
        pytest.skip(f"reference checkout unavailable at {REFERENCE}")
    return REFERENCE
