"""Forward parameter sensitivities: CVODES-style staggered tangents.

Solves the tangent ODE

    dS_p/dt = J(t, y) S_p + df/dtheta_p,      S_p(t0) = dy0/dtheta_p

alongside the state, one row per scalar parameter, inside the SAME
variable-order BDF step loop as the plain solve (``solver.bdf.solve``'s
``tangent=`` hook): the tangents share the state's step grid, order and
difference-history transforms, and every sensitivity linear solve reuses
the step's already-built Newton iteration matrix — no second Jacobian
build, no separate integration.  Everything is ``lax`` control flow, so a
forward-sensitivity solve jits, vmaps over ensemble lanes and shards over
the mesh exactly like the plain solve (the property a naive
``jax.jacfwd`` over the whole solver loses: it multiplies the while-loop
carry by P *and* re-runs Newton per tangent, and is the memory-hostile
path the ISSUE retires).

Cost: P extra RHS-jvp evaluations plus P triangular solves per accepted
step — linear in #params, like CVODES ``CVodeSensInit``.  For gradients
of a *scalar* QoI with many parameters, use :mod:`.adjoint` instead
(docs/sensitivity.md has the decision table).
"""

import jax
import jax.numpy as jnp

from ..solver import bdf
from . import params as P


def make_fdot(rhs_theta, theta, cfg):
    """Sensitivity-RHS factory: ``fdot(t, y, S) -> (P, n)`` with rows
    J(t, y) S_p + df/dtheta_p, evaluated as one jvp per tangent row
    (vmapped) — exact to roundoff, never materializes J, and costs about
    one RHS evaluation per row.

    ``rhs_theta(t, y, theta, cfg)`` is the theta-parameterized RHS
    (``params.make_rhs_theta``); ``theta`` is the dict pytree the tangent
    rows are ordered against (``params.flatten`` order, i.e.
    ``params.names``).
    """
    theta_flat, unflatten = P.flatten(theta)
    nP = theta_flat.shape[0]
    eyeP = jnp.eye(nP, dtype=theta_flat.dtype)

    def fdot(t, y, S):
        def one(s_row, e_row):
            _, dy = jax.jvp(
                lambda yy, tf: rhs_theta(t, yy, unflatten(tf), cfg),
                (y, theta_flat), (s_row, e_row))
            return dy

        return jax.vmap(one)(S, eyeP)

    return fdot


def solve_forward(rhs_theta, y0, t0, t1, theta, cfg, *, rtol=1e-6,
                  atol=1e-10, max_steps=100_000, n_save=0, dt0=None,
                  jac=None, jac_window=1, linsolve="auto", sens_iters=2,
                  sens_errcon=False, observer=None, observer_init=None,
                  S0=None, step_audit=False, stats=False, recorder=None):
    """Integrate state + forward sensitivities in one BDF solve.

    Returns the plain :class:`~..solver.sdirk.SolveResult` with
    ``tangents`` filled: a (P, n) block S = dy(t_end)/dtheta whose row
    order is ``params.flatten``/``params.names`` order of ``theta``.

    ``jac`` is the analytic state Jacobian at the *given* theta (build it
    from ``params.apply(mech, theta, spec)`` — api.py does); ``S0``
    overrides the zero initial tangents when y0 depends on theta.
    Remaining knobs mirror ``bdf.solve``, including the telemetry pair:
    ``stats=True`` turns on the device counter block (the tangent-carrying
    program counts exactly like the plain solve — obs/counters.py), and
    ``recorder`` (an ``obs.Recorder``) gets a blocking ``sens_forward``
    span around the solve.  Pass a recorder only from eager callers — a
    span inside a jitted/vmapped wrapper would time tracing, not solving.
    """
    from ..obs.recorder import span_or_null

    theta_flat, _ = P.flatten(theta)
    nP = theta_flat.shape[0]
    y0 = jnp.asarray(y0)
    if S0 is None:
        S0 = jnp.zeros((nP, y0.shape[0]), dtype=y0.dtype)
    fdot = make_fdot(rhs_theta, theta, cfg)

    def rhs(t, y, cfg):
        return rhs_theta(t, y, theta, cfg)

    with span_or_null(recorder, "sens_forward", n_params=int(nP)) as sp:
        res = bdf.solve(
            rhs, y0, t0, t1, cfg, rtol=rtol, atol=atol, max_steps=max_steps,
            n_save=n_save, dt0=dt0, jac=jac, jac_window=jac_window,
            linsolve=linsolve, observer=observer,
            observer_init=observer_init, tangent=(fdot, S0),
            sens_iters=sens_iters, sens_errcon=sens_errcon,
            step_audit=step_audit, stats=stats)
        if recorder is not None:
            jax.block_until_ready(res.y)
            sp["attrs"]["n_accepted"] = int(res.n_accepted)
    return res


# --------------------------------------------------------------------------
# brlint tier-C program contract (analysis/contracts.py): the
# tangent-carrying forward BDF step program must meet the same purity
# contract as the plain solve from day one (this audit caught an
# in-loop index-staging device_put in params.apply when it first ran).
# --------------------------------------------------------------------------
from ..analysis.contracts import Pure, program_contract  # noqa: E402


@program_contract(
    "sens-forward-step",
    doc="tangent-carrying forward BDF step program: pure")
def _contract_sens_forward(h):
    _spec, theta, rhs_theta = h.sens_fixture()

    def run(y0_):
        return solve_forward(rhs_theta, y0_, 0.0, 1e-7, theta, h.cfg,
                             rtol=1e-6, atol=1e-10, max_steps=3,
                             jac=h.jac).tangents

    yield Pure("sens-forward-step", h.jaxpr(run, h.y0))
