"""Parameter-sensitivity subsystem: forward tangents, adjoint gradients,
reaction ranking.

The reference's ``sens=true`` hook returns the ODE problem *unsolved*
(/root/reference/src/BatchReactor.jl:205-207) and leaves differentiation
to the caller; Sundials users instead get CVODES forward sensitivities
(``CVodeSensInit``) and checkpointed adjoints.  This package closes that
capability gap natively in JAX, in pure ``lax`` control flow so every
program jits, vmaps over ensemble lanes, and shards over the device mesh
exactly like the plain solve:

``params``
    Named, differentiable parameter pytrees theta (gas Arrhenius A/beta/Ea,
    surface A/Ea/sticking) extracted from the frozen mechanism bundles,
    with ``apply(mech, theta, spec)`` splicing perturbed values back in.
``forward``
    CVODES-style staggered forward sensitivities: tangent difference
    histories ride the existing variable-order BDF step machinery
    (``solver.bdf.solve(tangent=...)``), and every sensitivity linear
    solve reuses the step's already-built Newton iteration matrix.
``adjoint``
    Reverse-mode gradients of scalar QoIs at O(#params)-independent cost:
    an adaptive forward pass pins the step grid, then a fixed-grid SDIRK4
    re-solve — each implicit stage an implicit-function-theorem
    ``custom_vjp`` — is differentiated backwards under ``jax.checkpoint``
    segment rematerialization.
``rank``
    Normalized sensitivity coefficients d ln(QoI) / d ln(A_i) and top-k
    reaction ranking (the ignition-delay sensitivity workload).

Math contract and forward-vs-adjoint guidance: docs/sensitivity.md.
"""

from .params import ParamSpec, apply, extract, names, select  # noqa: F401
from .forward import make_fdot, solve_forward  # noqa: F401
from .adjoint import (final_species_qoi, ignition_delay_qoi,  # noqa: F401
                      solve_adjoint)
from .rank import normalized_sensitivities, top_k  # noqa: F401

__all__ = [
    "ParamSpec", "select", "extract", "apply", "names",
    "make_fdot", "solve_forward",
    "solve_adjoint", "final_species_qoi", "ignition_delay_qoi",
    "normalized_sensitivities", "top_k",
]
