"""Adjoint (reverse-mode) parameter gradients of scalar QoIs.

Reverse mode cannot traverse the solver's adaptive ``lax.while_loop``
(and a naive backsolve of a stiff chemistry ODE is unstable), so the
adjoint here is discretize-then-optimize on a *pinned* grid, the
checkpointed-adjoint shape CVODES calls ``CVodeAdjInit``:

1. **Grid-pinning pass** — one plain adaptive BDF solve at
   ``stop_gradient(theta)`` records its accepted-step times into the
   fixed-size trajectory buffer.  The grid is frozen (zero cotangent):
   gradients flow through solution *values*, never through step-size
   control — exactly the quantity the discrete solution defines.
2. **Differentiable re-solve** — a fixed-grid SDIRK4 sweep over those
   knots (the L-stable tableau from ``solver.sdirk``), each implicit
   stage an implicit-function-theorem ``jax.custom_vjp``: forward runs
   Newton to convergence; backward solves ONE transposed linear system
   ``(I - h gamma J)^T lam = zbar`` and pulls ``theta``/``cfg``
   cotangents through a single RHS vjp — Newton's iteration history is
   never differentiated or stored.  Padded (zero-width) grid slots are
   exact no-ops, so the whole program is fixed-shape and jit/vmap-clean.
3. **Checkpointing** — the step scan is chunked into segments with
   ``jax.checkpoint``: the backward pass stores only segment-boundary
   states and rematerializes in-segment stages, bounding live memory to
   O(n_segments + segment_len) states.

Cost of a gradient: one adaptive solve + one fixed-grid solve + one
backward sweep — independent of the number of parameters.  That is the
whole point: ranking every reaction of a large mechanism against one
ignition-delay QoI is one backward pass, where forward sensitivities
would pay P tangent rows (docs/sensitivity.md).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..solver import bdf
from ..solver.linalg import make_solve_m
from ..solver.sdirk import _A, _B, _C, _GAMMA
from . import params as P


def _resolve_linsolve(linsolve):
    if linsolve == "auto":
        return "lu" if jax.default_backend() == "cpu" else "inv32"
    return linsolve


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _implicit_stage(fns, base, t_s, hg, theta, cfg):
    """Solve the SDIRK stage equation z = base + hg * f(t_s, z) for z.

    ``fns = (f, jacf, linsolve)`` is static; forward runs modified Newton
    (iteration matrix factored once at the stage base), backward applies
    the implicit function theorem — see module docstring."""
    z, _ = _stage_newton(fns, base, t_s, hg, theta, cfg)
    return z


def _stage_newton(fns, base, t_s, hg, theta, cfg, max_iter=12):
    f, jacf, linsolve = fns
    M = jnp.eye(base.shape[0], dtype=base.dtype) - hg * jacf(
        t_s, base, theta, cfg)
    solve_m = make_solve_m(M, linsolve, base.dtype)
    # displacement-based convergence on the state scale; tight because the
    # backward pass assumes the stage equation holds at roundoff-ish level
    scale = 1e-10 + 1e-8 * jnp.abs(base)

    def cond(s):
        _, it, done = s
        return (~done) & (it < max_iter)

    def body(s):
        z, it, _ = s
        g = z - base - hg * f(t_s, z, theta, cfg)
        dz = solve_m(-g)
        z2 = z + dz
        dn = jnp.sqrt(jnp.mean(jnp.square(dz / scale)))
        return z2, it + 1, (dn < 1e-3) | ~jnp.isfinite(dn)

    z, it, _ = lax.while_loop(
        cond, body, (base, jnp.asarray(0, dtype=jnp.int32),
                     jnp.asarray(False)))
    return z, it


def _stage_fwd(fns, base, t_s, hg, theta, cfg):
    z = _implicit_stage(fns, base, t_s, hg, theta, cfg)
    return z, (z, t_s, hg, theta, cfg)


def _stage_bwd(fns, res, zbar):
    f, jacf, linsolve = fns
    z, t_s, hg, theta, cfg = res
    # IFT at the converged stage: (I - hg J) dz = dbase + hg f_theta dtheta
    #   => base_bar = M^-T zbar;  theta_bar = hg f_theta^T (M^-T zbar)
    J = jacf(t_s, z, theta, cfg)
    MT = (jnp.eye(z.shape[0], dtype=z.dtype) - hg * J).T
    lam = make_solve_m(MT, linsolve, z.dtype)(zbar)
    _, fvjp = jax.vjp(lambda th, cf: f(t_s, z, th, cf), theta, cfg)
    theta_bar, cfg_bar = fvjp(hg * lam)
    # the grid (t_s, hg) is pinned by the grid-pass and carries no
    # gradient by design (module docstring)
    return (lam, jnp.zeros_like(t_s), jnp.zeros_like(hg), theta_bar,
            cfg_bar)


_implicit_stage.defvjp(_stage_fwd, _stage_bwd)


def _sdirk_step(fns, y, t_prev, t_next, theta, cfg):
    """One fixed-step SDIRK4 step from t_prev to t_next (no-op when the
    slot is padding, t_next <= t_prev)."""
    h = t_next - t_prev
    live = h > 0
    h_eff = jnp.where(live, h, 0.0)
    h_safe = jnp.where(live, h, 1.0)
    ks = []
    for i, a_row in enumerate(_A):
        base = y
        for j in range(i):
            base = base + h_eff * a_row[j] * ks[j]
        t_s = t_prev + _C[i] * h_eff
        z = _implicit_stage(fns, base, t_s, h_eff * _GAMMA, theta, cfg)
        # k = f(t_s, z) at convergence, recovered without a second RHS
        # eval; exactly 0 on padded slots (z == base there)
        ks.append((z - base) / (h_safe * _GAMMA))
    return y + h_eff * sum(b * k for b, k in zip(_B, ks))


def _fixed_grid_solve(fns, y0, t_prev, t_next, theta, cfg, segments):
    """Scan the fixed grid in ``segments`` checkpointed chunks; returns
    (ys (N, n) states at the knots, y_final)."""
    N = t_prev.shape[0]
    if N % segments:
        raise ValueError(f"grid size {N} not divisible by "
                         f"segments={segments}")
    tp = t_prev.reshape(segments, -1)
    tn = t_next.reshape(segments, -1)

    @jax.checkpoint
    def segment(y, seg):
        tps, tns = seg

        def step(yc, knots):
            y2 = _sdirk_step(fns, yc, knots[0], knots[1], theta, cfg)
            return y2, y2

        return lax.scan(step, y, (tps, tns))

    y_final, ys = lax.scan(segment, y0, (tp, tn))
    return ys.reshape(N, -1), y_final


def final_species_qoi(index):
    """QoI builder: final-state component ``y(t1)[index]`` (a species mass
    density, or a coverage for indices past n_gas)."""

    def qoi(tk, ys, y_final):
        return y_final[index]

    return qoi


def ignition_delay_qoi(marker, frac=0.5):
    """QoI builder: ignition delay as the interpolated first crossing of
    the marker species below ``frac`` x its first-grid-point value (the
    fuel-consumption marker of ``parallel.ignition_observer``).  The
    crossing machinery lives in ``energy/ignition.py`` (the ONE
    grid-crossing rule, shared with the temperature-threshold QoI
    ``energy.temperature_ignition_qoi``): the crossing *index* is
    piecewise-constant in theta and stop-gradiented — gradients flow
    through the bracketing values — and a never-crossed series returns
    NaN (a silent last-knot tau would carry a silently-zero gradient)."""
    from ..energy.ignition import grid_crossing

    def qoi(tk, ys, y_final):
        m = ys[:, marker]
        return grid_crossing(tk, m, frac * m[0])

    return qoi


def solve_adjoint(rhs_theta, qoi_fn, y0, t0, t1, theta, cfg, *,
                  jac_theta=None, rtol=1e-6, atol=1e-10, grid_size=256,
                  segments=8, grid_refine=2, max_steps=100_000,
                  jac_window=1, linsolve="auto", dt0=None, stats=False,
                  recorder=None):
    """Gradient of a scalar QoI with respect to theta, adjoint-style.

    ``rhs_theta(t, y, theta, cfg)`` / optional ``jac_theta(t, y, theta,
    cfg)`` are the theta-parameterized RHS/Jacobian
    (``params.make_rhs_theta``); ``qoi_fn(tk, ys, y_final) -> scalar``
    consumes the knot times, the (grid_size, n) knot states and the final
    state (builders: :func:`final_species_qoi`,
    :func:`ignition_delay_qoi`).

    Returns ``(qoi, grad, aux)``: ``grad`` is a theta-shaped pytree, and
    ``aux`` carries the grid-pass SolveResult fields a caller should
    check — ``status`` and ``truncated`` (True when the adaptive pass
    accepted more steps than ``grid_size``; enlarge ``grid_size``, the
    re-solve grid silently loses resolution otherwise).

    ``grid_refine=r`` subdivides every adaptive step into r equal
    SDIRK4 substeps in the re-solve (local error / r^5 at ~r x stage
    cost): the pinned grid was sized for the BDF pass's error, not
    SDIRK4's, and one refinement level keeps the re-solve's
    discretization error comfortably below the grid-pass tolerance.

    Pure lax control flow end to end: jit it, vmap it over lanes, shard
    the vmapped batch — no host callbacks anywhere.

    Telemetry: ``stats=True`` turns on the grid-pinning pass's device
    counter block (returned in ``aux["stats"]``); ``recorder`` (an
    ``obs.Recorder``) gets blocking ``adjoint_pin`` / ``adjoint_grad``
    spans around the two passes — pass one only from eager callers (a
    span inside a jitted wrapper would time tracing, not solving).
    """
    from ..obs.recorder import span_or_null

    linsolve = _resolve_linsolve(linsolve)
    theta0 = jax.tree.map(lax.stop_gradient, theta)

    def rhs0(t, y, cfg):
        return rhs_theta(t, y, theta0, cfg)

    jac0 = None
    if jac_theta is not None:
        def jac0(t, y, cfg):
            return jac_theta(t, y, theta0, cfg)

    with span_or_null(recorder, "adjoint_pin", grid_size=int(grid_size)):
        prim = bdf.solve(rhs0, jnp.asarray(y0), t0, t1, cfg, rtol=rtol,
                         atol=atol, max_steps=max_steps, n_save=grid_size,
                         jac=jac0, jac_window=jac_window, linsolve=linsolve,
                         dt0=dt0, stats=stats)
        if recorder is not None:
            jax.block_until_ready(prim.y)
    t1 = jnp.asarray(t1, dtype=prim.ts.dtype)
    tk = jnp.minimum(lax.stop_gradient(prim.ts), t1)  # inf pads -> t1
    t_prev = jnp.concatenate(
        [jnp.reshape(jnp.asarray(t0, dtype=tk.dtype), (1,)), tk[:-1]])
    t_next = tk
    if grid_refine > 1:
        # equal subdivision of every slot; padded (zero-width) slots
        # subdivide into zero-width slots — still exact no-ops
        r = int(grid_refine)
        w = (jnp.arange(r, dtype=tk.dtype) / r)[None, :]
        h = (t_next - t_prev)[:, None]
        starts = t_prev[:, None] + h * w                       # (N, r)
        ends = jnp.concatenate([starts[:, 1:], t_next[:, None]], axis=1)
        t_prev, t_next = starts.reshape(-1), ends.reshape(-1)

    if jac_theta is not None:
        jacf = jac_theta
    else:
        def jacf(t, z, th, cf):
            return jax.jacfwd(lambda zz: rhs_theta(t, zz, th, cf))(z)

    fns = (rhs_theta, jacf, linsolve)

    def qoi_of(theta_):
        ys, y_final = _fixed_grid_solve(fns, jnp.asarray(y0), t_prev,
                                        t_next, theta_, cfg, segments)
        return qoi_fn(t_next, ys, y_final)

    with span_or_null(recorder, "adjoint_grad", segments=int(segments)):
        qoi, grad = jax.value_and_grad(qoi_of)(theta)
        if recorder is not None:
            jax.block_until_ready(qoi)
    aux = {"status": prim.status, "t": prim.t, "y": prim.y,
           "n_accepted": prim.n_accepted, "n_rejected": prim.n_rejected,
           "truncated": prim.n_accepted > grid_size, "ts": tk,
           "stats": prim.stats}
    return qoi, grad, aux


# --------------------------------------------------------------------------
# brlint tier-C program contract (analysis/contracts.py): the adjoint
# fixed-grid gradient program (IFT custom_vjp stages + checkpointed
# segments) — same purity contract; tiny grid, trace cost only.
# --------------------------------------------------------------------------
from ..analysis.contracts import Pure, program_contract  # noqa: E402


@program_contract(
    "sens-adjoint-grad",
    doc="adjoint fixed-grid gradient program: pure")
def _contract_sens_adjoint(h):
    _spec, theta, rhs_theta = h.sens_fixture()

    def run(y0_):
        _, grad, _ = solve_adjoint(
            rhs_theta, final_species_qoi(0), y0_, 0.0, 1e-7, theta,
            h.cfg, rtol=1e-6, atol=1e-10, grid_size=8, segments=2,
            max_steps=8)
        return grad["log_A"]

    yield Pure("sens-adjoint-grad", h.jaxpr(run, h.y0))
