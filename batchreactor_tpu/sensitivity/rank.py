"""Normalized sensitivity coefficients and top-k reaction ranking.

The production workload this subsystem exists for: given dQoI/dtheta
(forward tangents chained into a scalar, or an adjoint gradient), report
the dimensionless logarithmic coefficients

    s_i = d ln(QoI) / d ln(A_i)

and rank reactions by |s_i|.  Because the mechanism bundles store
pre-exponentials in the ln domain (``log_A`` IS ln A — models/gas.py),
a gradient with respect to ``theta["log_A"]`` is already d/d ln A; the
only normalization left is dividing by the QoI itself.
"""

import numpy as np


def normalized_sensitivities(qoi, dqoi_dlogA):
    """s = (1/qoi) * dqoi/dlnA — d ln(QoI)/d ln(A), elementwise over the
    selected reactions.  ``qoi`` scalar (or (B,) per-lane), ``dqoi_dlogA``
    (K,) (or (B, K)); shapes broadcast."""
    qoi = np.asarray(qoi)
    g = np.asarray(dqoi_dlogA)
    return g / qoi[..., None] if qoi.ndim else g / qoi


def top_k(coeffs, equations, k=10):
    """Rank reactions by |normalized coefficient|, descending.

    ``coeffs`` (K,) aligned with ``equations`` (K,); returns a list of
    ``(rank, reaction_index, equation, coefficient)`` tuples of length
    ``min(k, K)``.  For a (B, K) sweep, aggregate first (e.g.
    ``np.abs(coeffs).mean(axis=0)`` — then pass per-lane values back here
    for the per-condition view).
    """
    coeffs = np.asarray(coeffs)
    if coeffs.ndim != 1:
        raise ValueError(f"top_k wants a (K,) vector; got {coeffs.shape} "
                         f"(aggregate sweep axes first)")
    if len(equations) != coeffs.shape[0]:
        raise ValueError(f"{coeffs.shape[0]} coefficients vs "
                         f"{len(equations)} equations")
    order = np.argsort(-np.abs(coeffs), kind="stable")[:max(int(k), 0)]
    return [(r + 1, int(i), equations[int(i)], float(coeffs[int(i)]))
            for r, i in enumerate(order)]


def format_ranking(ranking, qoi_name="QoI"):
    """Render :func:`top_k` output as an aligned text table (the
    scripts/sens_rank.py CLI surface)."""
    if not ranking:
        return "(no reactions selected)"
    w = max(len(eq) for _, _, eq, _ in ranking)
    head = (f"{'rank':>4}  {'rxn':>4}  {'equation':<{w}}  "
            f"dln({qoi_name})/dlnA")
    lines = [head, "-" * len(head)]
    for r, i, eq, c in ranking:
        lines.append(f"{r:>4}  {i:>4}  {eq:<{w}}  {c:+.6e}")
    return "\n".join(lines)
