"""Named, differentiable mechanism-parameter pytrees (theta).

The mechanism bundles (models/gas.GasMechanism, models/surface.
SurfaceMechanism) are frozen pytrees of device tensors; the kinetics
kernels consume them as traced operands.  That makes every rate parameter
differentiable *in principle* — what is missing is a named, selectable
slice of them to differentiate *against*.  This module provides it:

  spec  = select(gm, fields=("log_A",), reactions="*O2*")   # what
  theta = extract(gm, spec)                                  # current values
  gm2   = apply(gm, theta, spec)                             # splice back

``theta`` is a plain dict pytree ``{field: (K,) array}`` over the K
selected reactions — pass it through jit/grad/vmap freely; ``apply`` is
pure and traces cleanly, so ``rhs(t, y, apply(gm, theta, spec), ...)``
is differentiable end-to-end in theta.

Note the ln-domain payoff: ``log_A`` *is* ln A (models/gas.py stores
pre-exponentials as natural logs for TPU range reasons), so a gradient
with respect to ``theta["log_A"]`` is directly the logarithmic
sensitivity d/d ln A — the normalized-coefficient convention rank.py
reports — with no chain-rule factor.
"""

import dataclasses
import fnmatch
import functools

import jax.numpy as jnp
import numpy as np

# differentiable per-reaction fields by mechanism kind; everything else in
# the bundles is structure (stoichiometry, masks) or parse-time metadata
_GAS_FIELDS = ("log_A", "beta", "Ea")
_SURF_FIELDS = ("log_A", "beta", "Ea", "stick_s0")


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """Static description of a theta slice: which mechanism kind, which
    per-reaction fields, which reaction rows.  Hashable (tuples only), so
    it can ride static argnums / lru_cache keys next to the mechanism."""

    kind: str            # "gas" | "surface"
    fields: tuple        # subset of the kind's differentiable fields
    rxn_idx: tuple       # selected reaction row indices (ints, sorted)
    equations: tuple     # the selected reactions' equation strings

    @property
    def n_reactions(self):
        return len(self.rxn_idx)

    @property
    def n_params(self):
        return len(self.fields) * len(self.rxn_idx)


def _kind_of(mech):
    # duck-typed: GasMechanism has falloff tables, SurfaceMechanism has
    # sticking columns — isinstance would force model imports here
    if hasattr(mech, "has_falloff"):
        return "gas"
    if hasattr(mech, "stick_s0"):
        return "surface"
    raise TypeError(f"not a mechanism bundle: {type(mech).__name__}")


def select(mech, fields=("log_A",), reactions=None):
    """Build a :class:`ParamSpec` for a mechanism.

    ``fields``: per-reaction parameter arrays to expose (gas: log_A, beta,
    Ea; surface: log_A, beta, Ea, stick_s0).  ``reactions`` selects rows:
    ``None`` = all, a sequence of ints = explicit indices, or a glob
    string matched case-insensitively against the reaction equations
    (e.g. ``"*O2*"`` for every reaction touching O2).
    """
    kind = _kind_of(mech)
    allowed = _GAS_FIELDS if kind == "gas" else _SURF_FIELDS
    fields = tuple(fields)
    unknown = [f for f in fields if f not in allowed]
    if unknown:
        raise ValueError(
            f"non-differentiable or unknown {kind} field(s) {unknown}; "
            f"choose from {allowed}")
    if not fields:
        raise ValueError("select needs at least one field")
    eqs = tuple(mech.equations)
    n = len(eqs)
    if reactions is None:
        idx = tuple(range(n))
    elif isinstance(reactions, str):
        pat = reactions.upper()
        idx = tuple(i for i, e in enumerate(eqs)
                    if fnmatch.fnmatch(e.upper(), pat))
        if not idx:
            raise ValueError(
                f"reaction glob {reactions!r} matches nothing in "
                f"{n} equations (e.g. {eqs[:3]}...)")
    else:
        idx = tuple(sorted({int(i) for i in reactions}))
        bad = [i for i in idx if not 0 <= i < n]
        if bad:
            raise IndexError(f"reaction indices {bad} out of range 0..{n-1}")
        if not idx:
            raise ValueError("empty reaction index selection")
    return ParamSpec(kind=kind, fields=fields, rxn_idx=idx,
                     equations=tuple(eqs[i] for i in idx))


@functools.lru_cache(maxsize=256)
def _idx_device(rxn_idx):
    """ONE jnp index array per selection, built eagerly (outside any
    trace) and reused by every :func:`apply` call.  A fresh
    ``np.asarray`` per call would be re-staged as an in-loop device_put
    each time ``apply`` is traced inside a solver step program (brlint
    tier B catches exactly this); a memoized concrete jnp array is
    hoisted into the program constants instead."""
    return jnp.asarray(np.asarray(rxn_idx, dtype=np.int32))


def extract(mech, spec):
    """Current parameter values as the theta pytree ``{field: (K,)}``."""
    if _kind_of(mech) != spec.kind:
        raise TypeError(f"spec is for a {spec.kind} mechanism, got "
                        f"{_kind_of(mech)}")
    idx = _idx_device(spec.rxn_idx)
    return {f: jnp.asarray(getattr(mech, f))[idx] for f in spec.fields}


def apply(mech, theta, spec):
    """Splice theta back into the mechanism: a new bundle whose selected
    rows carry theta's (possibly traced) values.  Pure — the input bundle
    is untouched, and tracing through this function is what makes the
    kinetics kernels differentiable in theta."""
    if set(theta) != set(spec.fields):
        raise ValueError(f"theta keys {sorted(theta)} != spec fields "
                         f"{sorted(spec.fields)}")
    idx = _idx_device(spec.rxn_idx)
    updates = {}
    for f in spec.fields:
        vals = jnp.asarray(theta[f])
        if vals.shape != (len(spec.rxn_idx),):
            raise ValueError(
                f"theta[{f!r}] must have shape ({len(spec.rxn_idx)},), "
                f"got {vals.shape}")
        updates[f] = jnp.asarray(getattr(mech, f)).at[idx].set(vals)
    return dataclasses.replace(mech, **updates)


def names(spec):
    """Human-readable labels, one per theta scalar, in ``ravel`` order of
    the dict pytree (sorted field keys, then reaction order) — the label
    axis of a flattened sensitivity vector."""
    return tuple(f"{f}[{eq}]" for f in sorted(spec.fields)
                 for eq in spec.equations)


def flatten(theta):
    """theta dict -> (flat (P,) array, unflatten) in the :func:`names`
    order (sorted keys).  A hand-rolled ravel keeps the order contract
    explicit and independent of pytree registration details."""
    keys = sorted(theta)
    sizes = [jnp.shape(theta[k])[0] for k in keys]
    flat = jnp.concatenate([jnp.asarray(theta[k]) for k in keys])

    def unflatten(vec):
        out, off = {}, 0
        for k, s in zip(keys, sizes):
            out[k] = vec[off:off + s]
            off += s
        return out

    return flat, unflatten


def make_rhs_theta(mech, spec, build_rhs):
    """Close a theta-parameterized RHS over a mechanism and a builder:
    ``rhs_theta(t, y, theta, cfg)`` re-splices theta each trace and calls
    ``build_rhs(mech_with_theta)(t, y, cfg)``.  ``build_rhs`` is e.g.
    ``lambda m: ops.rhs.make_gas_rhs(m, thermo)``."""

    def rhs_theta(t, y, theta, cfg):
        return build_rhs(apply(mech, theta, spec))(t, y, cfg)

    return rhs_theta
