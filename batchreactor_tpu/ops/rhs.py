"""Batch-reactor ODE right-hand side as a pure, jit/vmap-able JAX function.

Functional re-design of the reference's mutating ``residual!``
(/root/reference/src/BatchReactor.jl:312-376).  State vector layout matches the
reference (:224-232): per-species mass density rho_k = rho * Y_k [kg/m^3] for
the n_gas species, optionally followed by n_surf surface coverages theta_k.

Physics (docs at /root/reference/docs/src/index.md:26-38):
  d(rho_k)/dt = sdot_k M_k Asv + wdot_k M_k          (gas species)
  d(theta_k)/dt = sdot_k sigma_k / Gamma             (surface coverages)
  rho = sum rho_k;  p = rho R T / Wbar  (recomputed algebraically every call)
  isothermal, constant volume.

Reference quirk (SURVEY.md): at :345 the reference multiplies the ENTIRE
surface source vector (gas part and coverage part) by Asv, so coverage
dynamics are scaled by Asv relative to the textbook equation.  We reproduce
this behaviour behind ``asv_quirk`` (default True for parity).

Mechanism-shape padding (models/padding.py): these RHS/Jacobian builders
accept PADDED mechanism/thermo bundles unchanged — the kinetics kernels
are inert on the dead tail by construction (zero ``nu`` rows/columns
zero every dead contribution exactly; ``ln A = ln 0`` pad rows never
reach the state through all-zero ``dnu``; zero ``eff`` pad columns keep
the Jacobian's dead columns zero), so the padded gas RHS is the live RHS
plus exact-zero tail entries, bit-for-bit.  The identity-padding
byte-identity and the padded purity are pinned by the tier-C
``mech-padding`` contract next to this module's own.
"""

import os

import jax.numpy as jnp

from ..utils.composition import mass_to_mole, pressure
from . import gas_kinetics, surface_kinetics

# BR_JAC_BARRIER is read ONCE, at module import (ADVICE r5 / brlint
# env-read-in-trace): the fence decision is baked into every jit trace and
# the compiled-executable caches key on call arguments, not env vars, so a
# per-closure-build re-read would let a post-import toggle silently serve
# stale variants from cache.  In-process callers who need per-closure
# control pass ``fence_blocks=`` explicitly (scripts/coupled_jac_bisect.py).
_JAC_BARRIER_ENV = os.environ.get("BR_JAC_BARRIER") == "1"


def make_gas_rhs(gm, thermo, kc_compat=False):
    """Pure RHS for gas-only chemistry: rhs(t, y, cfg) with y = rho_k (S,).

    cfg is a dict pytree of per-lane parameters: {'T': K}.  Returns dy (S,).
    ``kc_compat`` selects the reference's equilibrium-constant quirk (see
    ops/gas_kinetics.equilibrium_constants).
    """

    def rhs(t, y, cfg):
        T = cfg["T"]
        # conc_k = x_k p/(RT) with p = rho R T/Wbar reduces exactly to
        # rho_k / W_k — the reference's mole-frac/pressure round-trip
        # (/root/reference/src/BatchReactor.jl:349-353) is algebraic identity.
        conc = y / thermo.molwt  # mol/m^3
        wdot = gas_kinetics.production_rates(T, conc, gm, thermo, kc_compat)
        return wdot * thermo.molwt

    return rhs


def make_gas_jac(gm, thermo, kc_compat=False):
    """Analytic Jacobian companion to :func:`make_gas_rhs`.

    ``jac(t, y, cfg) -> (S, S)`` with J_ab = d(rhs_a)/d(y_b).  Since
    conc = y/molwt and rhs = wdot*molwt, J = M_a (dwdot_a/dconc_b) / M_b.
    Exact (matches jax.jacfwd to roundoff) at ~1/13th the cost on GRI —
    this matrix is rebuilt every implicit step attempt (solver/sdirk.py).
    """
    molwt = thermo.molwt

    def jac(t, y, cfg):
        conc = y / molwt
        _, dwdot = gas_kinetics.production_rates_and_jac(
            cfg["T"], conc, gm, thermo, kc_compat)
        return dwdot * (molwt[:, None] / molwt[None, :])

    return jac


def make_surface_rhs(sm, thermo, gm=None, asv_quirk=True, kc_compat=False):
    """Pure RHS for surface (and optionally coupled gas) chemistry.

    y = [rho_k (n_gas), theta_k (n_surf)]; cfg = {'T': K, 'Asv': 1/m}.
    ``sm`` is a SurfaceMechanism; ``gm`` adds gas-phase chemistry on top
    (the reference's gas+surf mode, /root/reference/src/BatchReactor.jl:368-370).

    The reference's mole-frac/pressure round-trip (:334-353) is an
    algebraic identity in this state vector — both kinetics kernels consume
    concentrations, and x_k p/(RT) reduces exactly to rho_k/M_k — so no
    lane-local reduction (rho sum, x normalization, p) ever reaches the
    compiled program: the coupled RHS is the gas RHS plus the surface
    kernel plus a concat, the structure the TPU backend compiles
    (COMPILE_PROBE.json s1; the round-trip composition was a prime suspect
    in the round-4 coupled compile-wall bisect).
    """
    ng = len(thermo.species) if gm is None else gm.n_species

    def rhs(t, y, cfg):
        T, Asv = cfg["T"], cfg["Asv"]
        rho_k = y[:ng]
        theta = y[ng:]
        c_gas_cgs = rho_k / (thermo.molwt * 1e6)  # mol/cm^3
        sdot_gas, sdot_surf = surface_kinetics.production_rates_c(
            T, c_gas_cgs, theta, sm
        )
        sdot_gas = sdot_gas * Asv
        if asv_quirk:
            sdot_surf = sdot_surf * Asv  # reference :345 scales coverages too
        dy_gas = sdot_gas * thermo.molwt
        if gm is not None:
            conc = rho_k / thermo.molwt  # mol/m^3
            wdot = gas_kinetics.production_rates(T, conc, gm, thermo, kc_compat)
            dy_gas = dy_gas + wdot * thermo.molwt
        # Gamma stored in mol/cm^2 like the reference's site density
        # (/root/reference/test/lib/ch4ni.xml:6); x1e4 -> mol/m^2 (:367).
        dtheta = sdot_surf * sm.site_coordination / (sm.site_density * 1e4)
        return jnp.concatenate([dy_gas, dtheta])

    return rhs


def make_surface_jac(sm, thermo, gm=None, asv_quirk=True, kc_compat=False,
                     return_blocks=False, fence_blocks=None):
    """Analytic Jacobian companion to :func:`make_surface_rhs`.

    ``jac(t, y, cfg) -> (S, S)`` over the full state y = [rho_k, theta_k].
    Exploits the algebraic identity the RHS is built on: the mole-frac /
    pressure round-trip reduces to c_gas_k = rho_k / M_k (SI), so the cgs
    gas concentrations the surface kernel consumes are rho_k/M_k * 1e-6 and
    the chain rule is a diagonal scale — no d(mole_frac)/d(rho) matrix.
    Assembled blocks (ng gas + ns coverages):

      J_gg = Asv M_a dsdot_gas_a/dc_gas_b * 1e-6/M_b  [+ gas-phase block]
      J_gt = Asv M_a dsdot_gas_a/dtheta_b
      J_tg = quirk sigma_a/(Gamma 1e4) dsdot_surf_a/dc_gas_b * 1e-6/M_b
      J_tt = quirk sigma_a/(Gamma 1e4) dsdot_surf_a/dtheta_b

    with quirk = Asv when ``asv_quirk`` (reference :345 scales the coverage
    source by Asv too), else 1.  Matches ``jax.jacfwd`` of the RHS to
    roundoff (tests/test_surface.py) at a fraction of its n-forward-pass
    cost — this matrix is the Newton iteration matrix of every implicit
    step on the gas+surf flagship workload.

    ``return_blocks=True`` returns the four blocks ``(J_gg, J_gt, J_tg,
    J_tt)`` without ever building the concatenated matrix — the compile-
    wall bisect needs a program that truly lacks the ``jnp.block`` op
    (slicing the blocks back out of the full matrix leaves the concat in
    the traced program and only differs if XLA's slice-of-concatenate
    simplification fires).  ``fence_blocks`` wraps the four blocks in
    ``jax.lax.optimization_barrier`` before assembly so XLA's fusion
    search cannot chase producers across the assembly boundary —
    numerically the identity.  ``None`` consults the ``BR_JAC_BARRIER``
    env var ONCE per process, at module import (the decision is baked
    into each jit trace and executable caches key on call arguments, so
    a post-import env read would be silently stale anyway — ADVICE r5);
    pass ``fence_blocks`` explicitly for per-closure control.
    """
    ng = len(thermo.species) if gm is None else gm.n_species
    molwt = thermo.molwt
    if fence_blocks is None:
        fence_blocks = _JAC_BARRIER_ENV

    def jac(t, y, cfg):
        T, Asv = cfg["T"], cfg["Asv"]
        rho_k = y[:ng]
        theta = y[ng:]
        c_gas_cgs = rho_k / (molwt * 1e6)  # mol/cm^3 (same identity as rhs)
        _, _, (dg_dcg, dg_dth, ds_dcg, ds_dth) = (
            surface_kinetics.production_rates_and_jac_c(
                T, c_gas_cgs, theta, sm))
        dcg = 1e-6 / molwt                      # d c_gas_cgs_b / d rho_b
        quirk = Asv if asv_quirk else 1.0
        coef = quirk * sm.site_coordination / (sm.site_density * 1e4)
        J_gg = Asv * molwt[:, None] * dg_dcg * dcg[None, :]
        J_gt = Asv * molwt[:, None] * dg_dth
        J_tg = coef[:, None] * ds_dcg * dcg[None, :]
        J_tt = coef[:, None] * ds_dth
        if gm is not None:
            conc = rho_k / molwt
            _, dwdot = gas_kinetics.production_rates_and_jac(
                T, conc, gm, thermo, kc_compat)
            J_gg = J_gg + dwdot * (molwt[:, None] / molwt[None, :])
        if fence_blocks:
            import jax

            J_gg, J_gt, J_tg, J_tt = jax.lax.optimization_barrier(
                (J_gg, J_gt, J_tg, J_tt))
        if return_blocks:
            return J_gg, J_gt, J_tg, J_tt
        return jnp.block([[J_gg, J_gt], [J_tg, J_tt]])

    return jac


def make_udf_rhs(udf, molwt, species=None):
    """Pure RHS for a user-defined source function.

    ``udf(t, state_dict) -> source (S,) [mol/m^3/s]`` must be JAX-traceable;
    state_dict carries T, p, mole_frac, molwt, and species — the static
    tuple of species names, so a UDF author can map state-vector indices to
    names without out-of-band info (cf. UserDefinedState fields,
    /root/reference/src/BatchReactor.jl:199 and docs/src/index.md:68-76).
    """
    species = tuple(species) if species is not None else None

    def rhs(t, y, cfg):
        T = cfg["T"]
        rho = jnp.sum(y)
        mass_fracs = y / rho
        mole_fracs = mass_to_mole(mass_fracs, molwt)
        p = pressure(rho, mole_fracs, molwt, T)
        state = {"T": T, "p": p, "mole_frac": mole_fracs, "molwt": molwt,
                 "species": species}
        source = udf(t, state)
        return source * molwt

    return rhs


# --------------------------------------------------------------------------
# brlint tier-C program contract (analysis/contracts.py): the four
# chemistry modes and their analytic Jacobians are the innermost traced
# programs of every solve — pure (no callbacks, no in-loop staging) and
# f64-uniform (the dtype walk is skipped under the f32 rate-exponential
# formulation; the harness resolves that).
# --------------------------------------------------------------------------
from ..analysis.contracts import Budget, Pure, program_contract  # noqa: E402


@program_contract(
    "rhs-modes",
    doc="four chemistry modes + analytic jacobians: pure, f64-uniform",
    # first jaxpr-bearing obligation = the gas RHS (h2o2 fixture:
    # ~1.0e4 flops / ~16 KiB at the 2026-08 costmodel walk; 2.5x band
    # — the rate kernel is the throughput bound, a silent doubling is
    # exactly what tier D exists to catch)
    budget=Budget(flops_per_step=(4e3, 2.5e4), peak_bytes=64 * 1024,
                  doc="h2o2 gas RHS; 2.5x band vs the 2026-08 walk"))
def _contract_rhs_modes(h):
    for tag, rhs, jac, y0, cfg in h.modes:
        yield Pure(tag, h.jaxpr(rhs, 0.0, y0, cfg),
                   check_dtype=h.check_dtype)
        if jac is not None:
            yield Pure(tag.replace("-rhs", "-jac"),
                       h.jaxpr(jac, 0.0, y0, cfg),
                       check_dtype=h.check_dtype)
