"""Surface kinetics kernel — placeholder, implemented in the surface milestone."""


def production_rates(T, p, mole_fracs, theta, sm, thermo):  # pragma: no cover
    raise NotImplementedError("surface kinetics lands in a later milestone")
