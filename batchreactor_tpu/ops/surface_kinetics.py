"""Surface molar production rates as a pure jnp kernel.

Device-side rebuild of ``SurfaceReactions.calculate_molar_production_rates!``
(/root/reference/src/BatchReactor.jl:344).  Pure function of
(T, p, gas mole fractions, coverages); returns SI production rates
(mol/m^2/s) for gas species and surface species separately.  Rate-law
conventions are pinned against the committed golden trajectory — see the
models/surface.py module docstring.

Internally works in cgs (mol/cm^3 gas, mol/cm^2 surface) because the
mechanism's A values are cgs; the single x1e4 conversion happens at the end.
"""

import jax.numpy as jnp

from ..utils.constants import R
# the forward rates and the analytic Jacobian share ONE stoichiometric-
# product implementation (clamps included) so the 'Jacobian == derivative
# of the RHS' invariant cannot drift between two copies of the math
from .gas_kinetics import _stoich_prod, _stoich_prod_and_grad

_EXP_MAX = 690.0
# cgs gas constant for the sticking flux sqrt(R T / 2 pi M): erg/(mol K)
_R_CGS = R * 1e7
_PI = 3.141592653589793


def rate_constants(T, theta, sm, with_grad=False):
    """Effective rate constants (R,), cgs units.

    ``with_grad=True`` additionally returns dk/dtheta (R, Ss) — the single
    implementation both the forward rates and the analytic Jacobian use
    (same discipline as gas_kinetics._troe_F), so the 'Jacobian matches
    jacfwd to roundoff' invariant cannot drift.
    """
    # coverage-dependent activation energy: Ea_eff = Ea + eps @ theta
    # (applies to Arrhenius AND sticking rows — a <coverage> tag targeting a
    # stick id modifies the sticking probability's activation energy)
    Ea_eff = sm.Ea + sm.cov_eps @ theta
    log_arg = sm.beta * jnp.log(T) - Ea_eff / (R * T)
    k_arr = jnp.exp(jnp.clip(sm.log_A + log_arg, -_EXP_MAX, _EXP_MAX))
    # sticking: (s0/(1-s0/2) if MWC) sqrt(RT/2piM) [cm/s], theta enters the
    # rate directly (no Gamma^m) — golden-trajectory convention
    s_raw = sm.stick_s0 * jnp.exp(jnp.clip(log_arg, -_EXP_MAX, _EXP_MAX))
    denom = 1.0 - s_raw / 2.0
    s_eff = jnp.where(sm.mwc > 0, s_raw / denom, s_raw)
    # sqrt(T) * sqrt(const): the T-independent factor carries no batch dim
    # under vmap, so the per-lane cost is ONE scalar f64 sqrt instead of an
    # (R,)-row of them (f64 sqrt is emulated on TPU); <=2 ulp from the
    # fused form
    flux = jnp.sqrt(T) * jnp.sqrt(_R_CGS / (2.0 * _PI * sm.stick_molwt))
    k = jnp.where(sm.stick > 0, s_eff * flux, k_arr)
    if not with_grad:
        return k
    # d/dEa_eff: Arrhenius -k/(RT); stick s_raw' = -s_raw/(RT) through the
    # Motz-Wise chain d(s/(1-s/2))/ds = 1/denom^2
    dmwc_ds = jnp.where(sm.mwc > 0, 1.0 / (denom * denom), 1.0)
    dk_dEa = jnp.where(sm.stick > 0,
                       flux * dmwc_ds * (-s_raw / (R * T)),
                       -k_arr / (R * T))
    return k, dk_dEa[:, None] * sm.cov_eps


def reaction_rates_c(T, c_gas, theta, sm):
    """Rate of progress per reaction (R,), mol/cm^2/s, from cgs gas
    concentrations c_gas [mol/cm^3] directly."""
    c_surf = theta * sm.site_density / sm.site_coordination  # mol/cm^2
    k = rate_constants(T, theta, sm)
    gas_part = _stoich_prod(c_gas, sm.expo_gas, sm.int_expo)
    # stick rows use raw coverages; Arrhenius rows use surface concentrations
    surf_conc_part = _stoich_prod(c_surf, sm.expo_surf, sm.int_expo)
    surf_theta_part = _stoich_prod(theta, sm.expo_surf, sm.int_expo)
    surf_part = jnp.where(sm.stick > 0, surf_theta_part, surf_conc_part)
    return k * gas_part * surf_part


def reaction_rates(T, p, mole_fracs, theta, sm):
    """Rate of progress per reaction (R,), mol/cm^2/s."""
    return reaction_rates_c(T, mole_fracs * p / (R * T) * 1e-6, theta, sm)


def production_rates_c(T, c_gas, theta, sm):
    """(sdot_gas (Sg,), sdot_surf (Ss,)) in SI mol/m^2/s from cgs gas
    concentrations directly.

    The reactor hot loop (ops/rhs.make_surface_rhs) enters HERE: in the
    batch-reactor state the mole-fraction/pressure round-trip reduces
    algebraically to c_gas_k = rho_k / (M_k 1e6), so the lane-local
    reductions (rho sum, x normalization, p) the (T, p, x) form implies
    never reach the compiled program — the coupled RHS is then exactly the
    gas RHS plus this kernel plus a concat, the structure the TPU backend
    is proven to compile (COMPILE_PROBE.json s1; PERF.md round-5)."""
    q = reaction_rates_c(T, c_gas, theta, sm)        # mol/cm^2/s
    sdot_gas = (sm.nu_r_gas - sm.nu_f_gas).T @ q * 1e4
    sdot_surf = (sm.nu_r_surf - sm.nu_f_surf).T @ q * 1e4
    return sdot_gas, sdot_surf


def production_rates(T, p, mole_fracs, theta, sm):
    """(sdot_gas (Sg,), sdot_surf (Ss,)) in SI mol/m^2/s."""
    return production_rates_c(T, mole_fracs * p / (R * T) * 1e-6, theta, sm)


def production_rates_and_jac(T, p, mole_fracs, theta, sm):
    """Production rates plus their closed-form Jacobian blocks.

    Returns ``(sdot_gas, sdot_surf, (dgas_dcg, dgas_dth, dsurf_dcg,
    dsurf_dth))`` where the derivative blocks are of the *SI* production
    rates with respect to the *cgs* gas concentrations c_gas = x p/(RT) 1e-6
    [mol/cm^3] and the raw coverages theta.  The reactor-state chain rule
    (c_gas_k = rho_k / M_k * 1e-6 in the batch-reactor state) lives in
    ops/rhs.make_surface_jac.

    Rationale mirrors gas_kinetics.production_rates_and_jac: the implicit
    solver rebuilds this matrix every Newton step attempt, and
    ``jax.jacfwd`` through :func:`production_rates` costs one forward pass
    per state entry (66 for the gas+surf GRI+CH4/Ni flagship —
    /root/reference/src/BatchReactor.jl:344 is the reference's surface
    hot-loop call).  Derivative structure per reaction row j:

      q_j = k_j(theta) * G_j(c_gas) * S_j(theta)
      dk_j/dtheta_k = (dk_j/dEa_eff) cov_eps_jk — coverage-dependent
        activation energy, through the Arrhenius exp or the sticking
        probability (incl. the Motz-Wise chain d(s/(1-s/2))/ds = 1/(1-s/2)^2)
      dS_j/dtheta_k: stick rows use raw coverages; Arrhenius rows go through
        surface concentrations c_surf = theta Gamma/sigma.
    """
    return production_rates_and_jac_c(
        T, mole_fracs * p / (R * T) * 1e-6, theta, sm)


def production_rates_and_jac_c(T, c_gas, theta, sm):
    """:func:`production_rates_and_jac` from cgs gas concentrations
    directly — the reactor hot-loop entry (see
    :func:`production_rates_c` for why the (T, p, x) round-trip stays out
    of the compiled program)."""
    gamma_sig = sm.site_density / sm.site_coordination        # (Ss,)
    c_surf = theta * gamma_sig                                # mol/cm^2

    k, dk_dth = rate_constants(T, theta, sm, with_grad=True)  # (R,), (R, Ss)

    # --- stoichiometric products and gradients -----------------------------
    G, dG = _stoich_prod_and_grad(c_gas, sm.expo_gas, sm.int_expo)
    Sc, dSc = _stoich_prod_and_grad(c_surf, sm.expo_surf, sm.int_expo)
    St, dSt = _stoich_prod_and_grad(theta, sm.expo_surf, sm.int_expo)
    S_sel = jnp.where(sm.stick > 0, St, Sc)
    dS_dth = jnp.where(sm.stick[:, None] > 0, dSt,
                       dSc * gamma_sig[None, :])

    q = k * G * S_sel                                         # mol/cm^2/s
    dq_dcg = (k * S_sel)[:, None] * dG                        # (R, Sg)
    dq_dth = (G * S_sel)[:, None] * dk_dth + (k * G)[:, None] * dS_dth

    dnu_g = sm.nu_r_gas - sm.nu_f_gas                         # (R, Sg)
    dnu_s = sm.nu_r_surf - sm.nu_f_surf                       # (R, Ss)
    return (dnu_g.T @ q * 1e4, dnu_s.T @ q * 1e4,
            (dnu_g.T @ dq_dcg * 1e4, dnu_g.T @ dq_dth * 1e4,
             dnu_s.T @ dq_dcg * 1e4, dnu_s.T @ dq_dth * 1e4))
