"""Surface molar production rates as a pure jnp kernel.

Device-side rebuild of ``SurfaceReactions.calculate_molar_production_rates!``
(/root/reference/src/BatchReactor.jl:344).  Pure function of
(T, p, gas mole fractions, coverages); returns SI production rates
(mol/m^2/s) for gas species and surface species separately.  Rate-law
conventions are pinned against the committed golden trajectory — see the
models/surface.py module docstring.

Internally works in cgs (mol/cm^3 gas, mol/cm^2 surface) because the
mechanism's A values are cgs; the single x1e4 conversion happens at the end.
"""

import jax.numpy as jnp

from ..utils.constants import R

_EXP_MAX = 690.0
# cgs gas constant for the sticking flux sqrt(R T / 2 pi M): erg/(mol K)
_R_CGS = R * 1e7
_PI = 3.141592653589793


def _pow_prod(base, expo, int_expo):
    """prod_k base_k^expo_ik rows.  ``int_expo`` is static (decided at
    compile_mech time) so XLA materializes exactly one branch: the masked
    integer path for mechanisms whose exponents are all in {0,1,2,3}, or the
    log/exp general path for fractional/negative <order> overrides."""
    b = base[None, :]
    if int_expo:
        p = jnp.where(expo >= 1, b, 1.0)
        p = jnp.where(expo >= 2, p * b, p)
        p = jnp.where(expo >= 3, p * b, p)
        return jnp.prod(p, axis=1)
    safe = jnp.maximum(b, 1e-300)
    return jnp.exp(jnp.sum(expo * jnp.log(safe), axis=1))


def rate_constants(T, theta, sm):
    """Effective rate constants (R,), cgs units."""
    # coverage-dependent activation energy: Ea_eff = Ea + eps @ theta
    # (applies to Arrhenius AND sticking rows — a <coverage> tag targeting a
    # stick id modifies the sticking probability's activation energy)
    Ea_eff = sm.Ea + sm.cov_eps @ theta
    log_k = sm.log_A + sm.beta * jnp.log(T) - Ea_eff / (R * T)
    k_arr = jnp.exp(jnp.clip(log_k, -_EXP_MAX, _EXP_MAX))
    # sticking: (s0/(1-s0/2) if MWC) sqrt(RT/2piM) [cm/s], theta enters the
    # rate directly (no Gamma^m) — golden-trajectory convention
    s_eff = sm.stick_s0 * jnp.exp(
        jnp.clip(sm.beta * jnp.log(T) - Ea_eff / (R * T), -_EXP_MAX, _EXP_MAX)
    )
    s_eff = jnp.where(sm.mwc > 0, s_eff / (1.0 - s_eff / 2.0), s_eff)
    k_stick = s_eff * jnp.sqrt(_R_CGS * T / (2.0 * _PI * sm.stick_molwt))
    return jnp.where(sm.stick > 0, k_stick, k_arr)


def reaction_rates(T, p, mole_fracs, theta, sm):
    """Rate of progress per reaction (R,), mol/cm^2/s."""
    c_gas = mole_fracs * p / (R * T) * 1e-6           # mol/cm^3
    c_surf = theta * sm.site_density / sm.site_coordination  # mol/cm^2
    k = rate_constants(T, theta, sm)
    gas_part = _pow_prod(c_gas, sm.expo_gas, sm.int_expo)
    # stick rows use raw coverages; Arrhenius rows use surface concentrations
    surf_conc_part = _pow_prod(c_surf, sm.expo_surf, sm.int_expo)
    surf_theta_part = _pow_prod(theta, sm.expo_surf, sm.int_expo)
    surf_part = jnp.where(sm.stick > 0, surf_theta_part, surf_conc_part)
    return k * gas_part * surf_part


def production_rates(T, p, mole_fracs, theta, sm):
    """(sdot_gas (Sg,), sdot_surf (Ss,)) in SI mol/m^2/s."""
    q = reaction_rates(T, p, mole_fracs, theta, sm)  # mol/cm^2/s
    sdot_gas = (sm.nu_r_gas - sm.nu_f_gas).T @ q * 1e4
    sdot_surf = (sm.nu_r_surf - sm.nu_f_surf).T @ q * 1e4
    return sdot_gas, sdot_surf
