"""Gas-phase molar production rates as a pure jnp kernel.

Device-side rebuild of ``GasphaseReactions.calculate_molar_production_rates!``
(/root/reference/src/BatchReactor.jl:355).  The reference mutates state buffers
per call from inside CVODE; here ``production_rates(T, conc, gm, thermo)`` is a
pure function of scalar temperature and the (S,) concentration vector
[mol/m^3], returning (S,) molar production rates [mol/m^3/s].  It is
jit/vmap/jacfwd-safe: all clamps below exist to keep forward *and* tangent
values finite (Newton Jacobians are computed through this code).

Rate law (CHEMKIN-II semantics):
  kf_i = A_i T^beta_i exp(-Ea_i / RT)
  third body: rate *= cM_i = sum_k eff_ik c_k
  falloff:   kf = k_inf * Pr/(1+Pr) * F,  Pr = k0 cM / k_inf,
             F = 1 (Lindemann), TROE, or SRI blending
  reverse:   kr = kf / Kc, Kc = exp(-sum_k dnu_ik g_k/RT) * (p_atm/RT)^dnu_i
  wdot_k = sum_i dnu_ik (ratef_i - rater_i),  dnu = nu_r - nu_f
"""

import os

import jax.numpy as jnp

from ..utils.constants import P_ATM, R
from .thermo import gibbs_over_RT

_LOG10 = 2.302585092994046


def _exp(x):
    """exp for rate expressions.  The f32 formulation evaluates the
    transcendental in f32 (carriers stay f64): on TPU, f64 exp is
    double-double emulation (a long scalar chain per element) while f32 exp
    is native.  Relative error ~1e-6 on the rate CONSTANTS — far below both
    mechanism A-factor uncertainty and the 1e-6 rtol the error controller
    runs at; the RHS and the analytic Jacobian share this function, so
    Newton consistency holds.

    Arguments span the full +-690 clip window, which f32 exp cannot
    represent (overflow past ~88.7, flush below ~-87 — a naive cast turns
    kr = kf * exp(-lnKc) into 0 * inf = NaN for dissociation reactions at
    low T).  exp(x) = exp(x/8)^8 keeps the f32 argument within +-86.25 over
    the whole window; the three squarings happen in f64, where e^{+-690} is
    representable.

    Selection: BR_EXP32=1/0 pins it; unset, it defaults ON for accelerator
    backends and OFF on CPU (where the golden-parity tests pin f64-exact
    rate constants).  Resolved ONCE at first kernel trace and then frozen:
    compiled-executable caches (parallel/sweep.py lru_caches) key on solver
    arguments, not env vars, so a later re-read would let an in-process
    toggle silently serve the stale variant.  The backend is known by first
    trace time (jit requires one); scripts/perf_probe.py pins the env var
    in fresh subprocesses when isolating the lever.
    """
    if _exp32_enabled():
        e = jnp.exp((x * 0.125).astype(jnp.float32)).astype(jnp.float64)
        e2 = e * e
        e4 = e2 * e2
        return e4 * e4
    return jnp.exp(x)


_EXP32 = None


def _exp32_enabled():
    global _EXP32
    if _EXP32 is None:
        # justified suppression: this IS the documented once-per-process
        # freeze (_exp docstring) — the read happens at most once, is
        # cached in _EXP32 before the first kernel trace, and cannot be
        # hoisted to import because the unset-var default needs
        # jax.default_backend(), whose init at import would hang host-only
        # use on a wedged tunneled TPU (solver/bdf.py module comment)
        env = os.environ.get("BR_EXP32")  # brlint: disable=env-read-in-trace
        if env is not None:
            _EXP32 = env == "1"
        else:
            import jax

            _EXP32 = jax.default_backend() != "cpu"
    return _EXP32
# clamps: keep exponentials/logs finite under jacfwd without changing physics.
# 690 ~ ln(f64 max); physical rate constants in SI units never approach e^690,
# so the clip only engages on unreachable branches that `where` discards.
_EXP_MAX = 690.0
_TINY = 1e-300


def _stoich_prod(conc, nu, int_stoich):
    """prod_k c_k^nu_ik for each reaction row; fast path for integer nu<=3.

    Negative concentrations (transient Newton iterates) are handled exactly
    like CVODE sees them: integer powers of negative numbers, no NaNs.
    """
    c = conc[None, :]
    if int_stoich:
        p = jnp.where(nu >= 1, c, 1.0)
        p = jnp.where(nu >= 2, p * c, p)
        p = jnp.where(nu >= 3, p * c, p)
        return jnp.prod(p, axis=1)
    safe_c = jnp.where(conc > _TINY, conc, _TINY)[None, :]
    return jnp.exp(jnp.sum(nu * jnp.log(safe_c), axis=1))


def _arrhenius(T, log_A, beta, Ea):
    """k = exp(ln A + beta ln T - Ea/RT); parameters live in ln domain
    (GasMechanism docstring explains the TPU range rationale)."""
    logk = log_A + beta * jnp.log(T) - Ea / (R * T)
    return _exp(jnp.clip(logk, -_EXP_MAX, _EXP_MAX))


def _troe_F(T, Pr, troe, has_troe, with_grad=False):
    """TROE falloff blending factor; returns 1 where not TROE, finite always.

    ``with_grad=True`` also returns dF/dPr (0 where not TROE) — the single
    implementation both the forward rates and the analytic Jacobian use, so
    the 'Jacobian matches jacfwd to roundoff' invariant cannot drift.
    """
    a, T3, T1, T2 = troe[:, 0], troe[:, 1], troe[:, 2], troe[:, 3]
    Fcent = (1.0 - a) * _exp(-T / T3) + a * _exp(-T / T1) + _exp(-T2 / T)
    log_fc = jnp.log(jnp.maximum(Fcent, _TINY)) / _LOG10
    c = -0.4 - 0.67 * log_fc
    n = 0.75 - 1.27 * log_fc
    Pr_safe = jnp.maximum(Pr, _TINY)
    log_pr = jnp.log(Pr_safe) / _LOG10
    denom = n - 0.14 * (log_pr + c)
    f1 = (log_pr + c) / denom
    one_f1 = 1.0 + f1 * f1
    F_troe = _exp(_LOG10 * log_fc / one_f1)
    F = jnp.where(has_troe > 0, F_troe, 1.0)
    if not with_grad:
        return F
    # dF/dPr = F ln10 (dlogF/dlp) (dlp/dPr);  dlp/dPr = 1/(ln10 Pr)
    df1_dlp = n / (denom * denom)
    dlogF_dlp = -log_fc * 2.0 * f1 * df1_dlp / (one_f1 * one_f1)
    dF_dPr = jnp.where(has_troe > 0, F_troe * dlogF_dlp / Pr_safe, 0.0)
    return F, dF_dPr


def _sri_F(T, Pr, sri, has_sri, with_grad=False):
    """SRI falloff blending factor; returns 1 where not SRI, finite always.

    F = d T^e [a exp(-b/T) + exp(-T/c)]^X with X = 1/(1 + log10(Pr)^2)
    (CHEMKIN-II; 3-parameter form has d=1, e=0).  Shares the forward /
    gradient single-implementation rule with :func:`_troe_F`.
    """
    a, b, c = sri[:, 0], sri[:, 1], sri[:, 2]
    d, e = sri[:, 3], sri[:, 4]
    Pr_safe = jnp.maximum(Pr, _TINY)
    lp = jnp.log(Pr_safe) / _LOG10
    X = 1.0 / (1.0 + lp * lp)
    base = jnp.maximum(a * _exp(-b / T) + _exp(-T / c), _TINY)
    ln_base = jnp.log(base)
    F_sri = d * _exp(e * jnp.log(T)) * _exp(X * ln_base)
    F = jnp.where(has_sri > 0, F_sri, 1.0)
    if not with_grad:
        return F
    # dF/dPr = F ln(base) dX/dlp dlp/dPr;  dX/dlp = -2 lp X^2
    dF_dPr = jnp.where(
        has_sri > 0,
        F_sri * ln_base * (-2.0 * lp * X * X) / (_LOG10 * Pr_safe), 0.0)
    return F, dF_dPr


def _blend_F(T, Pr, gm, with_grad=False):
    """Falloff blending F (TROE, SRI, or Lindemann F=1) with optional
    dF/dPr.  TROE and SRI are mutually exclusive per reaction (parse-time
    check), so the product form composes the masked factors exactly."""
    if not with_grad:
        return (_troe_F(T, Pr, gm.troe, gm.has_troe)
                * _sri_F(T, Pr, gm.sri, gm.has_sri))
    Ft, dFt = _troe_F(T, Pr, gm.troe, gm.has_troe, with_grad=True)
    Fs, dFs = _sri_F(T, Pr, gm.sri, gm.has_sri, with_grad=True)
    return Ft * Fs, dFt * Fs + Ft * dFs


def _plog_interp(T, conc, gm):
    """PLOG rate interpolation: (ln k (R,), dlnk/dlnp slope (R,), Ctot).

    k(T, p): piecewise-linear in (ln p, ln k) between per-pressure
    Arrhenius fits, clamped to the table ends (Cantera semantics).  The
    reactor's pressure is algebraic, p = Ctot R T with Ctot = sum(max(c,0))
    — the same clamp the falloff collider uses for transient negative
    Newton iterates.  Rows are +inf/(ln 0) padded to the widest table; the
    interval search never lands on a pad (idx clamp + w clip), and a ragged
    row's beyond-table query degrades to the clamped end point exactly.
    """
    Ctot = jnp.maximum(jnp.sum(jnp.maximum(conc, 0.0)), _TINY)
    lnp = jnp.log(Ctot * R * T)
    lnk_pts = (gm.plog_logA + gm.plog_beta * jnp.log(T)
               - gm.plog_Ea / (R * T))                       # (R, P)
    grid = gm.plog_lnp                                        # (R, P)
    P = grid.shape[1]
    idx = jnp.clip(jnp.sum(grid <= lnp, axis=1) - 1, 0, max(P - 2, 0))
    lo = jnp.take_along_axis(grid, idx[:, None], axis=1)[:, 0]
    hi = jnp.take_along_axis(grid, (idx + 1)[:, None] if P > 1
                             else idx[:, None], axis=1)[:, 0]
    klo = jnp.take_along_axis(lnk_pts, idx[:, None], axis=1)[:, 0]
    khi = jnp.take_along_axis(lnk_pts, (idx + 1)[:, None] if P > 1
                              else idx[:, None], axis=1)[:, 0]
    span = hi - lo
    w_raw = jnp.where(jnp.isfinite(span) & (span > 0),
                      (lnp - lo) / jnp.where(span > 0, span, 1.0), 0.0)
    w = jnp.clip(w_raw, 0.0, 1.0)
    lnk = klo + w * (khi - klo)
    # slope is live only strictly inside the table (clamped regions are
    # pressure-independent — matches jacfwd through the clipped forward)
    inside = (w_raw > 0.0) & (w_raw < 1.0)
    slope = jnp.where(inside & jnp.isfinite(span) & (span > 0),
                      (khi - klo) / jnp.where(span > 0, span, 1.0), 0.0)
    return lnk, slope, Ctot


def _cheb_eval(T, conc, gm):
    """Chebyshev rate tables: (ln k (R,), d ln k / d log10 p (R,), Ctot).

    log10 k = sum_ij a_ij T_i(Ttil) T_j(Ptil) with Ttil the scaled inverse
    temperature and Ptil the scaled log10 pressure, both clamped to [-1, 1]
    (rates outside the declared window hold their boundary value, and the
    pressure derivative vanishes there — matching jacfwd through the
    clamp).  The polynomial degrees are static (table shapes), so the
    Chebyshev recurrence unrolls at trace time.
    """
    Ctot = jnp.maximum(jnp.sum(jnp.maximum(conc, 0.0)), _TINY)
    log10p = jnp.log(Ctot * R * T) / _LOG10
    iT_lo, iT_hi = gm.cheb_invT[:, 0], gm.cheb_invT[:, 1]
    p_lo, p_hi = gm.cheb_logP[:, 0], gm.cheb_logP[:, 1]
    Ttil = (2.0 / T - iT_lo - iT_hi) / (iT_hi - iT_lo)
    Ptil_raw = (2.0 * log10p - p_lo - p_hi) / (p_hi - p_lo)
    Ttil = jnp.clip(Ttil, -1.0, 1.0)
    inside_p = (Ptil_raw > -1.0) & (Ptil_raw < 1.0)
    Ptil = jnp.clip(Ptil_raw, -1.0, 1.0)
    NT, NP = gm.cheb_coef.shape[1], gm.cheb_coef.shape[2]

    def cheb_basis(x, n):
        out = [jnp.ones_like(x), x]
        for _ in range(2, n):
            out.append(2.0 * x * out[-1] - out[-2])
        return jnp.stack(out[:n], axis=-1)               # (R, n)

    Tb = cheb_basis(Ttil, max(NT, 2))[:, :NT]            # (R, NT)
    Pb = cheb_basis(Ptil, max(NP, 2))[:, :NP]            # (R, NP)
    log10k = jnp.einsum("rij,ri,rj->r", gm.cheb_coef, Tb, Pb)
    lnk = log10k * _LOG10 + gm.cheb_si_ln
    # dT_j/dx = j U_{j-1}(x) via the derivative recurrence; unrolled too
    dPb = [jnp.zeros_like(Ptil), jnp.ones_like(Ptil)]
    U_prev, U_cur = jnp.ones_like(Ptil), 2.0 * Ptil      # U0, U1
    for j in range(2, NP):
        dPb.append(j * U_cur)                            # U_cur == U_{j-1}
        U_prev, U_cur = U_cur, 2.0 * Ptil * U_cur - U_prev
    dPb = jnp.stack(dPb[:max(NP, 1)], axis=-1)[:, :NP]   # (R, NP)
    dlog10k_dPtil = jnp.einsum("rij,ri,rj->r", gm.cheb_coef, Tb, dPb)
    dlnk_dlog10p = jnp.where(
        inside_p, dlog10k_dPtil * _LOG10 * 2.0 / (p_hi - p_lo), 0.0)
    return lnk, dlnk_dlog10p, Ctot


def forward_rate_constants(T, conc, gm, with_grad=False,
                           falloff_compat=False):
    """Effective forward rate constants (R,) including third-body/falloff.

    Returns (kf, tb_factor); with ``with_grad=True`` additionally
    (dkf/dcM, dtb/dcM) for the analytic Jacobian (cM = eff @ conc, so
    d/dconc_k = d/dcM * eff_k).

    ``falloff_compat=True`` reproduces the reference stack's falloff
    convention (resolved round 2 against the full golden trajectory — see
    PARITY.md): the blended falloff rate k_inf*L*F is additionally
    multiplied by the collider concentration *in mol/cm^3* (cM * 1e-6),
    i.e. the reference treats ``(+M)`` like a plain ``+M`` third body in
    its cgs rate space.  Physical CHEMKIN-II/TROE semantics (no factor)
    is the default.
    """
    k_inf = _arrhenius(T, gm.log_A, gm.beta, gm.Ea)
    cM = gm.eff @ conc  # (R,)
    # plain third-body factor multiplies the rate, handled by caller via cM
    # falloff blending
    k0 = _arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0)
    ratio = k0 / jnp.maximum(k_inf, _TINY)
    cM_pos = jnp.maximum(cM, 0.0)
    Pr = ratio * cM_pos
    L = Pr / (1.0 + Pr)
    tb_factor = jnp.where(gm.has_tb > 0, cM, 1.0)
    fc = cM_pos * 1e-6 if falloff_compat else 1.0
    if not with_grad:
        F = _blend_F(T, Pr, gm)
        # sign_A: negative-A DUPLICATE rows (ln-domain stores |A|, the sign
        # is a linear side channel; falloff rows are parse-time positive)
        kf = gm.sign_A * jnp.where(gm.has_falloff > 0, k_inf * L * F * fc,
                                   k_inf)
        if gm.any_plog:  # static: non-PLOG mechanisms skip the interp
            lnk, _, _ = _plog_interp(T, conc, gm)
            kf = jnp.where(gm.has_plog > 0,
                           _exp(jnp.clip(lnk, -_EXP_MAX, _EXP_MAX)), kf)
        if gm.any_cheb:  # static
            lnk_c, _, _ = _cheb_eval(T, conc, gm)
            kf = jnp.where(gm.has_cheb > 0,
                           _exp(jnp.clip(lnk_c, -_EXP_MAX, _EXP_MAX)), kf)
        return kf, tb_factor
    F, dF_dPr = _blend_F(T, Pr, gm, with_grad=True)
    kf = gm.sign_A * jnp.where(gm.has_falloff > 0, k_inf * L * F * fc, k_inf)
    dkf_dPr = k_inf * (F / ((1.0 + Pr) * (1.0 + Pr)) + L * dF_dPr)
    # the forward path clamps Pr (and fc) at cM=0, so the true derivative is
    # 0 for transiently negative Newton iterates — match it exactly
    if falloff_compat:
        # d/dcM [kinf L F cM 1e-6] = (dkf/dPr ratio cM + kinf L F) 1e-6
        dkf_dcM = jnp.where(
            (gm.has_falloff > 0) & (cM > 0.0),
            (dkf_dPr * ratio * cM_pos + k_inf * L * F) * 1e-6, 0.0)
    else:
        dkf_dcM = jnp.where((gm.has_falloff > 0) & (cM > 0.0),
                            dkf_dPr * ratio, 0.0)
    dtb_dcM = jnp.where(gm.has_tb > 0, 1.0, 0.0)
    if not (gm.any_plog or gm.any_cheb):
        return kf, tb_factor, dkf_dcM, dtb_dcM, None
    # p = Ctot R T, so dkf/dc_k = kf * (dlnk/dlnp) / Ctot on positive-c
    # entries (the caller applies the (conc > 0) indicator chain)
    dkf_dCtot = jnp.zeros_like(kf)
    if gm.any_plog:
        lnk, slope, Ctot = _plog_interp(T, conc, gm)
        k_plog = _exp(jnp.clip(lnk, -_EXP_MAX, _EXP_MAX))
        kf = jnp.where(gm.has_plog > 0, k_plog, kf)
        dkf_dCtot = jnp.where(gm.has_plog > 0, k_plog * slope / Ctot,
                              dkf_dCtot)
    if gm.any_cheb:
        lnk_c, dlnk_dlog10p, Ctot = _cheb_eval(T, conc, gm)
        k_cheb = _exp(jnp.clip(lnk_c, -_EXP_MAX, _EXP_MAX))
        kf = jnp.where(gm.has_cheb > 0, k_cheb, kf)
        # dlog10 p / dCtot = 1 / (ln10 Ctot)
        dkf_dCtot = jnp.where(
            gm.has_cheb > 0, k_cheb * dlnk_dlog10p / (_LOG10 * Ctot),
            dkf_dCtot)
    return kf, tb_factor, dkf_dcM, dtb_dcM, dkf_dCtot


def equilibrium_constants(T, gm, thermo, kc_compat=False):
    """ln of concentration-based equilibrium constants, ln Kc (R,).

    ``kc_compat=True`` reproduces the reference stack's equilibrium-constant
    convention, reverse-engineered from the committed golden trajectory
    (/root/reference/test/batch_gas_and_surf/gas_profile.csv): the effective
    Kc equals the physical Kc times (1e6)^dn with p0 = 1 bar — a cgs
    concentration standard state (mol/cm^3) applied uniformly to every
    reversible reaction, consistent with GasphaseReactions computing reverse
    rates entirely in cgs space.  (Round 1 had inferred a falloff exclusion
    from t=0 data alone; the full-trajectory fit resolved it — falloff rows
    carry the factor too, paired with the falloff_compat forward convention.
    See PARITY.md.)  Physically correct SI (p0 = 1 atm) is the default."""
    g = gibbs_over_RT(T, thermo)  # (S,)
    dnu = gm.nu_r - gm.nu_f
    dG = dnu @ g  # (R,) Delta G / RT
    dn = jnp.sum(dnu, axis=1)
    if kc_compat:
        log_c0 = jnp.log(1e5 / (R * T)) + jnp.log(1e6)
    else:
        log_c0 = jnp.log(P_ATM / (R * T))
    log_Kc = -dG + dn * log_c0
    return log_Kc


def reverse_rate_constants(T, kf, gm, thermo, kc_compat=False, log_Kc=None):
    """Reverse rate constants kr (R,): kf/Kc for equilibrium-derived rows,
    explicit Arrhenius for ``REV``-parameterized rows (CHEMKIN-II).
    Pass a precomputed ``log_Kc`` to avoid re-evaluating the Gibbs
    polynomials (the Jacobian path needs it separately anyway)."""
    if log_Kc is None:
        log_Kc = equilibrium_constants(T, gm, thermo, kc_compat)
    # kr = kf/Kc evaluated as kf * exp(-ln Kc); clip keeps the unreachable
    # far-from-equilibrium extreme finite without changing reachable physics
    kr_eq = gm.rev_mask * kf * _exp(jnp.clip(-log_Kc, -_EXP_MAX, _EXP_MAX))
    kr_rev = gm.sign_A_rev * _arrhenius(T, gm.log_A_rev, gm.beta_rev,
                                        gm.Ea_rev)
    return jnp.where(gm.has_rev > 0, kr_rev, kr_eq)


def reaction_rates(T, conc, gm, thermo, kc_compat=False, falloff_compat=None):
    """Net rate of progress q_i (R,) [mol/m^3/s].

    ``falloff_compat=None`` follows ``kc_compat``: the two quirks travel
    together in the reference stack, so ``kc_compat=True`` is the full
    reference-parity mode (PARITY.md)."""
    if falloff_compat is None:
        falloff_compat = kc_compat
    kf, tb = forward_rate_constants(T, conc, gm,
                                    falloff_compat=falloff_compat)
    kr = reverse_rate_constants(T, kf, gm, thermo, kc_compat)
    rf = kf * _stoich_prod(conc, gm.nu_f, gm.int_stoich)
    rr = kr * _stoich_prod(conc, gm.nu_r, gm.int_stoich)
    return (rf - rr) * tb


def production_rates(T, conc, gm, thermo, kc_compat=False,
                     falloff_compat=None):
    """Species molar production rates wdot (S,) [mol/m^3/s]."""
    q = reaction_rates(T, conc, gm, thermo, kc_compat, falloff_compat)
    return (gm.nu_r - gm.nu_f).T @ q


def _stoich_prod_and_grad(conc, nu, int_stoich):
    """(P, dP): P_j = prod_k c_k^nu_jk and dP_jk = dP_j/dc_k.

    Integer path (nu in {0,1,2,3}) is exact at c == 0 — integer powers make
    f_jk = c_k^nu_jk hit 0.0 exactly, so the exclusive product
    E_jk = prod_{m != k} f_jm is recovered without dividing by zero:
    E = total/f where f != 0; where exactly one factor is zero, E is the
    product of the nonzero factors; with two or more zeros E = 0.
    """
    c = conc[None, :]
    if int_stoich:
        f = jnp.where(nu >= 1, c, 1.0)
        f = jnp.where(nu >= 2, f * c, f)
        f = jnp.where(nu >= 3, f * c, f)
        d = jnp.where(nu >= 1, 1.0, 0.0)
        d = jnp.where(nu >= 2, 2.0 * c, d)
        d = jnp.where(nu >= 3, 3.0 * c * c, d)
    else:
        safe_c = jnp.where(conc > _TINY, conc, _TINY)[None, :]
        f = jnp.exp(nu * jnp.log(safe_c))
        # the forward path clamps at _TINY, so jacfwd through it sees a zero
        # derivative there; match it exactly — the raw nu*f/safe_c quotient
        # reaches ~1e150 for nu=0.5 at conc=0 and would poison the Newton
        # matrix (fractional <order> overrides at zero coverage)
        d = jnp.where(conc[None, :] > _TINY, nu * f / safe_c, 0.0)
    iszero = f == 0.0
    f_safe = jnp.where(iszero, 1.0, f)
    total_nz = jnp.prod(f_safe, axis=1, keepdims=True)      # (R, 1)
    nzeros = jnp.sum(iszero, axis=1, keepdims=True)         # (R, 1)
    total = jnp.where(nzeros == 0, total_nz, 0.0)
    E = jnp.where(
        iszero,
        jnp.where(nzeros == 1, total_nz, 0.0),
        jnp.where(nzeros == 0, total_nz / f_safe, 0.0),
    )
    return total[:, 0], d * E


def production_rates_and_jac(T, conc, gm, thermo, kc_compat=False,
                             falloff_compat=None):
    """(wdot (S,), dwdot/dconc (S, S)) — analytic, closed form.

    ``jax.jacfwd`` through :func:`production_rates` costs S forward passes
    (~13x one RHS on GRI-Mech); the closed form is a handful of (R, S)
    elementwise ops plus one (S, R) @ (R, S) contraction, which is what the
    Newton iteration matrix of every implicit step is built from
    (solver/sdirk.py).  Derivative structure:

      q_j = tb_j * kf_j * (Pf_j - rev_j e^{-lnKc_j} Prp_j)
      dq/dc_k picks up (a) the stoichiometric-product derivatives, (b) the
      third-body factor tb = cM (dtb/dc_k = eff_jk), and (c) the falloff
      dependence kf(Pr), Pr = (k0/kinf) cM — including the TROE blending
      term dF/dPr, so the Jacobian is exact (matches jacfwd to roundoff;
      tests/test_gas_kinetics.py).
    """
    if falloff_compat is None:
        falloff_compat = kc_compat
    kf, tb, dkf_dcM, dtb_dcM, dkf_dCtot = forward_rate_constants(
        T, conc, gm, with_grad=True, falloff_compat=falloff_compat)
    log_Kc = equilibrium_constants(T, gm, thermo, kc_compat)
    kr = reverse_rate_constants(T, kf, gm, thermo, kc_compat, log_Kc=log_Kc)
    # equilibrium-derived rows: kr = (rev_mask e^{-lnKc}) kf scales with kf,
    # so dkr/dcM = (kr/kf) dkf/dcM; explicit-REV rows have no cM dependence
    rKc = gm.rev_mask * _exp(jnp.clip(-log_Kc, -_EXP_MAX, _EXP_MAX))
    dkr_dcM = jnp.where(gm.has_rev > 0, 0.0, rKc * dkf_dcM)

    Pf, dPf = _stoich_prod_and_grad(conc, gm.nu_f, gm.int_stoich)
    Prp, dPrp = _stoich_prod_and_grad(conc, gm.nu_r, gm.int_stoich)

    net = kf * Pf - kr * Prp                                 # (R,)
    q = tb * net
    # dq_jk = tb (kf dPf - kr dPrp)
    #       + (dtb/dcM net + tb (dkf/dcM Pf - dkr/dcM Prp)) eff_jk
    dq = tb[:, None] * (kf[:, None] * dPf - kr[:, None] * dPrp) + (
        dtb_dcM * net + tb * (dkf_dcM * Pf - dkr_dcM * Prp))[:, None] * gm.eff
    if gm.any_plog or gm.any_cheb:  # static branch
        # pressure chain: dCtot/dc_k = 1 on positive entries (the forward
        # path clamps negatives out of Ctot); kr = rKc kf rides along
        ind = (conc > 0.0).astype(kf.dtype)
        dq = dq + (tb * dkf_dCtot * (Pf - rKc * Prp))[:, None] * ind[None, :]

    dnu = gm.nu_r - gm.nu_f
    return dnu.T @ q, dnu.T @ dq
