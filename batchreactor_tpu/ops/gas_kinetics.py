"""Gas-phase molar production rates as a pure jnp kernel.

Device-side rebuild of ``GasphaseReactions.calculate_molar_production_rates!``
(/root/reference/src/BatchReactor.jl:355).  The reference mutates state buffers
per call from inside CVODE; here ``production_rates(T, conc, gm, thermo)`` is a
pure function of scalar temperature and the (S,) concentration vector
[mol/m^3], returning (S,) molar production rates [mol/m^3/s].  It is
jit/vmap/jacfwd-safe: all clamps below exist to keep forward *and* tangent
values finite (Newton Jacobians are computed through this code).

Rate law (CHEMKIN-II semantics):
  kf_i = A_i T^beta_i exp(-Ea_i / RT)
  third body: rate *= cM_i = sum_k eff_ik c_k
  falloff:   kf = k_inf * Pr/(1+Pr) * F,  Pr = k0 cM / k_inf,
             F = 1 (Lindemann) or TROE blending
  reverse:   kr = kf / Kc, Kc = exp(-sum_k dnu_ik g_k/RT) * (p_atm/RT)^dnu_i
  wdot_k = sum_i dnu_ik (ratef_i - rater_i),  dnu = nu_r - nu_f
"""

import jax.numpy as jnp

from ..utils.constants import P_ATM, R
from .thermo import gibbs_over_RT

_LOG10 = 2.302585092994046
# clamps: keep exponentials/logs finite under jacfwd without changing physics.
# 690 ~ ln(f64 max); physical rate constants in SI units never approach e^690,
# so the clip only engages on unreachable branches that `where` discards.
_EXP_MAX = 690.0
_TINY = 1e-300


def _stoich_prod(conc, nu, int_stoich):
    """prod_k c_k^nu_ik for each reaction row; fast path for integer nu<=3.

    Negative concentrations (transient Newton iterates) are handled exactly
    like CVODE sees them: integer powers of negative numbers, no NaNs.
    """
    c = conc[None, :]
    if int_stoich:
        p = jnp.where(nu >= 1, c, 1.0)
        p = jnp.where(nu >= 2, p * c, p)
        p = jnp.where(nu >= 3, p * c, p)
        return jnp.prod(p, axis=1)
    safe_c = jnp.where(conc > _TINY, conc, _TINY)[None, :]
    return jnp.exp(jnp.sum(nu * jnp.log(safe_c), axis=1))


def _arrhenius(T, log_A, beta, Ea):
    """k = exp(ln A + beta ln T - Ea/RT); parameters live in ln domain
    (GasMechanism docstring explains the TPU range rationale)."""
    logk = log_A + beta * jnp.log(T) - Ea / (R * T)
    return jnp.exp(jnp.clip(logk, -_EXP_MAX, _EXP_MAX))


def _troe_F(T, Pr, troe, has_troe):
    """TROE falloff blending factor; returns 1 where not TROE, finite always."""
    a, T3, T1, T2 = troe[:, 0], troe[:, 1], troe[:, 2], troe[:, 3]
    Fcent = (1.0 - a) * jnp.exp(-T / T3) + a * jnp.exp(-T / T1) + jnp.exp(-T2 / T)
    log_fc = jnp.log(jnp.maximum(Fcent, _TINY)) / _LOG10
    c = -0.4 - 0.67 * log_fc
    n = 0.75 - 1.27 * log_fc
    log_pr = jnp.log(jnp.maximum(Pr, _TINY)) / _LOG10
    f1 = (log_pr + c) / (n - 0.14 * (log_pr + c))
    log_F = log_fc / (1.0 + f1 * f1)
    return jnp.where(has_troe > 0, jnp.exp(_LOG10 * log_F), 1.0)


def forward_rate_constants(T, conc, gm):
    """Effective forward rate constants (R,) including third-body/falloff."""
    k_inf = _arrhenius(T, gm.log_A, gm.beta, gm.Ea)
    cM = gm.eff @ conc  # (R,)
    # plain third-body factor multiplies the rate, handled by caller via cM
    # falloff blending
    k0 = _arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0)
    Pr = k0 * jnp.maximum(cM, 0.0) / jnp.maximum(k_inf, _TINY)
    F = _troe_F(T, Pr, gm.troe, gm.has_troe)
    k_falloff = k_inf * (Pr / (1.0 + Pr)) * F
    kf = jnp.where(gm.has_falloff > 0, k_falloff, k_inf)
    tb_factor = jnp.where(gm.has_tb > 0, cM, 1.0)
    return kf, tb_factor


def equilibrium_constants(T, gm, thermo, kc_compat=False):
    """ln of concentration-based equilibrium constants, ln Kc (R,).

    ``kc_compat=True`` reproduces the reference stack's equilibrium-constant
    convention, reverse-engineered from the committed golden trajectory
    (/root/reference/test/batch_gas_and_surf/gas_profile.csv, row-2 finite
    differences): for non-falloff reversible reactions its effective Kc
    equals the physical Kc times (1e6)^dn with p0 = 1 bar — consistent with a
    cgs/SI conversion applied with inverted sign in GasphaseReactions
    (exact on the O2+M->2O+M reverse channel); falloff reactions do not carry
    the factor.  Physically correct SI (p0 = 1 atm) is the default."""
    g = gibbs_over_RT(T, thermo)  # (S,)
    dnu = gm.nu_r - gm.nu_f
    dG = dnu @ g  # (R,) Delta G / RT
    dn = jnp.sum(dnu, axis=1)
    if kc_compat:
        log_c0 = jnp.log(1e5 / (R * T)) + jnp.log(1e6) * (1.0 - gm.has_falloff)
    else:
        log_c0 = jnp.log(P_ATM / (R * T))
    log_Kc = -dG + dn * log_c0
    return log_Kc


def reaction_rates(T, conc, gm, thermo, kc_compat=False):
    """Net rate of progress q_i (R,) [mol/m^3/s]."""
    kf, tb = forward_rate_constants(T, conc, gm)
    log_Kc = equilibrium_constants(T, gm, thermo, kc_compat)
    # kr = kf/Kc evaluated as kf * exp(-ln Kc); clip keeps the unreachable
    # far-from-equilibrium extreme finite without changing reachable physics
    kr = gm.rev_mask * kf * jnp.exp(jnp.clip(-log_Kc, -_EXP_MAX, _EXP_MAX))
    rf = kf * _stoich_prod(conc, gm.nu_f, gm.int_stoich)
    rr = kr * _stoich_prod(conc, gm.nu_r, gm.int_stoich)
    return (rf - rr) * tb


def production_rates(T, conc, gm, thermo, kc_compat=False):
    """Species molar production rates wdot (S,) [mol/m^3/s]."""
    q = reaction_rates(T, conc, gm, thermo, kc_compat)
    return (gm.nu_r - gm.nu_f).T @ q
