"""Non-isothermal reactors: the energy ODE and ignition as a workload.

``eqns`` owns the temperature-row state extension, the adiabatic
constant-volume / constant-pressure RHS + analytic Jacobian, and the
T-row error-norm operand; ``ignition`` owns the shared ignition-delay
detectors, adjoint QoI, and the forward IFT gradient.  docs/energy.md
has the equations and mode table; the ``energy=`` knob on
``batch_reactor_sweep`` (api.py) is the entry surface.
"""

from .eqns import (ATOL_SCALE_KEY, DEFAULT_ATOL_T, ENERGY_MODES,
                   energy_atol_scale, energy_cfg, extend_states,
                   make_energy_jac, make_energy_rhs, resolve_energy)
from .ignition import (DEFAULT_DT_MIN, DEFAULT_DT_THRESHOLD,
                       delay_sensitivity_forward,
                       energy_ignition_observer, extract_delay,
                       grid_crossing, interp_crossing, merge_observers,
                       temperature_ignition_qoi)

__all__ = [
    "ATOL_SCALE_KEY",
    "DEFAULT_ATOL_T",
    "DEFAULT_DT_MIN",
    "DEFAULT_DT_THRESHOLD",
    "ENERGY_MODES",
    "delay_sensitivity_forward",
    "energy_atol_scale",
    "energy_cfg",
    "energy_ignition_observer",
    "extend_states",
    "extract_delay",
    "grid_crossing",
    "interp_crossing",
    "make_energy_jac",
    "make_energy_rhs",
    "merge_observers",
    "resolve_energy",
    "temperature_ignition_qoi",
]
