"""Non-isothermal reactor equations: the energy ODE over ``[rho_k, T]``.

The reference (and the isothermal reproduction, ``ops/rhs.py``) freezes
T as a per-lane parameter, so every sweep is chemistry at a pinned
temperature and "ignition delay" is a species-marker proxy.  This module
closes the loop: the state vector grows a trailing temperature row
``y = [rho*Y_1 .. rho*Y_S, T]`` and the energy RHS closes dT/dt from the
species production rates via on-device NASA-7 thermodynamics
(``ops/thermo.cp_h_s_over_R`` — already parsed into the frozen bundles),
turning the ensemble sweep into *physical* ignition: thermal runaway,
real ignition-delay tables, flammability-limit maps (docs/energy.md).

Modes (``resolve_energy`` is THE validation rule, shared by ``api.py``,
``parallel/checkpoint.py`` and ``serving/schema.py``):

* ``None`` — isothermal (the default; every traced program is
  byte-identical to the knob not existing — tier-C ``energy-noop-fork``);
* ``"adiabatic_v"`` — adiabatic constant-volume:

    d(rho_k)/dt = wdot_k M_k
    dT/dt       = -sum_k u_k wdot_k / sum_k c_k Cv_k

  with molar internal energies ``u_k = h_k - R T`` and ``Cv_k = Cp_k -
  R`` (the classic constant-U,V reactor; Cantera's IdealGasReactor);
* ``"adiabatic_p"`` — adiabatic constant-pressure: the partial
  densities pick up the thermal-expansion dilution of the constant-p
  ideal-gas closure ``rho = p Wbar / (R T)``,

    d(rho_k)/dt = wdot_k M_k - rho_k (sum_j wdot_j / Ctot + (dT/dt)/T)
    dT/dt       = -sum_k h_k wdot_k / sum_k c_k Cp_k

  (``Ctot = sum_j c_j``; the dilution keeps ``sum_k c_k = p/(RT)``
  invariant along the trajectory, the same algebraic-closure discipline
  as the isothermal pressure round-trip).

The analytic Jacobian (``make_energy_jac``) keeps the solvers' closed-
form economics: the species block reuses ``ops/gas_kinetics.
production_rates_and_jac`` unchanged, the dense ``dwdot/dT`` column is
ONE scalar jvp through the forward rate kernel (exact to roundoff — the
kernel's clamps were built for tangents; re-deriving d ln k/dT by hand
would just duplicate it), and the dT/dt row closes by the chain rule
over the mixture-heat-capacity sums, with the NASA-7 T-derivatives
(dCp/dT, dh/dT) also one scalar jvp.  Matches ``jax.jacfwd`` of the RHS
to roundoff (tests/test_energy.py), at ~2 extra RHS-cost over the
isothermal Jacobian.

Error-norm convention: the T row lives on a ~1000 K scale while the
species rows sit at ~1e-1 kg/m^3, so one scalar ``atol`` cannot serve
both.  The reserved per-lane cfg operand ``_atol_scale``
(:data:`~..solver.sdirk.ATOL_SCALE_KEY`) carries a per-component
multiplier on ``atol`` — :func:`energy_atol_scale` builds ones over the
species rows and ``atol_T / atol`` (default :data:`DEFAULT_ATOL_T` =
1e-4 K) on the T row.  Like ``_nlive`` it is a traced operand read with
``cfg.get`` at trace time: absent, the solvers trace the pre-energy
program byte-for-byte.

Mechanism-shape padding (models/padding.py): dead species are provably
inert in the energy sums — padded thermo rows carry ``cp_k = R`` (so
``Cv_k = 0``) and ``h_k = R T`` (so ``u_k = 0``), dead concentrations
and production rates are exactly ``0.0``, so every mixture sum equals
the live sum bit-for-bit and (``adiabatic_v``) the Jacobian's dead rows
AND columns stay exactly zero — the identity-Newton-block argument that
keeps padded step counts identical to the dedicated-shape program's.
(``adiabatic_p`` dead columns carry the harmless ``dCtot/dc`` coupling:
value-inert, factorization-ulp class.)
"""

import jax
import jax.numpy as jnp

from ..solver.sdirk import ATOL_SCALE_KEY, NLIVE_KEY  # noqa: F401
from ..utils.constants import R

#: accepted non-None mode literals, in documentation order
ENERGY_MODES = ("adiabatic_v", "adiabatic_p")

#: default absolute tolerance on the temperature row [K] — CVODE-style
#: chemistry setups run T at atol 1e-2..1e-6 K; 1e-4 keeps the T row's
#: error weight commensurate with rtol*T (~1e-3 K at 1000 K, rtol 1e-6)
#: without letting a near-zero-slope induction phase stall the controller
DEFAULT_ATOL_T = 1e-4


def resolve_energy(energy):
    """THE validation rule for the ``energy=`` knob (module doc), shared
    by every entry point so the accepted grammar cannot drift:
    ``None``/``False`` -> ``None`` (isothermal), the mode literals pass
    through, anything else is a loud error naming the accepted values."""
    if energy is None or energy is False:
        return None
    if energy in ENERGY_MODES:
        return energy
    raise ValueError(
        f"unknown energy mode {energy!r}; accepted: None (isothermal), "
        f"'adiabatic_v' (adiabatic constant-volume), 'adiabatic_p' "
        f"(adiabatic constant-pressure)")


def _mix_thermo(T, thermo):
    """(Cp (S,) [J/mol/K], h (S,) [J/mol]) at scalar T — the NASA-7
    evaluation both the RHS and (through one scalar jvp) the Jacobian's
    dCp/dT / dh/dT terms share, so forward and derivative cannot drift."""
    from ..ops.thermo import cp_h_s_over_R

    cp_R, h_RT, _ = cp_h_s_over_R(T, thermo)
    return cp_R * R, h_RT * (R * T)


def make_energy_rhs(gm, thermo, mode, kc_compat=False):
    """Pure RHS for non-isothermal gas chemistry over ``y = [rho_k, T]``
    (module doc equations).  ``mode=None`` returns the isothermal gas
    RHS unchanged — the dispatch is a traced no-op (tier-C
    ``energy-noop-fork``)."""
    mode = resolve_energy(mode)
    if mode is None:
        from ..ops.rhs import make_gas_rhs

        return make_gas_rhs(gm, thermo, kc_compat=kc_compat)
    from ..ops.gas_kinetics import production_rates

    molwt = thermo.molwt

    def rhs(t, y, cfg):
        rho_y, T = y[:-1], y[-1]
        conc = rho_y / molwt
        wdot = production_rates(T, conc, gm, thermo, kc_compat)
        cp, h = _mix_thermo(T, thermo)
        if mode == "adiabatic_v":
            u = h - R * T
            cv = cp - R
            Tdot = -(u @ wdot) / (conc @ cv)
            dy = wdot * molwt
        else:  # adiabatic_p
            Tdot = -(h @ wdot) / (conc @ cp)
            # constant-p dilution: keeps Ctot = p/(RT) invariant (module
            # doc); Ctot > 0 always (a lane starts with positive density
            # and the dilution preserves it)
            Ctot = jnp.sum(conc)
            dil = jnp.sum(wdot) / Ctot + Tdot / T
            dy = wdot * molwt - rho_y * dil
        return jnp.concatenate([dy, jnp.reshape(Tdot, (1,))])

    return rhs


def make_energy_jac(gm, thermo, mode, kc_compat=False):
    """Analytic Jacobian companion to :func:`make_energy_rhs`:
    ``jac(t, y, cfg) -> (S+1, S+1)`` over ``y = [rho_k, T]``.  The
    species block is the isothermal closed form; the dense T column is
    one scalar jvp of the rate kernel; the dT/dt row is the chain rule
    over the mixture sums (module doc).  ``mode=None`` returns the
    isothermal gas Jacobian unchanged."""
    mode = resolve_energy(mode)
    if mode is None:
        from ..ops.rhs import make_gas_jac

        return make_gas_jac(gm, thermo, kc_compat=kc_compat)
    from ..ops.gas_kinetics import production_rates, production_rates_and_jac

    molwt = thermo.molwt

    def jac(t, y, cfg):
        rho_y, T = y[:-1], y[-1]
        conc = rho_y / molwt
        wdot, dwdot = production_rates_and_jac(T, conc, gm, thermo,
                                               kc_compat)
        # the dense dwdot/dT column: one scalar jvp through the forward
        # kernel — exact (the clamps were designed for tangents), about
        # one RHS-evaluation of work
        one = jnp.ones_like(T)
        _, dwdot_dT = jax.jvp(
            lambda Tv: production_rates(Tv, conc, gm, thermo, kc_compat),
            (T,), (one,))
        (cp, h), (dcp, dh) = jax.jvp(
            lambda Tv: _mix_thermo(Tv, thermo), (T,), (one,))
        inv_w = 1.0 / molwt
        if mode == "adiabatic_v":
            u = h - R * T
            du = dh - R          # == Cv_k, evaluated through the SAME jvp
            cv = cp - R
            ccv = conc @ cv
            Tdot = -(u @ wdot) / ccv
            J_ss = dwdot * (molwt[:, None] * inv_w[None, :])
            J_sT = dwdot_dT * molwt
            # dTdot/dc_b = -(u . dwdot[:, b])/ccv - Tdot Cv_b/ccv
            dTdot_dc = -(u @ dwdot) / ccv - Tdot * cv / ccv
            J_Ts = dTdot_dc * inv_w
            J_TT = ((-(du @ wdot) - (u @ dwdot_dT)) / ccv
                    - Tdot * (conc @ dcp) / ccv)
        else:  # adiabatic_p
            ccp = conc @ cp
            Tdot = -(h @ wdot) / ccp
            dTdot_dc = -(h @ dwdot) / ccp - Tdot * cp / ccp
            dTdot_dT = ((-(dh @ wdot) - (h @ dwdot_dT)) / ccp
                        - Tdot * (conc @ dcp) / ccp)
            Ctot = jnp.sum(conc)
            W = jnp.sum(wdot)
            dil = W / Ctot + Tdot / T
            colsum = jnp.sum(dwdot, axis=0)          # dW/dc_b
            ddil_dc = (colsum / Ctot - W / (Ctot * Ctot)
                       + dTdot_dc / T)
            ddil_dT = (jnp.sum(dwdot_dT) / Ctot + dTdot_dT / T
                       - Tdot / (T * T))
            S = molwt.shape[0]
            J_ss = (dwdot * (molwt[:, None] * inv_w[None, :])
                    - dil * jnp.eye(S, dtype=y.dtype)
                    - rho_y[:, None] * (ddil_dc * inv_w)[None, :])
            J_sT = dwdot_dT * molwt - rho_y * ddil_dT
            J_Ts = dTdot_dc * inv_w
            J_TT = dTdot_dT
        top = jnp.concatenate([J_ss, J_sT[:, None]], axis=1)
        bot = jnp.concatenate(
            [J_Ts, jnp.reshape(J_TT, (1,))])[None, :]
        return jnp.concatenate([top, bot], axis=0)

    return jac


# --------------------------------------------------------------------------
# state / cfg extension helpers (the api.py wiring surface)
# --------------------------------------------------------------------------
def extend_states(y0s, T):
    """``(B, S) -> (B, S+1)``: append the per-lane initial temperature
    as the trailing state row (module doc layout).  For energy-mode
    sweeps this runs AFTER species padding (``models/padding.
    pad_states``), so the T row always sits at index ``S_pad``."""
    y0s = jnp.asarray(y0s)
    T = jnp.broadcast_to(jnp.asarray(T, dtype=y0s.dtype),
                         (y0s.shape[0],))
    return jnp.concatenate([y0s, T[:, None]], axis=1)


def energy_atol_scale(n_lanes, n, atol, atol_T=None):
    """The per-lane ``(B, n)`` :data:`~..solver.sdirk.ATOL_SCALE_KEY`
    operand for an energy-extended state: ones over the species rows,
    ``atol_T / atol`` on the trailing T row, so the solvers' scaled
    norms weight the temperature error at ``atol_T`` Kelvin (module doc
    norm convention).  ``atol_T=None`` -> :data:`DEFAULT_ATOL_T`."""
    atol_T = DEFAULT_ATOL_T if atol_T is None else float(atol_T)
    if atol_T <= 0:
        raise ValueError(f"atol_T must be positive Kelvin, got {atol_T}")
    row = jnp.ones((int(n),), dtype=jnp.float64)
    row = row.at[-1].set(atol_T / float(atol))
    return jnp.broadcast_to(row, (int(n_lanes), int(n)))


def energy_cfg(cfgs, energy, n_lanes, n, atol, atol_T=None):
    """A copy of the per-lane ``cfgs`` dict extended for an energy-mode
    sweep: the T-row atol-scale operand, and the live-count operand
    bumped by one when mechanism padding set it (the T row is live).
    ``energy=None`` returns ``cfgs`` UNCHANGED (same object): the
    isothermal path must not even copy the dict — the traced program
    stays byte-identical to the knob not existing (tier-C
    ``energy-noop-fork``)."""
    if resolve_energy(energy) is None:
        return cfgs
    out = dict(cfgs)
    if NLIVE_KEY in out:
        out[NLIVE_KEY] = jnp.asarray(out[NLIVE_KEY]) + 1.0
    out[ATOL_SCALE_KEY] = energy_atol_scale(n_lanes, n, atol, atol_T)
    return out


# --------------------------------------------------------------------------
# brlint tier-C program contracts (analysis/contracts.py).  The energy
# RHS/Jacobian are traced into every non-isothermal solver program;
# energy-noop-fork pins the mode=None dispatch byte-identical to the
# isothermal builders (sharing the mech-padding contract's baseline
# memo, so every no-op comparison uses the same "before").
# --------------------------------------------------------------------------
from ..analysis.contracts import Identical, Pure, program_contract  # noqa: E402


@program_contract(
    "energy-eqns",
    doc="non-isothermal RHS/Jacobian (both adiabatic modes) + the "
        "T-row-weighted solver program: pure")
def _contract_energy_eqns(h):
    jnp_ = h.jnp
    y0e = jnp_.concatenate([h.y0, jnp_.asarray([1100.0])])
    cfg_e = {**h.cfg,
             ATOL_SCALE_KEY: jnp_.ones_like(y0e).at[-1].set(1e6)}
    for mode in ENERGY_MODES:
        rhs = make_energy_rhs(h.gm, h.th, mode)
        jac = make_energy_jac(h.gm, h.th, mode)
        yield Pure(f"energy-rhs-{mode}", h.jaxpr(rhs, 0.0, y0e, cfg_e),
                   check_dtype=h.check_dtype)
        yield Pure(f"energy-jac-{mode}", h.jaxpr(jac, 0.0, y0e, cfg_e),
                   check_dtype=h.check_dtype)
    # the T-row-weighted BDF step program (the _atol_scale operand rides
    # cfg, exactly like _nlive): pure, no callbacks / in-loop staging
    from ..solver import bdf

    rhs_v = make_energy_rhs(h.gm, h.th, "adiabatic_v")
    jac_v = make_energy_jac(h.gm, h.th, "adiabatic_v")

    def run(y0_):
        return bdf.solve(rhs_v, y0_, 0.0, 1e-7, cfg_e, rtol=1e-6,
                         atol=1e-10, max_steps=3, n_save=0,
                         jac=jac_v).y

    yield Pure("energy-bdf-step", h.jaxpr(run, y0e))


@program_contract(
    "energy-noop-fork",
    doc="energy=None is a traced no-op: the mode dispatch returns the "
        "isothermal builders' programs byte-identical, and the cfg "
        "extension leaves the per-lane dict untouched")
def _contract_energy_noop(h):
    from ..analysis.contracts import CostProbe
    from ..ops.rhs import make_gas_jac, make_gas_rhs

    # tier-D opt-in: every contract must produce a cost-table row
    # (tests/test_costmodel.py), and this one's obligations are all
    # string pairs — probe the mode=None RHS trace the fork pins
    yield CostProbe("energy-rhs-none",
                    h.jaxpr(make_energy_rhs(h.gm, h.th, None), 0.0,
                            h.y0, h.cfg))
    yield Identical(
        "energy-noop-fork", "gas-rhs-energy-none",
        h.memo("gas-rhs-baseline",
               lambda: str(h.jaxpr(make_gas_rhs(h.gm, h.th), 0.0, h.y0,
                                   h.cfg))),
        str(h.jaxpr(make_energy_rhs(h.gm, h.th, None), 0.0, h.y0,
                    h.cfg)),
        "make_energy_rhs(mode=None) traced a DIFFERENT program than the "
        "isothermal gas RHS: the energy dispatch leaked into the "
        "isothermal path (energy/eqns.py contract)")
    yield Identical(
        "energy-noop-fork", "gas-jac-energy-none",
        h.memo("gas-jac-baseline",
               lambda: str(h.jaxpr(make_gas_jac(h.gm, h.th), 0.0, h.y0,
                                   h.cfg))),
        str(h.jaxpr(make_energy_jac(h.gm, h.th, None), 0.0, h.y0,
                    h.cfg)),
        "make_energy_jac(mode=None) traced a DIFFERENT program than the "
        "isothermal gas Jacobian (energy/eqns.py contract)")
    # the cfg extension at energy=None must leave the per-lane dict
    # UNTOUCHED (same object, not a copy): the solvers read the
    # _atol_scale operand with cfg.get at trace time, so "key absent"
    # IS the pre-energy solver/segment program byte-for-byte — this
    # pins the isothermal path never even growing the key
    cfg_none = energy_cfg(h.cfg, None, 1, h.y0.shape[0], 1e-10)
    yield Identical(
        "energy-noop-fork", "energy-cfg-none",
        repr(sorted(h.cfg)), repr(sorted(cfg_none)),
        "energy_cfg(energy=None) changed the per-lane cfg keys: the "
        "isothermal path would trace a different solver program "
        "(energy/eqns.py contract)")
    if cfg_none is not h.cfg:
        yield Identical(
            "energy-noop-fork", "energy-cfg-none-identity", "same",
            "copied",
            "energy_cfg(energy=None) copied the cfg dict instead of "
            "returning it unchanged (energy/eqns.py contract)")
