"""Ignition delay as a first-class quantity: detectors, QoIs, gradients.

Before the energy equation existed, ignition delay was a species-marker
proxy scattered across the stack: a fuel-consumption observer fold in
``parallel/sweep.py`` and a threshold-crossing QoI inside
``sensitivity/adjoint.py``.  This module is the shared home for the
crossing machinery, plus the detectors the *physical* (non-isothermal)
workload makes possible:

* :func:`interp_crossing` / :func:`grid_crossing` — the ONE linear-
  interpolation crossing rule.  ``sensitivity/adjoint.py``'s species
  QoI now delegates here, so the observer, the grid QoI, and the
  forward IFT gradient all define "the crossing" identically.
* :func:`energy_ignition_observer` — the streaming O(1)-memory detector
  for energy-mode sweeps: the running max of dT/dt over accepted-step
  intervals (the classic max-temperature-rise-rate marker) with a
  temperature-rise gate, plus the first interpolated crossing of
  ``T0 + dT_thr`` (the threshold marker the gradient passes
  differentiate).  Folds per accepted step; composes with the species
  fallback detector through :func:`merge_observers` (all keys
  ``ign_``-prefixed, so the two folds never collide).
* :func:`extract_delay` — host-side read-out: the max-dT/dt time where
  the lane actually ignited (temperature rose by >= ``dT_min``), NaN
  elsewhere (the ``parallel.ignition_observer`` NaN contract).
* :func:`temperature_ignition_qoi` — the adjoint-compatible grid QoI
  (``sensitivity.adjoint.solve_adjoint``): interpolated first rising
  crossing of ``T0 + dT_thr`` on the pinned-grid knot states, with the
  crossing *index* stop-gradiented so gradients flow through the
  bracketing values — dtau_ign/d(theta) at parameter-count-independent
  cost.
* :func:`delay_sensitivity_forward` — the CVODES-shaped forward
  gradient: solve to the crossing, then apply the implicit-function
  theorem at it.  tau is defined by ``T(tau) = T0 + dT_thr``, so
  ``dtau/dtheta = -S_T(tau) / Tdot(tau)`` with ``S_T`` the T row of the
  staggered forward tangents (``solver/bdf.py tangent=``) — one
  tangent-carrying solve per gradient, exact at the crossing the
  threshold defines.  FD-validated in tests/test_energy.py alongside
  the adjoint twin.
"""

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

#: default temperature-rise threshold [K] defining ignition for the
#: threshold detector and both gradient passes (the common 400 K
#: convention of shock-tube ignition-delay correlations)
DEFAULT_DT_THRESHOLD = 400.0

#: default minimum temperature rise [K] for a lane to count as ignited
#: in :func:`extract_delay` (below it the max-dT/dt time is induction
#: noise, not a runaway)
DEFAULT_DT_MIN = 50.0


def interp_crossing(t_prev, t_cur, v_prev, v_cur, thr):
    """Linearly interpolated crossing time of ``thr`` inside the
    bracketing interval ``(t_prev, v_prev) -> (t_cur, v_cur)`` — THE
    crossing rule every detector and QoI shares.  Degenerate brackets
    (``v_prev == v_cur``) clamp onto ``t_cur``."""
    denom = v_cur - v_prev
    w = jnp.where(denom != 0, (thr - v_prev) / denom, 1.0)
    w = jnp.clip(w, 0.0, 1.0)
    return t_prev + w * (t_cur - t_prev)


def grid_crossing(tk, m, thr, rising=False):
    """Interpolated FIRST crossing of ``thr`` by the grid series ``m``
    over knot times ``tk`` — the adjoint-QoI form: the crossing *index*
    is piecewise-constant in the parameters and stop-gradiented, so
    gradients flow through the bracketing VALUES (tk is grid-pinned and
    carries no gradient by the adjoint's design).  Returns NaN where
    the series never crosses (the ``parallel.ignition_observer``
    contract — a silent last-knot tau would carry a silently-zero
    gradient)."""
    hit = (m > thr) if rising else (m < thr)
    j = lax.stop_gradient(jnp.maximum(jnp.argmax(hit), 1))
    t_x = interp_crossing(tk[j - 1], tk[j], m[j - 1], m[j], thr)
    return jnp.where(jnp.any(hit), t_x, jnp.nan)


# --------------------------------------------------------------------------
# streaming detectors (observer folds — the O(1)-memory sweep surface)
# --------------------------------------------------------------------------
def energy_ignition_observer(t_index, dT_thr=DEFAULT_DT_THRESHOLD):
    """(observer, init) extracting ignition delay DURING an energy-mode
    solve (module doc).  ``t_index`` is the temperature row's state
    index (``S_pad`` — the trailing row).  Folded keys (all
    ``ign_``-prefixed so the species fallback detector merges cleanly):

    * ``ign_tau_dT`` — midpoint time of the steepest accepted-step
      dT/dt interval seen so far (the max-temperature-rise-rate
      marker); gate it with :func:`extract_delay`;
    * ``ign_tau_thr`` — interpolated first crossing of ``T0 + dT_thr``
      (NaN until crossed) — the threshold tau the gradient passes
      differentiate;
    * ``ign_T0`` / ``ign_T_max`` — first-seen and running-max
      temperature (the first accepted step sits ~1e-16 s after t0, so
      first-seen == initial to rounding — the species detector's m0
      convention).
    """

    init = {"ign_t_prev": jnp.nan, "ign_T_prev": jnp.nan,
            "ign_T0": jnp.nan, "ign_T_max": -jnp.inf,
            "ign_slope_max": -jnp.inf, "ign_tau_dT": jnp.nan,
            "ign_tau_thr": jnp.nan}

    def observer(t, y, acc):
        T = y[t_index]
        T0 = jnp.where(jnp.isnan(acc["ign_T0"]), T, acc["ign_T0"])
        dt = t - acc["ign_t_prev"]
        valid = jnp.isfinite(acc["ign_t_prev"]) & (dt > 0)
        slope = jnp.where(valid, (T - acc["ign_T_prev"])
                          / jnp.where(dt > 0, dt, 1.0), -jnp.inf)
        steeper = slope > acc["ign_slope_max"]
        tau_dT = jnp.where(steeper, acc["ign_t_prev"] + 0.5 * dt,
                           acc["ign_tau_dT"])
        thr = T0 + dT_thr
        crossed = (jnp.isnan(acc["ign_tau_thr"]) & valid
                   & (T >= thr) & (acc["ign_T_prev"] < thr))
        t_x = interp_crossing(acc["ign_t_prev"], t,
                              acc["ign_T_prev"], T, thr)
        return {"ign_t_prev": t, "ign_T_prev": T, "ign_T0": T0,
                "ign_T_max": jnp.maximum(T, acc["ign_T_max"]),
                "ign_slope_max": jnp.maximum(slope,
                                             acc["ign_slope_max"]),
                "ign_tau_dT": tau_dT,
                "ign_tau_thr": jnp.where(crossed, t_x,
                                         acc["ign_tau_thr"])}

    return observer, init


def merge_observers(a, a0, b, b0):
    """Compose two observer folds over disjoint key sets into one
    (dict-union accumulator); loud on a key collision — a silently
    shadowed fold would report one detector's tau as the other's."""
    overlap = sorted(set(a0) & set(b0))
    if overlap:
        raise ValueError(f"observer folds collide on key(s) {overlap}")

    init = {**a0, **b0}

    def observer(t, y, acc):
        out_a = a(t, y, {k: acc[k] for k in a0})
        out_b = b(t, y, {k: acc[k] for k in b0})
        return {**out_a, **out_b}

    return observer, init


def extract_delay(observed, dT_min=DEFAULT_DT_MIN):
    """Host-side per-lane ignition delay from an
    :func:`energy_ignition_observer` fold: the max-dT/dt time where the
    lane actually ignited (T rose by >= ``dT_min`` Kelvin over the
    run), NaN elsewhere — ``out["ignition_delay"]`` on
    ``batch_reactor_sweep`` energy runs."""
    tau = np.asarray(observed["ign_tau_dT"], dtype=np.float64)
    rise = (np.asarray(observed["ign_T_max"])
            - np.asarray(observed["ign_T0"]))
    return np.where(rise >= float(dT_min), tau, np.nan)


# --------------------------------------------------------------------------
# gradient-pass QoIs (adjoint) and the forward IFT pass
# --------------------------------------------------------------------------
def temperature_ignition_qoi(t_index, dT_thr=DEFAULT_DT_THRESHOLD):
    """Adjoint QoI builder (``sensitivity.adjoint.solve_adjoint``
    contract ``qoi(tk, ys, y_final) -> scalar``): ignition delay as the
    interpolated first rising crossing of ``T0 + dT_thr`` on the
    pinned-grid temperature row — dtau_ign/d(theta) at
    parameter-count-independent cost (module doc)."""

    def qoi(tk, ys, y_final):
        Tser = ys[:, t_index]
        return grid_crossing(tk, Tser, Tser[0] + dT_thr, rising=True)

    return qoi


def delay_sensitivity_forward(rhs_theta, y0, theta, cfg, t_index, *,
                              t_max, jac=None, dT_thr=DEFAULT_DT_THRESHOLD,
                              rtol=1e-8, atol=1e-12, max_steps=100_000,
                              jac_window=1, sens_iters=2):
    """Forward (tangent-based) ignition-delay gradient: ``(tau, grad,
    aux)`` with ``grad`` a theta-shaped pytree of dtau/dtheta.

    tau is the threshold tau — ``T(tau) = T0 + dT_thr`` — and the
    gradient is the implicit-function theorem at the crossing::

        0 = d/dtheta [T(tau(theta); theta) - T0]
          => dtau/dtheta = -S_T(tau) / Tdot(tau)

    evaluated in two passes: (1) a plain adaptive solve to ``t_max``
    locates the interpolated crossing (the
    :func:`energy_ignition_observer` threshold detector); (2) a
    staggered-tangent solve (``sensitivity.forward.solve_forward``) to
    ``t1 = tau`` lands state + tangents exactly at the crossing, where
    one RHS evaluation closes ``Tdot``.  Cost: one plain + one
    tangent-carrying solve — the CVODES shape.  Run at ``rtol <= 1e-8``
    (the docs/sensitivity.md tangent-accuracy tier).  NaN gradient when
    the lane never crosses inside ``t_max`` (``aux["ignited"]`` False).
    """
    from ..sensitivity import params as P
    from ..sensitivity.forward import solve_forward
    from ..solver import bdf

    theta0 = jax.tree.map(lax.stop_gradient, theta)

    def rhs0(t, y, cfg):
        return rhs_theta(t, y, theta0, cfg)

    jac0 = None
    if jac is not None:
        def jac0(t, y, cfg):
            return jac(t, y, theta0, cfg)

    observer, obs0 = energy_ignition_observer(t_index, dT_thr=dT_thr)
    pin = bdf.solve(rhs0, jnp.asarray(y0), 0.0, float(t_max), cfg,
                    rtol=rtol, atol=atol, max_steps=max_steps,
                    jac=jac0, jac_window=jac_window,
                    observer=observer, observer_init=obs0)
    tau = pin.observed["ign_tau_thr"]
    ignited = bool(np.isfinite(np.asarray(tau)))
    theta_flat, unflat = P.flatten(theta)
    if not ignited:
        grad = unflat(jnp.full((theta_flat.shape[0],), jnp.nan))
        return float(np.asarray(tau)), grad, {
            "ignited": False, "status": pin.status, "Tdot": np.nan}
    jac_fixed = None
    if jac is not None:
        def jac_fixed(t, y, cfg):
            return jac(t, y, theta, cfg)

    res = solve_forward(rhs_theta, y0, 0.0, tau, theta, cfg, rtol=rtol,
                        atol=atol, max_steps=max_steps, jac=jac_fixed,
                        jac_window=jac_window, sens_iters=sens_iters,
                        sens_errcon=True)
    Tdot = rhs_theta(res.t, res.y, theta, cfg)[t_index]
    grad_flat = -res.tangents[:, t_index] / Tdot
    return float(np.asarray(tau)), unflat(grad_flat), {
        "ignited": True, "status": res.status,
        "Tdot": float(np.asarray(Tdot)),
        "T_at_tau": float(np.asarray(res.y[t_index])),
        "n_accepted": int(res.n_accepted)}
