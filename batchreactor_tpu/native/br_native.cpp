// br_native — native (C++) runtime for batchreactor_tpu.
//
// The reference's native compute lives in two wrapped C libraries: SUNDIALS
// CVODE (variable-order BDF, /root/reference/src/BatchReactor.jl:138,210) and
// libxml2 (/root/reference/Project.toml:10,14).  This file is the framework's
// own native runtime: a CHEMKIN-semantics gas-kinetics right-hand side and a
// CVODE-class variable-order (1..5) BDF integrator with modified Newton and
// dense partially-pivoted LU, compiled to a shared library and driven from
// Python via ctypes (batchreactor_tpu/native/).
//
// Roles:
//   * backend="cpu" execution path for single conditions (host latency;
//     no XLA compile cost),
//   * the self-measured single-CPU baseline for bench.py (BASELINE.md:
//     the reference publishes no numbers, so the baseline is a CVODE-class
//     BDF on the identical RHS at identical tolerances — this integrator),
//   * a solver-vs-solver oracle for the JAX SDIRK4 path in tests.
//
// Numerical semantics mirror batchreactor_tpu/ops/{thermo,gas_kinetics}.py
// exactly (same clamps, same ln-domain Arrhenius parameters, same kc_compat
// convention) so C++ and JAX RHS evaluations agree to rounding error.
//
// BDF formulation: variable-step, variable-order BDF in backward-difference
// form with quasi-constant step sizes (Shampine & Reichelt, "The MATLAB ODE
// Suite", SIAM J. Sci. Comput. 18(1), 1997 — the ode15s/CVODE family).
// kappa = 0 (pure BDF, as CVODE).  Jacobian by difference quotients, reused
// lazily across steps (CVODE's quasi-constant iteration-matrix economy).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr double kR = 8.314472;        // J/mol/K (utils/constants.py)
constexpr double kPAtm = 101325.0;     // Pa
constexpr double kExpMax = 690.0;      // ln(f64 max) guard (ops/gas_kinetics.py)
constexpr double kTiny = 1e-300;
constexpr double kLog10 = 2.302585092994046;

inline double clamp(double x, double lo, double hi) {
  return x < lo ? lo : (x > hi ? hi : x);
}

}  // namespace

extern "C" {

// Gas-phase mechanism tensor bundle — pointer view of the Python-side
// GasMechanism + ThermoTable arrays (models/gas.py, models/thermo.py).
// All matrices row-major.  Lifetimes owned by the caller.
struct BrGasMech {
  int64_t S;                 // species
  int64_t R;                 // reactions
  const double* nu_f;        // (R,S)
  const double* nu_r;        // (R,S)
  const double* log_A;       // (R,)  ln-domain SI pre-exponentials
  const double* beta;        // (R,)
  const double* Ea;          // (R,)  J/mol
  const double* eff;         // (R,S) third-body efficiencies
  const double* has_tb;      // (R,)
  const double* has_falloff; // (R,)
  const double* log_A0;      // (R,)
  const double* beta0;       // (R,)
  const double* Ea0;         // (R,)
  const double* has_troe;    // (R,)
  const double* troe;        // (R,4) a, T3, T1, T2
  const double* has_sri;     // (R,)
  const double* sri;         // (R,5) a, b, c, d, e
  const double* rev_mask;    // (R,)
  const double* sign_A;      // (R,) +-1; negative-A DUPLICATE rows
  const double* has_rev;     // (R,) 1.0 where explicit REV parameters
  const double* log_A_rev;   // (R,) ln|A_rev|, SI
  const double* beta_rev;    // (R,)
  const double* Ea_rev;      // (R,) J/mol
  const double* sign_A_rev;  // (R,) +-1
  int64_t plog_P;            // PLOG table width (padded); 0 disables
  const double* has_plog;    // (R,)
  const double* plog_lnp;    // (R,P) ln(p/Pa), +inf padded
  const double* plog_logA;   // (R,P) ln A (SI)
  const double* plog_beta;   // (R,P)
  const double* plog_Ea;     // (R,P) J/mol
  int64_t cheb_NT;           // Chebyshev table rows (0 disables)
  int64_t cheb_NP;           // Chebyshev table cols
  const double* has_cheb;    // (R,)
  const double* cheb_coef;   // (R,NT,NP)
  const double* cheb_invT;   // (R,2) 1/Tmin, 1/Tmax
  const double* cheb_logP;   // (R,2) log10(Pmin/Pa), log10(Pmax/Pa)
  const double* cheb_si_ln;  // (R,) ln cgs->SI factor
  const double* coeffs;      // (S,2,7) NASA-7 low/high ranges
  const double* T_mid;       // (S,)
  const double* molwt;       // (S,) kg/mol
  int32_t kc_compat;         // PARITY.md equilibrium-constant quirk
  int32_t int_stoich;        // integer stoichiometry fast path
};

// y = per-species mass density rho_k (kg/m^3); dy = d(rho_k)/dt.
// Mirrors ops/rhs.make_gas_rhs: conc = y/molwt; dy = wdot*molwt.
void br_gas_rhs(const BrGasMech* m, double T, const double* y, double* dy) {
  const int64_t S = m->S, R = m->R;
  std::vector<double> conc(S), g(S), wdot(S, 0.0);
  for (int64_t k = 0; k < S; ++k) conc[k] = y[k] / m->molwt[k];

  // NASA-7 Gibbs g_k/(RT) = h/(RT) - s/R (ops/thermo.py)
  const double T2 = T * T, T3 = T2 * T, T4 = T3 * T, logT = std::log(T);
  for (int64_t k = 0; k < S; ++k) {
    const double* a = m->coeffs + (k * 2 + (T > m->T_mid[k] ? 1 : 0)) * 7;
    const double h = a[0] + a[1] / 2 * T + a[2] / 3 * T2 + a[3] / 4 * T3 +
                     a[4] / 5 * T4 + a[5] / T;
    const double s = a[0] * logT + a[1] * T + a[2] / 2 * T2 + a[3] / 3 * T3 +
                     a[4] / 4 * T4 + a[6];
    g[k] = h - s;
  }

  const double rt = kR * T;
  const double log_c0_phys = std::log(kPAtm / rt);
  const double log_c0_ref = std::log(1e5 / rt);

  // loop-invariant PLOG/CHEB pressure (p = Ctot R T): hundreds of
  // pressure-dependent rows must not each rescan the species
  double lnp = 0.0;
  if (m->plog_P > 0 || m->cheb_NT > 0) {
    double Ctot = 0.0;
    for (int64_t k = 0; k < S; ++k) Ctot += conc[k] > 0 ? conc[k] : 0.0;
    if (Ctot < kTiny) Ctot = kTiny;
    lnp = std::log(Ctot * kR * T);
  }

  for (int64_t i = 0; i < R; ++i) {
    const double* nuf = m->nu_f + i * S;
    const double* nur = m->nu_r + i * S;
    const double* effi = m->eff + i * S;

    double kf = std::exp(
        clamp(m->log_A[i] + m->beta[i] * logT - m->Ea[i] / rt, -kExpMax, kExpMax));
    double cM = 0.0;
    for (int64_t k = 0; k < S; ++k) cM += effi[k] * conc[k];

    const bool falloff = m->has_falloff[i] > 0;
    if (falloff) {
      const double k0 = std::exp(clamp(
          m->log_A0[i] + m->beta0[i] * logT - m->Ea0[i] / rt, -kExpMax, kExpMax));
      const double Pr = k0 * (cM > 0 ? cM : 0.0) / (kf > kTiny ? kf : kTiny);
      double F = 1.0;
      if (m->has_troe[i] > 0) {
        const double* t = m->troe + i * 4;
        const double a = t[0];
        double Fcent = (1.0 - a) * std::exp(-T / t[1]) + a * std::exp(-T / t[2]);
        if (std::isfinite(t[3])) Fcent += std::exp(-t[3] / T);
        const double log_fc =
            std::log(Fcent > kTiny ? Fcent : kTiny) / kLog10;
        const double c = -0.4 - 0.67 * log_fc;
        const double n = 0.75 - 1.27 * log_fc;
        const double log_pr = std::log(Pr > kTiny ? Pr : kTiny) / kLog10;
        const double f1 = (log_pr + c) / (n - 0.14 * (log_pr + c));
        F = std::exp(kLog10 * log_fc / (1.0 + f1 * f1));
      }
      if (m->has_sri[i] > 0) {
        // SRI blending: F = d T^e [a exp(-b/T) + exp(-T/c)]^X,
        // X = 1/(1 + log10(Pr)^2)  (mirrors ops/gas_kinetics._sri_F)
        const double* s = m->sri + i * 5;
        const double lp = std::log(Pr > kTiny ? Pr : kTiny) / kLog10;
        const double X = 1.0 / (1.0 + lp * lp);
        double base = s[0] * std::exp(-s[1] / T);
        if (std::isfinite(s[2])) base += std::exp(-T / s[2]);
        else base += 1.0;
        if (base < kTiny) base = kTiny;
        F = s[3] * std::pow(T, s[4]) * std::exp(X * std::log(base));
      }
      kf = kf * (Pr / (1.0 + Pr)) * F;
      // reference-parity falloff (PARITY.md, resolved round 2): the blended
      // rate is additionally multiplied by the collider concentration in
      // mol/cm^3 — the reference treats (+M) like a plain +M third body in
      // its cgs rate space
      if (m->kc_compat) kf *= (cM > 0.0 ? cM : 0.0) * 1e-6;
    }
    const double tb = m->has_tb[i] > 0 ? cM : 1.0;

    // equilibrium: ln Kc = -dG/RT + dn ln c0 (ops/gas_kinetics.py, PARITY.md)
    double dG = 0.0, dn = 0.0;
    for (int64_t k = 0; k < S; ++k) {
      const double d = nur[k] - nuf[k];
      dG += d * g[k];
      dn += d;
    }
    kf *= m->sign_A[i];  // negative-A DUPLICATE rows (ln-domain stores |A|)

    if (m->plog_P > 0 && m->has_plog[i] > 0) {
      // PLOG: piecewise-linear ln k in ln p between per-pressure Arrhenius
      // fits, clamped at the table ends (mirrors ops/gas_kinetics._plog_interp)
      const int64_t P = m->plog_P;
      const double* pg = m->plog_lnp + i * P;
      int64_t idx = -1;
      for (int64_t j = 0; j < P; ++j) idx += pg[j] <= lnp ? 1 : 0;
      if (idx < 0) idx = 0;
      if (idx > P - 2 && P > 1) idx = P - 2;
      const int64_t j1 = P > 1 ? idx + 1 : idx;
      const double lo = pg[idx], hi = pg[j1];
      auto lnk_at = [&](int64_t j) {
        return m->plog_logA[i * P + j] + m->plog_beta[i * P + j] * logT -
               m->plog_Ea[i * P + j] / rt;
      };
      const double klo = lnk_at(idx), khi = lnk_at(j1);
      const double span = hi - lo;
      double w = (std::isfinite(span) && span > 0) ? (lnp - lo) / span : 0.0;
      w = w < 0 ? 0.0 : (w > 1 ? 1.0 : w);
      kf = std::exp(clamp(klo + w * (khi - klo), -kExpMax, kExpMax));
    }

    if (m->cheb_NT > 0 && m->has_cheb[i] > 0) {
      // Chebyshev tables (mirrors ops/gas_kinetics._cheb_eval): log10 k =
      // sum a_ij T_i(Ttil) T_j(Ptil), window-clamped
      const double iT_lo = m->cheb_invT[i * 2], iT_hi = m->cheb_invT[i * 2 + 1];
      const double p_lo = m->cheb_logP[i * 2], p_hi = m->cheb_logP[i * 2 + 1];
      double Ttil = (2.0 / T - iT_lo - iT_hi) / (iT_hi - iT_lo);
      double Ptil = (2.0 * lnp / kLog10 - p_lo - p_hi) / (p_hi - p_lo);
      Ttil = Ttil < -1 ? -1.0 : (Ttil > 1 ? 1.0 : Ttil);
      Ptil = Ptil < -1 ? -1.0 : (Ptil > 1 ? 1.0 : Ptil);
      const int64_t NT = m->cheb_NT, NP = m->cheb_NP;
      double Tb[16], Pb[16];  // parse caps table degrees well below this
      Tb[0] = 1.0; if (NT > 1) Tb[1] = Ttil;
      for (int64_t a = 2; a < NT; ++a) Tb[a] = 2.0 * Ttil * Tb[a-1] - Tb[a-2];
      Pb[0] = 1.0; if (NP > 1) Pb[1] = Ptil;
      for (int64_t a = 2; a < NP; ++a) Pb[a] = 2.0 * Ptil * Pb[a-1] - Pb[a-2];
      double log10k = 0.0;
      const double* c = m->cheb_coef + i * NT * NP;
      for (int64_t a = 0; a < NT; ++a)
        for (int64_t b = 0; b < NP; ++b) log10k += c[a * NP + b] * Tb[a] * Pb[b];
      kf = std::exp(clamp(log10k * kLog10 + m->cheb_si_ln[i],
                          -kExpMax, kExpMax));
    }

    const double log_c0 =
        m->kc_compat ? log_c0_ref + std::log(1e6) : log_c0_phys;
    const double log_Kc = -dG + dn * log_c0;
    // reverse: explicit REV Arrhenius where given, else kf/Kc
    const double kr =
        m->has_rev[i] > 0
            ? m->sign_A_rev[i] *
                  std::exp(clamp(m->log_A_rev[i] + m->beta_rev[i] * logT -
                                     m->Ea_rev[i] / rt,
                                 -kExpMax, kExpMax))
            : m->rev_mask[i] * kf * std::exp(clamp(-log_Kc, -kExpMax, kExpMax));

    // stoichiometric concentration products (ops/gas_kinetics._stoich_prod:
    // integer powers keep transient negative concentrations NaN-free)
    double pf = 1.0, pr = 1.0;
    if (m->int_stoich) {
      for (int64_t k = 0; k < S; ++k) {
        int nf = (int)(nuf[k] + 0.5), nr = (int)(nur[k] + 0.5);
        for (int j = 0; j < nf; ++j) pf *= conc[k];
        for (int j = 0; j < nr; ++j) pr *= conc[k];
      }
    } else {
      double sf = 0.0, sr = 0.0;
      for (int64_t k = 0; k < S; ++k) {
        const double lc = std::log(conc[k] > kTiny ? conc[k] : kTiny);
        sf += nuf[k] * lc;
        sr += nur[k] * lc;
      }
      pf = std::exp(sf);
      pr = std::exp(sr);
    }
    const double q = (kf * pf - kr * pr) * tb;
    for (int64_t k = 0; k < S; ++k) wdot[k] += (nur[k] - nuf[k]) * q;
  }
  for (int64_t k = 0; k < S; ++k) dy[k] = wdot[k] * m->molwt[k];
}

// ---------------------------------------------------------------------------
// Generic CVODE-class BDF integrator.
// ---------------------------------------------------------------------------

typedef void (*BrRhsFn)(const void* ctx, double t, const double* y, double* dy);

struct BrStats {
  double t;           // time reached
  int32_t status;     // 0 success, 2 max steps, 3 dt underflow
  int32_t pad;
  int64_t n_steps;    // accepted
  int64_t n_rejected; // rejected attempts (error test + Newton failures)
  int64_t n_rhs;
  int64_t n_jac;
  int64_t n_lu;
};

enum { BR_SUCCESS = 0, BR_MAX_STEPS = 2, BR_DT_UNDERFLOW = 3 };

namespace {

constexpr int kMaxOrder = 5;
constexpr int kNewtonMax = 4;

struct Dense {
  // column-major n x n with LAPACK-style pivots
  int n;
  std::vector<double> a;
  std::vector<int> piv;
  // returns false on exact singularity
  bool factor() {
    for (int k = 0; k < n; ++k) {
      int p = k;
      double best = std::fabs(a[k * n + k]);
      for (int i = k + 1; i < n; ++i) {
        const double v = std::fabs(a[k * n + i]);
        if (v > best) { best = v; p = i; }
      }
      piv[k] = p;
      if (best == 0.0) return false;
      if (p != k)
        for (int j = 0; j < n; ++j) std::swap(a[j * n + k], a[j * n + p]);
      const double d = a[k * n + k];
      for (int i = k + 1; i < n; ++i) a[k * n + i] /= d;
      for (int j = k + 1; j < n; ++j) {
        const double ajk = a[j * n + k];
        if (ajk == 0.0) continue;
        for (int i = k + 1; i < n; ++i) a[j * n + i] -= a[k * n + i] * ajk;
      }
    }
    return true;
  }
  void solve(double* b) const {
    for (int k = 0; k < n; ++k) std::swap(b[k], b[piv[k]]);
    for (int k = 0; k < n; ++k)
      for (int i = k + 1; i < n; ++i) b[i] -= a[k * n + i] * b[k];
    for (int k = n - 1; k >= 0; --k) {
      b[k] /= a[k * n + k];
      for (int i = 0; i < k; ++i) b[i] -= a[k * n + i] * b[k];
    }
  }
};

// RMS of e scaled by atol + rtol*|y| (same norm as solver/sdirk.py)
double scaled_norm(const std::vector<double>& e, const std::vector<double>& y,
                   double rtol, double atol) {
  double s = 0.0;
  for (size_t i = 0; i < e.size(); ++i) {
    const double sc = atol + rtol * std::fabs(y[i]);
    const double v = e[i] / sc;
    s += v * v;
  }
  return std::sqrt(s / e.size());
}

// Rescale backward differences for a step-size change by `factor` at the
// current order (Shampine & Reichelt eq. for the R matrix): D <- (R U)^T D.
void change_D(std::vector<std::vector<double>>& D, int order, double factor) {
  const int m = order + 1;
  std::vector<double> R(m * m, 0.0), U(m * m, 0.0);
  auto fill = [m, order](std::vector<double>& M, double fac) {
    std::vector<double> W(m * m, 0.0);
    for (int j = 0; j < m; ++j) W[0 * m + j] = 1.0;  // row 0 all ones
    for (int i = 1; i <= order; ++i)
      for (int j = 1; j <= order; ++j)
        W[i * m + j] = (i - 1 - fac * j) / i;
    // cumulative product down the rows
    for (int i = 1; i < m; ++i)
      for (int j = 0; j < m; ++j) W[i * m + j] *= W[(i - 1) * m + j];
    M = W;
  };
  fill(R, factor);
  fill(U, 1.0);
  std::vector<double> RU(m * m, 0.0);
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < m; ++j) {
      double s = 0.0;
      for (int k = 0; k < m; ++k) s += R[i * m + k] * U[k * m + j];
      RU[i * m + j] = s;
    }
  const int n = (int)D[0].size();
  std::vector<std::vector<double>> nD(m, std::vector<double>(n, 0.0));
  for (int i = 0; i < m; ++i)       // nD[i] = sum_j RU[j,i] * D[j]
    for (int j = 0; j < m; ++j) {
      const double w = RU[j * m + i];
      if (w == 0.0) continue;
      for (int k = 0; k < n; ++k) nD[i][k] += w * D[j][k];
    }
  for (int i = 0; i < m; ++i) D[i] = nD[i];
}

}  // namespace

// Integrate dy/dt = f(t, y) from t0 to t1 with variable-order BDF.
// ts_out/ys_out: optional accepted-step trajectory buffer of n_save rows
// (pass n_save = 0 to skip).  Returns status (also in stats).
int32_t br_bdf(BrRhsFn f, const void* ctx, int64_t n_, const double* y0,
               double t0, double t1, double rtol, double atol,
               int64_t max_steps, double first_step, double* y_out,
               double* ts_out, double* ys_out, int64_t n_save,
               int64_t* n_saved, BrStats* stats) {
  const int n = (int)n_;
  const double span = t1 - t0;
  std::vector<double> y(y0, y0 + n), fy(n), scale(n);
  BrStats st = {t0, BR_MAX_STEPS, 0, 0, 0, 0, 0, 0};
  int64_t saved = 0;

  auto rhs = [&](double t, const std::vector<double>& yy,
                 std::vector<double>& out) {
    f(ctx, t, yy.data(), out.data());
    ++st.n_rhs;
  };

  rhs(t0, y, fy);
  double h;
  if (first_step > 0) {
    h = first_step;
  } else {
    // same first-step heuristic as solver/sdirk.py:103-112
    const double d0 = scaled_norm(y, y, rtol, atol);
    const double d1 = scaled_norm(fy, y, rtol, atol);
    h = clamp(0.01 * d0 / (d1 > 1e-30 ? d1 : 1e-30), span * 1e-24, span);
  }

  // backward differences D[0..kMaxOrder+2]
  std::vector<std::vector<double>> D(kMaxOrder + 3,
                                     std::vector<double>(n, 0.0));
  D[0] = y;
  for (int k = 0; k < n; ++k) D[1][k] = h * fy[k];
  int order = 1;
  int n_equal_steps = 0;

  // BDF coefficients: gamma_j = sum_{i<=j} 1/i; alpha=gamma (kappa=0);
  // error const at order j is 1/(j+1).
  double gamma[kMaxOrder + 2];
  gamma[0] = 0.0;
  for (int j = 1; j <= kMaxOrder + 1; ++j) gamma[j] = gamma[j - 1] + 1.0 / j;
  auto err_const = [](int j) { return 1.0 / (j + 1); };

  // lazy Jacobian + iteration matrix
  std::vector<double> J(n * n, 0.0);
  Dense lu;
  lu.n = n;
  lu.a.resize(n * n);
  lu.piv.resize(n);
  bool jac_current = false, lu_current = false;
  double c_lu = 0.0;  // the c the current LU was built with

  auto num_jac = [&](double t, const std::vector<double>& yy,
                     const std::vector<double>& f0) {
    std::vector<double> yp = yy, fp(n);
    const double sq = std::sqrt(2.220446049250313e-16);
    for (int j = 0; j < n; ++j) {
      const double dy =
          sq * std::fmax(std::fabs(yy[j]), std::fmax(atol, 1e-14));
      yp[j] = yy[j] + dy;
      rhs(t, yp, fp);
      for (int i = 0; i < n; ++i) J[j * n + i] = (fp[i] - f0[i]) / dy;
      yp[j] = yy[j];
    }
    ++st.n_jac;
    jac_current = true;
    lu_current = false;
  };

  const double newton_tol =
      std::fmax(10 * 2.22e-16 / rtol, std::fmin(0.03, std::sqrt(rtol)));
  double t = t0;
  const double h_min = span * 1e-22;

  std::vector<double> y_pred(n), psi(n), d(n), res(n), ynew(n), tmp(n);

  while (st.n_steps < max_steps) {
    if (t >= t1 - span * 1e-14) {
      st.status = BR_SUCCESS;
      break;
    }
    if (h > t1 - t) {
      const double factor = (t1 - t) / h;
      change_D(D, order, factor);
      h = t1 - t;
      n_equal_steps = 0;
    }

    const double t_new = t + h;
    // predictor and psi from differences
    for (int i = 0; i < n; ++i) {
      double yp = 0.0, ps = 0.0;
      for (int j = 0; j <= order; ++j) yp += D[j][i];
      for (int j = 1; j <= order; ++j) ps += gamma[j] * D[j][i];
      y_pred[i] = yp;
      psi[i] = ps / gamma[order];  // alpha = gamma (kappa=0)
    }
    const double c = h / gamma[order];
    for (int i = 0; i < n; ++i) scale[i] = atol + rtol * std::fabs(y_pred[i]);

    // modified Newton on d: F(d) = c f(t_new, y_pred+d) - psi - d = 0
    bool converged = false;
    bool step_fail = false;
    for (int attempt = 0; attempt < 2 && !converged; ++attempt) {
      if (!lu_current || c != c_lu) {
        for (int j = 0; j < n; ++j)
          for (int i = 0; i < n; ++i)
            lu.a[j * n + i] = (i == j ? 1.0 : 0.0) - c * J[j * n + i];
        if (!lu.factor()) { step_fail = true; break; }
        ++st.n_lu;
        lu_current = true;
        c_lu = c;
      }
      std::fill(d.begin(), d.end(), 0.0);
      ynew = y_pred;
      double dw_old = -1.0;
      converged = false;
      for (int it = 0; it < kNewtonMax; ++it) {
        rhs(t_new, ynew, tmp);
        bool finite = true;
        for (int i = 0; i < n; ++i) {
          res[i] = c * tmp[i] - psi[i] - d[i];
          if (!std::isfinite(res[i])) finite = false;
        }
        if (!finite) break;
        lu.solve(res.data());
        double dw = 0.0;
        for (int i = 0; i < n; ++i) {
          const double v = res[i] / scale[i];
          dw += v * v;
        }
        dw = std::sqrt(dw / n);
        double rate = dw_old > 0 ? dw / dw_old : 0.0;
        if (dw_old > 0 && (rate >= 1.0 ||
                           std::pow(rate, kNewtonMax - it) / (1 - rate) * dw >
                               newton_tol))
          break;  // diverging or too slow
        for (int i = 0; i < n; ++i) {
          d[i] += res[i];
          ynew[i] = y_pred[i] + d[i];
        }
        if (dw == 0.0 ||
            (dw_old > 0 ? rate / (1 - rate) * dw < newton_tol
                        : dw < 0.1 * newton_tol)) {
          converged = true;
          break;
        }
        dw_old = dw;
      }
      if (!converged && !jac_current) {
        rhs(t_new, y_pred, tmp);
        num_jac(t_new, y_pred, tmp);
      } else if (!converged) {
        break;
      }
    }

    if (!converged || step_fail) {
      // halve the step; the Jacobian (freshly rebuilt by the retry above)
      // is kept — only the iteration matrix needs rebuilding at the new c
      ++st.n_rejected;
      const double factor = 0.5;
      change_D(D, order, factor);
      h *= factor;
      n_equal_steps = 0;
      lu_current = false;
      if (h < h_min) { st.status = BR_DT_UNDERFLOW; break; }
      continue;
    }

    // local error estimate: err = err_const(order) * d
    double err_norm = 0.0;
    for (int i = 0; i < n; ++i) {
      const double v = err_const(order) * d[i] / scale[i];
      err_norm += v * v;
    }
    err_norm = std::sqrt(err_norm / n);

    if (err_norm > 1.0) {
      ++st.n_rejected;
      const double factor = std::fmax(
          0.1, 0.9 * std::pow(err_norm, -1.0 / (order + 1)));
      change_D(D, order, factor);
      h *= factor;
      n_equal_steps = 0;
      if (h < h_min) { st.status = BR_DT_UNDERFLOW; break; }
      continue;
    }

    // accept
    ++st.n_steps;
    ++n_equal_steps;
    t = t_new;
    // update differences: D[order+2] = d - D[order+1]; D[order+1] = d;
    // D[j] += D[j+1] downward
    for (int i = 0; i < n; ++i) {
      D[order + 2][i] = d[i] - D[order + 1][i];
      D[order + 1][i] = d[i];
    }
    for (int j = order; j >= 0; --j)
      for (int i = 0; i < n; ++i) D[j][i] += D[j + 1][i];
    y = D[0];
    jac_current = false;  // J ages; rebuilt on next Newton failure

    if (n_save > 0 && saved < n_save) {
      ts_out[saved] = t;
      std::memcpy(ys_out + saved * n, y.data(), n * sizeof(double));
      ++saved;
    }

    if (n_equal_steps < order + 1) continue;  // let the history settle

    // order/step selection (Shampine & Reichelt): compare error estimates
    // at order-1, order, order+1 via scaled differences
    for (int i = 0; i < n; ++i) scale[i] = atol + rtol * std::fabs(y[i]);
    double e_m = 1e300, e_p = 1e300;
    if (order > 1) {
      double s = 0.0;
      for (int i = 0; i < n; ++i) {
        const double v = err_const(order - 1) * D[order][i] / scale[i];
        s += v * v;
      }
      e_m = std::sqrt(s / n);
    }
    if (order < kMaxOrder) {
      double s = 0.0;
      for (int i = 0; i < n; ++i) {
        const double v = err_const(order + 1) * D[order + 2][i] / scale[i];
        s += v * v;
      }
      e_p = std::sqrt(s / n);
    }
    const double f_m =
        order > 1 ? std::pow(std::fmax(e_m, 1e-16), -1.0 / order) : 0.0;
    const double f_0 = std::pow(std::fmax(err_norm, 1e-16), -1.0 / (order + 1));
    const double f_p = order < kMaxOrder
                           ? std::pow(std::fmax(e_p, 1e-16), -1.0 / (order + 2))
                           : 0.0;
    int delta = 0;
    double best = f_0;
    if (f_m > best) { best = f_m; delta = -1; }
    if (f_p > best) { best = f_p; delta = 1; }
    order += delta;
    double factor = std::fmin(10.0, 0.9 * best);
    if (factor < 0.2) factor = 0.2;
    change_D(D, order, factor);
    h *= factor;
    n_equal_steps = 0;
    lu_current = false;
  }

  st.t = t;
  std::memcpy(y_out, y.data(), n * sizeof(double));
  if (n_saved) *n_saved = saved;
  if (stats) *stats = st;
  return st.status;
}

// ---------------------------------------------------------------------------
// Surface (catalytic) chemistry — native mirror of ops/surface_kinetics.py
// and ops/rhs.make_surface_rhs (reference semantics:
// SurfaceReactions.calculate_molar_production_rates!,
// /root/reference/src/BatchReactor.jl:344, conventions pinned in PARITY.md).
// ---------------------------------------------------------------------------

struct BrSurfMech {
  int64_t R;                  // reactions
  int64_t Sg;                 // gas species coupled to
  int64_t Ss;                 // surface species
  const double* nu_f_gas;     // (R,Sg)
  const double* nu_r_gas;     // (R,Sg)
  const double* nu_f_surf;    // (R,Ss)
  const double* nu_r_surf;    // (R,Ss)
  const double* expo_gas;     // (R,Sg) rate-law exponents
  const double* expo_surf;    // (R,Ss)
  const double* log_A;        // (R,) ln A, cgs
  const double* beta;         // (R,)
  const double* Ea;           // (R,) J/mol
  const double* cov_eps;      // (R,Ss) coverage-dependent Ea slopes, J/mol
  const double* stick;        // (R,) 1.0 for sticking rows
  const double* stick_s0;     // (R,)
  const double* stick_molwt;  // (R,) g/mol
  const double* mwc;          // (R,) Motz-Wise flag
  double site_density;        // Gamma, mol/cm^2
  const double* site_coordination;  // (Ss,) sigma
  const double* molwt_gas;    // (Sg,) kg/mol (gas state layout order)
  int32_t int_expo;           // all exponents in {0,1,2,3}
};

namespace {

constexpr double kRCgs = kR * 1e7;  // erg/(mol K)
constexpr double kPi = 3.141592653589793;

// prod_k base_k^expo_ik for one reaction row (ops/surface_kinetics._pow_prod)
inline double pow_prod_row(const double* base, const double* expo, int64_t n,
                           bool int_expo) {
  double p = 1.0;
  if (int_expo) {
    for (int64_t k = 0; k < n; ++k) {
      const int e = (int)(expo[k] + 0.5);
      for (int j = 0; j < e; ++j) p *= base[k];
    }
    return p;
  }
  double s = 0.0;
  for (int64_t k = 0; k < n; ++k)
    s += expo[k] * std::log(base[k] > kTiny ? base[k] : kTiny);
  return std::exp(s);
}

}  // namespace

// Surface molar production rates (SI, mol/m^2/s) from T [K], p [Pa], gas
// mole fractions x (Sg,), coverages theta (Ss,).  Mirrors
// ops/surface_kinetics.production_rates.
void br_surface_rates(const BrSurfMech* m, double T, double p,
                      const double* x, const double* theta,
                      double* sdot_gas, double* sdot_surf) {
  const int64_t R = m->R, Sg = m->Sg, Ss = m->Ss;
  std::vector<double> c_gas(Sg), c_surf(Ss);
  for (int64_t k = 0; k < Sg; ++k) c_gas[k] = x[k] * p / (kR * T) * 1e-6;
  for (int64_t k = 0; k < Ss; ++k)
    c_surf[k] = theta[k] * m->site_density / m->site_coordination[k];
  for (int64_t k = 0; k < Sg; ++k) sdot_gas[k] = 0.0;
  for (int64_t k = 0; k < Ss; ++k) sdot_surf[k] = 0.0;

  const double logT = std::log(T), rt = kR * T;
  for (int64_t i = 0; i < R; ++i) {
    double Ea_eff = m->Ea[i];
    const double* eps = m->cov_eps + i * Ss;
    for (int64_t k = 0; k < Ss; ++k) Ea_eff += eps[k] * theta[k];

    double k_rate;
    const bool is_stick = m->stick[i] > 0;
    if (is_stick) {
      // s_eff sqrt(RT/2 pi M) [cm/s]; coverages enter the rate directly
      // (no Gamma^m) — golden-trajectory convention (PARITY.md)
      double s_eff = m->stick_s0[i] *
          std::exp(clamp(m->beta[i] * logT - Ea_eff / rt, -kExpMax, kExpMax));
      if (m->mwc[i] > 0) s_eff = s_eff / (1.0 - s_eff / 2.0);
      k_rate = s_eff * std::sqrt(kRCgs * T / (2.0 * kPi * m->stick_molwt[i]));
    } else {
      k_rate = std::exp(clamp(m->log_A[i] + m->beta[i] * logT - Ea_eff / rt,
                              -kExpMax, kExpMax));
    }

    const double gas_part =
        pow_prod_row(c_gas.data(), m->expo_gas + i * Sg, Sg, m->int_expo);
    const double surf_part = pow_prod_row(
        is_stick ? theta : c_surf.data(), m->expo_surf + i * Ss, Ss,
        m->int_expo);
    const double q = k_rate * gas_part * surf_part;  // mol/cm^2/s

    const double* nfg = m->nu_f_gas + i * Sg;
    const double* nrg = m->nu_r_gas + i * Sg;
    const double* nfs = m->nu_f_surf + i * Ss;
    const double* nrs = m->nu_r_surf + i * Ss;
    for (int64_t k = 0; k < Sg; ++k) sdot_gas[k] += (nrg[k] - nfg[k]) * q;
    for (int64_t k = 0; k < Ss; ++k) sdot_surf[k] += (nrs[k] - nfs[k]) * q;
  }
  for (int64_t k = 0; k < Sg; ++k) sdot_gas[k] *= 1e4;   // -> mol/m^2/s
  for (int64_t k = 0; k < Ss; ++k) sdot_surf[k] *= 1e4;
}

// Full surface(+gas) reactor RHS over y = [rho_k (Sg), theta_k (Ss)].
// Mirrors ops/rhs.make_surface_rhs including the reference's Asv quirk
// (/root/reference/src/BatchReactor.jl:345: the WHOLE surface source —
// coverage part included — scales by Asv when asv_quirk).
void br_surf_rhs(const BrSurfMech* m, const BrGasMech* gm, double T,
                 double Asv, int32_t asv_quirk, const double* y, double* dy) {
  const int64_t Sg = m->Sg, Ss = m->Ss;
  std::vector<double> x(Sg), sdot_gas(Sg), sdot_surf(Ss);
  double rho = 0.0;
  for (int64_t k = 0; k < Sg; ++k) rho += y[k];
  // mass fracs -> mole fracs; p = rho R T sum(Y_k/M_k)
  double inv_wbar = 0.0;
  for (int64_t k = 0; k < Sg; ++k) {
    x[k] = (y[k] / rho) / m->molwt_gas[k];
    inv_wbar += x[k];
  }
  const double p = rho * kR * T * inv_wbar;
  for (int64_t k = 0; k < Sg; ++k) x[k] /= inv_wbar;

  br_surface_rates(m, T, p, x.data(), y + Sg, sdot_gas.data(),
                   sdot_surf.data());

  for (int64_t k = 0; k < Sg; ++k)
    dy[k] = sdot_gas[k] * Asv * m->molwt_gas[k];
  if (gm) {
    std::vector<double> yg(Sg), dyg(Sg);
    // conc = x p/(RT) = rho_k/M_k: reuse the gas RHS on the mass densities
    for (int64_t k = 0; k < Sg; ++k) yg[k] = y[k];
    br_gas_rhs(gm, T, yg.data(), dyg.data());
    for (int64_t k = 0; k < Sg; ++k) dy[k] += dyg[k];
  }
  const double covg_scale = asv_quirk ? Asv : 1.0;
  for (int64_t k = 0; k < Ss; ++k)
    dy[Sg + k] = sdot_surf[k] * covg_scale * m->site_coordination[k] /
                 (m->site_density * 1e4);
}

// Convenience: BDF over the built-in gas RHS at fixed temperature T
// (isothermal reactor, /root/reference/src/BatchReactor.jl:14-17).
struct GasCtx {
  const BrGasMech* m;
  double T;
};

static void gas_rhs_tramp(const void* ctx, double t, const double* y,
                          double* dy) {
  (void)t;
  const GasCtx* g = (const GasCtx*)ctx;
  br_gas_rhs(g->m, g->T, y, dy);
}

int32_t br_solve_gas_bdf(const BrGasMech* m, double T, const double* y0,
                         double t0, double t1, double rtol, double atol,
                         int64_t max_steps, double first_step, double* y_out,
                         double* ts_out, double* ys_out, int64_t n_save,
                         int64_t* n_saved, BrStats* stats) {
  GasCtx ctx = {m, T};
  return br_bdf(gas_rhs_tramp, &ctx, m->S, y0, t0, t1, rtol, atol, max_steps,
                first_step, y_out, ts_out, ys_out, n_save, n_saved, stats);
}

// Convenience: BDF over the surface(+gas) RHS (gm may be null: surf-only).
struct SurfCtx {
  const BrSurfMech* m;
  const BrGasMech* gm;
  double T;
  double Asv;
  int32_t asv_quirk;
};

static void surf_rhs_tramp(const void* ctx, double t, const double* y,
                           double* dy) {
  (void)t;
  const SurfCtx* s = (const SurfCtx*)ctx;
  br_surf_rhs(s->m, s->gm, s->T, s->Asv, s->asv_quirk, y, dy);
}

int32_t br_solve_surf_bdf(const BrSurfMech* m, const BrGasMech* gm, double T,
                          double Asv, int32_t asv_quirk, const double* y0,
                          double t0, double t1, double rtol, double atol,
                          int64_t max_steps, double first_step, double* y_out,
                          double* ts_out, double* ys_out, int64_t n_save,
                          int64_t* n_saved, BrStats* stats) {
  SurfCtx ctx = {m, gm, T, Asv, asv_quirk};
  return br_bdf(surf_rhs_tramp, &ctx, m->Sg + m->Ss, y0, t0, t1, rtol, atol,
                max_steps, first_step, y_out, ts_out, ys_out, n_save, n_saved,
                stats);
}

}  // extern "C"
