"""ctypes bindings + on-demand g++ build for br_native.cpp.

The C++ source ships INSIDE the package (br_native.cpp next to this file,
included in the wheel via pyproject package-data) so an installed
distribution can still build and use ``backend="cpu"``.  The shared object
builds next to the source when that directory is writable (the dev-checkout
case), else into ``~/.cache/batchreactor_tpu`` (read-only site-packages).
"""

import ctypes
import dataclasses
import os
import subprocess
import threading

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "br_native.cpp")


def _so_path():
    """Build target named by a content hash of the C++ source: the cache
    directory is shared across package versions and wheel-extracted files
    can carry archive timestamps older than a previously built .so, so an
    mtime freshness check could silently load a stale library with an
    incompatible struct ABI.  A hash-named .so is correct by construction
    (exists == built from exactly this source)."""
    import hashlib

    try:
        with open(_SRC, "rb") as fh:
            tag = hashlib.sha256(fh.read()).hexdigest()[:12]
    except OSError:
        tag = "nosrc"
    name = f"libbr_native-{tag}.so"
    if os.access(_PKG_DIR, os.W_OK):
        return os.path.join(_PKG_DIR, name)
    cache = os.path.join(os.path.expanduser("~"), ".cache",
                         "batchreactor_tpu")
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, name)


_SO = _so_path()

_lock = threading.Lock()
_lib = None


class NativeUnavailable(RuntimeError):
    """Raised when the shared library cannot be built or loaded."""


def _build():
    # compile to a temp path and rename: the hash-named target is trusted
    # by existence alone, so a partial file from an interrupted g++ must
    # never land at _SO (rename on the same filesystem is atomic)
    tmp = _SO + f".build{os.getpid()}"
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise NativeUnavailable(f"g++ build failed: {detail}") from e
    # GC stale revisions: hash-named siblings accumulate one per source
    # edit / wheel upgrade otherwise
    import glob

    for old in glob.glob(os.path.join(os.path.dirname(_SO),
                                      "libbr_native-*.so")):
        if old != _SO:
            try:
                os.unlink(old)
            except OSError:
                pass


class _BrGasMech(ctypes.Structure):
    _fields_ = [
        ("S", ctypes.c_int64),
        ("R", ctypes.c_int64),
        ("nu_f", ctypes.POINTER(ctypes.c_double)),
        ("nu_r", ctypes.POINTER(ctypes.c_double)),
        ("log_A", ctypes.POINTER(ctypes.c_double)),
        ("beta", ctypes.POINTER(ctypes.c_double)),
        ("Ea", ctypes.POINTER(ctypes.c_double)),
        ("eff", ctypes.POINTER(ctypes.c_double)),
        ("has_tb", ctypes.POINTER(ctypes.c_double)),
        ("has_falloff", ctypes.POINTER(ctypes.c_double)),
        ("log_A0", ctypes.POINTER(ctypes.c_double)),
        ("beta0", ctypes.POINTER(ctypes.c_double)),
        ("Ea0", ctypes.POINTER(ctypes.c_double)),
        ("has_troe", ctypes.POINTER(ctypes.c_double)),
        ("troe", ctypes.POINTER(ctypes.c_double)),
        ("has_sri", ctypes.POINTER(ctypes.c_double)),
        ("sri", ctypes.POINTER(ctypes.c_double)),
        ("rev_mask", ctypes.POINTER(ctypes.c_double)),
        ("sign_A", ctypes.POINTER(ctypes.c_double)),
        ("has_rev", ctypes.POINTER(ctypes.c_double)),
        ("log_A_rev", ctypes.POINTER(ctypes.c_double)),
        ("beta_rev", ctypes.POINTER(ctypes.c_double)),
        ("Ea_rev", ctypes.POINTER(ctypes.c_double)),
        ("sign_A_rev", ctypes.POINTER(ctypes.c_double)),
        ("plog_P", ctypes.c_int64),
        ("has_plog", ctypes.POINTER(ctypes.c_double)),
        ("plog_lnp", ctypes.POINTER(ctypes.c_double)),
        ("plog_logA", ctypes.POINTER(ctypes.c_double)),
        ("plog_beta", ctypes.POINTER(ctypes.c_double)),
        ("plog_Ea", ctypes.POINTER(ctypes.c_double)),
        ("cheb_NT", ctypes.c_int64),
        ("cheb_NP", ctypes.c_int64),
        ("has_cheb", ctypes.POINTER(ctypes.c_double)),
        ("cheb_coef", ctypes.POINTER(ctypes.c_double)),
        ("cheb_invT", ctypes.POINTER(ctypes.c_double)),
        ("cheb_logP", ctypes.POINTER(ctypes.c_double)),
        ("cheb_si_ln", ctypes.POINTER(ctypes.c_double)),
        ("coeffs", ctypes.POINTER(ctypes.c_double)),
        ("T_mid", ctypes.POINTER(ctypes.c_double)),
        ("molwt", ctypes.POINTER(ctypes.c_double)),
        ("kc_compat", ctypes.c_int32),
        ("int_stoich", ctypes.c_int32),
    ]


class _BrSurfMech(ctypes.Structure):
    _fields_ = [
        ("R", ctypes.c_int64),
        ("Sg", ctypes.c_int64),
        ("Ss", ctypes.c_int64),
        ("nu_f_gas", ctypes.POINTER(ctypes.c_double)),
        ("nu_r_gas", ctypes.POINTER(ctypes.c_double)),
        ("nu_f_surf", ctypes.POINTER(ctypes.c_double)),
        ("nu_r_surf", ctypes.POINTER(ctypes.c_double)),
        ("expo_gas", ctypes.POINTER(ctypes.c_double)),
        ("expo_surf", ctypes.POINTER(ctypes.c_double)),
        ("log_A", ctypes.POINTER(ctypes.c_double)),
        ("beta", ctypes.POINTER(ctypes.c_double)),
        ("Ea", ctypes.POINTER(ctypes.c_double)),
        ("cov_eps", ctypes.POINTER(ctypes.c_double)),
        ("stick", ctypes.POINTER(ctypes.c_double)),
        ("stick_s0", ctypes.POINTER(ctypes.c_double)),
        ("stick_molwt", ctypes.POINTER(ctypes.c_double)),
        ("mwc", ctypes.POINTER(ctypes.c_double)),
        ("site_density", ctypes.c_double),
        ("site_coordination", ctypes.POINTER(ctypes.c_double)),
        ("molwt_gas", ctypes.POINTER(ctypes.c_double)),
        ("int_expo", ctypes.c_int32),
    ]


class _BrStats(ctypes.Structure):
    _fields_ = [
        ("t", ctypes.c_double),
        ("status", ctypes.c_int32),
        ("pad", ctypes.c_int32),
        ("n_steps", ctypes.c_int64),
        ("n_rejected", ctypes.c_int64),
        ("n_rhs", ctypes.c_int64),
        ("n_jac", ctypes.c_int64),
        ("n_lu", ctypes.c_int64),
    ]


_RHS_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_double,
                           ctypes.POINTER(ctypes.c_double),
                           ctypes.POINTER(ctypes.c_double))

_DP = ctypes.POINTER(ctypes.c_double)
_I64P = ctypes.POINTER(ctypes.c_int64)


def load_library():
    """Build (if stale) and load the shared library; cached per process."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SRC):
            raise NativeUnavailable(f"native source missing: {_SRC}")
        # the .so name embeds a content hash of the source (_so_path), so
        # existence alone proves freshness — no mtime comparison, which
        # wheel-extracted archive timestamps would defeat
        if not os.path.exists(_SO):
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            raise NativeUnavailable(str(e)) from e
        lib.br_gas_rhs.restype = None
        lib.br_gas_rhs.argtypes = [ctypes.POINTER(_BrGasMech),
                                   ctypes.c_double, _DP, _DP]
        lib.br_bdf.restype = ctypes.c_int32
        lib.br_bdf.argtypes = [
            _RHS_CB, ctypes.c_void_p, ctypes.c_int64, _DP,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int64, ctypes.c_double, _DP, _DP, _DP, ctypes.c_int64,
            _I64P, ctypes.POINTER(_BrStats)]
        lib.br_solve_gas_bdf.restype = ctypes.c_int32
        lib.br_solve_gas_bdf.argtypes = [
            ctypes.POINTER(_BrGasMech), ctypes.c_double, _DP,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int64, ctypes.c_double, _DP, _DP, _DP, ctypes.c_int64,
            _I64P, ctypes.POINTER(_BrStats)]
        lib.br_surface_rates.restype = None
        lib.br_surface_rates.argtypes = [
            ctypes.POINTER(_BrSurfMech), ctypes.c_double, ctypes.c_double,
            _DP, _DP, _DP, _DP]
        lib.br_surf_rhs.restype = None
        lib.br_surf_rhs.argtypes = [
            ctypes.POINTER(_BrSurfMech), ctypes.POINTER(_BrGasMech),
            ctypes.c_double, ctypes.c_double, ctypes.c_int32, _DP, _DP]
        lib.br_solve_surf_bdf.restype = ctypes.c_int32
        lib.br_solve_surf_bdf.argtypes = [
            ctypes.POINTER(_BrSurfMech), ctypes.POINTER(_BrGasMech),
            ctypes.c_double, ctypes.c_double, ctypes.c_int32, _DP,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int64, ctypes.c_double, _DP, _DP, _DP, ctypes.c_int64,
            _I64P, ctypes.POINTER(_BrStats)]
        _lib = lib
        return lib


def available():
    """True iff the native runtime builds and loads on this host."""
    try:
        load_library()
        return True
    except NativeUnavailable:
        return False


def _carr(x):
    a = np.ascontiguousarray(np.asarray(x, dtype=np.float64))
    return a, a.ctypes.data_as(_DP)


def _pack_mech(gm, thermo, kc_compat):
    """Pack GasMechanism + ThermoTable into a _BrGasMech struct.

    Returns (struct, keepalive_list) — the caller must keep the list alive
    for the duration of any native call using the struct.
    """
    keep = []
    m = _BrGasMech()
    m.S = len(gm.species)
    m.R = len(gm.equations)
    for field, src in [
        ("nu_f", gm.nu_f), ("nu_r", gm.nu_r), ("log_A", gm.log_A),
        ("beta", gm.beta), ("Ea", gm.Ea), ("eff", gm.eff),
        ("has_tb", gm.has_tb), ("has_falloff", gm.has_falloff),
        ("log_A0", gm.log_A0), ("beta0", gm.beta0), ("Ea0", gm.Ea0),
        ("has_troe", gm.has_troe), ("troe", gm.troe),
        ("has_sri", gm.has_sri), ("sri", gm.sri),
        ("rev_mask", gm.rev_mask), ("sign_A", gm.sign_A),
        ("has_rev", gm.has_rev), ("log_A_rev", gm.log_A_rev),
        ("beta_rev", gm.beta_rev), ("Ea_rev", gm.Ea_rev),
        ("sign_A_rev", gm.sign_A_rev), ("has_plog", gm.has_plog),
        ("plog_lnp", gm.plog_lnp), ("plog_logA", gm.plog_logA),
        ("plog_beta", gm.plog_beta), ("plog_Ea", gm.plog_Ea),
        ("has_cheb", gm.has_cheb), ("cheb_coef", gm.cheb_coef),
        ("cheb_invT", gm.cheb_invT), ("cheb_logP", gm.cheb_logP),
        ("cheb_si_ln", gm.cheb_si_ln),
        ("coeffs", thermo.coeffs),
        ("T_mid", thermo.T_mid), ("molwt", thermo.molwt),
    ]:
        arr, ptr = _carr(src)
        keep.append(arr)
        setattr(m, field, ptr)
    m.plog_P = int(gm.plog_lnp.shape[1]) if gm.any_plog else 0
    m.cheb_NT = int(gm.cheb_coef.shape[1]) if gm.any_cheb else 0
    m.cheb_NP = int(gm.cheb_coef.shape[2]) if gm.any_cheb else 0
    m.kc_compat = 1 if kc_compat else 0
    m.int_stoich = 1 if gm.int_stoich else 0
    return m, keep


def _pack_surf(sm, molwt_gas):
    """Pack a SurfaceMechanism into a _BrSurfMech struct (+ keepalives)."""
    keep = []
    m = _BrSurfMech()
    m.R = len(sm.equations)
    m.Sg = len(sm.gas_species)
    m.Ss = len(sm.species)
    for field, src in [
        ("nu_f_gas", sm.nu_f_gas), ("nu_r_gas", sm.nu_r_gas),
        ("nu_f_surf", sm.nu_f_surf), ("nu_r_surf", sm.nu_r_surf),
        ("expo_gas", sm.expo_gas), ("expo_surf", sm.expo_surf),
        ("log_A", sm.log_A), ("beta", sm.beta), ("Ea", sm.Ea),
        ("cov_eps", sm.cov_eps), ("stick", sm.stick),
        ("stick_s0", sm.stick_s0), ("stick_molwt", sm.stick_molwt),
        ("mwc", sm.mwc), ("site_coordination", sm.site_coordination),
        ("molwt_gas", molwt_gas),
    ]:
        arr, ptr = _carr(src)
        keep.append(arr)
        setattr(m, field, ptr)
    m.site_density = float(np.asarray(sm.site_density))
    m.int_expo = 1 if sm.int_expo else 0
    return m, keep


def surface_rates(sm, T, p, mole_fracs, theta):
    """Native surface production rates (sdot_gas, sdot_surf) [mol/m^2/s]
    (same semantics as ops.surface_kinetics.production_rates); a
    cross-implementation test oracle."""
    lib = load_library()
    molwt_stub = np.ones(len(sm.gas_species))
    m, keep = _pack_surf(sm, molwt_stub)
    x_arr, x_ptr = _carr(mole_fracs)
    th_arr, th_ptr = _carr(theta)
    sg = np.empty(len(sm.gas_species))
    ss = np.empty(len(sm.species))
    lib.br_surface_rates(ctypes.byref(m), float(T), float(p), x_ptr, th_ptr,
                         sg.ctypes.data_as(_DP), ss.ctypes.data_as(_DP))
    del keep, x_arr, th_arr
    return sg, ss


def surf_rhs(sm, thermo, T, Asv, y, gm=None, asv_quirk=True,
             kc_compat=False):
    """Native surface(+gas) reactor RHS over y = [rho_k, theta_k]
    (same semantics as ops.rhs.make_surface_rhs)."""
    lib = load_library()
    m, keep = _pack_surf(sm, np.asarray(thermo.molwt))
    gm_ref = None
    if gm is not None:
        gmm, keep_g = _pack_mech(gm, thermo, kc_compat)
        keep += keep_g
        gm_ref = ctypes.byref(gmm)
    y_arr, y_ptr = _carr(y)
    out = np.empty_like(y_arr)
    lib.br_surf_rhs(ctypes.byref(m), gm_ref, float(T), float(Asv),
                    1 if asv_quirk else 0, y_ptr, out.ctypes.data_as(_DP))
    del keep, y_arr
    return out


@dataclasses.dataclass
class NativeResult:
    """Outcome of a native BDF solve (mirrors solver.sdirk.SolveResult)."""

    t: float
    y: np.ndarray
    status: str          # "Success" | "MaxIters" | "DtLessThanMin"
    n_accepted: int
    n_rejected: int
    n_rhs: int
    n_jac: int
    n_lu: int
    ts: np.ndarray       # (n_saved,) accepted-step times
    ys: np.ndarray       # (n_saved, S) accepted-step states


_STATUS = {0: "Success", 2: "MaxIters", 3: "DtLessThanMin"}


def gas_rhs(gm, thermo, T, y, kc_compat=False):
    """Native evaluation of the gas RHS dy/dt (same semantics as
    ops.rhs.make_gas_rhs); used as a cross-implementation test oracle."""
    lib = load_library()
    m, keep = _pack_mech(gm, thermo, kc_compat)
    y_arr, y_ptr = _carr(y)
    if y_arr.shape != (len(gm.species),):
        raise ValueError(f"y has shape {y_arr.shape}, mechanism has "
                         f"{len(gm.species)} species")
    out = np.empty_like(y_arr)
    lib.br_gas_rhs(ctypes.byref(m), float(T), y_ptr, out.ctypes.data_as(_DP))
    del keep, y_arr
    return out


def _run(call, n, n_save):
    ts = np.empty(max(n_save, 1), dtype=np.float64)
    ys = np.empty((max(n_save, 1), n), dtype=np.float64)
    y_out = np.empty(n, dtype=np.float64)
    n_saved = ctypes.c_int64(0)
    stats = _BrStats()
    call(y_out, ts, ys, n_saved, stats)
    k = int(n_saved.value)
    return NativeResult(
        t=float(stats.t), y=y_out, status=_STATUS.get(stats.status, "Failure"),
        n_accepted=int(stats.n_steps), n_rejected=int(stats.n_rejected),
        n_rhs=int(stats.n_rhs), n_jac=int(stats.n_jac), n_lu=int(stats.n_lu),
        ts=ts[:k].copy(), ys=ys[:k].copy(),
    )


def solve_gas_bdf(gm, thermo, T, y0, t0, t1, *, rtol=1e-6, atol=1e-10,
                  max_steps=200_000, first_step=0.0, n_save=0,
                  kc_compat=False):
    """Integrate the gas-phase reactor with the native BDF (CVODE-class):
    the ``backend="cpu"`` solve path and the bench baseline integrator."""
    lib = load_library()
    m, keep = _pack_mech(gm, thermo, kc_compat)
    y0_arr, y0_ptr = _carr(y0)
    if y0_arr.shape != (len(gm.species),):
        raise ValueError(f"y0 has shape {y0_arr.shape}, mechanism has "
                         f"{len(gm.species)} species")
    n = y0_arr.shape[0]

    def call(y_out, ts, ys, n_saved, stats):
        lib.br_solve_gas_bdf(
            ctypes.byref(m), float(T), y0_ptr, float(t0), float(t1),
            float(rtol), float(atol), int(max_steps), float(first_step),
            y_out.ctypes.data_as(_DP), ts.ctypes.data_as(_DP),
            ys.ctypes.data_as(_DP), int(n_save), ctypes.byref(n_saved),
            ctypes.byref(stats))

    res = _run(call, n, n_save)
    del keep, y0_arr
    return res


def solve_surf_bdf(sm, thermo, T, Asv, y0, t0, t1, *, gm=None,
                   asv_quirk=True, kc_compat=False, rtol=1e-6, atol=1e-10,
                   max_steps=200_000, first_step=0.0, n_save=0):
    """Integrate the surface (and optionally coupled gas) reactor with the
    native BDF — the all-native ``backend="cpu"`` path for surfchem modes
    (role of the reference's CVODE solve, /root/reference/src/BatchReactor.jl:210)."""
    lib = load_library()
    m, keep = _pack_surf(sm, np.asarray(thermo.molwt))
    gm_ref = None
    if gm is not None:
        gmm, keep_g = _pack_mech(gm, thermo, kc_compat)
        keep += keep_g
        gm_ref = ctypes.byref(gmm)
    y0_arr, y0_ptr = _carr(y0)
    n = len(sm.gas_species) + len(sm.species)
    if y0_arr.shape != (n,):
        raise ValueError(f"y0 has shape {y0_arr.shape}, expected ({n},)")

    def call(y_out, ts, ys, n_saved, stats):
        lib.br_solve_surf_bdf(
            ctypes.byref(m), gm_ref, float(T), float(Asv),
            1 if asv_quirk else 0, y0_ptr, float(t0), float(t1),
            float(rtol), float(atol), int(max_steps), float(first_step),
            y_out.ctypes.data_as(_DP), ts.ctypes.data_as(_DP),
            ys.ctypes.data_as(_DP), int(n_save), ctypes.byref(n_saved),
            ctypes.byref(stats))

    res = _run(call, n, n_save)
    del keep, y0_arr
    return res


def solve_bdf(rhs, y0, t0, t1, *, rtol=1e-6, atol=1e-10, max_steps=200_000,
              first_step=0.0, n_save=0):
    """Generic native BDF over a Python RHS callback ``rhs(t, y) -> dy``.

    The callback crosses the ctypes boundary per evaluation, so this path is
    for correctness work (UDF chemistry, solver cross-checks), not speed —
    use :func:`solve_gas_bdf` for the all-native hot path.
    """
    lib = load_library()
    y0_arr, y0_ptr = _carr(y0)
    n = y0_arr.shape[0]
    err: list = []

    @_RHS_CB
    def cb(_ctx, t, y_ptr, dy_ptr):
        if err:  # user code already failed: poison without re-entering it
            bad = np.full(n, np.nan)
            ctypes.memmove(dy_ptr, bad.ctypes.data, n * 8)
            return
        try:
            y = np.ctypeslib.as_array(y_ptr, shape=(n,))
            dy = np.asarray(rhs(float(t), y.copy()), dtype=np.float64)
            if dy.shape != (n,):
                raise ValueError(f"rhs returned shape {dy.shape}, "
                                 f"expected ({n},)")
            ctypes.memmove(dy_ptr, dy.ctypes.data, n * 8)
        except Exception as e:  # noqa: BLE001 — can't raise through C
            err.append(e)
            bad = np.full(n, np.nan)
            ctypes.memmove(dy_ptr, bad.ctypes.data, n * 8)

    def call(y_out, ts, ys, n_saved, stats):
        lib.br_bdf(
            cb, None, n, y0_ptr, float(t0), float(t1), float(rtol),
            float(atol), int(max_steps), float(first_step),
            y_out.ctypes.data_as(_DP), ts.ctypes.data_as(_DP),
            ys.ctypes.data_as(_DP), int(n_save), ctypes.byref(n_saved),
            ctypes.byref(stats))

    res = _run(call, n, n_save)
    if err:
        raise err[0]
    del y0_arr
    return res
