"""Daemon front-ends: stdlib HTTP (``POST /solve``) and stdin-JSONL.

The HTTP face is the ``obs.MetricsServer`` shape grown a solve
endpoint: a ``ThreadingHTTPServer`` background thread (``port=0`` binds
an ephemeral port, read it from ``.port``/``.url`` — the no-fixed-port
discipline the whole test/CI tier uses) serving

* ``POST /solve`` — one schema request in, one response out.  The
  handler thread blocks on the request's future (each HTTP connection
  is its own thread; the solver never waits on HTTP).  Scheduler
  rejections map to ``503`` (``overloaded`` / ``draining`` — the
  backpressure contract is an HTTP status, not a silent queue), schema
  rejections to ``400``, a dead stream to ``500``.
* ``GET /metrics`` — the session registry's Prometheus exposition (the
  PR-9 live plane: ``br_sweep_occupancy``, backlog depth, and the
  ``serve_*`` queue gauges move between mid-flight scrapes, and the
  ``br_serve_stage_seconds`` latency-stage histograms show the live
  queue-wait vs solve-time distributions — docs/observability.md
  "Histograms").
* ``GET /healthz`` — registry liveness + the session's serving block
  (fingerprint, warm state, compile count, drain flag).

The JSONL face (:func:`serve_jsonl`) reads one request object per stdin
line and writes responses as they resolve (out-of-order completion is
the point — ids correlate), then drains on EOF.  Both faces answer
every accepted request exactly once; ``scripts/serve.py`` wires them to
SIGTERM-with-grace teardown (``resilience.run_guarded`` supervision).
"""

import http.server
import json
import threading
from concurrent import futures

from . import schema
from .scheduler import SchedulerReject

#: brlint host-concurrency lint (analysis/concurrency.py): the request
#: plumbing runs on HTTP handler threads (each connection is its own
#: thread — cross-module thread entry is declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("ServingServer.solve", "ServingServer.healthz")


class _ServeHandler(http.server.BaseHTTPRequestHandler):
    front = None    # bound per-server via a subclass (ServingServer)

    def _send(self, code, obj, ctype="application/json"):
        body = (json.dumps(obj) + "\n").encode() if not isinstance(
            obj, bytes) else obj
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib handler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200, self.front.session.registry.prometheus()
                           .encode(),
                           ctype="text/plain; version=0.0.4; "
                                 "charset=utf-8")
            elif path == "/healthz":
                self._send(200, self.front.healthz())
            else:
                self.send_error(404, "unknown path (GET /metrics, "
                                     "GET /healthz, POST /solve)")
        except Exception as e:  # noqa: BLE001 — a scrape must never
            #                     kill the serving thread
            self.send_error(500, f"{type(e).__name__}: {e}")

    def do_POST(self):  # noqa: N802 — stdlib handler contract
        path = self.path.split("?", 1)[0]
        if path not in ("/solve", "/mechanism"):
            self.send_error(404, "POST /solve and POST /mechanism are "
                                 "the write paths")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            obj = json.loads(raw.decode() or "null")
        except (ValueError, UnicodeDecodeError) as e:
            self._send(400, schema.error_response(
                None, "invalid", f"request body is not JSON: {e}"))
            return
        if path == "/mechanism":
            code, resp = self.front.upload(obj)
        else:
            code, resp = self.front.solve(obj)
        self._send(code, resp)

    def log_message(self, *_args):
        pass    # request logging rides the obs recorder, not stderr


class ServingServer:
    """Module doc.  Composes a :class:`~.session.SolverSession` and a
    :class:`~.scheduler.Scheduler` behind one HTTP port; use as a
    context manager, or ``start()``/``close()`` for a long-lived
    daemon (``scripts/serve.py``)."""

    def __init__(self, session, scheduler, port=0, host="127.0.0.1",
                 request_timeout=None, store=None, membership=None):
        self.session = session
        self.scheduler = scheduler
        #: multi-mechanism store (docs/serving.md): routes per-request
        #: ``mech`` keys and accepts ``POST /mechanism`` uploads; None
        #: keeps the single-mechanism daemon byte-compatible
        self.store = store
        #: fleet membership (:class:`~..fleet.MemberRegistration`) —
        #: when set, ``close()`` runs the drain handshake: the draining
        #: flag goes up FIRST so the router stops sending new work (and
        #: fails over in-flight retries) while this daemon finishes what
        #: it already accepted, and the member deregisters LAST, after
        #: the final request has answered
        self.membership = membership
        self.request_timeout = float(
            session.spec.request_timeout_s if request_timeout is None
            else request_timeout)
        self._requested = (host, int(port))
        self._server = None
        self._thread = None
        self._ids = _IdSource()

    # ---- request plumbing (shared by HTTP and tests) ----------------------
    def _route(self, obj):
        """(session, scheduler) for a raw request object's ``mech`` key
        — routed BEFORE validation, which needs the target session's
        species list."""
        mech = obj.get("mech") if isinstance(obj, dict) else None
        if self.store is None:
            if mech is not None:
                from .session import UnknownMechanism

                raise UnknownMechanism(
                    f"mech={mech!r} routing needs the multi-mechanism "
                    f"store; this daemon serves one mechanism")
            return self.session, self.scheduler
        return self.store.resolve(mech)

    def solve(self, obj):
        """One request object -> ``(http_status, response_object)``."""
        from .session import UnknownMechanism

        rid = obj.get("id") if isinstance(obj, dict) else None
        try:
            session, scheduler = self._route(obj)
        except UnknownMechanism as e:
            return 404, schema.error_response(
                rid, "unknown_mechanism", e.args[0])
        try:
            req = schema.validate_request(
                obj, species=session.species,
                rtol_default=session.spec.rtol,
                atol_default=session.spec.atol,
                default_id=self._ids.next(),
                max_lanes=session.spec.max_lanes_per_request,
                energy_modes=getattr(session.spec, "energy_modes", ()))
        except ValueError as e:
            return 400, schema.error_response(rid, "invalid", e)
        try:
            future = scheduler.submit(req)
        except SchedulerReject as e:
            return 503, schema.error_response(req.id, e.code, e)
        try:
            result = future.result(timeout=self.request_timeout)
        except SchedulerReject as e:       # pragma: no cover — defensive
            return 503, schema.error_response(req.id, e.code, e)
        except Exception as e:  # noqa: BLE001 — stream death / timeout:
            #                     the request is answered, loudly
            return 500, schema.error_response(
                req.id, "internal", f"{type(e).__name__}: {e}")
        return 200, schema.ok_response(
            req.id, session.render_result(result))

    def upload(self, obj):
        """One mechanism-upload object -> ``(http_status, response)``
        (``POST /mechanism``; grammar schema.validate_upload)."""
        rid = obj.get("id") if isinstance(obj, dict) else None
        if self.store is None:
            return 404, schema.error_response(
                rid, "invalid", "this daemon runs without a mechanism "
                "store (scripts/serve.py --store)")
        try:
            upload = schema.validate_upload(obj)
        except ValueError as e:
            return 400, schema.error_response(rid, "invalid", e)
        try:
            _fp, info = self.store.add_upload(upload)
        except ValueError as e:
            return 400, schema.error_response(upload["id"], "invalid", e)
        except Exception as e:  # noqa: BLE001 — answered, loudly
            return 500, schema.error_response(
                upload["id"], "internal", f"{type(e).__name__}: {e}")
        return 200, schema.ok_response(upload["id"], info)

    def healthz(self):
        h = self.session.registry.healthz()
        queued, inflight = self.scheduler.depth()
        h["serving"] = {**self.session.healthz_extra(),
                        "queued_lanes": queued,
                        "inflight_lanes": inflight,
                        # the request-tracing plane's alarm config
                        # (docs/observability.md "Request tracing"):
                        # operators read whether slow-request
                        # flight-recorder dumps are armed, and at what
                        # threshold, off the daemon itself
                        "slow_request_s": float(getattr(
                            self.session.spec, "slow_request_s", 0.0)
                            or 0.0),
                        "draining": bool(self.scheduler._draining)}
        if self.store is not None:
            h["serving"]["store"] = self.store.healthz()
        if self.membership is not None:
            h["serving"]["fleet"] = {
                "member": self.membership.name,
                "fleet_dir": self.membership.fleet_dir,
            }
        return h

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        if self._server is not None:
            return self
        self.scheduler.start()
        handler = type("_BoundServeHandler", (_ServeHandler,),
                       {"front": self})
        self._server = http.server.ThreadingHTTPServer(
            self._requested, handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="br-serve-http")
        self._thread.start()
        rec = self.session.recorder
        if rec is not None:
            rec.event("serving_bound", host=self._server.server_address[0],
                      port=self.port)
        return self

    @property
    def port(self):
        if self._server is None:
            raise RuntimeError("ServingServer not started")
        return self._server.server_address[1]

    @property
    def url(self):
        return (f"http://{self._server.server_address[0]}:{self.port}")

    def close(self, drain_timeout=None):
        """Drain the scheduler (every accepted request answers), then
        stop the HTTP thread.  Fleet mode adds the drain handshake
        around that: mark draining first, deregister last."""
        if self.membership is not None:
            self.membership.mark_draining()
        if self.store is not None:
            self.store.drain(drain_timeout)
        self.scheduler.drain(drain_timeout)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread.join()
            self._server = self._thread = None
        if self.membership is not None:
            self.membership.deregister()

    def __enter__(self):
        return self.start()

    def __exit__(self, *_exc):
        self.close()


class _IdSource:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def next(self):
        with self._lock:
            self._n += 1
            return f"req-{self._n}"


def serve_jsonl(session, scheduler, infile, outfile):
    """The stdin-JSONL front-end (module doc): one request object per
    input line, one response object per output line as each resolves
    (out-of-order; correlate by id).  Returns ``(accepted, rejected)``
    after EOF drains the queue."""
    write_lock = threading.Lock()
    ids = _IdSource()
    accepted = rejected = 0
    pending = []

    def _emit(obj):
        with write_lock:
            outfile.write(json.dumps(obj) + "\n")
            outfile.flush()

    for line in infile:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
            req = schema.validate_request(
                obj, species=session.species,
                rtol_default=session.spec.rtol,
                atol_default=session.spec.atol,
                default_id=ids.next(),
                max_lanes=session.spec.max_lanes_per_request,
                energy_modes=getattr(session.spec, "energy_modes", ()))
        except ValueError as e:
            rejected += 1
            _emit(schema.error_response(
                obj.get("id") if isinstance(obj, dict) else None,
                "invalid", e))
            continue
        try:
            future = scheduler.submit(req)
        except SchedulerReject as e:
            rejected += 1
            _emit(schema.error_response(req.id, e.code, e))
            continue
        accepted += 1

        def _done(fut, rid=req.id):
            try:
                _emit(schema.ok_response(
                    rid, session.render_result(fut.result())))
            except Exception as e:  # noqa: BLE001 — answered, loudly
                _emit(schema.error_response(
                    rid, "internal", f"{type(e).__name__}: {e}"))

        future.add_done_callback(_done)
        pending.append(future)
    scheduler.drain()
    futures.wait(pending)   # belt over braces: every response line has
    #                         been emitted by its done-callback
    return accepted, rejected
