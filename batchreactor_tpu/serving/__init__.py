"""Sweep-as-a-service: the resident solver daemon (docs/serving.md).

The serving plane assembles four existing subsystems into a long-lived
process that answers a live stream of reactor-condition requests from
ONE warm, continuously-batched device program:

* warm AOT bucket executables (:mod:`~batchreactor_tpu.aot` — a warmed
  session serves with ``compiles == 0``);
* the device-resident streaming driver with live lane admission
  (``parallel/sweep.py`` ``admission=`` + the ``_feed``/``_on_harvest``
  hooks this PR adds — a request arriving mid-stream rides lanes freed
  by finished conditions);
* explicit admission-control backpressure and graceful drain
  (:mod:`.scheduler` — ``overloaded``/``draining`` rejections, never
  silent queueing; SIGTERM answers everything accepted);
* the live telemetry plane (:mod:`~batchreactor_tpu.obs.live` —
  ``GET /metrics`` mid-flight, flight-recorder postmortems).

Layering (request path)::

    schema.validate_request     # loud, versioned JSON grammar
      -> Scheduler.submit       # queue + backpressure; future per request
        -> SolverSession.stream # one resident program per pack key
          -> on_harvest         # future resolves as the LAST lane lands

Entry points: ``scripts/serve.py`` (HTTP / stdin-JSONL daemon),
``scripts/serve_bench.py`` (seeded Poisson load + latency percentiles),
``scripts/warm_cache.py --spec serve.json`` (pre-bake the session's
program set).  Import is lazy jax-wise: :mod:`.schema`,
:mod:`.scheduler` and :mod:`.client` are numpy/stdlib-only, so clients
and the scheduler tests never pay a device.
"""

from .schema import (SCHEMA_VERSION, TRACE_CTX_VERSION,  # noqa: F401
                     Request, error_response, ok_response,
                     trace_ctx_payload, validate_request,
                     validate_trace_ctx, validate_upload)
from .scheduler import (Draining, Overloaded, RequestResult,  # noqa: F401
                        Scheduler, SchedulerReject)
from .client import (ServeError, SolveClient, poisson_trace,  # noqa: F401
                     stitched_attribution, trace_summary,
                     with_trace_ctx)

__all__ = [
    "SCHEMA_VERSION", "Request", "validate_request", "validate_upload",
    "error_response",
    "ok_response", "Scheduler", "SchedulerReject", "Overloaded",
    "Draining", "RequestResult", "SolverSession", "SessionSpec",
    "SessionStore", "UnknownMechanism",
    "load_spec", "ServingServer", "serve_jsonl", "SolveClient",
    "ServeError", "poisson_trace", "trace_summary",
    "TRACE_CTX_VERSION", "validate_trace_ctx", "trace_ctx_payload",
    "with_trace_ctx", "stitched_attribution",
]

_LAZY = {"SolverSession": "session", "SessionSpec": "session",
         "SessionStore": "session", "UnknownMechanism": "session",
         "load_spec": "session", "ServingServer": "server",
         "serve_jsonl": "server"}


def __getattr__(name):
    # session/server import jax (through api._sweep_fns); loading them
    # lazily keeps `from batchreactor_tpu.serving import SolveClient`
    # device-free for remote clients
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
