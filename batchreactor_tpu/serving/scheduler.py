"""The coalescer: a thread-safe request queue feeding resident streams.

Requests land here (:meth:`Scheduler.submit`) from any number of
front-end threads, are grouped by *pack key* ``(t1, rtol, atol,
energy)`` — ``t1`` and the conditions are traced operands of one shared
program; ``rtol``/``atol``/``energy`` are static and therefore a
distinct compiled program (an energy lane's state is one row wider) —
and are packed into the PR-8 admission backlog of a resident streaming
sweep: the scheduler's worker thread runs one *epoch* per active pack
key through ``session.stream``, whose

* ``feed(n_space, idle)`` hook pulls newly-arrived requests of the same
  key INTO the live backlog (``parallel/sweep.py`` ``_feed`` contract)
  — continuous admission, the LLM-inference-server shape: a request
  arriving mid-stream rides freed lanes without a fresh dispatch;
* ``on_harvest(gids, payload)`` hook resolves each request's future the
  moment its LAST lane harvests — results are un-shuffled to request
  lane order via the gid map (the driver already un-shuffles slot ->
  global-index; the scheduler maps global index -> (request, offset)).

An epoch ends when its feed goes idle past ``idle_timeout_s`` (the
resident program is released; the next request re-enters through the
warmed AOT cache at zero compiles), when a different pack key has work
waiting (fairness rotation), or at drain.

**Multi-epoch capacity** (``SessionSpec.resident_epochs`` — docs/
serving.md "Capacity levers"): with ``resident_epochs=N`` the scheduler
runs N worker threads, each hosting its own resident streaming epoch,
all pulling from the ONE shared pack-key queue.  The spray is
pull-based: each epoch's seed/feed pops up to its own free-slot depth
under the scheduler lock, so pops are disjoint and exactly-once
resolution needs no new machinery — a request belongs to exactly the
epoch that popped it, and its harvest un-shuffle stays epoch-local.
Lanes a secondary epoch pulls count ``epoch_spray``; each epoch
publishes its driver gauges under its own live source (``sweep-e0``,
``sweep-e1``, ...) so per-epoch occupancy survives the registry merge.
``resident_epochs=1`` is byte-identical to the single-worker scheduler
(same thread name, same stream call signature, zero spray).

**Backpressure is explicit**: ``submit`` REJECTS with
:class:`Overloaded` once ``max_queue_lanes`` lanes are queued
(un-admitted) — never silent unbounded queueing — and with
:class:`Draining` after :meth:`drain` began; accepted requests are
always answered exactly once (drain finishes the backlog first, and a
dead stream resolves its requests with ``internal`` errors rather than
dropping them).

**Request-lifecycle tracing** (obs/trace.py — docs/observability.md
"Request tracing"): every accepted request carries a
:class:`~..obs.trace.RequestTrace` marked lock-cheaply at the points
that already exist — ``submitted`` in :meth:`Scheduler.submit`,
``coalesced`` in ``_pop_work_locked``, ``admitted`` on joining the
epoch backlog, ``first_harvest`` in the harvest hook (idempotent),
``stalled`` under the injected fault, ``resolved`` at
``_resolve``/``_fail``.  Resolution folds the per-stage durations into
the ``serve_stage_seconds`` histograms (the live ``/metrics``
decomposition), emits the ``request_trace`` JSONL event, and — past
``spec.slow_request_s`` — a structured ``slow_request`` event that
arms the flight recorder.

The module imports stdlib + numpy only (no jax): the session object
carries all device work, so the scheduler invariants are unit-testable
against a fake session (tests/test_serving.py).
"""

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs.trace import RequestTrace

#: brlint host-concurrency lint (analysis/concurrency.py): the producer
#: surface is called from arbitrary front-end threads (HTTP handler
#: threads, the JSONL reader) — declared here because cross-module
#: thread entry is declared, not inferred
_BRLINT_THREAD_ENTRIES = ("Scheduler.submit", "Scheduler.drain",
                          "Scheduler.depth", "Scheduler.start")


class SchedulerReject(RuntimeError):
    """A request the scheduler refused; ``code`` is the response error
    code (schema.ERROR_CODES)."""

    code = "internal"


class Overloaded(SchedulerReject):
    """Queue bound reached — admission-control backpressure."""

    code = "overloaded"


class Draining(SchedulerReject):
    """The scheduler is draining (SIGTERM path): in-flight work still
    answers, new work is refused."""

    code = "draining"


@dataclasses.dataclass
class RequestResult:
    """What a request's future resolves to: per-lane arrays in REQUEST
    lane order (the harvest un-shuffle target), plus provenance and
    wall time.  ``serving/session.py render_result`` turns this into
    the response payload."""

    request: object
    t: np.ndarray
    y: np.ndarray
    status: np.ndarray
    n_accepted: np.ndarray
    n_rejected: np.ndarray
    stats: dict | None
    observed: dict | None
    provenance: list
    elapsed_s: float
    #: the request's lifecycle trace (obs/trace.py) — stage marks the
    #: scheduler captured; ``render_result`` exports it behind the
    #: request's ``trace=`` key
    trace: object = None


class _Work:
    """One accepted request in flight: its future, pre-packed lane
    blocks, per-lane result buffers, the harvest countdown, and the
    lifecycle trace (obs/trace.py — constructing it marks
    ``submitted``; the other stages mark at the existing scheduler
    points, one clock read each, no locks of their own: the trace is
    touched by the submit thread once and the worker thereafter)."""

    __slots__ = ("request", "future", "y0", "cfg", "t", "y", "status",
                 "n_acc", "n_rej", "stats", "observed", "remaining",
                 "trace", "stall_s", "seq")

    def __init__(self, request, y0, cfg, seq):
        self.request = request
        self.future = Future()
        self.y0 = y0
        self.cfg = cfg
        k = request.n_lanes
        self.t = np.full((k,), np.nan)
        self.y = np.array(y0, copy=True)
        self.status = np.full((k,), -1, dtype=np.int32)
        self.n_acc = np.zeros((k,), dtype=np.int64)
        self.n_rej = np.zeros((k,), dtype=np.int64)
        self.stats = None
        self.observed = None
        self.remaining = k
        self.trace = RequestTrace(request.id,
                                  pack_key=request.pack_key(), lanes=k)
        # inherited distributed-trace context (schema.Request
        # trace_ctx — docs/observability.md "Fleet tracing"): adopt
        # the fleet identity so this daemon's stage marks export as
        # child spans of ONE cross-host trace; getattr-gated so
        # pre-ctx request stubs (tests) keep working
        ctx = getattr(request, "trace_ctx", None)
        if ctx is not None:
            self.trace.adopt(*ctx)
        self.stall_s = 0.0
        self.seq = seq


class Scheduler:
    """Module doc.  ``session`` provides ``request_lanes`` /
    ``stream`` / ``spec`` (a real :class:`~.session.SolverSession`, or
    any stub with that surface — the invariant tests use one)."""

    def __init__(self, session, *, max_queue_lanes=None,
                 idle_timeout=None):
        self.session = session
        spec = session.spec
        self.max_queue_lanes = int(
            spec.max_queue_lanes if max_queue_lanes is None
            else max_queue_lanes)
        self.idle_timeout = float(
            spec.idle_timeout_s if idle_timeout is None else idle_timeout)
        self._cond = threading.Condition()
        self._queues = {}            # pack key -> deque[_Work]
        self._queued_lanes = 0
        self._inflight_lanes = 0
        self._draining = False
        self._closed = False
        self._seq = 0
        # capacity plane (module doc): N resident epochs, one worker
        # thread each.  The session resolves "auto" (one per local
        # device) to an int before the scheduler sees it; a stub
        # session without the knob runs single-epoch
        epochs = getattr(session, "resident_epochs", None)
        if epochs is None:
            epochs = getattr(spec, "resident_epochs", 1)
        try:
            epochs = int(epochs)
        except (TypeError, ValueError):
            epochs = 1
        self.epochs = max(epochs, 1)
        self._worker = threading.Thread(target=self._run, args=(0,),
                                        daemon=True,
                                        name="br-serve-scheduler")
        self._workers = [self._worker] + [
            threading.Thread(target=self._run, args=(k,), daemon=True,
                             name=f"br-serve-scheduler-{k}")
            for k in range(1, self.epochs)]
        self._started = False

    # ---- producer side ----------------------------------------------------
    def start(self):
        # under the lock: two front-end threads racing an unguarded
        # check-then-set could both see _started False and double-start
        # the worker (Thread.start raises RuntimeError on the loser) —
        # caught by the brlint host-concurrency lint, regression in
        # tests/test_serving.py
        with self._cond:
            if not self._started:
                self._started = True
                for w in self._workers:
                    w.start()
        return self

    def submit(self, request):
        """Queue one validated request; returns its ``Future`` (resolves
        to a :class:`RequestResult`).  Raises :class:`Overloaded` /
        :class:`Draining` — the caller maps those onto 503 responses."""
        rec = getattr(self.session, "recorder", None)
        # pack lanes OUTSIDE the lock (y0 construction does real work);
        # an invalid composition raises here, before anything is queued
        y0, cfg = self.session.request_lanes(request)
        with self._cond:
            if self._draining or self._closed:
                if rec is not None:
                    rec.counter("serve_rejects_draining")
                raise Draining("scheduler is draining; request refused")
            if self._queued_lanes + request.n_lanes > self.max_queue_lanes:
                if rec is not None:
                    rec.counter("serve_rejects_overload")
                raise Overloaded(
                    f"admission queue full ({self._queued_lanes} + "
                    f"{request.n_lanes} lanes > bound "
                    f"{self.max_queue_lanes}); retry with backoff")
            work = _Work(request, y0, cfg, self._seq)
            self._seq += 1
            self._queues.setdefault(request.pack_key(),
                                    collections.deque()).append(work)
            self._queued_lanes += request.n_lanes
            if rec is not None:
                rec.counter("serve_requests")
                rec.counter("serve_lanes", request.n_lanes)
            self._publish_locked()
            self._cond.notify_all()
        return work.future

    def drain(self, timeout=None):
        """Stop accepting, answer everything accepted, stop the worker.
        Returns True when the queue fully drained within ``timeout``."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        if not self._started:
            # no worker ever ran: anything queued can never be served —
            # answer it loudly rather than stranding the futures
            with self._cond:
                stranded = [w for q in self._queues.values() for w in q]
                self._queues.clear()
                self._queued_lanes = 0
                self._closed = True
            for w in stranded:
                w.future.set_exception(Draining(
                    "scheduler closed before it ever started"))
            return True
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for w in self._workers:
            w.join(None if deadline is None
                   else max(deadline - time.monotonic(), 0.0))
        done = not any(w.is_alive() for w in self._workers)
        with self._cond:
            self._closed = True
        return done

    close = drain

    def depth(self):
        """(queued_lanes, inflight_lanes) — the backpressure gauges."""
        with self._cond:
            return self._queued_lanes, self._inflight_lanes

    def _publish_locked(self):
        reg = getattr(self.session, "registry", None)
        if reg is None:
            return
        reg.publish("serve", gauges={
            "serve_queue_lanes": int(self._queued_lanes),
            "serve_inflight_lanes": int(self._inflight_lanes),
            "serve_pending_requests": int(
                sum(len(q) for q in self._queues.values())),
            "serve_draining": int(self._draining),
            "resident_epochs": int(self.epochs)})

    # ---- worker side ------------------------------------------------------
    def _next_key_locked(self):
        """The pack key of the oldest queued request (FIFO fairness
        across keys), or None."""
        best = None
        for key, q in self._queues.items():
            if q and (best is None or q[0].seq < best[1]):
                best = (key, q[0].seq)
        return best[0] if best else None

    def _run(self, epoch=0):
        while True:
            with self._cond:
                key = self._next_key_locked()
                while key is None and not self._draining:
                    self._cond.wait()
                    key = self._next_key_locked()
                if key is None:       # draining and empty: done
                    self._publish_locked()
                    break
            self._run_epoch(key, epoch)
        with self._cond:
            self._publish_locked()

    def _pop_work_locked(self, key, n_space, epoch=0):
        """Pop whole queued requests of ``key`` up to ~``n_space`` lanes
        (always at least one when any is queued) — the rest stays
        QUEUED, which is what keeps the ``max_queue_lanes`` bound
        meaningful while a stream is resident.  Pops are the spray:
        each epoch pulls up to its own free-slot depth under THIS lock,
        so concurrent epochs never double-pop a request."""
        q = self._queues.get(key)
        works, lanes = [], 0
        while q and (not works or lanes + q[0].request.n_lanes
                     <= max(int(n_space), 1)):
            w = q.popleft()
            w.trace.mark("coalesced")   # left the queue into an epoch
            works.append(w)
            lanes += w.request.n_lanes
        if q is not None and not q:
            del self._queues[key]
        self._queued_lanes -= lanes
        self._inflight_lanes += lanes
        if works:
            if epoch:
                rec = getattr(self.session, "recorder", None)
                if rec is not None:
                    rec.counter("epoch_spray", lanes)
            self._publish_locked()
        return works

    def _run_epoch(self, key, epoch=0):
        """One resident stream over one pack key (module doc);
        ``epoch`` is this worker's slot in the multi-epoch spray."""
        from ..resilience import inject

        rec = getattr(self.session, "recorder", None)
        if rec is not None:
            rec.counter("serve_epochs")
        # pack key: (t1, rtol, atol) pre-energy, (t1, rtol, atol,
        # energy) since — the star-unpack keeps fake-session tests and
        # any 3-tuple producer working
        t1, rtol, atol, *rest = key
        energy = rest[0] if rest else None
        gid_map = []      # gid -> (_Work, lane offset); driver gids are
        #                   append-order over (initial backlog + feeds)
        epoch_works = []

        def _admit(works):
            for w in works:
                w.trace.mark("admitted")   # joins the resident backlog
                w.stall_s = inject.slow_request_delay(w.request.id)
                epoch_works.append(w)
                for off in range(w.request.n_lanes):
                    gid_map.append((w, off))

        def _stack(works):
            y0 = np.concatenate([w.y0 for w in works])
            cfg = {k: np.concatenate([np.asarray(w.cfg[k])
                                      for w in works])
                   for k in works[0].cfg}
            return y0, cfg

        # seed the epoch with ~one resident program's worth of lanes;
        # the rest stays queued and flows in through the feed
        cap = getattr(self.session, "bucket_cap", None)
        coalesce = float(getattr(self.session.spec, "coalesce_s", 0.0)
                         or 0.0)
        adaptive = bool(getattr(self.session.spec, "coalesce_adaptive",
                                False))
        with self._cond:
            if coalesce > 0:
                # batching window (SessionSpec.coalesce_s): give
                # concurrent arrivals a beat to fill the resident
                # program before the seed is cut — counted against
                # THIS epoch's pack key (other keys' lanes cannot ride
                # this program and must not cut its window short)
                def _key_lanes():
                    return sum(w.request.n_lanes
                               for w in self._queues.get(key, ()))

                start = time.monotonic()
                window = coalesce
                while (_key_lanes() < (cap or 1)
                       and not self._draining):
                    window = coalesce
                    if adaptive:
                        # ROADMAP 2d (SessionSpec.coalesce_adaptive):
                        # the window the queue has EARNED — fill
                        # fraction x coalesce_s, re-evaluated on every
                        # wakeup.  Mostly-free resident slots mean the
                        # batch was never coming: seed now, let
                        # latecomers ride the live feed
                        free = (self.epochs * (cap or 1)
                                - self._inflight_lanes)
                        if _key_lanes() <= max(free, 0):
                            # the resident tier can absorb everything
                            # queued RIGHT NOW: waiting buys no batch
                            # density, only queue-wait — collapse the
                            # window to zero
                            window = 0.0
                            break
                        window = coalesce * (_key_lanes()
                                             / float(cap or 1))
                    left = start + window - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                # the adaptive lever's telemetry (docs/observability.md
                # "Request tracing"): the window this epoch CLOSED at —
                # a gauge for the live scrape and a histogram so the
                # chosen-window distribution sits next to the stage
                # waterfalls it shapes (obs/counters.py
                # COALESCE_HIST_KEYS)
                if rec is not None:
                    rec.observe("coalesce_window_s", window,
                                mode=("adaptive" if adaptive
                                      else "fixed"))
                reg = getattr(self.session, "registry", None)
                if reg is not None:
                    reg.publish("coalesce", gauges={
                        "coalesce_window_s": round(window, 6)})
            seed = self._pop_work_locked(
                key, cap if cap else self.max_queue_lanes, epoch)
            if not seed:    # drained away (or sprayed onto a sibling
                return      # epoch) while coalescing
        _admit(seed)
        y0s, cfgs = _stack(seed)

        def feed(n_space, idle):
            with self._cond:
                deadline = time.monotonic() + self.idle_timeout
                while True:
                    works = self._pop_work_locked(key, n_space, epoch)
                    if works:
                        break
                    other = any(k != key and q
                                for k, q in self._queues.items())
                    if self._draining or other:
                        return None     # rotate / drain: close the feed
                    if not idle:
                        # zero-lane rows keep each cfg leaf's trailing
                        # shape (the energy _atol_scale leaf is (k, n),
                        # not (k,)) so the driver's concatenate stays
                        # shape-consistent
                        return (np.zeros((0,) + y0s.shape[1:]),
                                {k: np.zeros(
                                    (0,) + np.asarray(cfgs[k]).shape[1:])
                                 for k in cfgs})
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return None     # idle past the timeout: release
                        #                 the resident program
                    self._cond.wait(left)
            _admit(works)
            return _stack(works)

        def on_harvest(gids, payload):
            finished = []
            for row, gid in enumerate(np.asarray(gids)):
                w, off = gid_map[int(gid)]
                w.trace.mark("first_harvest")   # idempotent: FIRST wins
                w.t[off] = payload["t"][row]
                w.y[off] = payload["y"][row]
                w.status[off] = payload["status"][row]
                w.n_acc[off] = payload["n_accepted"][row]
                w.n_rej[off] = payload["n_rejected"][row]
                if "stats" in payload:
                    if w.stats is None:
                        w.stats = {
                            k: np.zeros((w.request.n_lanes,)
                                        + np.asarray(v).shape[1:],
                                        dtype=np.asarray(v).dtype)
                            for k, v in payload["stats"].items()}
                    for k, v in payload["stats"].items():
                        w.stats[k][off] = np.asarray(v)[row]
                if "observed" in payload:
                    if w.observed is None:
                        w.observed = {
                            k: np.zeros((w.request.n_lanes,)
                                        + np.asarray(v).shape[1:],
                                        dtype=np.asarray(v).dtype)
                            for k, v in payload["observed"].items()}
                    for k, v in payload["observed"].items():
                        w.observed[k][off] = np.asarray(v)[row]
                w.remaining -= 1
                if w.remaining == 0:
                    finished.append(w)
            for w in finished:
                self._resolve(w)

        try:
            # energy rides only when set, so fake sessions (and any
            # pre-energy stream signature) keep working; the per-epoch
            # live source likewise rides only at resident_epochs > 1 —
            # single-epoch keeps today's stream call byte-identical
            ekw = {} if energy is None else {"energy": energy}
            if self.epochs > 1:
                ekw["live_source"] = f"sweep-e{epoch}"
            self.session.stream(y0s, cfgs, t1=t1, rtol=rtol, atol=atol,
                                on_harvest=on_harvest, feed=feed, **ekw)
        except BaseException as e:  # noqa: BLE001 — an epoch must not
            #                         kill the scheduler thread; every
            #                         admitted request is answered
            if rec is not None:
                rec.event("fault", kind="serve_epoch_error",
                          error=f"{type(e).__name__}: {e}")
        finally:
            # a stream that died (or a driver bug) must still answer
            # every admitted request exactly once
            for w in epoch_works:
                if not w.future.done():
                    self._fail(w, RuntimeError(
                        "serving stream ended before this request "
                        "harvested (see the daemon's fault events)"))

    def _settle_locked(self, w):
        self._inflight_lanes -= w.request.n_lanes
        self._publish_locked()

    def _resolve(self, w):
        from ..solver.sdirk import SUCCESS

        if w.stall_s:
            # deterministic slow_request fault injection: the stall sits
            # between admission and harvest-resolution, exactly where a
            # slow consumer would (resilience/inject.py); the trace's
            # ``stalled`` mark opens here, so ``stalled -> resolved``
            # carries the injected delay in the waterfall
            w.trace.mark("stalled")
            rec = getattr(self.session, "recorder", None)
            if rec is not None:
                rec.counter("serve_stalls")
                rec.event("fault", kind="slow_request",
                          request=w.request.id, delay_s=w.stall_s)
            time.sleep(w.stall_s)
        w.trace.mark("resolved")
        prov = ["success" if int(c) == int(SUCCESS) else "failed"
                for c in w.status]
        result = RequestResult(
            request=w.request, t=w.t, y=w.y, status=w.status,
            n_accepted=w.n_acc, n_rejected=w.n_rej, stats=w.stats,
            observed=w.observed, provenance=prov,
            elapsed_s=w.trace.total_s(), trace=w.trace)
        with self._cond:
            self._settle_locked(w)
        rec = getattr(self.session, "recorder", None)
        if rec is not None:
            rec.counter("serve_answered")
            self._record_trace(rec, w.trace)
        w.future.set_result(result)

    def _record_trace(self, rec, trace):
        """Fold one resolved trace onto the obs plane: the per-stage
        ``serve_stage_seconds`` histograms (``{stage="total"}`` is the
        request latency — the old summed ``serve_latency_s`` counter,
        migrated), the ``request_trace`` JSONL event, and — past the
        spec's ``slow_request_s`` threshold — a structured
        ``slow_request`` event that arms the flight recorder with a
        counter snapshot (obs/live.py), so a latency excursion leaves
        postmortem evidence behind."""
        total = trace.total_s()
        for stage, dur in trace.segments().items():
            rec.observe("serve_stage_seconds", dur, stage=stage)
        rec.observe("serve_stage_seconds", total, stage="total")
        rec.event("request_trace", **trace.to_attrs())
        slow = float(getattr(self.session.spec, "slow_request_s", 0.0)
                     or 0.0)
        if slow and total >= slow:
            from ..obs.live import flight_note_counters

            rec.event("slow_request", request=trace.request_id,
                      total_s=round(total, 6), threshold_s=slow,
                      stages={s: round(v, 6)
                              for s, v in trace.segments().items()})
            flight_note_counters(rec)

    def _fail(self, w, exc):
        w.trace.mark("resolved")
        with self._cond:
            self._settle_locked(w)
        rec = getattr(self.session, "recorder", None)
        if rec is not None:
            rec.counter("serve_failed")
            # failed requests export their trace (a stream death's
            # timing is postmortem evidence) but never enter the
            # latency histograms — a half-served request's wall would
            # poison the distributions the gate bands check
            rec.event("request_trace", failed=True, **w.trace.to_attrs())
        w.future.set_exception(exc)
