"""Versioned request/response schema of the serving plane.

One request asks the daemon to solve ``k >= 1`` reactor conditions (the
request's *lanes*) to a common horizon under common tolerances — the
programmatic ``batch_reactor_sweep`` tuple ``(T, p, X, t1, rtol/atol)``
as JSON.  Validation follows the ``api.py`` loudness convention: every
malformed field is a specific ``ValueError`` naming the field and the
expected grammar, unknown keys are rejected (a typo'd knob must not be
silently ignored), and the validated form is a frozen :class:`Request`
the scheduler packs from — nothing downstream re-checks.

Request JSON (``POST /solve`` body, or one stdin-JSONL line)::

    {"v": 1,                      # optional; must be 1 when present
     "id": "run-42/7",            # optional; the server assigns one
     "T": 1100.0 | [..k..],       # K       (scalars broadcast over lanes)
     "p": 101325.0 | [..k..],     # Pa      (optional, default 1e5)
     "X": {"H2": 0.3, ...},       # mole fractions, scalar or [..k..]
     "t1": 0.05,                  # s, the integration horizon
     "rtol": 1e-6, "atol": 1e-10, # optional (session defaults); NOTE: a
                                  # non-default pair compiles a new
                                  # program on first use (docs/serving.md)
     "Asv": 1.0,                  # optional surface-coupling parameter
     "n_save": 0,                 # optional; only 0 is accepted — the
                                  # admission gear streams final states,
                                  # not trajectories (loud error)
     "mech": "user-mech-7",       # optional mechanism routing key
                                  # (multi-mechanism store; upload id or
                                  # fingerprint prefix — docs/serving.md)
     "trace": true,               # optional; the ok response gains a
                                  # versioned "trace" section — the
                                  # request-lifecycle stage waterfall
                                  # (obs/trace.py; docs/serving.md).
                                  # Absent/false responses are
                                  # byte-identical to pre-trace ones
     "energy": "adiabatic_v"}     # optional non-isothermal mode
                                  # (docs/energy.md: adiabatic_v /
                                  # adiabatic_p; the session spec must
                                  # list it in solver.energy_modes —
                                  # energy lanes answer with per-lane
                                  # "T" and "ignition_delay")

Responses are ``{"v": 1, "id": ..., "status": "ok" | "error", ...}``:
``ok`` carries per-lane ``t`` / ``solver_status`` / ``provenance`` /
final mole fractions ``x`` (+ ``tau`` when the session runs an ignition
observer, solver counter ``stats`` when it runs instrumented, and the
``trace`` stage waterfall when the request asked for it);
``error`` carries ``{"code", "message"}`` with the codes ``invalid``
(schema/species rejection), ``overloaded`` (admission-control
backpressure — the queue bound is a promise, never silent queueing),
``draining`` (SIGTERM received; in-flight work still answers), and
``internal`` (the stream died under the request).  Nothing here imports
jax — the schema is shared by the client, the jsonl front-end, and the
scheduler tests.
"""

import dataclasses

import numpy as np

SCHEMA_VERSION = 1

#: the only keys a request may carry (anything else is a loud error)
_REQUEST_KEYS = ("v", "id", "T", "p", "X", "t1", "rtol", "atol", "Asv",
                 "n_save", "mech", "energy", "trace")

#: the non-None energy-mode literals (energy/eqns.py ENERGY_MODES,
#: duplicated here because the schema imports no jax-reaching module —
#: tests pin the two tuples equal)
ENERGY_MODES = ("adiabatic_v", "adiabatic_p")

#: error codes a response may carry
ERROR_CODES = ("invalid", "overloaded", "draining", "internal",
               "unknown_mechanism")

#: the only keys a mechanism upload may carry (POST /mechanism body —
#: docs/serving.md "Mechanism upload"); ``mech``/``therm`` are the
#: INLINE file texts (CHEMKIN-II / NASA-7), not paths: the daemon owns
#: no shared filesystem with its clients
_UPLOAD_KEYS = ("v", "id", "mech", "therm", "warm")


@dataclasses.dataclass(frozen=True)
class Request:
    """A validated solve request: per-lane condition arrays (all
    broadcast to ``n_lanes``) plus the scalar pack key ``(t1, rtol,
    atol)`` the scheduler coalesces on."""

    id: str
    T: np.ndarray          # (k,) float64, K
    p: np.ndarray          # (k,) float64, Pa
    Asv: np.ndarray        # (k,) float64
    X: dict                # {species: (k,) float64}
    t1: float
    rtol: float
    atol: float
    #: mechanism routing key (multi-mechanism store — docs/serving.md):
    #: an upload id or fingerprint prefix; None = the session default.
    #: Routing happens BEFORE scheduling (each mechanism owns its own
    #: scheduler), so it is not part of pack_key.
    mech: str | None = None
    #: non-isothermal reactor mode (docs/energy.md): None = isothermal,
    #: else an :data:`ENERGY_MODES` literal.  Part of pack_key — an
    #: energy lane carries the trailing T state row, so it can never
    #: share a resident program with isothermal lanes.
    energy: str | None = None
    #: request-lifecycle trace export (obs/trace.py): True adds the
    #: versioned ``"trace"`` stage-waterfall section to the ok
    #: response.  Pure response shaping — never part of pack_key, and
    #: the server-side capture runs either way (the histograms are
    #: always-on); False/absent responses are byte-identical to
    #: pre-trace ones.
    trace: bool = False

    @property
    def n_lanes(self):
        return int(self.T.shape[0])

    def pack_key(self):
        """Requests sharing this key can ride one resident stream: t1
        is a traced operand of the shared program, rtol/atol/energy are
        static (a distinct combination is a distinct compiled
        program — the energy state is one row wider)."""
        return (self.t1, self.rtol, self.atol, self.energy)


def _as_lane_array(name, value, rid):
    """One condition field -> (k,) float64 (k=1 for scalars), loudly."""
    try:
        arr = np.asarray(value, dtype=np.float64)
    except (TypeError, ValueError):
        raise ValueError(
            f"request {rid!r}: {name} must be a number or a flat list "
            f"of numbers; got {value!r}") from None
    if arr.ndim > 1:
        raise ValueError(
            f"request {rid!r}: {name} must be a number or a FLAT list; "
            f"got shape {arr.shape}")
    arr = np.atleast_1d(arr)
    if arr.size == 0:
        raise ValueError(f"request {rid!r}: {name} must not be empty")
    if not np.all(np.isfinite(arr)):
        raise ValueError(
            f"request {rid!r}: {name} must be finite; got {value!r}")
    return arr


def _positive_scalar(name, value, rid):
    try:
        v = float(value)
    except (TypeError, ValueError):
        raise ValueError(f"request {rid!r}: {name} must be a number; "
                         f"got {value!r}") from None
    if not np.isfinite(v) or v <= 0:
        raise ValueError(f"request {rid!r}: {name} must be a finite "
                         f"positive number; got {value!r}")
    return v


def validate_request(obj, *, species=None, rtol_default=1e-6,
                     atol_default=1e-10, default_id=None,
                     max_lanes=None, energy_modes=()):
    """Validate one request JSON object into a :class:`Request` (module
    doc grammar); every rejection is a ``ValueError`` naming the field.

    ``species`` (the session's gas species tuple) makes unknown ``X``
    keys a validation error here instead of a failure deep in lane
    packing; ``max_lanes`` bounds one request's lane count (a request
    larger than the whole admission queue could never be accepted);
    ``energy_modes`` is the tuple of non-isothermal modes THIS session
    warmed (``SessionSpec.energy_modes``) — a request asking for an
    un-warmed mode rejects here, before anything queues.
    """
    if not isinstance(obj, dict):
        raise ValueError(f"request must be a JSON object; got "
                         f"{type(obj).__name__}")
    unknown = sorted(set(obj) - set(_REQUEST_KEYS))
    if unknown:
        raise ValueError(f"unknown request key(s) {unknown}; known keys: "
                         f"{list(_REQUEST_KEYS)}")
    v = obj.get("v", SCHEMA_VERSION)
    if v != SCHEMA_VERSION:
        raise ValueError(f"unsupported schema version {v!r} (this server "
                         f"speaks v{SCHEMA_VERSION})")
    rid = obj.get("id", default_id)
    if rid is None:
        raise ValueError("request needs an 'id' (or the caller must "
                         "supply default_id)")
    rid = str(rid)

    for key in ("T", "X", "t1"):
        if key not in obj:
            raise ValueError(f"request {rid!r}: missing required key "
                             f"{key!r}")
    T = _as_lane_array("T", obj["T"], rid)
    if np.any(T <= 0):
        raise ValueError(f"request {rid!r}: T must be positive Kelvin")
    p = _as_lane_array("p", obj.get("p", 1e5), rid)
    if np.any(p <= 0):
        raise ValueError(f"request {rid!r}: p must be positive Pa")
    Asv = _as_lane_array("Asv", obj.get("Asv", 1.0), rid)

    X_in = obj["X"]
    if not isinstance(X_in, dict) or not X_in:
        raise ValueError(f"request {rid!r}: X must be a non-empty "
                         f"{{species: fraction}} object")
    X = {}
    for name, val in X_in.items():
        arr = _as_lane_array(f"X[{name}]", val, rid)
        if np.any(arr < 0):
            raise ValueError(f"request {rid!r}: X[{name}] must be "
                             f"non-negative mole fractions")
        X[str(name)] = arr
    if species is not None:
        idx = {s.upper() for s in species}
        missing = sorted(n for n in X if n.upper() not in idx)
        if missing:
            raise ValueError(
                f"request {rid!r}: composition species {missing} not in "
                f"the session mechanism (species: {list(species)[:6]}...)")

    # lanes = broadcast of every per-lane field; mismatched non-1
    # lengths are a packing ambiguity, not a broadcast
    lengths = {int(a.shape[0])
               for a in (T, p, Asv, *X.values()) if a.shape[0] != 1}
    if len(lengths) > 1:
        raise ValueError(
            f"request {rid!r}: per-lane fields disagree on lane count "
            f"{sorted(lengths)}; scalars broadcast, lists must match")
    k = lengths.pop() if lengths else 1
    if max_lanes is not None and k > int(max_lanes):
        raise ValueError(
            f"request {rid!r}: {k} lanes exceeds the per-request bound "
            f"{int(max_lanes)}; split the request")

    t1 = _positive_scalar("t1", obj["t1"], rid)
    rtol = _positive_scalar("rtol", obj.get("rtol", rtol_default), rid)
    atol = _positive_scalar("atol", obj.get("atol", atol_default), rid)
    n_save = obj.get("n_save", 0)
    if n_save not in (0, None):
        raise ValueError(
            f"request {rid!r}: n_save={n_save!r} is not supported — the "
            f"streaming admission gear returns final states only "
            f"(n_save=0); run a trajectory solve through batch_reactor")

    mech = obj.get("mech")
    if mech is not None and (not isinstance(mech, str) or not mech):
        raise ValueError(
            f"request {rid!r}: mech must be a non-empty mechanism id "
            f"string; got {mech!r}")

    trace = obj.get("trace", False)
    if not isinstance(trace, bool):
        raise ValueError(
            f"request {rid!r}: trace must be a JSON boolean; got "
            f"{trace!r} (true = add the stage-waterfall section to "
            f"the response)")

    energy = obj.get("energy")
    if energy is not None:
        if energy not in ENERGY_MODES:
            # name the accepted literals (the api.py loudness
            # convention — a typo'd mode must say what IS accepted)
            raise ValueError(
                f"request {rid!r}: unknown energy mode {energy!r}; "
                f"accepted: {list(ENERGY_MODES)} (omit the key for an "
                f"isothermal solve)")
        if tuple(energy_modes or ()) and energy not in energy_modes:
            raise ValueError(
                f"request {rid!r}: energy mode {energy!r} is not "
                f"enabled on this session (warmed modes: "
                f"{list(energy_modes)}); add it to the session spec's "
                f"solver.energy_modes")
        if not energy_modes:
            raise ValueError(
                f"request {rid!r}: energy mode {energy!r} is not "
                f"enabled on this session (no solver.energy_modes in "
                f"the session spec)")
        if "Asv" in obj and np.any(Asv != 1.0):
            # incompatible-knob rejection (the n_save convention below):
            # Asv couples surface chemistry, energy mode is gas-only
            # adiabatic — a silently ignored Asv would report physics
            # that never ran
            raise ValueError(
                f"request {rid!r}: Asv is a surface-coupling parameter; "
                f"energy={energy!r} runs gas-only adiabatic chemistry — "
                f"drop Asv or the energy key")

    bcast = (lambda a: np.broadcast_to(a, (k,)).copy()
             if a.shape[0] == 1 else a)
    X = {n: bcast(a) for n, a in X.items()}
    # every lane needs a positive total: a zero-sum composition would
    # make the initial state 0/0 = NaN (mole_to_mass normalizes by the
    # mixture mass) — the lane would burn its whole device budget and
    # answer NaNs, which bare-JSON serializers reject
    total = sum(X.values())
    if np.any(total <= 0):
        bad = int(np.argmax(total <= 0))
        raise ValueError(
            f"request {rid!r}: lane {bad} composition sums to "
            f"{float(total[bad])!r}; mole fractions must sum > 0 on "
            f"every lane")
    return Request(id=rid, T=bcast(T), p=bcast(p), Asv=bcast(Asv),
                   X=X, t1=t1, rtol=rtol, atol=atol, mech=mech,
                   energy=energy, trace=trace)


def validate_upload(obj, *, default_id=None):
    """Validate one mechanism-upload JSON object (``POST /mechanism``;
    grammar: docs/serving.md "Mechanism upload") into a plain dict
    ``{"id", "mech", "therm", "warm"}`` — the ``api.py`` loudness
    convention: unknown keys reject, every malformed field is a specific
    ``ValueError``.  ``mech``/``therm`` are inline CHEMKIN-II / NASA-7
    texts; parsing errors surface later, from the store's compile, as
    ``invalid`` responses naming the parser's complaint."""
    if not isinstance(obj, dict):
        raise ValueError(f"mechanism upload must be a JSON object; got "
                         f"{type(obj).__name__}")
    unknown = sorted(set(obj) - set(_UPLOAD_KEYS))
    if unknown:
        raise ValueError(f"unknown upload key(s) {unknown}; known keys: "
                         f"{list(_UPLOAD_KEYS)}")
    v = obj.get("v", SCHEMA_VERSION)
    if v != SCHEMA_VERSION:
        raise ValueError(f"unsupported schema version {v!r} (this server "
                         f"speaks v{SCHEMA_VERSION})")
    uid = obj.get("id", default_id)
    if uid is None or not isinstance(uid, str) or not uid:
        raise ValueError("mechanism upload needs a non-empty string 'id' "
                         "(the mech routing key of later solve requests)")
    for key in ("mech", "therm"):
        text = obj.get(key)
        if not isinstance(text, str) or not text.strip():
            raise ValueError(
                f"upload {uid!r}: {key!r} must be the non-empty inline "
                f"file text ({'CHEMKIN-II mechanism' if key == 'mech' else 'NASA-7 thermo database'})")
    warm = obj.get("warm", True)
    if not isinstance(warm, bool):
        raise ValueError(f"upload {uid!r}: warm must be a boolean; got "
                         f"{warm!r}")
    return {"id": uid, "mech": obj["mech"], "therm": obj["therm"],
            "warm": warm}


def error_response(rid, code, message):
    """An ``error`` response object (module doc)."""
    if code not in ERROR_CODES:
        raise ValueError(f"unknown error code {code!r}; known: "
                         f"{ERROR_CODES}")
    return {"v": SCHEMA_VERSION, "id": rid, "status": "error",
            "error": {"code": code, "message": str(message)}}


def ok_response(rid, payload):
    """An ``ok`` response object around a per-lane result payload."""
    return {"v": SCHEMA_VERSION, "id": rid, "status": "ok", **payload}
