"""SolverSession: the warm, device-resident half of the serving daemon.

A session owns everything that must exist BEFORE the first request can
be answered fast: the frozen mechanism bundle (parsed once), the exact
sweep callables ``batch_reactor_sweep`` would build (``api._sweep_fns``
— identical construction => identical traced programs => identical
AOT/persistent-cache keys), the bucket ladder and solver config, and
the obs plane (recorder + live registry + a session-wide
``CompileWatch``).  :meth:`warmup` drives the :mod:`~batchreactor_tpu.
aot` registry over the ladder — including the streaming compaction
program via the warmup ``backlog`` knob — so a warmed session serves
its first request with ``compiles == 0`` (the acceptance surface
``scripts/serve_bench.py`` and the tier-1 e2e assert).

Sessions are keyed by :attr:`fingerprint` (mechanism fingerprint — the
same content hash the AOT registry and checkpoint resume trust), so the
ROADMAP-5 multi-mechanism store is a ``{fingerprint: SolverSession}``
dict away: everything request-scoped lives in the scheduler, everything
mechanism-scoped lives here.

The session spec (``serve.json``) is the ONE configuration artifact the
daemon and ``scripts/warm_cache.py --spec`` share: both resolve it
through :func:`load_spec` / :meth:`SolverSession.warmup_specs`, so the
warmer provably bakes the same program keys the server will run
(mechanism fingerprint x solver flags x ladder — drift is structurally
impossible, not just discouraged).
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np

from .schema import Request  # noqa: F401  (re-exported for callers)

#: brlint host-concurrency lint (analysis/concurrency.py): these run on
#: other modules' threads — request packing on HTTP front-end threads,
#: the stream on the scheduler worker, the health block on handler
#: threads (cross-module thread entry is declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("SolverSession.request_lanes",
                          "SolverSession.stream",
                          "SolverSession.render_result",
                          "SolverSession.healthz_extra")

#: spec keys, per section — unknown keys are loud errors (the schema.py
#: convention: a typo'd knob must not be silently ignored)
_MECH_KEYS = ("mech", "therm")
_SOLVER_KEYS = ("method", "rtol", "atol", "jac_window", "linsolve",
                "setup_economy", "stale_tol", "segment_steps",
                "max_attempts", "stats", "ignition_marker",
                "ignition_mode", "mech_operands", "species_buckets",
                "reaction_buckets", "energy_modes")
_SERVE_KEYS = ("resident", "refill", "buckets", "poll_every",
               "max_queue_lanes", "idle_timeout_s", "request_timeout_s",
               "max_lanes_per_request", "coalesce_s",
               "coalesce_adaptive", "max_mechanisms",
               "slow_request_s", "resident_epochs", "mesh_resident",
               "upshift", "upshift_patience")


@dataclasses.dataclass(frozen=True)
class SessionSpec:
    """A validated serving session spec (``serve.json``).  ``mech`` /
    ``therm`` are resolved absolute paths; everything else is the
    solver/serve config with defaults applied."""

    mech: str
    therm: str
    # solver config (the sweep flag set — part of every program key)
    method: str = "bdf"
    rtol: float = 1e-6
    atol: float = 1e-10
    jac_window: object = None        # None = the platform rule
    linsolve: str = "auto"
    setup_economy: bool = False
    stale_tol: float = 0.3
    segment_steps: int = 64
    max_attempts: int = 200_000
    stats: bool = True
    ignition_marker: object = None
    ignition_mode: str = "half"
    #: mechanism-shape generalization (docs/performance.md
    #: "Mechanism-shape economy"): ``mech_operands=True`` pads the
    #: mechanism onto the ``species_buckets`` x ``reaction_buckets``
    #: (S, R) rung (pow2 ladders by default, the api.py rule) and lifts
    #: the tensors to traced operands — every mechanism in one rung then
    #: serves through ONE compiled executable, the multi-mechanism
    #: store's (SessionStore) zero-compile upload path
    mech_operands: bool = False
    species_buckets: object = None
    reaction_buckets: object = None
    #: non-isothermal serving (docs/energy.md): the tuple of energy-mode
    #: literals this session warms and serves — each mode is its own
    #: program family (the state grows the trailing T row), warmed per
    #: ladder rung alongside the isothermal set; a request's ``energy``
    #: key must name one of these (schema.validate_request) and joins
    #: its pack key, so energy and isothermal lanes never share a
    #: resident program.  ``()`` (default) serves isothermal only.
    energy_modes: tuple = ()
    # serve config (scheduler/capacity — NOT part of the program keys)
    resident: int = 8
    refill: object = 1
    buckets: object = "pow2"
    poll_every: int = 1
    max_queue_lanes: int = 256
    idle_timeout_s: float = 0.25
    request_timeout_s: float = 300.0
    max_lanes_per_request: object = None
    #: batching window: a fresh epoch waits up to this long for the
    #: queue to fill one resident program before seeding (the inference
    #: servers' max-batch-delay knob; 0 = dispatch immediately).  Lanes
    #: arriving after the seed still join through the live feed.
    coalesce_s: float = 0.0
    #: adaptive batching window (ROADMAP 2d): scale the effective
    #: coalesce window by the queue's fill fraction — an epoch whose
    #: pack key has most of the resident program's slots FREE seeds
    #: almost immediately (window ~ ``coalesce_s * queued/cap``, so an
    #: unsaturated trace stops paying max-batch-delay for batches that
    #: were never coming), while a nearly-full queue still waits up to
    #: the full window for the last slots.  Latecomers ride the live
    #: feed either way.  Off (False) keeps the fixed window — the
    #: bit-exactness e2e tests pin a full fixed window so every
    #: concurrent request provably joins one seed.
    coalesce_adaptive: bool = False
    #: multi-mechanism store capacity (SessionStore): resident sessions
    #: beyond this LRU-evict (their manifest entries unpin; the
    #: ``mech_evicted``/``aot_evictions`` counters record it)
    max_mechanisms: int = 8
    #: slow-request alarm threshold [s] (docs/observability.md "Request
    #: tracing"): a request whose server-side ``submitted -> resolved``
    #: wall reaches this emits a structured ``slow_request`` event with
    #: its stage decomposition and arms the flight recorder with a
    #: counter snapshot.  0 (default) disables the alarm; the
    #: histograms and per-request traces record regardless.
    slow_request_s: float = 0.0
    #: capacity plane (docs/serving.md "Capacity levers"): number of
    #: resident streaming epochs the scheduler runs concurrently, each
    #: a full ``resident``-slot program pulling from the one shared
    #: pack-key queue.  ``"auto"`` = one per local device.  1 (default)
    #: is byte-identical to the single-epoch scheduler.
    resident_epochs: object = 1
    #: mesh-sharded resident program: lay the streaming carry out with
    #: a NamedSharding over the batch dim so one epoch spans this many
    #: local devices (``True`` = all of them).  Buckets must divide
    #: over the mesh; ``None`` (default) keeps the single-device
    #: program byte-identical.
    mesh_resident: object = None
    #: resident-bucket up-shift autoscaling: the lane ceiling the
    #: resident program may climb to (along the warmed ``buckets``
    #: ladder) when backlog outgrows the current rung — the dual of the
    #: drain-time down-shift.  ``None`` (default) never up-shifts.
    upshift: object = None
    #: consecutive over-headroom polls before an up-shift fires (and
    #: the post-shift cooldown) — the hysteresis damping both shift
    #: directions against an oscillating backlog.
    upshift_patience: int = 2


def load_spec(source):
    """``serve.json`` -> :class:`SessionSpec`.  ``source`` is a path, a
    JSON string, or an already-parsed dict; relative mechanism paths
    resolve against the spec file's directory (a spec checked into a
    repo keeps working from any CWD).  Unknown keys at any level are
    loud ``ValueError``s."""
    base = os.getcwd()
    if isinstance(source, dict):
        obj = source
    else:
        text = str(source)
        if text.lstrip().startswith("{"):
            obj = json.loads(text)
        else:
            base = os.path.dirname(os.path.abspath(text))
            with open(text) as f:
                obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"session spec must be a JSON object; got "
                         f"{type(obj).__name__}")
    unknown = sorted(set(obj) - {"mechanism", "solver", "serve"})
    if unknown:
        raise ValueError(f"unknown session-spec section(s) {unknown}; "
                         f"known: ['mechanism', 'solver', 'serve']")
    mech_sec = obj.get("mechanism")
    if not isinstance(mech_sec, dict):
        raise ValueError("session spec needs a 'mechanism' section "
                         "{'mech': ..., 'therm': ...}")

    def _section(sec, known, name):
        unknown = sorted(set(sec) - set(known))
        if unknown:
            raise ValueError(f"unknown {name} key(s) {unknown}; known: "
                             f"{list(known)}")
        return dict(sec)

    mech_sec = _section(mech_sec, _MECH_KEYS, "mechanism")
    for key in _MECH_KEYS:
        if key not in mech_sec:
            raise ValueError(f"session spec mechanism section needs "
                             f"{key!r}")
    kw = {}
    kw.update(_section(obj.get("solver") or {}, _SOLVER_KEYS, "solver"))
    kw.update(_section(obj.get("serve") or {}, _SERVE_KEYS, "serve"))
    if isinstance(kw.get("buckets"), list):
        kw["buckets"] = tuple(int(b) for b in kw["buckets"])
    if kw.get("energy_modes") is not None:
        from .schema import ENERGY_MODES

        modes = tuple(kw["energy_modes"])
        bad = [m for m in modes if m not in ENERGY_MODES]
        if bad:
            raise ValueError(
                f"session spec: unknown energy mode(s) {bad}; "
                f"accepted: {list(ENERGY_MODES)}")
        kw["energy_modes"] = modes
    resolve = (lambda p: p if os.path.isabs(p)
               else os.path.normpath(os.path.join(base, p)))
    spec = SessionSpec(mech=resolve(mech_sec["mech"]),
                       therm=resolve(mech_sec["therm"]), **kw)
    if spec.method not in ("bdf", "sdirk"):
        raise ValueError(f"session spec: unknown method {spec.method!r}")
    if int(spec.resident) < 1:
        raise ValueError(f"session spec: resident must be >= 1, got "
                         f"{spec.resident!r}")
    if int(spec.segment_steps) < 1:
        raise ValueError(f"session spec: segment_steps must be >= 1, "
                         f"got {spec.segment_steps!r}")
    if int(spec.max_queue_lanes) < 1:
        raise ValueError(f"session spec: max_queue_lanes must be >= 1, "
                         f"got {spec.max_queue_lanes!r}")
    re_ = spec.resident_epochs
    if re_ != "auto" and (isinstance(re_, bool)
                          or not isinstance(re_, int) or re_ < 1):
        raise ValueError(f"session spec: resident_epochs must be an "
                         f"int >= 1 or 'auto', got {re_!r}")
    mr = spec.mesh_resident
    if mr is not None and mr is not True and mr is not False and (
            isinstance(mr, bool) or not isinstance(mr, int) or mr < 1):
        raise ValueError(f"session spec: mesh_resident must be null, "
                         f"true (all local devices), or an int >= 1; "
                         f"got {mr!r}")
    up = spec.upshift
    if up is not None and (isinstance(up, bool)
                           or not isinstance(up, int)
                           or up < int(spec.resident)):
        raise ValueError(f"session spec: upshift must be an int >= "
                         f"resident ({spec.resident}) — it is the lane "
                         f"CEILING the resident program may climb to; "
                         f"got {up!r}")
    if int(spec.upshift_patience) < 1:
        raise ValueError(f"session spec: upshift_patience must be >= 1, "
                         f"got {spec.upshift_patience!r}")
    return spec


class SolverSession:
    """Module doc.  Build with :func:`from_spec` (parses the mechanism)
    or directly from pre-built ``gm``/``thermo`` objects (tests, and
    callers that already hold the bundles)."""

    #: serving epochs are open-ended: the stream lives while its feed
    #: does, so the segment ceiling is a runaway bound, not a budget
    MAX_SEGMENTS = 1 << 30

    def __init__(self, gm, thermo, spec, recorder=None):
        from ..aot import mechanism_fingerprint, normalize_buckets, \
            resolve_bucket
        from ..api import _padded_mech, _segmented_builder, _sweep_fns, \
            resolve_jac_window
        from ..obs import CompileWatch, LiveRegistry, Recorder

        self.gm = gm
        self.thermo = thermo
        self.spec = spec
        self.species = tuple(thermo.species)
        self._sp_idx = {s.upper(): k for k, s in enumerate(self.species)}
        marker_idx = None
        if spec.ignition_marker is not None:
            key = str(spec.ignition_marker).upper()
            if key not in self._sp_idx:
                raise ValueError(
                    f"session spec: ignition_marker "
                    f"{spec.ignition_marker!r} not in the mechanism")
            marker_idx = self._sp_idx[key]
        # mechanism-shape resolution (api.py rule: operand mode defaults
        # both ladders to pow2) — the padded twins drive the kernels,
        # self.species/self.thermo stay LIVE for packing and rendering
        sb = spec.species_buckets
        rb = spec.reaction_buckets
        if spec.mech_operands:
            sb = "pow2" if sb is None else sb
            rb = "pow2" if rb is None else rb
        sb, rb = normalize_buckets(sb), normalize_buckets(rb)
        self.mech_shape = None
        self.mech_bundle = None
        gm_kernel, th_kernel = gm, thermo
        if sb is not None or rb is not None:
            s_pad = (resolve_bucket(len(self.species), sb)
                     if sb is not None else len(self.species))
            r_pad = (resolve_bucket(gm.n_reactions, rb)
                     if rb is not None else gm.n_reactions)
            self.mech_shape = (s_pad, r_pad)
            gm_kernel, th_kernel = _padded_mech(
                gm, thermo, s_pad, r_pad,
                canonical=bool(spec.mech_operands))
        # the EXACT callables batch_reactor_sweep builds: identical
        # construction => identical traced programs => identical AOT keys
        (self.rhs, self.jac, self.observer,
         self.observer_init) = _sweep_fns(
            "gas", None, gm_kernel, None, th_kernel, False, True,
            marker_idx, spec.ignition_mode)
        import jax

        # the session fingerprint stays CONTENT-based (it keys the
        # multi-mechanism store and request routing) even in operand
        # mode, where the EXECUTION callable is the shared content-free
        # builder — two mechanisms sharing one executable must still be
        # two sessions
        self.fingerprint = mechanism_fingerprint(
            self.rhs, self.jac, self.observer,
            extra=jax.tree_util.tree_map(repr, self.observer_init))
        if spec.mech_operands:
            # mechanism-as-operand (api.py mech_operands): the kernels
            # swap for the shared cached builder + the padded bundle as
            # a traced operand — any mechanism on this (S, R) rung runs
            # the SAME executable (docs/performance.md)
            self.mech_bundle = (gm_kernel, None, th_kernel)
            self.rhs = _segmented_builder("gas", None, False, True)
            self.jac = None
        # per-energy-mode callables (docs/energy.md serving): None is
        # the isothermal set above; each listed mode builds its own
        # rhs/jac/observer through the SAME api construction, so served
        # energy lanes and direct batch_reactor_sweep(energy=) lanes run
        # identical programs (and share AOT keys)
        self._mode_fns = {None: (self.rhs, self.jac, self.observer,
                                 self.observer_init)}
        for m in tuple(spec.energy_modes or ()):
            rhs_m, jac_m, obs_m, obs0_m = _sweep_fns(
                "gas", None, gm_kernel, None, th_kernel, False, True,
                marker_idx, spec.ignition_mode, "analytic", m)
            if spec.mech_operands:
                rhs_m = _segmented_builder("gas", None, False, True, m)
                jac_m = None
            self._mode_fns[m] = (rhs_m, jac_m, obs_m, obs0_m)
        self.jac_window = resolve_jac_window(spec.jac_window, spec.method)
        self.buckets = normalize_buckets(spec.buckets)
        # capacity plane (docs/serving.md "Capacity levers"): resolve
        # the spec's "auto"/bool forms against the local device set
        # once, here — the scheduler and the stream read ints
        mr = spec.mesh_resident
        self.mesh_resident = (len(jax.local_devices()) if mr is True
                              else int(mr) if mr else None)
        self._mesh_size = self.mesh_resident or 1
        self.resident_epochs = (
            max(1, len(jax.local_devices()))
            if spec.resident_epochs == "auto"
            else max(1, int(spec.resident_epochs)))
        #: the largest resident program shape the session will run —
        #: admission packs into at most this many slots
        self.bucket_cap = resolve_bucket(int(spec.resident), self.buckets,
                                         mesh_size=self._mesh_size)
        self.recorder = recorder if recorder is not None else Recorder()
        self.registry = LiveRegistry(
            recorder=self.recorder,
            meta={"entry": "serving", "fingerprint": self.fingerprint,
                  "mech": os.path.basename(spec.mech),
                  "bucket_cap": self.bucket_cap})
        self._watch = CompileWatch(recorder=self.recorder,
                                   default_label="serve-host")
        self._watch_entered = False
        self.warmed = None      # list[WarmupResult] after warmup()
        self._t0 = time.time()

    @classmethod
    def from_spec(cls, source, recorder=None):
        import batchreactor_tpu as br

        spec = load_spec(source)
        gm = br.compile_gaschemistry(spec.mech)
        th = br.create_thermo(list(gm.species), spec.therm)
        return cls(gm, th, spec, recorder=recorder)

    # ---- lifecycle --------------------------------------------------------
    def __enter__(self):
        if not self._watch_entered:
            self._watch.__enter__()
            # lifecycle flag, main thread only: set before the
            # scheduler/front-ends start and cleared after they drain
            # (scripts/serve.py ordering); stream() only reads it.  A
            # GIL-atomic bool store needs no lock at that phase.
            self._watch_entered = True  # brlint: disable=unguarded-shared-mutation
        return self

    def __exit__(self, *exc):
        if self._watch_entered:
            # lifecycle flag, main thread only (see __enter__)
            self._watch_entered = False  # brlint: disable=unguarded-shared-mutation
            self._watch.__exit__(*exc)

    def compile_summary(self):
        """The session watch's compile/retrace summary (obs.CompileWatch
        semantics) — the ``compiles == 0`` serving contract reads off
        this after warmup."""
        return self._watch.summary()

    def program_compiles(self):
        """True-XLA-compile counts per ARMED single-program label
        (``sweep-segment`` / ``sweep-compact``) during this session —
        THE warm-serving contract: all zeros after :meth:`warmup` (the
        PR-5 per-label convention; sub-ms host eager-op compiles ride
        the unarmed ``serve-host`` label and totals instead)."""
        w = self._watch.summary()
        return {label: e["compiles"]
                for label, e in (w.get("by_label") or {}).items()
                if e.get("single_program")}

    # ---- warmup (the aot/ registry face) ----------------------------------
    def _energy_fns(self, energy):
        """The per-mode ``(rhs, jac, observer, observer_init)`` set;
        loud on a mode the session never built (schema validation gates
        requests, this guards programmatic callers)."""
        try:
            return self._mode_fns[energy]
        except KeyError:
            raise ValueError(
                f"energy mode {energy!r} is not enabled on this "
                f"session (warmed modes: "
                f"{list(self.spec.energy_modes)}); add it to the "
                f"session spec's solver.energy_modes") from None

    def _stream_flags(self, rtol, atol, energy=None):
        """THE sweep flag set — shared verbatim by :meth:`stream` and
        :meth:`warmup_specs` so the warmed program keys cannot drift
        from the served ones (every key here shapes the traced
        program).  ``energy`` selects the per-mode callable set (the
        pack key's static half)."""
        s = self.spec
        _rhs, jac_m, obs_m, obs0_m = self._energy_fns(energy)
        flags = dict(method=s.method, rtol=float(rtol), atol=float(atol),
                     jac=jac_m, observer=obs_m,
                     observer_init=obs0_m,
                     jac_window=self.jac_window, linsolve=s.linsolve,
                     setup_economy=bool(s.setup_economy),
                     stale_tol=float(s.stale_tol), stats=bool(s.stats),
                     segment_steps=int(s.segment_steps),
                     max_attempts=int(s.max_attempts))
        if self.mech_bundle is not None:
            # operand mode: the bundle rides the flag set verbatim into
            # both the warmup specs and the live stream call — the aot
            # registry keys it by SHAPE class (registry._resolve_spec),
            # so shared-rung mechanisms resolve to one program key
            flags["rhs_bundle"] = self.mech_bundle
        return flags

    def warmup_specs(self, rtol=None, atol=None):
        """One ``aot.warmup`` spec per ladder rung per energy mode
        (isothermal + every ``spec.energy_modes`` entry) <= the
        resident cap — or, with ``upshift`` set, <= the resolved
        up-shift ceiling, so every rung the autoscaler can climb to is
        warmed and a live up-shift migration compiles nothing: each
        warms its rung's segment program AND (``backlog=2`` +
        ``admission=rung``) the traced compaction/admission step, so a
        cold daemon's first streamed request — isothermal or adiabatic
        — compiles nothing.  Under ``mesh_resident`` the rung set is
        the mesh-divisible ladder and each spec carries the mesh knob
        (a distinct program family — its AOT keys grow the mesh axis);
        unset, the spec dicts are byte-identical to the pre-mesh keys."""
        from ..aot import resolve_bucket

        rtol = self.spec.rtol if rtol is None else rtol
        atol = self.spec.atol if atol is None else atol
        top = self.bucket_cap
        if self.spec.upshift is not None:
            top = max(top, resolve_bucket(
                int(self.spec.upshift), self.buckets,
                mesh_size=self._mesh_size))
        if self.buckets is None:
            rungs = (top,)
        else:
            rungs = tuple(sorted({
                resolve_bucket(b, self.buckets,
                               mesh_size=self._mesh_size)
                for b in range(1, top + 1)}))
            rungs = tuple(b for b in rungs if b <= top)
        mesh_kw = ({} if self.mesh_resident is None
                   else {"mesh_resident": self.mesh_resident})
        specs = []
        for mode in (None,) + tuple(self.spec.energy_modes or ()):
            # exemplar lane: an equimolar mix over the first two
            # species is shape-complete (values never enter the
            # program key)
            y0, cfg_row = self._exemplar(energy=mode, atol=atol)
            rhs_m = self._energy_fns(mode)[0]
            specs.extend(
                dict(rhs=rhs_m, y0=y0, cfg=cfg_row, lanes=[r],
                     buckets=self.buckets, backlog=2, admission=r,
                     refill=1, poll_every=int(self.spec.poll_every),
                     **mesh_kw, **self._stream_flags(rtol, atol, mode))
                for r in rungs)
        return specs

    def _exemplar(self, energy=None, atol=None):
        """One exemplar (y0, cfg) row for warmup spec construction —
        only shapes matter, but the values must be solvable (finite
        density).  ``energy`` extends the row with the trailing T state
        and the T-row atol weight, exactly like :meth:`request_lanes`."""
        X = np.zeros((1, len(self.species)))
        X[0, 0] = 1.0
        y0 = np.asarray(self._solution_vectors(
            X, np.asarray([1500.0]), np.asarray([1e5])))[0]
        cfg = {"T": 1500.0, "Asv": 1.0}
        if self.mech_shape is not None:
            y0, cfg = self._pad_lanes(y0[None, :], cfg)
            y0 = y0[0]
            cfg = {k: (float(v) if np.ndim(v) == 0 else float(v[0]))
                   for k, v in cfg.items()}
        if energy is not None:
            y0, cfg1 = self._energy_lanes(
                y0[None, :], {k: np.asarray([v]) for k, v in cfg.items()},
                np.asarray([1500.0]),
                self.spec.atol if atol is None else atol)
            y0 = y0[0]
            cfg = {k: (np.asarray(v)[0] if np.ndim(v) else v)
                   for k, v in cfg1.items()}
        return y0, cfg

    def _pad_lanes(self, y0, cfg):
        """Dead-species padding of packed lane blocks: zero mass columns
        + the live-count norm operand (models/padding.py contract)."""
        from ..models.padding import NLIVE_KEY

        k, s_live = y0.shape[0], y0.shape[1]
        s_pad = self.mech_shape[0]
        if s_live < s_pad:
            y0 = np.concatenate(
                [y0, np.zeros((k, s_pad - s_live), dtype=y0.dtype)],
                axis=1)
        cfg = dict(cfg)
        cfg[NLIVE_KEY] = np.full((k,), float(len(self.species)))
        return y0, cfg

    def _energy_lanes(self, y0, cfg, T, atol):
        """Energy-mode lane extension (docs/energy.md): the trailing T
        state row (after species padding, so it sits at S_pad), the
        live-count bump (the T row is live), and the T-row atol weight
        — value-identical to ``api.batch_reactor_sweep``'s
        ``energy/eqns.py`` construction, so a served adiabatic lane and
        a direct sweep lane are the same numbers."""
        from ..energy.eqns import energy_atol_scale
        from ..models.padding import NLIVE_KEY
        from ..solver.sdirk import ATOL_SCALE_KEY

        k = y0.shape[0]
        y0 = np.concatenate(
            [y0, np.asarray(T, dtype=np.float64)[:, None]], axis=1)
        cfg = dict(cfg)
        if NLIVE_KEY in cfg:
            cfg[NLIVE_KEY] = np.asarray(cfg[NLIVE_KEY]) + 1.0
        cfg[ATOL_SCALE_KEY] = np.asarray(
            energy_atol_scale(k, y0.shape[1], atol))
        return y0, cfg

    def warmup(self, cache_dir=None, log=None, manifest_tag=None):
        """Pre-bake the session's program set (:mod:`~batchreactor_tpu.
        aot` — persistent cache + manifest + in-process dispatch cache).
        Returns the per-program :class:`aot.WarmupResult` list; after a
        warm pass a serving stream compiles nothing
        (:meth:`compile_summary`).

        ``manifest_tag`` names a per-member part manifest (fleet mode:
        N daemons warming one shared ``cache_dir`` concurrently) that is
        folded into the main manifest via the crash-atomic
        ``aot.merge_manifests`` path instead of racing on it."""
        from ..aot import warmup as aot_warmup

        t0 = time.perf_counter()
        specs = self.warmup_specs()
        if log is not None:
            # static pre-flight: predicted resident HBM across the
            # warmed program set (analysis/costmodel.py, stdlib
            # estimator — ~3x band) so a mis-sized resident cap is
            # visible in the startup log BEFORE the chip pays for it
            from ..analysis.costmodel import estimate_rung

            def fmt(b):
                return (f"{b / 2**20:.1f} MiB" if b >= 2**20
                        else f"{b / 1024:.0f} KiB")

            hbm = 0
            for s in specs:
                n = int(np.asarray(s["y0"]).shape[-1])
                est = estimate_rung(
                    max(s.get("lanes") or (1,)), n,
                    int(self.gm.n_reactions))
                hbm += est["hbm_bytes"]
                log(f"[warmup] rung={max(s.get('lanes') or (1,))} n={n} "
                    f"predicted resident ~{fmt(est['hbm_bytes'])}")
            log(f"[warmup] predicted resident HBM across "
                f"{len(specs)} warmed program(s): ~{fmt(hbm)} "
                f"(static cost model, ~3x band)")
        # startup lifecycle, main thread only: warmup completes before
        # the scheduler/HTTP front-ends start (scripts/serve.py
        # ordering); healthz_extra only reads the reference, and a
        # GIL-atomic list-reference store cannot tear
        self.warmed = aot_warmup(  # brlint: disable=unguarded-shared-mutation
            specs, cache_dir=cache_dir, log=log, manifest_tag=manifest_tag,
            merge=manifest_tag is not None)
        if self.recorder is not None:
            self.recorder.counter("serve_warmup_s",
                                  time.perf_counter() - t0)
        return self.warmed

    # ---- request -> lanes --------------------------------------------------
    def _solution_vectors(self, X, T, p):
        import jax.numpy as jnp

        from ..parallel.grid import sweep_solution_vectors

        return sweep_solution_vectors(jnp.asarray(X), self.thermo.molwt,
                                      jnp.asarray(T), jnp.asarray(p))

    def request_lanes(self, req):
        """Pack one validated :class:`~.schema.Request` into sweep lane
        blocks: ``(y0 (k, S) float64, {"T": (k,), "Asv": (k,)})`` —
        exactly the state construction ``batch_reactor_sweep`` performs,
        so a served lane and a direct sweep lane are the same numbers."""
        k = req.n_lanes
        X = np.zeros((k, len(self.species)))
        for name, vals in req.X.items():
            X[:, self._sp_idx[name.upper()]] = vals
        y0 = np.asarray(self._solution_vectors(X, req.T, req.p))
        cfg = {"T": np.asarray(req.T, dtype=np.float64),
               "Asv": np.asarray(req.Asv, dtype=np.float64)}
        if self.mech_shape is not None:
            y0, cfg = self._pad_lanes(y0, cfg)
        if getattr(req, "energy", None) is not None:
            self._energy_fns(req.energy)   # loud before anything queues
            y0, cfg = self._energy_lanes(y0, cfg, req.T, req.atol)
        return y0, cfg

    # ---- the resident stream ----------------------------------------------
    def stream(self, y0s, cfgs, *, t1, rtol, atol, energy=None,
               on_harvest=None, feed=None, live_source="sweep"):
        """Run one resident streaming sweep epoch over the given
        backlog, with the scheduler's harvest/feed hooks attached
        (``parallel.ensemble_solve_segmented`` ``_on_harvest``/
        ``_feed`` contract).  ``energy`` (a pack key's static half)
        selects the per-mode program family; ``live_source`` names this
        epoch's live-registry source (the multi-epoch scheduler passes
        ``sweep-e{k}`` so per-epoch gauges survive the merge).  Blocks
        until the feed closes and every admitted lane harvests."""
        import jax.numpy as jnp

        from ..parallel.sweep import ensemble_solve_segmented

        s = self.spec
        return ensemble_solve_segmented(
            self._energy_fns(energy)[0], jnp.asarray(y0s), 0.0,
            float(t1),
            {k: jnp.asarray(v) for k, v in cfgs.items()},
            max_segments=self.MAX_SEGMENTS,
            admission=int(s.resident),
            refill=s.refill, buckets=self.buckets,
            poll_every=int(s.poll_every),
            mesh_resident=self.mesh_resident,
            upshift=(None if s.upshift is None else int(s.upshift)),
            upshift_patience=int(s.upshift_patience),
            recorder=self.recorder,
            watch=self._watch if self._watch_entered else None,
            live=self.registry, _on_harvest=on_harvest, _feed=feed,
            _live_source=str(live_source),
            **self._stream_flags(rtol, atol, energy))

    # ---- results -> response payload --------------------------------------
    def fractions(self, y_rows):
        """Final mole fractions per lane from final-state rows (the
        ``batch_reactor_sweep`` output math)."""
        y = np.asarray(y_rows)
        ng = len(self.species)
        moles = y[:, :ng] / np.asarray(self.thermo.molwt)
        return moles / moles.sum(axis=1, keepdims=True)

    def render_result(self, result):
        """A scheduler :class:`~.scheduler.RequestResult` -> the ``ok``
        response payload (schema module doc)."""
        from ..api import _status_str

        x = self.fractions(result.y)
        payload = {
            "lanes": int(result.t.shape[0]),
            "t": [float(v) for v in result.t],
            "solver_status": [_status_str(c) for c in result.status],
            "provenance": list(result.provenance),
            "x": {s: [float(v) for v in x[:, k]]
                  for k, s in enumerate(self.species)},
            "n_accepted": [int(v) for v in result.n_accepted],
            "n_rejected": [int(v) for v in result.n_rejected],
            "elapsed_ms": round(1e3 * result.elapsed_s, 3),
        }
        if result.observed is not None and "tau" in result.observed:
            payload["tau"] = [float(v) for v in result.observed["tau"]]
        if getattr(result.request, "energy", None) is not None:
            # the physical-ignition payload (docs/energy.md): final
            # temperatures + the max-dT/dt delay (NaN -> null where the
            # lane never ignited)
            from ..energy.ignition import extract_delay

            payload["energy"] = result.request.energy
            payload["T"] = [float(v)
                            for v in np.asarray(result.y)[:, -1]]
            if (result.observed is not None
                    and "ign_tau_dT" in result.observed):
                delay = extract_delay(result.observed)
                payload["ignition_delay"] = [
                    None if np.isnan(v) else float(v) for v in delay]
        if result.stats is not None:
            from ..obs import counters as C

            payload["stats"] = {
                k: np.asarray(v).tolist() for k, v in result.stats.items()
                if k not in C.AUDIT_KEYS and k not in C.TIMELINE_KEYS}
        if getattr(result.request, "trace", False) \
                and result.trace is not None:
            # the trace= opt-in (docs/serving.md): the versioned stage
            # waterfall; absent-key requests get byte-identical
            # pre-trace responses
            payload["trace"] = result.trace.to_payload()
        return payload

    def obs_report(self, meta=None):
        """The session's full obs report (``obs.build_report`` over the
        session recorder + compile watch): spans, counters, the
        ``serve_stage_seconds`` histograms, and the per-request
        ``request_trace`` events — the serving evidence artifact
        ``scripts/serve.py --obs-out`` / ``serve_bench.py --obs-out``
        write and ``scripts/obs_trace.py`` / ``obs_gate.py`` consume."""
        from ..obs import build_report

        base = {"entry": "serving", "fingerprint": self.fingerprint,
                "mech": os.path.basename(self.spec.mech)}
        return build_report(recorder=self.recorder, watch=self._watch,
                            meta={**base, **(meta or {})})

    def healthz_extra(self):
        """Serving fields the daemon folds into ``/healthz``."""
        w = self.compile_summary()
        return {"fingerprint": self.fingerprint,
                "species": len(self.species),
                "bucket_cap": self.bucket_cap,
                "resident_epochs": self.resident_epochs,
                "mesh_resident": self.mesh_resident,
                "upshift": (None if self.spec.upshift is None
                            else int(self.spec.upshift)),
                "mech_shape": self.mech_shape,
                "mech_operands": self.mech_bundle is not None,
                "energy_modes": list(self.spec.energy_modes or ()),
                "warmed": (None if self.warmed is None
                           else sum(1 for r in self.warmed if r.warm)),
                "compiles": w.get("compiles"),
                "program_compiles": sum(self.program_compiles()
                                        .values()),
                "uptime_s": round(time.time() - self._t0, 3)}


class UnknownMechanism(KeyError):
    """A solve request's ``mech`` routing key matched no resident
    session (schema error code ``unknown_mechanism``)."""


class SessionStore:
    """The ``{fingerprint: SolverSession}`` multi-mechanism store
    (ROADMAP 5; docs/serving.md "Multi-mechanism serving").

    One daemon, many mechanisms: every resident mechanism owns a
    :class:`SolverSession` + scheduler pair, keyed by the session's
    content fingerprint and aliased by upload id, with the base spec's
    solver/serve sections as the shared template — so every session
    shares one solver flag set, one bucket ladder, and (under
    ``mech_operands``) ONE compiled executable per (B, S, R) rung:
    a new mechanism landing in a warmed rung warms at zero compiles.

    Capacity: at most ``spec.max_mechanisms`` resident sessions;
    beyond that the least-recently-REQUESTED unpinned session is
    drained and dropped (``mech_evicted`` counter), and the AOT
    manifest's LRU policy (:func:`aot.enforce_capacity`) trims the
    registry with it (``aot_evictions``).  The DEFAULT session (the
    daemon's serve.json mechanism) is pinned and never evicts.

    Thread contract: ``resolve``/``add_*``/``healthz`` are called from
    front-end handler threads — every mutation of the session map holds
    ``_lock``; the per-session schedulers own their request streams.
    """

    #: brlint host-concurrency lint: these run on HTTP handler threads
    _BRLINT_THREAD_ENTRIES = ("SessionStore.resolve",
                              "SessionStore.add_upload",
                              "SessionStore.healthz",
                              "SessionStore.mechanisms")

    def __init__(self, session, scheduler=None, *, cache_dir=None,
                 upload_dir=None, scheduler_factory=None):
        import tempfile

        from .scheduler import Scheduler

        self._lock = threading.RLock()
        self._factory = scheduler_factory or (lambda s: Scheduler(s))
        self.cache_dir = cache_dir
        self.recorder = session.recorder
        self.base_spec = session.spec
        self.max_mechanisms = max(1, int(
            getattr(session.spec, "max_mechanisms", 8)))
        self._entries = {}      # fingerprint -> entry dict
        self._aliases = {}      # upload/mech id -> fingerprint
        self._owns_dir = upload_dir is None
        self._dir = upload_dir or tempfile.mkdtemp(prefix="br-mechs-")
        self._seq = 0
        if scheduler is None:
            scheduler = self._factory(session)
        self.default_fingerprint = session.fingerprint
        self._admit(session, scheduler, mech_id="default", pinned=True)

    # ---- admission ---------------------------------------------------------
    def _admit(self, session, scheduler, mech_id, pinned=False):
        redundant = None
        with self._lock:
            fp = session.fingerprint
            entry = self._entries.get(fp)
            if entry is None:
                self._seq += 1
                entry = {"session": session, "scheduler": scheduler,
                         "ids": set(), "pinned": pinned,
                         "last_used": self._seq}
                self._entries[fp] = entry
                if self.recorder is not None:
                    self.recorder.counter("mech_admitted")
            elif entry["session"] is not session:
                # two concurrent uploads of one mechanism: first admit
                # wins, the loser's freshly-started pair shuts down
                redundant = scheduler
            entry["pinned"] = entry["pinned"] or pinned
            if mech_id is not None:
                entry["ids"].add(str(mech_id))
                self._aliases[str(mech_id)] = fp
            evicted = self._pop_over_capacity_locked(keep=fp)
        # teardown OUTSIDE the lock: a victim drain joins a worker that
        # may still be finishing device solves (up to the drain timeout)
        # — under the lock it would stall resolve() for EVERY mechanism
        for victim in evicted:
            self._teardown_evicted(victim)
        if redundant is not None:
            try:
                redundant.drain(timeout=5.0)
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            session.__exit__(None, None, None)
        return fp

    def _pop_over_capacity_locked(self, keep=None):
        """Pop LRU unpinned entries beyond capacity (map surgery only —
        no draining, no I/O); returns the popped entries for the caller
        to tear down outside the lock."""
        popped = []
        while len(self._entries) > self.max_mechanisms:
            victims = sorted(
                (fp for fp, e in self._entries.items()
                 if not e["pinned"] and fp != keep),
                key=lambda fp: self._entries[fp]["last_used"])
            if not victims:
                # everything (else) pinned or just-admitted: capacity
                # degrades to advisory rather than evicting the session
                # the caller is about to hand out
                break
            fp = victims[0]
            entry = self._entries.pop(fp)
            for mid in entry["ids"]:
                self._aliases.pop(mid, None)
            if self.recorder is not None:
                self.recorder.counter("mech_evicted")
            popped.append(entry)
        return popped

    def _teardown_evicted(self, entry):
        from ..aot import enforce_capacity

        try:
            entry["scheduler"].drain(timeout=30.0)
        except Exception:  # noqa: BLE001 — eviction must not wedge
            pass
        entry["session"].__exit__(None, None, None)
        if self.cache_dir is not None:
            # registry-side LRU: trim the manifest with the store
            enforce_capacity(
                self.cache_dir,
                self.max_mechanisms * max(
                    1, len(self._programs_per_session())),
                recorder=self.recorder)

    def _programs_per_session(self):
        with self._lock:
            e = self._entries.get(self.default_fingerprint)
        if e is None:
            return ()
        return e["session"].warmup_specs()

    def add_session(self, session, mech_id=None, warm=True):
        """Admit a pre-built session (tests, programmatic callers);
        warms it (shared-rung programs load at zero compiles), starts
        its scheduler, returns the fingerprint."""
        with self._lock:
            existing = self._entries.get(session.fingerprint)
            if existing is not None:
                if mech_id is not None:
                    existing["ids"].add(str(mech_id))
                    self._aliases[str(mech_id)] = session.fingerprint
                return session.fingerprint
        session.__enter__()
        if warm:
            session.warmup(cache_dir=self.cache_dir)
        scheduler = self._factory(session).start()
        return self._admit(session, scheduler, mech_id)

    def _session_keys(self, session):
        """The session's warm-cache program keys (the manifest rows its
        requests keep alive through :func:`aot.touch_keys`)."""
        if session.warmed:
            return [r.key for r in session.warmed]
        return []

    def add_mechanism(self, mech_path, therm_path, mech_id=None,
                      warm=True):
        """Build + admit a session for a mechanism file pair under the
        base spec's solver/serve template."""
        import batchreactor_tpu as br

        spec = dataclasses.replace(
            self.base_spec, mech=os.path.abspath(str(mech_path)),
            therm=os.path.abspath(str(therm_path)))
        gm = br.compile_gaschemistry(spec.mech)
        th = br.create_thermo(list(gm.species), spec.therm)
        session = SolverSession(gm, th, spec, recorder=self.recorder)
        return self.add_session(session, mech_id=mech_id, warm=warm)

    def add_upload(self, upload):
        """One validated upload (schema.validate_upload) -> (fingerprint,
        healthz-style info dict).  The inline texts land under the store
        dir; a parse failure raises ``ValueError`` (the front-end's
        ``invalid`` response)."""
        uid = upload["id"]
        mech_path = os.path.join(self._dir, f"{_safe_name(uid)}.dat")
        therm_path = os.path.join(self._dir, f"{_safe_name(uid)}.therm")
        for path, text in ((mech_path, upload["mech"]),
                           (therm_path, upload["therm"])):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        try:
            fp = self.add_mechanism(mech_path, therm_path, mech_id=uid,
                                    warm=upload.get("warm", True))
        except (KeyError, ValueError, NotImplementedError) as e:
            raise ValueError(f"mechanism upload {uid!r} rejected: "
                             f"{e}") from e
        with self._lock:
            session = self._entries[fp]["session"]
        return fp, {"fingerprint": fp, "id": uid,
                    "species": list(session.species),
                    "mech_shape": session.mech_shape,
                    "warmed": (None if session.warmed is None else
                               sum(1 for r in session.warmed if r.warm)),
                    "program_compiles": session.program_compiles()}

    # ---- routing -----------------------------------------------------------
    #: minimum seconds between manifest ``last_used`` touches per
    #: session — the LRU clock is request-driven but must not pay a
    #: manifest load+save on every solve
    TOUCH_EVERY_S = 60.0

    def resolve(self, mech=None):
        """Route a request's ``mech`` key (upload id, full fingerprint,
        or unambiguous fingerprint prefix; None = default) to its
        ``(session, scheduler)`` pair, advancing the LRU clock — both
        the store's in-memory one and (throttled, when a cache dir is
        managed) the warm-cache manifest's ``last_used``, so
        :func:`aot.enforce_capacity` evicts by true recency of USE, not
        warm time."""
        touch = None
        with self._lock:
            if mech is None:
                fp = self.default_fingerprint
            else:
                fp = self._aliases.get(str(mech))
                if fp is None:
                    hits = [f for f in self._entries
                            if f.startswith(str(mech))]
                    if len(hits) != 1:
                        raise UnknownMechanism(
                            f"unknown mechanism {mech!r} "
                            f"({len(self._entries)} resident; upload it "
                            f"via POST /mechanism or use a resident id)")
                    fp = hits[0]
            entry = self._entries.get(fp)
            if entry is None:
                raise UnknownMechanism(f"mechanism {mech!r} is no longer "
                                       f"resident (evicted)")
            self._seq += 1
            entry["last_used"] = self._seq
            if self.cache_dir is not None:
                now = time.monotonic()
                if now - entry.get("touched_at", 0.0) > self.TOUCH_EVERY_S:
                    entry["touched_at"] = now
                    touch = self._session_keys(entry["session"])
            session, scheduler = entry["session"], entry["scheduler"]
        if touch:
            # manifest I/O outside the lock (routing must never wait on
            # a disk write); touch_keys itself is load+atomic-replace
            from ..aot import touch_keys

            touch_keys(self.cache_dir, touch)
        return session, scheduler

    def mechanisms(self):
        """Healthz-facing census: one row per resident session."""
        with self._lock:
            return [{"fingerprint": fp,
                     "ids": sorted(e["ids"]),
                     "pinned": e["pinned"],
                     "species": len(e["session"].species),
                     "mech_shape": e["session"].mech_shape,
                     "program_compiles": sum(
                         e["session"].program_compiles().values())}
                    for fp, e in self._entries.items()]

    def healthz(self):
        return {"mechanisms": self.mechanisms(),
                "max_mechanisms": self.max_mechanisms}

    # ---- lifecycle ---------------------------------------------------------
    def drain(self, timeout=None):
        """Drain every resident scheduler and close the sessions the
        STORE admitted (the daemon's SIGTERM path); the default
        session's context stays caller-owned (scripts/serve.py's
        ``with session:``), and the store's upload temp dir is removed
        when the store created it."""
        import shutil

        with self._lock:
            entries = list(self._entries.values())
        ok = True
        for e in entries:
            try:
                ok = e["scheduler"].drain(timeout) and ok
            except Exception:  # noqa: BLE001 — drain-all must finish
                ok = False
            if e["session"].fingerprint != self.default_fingerprint:
                # symmetric with add_session's __enter__ (eviction and
                # the redundant-admit path already close theirs)
                e["session"].__exit__(None, None, None)
        if self._owns_dir:
            shutil.rmtree(self._dir, ignore_errors=True)
        return ok


def _safe_name(name):
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)
