"""SolverSession: the warm, device-resident half of the serving daemon.

A session owns everything that must exist BEFORE the first request can
be answered fast: the frozen mechanism bundle (parsed once), the exact
sweep callables ``batch_reactor_sweep`` would build (``api._sweep_fns``
— identical construction => identical traced programs => identical
AOT/persistent-cache keys), the bucket ladder and solver config, and
the obs plane (recorder + live registry + a session-wide
``CompileWatch``).  :meth:`warmup` drives the :mod:`~batchreactor_tpu.
aot` registry over the ladder — including the streaming compaction
program via the warmup ``backlog`` knob — so a warmed session serves
its first request with ``compiles == 0`` (the acceptance surface
``scripts/serve_bench.py`` and the tier-1 e2e assert).

Sessions are keyed by :attr:`fingerprint` (mechanism fingerprint — the
same content hash the AOT registry and checkpoint resume trust), so the
ROADMAP-5 multi-mechanism store is a ``{fingerprint: SolverSession}``
dict away: everything request-scoped lives in the scheduler, everything
mechanism-scoped lives here.

The session spec (``serve.json``) is the ONE configuration artifact the
daemon and ``scripts/warm_cache.py --spec`` share: both resolve it
through :func:`load_spec` / :meth:`SolverSession.warmup_specs`, so the
warmer provably bakes the same program keys the server will run
(mechanism fingerprint x solver flags x ladder — drift is structurally
impossible, not just discouraged).
"""

import dataclasses
import json
import os
import time

import numpy as np

from .schema import Request  # noqa: F401  (re-exported for callers)

#: brlint host-concurrency lint (analysis/concurrency.py): these run on
#: other modules' threads — request packing on HTTP front-end threads,
#: the stream on the scheduler worker, the health block on handler
#: threads (cross-module thread entry is declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("SolverSession.request_lanes",
                          "SolverSession.stream",
                          "SolverSession.render_result",
                          "SolverSession.healthz_extra")

#: spec keys, per section — unknown keys are loud errors (the schema.py
#: convention: a typo'd knob must not be silently ignored)
_MECH_KEYS = ("mech", "therm")
_SOLVER_KEYS = ("method", "rtol", "atol", "jac_window", "linsolve",
                "setup_economy", "stale_tol", "segment_steps",
                "max_attempts", "stats", "ignition_marker",
                "ignition_mode")
_SERVE_KEYS = ("resident", "refill", "buckets", "poll_every",
               "max_queue_lanes", "idle_timeout_s", "request_timeout_s",
               "max_lanes_per_request", "coalesce_s")


@dataclasses.dataclass(frozen=True)
class SessionSpec:
    """A validated serving session spec (``serve.json``).  ``mech`` /
    ``therm`` are resolved absolute paths; everything else is the
    solver/serve config with defaults applied."""

    mech: str
    therm: str
    # solver config (the sweep flag set — part of every program key)
    method: str = "bdf"
    rtol: float = 1e-6
    atol: float = 1e-10
    jac_window: object = None        # None = the platform rule
    linsolve: str = "auto"
    setup_economy: bool = False
    stale_tol: float = 0.3
    segment_steps: int = 64
    max_attempts: int = 200_000
    stats: bool = True
    ignition_marker: object = None
    ignition_mode: str = "half"
    # serve config (scheduler/capacity — NOT part of the program keys)
    resident: int = 8
    refill: object = 1
    buckets: object = "pow2"
    poll_every: int = 1
    max_queue_lanes: int = 256
    idle_timeout_s: float = 0.25
    request_timeout_s: float = 300.0
    max_lanes_per_request: object = None
    #: batching window: a fresh epoch waits up to this long for the
    #: queue to fill one resident program before seeding (the inference
    #: servers' max-batch-delay knob; 0 = dispatch immediately).  Lanes
    #: arriving after the seed still join through the live feed.
    coalesce_s: float = 0.0


def load_spec(source):
    """``serve.json`` -> :class:`SessionSpec`.  ``source`` is a path, a
    JSON string, or an already-parsed dict; relative mechanism paths
    resolve against the spec file's directory (a spec checked into a
    repo keeps working from any CWD).  Unknown keys at any level are
    loud ``ValueError``s."""
    base = os.getcwd()
    if isinstance(source, dict):
        obj = source
    else:
        text = str(source)
        if text.lstrip().startswith("{"):
            obj = json.loads(text)
        else:
            base = os.path.dirname(os.path.abspath(text))
            with open(text) as f:
                obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"session spec must be a JSON object; got "
                         f"{type(obj).__name__}")
    unknown = sorted(set(obj) - {"mechanism", "solver", "serve"})
    if unknown:
        raise ValueError(f"unknown session-spec section(s) {unknown}; "
                         f"known: ['mechanism', 'solver', 'serve']")
    mech_sec = obj.get("mechanism")
    if not isinstance(mech_sec, dict):
        raise ValueError("session spec needs a 'mechanism' section "
                         "{'mech': ..., 'therm': ...}")

    def _section(sec, known, name):
        unknown = sorted(set(sec) - set(known))
        if unknown:
            raise ValueError(f"unknown {name} key(s) {unknown}; known: "
                             f"{list(known)}")
        return dict(sec)

    mech_sec = _section(mech_sec, _MECH_KEYS, "mechanism")
    for key in _MECH_KEYS:
        if key not in mech_sec:
            raise ValueError(f"session spec mechanism section needs "
                             f"{key!r}")
    kw = {}
    kw.update(_section(obj.get("solver") or {}, _SOLVER_KEYS, "solver"))
    kw.update(_section(obj.get("serve") or {}, _SERVE_KEYS, "serve"))
    if isinstance(kw.get("buckets"), list):
        kw["buckets"] = tuple(int(b) for b in kw["buckets"])
    resolve = (lambda p: p if os.path.isabs(p)
               else os.path.normpath(os.path.join(base, p)))
    spec = SessionSpec(mech=resolve(mech_sec["mech"]),
                       therm=resolve(mech_sec["therm"]), **kw)
    if spec.method not in ("bdf", "sdirk"):
        raise ValueError(f"session spec: unknown method {spec.method!r}")
    if int(spec.resident) < 1:
        raise ValueError(f"session spec: resident must be >= 1, got "
                         f"{spec.resident!r}")
    if int(spec.segment_steps) < 1:
        raise ValueError(f"session spec: segment_steps must be >= 1, "
                         f"got {spec.segment_steps!r}")
    if int(spec.max_queue_lanes) < 1:
        raise ValueError(f"session spec: max_queue_lanes must be >= 1, "
                         f"got {spec.max_queue_lanes!r}")
    return spec


class SolverSession:
    """Module doc.  Build with :func:`from_spec` (parses the mechanism)
    or directly from pre-built ``gm``/``thermo`` objects (tests, and
    callers that already hold the bundles)."""

    #: serving epochs are open-ended: the stream lives while its feed
    #: does, so the segment ceiling is a runaway bound, not a budget
    MAX_SEGMENTS = 1 << 30

    def __init__(self, gm, thermo, spec, recorder=None):
        from ..aot import mechanism_fingerprint, normalize_buckets, \
            resolve_bucket
        from ..api import _sweep_fns, resolve_jac_window
        from ..obs import CompileWatch, LiveRegistry, Recorder

        self.gm = gm
        self.thermo = thermo
        self.spec = spec
        self.species = tuple(thermo.species)
        self._sp_idx = {s.upper(): k for k, s in enumerate(self.species)}
        marker_idx = None
        if spec.ignition_marker is not None:
            key = str(spec.ignition_marker).upper()
            if key not in self._sp_idx:
                raise ValueError(
                    f"session spec: ignition_marker "
                    f"{spec.ignition_marker!r} not in the mechanism")
            marker_idx = self._sp_idx[key]
        # the EXACT callables batch_reactor_sweep builds: identical
        # construction => identical traced programs => identical AOT keys
        (self.rhs, self.jac, self.observer,
         self.observer_init) = _sweep_fns(
            "gas", None, gm, None, thermo, False, True, marker_idx,
            spec.ignition_mode)
        self.jac_window = resolve_jac_window(spec.jac_window, spec.method)
        self.buckets = normalize_buckets(spec.buckets)
        #: the largest resident program shape the session will run —
        #: admission packs into at most this many slots
        self.bucket_cap = resolve_bucket(int(spec.resident), self.buckets)
        import jax

        self.fingerprint = mechanism_fingerprint(
            self.rhs, self.jac, self.observer,
            extra=jax.tree_util.tree_map(repr, self.observer_init))
        self.recorder = recorder if recorder is not None else Recorder()
        self.registry = LiveRegistry(
            recorder=self.recorder,
            meta={"entry": "serving", "fingerprint": self.fingerprint,
                  "mech": os.path.basename(spec.mech),
                  "bucket_cap": self.bucket_cap})
        self._watch = CompileWatch(recorder=self.recorder,
                                   default_label="serve-host")
        self._watch_entered = False
        self.warmed = None      # list[WarmupResult] after warmup()
        self._t0 = time.time()

    @classmethod
    def from_spec(cls, source, recorder=None):
        import batchreactor_tpu as br

        spec = load_spec(source)
        gm = br.compile_gaschemistry(spec.mech)
        th = br.create_thermo(list(gm.species), spec.therm)
        return cls(gm, th, spec, recorder=recorder)

    # ---- lifecycle --------------------------------------------------------
    def __enter__(self):
        if not self._watch_entered:
            self._watch.__enter__()
            # lifecycle flag, main thread only: set before the
            # scheduler/front-ends start and cleared after they drain
            # (scripts/serve.py ordering); stream() only reads it.  A
            # GIL-atomic bool store needs no lock at that phase.
            self._watch_entered = True  # brlint: disable=unguarded-shared-mutation
        return self

    def __exit__(self, *exc):
        if self._watch_entered:
            # lifecycle flag, main thread only (see __enter__)
            self._watch_entered = False  # brlint: disable=unguarded-shared-mutation
            self._watch.__exit__(*exc)

    def compile_summary(self):
        """The session watch's compile/retrace summary (obs.CompileWatch
        semantics) — the ``compiles == 0`` serving contract reads off
        this after warmup."""
        return self._watch.summary()

    def program_compiles(self):
        """True-XLA-compile counts per ARMED single-program label
        (``sweep-segment`` / ``sweep-compact``) during this session —
        THE warm-serving contract: all zeros after :meth:`warmup` (the
        PR-5 per-label convention; sub-ms host eager-op compiles ride
        the unarmed ``serve-host`` label and totals instead)."""
        w = self._watch.summary()
        return {label: e["compiles"]
                for label, e in (w.get("by_label") or {}).items()
                if e.get("single_program")}

    # ---- warmup (the aot/ registry face) ----------------------------------
    def _stream_flags(self, rtol, atol):
        """THE sweep flag set — shared verbatim by :meth:`stream` and
        :meth:`warmup_specs` so the warmed program keys cannot drift
        from the served ones (every key here shapes the traced
        program)."""
        s = self.spec
        return dict(method=s.method, rtol=float(rtol), atol=float(atol),
                    jac=self.jac, observer=self.observer,
                    observer_init=self.observer_init,
                    jac_window=self.jac_window, linsolve=s.linsolve,
                    setup_economy=bool(s.setup_economy),
                    stale_tol=float(s.stale_tol), stats=bool(s.stats),
                    segment_steps=int(s.segment_steps),
                    max_attempts=int(s.max_attempts))

    def warmup_specs(self, rtol=None, atol=None):
        """One ``aot.warmup`` spec per ladder rung <= the resident cap:
        each warms its rung's segment program AND (``backlog=2`` +
        ``admission=rung``) the traced compaction/admission step, so a
        cold daemon's first streamed request compiles nothing."""
        from ..aot import bucket_ladder

        rtol = self.spec.rtol if rtol is None else rtol
        atol = self.spec.atol if atol is None else atol
        # exemplar lane: an equimolar mix over the first two species is
        # shape-complete (values never enter the program key)
        y0, cfg_row = self._exemplar()
        if self.buckets is None:
            rungs = (self.bucket_cap,)
        else:
            rungs = tuple(
                b for b in bucket_ladder(
                    range(1, self.bucket_cap + 1), self.buckets)
                if b <= self.bucket_cap)
        return [dict(rhs=self.rhs, y0=y0, cfg=cfg_row, lanes=[r],
                     buckets=self.buckets, backlog=2, admission=r,
                     refill=1, poll_every=int(self.spec.poll_every),
                     **self._stream_flags(rtol, atol))
                for r in rungs]

    def _exemplar(self):
        """One exemplar (y0, cfg) row for warmup spec construction —
        only shapes matter, but the values must be solvable (finite
        density)."""
        X = np.zeros((1, len(self.species)))
        X[0, 0] = 1.0
        y0 = np.asarray(self._solution_vectors(
            X, np.asarray([1500.0]), np.asarray([1e5])))[0]
        return y0, {"T": 1500.0, "Asv": 1.0}

    def warmup(self, cache_dir=None, log=None):
        """Pre-bake the session's program set (:mod:`~batchreactor_tpu.
        aot` — persistent cache + manifest + in-process dispatch cache).
        Returns the per-program :class:`aot.WarmupResult` list; after a
        warm pass a serving stream compiles nothing
        (:meth:`compile_summary`)."""
        from ..aot import warmup as aot_warmup

        t0 = time.perf_counter()
        # startup lifecycle, main thread only: warmup completes before
        # the scheduler/HTTP front-ends start (scripts/serve.py
        # ordering); healthz_extra only reads the reference, and a
        # GIL-atomic list-reference store cannot tear
        self.warmed = aot_warmup(  # brlint: disable=unguarded-shared-mutation
            self.warmup_specs(), cache_dir=cache_dir, log=log)
        if self.recorder is not None:
            self.recorder.counter("serve_warmup_s",
                                  time.perf_counter() - t0)
        return self.warmed

    # ---- request -> lanes --------------------------------------------------
    def _solution_vectors(self, X, T, p):
        import jax.numpy as jnp

        from ..parallel.grid import sweep_solution_vectors

        return sweep_solution_vectors(jnp.asarray(X), self.thermo.molwt,
                                      jnp.asarray(T), jnp.asarray(p))

    def request_lanes(self, req):
        """Pack one validated :class:`~.schema.Request` into sweep lane
        blocks: ``(y0 (k, S) float64, {"T": (k,), "Asv": (k,)})`` —
        exactly the state construction ``batch_reactor_sweep`` performs,
        so a served lane and a direct sweep lane are the same numbers."""
        k = req.n_lanes
        X = np.zeros((k, len(self.species)))
        for name, vals in req.X.items():
            X[:, self._sp_idx[name.upper()]] = vals
        y0 = np.asarray(self._solution_vectors(X, req.T, req.p))
        return y0, {"T": np.asarray(req.T, dtype=np.float64),
                    "Asv": np.asarray(req.Asv, dtype=np.float64)}

    # ---- the resident stream ----------------------------------------------
    def stream(self, y0s, cfgs, *, t1, rtol, atol, on_harvest=None,
               feed=None):
        """Run one resident streaming sweep epoch over the given
        backlog, with the scheduler's harvest/feed hooks attached
        (``parallel.ensemble_solve_segmented`` ``_on_harvest``/
        ``_feed`` contract).  Blocks until the feed closes and every
        admitted lane harvests."""
        import jax.numpy as jnp

        from ..parallel.sweep import ensemble_solve_segmented

        s = self.spec
        return ensemble_solve_segmented(
            self.rhs, jnp.asarray(y0s), 0.0, float(t1),
            {k: jnp.asarray(v) for k, v in cfgs.items()},
            max_segments=self.MAX_SEGMENTS,
            admission=int(s.resident),
            refill=s.refill, buckets=self.buckets,
            poll_every=int(s.poll_every),
            recorder=self.recorder,
            watch=self._watch if self._watch_entered else None,
            live=self.registry, _on_harvest=on_harvest, _feed=feed,
            **self._stream_flags(rtol, atol))

    # ---- results -> response payload --------------------------------------
    def fractions(self, y_rows):
        """Final mole fractions per lane from final-state rows (the
        ``batch_reactor_sweep`` output math)."""
        y = np.asarray(y_rows)
        ng = len(self.species)
        moles = y[:, :ng] / np.asarray(self.thermo.molwt)
        return moles / moles.sum(axis=1, keepdims=True)

    def render_result(self, result):
        """A scheduler :class:`~.scheduler.RequestResult` -> the ``ok``
        response payload (schema module doc)."""
        from ..api import _status_str

        x = self.fractions(result.y)
        payload = {
            "lanes": int(result.t.shape[0]),
            "t": [float(v) for v in result.t],
            "solver_status": [_status_str(c) for c in result.status],
            "provenance": list(result.provenance),
            "x": {s: [float(v) for v in x[:, k]]
                  for k, s in enumerate(self.species)},
            "n_accepted": [int(v) for v in result.n_accepted],
            "n_rejected": [int(v) for v in result.n_rejected],
            "elapsed_ms": round(1e3 * result.elapsed_s, 3),
        }
        if result.observed is not None and "tau" in result.observed:
            payload["tau"] = [float(v) for v in result.observed["tau"]]
        if result.stats is not None:
            from ..obs import counters as C

            payload["stats"] = {
                k: np.asarray(v).tolist() for k, v in result.stats.items()
                if k not in C.AUDIT_KEYS and k not in C.TIMELINE_KEYS}
        return payload

    def healthz_extra(self):
        """Serving fields the daemon folds into ``/healthz``."""
        w = self.compile_summary()
        return {"fingerprint": self.fingerprint,
                "species": len(self.species),
                "bucket_cap": self.bucket_cap,
                "warmed": (None if self.warmed is None
                           else sum(1 for r in self.warmed if r.warm)),
                "compiles": w.get("compiles"),
                "program_compiles": sum(self.program_compiles()
                                        .values()),
                "uptime_s": round(time.time() - self._t0, 3)}
