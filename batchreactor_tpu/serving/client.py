"""Client + load-trace tooling for the serving daemon.

:class:`SolveClient` is the minimal stdlib HTTP client (urllib) the
tests, ``scripts/serve_bench.py``, and operators use: ``solve`` posts a
schema request and returns the parsed response, raising
:class:`ServeError` (with the server's error code) on anything but
``status == "ok"``.

:func:`poisson_trace` builds the SEEDED open-loop request trace the
bench protocol measures under: exponential inter-arrival gaps at a
target rate, deterministic per seed — two runs of the same seed issue
byte-identical schedules, so a latency regression is a change in the
server, not the load.  :func:`run_trace` fires a trace against a
client from worker threads (open-loop: a slow response does not slow
the arrival process — the honest way to find the knee) and returns
per-request latency records for the p50/p95/p99 + cond/s summary
(:func:`summarize`); when the requests carried ``trace: true``,
:func:`trace_summary` adds the server-side stage decomposition and the
client~server latency-attribution check (docs/observability.md
"Request tracing").
"""

import json
import random
import threading
import time
import urllib.error
import urllib.request


class ServeError(RuntimeError):
    """A non-ok response; ``code`` is the schema error code and
    ``response`` the parsed body (when the server sent one)."""

    def __init__(self, code, message, response=None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.response = response


class SolveClient:
    """Module doc.  ``url`` is the daemon base url
    (``http://host:port``)."""

    def __init__(self, url, timeout=300.0):
        self.url = url.rstrip("/")
        self.timeout = float(timeout)

    def _get(self, path):
        with urllib.request.urlopen(self.url + path,
                                    timeout=self.timeout) as r:
            return r.read().decode()

    def healthz(self):
        return json.loads(self._get("/healthz"))

    def metrics(self):
        """The raw Prometheus exposition text."""
        return self._get("/metrics")

    def solve(self, request):
        """POST one request object; returns the parsed ``ok`` response
        or raises :class:`ServeError` with the server's code."""
        body = json.dumps(request).encode()
        req = urllib.request.Request(
            self.url + "/solve", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                resp = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                resp = json.loads(e.read().decode())
            except (ValueError, OSError):
                raise ServeError("internal",
                                 f"HTTP {e.code}: {e.reason}") from None
            err = resp.get("error") or {}
            raise ServeError(err.get("code", "internal"),
                             err.get("message", f"HTTP {e.code}"),
                             resp) from None
        if resp.get("status") != "ok":
            err = resp.get("error") or {}
            raise ServeError(err.get("code", "internal"),
                            err.get("message", "non-ok response"), resp)
        return resp

    def upload_mechanism(self, mech_id, mech_text, therm_text,
                         warm=True):
        """POST one mechanism upload (``POST /mechanism`` —
        schema.validate_upload grammar); returns the parsed ``ok``
        response (fingerprint, species, warm state) or raises
        :class:`ServeError`."""
        body = json.dumps({"id": str(mech_id), "mech": mech_text,
                           "therm": therm_text,
                           "warm": bool(warm)}).encode()
        req = urllib.request.Request(
            self.url + "/mechanism", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                resp = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                resp = json.loads(e.read().decode())
            except (ValueError, OSError):
                raise ServeError("internal",
                                 f"HTTP {e.code}: {e.reason}") from None
            err = resp.get("error") or {}
            raise ServeError(err.get("code", "internal"),
                             err.get("message", f"HTTP {e.code}"),
                             resp) from None
        if resp.get("status") != "ok":
            err = resp.get("error") or {}
            raise ServeError(err.get("code", "internal"),
                             err.get("message", "non-ok response"), resp)
        return resp


def with_trace_ctx(request, trace_id=None, span="client"):
    """Attach a distributed-trace envelope (``schema.trace_ctx_payload``
    — docs/observability.md "Fleet tracing") to a copy of ``request``.
    The default trace id derives from the request id (``t-<id>``) —
    DETERMINISTIC, no rng draw, so a seeded :func:`poisson_trace`
    schedule stays byte-identical with tracing on, and the bench can
    re-derive each record's trace id to join client latency against
    the stitched fleet waterfall."""
    from .schema import trace_ctx_payload

    req = dict(request)
    tid = (f"t-{req.get('id')}" if trace_id is None else trace_id)
    req["trace_ctx"] = trace_ctx_payload(tid, span=span)
    return req


def poisson_trace(n_requests, rate_hz, seed, make_request):
    """The seeded open-loop trace: ``[(send_at_s, request), ...]`` with
    exponential inter-arrival gaps at ``rate_hz`` mean arrivals/s.
    ``make_request(i, rng)`` builds request ``i`` (the rng is the
    trace's own — condition randomization stays inside the seed)."""
    rng = random.Random(int(seed))
    t = 0.0
    out = []
    for i in range(int(n_requests)):
        t += rng.expovariate(float(rate_hz))
        out.append((t, make_request(i, rng)))
    return out


def run_trace(client, trace, on_result=None):
    """Fire a :func:`poisson_trace` schedule open-loop: each request is
    posted from its own thread at its scheduled instant.  Returns one
    record per request: ``{"id", "send_at", "latency_s", "ok",
    "code", "response"}`` in trace order."""
    records = [None] * len(trace)
    threads = []
    t0 = time.perf_counter()

    def _fire(i, send_at, request):
        delay = send_at - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        sent = time.perf_counter()
        try:
            resp = client.solve(request)
            ok, code = True, None
        except ServeError as e:
            resp, ok, code = e.response, False, e.code
        except OSError as e:
            # transport-level failure (connection reset/refused under
            # overload, daemon gone): a record, not a dead thread — the
            # summary must account for every request fired
            resp, ok, code = {"error": str(e)}, False, "transport"
        records[i] = {"id": request.get("id", i), "send_at": send_at,
                      "latency_s": time.perf_counter() - sent,
                      "ok": ok, "code": code, "response": resp}
        if on_result is not None:
            on_result(records[i])

    for i, (send_at, request) in enumerate(trace):
        th = threading.Thread(target=_fire, args=(i, send_at, request),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return records


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def summarize(records, wall_s):
    """The bench summary (PERF.md round-10 evidence format): counts,
    sustained cond/s over the trace wall, and latency percentiles over
    the ANSWERED requests."""
    ok = [r for r in records if r and r["ok"]]
    lat = sorted(r["latency_s"] for r in ok)
    lanes = sum(len((r["response"] or {}).get("t", []))
                for r in ok)
    return {
        "requests": len(records),
        "answered": len(ok),
        "rejected": sum(1 for r in records
                        if r and not r["ok"]),
        "lanes": lanes,
        "wall_s": round(wall_s, 4),
        "cond_per_s": round(lanes / wall_s, 3) if wall_s > 0 else None,
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 3) if lat else None,
        "p95_ms": round(1e3 * _percentile(lat, 0.95), 3) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 3) if lat else None,
    }


def trace_summary(records, attribution_tol_ms=2000.0):
    """The SERVER-side half of the bench evidence, from the ``trace``
    sections of answered responses (requests sent with ``trace:
    true``): per-stage p50/p95/mean over the waterfall segments
    (obs/trace.py vocabulary), server total percentiles, and the
    client~server attribution check — client ``latency_s`` must cover
    the server ``submitted -> resolved`` wall (small negative slack
    for clock granularity) and exceed it by at most
    ``attribution_tol_ms`` of transport/thread-wakeup overhead, which
    pins the two clocks against stage-attribution bugs.  Returns
    ``None`` when no record carries a trace."""
    traced = [(r, r["response"]["trace"]) for r in records
              if r and r["ok"] and (r.get("response") or {}).get("trace")]
    if not traced:
        return None
    by_stage = {}
    for _r, tr in traced:
        for stage, dur in (tr.get("segments") or {}).items():
            by_stage.setdefault(stage, []).append(float(dur))

    def pct(vals, q):
        return _percentile(sorted(vals), q)

    totals = [float(tr["total_s"]) for _r, tr in traced]
    gaps_ms = [1e3 * (r["latency_s"] - float(tr["total_s"]))
               for r, tr in traced]
    violations = [
        {"id": r["id"], "gap_ms": round(g, 3)}
        for (r, _t), g in zip(traced, gaps_ms)
        if g < -5.0 or g > attribution_tol_ms]
    return {
        "server_stages": {
            stage: {"n": len(durs),
                    "mean_ms": round(1e3 * sum(durs) / len(durs), 3),
                    "p50_ms": round(1e3 * pct(durs, 0.50), 3),
                    "p95_ms": round(1e3 * pct(durs, 0.95), 3)}
            for stage, durs in sorted(by_stage.items())},
        "server_total_p50_ms": round(1e3 * pct(totals, 0.50), 3),
        "server_total_p95_ms": round(1e3 * pct(totals, 0.95), 3),
        "attribution": {
            "n": len(gaps_ms),
            "max_gap_ms": round(max(gaps_ms), 3),
            "p50_gap_ms": round(pct(gaps_ms, 0.50), 3),
            "tol_ms": attribution_tol_ms,
            "ok": not violations,
            "violations": violations[:8]},
    }


def stitched_attribution(records, stitched, attribution_tol_ms=2000.0):
    """The :func:`trace_summary` attribution check EXTENDED ACROSS THE
    ROUTER HOP (docs/observability.md "Fleet tracing"): client
    ``latency_s`` vs the stitched trace's end-to-end ``total_s``
    (``obs.stitch`` — the router's wall, which brackets every hop).
    Records join their trace by the :func:`with_trace_ctx` derivation
    ``t-<id>``.  Same gap rule as the single-host check: the client
    must cover the stitched wall (>= -5 ms clock slack) and exceed it
    by at most ``attribution_tol_ms``.  Returns ``None`` when nothing
    joined — the caller treats that as "tracing was off", not a
    pass."""
    by_trace = {}
    for t in stitched:
        if t.get("trace") is not None and t.get("total_s") is not None:
            by_trace.setdefault(t["trace"], t)
    gaps_ms, violations = [], []
    for r in records:
        if not r or not r["ok"]:
            continue
        t = by_trace.get(f"t-{r['id']}")
        if t is None:
            continue
        g = 1e3 * (r["latency_s"] - float(t["total_s"]))
        gaps_ms.append(g)
        if g < -5.0 or g > attribution_tol_ms:
            violations.append({"id": r["id"], "gap_ms": round(g, 3)})
    if not gaps_ms:
        return None
    return {"n": len(gaps_ms),
            "max_gap_ms": round(max(gaps_ms), 3),
            "p50_gap_ms": round(_percentile(sorted(gaps_ms), 0.50), 3),
            "tol_ms": attribution_tol_ms,
            "ok": not violations,
            "violations": violations[:8]}
