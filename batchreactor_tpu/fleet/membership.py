"""Elastic fleet membership over a shared directory.

The fleet coordinates the way the elastic sweep does (``parallel/
multihost.py``): through files in a shared directory, with the
``resilience.heartbeat`` mtime convention as the liveness signal — no
coordinator, no gossip, nothing to fail separately.  Layout under one
``fleet_dir``::

    members/<name>.json      # registration: {"name", "url", "pid", ...}
    members/<name>.hb        # heartbeat file (resilience.Heartbeat)
    members/<name>.draining  # drain-handshake flag (empty file)
    hosts/p<pid>.metrics.json  # obs.live fleet snapshot (PR-9 shape)

**Member side** (:class:`MemberRegistration`, wired by
``scripts/serve.py --fleet-dir``): register atomically, beat every
``heartbeat_s``, and on each beat drop the daemon's metrics snapshot
beside it (``obs.live.write_fleet_snapshot`` — the same artifact the
elastic sweep drops, so the router's ``/metrics`` fleet merge is the
PR-9 machinery verbatim).  The drain handshake is
:meth:`MemberRegistration.mark_draining` BEFORE the server closes: the
router stops routing new work to a draining member while its in-flight
requests finish — the graceful half of failover (the abrupt half is
the heartbeat aging out).

**Router side** (:func:`read_members`): scan the registrations, call
each heartbeat's age against ``dead_after_s``, and hand the live,
non-draining set to the hash ring.  A member that stops beating simply
ages out — its arc reassigns to survivors with no tombstone protocol.

stdlib-only; the router must work with wedged devices and without jax.
"""

import json
import os
import time

from ..resilience.heartbeat import Heartbeat, file_age

#: brlint host-concurrency lint (analysis/concurrency.py): the snapshot
#: hook runs on the heartbeat thread (cross-module thread entry is
#: declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("MemberRegistration.snapshot",)

#: heartbeat cadence / staleness defaults — serving members beat like
#: elastic sweep processes (dead_after ~= 6 beats, the multihost rule)
DEFAULT_HEARTBEAT_S = 0.5
DEFAULT_DEAD_AFTER_S = 3.0


def _members_dir(fleet_dir):
    d = os.path.join(fleet_dir, "members")
    os.makedirs(d, exist_ok=True)
    return d


def _safe(name):
    return "".join(c if c.isalnum() or c in "-_." else "-"
                   for c in str(name))


def member_paths(fleet_dir, name):
    """(info_json, heartbeat, draining_flag) paths for ``name``."""
    base = os.path.join(_members_dir(fleet_dir), _safe(name))
    return base + ".json", base + ".hb", base + ".draining"


def obs_dir(fleet_dir):
    """``<fleet_dir>/obs`` — where the fleet's per-host trace streams
    land (``scripts/serve_fleet.py --obs-dir`` default; the layout
    ``obs.stitch.load_fleet`` reads: ``router.jsonl`` + one
    ``<member>.jsonl`` per member).  Created on first ask."""
    d = os.path.join(str(fleet_dir), "obs")
    os.makedirs(d, exist_ok=True)
    return d


def member_obs_path(fleet_dir, name):
    """``<fleet_dir>/obs/<name>.jsonl`` — one host's trace stream; the
    file STEM is the host name ``obs.stitch`` joins the router's hop
    ledger against, so it must match the registration name."""
    return os.path.join(obs_dir(fleet_dir), _safe(name) + ".jsonl")


class MemberInfo(dict):
    """One member's router-side view (a dict for JSON-friendliness):
    ``name``, ``url``, ``pid``, ``age_s`` (heartbeat age), ``alive``
    (age <= dead_after), ``draining`` (drain handshake flagged).
    Routable = alive and not draining."""

    @property
    def routable(self):
        return bool(self.get("alive")) and not self.get("draining")


def read_members(fleet_dir, dead_after_s=DEFAULT_DEAD_AFTER_S):
    """All registered members, sorted by name — dead ones included
    (``alive=False``) so healthz can show who aged out; routing uses
    ``MemberInfo.routable``.  A torn registration (writer died before
    the atomic replace existed, or a disk fault) is skipped, not
    fatal."""
    d = _members_dir(fleet_dir)
    out = []
    now = time.time()
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, fname)) as f:
                info = json.load(f)
        except (OSError, ValueError):
            continue
        name = info.get("name") or fname[:-5]
        _j, hb, drain = member_paths(fleet_dir, name)
        age = file_age(hb, now=now)
        out.append(MemberInfo(
            info, age_s=(None if age is None else round(age, 3)),
            alive=(age is not None and age <= float(dead_after_s)),
            draining=os.path.exists(drain)))
    return out


class MemberRegistration:
    """Module doc: one serving daemon's membership handle.  Lifecycle
    is ``register() -> [serve] -> mark_draining() -> deregister()``;
    the heartbeat thread (and its per-beat metrics snapshot) runs in
    between.  ``registry`` (an ``obs.LiveRegistry``) is optional — no
    registry means membership without telemetry snapshots."""

    def __init__(self, fleet_dir, name, url, *, pid=None, registry=None,
                 heartbeat_s=DEFAULT_HEARTBEAT_S, meta=None):
        self.fleet_dir = str(fleet_dir)
        self.name = _safe(name)
        self.url = str(url)
        #: snapshot/registration identity — usually the OS pid, but any
        #: id works (in-process fleets, e.g. serve_bench --router, run
        #: N members under ONE pid and need distinct snapshot files)
        self.pid = os.getpid() if pid is None else pid
        self.registry = registry
        self.heartbeat_s = float(heartbeat_s)
        self.meta = dict(meta or {})
        self._paths = member_paths(self.fleet_dir, self.name)
        self._hb = None

    # ---- lifecycle ---------------------------------------------------------
    def register(self):
        """Write the registration atomically, take one synchronous
        beat (readers never see a registered-but-beatless member), and
        start the heartbeat thread."""
        info_path, hb_path, drain_path = self._paths
        try:
            os.remove(drain_path)   # re-registration clears a stale flag
        except OSError:
            pass
        info = {"name": self.name, "url": self.url, "pid": self.pid,
                "time": time.time(), **self.meta}
        tmp = f"{info_path}.tmp{self.pid}"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, info_path)
        self._hb = Heartbeat(hb_path, self.heartbeat_s,
                             on_beat=self.snapshot,
                             name=f"br-fleet-member-{self.name}")
        self._hb.beat()
        self._hb.start()
        return self

    def snapshot(self):
        """Drop this member's metrics snapshot into the fleet dir (the
        obs.live PR-9 artifact the router's ``/metrics`` merges); runs
        on the heartbeat thread after every beat."""
        if self.registry is None:
            return
        from ..obs.live import write_fleet_snapshot

        write_fleet_snapshot(self.fleet_dir, self.pid, self.registry)

    def mark_draining(self):
        """The drain handshake: flag this member BEFORE its server
        stops accepting, so the router routes around it while in-flight
        requests finish (new work would race the close and fail
        noisily instead of gracefully)."""
        drain_path = self._paths[2]
        with open(drain_path, "w") as f:
            f.write(str(time.time()))

    def deregister(self):
        """Stop the heartbeat and remove the registration (the metrics
        snapshot stays — the fleet merge keeps the departed member's
        counters, and its age gauge shows it stopped).  Idempotent."""
        if self._hb is not None:
            self._hb.stop()
            self._hb = None
        info_path, hb_path, _drain = self._paths
        for path in (info_path, hb_path):
            try:
                os.remove(path)
            except OSError:
                pass

    def __enter__(self):
        return self.register()

    def __exit__(self, *_exc):
        self.mark_draining()
        self.deregister()
