"""Consistent-hash ring: affinity routing for the serving fleet.

The router's whole job is keeping each member's warm state warm: a
daemon that has served pack key ``(t1, rtol, atol, energy)`` for
mechanism ``m`` holds the AOT programs and (while the epoch is
resident) the streaming backlog for exactly that key, so the router
must send every request of that key to the same member — and when
membership changes, move as few keys as possible (a moved key pays one
cold epoch on its new host; a full reshuffle pays it everywhere at
once).

That is the textbook consistent-hash ring: each member owns ``vnodes``
points on a 2^64 ring (sha256 of ``"<member>#<k>"`` — *not* python's
``hash``, which is per-process salted and would reshuffle the fleet on
every router restart), a key routes to the first member point at or
clockwise-after its own hash, and adding/removing one member moves only
the arcs adjacent to that member's points (the bounded-churn property
tests in ``tests/test_fleet.py`` pin this).  Virtual nodes smooth the
arc sizes so a 2-member fleet splits load ~evenly instead of wherever
two raw hashes happened to land.

Deterministic by construction: same member set => same ring => same
routes, across processes and restarts (the warm AOT cache on disk
outlives the router, so a restarted router must route a key back to
the member whose cache already holds it).

stdlib-only and stateless under reads; the router owns the mutation
lock (a ring is rebuilt, not edited, on membership change).
"""

import bisect
import hashlib

#: virtual nodes per member — 64 keeps the max/min arc ratio tight
#: (~1.3x at 2-8 members) at a few KiB of ring per member
DEFAULT_VNODES = 64


def _hash64(data):
    return int.from_bytes(
        hashlib.sha256(data.encode("utf-8")).digest()[:8], "big")


def canonical_key(parts):
    """One stable string for a route-key tuple: ``repr`` of each part
    joined with unit separators (floats keep full precision through
    ``repr``, ``None`` canonicalizes, and no two distinct tuples
    collide on a separator embedded in a mechanism id)."""
    return "\x1f".join(repr(p) for p in parts)


def request_key(obj):
    """The routing key of a raw (pre-validation) request object:
    ``(mech, t1, rtol, atol, energy)`` — the mechanism routing key plus
    the pack key's fields, i.e. the warm-state identity the request
    will occupy on whichever member serves it.  Absent fields
    canonicalize to ``None`` (the member applies its spec defaults, so
    two requests that omit ``rtol`` land on one member and share its
    default-rtol program).  Validation happens on the member — the
    router only peeks."""
    if not isinstance(obj, dict):
        return ("invalid",)
    return (obj.get("mech"), obj.get("t1"), obj.get("rtol"),
            obj.get("atol"), obj.get("energy"))


class HashRing:
    """Module doc.  ``members`` is any iterable of member names
    (strings); routes are deterministic functions of the member SET
    (insertion order never matters)."""

    def __init__(self, members=(), vnodes=DEFAULT_VNODES):
        self.vnodes = int(vnodes)
        self._members = tuple(sorted(set(str(m) for m in members)))
        self._points = []      # sorted (hash, member)
        for m in self._members:
            for k in range(self.vnodes):
                self._points.append((_hash64(f"{m}#{k}"), m))
        self._points.sort()
        self._hashes = [h for h, _m in self._points]

    # ---- membership (functional: build a new ring) -------------------------
    def members(self):
        return self._members

    def with_members(self, members):
        """A new ring over ``members`` (same vnodes) — the router
        rebuilds on membership change rather than editing in place, so
        a concurrent reader always sees one consistent ring."""
        return HashRing(members, vnodes=self.vnodes)

    # ---- routing -----------------------------------------------------------
    def route(self, key):
        """The member owning ``key`` (a tuple — see
        :func:`request_key` — or a pre-canonicalized string); ``None``
        on an empty ring."""
        prefs = self.preference(key, n=1)
        return prefs[0] if prefs else None

    def preference(self, key, n=None):
        """The failover order for ``key``: the first ``n`` DISTINCT
        members clockwise from the key's point (all members when ``n``
        is None).  Element 0 is the primary; the router walks the rest
        when a forward fails — so a dead primary's keys land on the
        same survivor every time (its arc *reassigns*, it does not
        scatter)."""
        if not self._points:
            return []
        if not isinstance(key, str):
            key = canonical_key(key)
        h = _hash64(key)
        start = bisect.bisect_right(self._hashes, h) % len(self._points)
        want = len(self._members) if n is None else min(
            int(n), len(self._members))
        out = []
        for i in range(len(self._points)):
            m = self._points[(start + i) % len(self._points)][1]
            if m not in out:
                out.append(m)
                if len(out) >= want:
                    break
        return out

    def arc_share(self, samples=4096):
        """Approximate fraction of key space owned per member (sampled
        — healthz/debug surface, not a routing primitive)."""
        if not self._members:
            return {}
        counts = dict.fromkeys(self._members, 0)
        for i in range(int(samples)):
            counts[self.route(f"sample:{i}")] += 1
        return {m: c / float(samples) for m, c in sorted(counts.items())}
