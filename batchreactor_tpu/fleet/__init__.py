"""The replicated serving tier (ROADMAP 3; docs/serving.md "Fleet").

PR-15's stage decomposition showed the saturated single daemon is
~92% queue-wait — admission-bound, not solve-bound — so the capacity
lever past one host is N daemons, not a bigger one.  This package
composes planes that already exist into that tier:

* :mod:`.ring` — the consistent-hash ring: requests route by
  (mechanism, pack key) so each member's warmed AOT programs and
  resident streaming epochs stay hot, and membership churn moves only
  the departed arcs;
* :mod:`.membership` — elastic membership over a shared fleet dir via
  the ``resilience.heartbeat`` mtime convention (register / beat /
  drain-handshake / age-out), with each member dropping its
  ``obs.live`` metrics snapshot beside its beat;
* :mod:`.router` — the thin, jax-free HTTP router: forward with
  failover (transport failure or ``draining`` -> next member
  clockwise; deterministic solves make the survivor's answer
  bit-exact, answered exactly once), replicate ``POST /mechanism``
  fleet-wide, and serve the merged fleet ``/metrics``;
* :mod:`.replication` — the upload journal + fan-out (idempotent by
  fingerprint, versioned by id, replayed to late joiners).

Everything here is importable WITHOUT jax (the ``bench.py`` /
``obs_fleet.py`` discipline): a wedged device must never take the
routing/telemetry plane down with it.  Entry points:
``scripts/serve_fleet.py`` (N daemons + router under one supervisor),
``scripts/serve.py --fleet-dir`` (one member), ``scripts/serve_bench.py
--router N`` (the fleet bench protocol).
"""

from .membership import (DEFAULT_DEAD_AFTER_S, DEFAULT_HEARTBEAT_S,
                         MemberInfo, MemberRegistration,
                         member_obs_path, member_paths, obs_dir,
                         read_members)
from .replication import UploadJournal, replicate_upload
from .ring import DEFAULT_VNODES, HashRing, canonical_key, request_key
from .router import FleetRouter

__all__ = [
    "HashRing",
    "canonical_key",
    "request_key",
    "DEFAULT_VNODES",
    "MemberRegistration",
    "MemberInfo",
    "member_paths",
    "member_obs_path",
    "obs_dir",
    "read_members",
    "DEFAULT_HEARTBEAT_S",
    "DEFAULT_DEAD_AFTER_S",
    "UploadJournal",
    "replicate_upload",
    "FleetRouter",
]
