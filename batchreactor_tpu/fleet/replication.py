"""Mechanism-upload replication: every member serves every mechanism.

``POST /mechanism`` against the router must leave the fleet uniform —
a request routed by mechanism id has to find that mechanism resident on
whichever member its hash arc names.  The protocol leans on properties
the serving store already has, so replication is a fan-out, not a
consensus round:

* **idempotent by fingerprint** — ``SessionStore._admit`` dedupes on
  the mechanism's content fingerprint, so delivering one upload to a
  member twice (a retry racing a slow first delivery, a journal replay
  to a member that already has it) admits once and re-aliases the id;
* **versioned by id** — re-uploading an id with new content builds a
  new session under that alias (latest wins), and the journal keeps
  only the latest per id, so a late joiner replays the current set,
  not the history;
* **answered honestly** — the router reports per-member results; a
  partial failure is a loud ``internal`` response naming the members
  that missed (the client retries; idempotency makes the retry safe),
  never a silently divergent fleet.

The :class:`UploadJournal` is router-local state: a member that joins
AFTER an upload gets the journal replayed to it before the ring routes
to it (``fleet/router.py``).  A *router* restart loses the journal but
not the fleet — members keep their resident mechanisms, and the next
upload repopulates it.

stdlib-only (urllib + threading): replication runs on router handler
threads.
"""

import json
import threading
import urllib.error
import urllib.request

#: brlint host-concurrency lint (analysis/concurrency.py): the journal
#: is touched from router HTTP handler threads (cross-module thread
#: entry is declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("UploadJournal.record", "UploadJournal.replay",
                          "UploadJournal.ids")


class UploadJournal:
    """Module doc: the latest accepted upload object per id, in
    first-accepted order (replay order is deterministic)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id = {}     # id -> upload object
        self._order = []     # ids, first-accepted order

    def record(self, upload):
        """Remember ``upload`` (a validated ``POST /mechanism`` body)
        under its id; re-recording an id replaces the payload (latest
        wins — the version semantics)."""
        uid = str(upload["id"])
        with self._lock:
            if uid not in self._by_id:
                self._order.append(uid)
            self._by_id[uid] = dict(upload)

    def replay(self):
        """The uploads a joining member must absorb, in order."""
        with self._lock:
            return [dict(self._by_id[uid]) for uid in self._order]

    def ids(self):
        with self._lock:
            return list(self._order)


def post_json(url, path, obj, timeout):
    """POST ``obj`` as JSON to ``url + path``; returns ``(status,
    parsed_body)``.  HTTP error statuses return their parsed body (the
    serving error-response grammar) rather than raising; only
    transport-level failures (``OSError`` — connection refused/reset,
    timeout) propagate, because only those mean "the member may not
    have seen this" and justify failover/retry."""
    body = json.dumps(obj).encode()
    req = urllib.request.Request(
        url.rstrip("/") + path, data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.getcode(), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except (ValueError, OSError):
            return e.code, {"status": "error",
                            "error": {"code": "internal",
                                      "message": f"HTTP {e.code}: "
                                                 f"{e.reason}"}}


def replicate_upload(member, upload, timeout):
    """Deliver one upload to one member: ``{"member", "ok", "status",
    "response"}`` — transport failures fold into ``ok=False`` with a
    synthesized response (the caller aggregates; a replication sweep
    must report every member, not die at the first dead one)."""
    try:
        status, resp = post_json(member["url"], "/mechanism", upload,
                                 timeout)
    except OSError as e:
        return {"member": member["name"], "ok": False, "status": None,
                "response": {"status": "error",
                             "error": {"code": "internal",
                                       "message": f"transport: {e}"}}}
    return {"member": member["name"],
            "ok": bool(resp.get("status") == "ok"),
            "status": status, "response": resp}
