"""The fleet router: one thin HTTP face over N serving daemons.

A :class:`FleetRouter` is a stdlib ``ThreadingHTTPServer`` (the
``obs.MetricsServer`` / ``serving.ServingServer`` shape) that owns NO
solver state — it peeks each request's routing key, consistent-hashes
it onto the live member set (``fleet/ring.py``), and forwards over
HTTP.  Deliberately jax-free: the router must keep answering (and keep
serving the fleet ``/metrics``) when every device in the fleet is
wedged, the same contract as ``scripts/obs_fleet.py``.

* ``POST /solve`` — route by ``(mech, t1, rtol, atol, energy)``
  (:func:`~.ring.request_key`: the mechanism + pack-key identity of the
  warm state the request will occupy) and forward.  A member's answer —
  ok or an honest error (``invalid`` / ``overloaded`` / ``unknown_
  mechanism``) — passes through with its HTTP status, plus a
  ``router`` section (host, attempts, failover flag) as provenance.
  **Failover**: a transport-level failure (connection refused/reset —
  the member is gone) or a ``draining`` rejection (the drain
  handshake) sends the request to the next distinct member clockwise;
  the sweep is deterministic, so the survivor's answer is bit-exact
  the one the dead member would have given, and the client gets
  exactly one answer.  Only when every member fails does the router
  answer — loudly — with ``internal``/503.  Nothing ever queues
  silently on the router.
* ``POST /mechanism`` — replicate to every live member
  (``fleet/replication.py``: idempotent by fingerprint, versioned by
  id), journal for replay to later joiners, report per-member results.
* ``GET /metrics`` — the router registry's exposition WITH the shared
  ``fleet_dir`` merge appended (``obs.live``: per-host counters/gauges,
  counters summed, gauges max-reduced, histograms slot-wise — the PR-9
  machinery verbatim, fed by each member's heartbeat snapshots) plus
  the router's own ``route_*``/``fleet_*`` counters, the
  ``route_seconds`` histogram (``obs/counters.py`` FAMILIES), and the
  SLO monitor's ``br_slo_*`` burn-rate gauges (``obs/slo.py``).

**Distributed tracing** (docs/observability.md "Fleet tracing"): every
``/solve`` carries a ``trace_ctx`` envelope downstream — inherited
from the client when present, minted here when absent — and every
terminal outcome (success, upstream error, invalid envelope, no
members) emits ONE ``request_trace`` recorder event with the hop
ledger (member, hop number, send/recv wall bracket, outcome) that
``obs.stitch`` joins with the members' stage waterfalls into
fleet-wide traces; a failover chain is one trace with honest hop
provenance.  The same outcomes feed the continuous SLO monitor.
* ``GET /healthz`` — membership census (alive, draining, aged-out),
  ring arc shares, journal ids.

Membership is read from the shared fleet dir (``fleet/membership.py``)
with a small cache TTL; a member that stops heartbeating ages out and
its hash arc reassigns to the survivors.  Between the death and the
age-out, forwards to it fail at transport level and the failover path
covers the gap (the member is also marked *suspect* so subsequent
requests skip it first).
"""

import http.server
import json
import threading
import time
import uuid

from ..obs.live import LiveRegistry
from ..obs.recorder import Recorder
from ..obs.slo import SloMonitor
from ..obs.trace import TRACE_VERSION
from ..serving import schema
from .membership import DEFAULT_DEAD_AFTER_S, read_members
from .replication import UploadJournal, post_json, replicate_upload
from .ring import HashRing, request_key

#: brlint host-concurrency lint (analysis/concurrency.py): the routing
#: surface runs on HTTP handler threads (each connection is its own
#: thread — cross-module thread entry is declared, not inferred)
_BRLINT_THREAD_ENTRIES = ("FleetRouter.solve", "FleetRouter.upload",
                          "FleetRouter.healthz",
                          "FleetRouter.metrics_text")


class _RouterHandler(http.server.BaseHTTPRequestHandler):
    front = None    # bound per-server via a subclass (FleetRouter)

    def _send(self, code, obj, ctype="application/json"):
        body = (json.dumps(obj) + "\n").encode() if not isinstance(
            obj, bytes) else obj
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib handler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200, self.front.metrics_text().encode(),
                           ctype="text/plain; version=0.0.4; "
                                 "charset=utf-8")
            elif path == "/healthz":
                self._send(200, self.front.healthz())
            else:
                self.send_error(404, "unknown path (GET /metrics, "
                                     "GET /healthz, POST /solve, "
                                     "POST /mechanism)")
        except Exception as e:  # noqa: BLE001 — a scrape must never
            #                     kill the router thread
            self.send_error(500, f"{type(e).__name__}: {e}")

    def do_POST(self):  # noqa: N802 — stdlib handler contract
        path = self.path.split("?", 1)[0]
        if path not in ("/solve", "/mechanism"):
            self.send_error(404, "POST /solve and POST /mechanism are "
                                 "the write paths")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            obj = json.loads(raw.decode() or "null")
        except (ValueError, UnicodeDecodeError) as e:
            self._send(400, schema.error_response(
                None, "invalid", f"request body is not JSON: {e}"))
            return
        if path == "/mechanism":
            code, resp = self.front.upload(obj)
        else:
            code, resp = self.front.solve(obj)
        self._send(code, resp)

    def log_message(self, *_args):
        pass    # request logging rides the obs recorder, not stderr


class FleetRouter:
    """Module doc.  ``fleet_dir`` is the shared membership/telemetry
    directory every member registered into (``scripts/serve.py
    --fleet-dir``); the router holds no other state worth preserving —
    kill it and start another, the fleet (and its warm caches) carries
    the identity."""

    def __init__(self, fleet_dir, port=0, host="127.0.0.1", *,
                 dead_after_s=DEFAULT_DEAD_AFTER_S, vnodes=None,
                 request_timeout=300.0, refresh_s=None, recorder=None):
        self.fleet_dir = str(fleet_dir)
        self.dead_after_s = float(dead_after_s)
        self.request_timeout = float(request_timeout)
        #: membership cache TTL — a fraction of the death threshold so
        #: an age-out is noticed within ~1 beat of it happening
        self.refresh_s = (self.dead_after_s / 6.0 if refresh_s is None
                          else float(refresh_s))
        self.recorder = recorder if recorder is not None else Recorder()
        self.registry = LiveRegistry(
            recorder=self.recorder, fleet_dir=self.fleet_dir,
            meta={"entry": "fleet-router"})
        #: the continuous SLO monitor (obs/slo.py — docs/observability
        #: .md "SLO monitor"): every terminal solve() outcome feeds it,
        #: and its br_slo_* gauges append to /metrics (metrics_text)
        self.slo = SloMonitor(recorder=self.recorder)
        self._lock = threading.Lock()
        from .ring import DEFAULT_VNODES

        self._ring = HashRing((), vnodes=(DEFAULT_VNODES if vnodes
                                          is None else int(vnodes)))
        self._members = {}       # name -> MemberInfo (routable set)
        self._census = []        # every registration, incl. dead
        self._suspects = {}      # name -> monotonic expiry
        self._refreshed_at = -1e9
        self._journal = UploadJournal()
        self._requested = (host, int(port))
        self._server = None
        self._thread = None

    # ---- membership view ---------------------------------------------------
    def _view(self, force=False):
        """(ring, {name: MemberInfo}) — refreshed from the fleet dir at
        most every ``refresh_s`` (one claiming thread re-reads; the
        rest route on the cached view, which is the point of the TTL).
        New routable members absorb the upload journal BEFORE they
        enter the ring, so a late joiner never serves a mechanism-less
        arc."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._refreshed_at < self.refresh_s:
                return self._ring, dict(self._members)
            self._refreshed_at = now   # claim this refresh
            known = set(self._members)
        census = read_members(self.fleet_dir, self.dead_after_s)
        routable = {m["name"]: m for m in census if m.routable}
        joined = sorted(n for n in routable if n not in known)
        for name in joined:
            # journal replay OUTSIDE the lock (HTTP against the member);
            # failure keeps the member out of the ring until the next
            # refresh retries — replication is idempotent by fingerprint
            for upload in self._journal.replay():
                res = replicate_upload(routable[name], upload,
                                       self.request_timeout)
                if not res["ok"]:
                    del routable[name]
                    self.recorder.event(
                        "fault", kind="fleet_replay_failed",
                        member=name, upload=upload.get("id"))
                    break
        with self._lock:
            old = set(self._members)
            new = set(routable)
            self._census = census
            self._members = routable
            if new != old:
                self._ring = self._ring.with_members(new)
                for _n in sorted(new - old):
                    self.recorder.counter("fleet_members_joined")
                for _n in sorted(old - new):
                    self.recorder.counter("fleet_members_left")
            for name in [s for s, t in self._suspects.items()
                         if t <= now or s not in new]:
                self._suspects.pop(name, None)
            ring, members = self._ring, dict(self._members)
        self.registry.publish("fleet-router", gauges={
            "fleet_members_routable": len(members),
            "fleet_members_registered": len(census),
            "fleet_members_draining": sum(
                1 for m in census if m.get("draining"))})
        return ring, members

    def _mark_suspect(self, name):
        with self._lock:
            self._suspects[name] = time.monotonic() + self.dead_after_s

    def _candidates(self, ring, members, key):
        """Members to try for ``key``, failover order: the ring's
        preference walk, suspects demoted to the tail (a suspect is
        skipped first, not forgotten — if every healthy member fails
        it is still the honest last resort)."""
        with self._lock:
            now = time.monotonic()
            suspects = {n for n, t in self._suspects.items() if t > now}
        prefs = [members[n] for n in ring.preference(key)
                 if n in members]
        healthy = [m for m in prefs if m["name"] not in suspects]
        demoted = [m for m in prefs if m["name"] in suspects]
        return healthy + demoted

    # ---- request plumbing (shared by HTTP and tests) ----------------------
    def _trace_event(self, rid, tid, parent, base_hop, minted, wall0,
                     total_s, hops, tried, host=None, code=None):
        """The router's terminal ``request_trace`` event — ONE per
        ``solve()`` outcome, success or rejection, so error-rate SLOs
        count what the response alone would hide (ISSUE-18 satellite).
        Carries the hop ledger (send/recv wall bracket per attempt)
        ``obs.stitch`` joins member waterfalls into, and feeds the
        same outcome to the SLO monitor."""
        attrs = {"request": rid, "v": TRACE_VERSION, "span": "route",
                 "minted": minted, "hop": base_hop,
                 "wall_start": round(wall0, 6),
                 "total_s": round(total_s, 6),
                 "failover": bool(tried), "tried": list(tried),
                 "hops": hops}
        if tid is not None:
            attrs["trace"] = tid
            attrs["parent_span"] = parent
        if host is not None:
            attrs["host"] = host
        failed = code is not None
        if failed:
            attrs["code"] = code
            attrs["failed"] = True
        self.recorder.event("request_trace", **attrs)
        self.slo.record(total_s, ok=not failed,
                        failover=bool(tried), at=wall0 + total_s)

    def solve(self, obj):
        """One request object -> ``(http_status, response_object)``,
        forwarded to the key's member with failover (module doc).

        Distributed tracing (docs/observability.md "Fleet tracing"):
        an inherited ``trace_ctx`` is validated (a malformed envelope
        is an ``invalid`` rejection — counted, not silent), MINTED
        when absent, and forwarded on EVERY hop with the hop count
        advanced — so a member's stage marks join one fleet-wide
        trace whether the client traced or not.  The RESPONSE is
        untouched by tracing: the ``router`` section stays exactly
        ``{host, attempts, failover, tried}`` and ctx-less requests
        are byte-identical to the pre-tracing wire format."""
        rec = self.recorder
        rec.counter("route_requests")
        rid = obj.get("id") if isinstance(obj, dict) else None
        t0 = time.perf_counter()
        wall0 = time.time()
        try:
            ctx = schema.validate_trace_ctx(
                obj.get("trace_ctx") if isinstance(obj, dict)
                else None, rid)
        except ValueError as e:
            self._trace_event(rid, None, None, 0, False, wall0,
                              time.perf_counter() - t0, [], [],
                              code="invalid")
            return 400, schema.error_response(rid, "invalid", e)
        if ctx is None:
            tid, parent, base_hop = f"r-{uuid.uuid4().hex[:16]}", None, 0
            minted = True
        else:
            tid, parent, base_hop = ctx
            minted = False
        ring, members = self._view()
        candidates = self._candidates(ring, members, request_key(obj))
        if not candidates:
            rec.counter("route_no_members")
            self._trace_event(rid, tid, parent, base_hop, minted,
                              wall0, time.perf_counter() - t0, [], [],
                              code="internal")
            return 503, schema.error_response(
                rid, "internal",
                f"no routable fleet members (fleet dir "
                f"{self.fleet_dir}; registered: "
                f"{[m['name'] for m in self._census_snapshot()]})")
        tried = []
        hops = []
        last = "unreachable"
        for member in candidates:
            hop_n = base_hop + len(tried) + 1
            if isinstance(obj, dict):
                fobj = dict(obj)
                fobj["trace_ctx"] = schema.trace_ctx_payload(
                    tid, span=f"route:{hop_n}", hop=hop_n)
            else:
                fobj = obj
            hop = {"member": member["name"], "hop": hop_n,
                   "send_wall": round(time.time(), 6)}
            try:
                status, resp = post_json(member["url"], "/solve", fobj,
                                         self.request_timeout)
            except OSError as e:
                # the member is gone (or wedged past the deadline):
                # demote it and re-route — the solve is deterministic,
                # so the survivor's answer is THE answer, delivered
                # exactly once
                hop.update(recv_wall=round(time.time(), 6),
                           outcome="transport")
                hops.append(hop)
                tried.append(member["name"])
                last = f"{member['name']}: {type(e).__name__}: {e}"
                self._mark_suspect(member["name"])
                rec.counter("route_failovers")
                rec.event("fault", kind="route_failover",
                          member=member["name"], error=str(e))
                continue
            hop["recv_wall"] = round(time.time(), 6)
            code = ((resp.get("error") or {}).get("code")
                    if isinstance(resp, dict) else None)
            if code == "draining":
                # the drain handshake's race window: the member flagged
                # itself between our membership read and the forward —
                # its arc is already reassigning, follow it
                hop["outcome"] = "draining"
                hops.append(hop)
                tried.append(member["name"])
                last = f"{member['name']}: draining"
                rec.counter("route_failovers")
                continue
            hop["outcome"] = "ok" if code is None else code
            hops.append(hop)
            if code is not None:
                rec.counter("route_upstream_errors")
            if isinstance(resp, dict):
                resp["router"] = {"host": member["name"],
                                  "attempts": len(tried) + 1,
                                  "failover": bool(tried),
                                  "tried": tried}
            dt = time.perf_counter() - t0
            rec.observe("route_seconds", dt,
                        path="failover" if tried else "direct")
            self._trace_event(rid, tid, parent, base_hop, minted,
                              wall0, dt, hops, tried,
                              host=member["name"], code=code)
            return status, resp
        rec.counter("route_no_members")
        self._trace_event(rid, tid, parent, base_hop, minted, wall0,
                          time.perf_counter() - t0, hops, tried,
                          code="internal")
        return 503, schema.error_response(
            rid, "internal",
            f"all {len(candidates)} fleet member(s) failed "
            f"(tried {tried}; last: {last}); the request was not "
            f"served")

    def _census_snapshot(self):
        with self._lock:
            return list(self._census)

    def upload(self, obj):
        """One mechanism upload -> ``(http_status, response)``:
        journal, replicate to every routable member, report per-member
        results (module doc — a partial failure answers ``internal``
        and the idempotent retry finishes the job)."""
        rec = self.recorder
        rid = obj.get("id") if isinstance(obj, dict) else None
        try:
            upload = schema.validate_upload(obj)
        except ValueError as e:
            return 400, schema.error_response(rid, "invalid", e)
        _ring, members = self._view(force=True)
        if not members:
            return 503, schema.error_response(
                upload["id"], "internal",
                "no routable fleet members to replicate to")
        # journal FIRST: a member joining mid-upload replays it (the
        # fingerprint-idempotent store makes double delivery a no-op)
        self._journal.record(upload)
        rec.counter("fleet_uploads")
        results = []
        for name in sorted(members):
            results.append(replicate_upload(members[name], upload,
                                            self.request_timeout))
            rec.counter("fleet_replications")
        failed = [r["member"] for r in results if not r["ok"]]
        info = {"replicated": [r["member"] for r in results
                               if r["ok"]],
                "failed": failed,
                "fingerprint": next(
                    (r["response"].get("fingerprint")
                     for r in results if r["ok"]), None)}
        if failed:
            rec.event("fault", kind="fleet_replication_partial",
                      failed=failed, upload=upload["id"])
            resp = schema.error_response(
                upload["id"], "internal",
                f"replication incomplete: {failed} failed (retry is "
                f"safe — admission is idempotent by fingerprint)")
            resp["replication"] = info
            return 500, resp
        resp = schema.ok_response(upload["id"], info)
        return 200, resp

    # ---- read endpoints ----------------------------------------------------
    def metrics_text(self):
        """The ``/metrics`` exposition: router counters + histograms +
        the fleet-dir merge (``LiveRegistry.prometheus`` with
        ``fleet_dir`` set appends the per-host + merged section) plus
        the SLO monitor's ``br_slo_*`` gauges (obs/slo.py)."""
        base = self.registry.prometheus()
        slo = self.slo.prometheus()
        if slo and base and not base.endswith("\n"):
            base += "\n"
        return base + slo

    def healthz(self):
        ring, members = self._view()
        census = self._census_snapshot()
        with self._lock:
            now = time.monotonic()
            suspects = sorted(n for n, t in self._suspects.items()
                              if t > now)
        return {"ok": bool(members), "time": time.time(),
                "router": {
                    "fleet_dir": self.fleet_dir,
                    "members": census,
                    "routable": sorted(members),
                    "suspects": suspects,
                    "dead_after_s": self.dead_after_s,
                    "arc_share": {m: round(v, 4) for m, v in
                                  ring.arc_share(samples=512).items()},
                    "uploads": self._journal.ids()}}

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        if self._server is not None:
            return self
        handler = type("_BoundRouterHandler", (_RouterHandler,),
                       {"front": self})
        self._server = http.server.ThreadingHTTPServer(
            self._requested, handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="br-fleet-router")
        self._thread.start()
        self.recorder.event("router_bound",
                            host=self._server.server_address[0],
                            port=self.port)
        return self

    @property
    def port(self):
        if self._server is None:
            raise RuntimeError("FleetRouter not started")
        return self._server.server_address[1]

    @property
    def url(self):
        return f"http://{self._server.server_address[0]}:{self.port}"

    def close(self):
        """Stop the HTTP front (members keep serving; the router holds
        no request state — in-flight forwards on handler threads finish
        their response writes)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread.join()
            self._server = self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *_exc):
        self.close()
