"""Continuous SLO monitoring: declarative objectives, sliding windows,
multi-window burn-rate alerts.

An :class:`Objective` states a service-level contract over the request
stream in ONE of three vocabularies (docs/observability.md "SLO
monitor"):

* ``latency`` — at most ``budget`` of requests may take longer than
  ``threshold_s`` end-to-end (``p95 <= 2.5s`` is spelled "budget 0.05
  over threshold 2.5" — the quantile contract in its countable form);
* ``error`` — at most ``budget`` of requests may fail (any honest
  error response: ``invalid`` / ``overloaded`` / ``internal`` / ...);
* ``failover`` — at most ``budget`` of requests may need a failover
  re-route (the fleet's churn signal: a rising failover rate means
  members are dying faster than the ring re-balances).

:class:`SloMonitor` evaluates the objectives continuously over a
sliding window of per-request samples fed by the fleet router
(``fleet/router.py`` — every terminal ``solve()`` outcome, success or
rejection, is one sample).  Alerting is MULTI-WINDOW BURN RATE, the
SRE-workbook shape: ``burn = bad_fraction / budget`` measured over both
a slow window (``window_s``) and a fast window (``fast_window_s``); an
objective alerts only when BOTH burns exceed ``burn_alert`` — the slow
window keeps one transient spike from paging, the fast window ends the
alert promptly once the bleeding stops.  Alert STATE TRANSITIONS
(firing and resolving both) are first-class recorder events
(``slo_alert``) and bump the ``slo_alerts`` counter
(``obs/counters.py`` SLO_KEYS); the continuous values render as
``br_slo_*`` gauges appended to the router ``/metrics``
(:meth:`SloMonitor.prometheus`).

:func:`evaluate_traces` is the same arithmetic over STITCHED fleet
traces (``obs.stitch``) — the offline surface ``scripts/obs_slo.py
--gate`` checks against a banked baseline in CI, turning the latency
baselines from a post-hoc diff into a live contract.

Pure stdlib — the SLO plane rides the jax-free router and must keep
evaluating when every device is wedged.
"""

import threading
import time
from collections import deque

from .export import _metric

#: schema version riding ``slo_alert`` events and the gate summary —
#: bump on any layout change
SLO_VERSION = 1

#: the objective vocabulary (module doc)
OBJECTIVE_KINDS = ("latency", "error", "failover")


class Objective:
    """One declarative objective (module doc): ``budget`` is the
    allowed BAD fraction of requests in a window; ``latency``
    objectives additionally carry the ``threshold_s`` a request must
    beat to count as good.  Loud on every malformed field — a silently
    ignored objective is an SLO that never pages."""

    __slots__ = ("name", "kind", "budget", "threshold_s")

    def __init__(self, name, kind, budget, threshold_s=None):
        if not isinstance(name, str) or not name:
            raise ValueError(f"objective name must be a non-empty "
                             f"string; got {name!r}")
        if kind not in OBJECTIVE_KINDS:
            raise ValueError(f"objective {name!r}: unknown kind "
                             f"{kind!r}; vocabulary: {OBJECTIVE_KINDS}")
        budget = float(budget)
        if not 0.0 < budget < 1.0:
            raise ValueError(f"objective {name!r}: budget must be a "
                             f"fraction in (0, 1); got {budget!r}")
        if kind == "latency":
            if threshold_s is None or float(threshold_s) <= 0.0:
                raise ValueError(
                    f"objective {name!r}: latency objectives need "
                    f"threshold_s > 0; got {threshold_s!r}")
            threshold_s = float(threshold_s)
        elif threshold_s is not None:
            raise ValueError(
                f"objective {name!r}: threshold_s only applies to "
                f"latency objectives (kind is {kind!r})")
        self.name = name
        self.kind = kind
        self.budget = budget
        self.threshold_s = threshold_s

    def bad(self, latency_s, ok, failover):
        """Is one ``(latency_s, ok, failover)`` sample BAD under this
        objective?  (A failed request counts against a latency
        objective only through the error objective — its latency is
        the rejection's, not a solve's.)"""
        if self.kind == "latency":
            return bool(ok) and float(latency_s) > self.threshold_s
        if self.kind == "error":
            return not ok
        return bool(failover)

    def describe(self):
        """JSON-able self-description (the gate summary / healthz
        block)."""
        d = {"kind": self.kind, "budget": self.budget}
        if self.threshold_s is not None:
            d["threshold_s"] = self.threshold_s
        return d


#: the router's default contract (scripts/obs_slo.py --gate checks the
#: same three against the banked baseline): p95 end-to-end under 2.5 s,
#: <=1% errors, <=5% failovers
DEFAULT_OBJECTIVES = (
    Objective("latency_p95", "latency", budget=0.05, threshold_s=2.5),
    Objective("error_rate", "error", budget=0.01),
    Objective("failover_rate", "failover", budget=0.05),
)


class SloMonitor:
    """Module doc: the continuous evaluator.  Thread-safe — ``record``
    runs on router handler threads, ``prometheus`` on the scrape
    thread (``fleet/router.py`` ``_BRLINT_THREAD_ENTRIES``)."""

    def __init__(self, objectives=None, *, window_s=300.0,
                 fast_window_s=30.0, burn_alert=2.0, recorder=None):
        objs = tuple(DEFAULT_OBJECTIVES if objectives is None
                     else objectives)
        if not objs:
            raise ValueError("SloMonitor needs at least one objective")
        names = [o.name for o in objs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        for o in objs:
            if not isinstance(o, Objective):
                raise ValueError(f"objectives must be Objective "
                                 f"instances; got {type(o).__name__}")
        self.objectives = objs
        self.window_s = float(window_s)
        self.fast_window_s = float(fast_window_s)
        if not 0.0 < self.fast_window_s < self.window_s:
            raise ValueError(
                f"fast_window_s ({self.fast_window_s}) must sit inside "
                f"window_s ({self.window_s}) — multi-window burn needs "
                f"two distinct horizons")
        self.burn_alert = float(burn_alert)
        if self.burn_alert <= 0.0:
            raise ValueError(f"burn_alert must be > 0; got "
                             f"{self.burn_alert!r}")
        self.recorder = recorder
        self._lock = threading.Lock()
        self._samples = deque()   # (at, latency_s, ok, failover)
        self._alerting = {o.name: False for o in objs}

    # ---- feeding -----------------------------------------------------------
    def record(self, latency_s, ok=True, failover=False, at=None):
        """Fold one terminal request outcome into the window."""
        at = time.time() if at is None else float(at)
        with self._lock:
            self._samples.append((at, float(latency_s), bool(ok),
                                  bool(failover)))
            self._trim_locked(at)

    def _trim_locked(self, now):
        floor = now - self.window_s
        while self._samples and self._samples[0][0] < floor:
            self._samples.popleft()

    # ---- evaluation --------------------------------------------------------
    def evaluate(self, now=None):
        """Evaluate every objective over both windows; emit
        ``slo_alert`` events / ``slo_alerts`` counters on state
        transitions.  Returns ``{name: {requests, bad, bad_fraction,
        burn, fast: {...}, alerting}}``."""
        now = time.time() if now is None else float(now)
        with self._lock:
            self._trim_locked(now)
            samples = list(self._samples)
        fast_floor = now - self.fast_window_s
        out = {}
        transitions = []
        for o in self.objectives:
            slow = self._window_stats(o, samples)
            fast = self._window_stats(
                o, [s for s in samples if s[0] >= fast_floor])
            alerting = (slow["requests"] > 0 and fast["requests"] > 0
                        and slow["burn"] >= self.burn_alert
                        and fast["burn"] >= self.burn_alert)
            with self._lock:
                was = self._alerting[o.name]
                self._alerting[o.name] = alerting
            if alerting != was:
                transitions.append((o, alerting, slow, fast))
            out[o.name] = {**o.describe(), **slow, "fast": fast,
                           "alerting": alerting}
        rec = self.recorder
        if rec is not None:
            for o, firing, slow, fast in transitions:
                rec.counter("slo_alerts")
                rec.event("slo_alert", v=SLO_VERSION, objective=o.name,
                          state=("firing" if firing else "resolved"),
                          burn=slow["burn"], burn_fast=fast["burn"],
                          bad_fraction=slow["bad_fraction"],
                          budget=o.budget)
        return out

    @staticmethod
    def _window_stats(objective, samples):
        n = len(samples)
        bad = sum(1 for at, lat, ok, fo in samples
                  if objective.bad(lat, ok, fo))
        frac = (bad / n) if n else 0.0
        return {"requests": n, "bad": bad,
                "bad_fraction": round(frac, 6),
                "burn": round(frac / objective.budget, 6)}

    # ---- exposition --------------------------------------------------------
    def prometheus(self, now=None):
        """The ``br_slo_*`` gauge families the router appends to its
        ``/metrics`` (rendered with ``obs.export._metric`` — the same
        escaping/ordering every exposition family shares)."""
        results = self.evaluate(now)
        lines = []
        _metric(lines, "br_slo_requests", "gauge",
                "Requests in the SLO sliding window, per horizon.",
                [({"window": "slow"},
                  next(iter(results.values()))["requests"]),
                 ({"window": "fast"},
                  next(iter(results.values()))["fast"]["requests"])])
        _metric(lines, "br_slo_bad_fraction", "gauge",
                "Fraction of windowed requests violating each "
                "objective.",
                [({"objective": name, "window": w},
                  (r if w == "slow" else r["fast"])["bad_fraction"])
                 for name, r in sorted(results.items())
                 for w in ("slow", "fast")])
        _metric(lines, "br_slo_burn_rate", "gauge",
                "Error-budget burn rate (bad_fraction / budget) per "
                "objective and window; sustained > burn_alert on both "
                "windows fires the alert.",
                [({"objective": name, "window": w},
                  (r if w == "slow" else r["fast"])["burn"])
                 for name, r in sorted(results.items())
                 for w in ("slow", "fast")])
        _metric(lines, "br_slo_alert", "gauge",
                "1 while the objective's multi-window burn alert is "
                "firing.",
                [({"objective": name}, int(r["alerting"]))
                 for name, r in sorted(results.items())])
        return "\n".join(lines) + ("\n" if lines else "")


def evaluate_traces(traces, objectives=None):
    """The monitor's arithmetic over STITCHED traces (``obs.stitch``) —
    one offline pass, no windows (a banked CI run is one window).
    Returns ``{name: {kind, budget[, threshold_s], requests, bad,
    bad_fraction, burn, ok}}`` — ``ok`` is the plain budget check
    ``scripts/obs_slo.py --gate`` turns into an exit code."""
    objs = tuple(DEFAULT_OBJECTIVES if objectives is None
                 else objectives)
    out = {}
    for o in objs:
        if not isinstance(o, Objective):
            raise ValueError(f"objectives must be Objective instances; "
                             f"got {type(o).__name__}")
        n = bad = 0
        for t in traces:
            lat = t.get("total_s")
            if lat is None:
                continue
            ok = not t.get("failed") and t.get("code") is None
            n += 1
            if o.bad(lat, ok, bool(t.get("failover"))):
                bad += 1
        frac = (bad / n) if n else 0.0
        out[o.name] = {**o.describe(), "requests": n, "bad": bad,
                       "bad_fraction": round(frac, 6),
                       "burn": round(frac / o.budget, 6),
                       "ok": frac <= o.budget}
    return out
