"""Assemble, render, and diff telemetry reports.

A *report* is a plain JSON-able dict (schema ``br-obs-v1``) combining the
three telemetry sources — Recorder spans/events/counters, device-side
solver stats, and CompileWatch compile/retrace counts — into the one
artifact ``scripts/obs_report.py`` renders, ``obs.export`` serializes,
and future perf PRs cite instead of ad-hoc probe scripts (PERF.md).

Report layout::

    {"schema": "br-obs-v1",
     "meta":     {...free-form: label, backend, workload...},
     "spans":    [{name, path, depth, start, dur, attrs, seq}, ...],
     "events":   [{name, time, attrs}, ...],
     "counters": {name: number},
     "histograms": {name: [{"labels": {...}, "le": [...],
                            "counts": [...], "sum", "count"}, ...]}
                   | None,
     "solver_stats": {"totals": {...}, "per_lane": {key: [...]}} | None,
     "compile": {"available", "compiles", "traces", "retraces",
                 "compile_s", "by_label": {...}} | None}

``histograms`` (the ``obs/counters.py`` HIST_KEYS family —
docs/observability.md "Histograms") carries one series per label set:
``counts`` has one slot per ``le`` upper bound plus a trailing +Inf
overflow slot, and a MISSING family diffs as empty (count 0) — the
missing->0 convention lifted to distributions.
"""

import numpy as np

from . import counters as C

SCHEMA = "br-obs-v1"


def stats_totals(stats):
    """Alias of :func:`obs.counters.totals` re-exported at package level
    (the reduction most callers want)."""
    return C.totals(stats)


def _jsonable(v):
    """Coerce numpy scalars/arrays (and nested containers) to plain
    python so the report round-trips through json exactly."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, np.ndarray):
        return _jsonable(v.tolist())
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if hasattr(v, "item") and not isinstance(v, (int, float, str, bool,
                                                 type(None))):
        # 0-d jax arrays and friends
        try:
            return _jsonable(v.item())
        except (TypeError, ValueError):
            return repr(v)
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    return repr(v)


def build_report(recorder=None, solver_stats=None, watch=None, meta=None):
    """Assemble the report dict from whichever sources the caller has.

    ``solver_stats`` is a ``SolveResult.stats`` dict (scalar per-lane or
    vmap-batched); per-lane arrays are included only when batched (a
    single-condition solve's totals ARE its per-lane view)."""
    spans, events, ctrs = ([], [], {})
    hists = None
    if recorder is not None:
        spans, events, ctrs = recorder.snapshot()
        snap = getattr(recorder, "hist_snapshot", None)
        if snap is not None:
            le = list(C.HIST_BUCKET_EDGES)
            hists = {name: [{"le": le, **ser} for ser in series]
                     for name, series in snap().items()} or None
    stats_block = None
    if solver_stats is not None:
        totals = C.totals(solver_stats)
        stats_block = {"totals": totals}
        lanes = C.per_lane(solver_stats)
        if lanes and any(np.asarray(v).ndim >= 1 and k != "order_hist"
                         for k, v in lanes.items()):
            first = next(iter(lanes.values()))
            if np.asarray(first).ndim >= 1:
                stats_block["per_lane"] = {k: np.asarray(v).tolist()
                                           for k, v in lanes.items()}
    return _jsonable({
        "schema": SCHEMA,
        "meta": dict(meta or {}),
        "spans": spans,
        "events": events,
        "counters": ctrs,
        "histograms": hists,
        "solver_stats": stats_block,
        "compile": watch.summary() if watch is not None else None,
    })


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------
def _fmt_dur(d):
    return "   ...  " if d is None else f"{d:8.3f}s"


def hist_series_name(name, labels):
    """``serve_stage_seconds{stage="total"}`` — the one series-naming
    rule render, diff, and the gate share."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return name + "{" + inner + "}"


def _fmt_hs(v):
    """Histogram seconds, human-scaled (quantiles are None on empty)."""
    if v is None:
        return "-"
    return f"{1e3 * v:.1f}ms" if v < 1.0 else f"{v:.3f}s"


def render(report):
    """Human-readable multi-line rendering: span tree (indented by
    nesting depth, start order), counters, solver-stat totals with the
    order histogram, compile/retrace summary, and any events."""
    lines = []
    meta = report.get("meta") or {}
    head = " ".join(f"{k}={v}" for k, v in sorted(meta.items()))
    lines.append(f"obs report [{report.get('schema', '?')}]"
                 + (f"  {head}" if head else ""))

    spans = sorted(report.get("spans") or [], key=lambda s: s.get("seq", 0))
    if spans:
        lines.append("spans:")
        for s in spans:
            attrs = s.get("attrs") or {}
            extra = ("  " + " ".join(f"{k}={v}" for k, v in
                                     sorted(attrs.items()))) if attrs else ""
            lines.append(f"  {_fmt_dur(s.get('dur'))}  "
                         f"{'  ' * s.get('depth', 0)}{s['name']}{extra}")

    ctrs = report.get("counters") or {}
    if ctrs:
        lines.append("counters:")
        for k in sorted(ctrs):
            lines.append(f"  {k}: {ctrs[k]}")
        occ = C.occupancy(ctrs)
        if occ is not None:
            lines.append(f"  occupancy: {occ:.4f} "
                         f"(lane_attempts / lane_capacity)")

    hists = report.get("histograms") or {}
    if hists:
        lines.append("histograms:")
        for name in sorted(hists):
            for ser in hists[name]:
                lines.append(
                    f"  {hist_series_name(name, ser.get('labels'))}: "
                    f"n={ser['count']} mean={_fmt_hs(C.hist_mean(ser))} "
                    f"p50={_fmt_hs(C.hist_quantile(ser, 0.50))} "
                    f"p95={_fmt_hs(C.hist_quantile(ser, 0.95))} "
                    f"p99={_fmt_hs(C.hist_quantile(ser, 0.99))}")

    st = (report.get("solver_stats") or {}).get("totals")
    if st:
        lines.append("solver:")
        for k in ("n_accepted", "n_rejected", "newton_iters", "jac_builds",
                  "factorizations", "setup_reuses", "precond_age",
                  "err_rejects", "conv_rejects"):
            if k in st:
                lines.append(f"  {k}: {st[k]}")
        if "order_hist" in st:
            hist = st["order_hist"]
            lines.append("  order_hist: "
                         + " ".join(f"{q}:{n}" for q, n in
                                    enumerate(hist) if q >= 1))
        per_lane = (report.get("solver_stats") or {}).get("per_lane")
        if per_lane:
            b = len(next(iter(per_lane.values())))
            lines.append(f"  (per-lane stats for {b} lanes in the report)")

    comp = report.get("compile")
    if comp is not None:
        if not comp.get("available", True):
            lines.append("compile: unavailable (no jax.monitoring)")
        else:
            cache = ""
            if "cache_hits" in comp:
                cache = (f", cache {comp['cache_hits']} hits / "
                         f"{comp.get('cache_misses', 0)} misses")
            lines.append(f"compile: {comp['compiles']} compiles "
                         f"({comp['compile_s']:.2f}s), {comp['traces']} "
                         f"traces, {comp['retraces']} retraces{cache}")
            for label, v in sorted((comp.get("by_label") or {}).items()):
                progs = v.get("programs") or {}
                extra = (f" programs={len(progs)}" if len(progs) > 1
                         else "")
                lines.append(f"  {label}: compiles={v['compiles']} "
                             f"traces={v['traces']} "
                             f"retraces={v['retraces']}{extra}")

    events = report.get("events") or []
    if events:
        lines.append("events:")
        for e in events:
            attrs = e.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            lines.append(f"  {e['name']}" + (f"  {extra}" if extra else ""))
    return "\n".join(lines)


def diff(a, b):
    """Compare two reports (baseline ``a`` -> candidate ``b``): per-name
    span totals, recorder counters (e.g. the segmented drivers'
    ``blocking_syncs``), solver-stat totals, and compile counts, with
    absolute and relative deltas — the tool perf PRs cite for
    before/after numbers."""

    def span_totals(rep):
        agg = {}
        for s in rep.get("spans") or []:
            if s.get("dur") is not None:
                agg[s["name"]] = agg.get(s["name"], 0.0) + s["dur"]
        return agg

    lines = ["obs diff (a -> b)"]
    sa, sb = span_totals(a), span_totals(b)
    for name in sorted(set(sa) | set(sb)):
        va, vb = sa.get(name), sb.get(name)
        if va is None or vb is None:
            lines.append(f"  span {name}: "
                         f"{'-' if va is None else f'{va:.3f}s'} -> "
                         f"{'-' if vb is None else f'{vb:.3f}s'}")
        else:
            pct = 100.0 * (vb - va) / va if va else float("inf")
            lines.append(f"  span {name}: {va:.3f}s -> {vb:.3f}s "
                         f"({pct:+.1f}%)")

    def _fmt_ctr(v):
        # float counters are accumulated wall-clock (e.g. poll_wait_s):
        # format like span durations, not full-precision repr noise
        if isinstance(v, float):
            return f"{v:.3f}"
        return str(v)

    ka, kb = a.get("counters") or {}, b.get("counters") or {}
    missing_zero = C.missing_zero_keys()
    for k in sorted(set(ka) | set(kb)):
        va, vb = ka.get(k), kb.get(k)
        if k in missing_zero:
            # host counter families (fault/admission/live/serve — the
            # counters.FAMILIES registry's missing_zero declaration,
            # which registering a future family joins automatically)
            # are absent from reports whose run never exercised the
            # surface: missing is 0, not a difference (the
            # setup_reuses/cache_* convention)
            va, vb = va or 0, vb or 0
            if va == vb:
                continue
        if va != vb:
            lines.append(f"  counter {k}: {_fmt_ctr(va)} -> {_fmt_ctr(vb)}")
    # histogram families (HIST_KEYS — the serve_stage_seconds latency
    # decomposition): missing is EMPTY (count 0, quantiles None), the
    # missing->0 convention lifted to distributions, so a baseline that
    # never served diffs cleanly against a serving run.  Rendered as
    # count + p50/p99 shifts, not raw bucket vectors.
    def hist_series(rep):
        out = {}
        for name, series in (rep.get("histograms") or {}).items():
            for ser in series:
                out[hist_series_name(name, ser.get("labels"))] = ser
        return out

    ha, hb = hist_series(a), hist_series(b)
    empty = C.hist_new()
    for key in sorted(set(ha) | set(hb)):
        va, vb = ha.get(key, empty), hb.get(key, empty)
        if va["count"] == vb["count"] and va["counts"] == vb["counts"]:
            continue
        lines.append(
            f"  hist {key}: n {va['count']} -> {vb['count']}, "
            f"p50 {_fmt_hs(C.hist_quantile(va, 0.5))} -> "
            f"{_fmt_hs(C.hist_quantile(vb, 0.5))}, "
            f"p99 {_fmt_hs(C.hist_quantile(va, 0.99))} -> "
            f"{_fmt_hs(C.hist_quantile(vb, 0.99))}")

    # derived occupancy gauge (continuous batching): shown whenever either
    # side recorded capacity, so an admission A/B reads as one ratio
    # instead of two raw counter deltas
    oa, ob = C.occupancy(ka), C.occupancy(kb)
    if (oa is not None or ob is not None) and oa != ob:
        lines.append(f"  occupancy: "
                     f"{'-' if oa is None else f'{oa:.4f}'} -> "
                     f"{'-' if ob is None else f'{ob:.4f}'}")

    ta = (a.get("solver_stats") or {}).get("totals") or {}
    tb = (b.get("solver_stats") or {}).get("totals") or {}
    for k in sorted(set(ta) | set(tb)):
        va, vb = ta.get(k), tb.get(k)
        if k in ("setup_reuses", "precond_age"):
            # setup-economy keys are absent from pre-economy archived
            # reports: missing is 0, not a difference (the cache_* key
            # convention below)
            va, vb = va or 0, vb or 0
        if va != vb:
            lines.append(f"  solver {k}: {va} -> {vb}")
    ca, cb = a.get("compile") or {}, b.get("compile") or {}
    for k in ("compiles", "retraces", "cache_hits", "cache_misses"):
        # cache_* keys are absent from pre-AOT archived reports: a
        # missing counter is 0, not a difference
        va, vb = ca.get(k) or 0, cb.get(k) or 0
        if va != vb:
            lines.append(f"  compile {k}: {va} -> {vb}")
    # per-label compile counts: the AOT program store's zero-recompile
    # evidence is the ARMED sweep label going to zero ("compile
    # [sweep-segment] compiles: N -> 0"), distinct from sub-ms host
    # eager-op compiles that ride the totals
    bla, blb = (ca.get("by_label") or {}), (cb.get("by_label") or {})
    for label in sorted(set(bla) | set(blb)):
        va = (bla.get(label) or {}).get("compiles", 0)
        vb = (blb.get(label) or {}).get("compiles", 0)
        if va != vb:
            lines.append(f"  compile [{label}] compiles: {va} -> {vb}")
    # compile wall is the AOT program store's headline evidence
    # ("compiles: N -> 0" above, seconds saved here); float-compare with
    # a render threshold so ~us jitter doesn't read as a diff
    va, vb = ca.get("compile_s"), cb.get("compile_s")
    if (va is None) != (vb is None) or (
            va is not None and abs(va - vb) >= 5e-4):
        lines.append(f"  compile compile_s: {_fmt_ctr(va)} -> "
                     f"{_fmt_ctr(vb)}")
    if len(lines) == 1:
        lines.append("  (no differences in spans / counters / solver "
                     "stats / compiles)")
    return "\n".join(lines)
